// CPython extension binding for the staging tables (native/tables.cpp).
//
// The ctypes path needs the caller to pack a list of bytes into one blob +
// offsets (a Python-side O(n) pass that shows up in merge profiles); here
// the extension walks the PyBytes list directly in C.  Output arrays are
// caller-allocated numpy buffers passed via the buffer protocol, so no
// numpy C-API dependency.
//
// Built by native/Makefile into constdb_tpu/_native/cst_ext*.so;
// utils/native_tables.py prefers it and falls back to ctypes, then to pure
// Python.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include "tables.cpp"  // self-contained: StrTable / I64Table definitions
#include "resp.cpp"    // RESP flat-array fast parser (py_resp_parse)

namespace {

const char* kStrCapsule = "constdb.StrTable";
const char* kI64Capsule = "constdb.I64Table";

void str_destructor(PyObject* cap) {
    delete static_cast<StrTable*>(PyCapsule_GetPointer(cap, kStrCapsule));
}
void i64_destructor(PyObject* cap) {
    delete static_cast<I64Table*>(PyCapsule_GetPointer(cap, kI64Capsule));
}

StrTable* get_str(PyObject* cap) {
    return static_cast<StrTable*>(PyCapsule_GetPointer(cap, kStrCapsule));
}
I64Table* get_i64(PyObject* cap) {
    return static_cast<I64Table*>(PyCapsule_GetPointer(cap, kI64Capsule));
}

bool out_buffer(PyObject* obj, Py_buffer* view, Py_ssize_t need_items) {
    if (PyObject_GetBuffer(obj, view, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) != 0)
        return false;
    if (view->len < (Py_ssize_t)(need_items * sizeof(int64_t))) {
        PyBuffer_Release(view);
        PyErr_SetString(PyExc_ValueError, "output buffer too small");
        return false;
    }
    return true;
}

// ------------------------------------------------------------------ StrTable

PyObject* py_strtab_new(PyObject*, PyObject* args) {
    Py_ssize_t cap_hint = 16;
    if (!PyArg_ParseTuple(args, "|n", &cap_hint)) return nullptr;
    return PyCapsule_New(new StrTable((size_t)cap_hint), kStrCapsule,
                         str_destructor);
}

PyObject* py_strtab_len(PyObject*, PyObject* args) {
    PyObject* cap;
    if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
    StrTable* t = get_str(cap);
    if (!t) return nullptr;
    return PyLong_FromSsize_t((Py_ssize_t)t->count);
}

PyObject* py_strtab_get_or_insert(PyObject*, PyObject* args) {
    PyObject* cap;
    Py_buffer b;
    if (!PyArg_ParseTuple(args, "Oy*", &cap, &b)) return nullptr;
    StrTable* t = get_str(cap);
    if (!t) { PyBuffer_Release(&b); return nullptr; }
    int64_t id = t->get_or_insert((const uint8_t*)b.buf, (int64_t)b.len);
    PyBuffer_Release(&b);
    return PyLong_FromLongLong(id);
}

PyObject* py_strtab_lookup(PyObject*, PyObject* args) {
    PyObject* cap;
    Py_buffer b;
    if (!PyArg_ParseTuple(args, "Oy*", &cap, &b)) return nullptr;
    StrTable* t = get_str(cap);
    if (!t) { PyBuffer_Release(&b); return nullptr; }
    int64_t id = t->lookup((const uint8_t*)b.buf, (int64_t)b.len);
    PyBuffer_Release(&b);
    return PyLong_FromLongLong(id);
}

// (table, list[bytes], out int64[n]) -> n_new
PyObject* py_strtab_get_or_insert_batch(PyObject*, PyObject* args) {
    PyObject *cap, *list, *out;
    if (!PyArg_ParseTuple(args, "OOO", &cap, &list, &out)) return nullptr;
    StrTable* t = get_str(cap);
    if (!t) return nullptr;
    PyObject* seq = PySequence_Fast(list, "expected a sequence of bytes");
    if (!seq) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    Py_buffer ob;
    if (!out_buffer(out, &ob, n)) { Py_DECREF(seq); return nullptr; }
    int64_t* dst = (int64_t*)ob.buf;
    t->batch_begin((size_t)n);
    int64_t before = (int64_t)t->count;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* item = PySequence_Fast_GET_ITEM(seq, i);
        char* p;
        Py_ssize_t len;
        if (PyBytes_AsStringAndSize(item, &p, &len) != 0) {
            PyBuffer_Release(&ob);
            Py_DECREF(seq);
            return nullptr;
        }
        dst[i] = t->get_or_insert((const uint8_t*)p, (int64_t)len);
    }
    int64_t fresh = (int64_t)t->count - before;
    t->batch_end((size_t)n, (size_t)fresh);
    PyBuffer_Release(&ob);
    Py_DECREF(seq);
    return PyLong_FromLongLong(fresh);
}

PyObject* py_strtab_lookup_batch(PyObject*, PyObject* args) {
    PyObject *cap, *list, *out;
    if (!PyArg_ParseTuple(args, "OOO", &cap, &list, &out)) return nullptr;
    StrTable* t = get_str(cap);
    if (!t) return nullptr;
    PyObject* seq = PySequence_Fast(list, "expected a sequence of bytes");
    if (!seq) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    Py_buffer ob;
    if (!out_buffer(out, &ob, n)) { Py_DECREF(seq); return nullptr; }
    int64_t* dst = (int64_t*)ob.buf;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* item = PySequence_Fast_GET_ITEM(seq, i);
        char* p;
        Py_ssize_t len;
        if (PyBytes_AsStringAndSize(item, &p, &len) != 0) {
            PyBuffer_Release(&ob);
            Py_DECREF(seq);
            return nullptr;
        }
        dst[i] = t->lookup((const uint8_t*)p, (int64_t)len);
    }
    PyBuffer_Release(&ob);
    Py_DECREF(seq);
    Py_RETURN_NONE;
}

PyObject* py_strtab_bytes_of(PyObject*, PyObject* args) {
    PyObject* cap;
    Py_ssize_t id;
    if (!PyArg_ParseTuple(args, "On", &cap, &id)) return nullptr;
    StrTable* t = get_str(cap);
    if (!t) return nullptr;
    if (id < 0 || (size_t)id >= t->count) {
        PyErr_SetString(PyExc_IndexError, "string id out of range");
        return nullptr;
    }
    return PyBytes_FromStringAndSize(
        (const char*)t->arena.data() + t->offs[id], (Py_ssize_t)t->lens[id]);
}

// ------------------------------------------------------------------ I64Table

PyObject* py_i64_new(PyObject*, PyObject* args) {
    Py_ssize_t cap_hint = 16;
    if (!PyArg_ParseTuple(args, "|n", &cap_hint)) return nullptr;
    return PyCapsule_New(new I64Table((size_t)cap_hint), kI64Capsule,
                         i64_destructor);
}

PyObject* py_i64_len(PyObject*, PyObject* args) {
    PyObject* cap;
    if (!PyArg_ParseTuple(args, "O", &cap)) return nullptr;
    I64Table* t = get_i64(cap);
    if (!t) return nullptr;
    return PyLong_FromSsize_t((Py_ssize_t)t->count);
}

PyObject* py_i64_get(PyObject*, PyObject* args) {
    PyObject* cap;
    long long k, dflt;
    if (!PyArg_ParseTuple(args, "OLL", &cap, &k, &dflt)) return nullptr;
    I64Table* t = get_i64(cap);
    if (!t) return nullptr;
    return PyLong_FromLongLong(t->get(k, dflt));
}

PyObject* py_i64_put(PyObject*, PyObject* args) {
    PyObject* cap;
    long long k, v;
    if (!PyArg_ParseTuple(args, "OLL", &cap, &k, &v)) return nullptr;
    I64Table* t = get_i64(cap);
    if (!t) return nullptr;
    t->put(k, v);
    Py_RETURN_NONE;
}

PyObject* py_i64_del(PyObject*, PyObject* args) {
    PyObject* cap;
    long long k, dflt;
    if (!PyArg_ParseTuple(args, "OLL", &cap, &k, &dflt)) return nullptr;
    I64Table* t = get_i64(cap);
    if (!t) return nullptr;
    return PyLong_FromLongLong(t->del(k, dflt));
}

bool in_buffer(PyObject* obj, Py_buffer* view) {
    return PyObject_GetBuffer(obj, view, PyBUF_C_CONTIGUOUS) == 0;
}

// (table, keys int64[n], dflt, out int64[n])
PyObject* py_i64_lookup_batch(PyObject*, PyObject* args) {
    PyObject *cap, *keys, *out;
    long long dflt;
    if (!PyArg_ParseTuple(args, "OOLO", &cap, &keys, &dflt, &out)) return nullptr;
    I64Table* t = get_i64(cap);
    if (!t) return nullptr;
    Py_buffer kb, ob;
    if (!in_buffer(keys, &kb)) return nullptr;
    Py_ssize_t n = kb.len / (Py_ssize_t)sizeof(int64_t);
    if (!out_buffer(out, &ob, n)) { PyBuffer_Release(&kb); return nullptr; }
    const int64_t* ks = (const int64_t*)kb.buf;
    int64_t* dst = (int64_t*)ob.buf;
    for (Py_ssize_t i = 0; i < n; i++) dst[i] = t->get(ks[i], dflt);
    PyBuffer_Release(&ob);
    PyBuffer_Release(&kb);
    Py_RETURN_NONE;
}

// (table, keys int64[n], vals int64[n])
PyObject* py_i64_put_batch(PyObject*, PyObject* args) {
    PyObject *cap, *keys, *vals;
    if (!PyArg_ParseTuple(args, "OOO", &cap, &keys, &vals)) return nullptr;
    I64Table* t = get_i64(cap);
    if (!t) return nullptr;
    Py_buffer kb, vb;
    if (!in_buffer(keys, &kb)) return nullptr;
    if (!in_buffer(vals, &vb)) { PyBuffer_Release(&kb); return nullptr; }
    Py_ssize_t n = kb.len / (Py_ssize_t)sizeof(int64_t);
    const int64_t* ks = (const int64_t*)kb.buf;
    const int64_t* vs = (const int64_t*)vb.buf;
    t->batch_begin((size_t)n);
    size_t pb_before = t->count;
    for (Py_ssize_t i = 0; i < n; i++) t->put(ks[i], vs[i]);
    t->batch_end((size_t)n, t->count - pb_before);
    PyBuffer_Release(&vb);
    PyBuffer_Release(&kb);
    Py_RETURN_NONE;
}

// (table, keys int64[n], next, out int64[n]) -> n_new
PyObject* py_i64_get_or_assign_batch(PyObject*, PyObject* args) {
    PyObject *cap, *keys, *out;
    long long next;
    if (!PyArg_ParseTuple(args, "OOLO", &cap, &keys, &next, &out)) return nullptr;
    I64Table* t = get_i64(cap);
    if (!t) return nullptr;
    Py_buffer kb, ob;
    if (!in_buffer(keys, &kb)) return nullptr;
    Py_ssize_t n = kb.len / (Py_ssize_t)sizeof(int64_t);
    if (!out_buffer(out, &ob, n)) { PyBuffer_Release(&kb); return nullptr; }
    const int64_t* ks = (const int64_t*)kb.buf;
    int64_t* dst = (int64_t*)ob.buf;
    t->batch_begin((size_t)n);
    int64_t start = next;
    for (Py_ssize_t i = 0; i < n; i++) {
        int64_t v = t->get(ks[i], INT64_MIN);
        if (v == INT64_MIN) {
            v = next++;
            t->put(ks[i], v);
        }
        dst[i] = v;
    }
    t->batch_end((size_t)n, (size_t)(next - start));
    PyBuffer_Release(&ob);
    PyBuffer_Release(&kb);
    return PyLong_FromLongLong(next - start);
}

// Bool mask of non-None entries of a list, returned as raw bytes (the
// Python side views it as a bool ndarray).  The per-row `v is not None`
// generator over multi-million-row value columns is one of the largest
// host costs in the merge dispatch (engine/tpu.py staging).
static PyObject* py_nonnull_mask(PyObject*, PyObject* args) {
    PyObject* lst;
    if (!PyArg_ParseTuple(args, "O", &lst)) return nullptr;
    if (!PyList_CheckExact(lst)) {
        PyErr_SetString(PyExc_TypeError, "nonnull_mask expects a list");
        return nullptr;
    }
    Py_ssize_t n = PyList_GET_SIZE(lst);
    // bytearray (not bytes): numpy views over it stay WRITABLE, matching
    // the pure-Python fallback's mutability contract
    PyObject* out = PyByteArray_FromStringAndSize(nullptr, n);
    if (!out) return nullptr;
    char* p = PyByteArray_AS_STRING(out);
    for (Py_ssize_t i = 0; i < n; i++)
        p[i] = PyList_GET_ITEM(lst, i) != Py_None;
    return out;
}

PyMethodDef methods[] = {
    {"nonnull_mask", py_nonnull_mask, METH_VARARGS,
     "nonnull_mask(list) -> bytearray bool mask of non-None entries"},
    {"strtab_new", py_strtab_new, METH_VARARGS, ""},
    {"strtab_len", py_strtab_len, METH_VARARGS, ""},
    {"strtab_get_or_insert", py_strtab_get_or_insert, METH_VARARGS, ""},
    {"strtab_lookup", py_strtab_lookup, METH_VARARGS, ""},
    {"strtab_get_or_insert_batch", py_strtab_get_or_insert_batch, METH_VARARGS, ""},
    {"strtab_lookup_batch", py_strtab_lookup_batch, METH_VARARGS, ""},
    {"strtab_bytes_of", py_strtab_bytes_of, METH_VARARGS, ""},
    {"i64_new", py_i64_new, METH_VARARGS, ""},
    {"i64_len", py_i64_len, METH_VARARGS, ""},
    {"i64_get", py_i64_get, METH_VARARGS, ""},
    {"i64_put", py_i64_put, METH_VARARGS, ""},
    {"i64_del", py_i64_del, METH_VARARGS, ""},
    {"i64_lookup_batch", py_i64_lookup_batch, METH_VARARGS, ""},
    {"i64_put_batch", py_i64_put_batch, METH_VARARGS, ""},
    {"i64_get_or_assign_batch", py_i64_get_or_assign_batch, METH_VARARGS, ""},
    {"resp_parse", py_resp_parse, METH_VARARGS,
     "resp_parse(buf, pos, Arr, Bulk, Int, Simple, Err, nil[, max]) -> "
     "(msgs, new_pos, fallback)"},
    {"resp_encode", py_resp_encode, METH_VARARGS,
     "resp_encode(out, msg, Arr, Bulk, Int, Simple, Err, NilT, NoReplyT) "
     "-> appended? (False = caller must use the pure-Python encoder)"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "cst_ext",
    "Native staging tables (CPython binding)", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit_cst_ext(void) { return PyModule_Create(&moduledef); }
