// Native RESP fast path: parse flat command arrays at C speed.
//
// The op path is parse-bound (OPBENCH.md): every pipelined client command
// is a flat `*N` array of `$` bulks / `:` ints, and the pure-Python
// scanner costs ~10us per message.  The reference answers the same
// pressure with N parse THREADS feeding one exec thread (reference
// README.md:12, src/lib.rs:138-142); this build keeps the single-writer
// asyncio loop and moves the parse itself into C instead.
//
// resp_parse(buffer, pos, Arr, Bulk, Int, Simple, Err, nil[, max_msgs])
// scans from `pos` and returns (messages, new_pos, fallback):
//   * messages — list of fully-constructed message objects (instances
//     built via tp_alloc + slot set, skipping __init__ bytecode).
//     Coverage: the full value grammar recursively — `*N` arrays
//     (including `*0` → Arr([]) and `*-1` → nil, nested to a small C
//     depth cap), `+simple`, `-err`, `:int`, `$bulk`, `$-1` (nil) —
//     i.e. both directions of the protocol, commands AND replies
//     (r18: reply arrays used to defer on `*0`/nesting, which made
//     every pipelined read client pay the pure-parser price for empty
//     and hash-pair replies);
//   * new_pos  — first unconsumed byte (a partial trailing message is
//     left unconsumed);
//   * fallback — true when the next message needs the general parser:
//     over-deep nesting, unknown type byte, or ANY shape this fast
//     path cannot parse cleanly (overlong integers, malformed framing,
//     oversized bulks...).  The pure-Python parser is the semantics
//     reference — it either accepts what C was too strict for (e.g. a
//     bare CR inside a simple line, a >64-bit integer) or raises its own
//     InvalidRequestMsg — so deferring to it on every non-clean parse
//     keeps behavior bit-identical, error text included.  The C side
//     itself raises only on CPython allocation failures.
//
// Messages parsed BEFORE a bad frame in the same scan are still returned
// (the caller executes them, then the pure parser surfaces the error) —
// the same delivery order the pure parser produces one call at a time.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>
#include <string>

namespace resp {

constexpr Py_ssize_t kMaxLine = 1 << 20;
constexpr Py_ssize_t kMaxArr = 1 << 20;
constexpr long long kMaxBulk = 512LL << 20;
constexpr Py_ssize_t kMaxDigits = 18;  // always < LLONG_MAX: no overflow UB

struct Names {
    PyObject* val = nullptr;
    PyObject* items = nullptr;
};

inline Names& names() {
    static Names n;
    if (!n.val) {
        n.val = PyUnicode_InternFromString("val");
        n.items = PyUnicode_InternFromString("items");
    }
    return n;
}

// Object construction without __init__: alloc the (slotted, dict-less)
// instance and set its single slot.  Steals `value`.
inline PyObject* make1(PyObject* type, PyObject* name, PyObject* value) {
    if (!value) return nullptr;
    PyTypeObject* t = reinterpret_cast<PyTypeObject*>(type);
    PyObject* obj = t->tp_alloc(t, 0);
    if (!obj) {
        Py_DECREF(value);
        return nullptr;
    }
    if (PyObject_SetAttr(obj, name, value) != 0) {
        Py_DECREF(value);
        Py_DECREF(obj);
        return nullptr;
    }
    Py_DECREF(value);
    return obj;
}

// Scan an integer line "<digits>\r\n" (optionally signed) starting at p.
// Returns: 1 ok, 0 need-more, -1 not fast-parseable (caller falls back to
// the pure parser; no python error is set).
inline int int_line(const char* b, Py_ssize_t len, Py_ssize_t p,
                    long long* out, Py_ssize_t* next) {
    const char* cr = static_cast<const char*>(
        memchr(b + p, '\r', static_cast<size_t>(len - p)));
    if (!cr || cr - b + 1 >= len) {
        if (len - p > kMaxLine) return -1;  // pure parser raises
        return 0;
    }
    Py_ssize_t e = cr - b;
    if (b[e + 1] != '\n') return -1;  // bare CR: defer to pure parser
    bool neg = false;
    Py_ssize_t i = p;
    if (i < e && (b[i] == '-' || b[i] == '+')) {
        neg = b[i] == '-';
        i++;
    }
    // > kMaxDigits would overflow long long (UB) — and the pure parser
    // handles arbitrary-precision integers correctly, so defer
    if (i >= e || e - i > kMaxDigits) return -1;
    long long v = 0;
    for (; i < e; i++) {
        if (b[i] < '0' || b[i] > '9') return -1;
        v = v * 10 + (b[i] - '0');
    }
    *out = neg ? -v : v;
    *next = e + 2;
    return 1;
}

// the C recursion cap for nested reply arrays: well under the pure
// parser's max_depth=32, so anything deeper defers (the pure parser
// then builds it or raises "nesting too deep" — identical either way)
constexpr int kMaxCDepth = 8;

struct ParseCtx {
    const char* b;
    Py_ssize_t len;
    PyObject *arr_t, *bulk_t, *int_t, *simple_t, *err_t, *nil_obj;
    long long bulk_cap;
};

// Parse ONE value of the RESP grammar starting at *pos.
// Returns: 1 ok (*out set, *pos advanced), 0 need-more, -1 defer to the
// pure parser, -2 CPython error (exception set).  *pos is only advanced
// on success; `fullsync` (top-level arrays only) reports a frame whose
// first element is the bulk "fullsync" — raw snapshot bytes follow it on
// the stream, so the caller must stop the batch scan there.
inline int parse_any(const ParseCtx& c, Py_ssize_t* pos, int depth,
                     PyObject** out, bool* fullsync) {
    if (*pos >= c.len) return 0;
    const char* b = c.b;
    const Py_ssize_t len = c.len;
    Names& nm = names();
    const char t = b[*pos];
    if (t == '+' || t == '-') {
        // simple / error line.  The pure parser's _line scans for the
        // CRLF PAIR, so a bare CR inside the line is part of the payload
        // there — defer rather than diverge.
        const char* cr = static_cast<const char*>(
            memchr(b + *pos, '\r', static_cast<size_t>(len - *pos)));
        if (!cr || cr - b + 1 >= len) {
            if (len - *pos > kMaxLine) return -1;  // pure parser raises
            return 0;
        }
        Py_ssize_t e = cr - b;
        if (b[e + 1] != '\n') return -1;
        PyObject* obj = make1(
            t == '+' ? c.simple_t : c.err_t, nm.val,
            PyBytes_FromStringAndSize(b + *pos + 1, e - *pos - 1));
        if (!obj) return -2;
        *out = obj;
        *pos = e + 2;
        return 1;
    }
    if (t == ':') {
        long long v;
        Py_ssize_t q;
        int st = int_line(b, len, *pos + 1, &v, &q);
        if (st <= 0) return st;
        PyObject* obj = make1(c.int_t, nm.val, PyLong_FromLongLong(v));
        if (!obj) return -2;
        *out = obj;
        *pos = q;
        return 1;
    }
    if (t == '$') {
        long long ln;
        Py_ssize_t q;
        int st = int_line(b, len, *pos + 1, &ln, &q);
        if (st <= 0) return st;
        if (ln < 0) {
            if (ln != -1) return -1;  // pure parser raises
            Py_INCREF(c.nil_obj);
            *out = c.nil_obj;
            *pos = q;
            return 1;
        }
        if (ln > c.bulk_cap) return -1;  // pure parser raises "too large"
        if (q + ln + 2 > len) return 0;  // need more
        if (b[q + ln] != '\r' || b[q + ln + 1] != '\n')
            return -1;  // pure parser raises "missing CRLF"
        PyObject* obj = make1(c.bulk_t, nm.val,
                              PyBytes_FromStringAndSize(b + q, ln));
        if (!obj) return -2;
        *out = obj;
        *pos = q + ln + 2;
        return 1;
    }
    if (t != '*') return -1;  // unknown type byte: pure parser raises
    if (depth >= kMaxCDepth) return -1;  // pure parser handles/raises
    long long cnt;
    Py_ssize_t p;
    int st = int_line(b, len, *pos + 1, &cnt, &p);
    if (st <= 0) return st;
    if (cnt < 0) {
        if (cnt != -1) return -1;  // pure parser raises
        Py_INCREF(c.nil_obj);
        *out = c.nil_obj;
        *pos = p;
        return 1;
    }
    if (cnt > kMaxArr) return -1;  // pure parser raises "too large"
    PyObject* items = PyList_New(cnt);
    if (!items) return -2;
    for (long long i = 0; i < cnt; i++) {
        PyObject* obj = nullptr;
        int st2 = parse_any(c, &p, depth + 1, &obj, nullptr);
        if (st2 != 1) {
            Py_DECREF(items);  // safe: unfilled tail slots are NULL
            return st2;
        }
        PyList_SET_ITEM(items, i, obj);
        // a FULLSYNC frame is followed by RAW (non-RESP) snapshot bytes
        // on the same stream; scanning past it would consume them as
        // frames (replica/link.py drains them via take_raw)
        if (i == 0 && fullsync != nullptr && Py_TYPE(obj) ==
                reinterpret_cast<PyTypeObject*>(c.bulk_t)) {
            PyObject* v = PyObject_GetAttr(obj, nm.val);
            if (!v) {
                Py_DECREF(items);
                return -2;
            }
            if (PyBytes_Check(v) && PyBytes_GET_SIZE(v) == 8 &&
                strncasecmp(PyBytes_AS_STRING(v), "fullsync", 8) == 0)
                *fullsync = true;
            Py_DECREF(v);
        }
    }
    PyObject* arr = make1(c.arr_t, nm.items, items);
    if (!arr) return -2;
    *out = arr;
    *pos = p;
    return 1;
}

}  // namespace resp

static PyObject* py_resp_parse(PyObject*, PyObject* args) {
    Py_buffer view;
    Py_ssize_t pos;
    PyObject *arr_t, *bulk_t, *int_t, *simple_t, *err_t, *nil_obj;
    Py_ssize_t max_msgs = 1024;
    // configurable parse-time bulk ceiling (CONSTDB_PROTO_MAX_BULK):
    // a $-header past it defers to the pure parser, which raises the
    // protocol error — never buffers toward the declared length.
    // Clamped to the wire format's hard 512MB ceiling; <= 0 = default.
    long long max_bulk = 0;
    if (!PyArg_ParseTuple(args, "y*nOOOOOO|nL", &view, &pos, &arr_t, &bulk_t,
                          &int_t, &simple_t, &err_t, &nil_obj, &max_msgs,
                          &max_bulk))
        return nullptr;
    const long long bulk_cap =
        (max_bulk > 0 && max_bulk < resp::kMaxBulk) ? max_bulk
                                                    : resp::kMaxBulk;
    resp::ParseCtx ctx{static_cast<const char*>(view.buf), view.len,
                       arr_t, bulk_t, int_t, simple_t, err_t, nil_obj,
                       bulk_cap};

    PyObject* out = PyList_New(0);
    int fallback = 0;
    if (!out) {
        PyBuffer_Release(&view);
        return nullptr;
    }

    while (PyList_GET_SIZE(out) < max_msgs && pos < ctx.len) {
        PyObject* obj = nullptr;
        bool is_fullsync = false;
        Py_ssize_t p = pos;
        int st = resp::parse_any(ctx, &p, 0, &obj, &is_fullsync);
        if (st == 0) break;  // partial trailing message: need more bytes
        if (st == -1) {
            fallback = 1;  // defer this message to the pure parser
            break;
        }
        if (st == -2) goto fail;
        int rc = PyList_Append(out, obj);
        Py_DECREF(obj);
        if (rc != 0) goto fail;
        pos = p;
        if (is_fullsync) break;  // raw snapshot bytes follow
    }

    PyBuffer_Release(&view);
    return Py_BuildValue("(Nni)", out, pos, fallback);

fail:
    Py_DECREF(out);
    PyBuffer_Release(&view);
    return nullptr;
}

// ---------------------------------------------------------------- encoder
//
// resp_encode(out_bytearray, msg, Arr, Bulk, Int, Simple, Err, NilT, NoReplyT)
// appends msg's wire encoding to `out` and returns True, or returns False
// when msg has ANY shape this fast path cannot encode cleanly (subclass,
// non-bytes payload, >64-bit int, NoReply inside an Arr...) — the caller
// then falls back to the pure-Python encoder, which either handles it or
// raises its own error, keeping behavior identical.  Small non-negative
// int replies are interned (parity: reference src/resp.rs:12-27 pre-builds
// the common counter replies).

namespace resp {

constexpr int kInternedInts = 10000;

inline const std::string* interned_int(long long v) {
    static std::string table[kInternedInts];
    static bool built = false;
    if (!built) {
        char buf[32];
        for (int i = 0; i < kInternedInts; i++) {
            int n = snprintf(buf, sizeof buf, ":%d\r\n", i);
            table[i].assign(buf, static_cast<size_t>(n));
        }
        built = true;
    }
    return (v >= 0 && v < kInternedInts) ? &table[v] : nullptr;
}

struct EncTypes {
    PyTypeObject *arr, *bulk, *i, *simple, *err, *nil, *noreply;
};

// returns 1 ok, 0 fallback-needed (no python error set), -1 python error
inline int encode1(std::string& out, PyObject* m, const EncTypes& t,
                   int depth, bool top) {
    if (depth > 32) return 0;
    PyTypeObject* ty = Py_TYPE(m);
    if (ty == t.noreply) return top ? 1 : 0;  // inside Arr: pure path raises
    if (ty == t.nil) {
        out.append("$-1\r\n", 5);
        return 1;
    }
    Names& nm = names();
    if (ty == t.i) {
        PyObject* val = PyObject_GetAttr(m, nm.val);
        if (!val) return -1;
        if (!PyLong_CheckExact(val)) {
            Py_DECREF(val);
            return 0;
        }
        int overflow = 0;
        long long v = PyLong_AsLongLongAndOverflow(val, &overflow);
        Py_DECREF(val);
        if (overflow || (v == -1 && PyErr_Occurred())) {
            PyErr_Clear();
            return 0;  // arbitrary-precision: pure path formats it
        }
        if (const std::string* s = interned_int(v)) {
            out.append(*s);
        } else {
            char buf[32];
            int n = snprintf(buf, sizeof buf, ":%lld\r\n", v);
            out.append(buf, static_cast<size_t>(n));
        }
        return 1;
    }
    if (ty == t.bulk || ty == t.simple || ty == t.err) {
        PyObject* val = PyObject_GetAttr(m, nm.val);
        if (!val) return -1;
        if (!PyBytes_CheckExact(val)) {
            Py_DECREF(val);
            return 0;
        }
        char* p;
        Py_ssize_t n;
        PyBytes_AsStringAndSize(val, &p, &n);
        if (ty == t.bulk) {
            char head[32];
            int hn = snprintf(head, sizeof head, "$%lld\r\n",
                              static_cast<long long>(n));
            out.append(head, static_cast<size_t>(hn));
            out.append(p, static_cast<size_t>(n));
            out.append("\r\n", 2);
        } else {
            out.push_back(ty == t.simple ? '+' : '-');
            out.append(p, static_cast<size_t>(n));
            out.append("\r\n", 2);
        }
        Py_DECREF(val);
        return 1;
    }
    if (ty == t.arr) {
        PyObject* items = PyObject_GetAttr(m, nm.items);
        if (!items) return -1;
        if (!PyList_CheckExact(items)) {
            Py_DECREF(items);
            return 0;
        }
        Py_ssize_t n = PyList_GET_SIZE(items);
        char head[32];
        int hn = snprintf(head, sizeof head, "*%lld\r\n",
                          static_cast<long long>(n));
        out.append(head, static_cast<size_t>(hn));
        for (Py_ssize_t j = 0; j < n; j++) {
            int rc = encode1(out, PyList_GET_ITEM(items, j), t, depth + 1,
                             false);
            if (rc != 1) {
                Py_DECREF(items);
                return rc;
            }
        }
        Py_DECREF(items);
        return 1;
    }
    return 0;  // unknown / subclassed message type
}

}  // namespace resp

static PyObject* py_resp_encode(PyObject*, PyObject* args) {
    PyObject *out, *msg;
    resp::EncTypes t;
    if (!PyArg_ParseTuple(args, "OOOOOOOOO", &out, &msg, &t.arr, &t.bulk,
                          &t.i, &t.simple, &t.err, &t.nil, &t.noreply))
        return nullptr;
    if (!PyByteArray_CheckExact(out)) {
        PyErr_SetString(PyExc_TypeError, "out must be a bytearray");
        return nullptr;
    }
    std::string buf;
    int rc = resp::encode1(buf, msg, t, 0, true);
    if (rc < 0) return nullptr;
    if (rc == 0) Py_RETURN_FALSE;
    Py_ssize_t old = PyByteArray_GET_SIZE(out);
    if (PyByteArray_Resize(out, old + static_cast<Py_ssize_t>(buf.size())))
        return nullptr;
    memcpy(PyByteArray_AS_STRING(out) + old, buf.data(), buf.size());
    Py_RETURN_TRUE;
}
