// CRC-64/XZ (reflected, poly 0x42F0E1EBA9EA3693) — slice-by-8.
// Native counterpart of constdb_tpu/utils/checksum.py; loaded via ctypes.
#include <cstdint>
#include <cstddef>

namespace {

constexpr uint64_t kPoly = 0xC96C5795D7870F42ULL;

struct Tables {
    uint64_t t[8][256];
    Tables() {
        for (int i = 0; i < 256; i++) {
            uint64_t crc = (uint64_t)i;
            for (int k = 0; k < 8; k++)
                crc = (crc & 1) ? (crc >> 1) ^ kPoly : crc >> 1;
            t[0][i] = crc;
        }
        for (int i = 0; i < 256; i++)
            for (int s = 1; s < 8; s++)
                t[s][i] = (t[s - 1][i] >> 8) ^ t[0][t[s - 1][i] & 0xFF];
    }
};

const Tables kTables;

}  // namespace

extern "C" uint64_t cst_crc64(uint64_t crc, const unsigned char* data, size_t len) {
    crc = ~crc;
    const uint64_t(*t)[256] = kTables.t;
    while (len >= 8) {
        crc ^= (uint64_t)data[0] | ((uint64_t)data[1] << 8) | ((uint64_t)data[2] << 16) |
               ((uint64_t)data[3] << 24) | ((uint64_t)data[4] << 32) | ((uint64_t)data[5] << 40) |
               ((uint64_t)data[6] << 48) | ((uint64_t)data[7] << 56);
        crc = t[7][crc & 0xFF] ^ t[6][(crc >> 8) & 0xFF] ^ t[5][(crc >> 16) & 0xFF] ^
              t[4][(crc >> 24) & 0xFF] ^ t[3][(crc >> 32) & 0xFF] ^ t[2][(crc >> 40) & 0xFF] ^
              t[1][(crc >> 48) & 0xFF] ^ t[0][crc >> 56];
        data += 8;
        len -= 8;
    }
    while (len--) crc = kTables.t[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
    return ~crc;
}
