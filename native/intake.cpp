// Native intake engine: classify + pre-parse pipelined client commands.
//
// The serve coalescer (server/serve.py) plans a fixed command set into
// columnar planes; everything else executes per-command.  This stage
// moves the per-command *intake* — RESP framing, argument extraction,
// command classification — into C: intake_scan drains a connection's
// pipelined bytes in one call and emits an opcode string + per-command
// payloads the Python planners consume without ever constructing message
// objects for the plannable set.  The split it encodes is EXACTLY the
// coalescer's plannable/barrier split; the Python side remains the
// semantics reference, and anything this scan cannot take cleanly is
// left unconsumed for the reference path (byte-identical replies,
// planes, and replication log either way — tests/test_resp_fuzz.py
// pins the differential).
//
// NATIVE-INTAKE-TABLE-BEGIN (parsed by analysis/rules.py NATIVE-CONTRACT)
//   native: set incr decr sadd srem hset hdel
//   native-reads: get scnt sismember smembers hget hgetall llen hlen
//   python-only: cntundo tensor.set tensor.merge lrange
// NATIVE-INTAKE-TABLE-END
//
// Routability contract (cluster mode): every native/native-reads entry
// must be slot-routable — first-key-confined, non-CTRL, non-empty
// families — because the serve coalescer extracts the routing key from
// the scanned payload (payloads[i][1][0] for writes, payloads[i][0] for
// reads) to demote would-redirect commands back to the per-command
// path.  A CTRL or keyless command in these rows would fast-path here
// while the slot router skips it; the NATIVE-CONTRACT lint's
// `:unroutable` direction rejects that statically.
//
// intake_scan(buf, pos, Arr, Bulk, Int, Simple, Err, nil[, max_bulk,
// max_msgs]) returns (ops, payloads, new_pos):
//   * ops      — bytes; ops[i] is message i's opcode (Op below; 0 means
//                not natively plannable — payloads[i] is the full parsed
//                message object and the Python coalescer handles it).
//   * payloads — write opcodes (1..9): a (bulks, raws) pair — bulks is
//                the list of Bulk objects for items[1:] (the replication
//                log args), raws the same payload bytes as a tuple (the
//                planner inputs); one underlying bytes object per item,
//                shared between both views.  Read opcodes (10..16): the
//                raws tuple alone (a message object is rebuilt on the
//                Python side only if the read demotes).  OP_OTHER: the
//                message object itself.
//   * new_pos  — first unconsumed byte.
//
// The scan STOPS (leaving the remainder for the pure drain path) on: a
// non-'*' top byte, partial/malformed frames, any shape resp::parse_any
// defers on, and any message whose first element is the bulk "sync" or
// "fullsync" (connection upgrades belong to the io loop).  Stopping is
// always exact — unconsumed bytes re-parse through the reference path.
//
// No code in this file mutates store state: the outputs are inert
// opcodes + payload views; every merge still flows through the Python
// coalescer's planes (docs/INVARIANTS.md, native plane laws).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>
#include <string>

namespace intake {

enum Op : unsigned char {
    OP_OTHER = 0,
    // writes (plannable: SERVE_PLANNERS mirrors)
    OP_SET = 1,
    OP_INCR1 = 2,  // incr without an explicit delta
    OP_INCR = 3,
    OP_DECR1 = 4,
    OP_DECR = 5,
    OP_SADD = 6,
    OP_SREM = 7,
    OP_HSET = 8,
    OP_HDEL = 9,
    // reads (plannable: SERVE_READS mirrors)
    OP_GET = 10,
    OP_SCNT = 11,
    OP_SISMEMBER = 12,
    OP_SMEMBERS = 13,
    OP_HGET = 14,
    OP_HGETALL = 15,
    OP_LLEN = 16,
    OP_HLEN = 17,
};

constexpr unsigned char kFirstRead = OP_GET;
constexpr Py_ssize_t kMaxFlatItems = 512;

struct FlatCmd {
    Py_ssize_t off[kMaxFlatItems];
    Py_ssize_t len[kMaxFlatItems];
    Py_ssize_t n = 0;
    Py_ssize_t end = 0;  // first byte after the message
};

// Scan one flat command array (`*N` of `$` bulks only) starting at p.
// Returns 1 ok, 0 need-more, -1 not-flat / malformed / over caps (the
// caller retries via resp::parse_any or stops the scan).
inline int scan_flat(const char* b, Py_ssize_t blen, Py_ssize_t p,
                     long long bulk_cap, FlatCmd* fc) {
    long long cnt;
    Py_ssize_t q;
    int st = resp::int_line(b, blen, p + 1, &cnt, &q);
    if (st <= 0) return st;
    if (cnt < 0 || cnt > kMaxFlatItems) return -1;
    for (long long i = 0; i < cnt; i++) {
        if (q >= blen) return 0;
        if (b[q] != '$') return -1;
        long long ln;
        Py_ssize_t r;
        st = resp::int_line(b, blen, q + 1, &ln, &r);
        if (st <= 0) return st;
        if (ln < 0 || ln > bulk_cap) return -1;
        if (r + ln + 2 > blen) return 0;
        if (b[r + ln] != '\r' || b[r + ln + 1] != '\n') return -1;
        fc->off[i] = r;
        fc->len[i] = ln;
        q = r + ln + 2;
    }
    fc->n = (Py_ssize_t)cnt;
    fc->end = q;
    return 1;
}

// Opcode for a lowercase command name + total item count.  Arity gates
// mirror the Python planners EXACTLY (anything they would demote on —
// wrong arity, extra args — classifies OP_OTHER and takes the reference
// path, where the planner itself decides).  Uppercase names also take
// OP_OTHER: the Python _planner_of lowercases and plans identically.
inline unsigned char classify(const char* nm, Py_ssize_t nl, Py_ssize_t n) {
    switch (nl) {
        case 3:
            if (!memcmp(nm, "set", 3)) return n == 3 ? OP_SET : OP_OTHER;
            if (!memcmp(nm, "get", 3)) return n == 2 ? OP_GET : OP_OTHER;
            break;
        case 4:
            if (!memcmp(nm, "incr", 4))
                return n == 2 ? OP_INCR1 : (n == 3 ? OP_INCR : OP_OTHER);
            if (!memcmp(nm, "decr", 4))
                return n == 2 ? OP_DECR1 : (n == 3 ? OP_DECR : OP_OTHER);
            if (!memcmp(nm, "sadd", 4)) return n >= 3 ? OP_SADD : OP_OTHER;
            if (!memcmp(nm, "srem", 4)) return n >= 3 ? OP_SREM : OP_OTHER;
            if (!memcmp(nm, "hset", 4))
                return (n >= 4 && !(n & 1)) ? OP_HSET : OP_OTHER;
            if (!memcmp(nm, "hdel", 4)) return n >= 3 ? OP_HDEL : OP_OTHER;
            if (!memcmp(nm, "scnt", 4)) return n == 2 ? OP_SCNT : OP_OTHER;
            if (!memcmp(nm, "hget", 4)) return n == 3 ? OP_HGET : OP_OTHER;
            if (!memcmp(nm, "llen", 4)) return n == 2 ? OP_LLEN : OP_OTHER;
            if (!memcmp(nm, "hlen", 4)) return n == 2 ? OP_HLEN : OP_OTHER;
            break;
        case 7:
            if (!memcmp(nm, "hgetall", 7))
                return n == 2 ? OP_HGETALL : OP_OTHER;
            break;
        case 8:
            if (!memcmp(nm, "smembers", 8))
                return n == 2 ? OP_SMEMBERS : OP_OTHER;
            break;
        case 9:
            if (!memcmp(nm, "sismember", 9))
                return n == 3 ? OP_SISMEMBER : OP_OTHER;
            break;
    }
    return OP_OTHER;
}

// (bulks, raws) for a write opcode: items[1:] as Bulk objects AND as the
// same underlying bytes in a tuple.
inline PyObject* write_payload(const resp::ParseCtx& c, const FlatCmd& fc) {
    Py_ssize_t m = fc.n - 1;
    resp::Names& nm = resp::names();
    PyObject* bulks = PyList_New(m);
    PyObject* raws = PyTuple_New(m);
    if (!bulks || !raws) {
        Py_XDECREF(bulks);
        Py_XDECREF(raws);
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < m; i++) {
        PyObject* raw = PyBytes_FromStringAndSize(c.b + fc.off[i + 1],
                                                  fc.len[i + 1]);
        if (!raw) {
            Py_DECREF(bulks);
            Py_DECREF(raws);
            return nullptr;
        }
        PyTuple_SET_ITEM(raws, i, raw);  // steals
        Py_INCREF(raw);                  // for make1, which steals too
        PyObject* blk = resp::make1(c.bulk_t, nm.val, raw);
        if (!blk) {
            Py_DECREF(bulks);
            Py_DECREF(raws);
            return nullptr;
        }
        PyList_SET_ITEM(bulks, i, blk);
    }
    PyObject* pay = PyTuple_New(2);
    if (!pay) {
        Py_DECREF(bulks);
        Py_DECREF(raws);
        return nullptr;
    }
    PyTuple_SET_ITEM(pay, 0, bulks);
    PyTuple_SET_ITEM(pay, 1, raws);
    return pay;
}

// raws tuple for a read opcode: items[1:] as bytes.
inline PyObject* read_payload(const resp::ParseCtx& c, const FlatCmd& fc) {
    Py_ssize_t m = fc.n - 1;
    PyObject* raws = PyTuple_New(m);
    if (!raws) return nullptr;
    for (Py_ssize_t i = 0; i < m; i++) {
        PyObject* raw = PyBytes_FromStringAndSize(c.b + fc.off[i + 1],
                                                  fc.len[i + 1]);
        if (!raw) {
            Py_DECREF(raws);
            return nullptr;
        }
        PyTuple_SET_ITEM(raws, i, raw);
    }
    return raws;
}

// Full message object for a flat OP_OTHER command (avoids re-parsing).
inline PyObject* flat_msg(const resp::ParseCtx& c, const FlatCmd& fc) {
    resp::Names& nm = resp::names();
    PyObject* items = PyList_New(fc.n);
    if (!items) return nullptr;
    for (Py_ssize_t i = 0; i < fc.n; i++) {
        PyObject* blk = resp::make1(
            c.bulk_t, nm.val,
            PyBytes_FromStringAndSize(c.b + fc.off[i], fc.len[i]));
        if (!blk) {
            Py_DECREF(items);
            return nullptr;
        }
        PyList_SET_ITEM(items, i, blk);
    }
    return resp::make1(c.arr_t, nm.items, items);
}

// "sync" / "fullsync" (case-insensitive), matching the io loop's upgrade
// scan — such frames must surface through the reference path.
inline bool is_upgrade_name(const char* p, Py_ssize_t n) {
    return (n == 4 && strncasecmp(p, "sync", 4) == 0) ||
           (n == 8 && strncasecmp(p, "fullsync", 8) == 0);
}

// A parse_any-built message whose first element is an upgrade bulk.
// Returns 1 yes, 0 no, -1 python error.
inline int msg_is_upgrade(const resp::ParseCtx& c, PyObject* msg) {
    if (Py_TYPE(msg) != reinterpret_cast<PyTypeObject*>(c.arr_t)) return 0;
    resp::Names& nm = resp::names();
    PyObject* items = PyObject_GetAttr(msg, nm.items);
    if (!items) return -1;
    int res = 0;
    if (PyList_CheckExact(items) && PyList_GET_SIZE(items) > 0) {
        PyObject* head = PyList_GET_ITEM(items, 0);
        if (Py_TYPE(head) == reinterpret_cast<PyTypeObject*>(c.bulk_t)) {
            PyObject* v = PyObject_GetAttr(head, nm.val);
            if (!v) {
                Py_DECREF(items);
                return -1;
            }
            if (PyBytes_CheckExact(v) &&
                is_upgrade_name(PyBytes_AS_STRING(v), PyBytes_GET_SIZE(v)))
                res = 1;
            Py_DECREF(v);
        }
    }
    Py_DECREF(items);
    return res;
}

}  // namespace intake

static PyObject* py_intake_scan(PyObject*, PyObject* args) {
    Py_buffer view;
    Py_ssize_t pos;
    PyObject *arr_t, *bulk_t, *int_t, *simple_t, *err_t, *nil_obj;
    long long max_bulk = 0;
    Py_ssize_t max_msgs = 4096;
    if (!PyArg_ParseTuple(args, "y*nOOOOOO|Ln", &view, &pos, &arr_t, &bulk_t,
                          &int_t, &simple_t, &err_t, &nil_obj, &max_bulk,
                          &max_msgs))
        return nullptr;
    const long long bulk_cap =
        (max_bulk > 0 && max_bulk < resp::kMaxBulk) ? max_bulk
                                                    : resp::kMaxBulk;
    resp::ParseCtx ctx{static_cast<const char*>(view.buf), view.len,
                       arr_t, bulk_t, int_t, simple_t, err_t, nil_obj,
                       bulk_cap};
    std::string ops;
    PyObject* payloads = PyList_New(0);
    if (!payloads) {
        PyBuffer_Release(&view);
        return nullptr;
    }
    const char* b = ctx.b;
    while ((Py_ssize_t)ops.size() < max_msgs && pos < ctx.len) {
        if (b[pos] != '*') break;  // inline/garbage: pure parser decides
        intake::FlatCmd fc;
        int st = intake::scan_flat(b, ctx.len, pos, bulk_cap, &fc);
        if (st == 0) break;  // partial trailing message
        unsigned char op = intake::OP_OTHER;
        PyObject* payload = nullptr;
        if (st == 1) {
            if (fc.n > 0 &&
                intake::is_upgrade_name(b + fc.off[0], fc.len[0]))
                break;  // SYNC/FULLSYNC: the io loop owns the upgrade
            if (fc.n > 0)
                op = intake::classify(b + fc.off[0], fc.len[0], fc.n);
            if (op >= intake::kFirstRead)
                payload = intake::read_payload(ctx, fc);
            else if (op != intake::OP_OTHER)
                payload = intake::write_payload(ctx, fc);
            else
                payload = intake::flat_msg(ctx, fc);
            if (!payload) goto fail;
            pos = fc.end;
        } else {  // non-flat: nested/int items, nil counts... full parse
            Py_ssize_t p = pos;
            bool fullsync = false;
            int st2 = resp::parse_any(ctx, &p, 0, &payload, &fullsync);
            if (st2 == 0 || st2 == -1) break;  // pure parser's business
            if (st2 == -2) goto fail;
            int up = fullsync ? 1 : intake::msg_is_upgrade(ctx, payload);
            if (up != 0) {
                Py_DECREF(payload);
                if (up < 0) goto fail;
                break;  // leave the upgrade frame unconsumed
            }
            pos = p;
        }
        ops.push_back((char)op);
        int rc = PyList_Append(payloads, payload);
        Py_DECREF(payload);
        if (rc != 0) goto fail;
    }
    {
        PyObject* opb = PyBytes_FromStringAndSize(ops.data(),
                                                  (Py_ssize_t)ops.size());
        if (!opb) goto fail;
        PyBuffer_Release(&view);
        return Py_BuildValue("(NNn)", opb, payloads, pos);
    }
fail:
    Py_DECREF(payloads);
    PyBuffer_Release(&view);
    return nullptr;
}
