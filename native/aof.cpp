// Native AOF segment scanner: record framing + crc + frame decode in C.
//
// Boot replay is scan-bound before it is merge-bound: a segment is
// millions of tiny `len | crc32 | rtype | payload` records, and the
// pure-Python loop in persist/oplog.py scan_segment pays ~9us of
// interpreter dispatch per record before a single op applies.  The
// recovery bench (bench.py --mode recover) showed the scan+decode floor
// capping the bulk-replay speedup, so this moves the whole per-record
// walk into one C call per segment.
//
// aof_scan(buf, pos, max_record[, Arr, Bulk, Int, Simple, Err, nil])
//   -> (records, valid_pos)
//
//   * records — the maximal valid record prefix, in file order.  Every
//     record is `(rtype, payload_bytes)` — EXCEPT REC_FRAME records
//     when the six RESP message classes are passed AND the payload
//     parses cleanly, which come back pre-decoded as
//     `(2, origin, uuid, name_bytes, args_list)` so the replay loop
//     never touches the payload again (no intermediate payload bytes
//     object, no second parse pass).
//   * valid_pos — offset of the first invalid byte (the torn-tail
//     truncation point), exactly scan_segment's contract: short length
//     word, zero/oversized length, crc mismatch, or unknown rtype all
//     stop the scan there.
//
// Fidelity rule: a REC_FRAME payload is pre-decoded ONLY when this C
// path reproduces the Python decode bit-for-bit — canonical varint
// header, exactly one flat RESP array consuming the whole payload,
// first element a Bulk.  Anything else (overlong varint, trailing
// bytes, fallback-grade RESP, top-level non-array) degrades to the raw
// `(rtype, payload)` tuple and the Python side re-decodes it — and
// accepts or loudly skips it — through the reference path.  The crc
// is zlib.crc32 (CRC-32/ISO-HDLC), table-driven here.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>

namespace aof {

// NATIVE-AOF-TABLE-BEGIN (parsed by analysis/rules.py NATIVE-CONTRACT)
//   record-types: batch=1 frame=2 wmark=3
// NATIVE-AOF-TABLE-END
//
// The marker block above is the checkable contract with
// persist/oplog.py's REC_* constants: the lint cross-checks both
// directions (a REC_ constant the table doesn't know, a table entry
// with no REC_ twin, or a value drift all fail), so the two decoders
// can never silently classify each other's records as corruption.
constexpr int kRecBatch = 1;
constexpr int kRecFrame = 2;
constexpr int kRecWmark = 3;

inline const uint32_t* crc_table() {
    static uint32_t tab[256];
    static bool built = false;
    if (!built) {
        for (uint32_t i = 0; i < 256; i++) {
            uint32_t c = i;
            for (int k = 0; k < 8; k++)
                c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
            tab[i] = c;
        }
        built = true;
    }
    return tab;
}

inline uint32_t crc32(const uint8_t* p, Py_ssize_t n) {
    const uint32_t* tab = crc_table();
    uint32_t c = 0xFFFFFFFFu;
    for (Py_ssize_t i = 0; i < n; i++)
        c = tab[(c ^ p[i]) & 0xFFu] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

// Canonical uvarint (utils/varint.py write_uvarint's exact envelope:
// tag in the top 2 bits, big-endian value bytes, overlong forms
// REJECTED).  Returns 1 ok, 0 malformed/truncated — no Python error.
inline int uvarint(const uint8_t* b, Py_ssize_t len, Py_ssize_t* pos,
                   uint64_t* out) {
    Py_ssize_t p = *pos;
    if (p >= len) return 0;
    const uint8_t flag = b[p];
    const int tag = flag >> 6;
    if (tag == 0) {
        *out = flag;
        *pos = p + 1;
        return 1;
    }
    if (tag == 1) {
        if (p + 2 > len) return 0;
        const uint64_t v = ((uint64_t)(flag & 0x3Fu) << 8) | b[p + 1];
        if (v < (1u << 6)) return 0;  // non-canonical (overlong)
        *out = v;
        *pos = p + 2;
        return 1;
    }
    if (tag == 2) {
        if (p + 4 > len) return 0;
        const uint64_t v = ((uint64_t)(flag & 0x3Fu) << 24) |
                           ((uint64_t)b[p + 1] << 16) |
                           ((uint64_t)b[p + 2] << 8) | b[p + 3];
        if (v < (1u << 14)) return 0;
        *out = v;
        *pos = p + 4;
        return 1;
    }
    if (flag != 0xC0u) return 0;  // tag-3 flag low bits must be clear
    if (p + 9 > len) return 0;
    uint64_t v = 0;
    for (int i = 1; i <= 8; i++) v = (v << 8) | b[p + i];
    if (v < (1ull << 30)) return 0;
    *out = v;
    *pos = p + 9;
    return 1;
}

// Decode one REC_FRAME payload body into `(2, origin, uuid, name, args)`.
// Returns nullptr WITHOUT a Python error when the payload needs the
// pure-path fallback; nullptr WITH an error only on CPython failures.
PyObject* decode_frame(const uint8_t* p, Py_ssize_t len, PyObject* arr_t,
                       PyObject* bulk_t, PyObject* int_t,
                       PyObject* simple_t, PyObject* err_t,
                       PyObject* nil_obj) {
    Py_ssize_t pos = 0;
    uint64_t origin, uuid;
    if (!uvarint(p, len, &pos, &origin)) return nullptr;
    if (!uvarint(p, len, &pos, &uuid)) return nullptr;
    resp::ParseCtx ctx{reinterpret_cast<const char*>(p),
                       len,
                       arr_t,
                       bulk_t,
                       int_t,
                       simple_t,
                       err_t,
                       nil_obj,
                       resp::kMaxBulk};
    PyObject* msg = nullptr;
    const int st = resp::parse_any(ctx, &pos, 0, &msg, nullptr);
    if (st == -2) return nullptr;  // CPython error already set
    if (st != 1) return nullptr;   // partial / fallback-grade payload
    if (pos != len ||
        Py_TYPE(msg) != reinterpret_cast<PyTypeObject*>(arr_t)) {
        // trailing bytes, or a top-level non-array (nil/bulk/int...)
        Py_DECREF(msg);
        return nullptr;
    }
    PyObject* items = PyObject_GetAttr(msg, resp::names().items);
    Py_DECREF(msg);
    if (!items) return nullptr;  // error set
    if (!PyList_CheckExact(items) || PyList_GET_SIZE(items) < 1) {
        Py_DECREF(items);
        return nullptr;
    }
    PyObject* first = PyList_GET_ITEM(items, 0);
    if (Py_TYPE(first) != reinterpret_cast<PyTypeObject*>(bulk_t)) {
        Py_DECREF(items);
        return nullptr;
    }
    PyObject* name = PyObject_GetAttr(first, resp::names().val);
    if (!name) {
        Py_DECREF(items);
        return nullptr;  // error set
    }
    if (!PyBytes_CheckExact(name)) {
        Py_DECREF(name);
        Py_DECREF(items);
        return nullptr;
    }
    PyObject* rest = PyList_GetSlice(items, 1, PyList_GET_SIZE(items));
    Py_DECREF(items);
    if (!rest) {
        Py_DECREF(name);
        return nullptr;  // error set
    }
    // (iKKNN): N steals name/rest
    PyObject* rec =
        Py_BuildValue("(iKKNN)", kRecFrame, (unsigned long long)origin,
                      (unsigned long long)uuid, name, rest);
    return rec;  // nullptr -> error set, refs already consumed
}

// Raw-mode frame decode: a FLAT command array of bulk strings comes
// back as plain PyBytes name + args (no message objects).  The bulk
// replay path unwraps every argument into bytes immediately (columnar
// group-encode), so building Bulk wrappers just to strip them is pure
// overhead — about half the scan cost at the record sizes the recovery
// bench replays.  Anything non-flat (ints, nested arrays, nils) bails
// so the caller can fall back to the object decode.  Returns nullptr
// WITHOUT a Python error on any bail; WITH an error only on CPython
// failures.
PyObject* decode_frame_raw(const uint8_t* p, Py_ssize_t len) {
    Py_ssize_t pos = 0;
    uint64_t origin, uuid;
    if (!uvarint(p, len, &pos, &origin)) return nullptr;
    if (!uvarint(p, len, &pos, &uuid)) return nullptr;
    const char* b = reinterpret_cast<const char*>(p);
    if (pos >= len || b[pos] != '*') return nullptr;
    long long cnt;
    Py_ssize_t q;
    if (resp::int_line(b, len, pos + 1, &cnt, &q) != 1) return nullptr;
    if (cnt < 1 || cnt > (long long)resp::kMaxArr) return nullptr;
    PyObject* name = nullptr;
    PyObject* args = PyList_New((Py_ssize_t)cnt - 1);
    if (!args) return nullptr;
    bool ok = true;
    for (long long i = 0; ok && i < cnt; i++) {
        long long ln;
        Py_ssize_t r;
        if (q >= len || b[q] != '$' ||
            resp::int_line(b, len, q + 1, &ln, &r) != 1 || ln < 0 ||
            ln > resp::kMaxBulk || r + ln + 2 > len || b[r + ln] != '\r' ||
            b[r + ln + 1] != '\n') {
            ok = false;
            break;
        }
        PyObject* s = PyBytes_FromStringAndSize(b + r, (Py_ssize_t)ln);
        if (!s) {
            Py_XDECREF(name);
            Py_DECREF(args);
            return nullptr;  // error set
        }
        if (i == 0)
            name = s;
        else
            PyList_SET_ITEM(args, i - 1, s);
        q = r + ln + 2;
    }
    if (!ok || q != len) {
        Py_XDECREF(name);
        Py_DECREF(args);
        return nullptr;
    }
    return Py_BuildValue("(iKKNN)", kRecFrame, (unsigned long long)origin,
                         (unsigned long long)uuid, name, args);
}

}  // namespace aof

static PyObject* py_aof_scan(PyObject*, PyObject* args) {
    Py_buffer view;
    Py_ssize_t pos;
    long long max_record;
    PyObject *arr_t = nullptr, *bulk_t = nullptr, *int_t = nullptr,
             *simple_t = nullptr, *err_t = nullptr, *nil_obj = nullptr;
    int raw = 0;
    if (!PyArg_ParseTuple(args, "y*nL|OOOOOOi", &view, &pos, &max_record,
                          &arr_t, &bulk_t, &int_t, &simple_t, &err_t,
                          &nil_obj, &raw))
        return nullptr;
    const uint8_t* b = static_cast<const uint8_t*>(view.buf);
    const Py_ssize_t n = view.len;
    const bool fuse = nil_obj != nullptr;
    PyObject* out = PyList_New(0);
    if (!out) {
        PyBuffer_Release(&view);
        return nullptr;
    }
    while (pos + 8 <= n) {
        const uint64_t ln = (uint64_t)b[pos] | ((uint64_t)b[pos + 1] << 8) |
                            ((uint64_t)b[pos + 2] << 16) |
                            ((uint64_t)b[pos + 3] << 24);
        if (ln < 1 || (long long)ln > max_record ||
            pos + 8 + (Py_ssize_t)ln > n)
            break;
        const uint32_t want = (uint32_t)b[pos + 4] |
                              ((uint32_t)b[pos + 5] << 8) |
                              ((uint32_t)b[pos + 6] << 16) |
                              ((uint32_t)b[pos + 7] << 24);
        const uint8_t* body = b + pos + 8;
        if (aof::crc32(body, (Py_ssize_t)ln) != want) break;
        const int rtype = body[0];
        if (rtype < aof::kRecBatch || rtype > aof::kRecWmark) break;
        PyObject* rec = nullptr;
        if (fuse && rtype == aof::kRecFrame) {
            if (raw) {
                rec = aof::decode_frame_raw(body + 1, (Py_ssize_t)(ln - 1));
                if (!rec && PyErr_Occurred()) goto fail;
            }
            if (!rec) {
                rec = aof::decode_frame(body + 1, (Py_ssize_t)(ln - 1),
                                        arr_t, bulk_t, int_t, simple_t,
                                        err_t, nil_obj);
                if (!rec && PyErr_Occurred()) goto fail;
            }
        }
        if (!rec)
            rec = Py_BuildValue("(iy#)", rtype,
                                reinterpret_cast<const char*>(body) + 1,
                                (Py_ssize_t)(ln - 1));
        if (!rec) goto fail;
        {
            const int rc = PyList_Append(out, rec);
            Py_DECREF(rec);
            if (rc != 0) goto fail;
        }
        pos += 8 + (Py_ssize_t)ln;
    }
    PyBuffer_Release(&view);
    return Py_BuildValue("(Nn)", out, pos);

fail:
    Py_DECREF(out);
    PyBuffer_Release(&view);
    return nullptr;
}
