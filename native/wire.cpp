// Native REPLBATCH blob columns (replica/wire.py hot loops).
//
// The columnar wire codec's int columns decode with one np.frombuffer,
// but the BLOB columns (keys, register values, element members) pay a
// per-row Python loop on both sides: a fromiter + join on the pusher's
// _pack_blobs, a slice loop on the receiver's _Reader.blobs.  These two
// move here; layout is byte-identical to the Python reference (one width
// byte + little-endian lengths with the width's max value as the None
// sentinel + concatenated payloads).
//
// Both entry points DECLINE rather than raise on anything off the happy
// path — a non-list input, a non-bytes item, an over-wide blob, a bad
// width byte, truncation — returning False/None so the caller falls
// through to the pure-Python path, which either handles the shape or
// raises its own _PatternError/WireFormatError with the reference
// message.  Error behavior therefore never diverges; only the clean-path
// cycles move.  crc validation stays in replica/wire.py (_decode): the
// corruption→demotion accounting is receiver-side Python either way.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>

namespace wire {

// little-endian length write for w in {1,2,4}
inline void put_len(char* p, int w, unsigned long long v) {
    for (int i = 0; i < w; i++) p[i] = (char)((v >> (8 * i)) & 0xff);
}

inline unsigned long long get_len(const unsigned char* p, int w) {
    unsigned long long v = 0;
    for (int i = 0; i < w; i++) v |= (unsigned long long)p[i] << (8 * i);
    return v;
}

}  // namespace wire

// wire_pack_blobs(out_bytearray, items_list) -> True (appended) | False
// (decline: caller runs the pure packer).
static PyObject* py_wire_pack_blobs(PyObject*, PyObject* args) {
    PyObject *out, *items;
    if (!PyArg_ParseTuple(args, "OO", &out, &items)) return nullptr;
    if (!PyByteArray_CheckExact(out) || !PyList_CheckExact(items))
        Py_RETURN_FALSE;
    Py_ssize_t n = PyList_GET_SIZE(items);
    long long mx = 0;
    unsigned long long total = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* it = PyList_GET_ITEM(items, i);
        if (it == Py_None) continue;
        if (!PyBytes_CheckExact(it)) Py_RETURN_FALSE;
        Py_ssize_t ln = PyBytes_GET_SIZE(it);
        if (ln > mx) mx = ln;
        total += (unsigned long long)ln;
    }
    int w;
    if (mx < 0xff) w = 1;
    else if (mx < 0xffff) w = 2;
    else if (mx < 0xffffffffLL) w = 4;
    else Py_RETURN_FALSE;  // pure packer raises "blob too large"
    const unsigned long long sentinel = (1ULL << (8 * w)) - 1;
    Py_ssize_t old = PyByteArray_GET_SIZE(out);
    if (PyByteArray_Resize(out, old + 1 + n * w + (Py_ssize_t)total))
        return nullptr;
    char* p = PyByteArray_AS_STRING(out) + old;
    *p++ = (char)w;
    char* lens = p;
    char* pay = p + n * w;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* it = PyList_GET_ITEM(items, i);
        if (it == Py_None) {
            wire::put_len(lens + i * w, w, sentinel);
            continue;
        }
        Py_ssize_t ln = PyBytes_GET_SIZE(it);
        wire::put_len(lens + i * w, w, (unsigned long long)ln);
        memcpy(pay, PyBytes_AS_STRING(it), (size_t)ln);
        pay += ln;
    }
    Py_RETURN_TRUE;
}

// wire_unpack_blobs(buf, pos, n) -> (list, new_pos) | None (decline: the
// pure reader re-runs the column and raises the reference error).
static PyObject* py_wire_unpack_blobs(PyObject*, PyObject* args) {
    Py_buffer view;
    Py_ssize_t pos, n;
    if (!PyArg_ParseTuple(args, "y*nn", &view, &pos, &n)) return nullptr;
    const unsigned char* b = (const unsigned char*)view.buf;
    const Py_ssize_t len = view.len;
    if (n < 0 || pos < 0 || pos + 1 > len) goto decline;
    {
        int w = b[pos];
        if (w != 1 && w != 2 && w != 4) goto decline;
        Py_ssize_t lens_at = pos + 1;
        if (n > (len - lens_at) / w) goto decline;
        Py_ssize_t blob_at = lens_at + n * w;
        const unsigned long long sentinel = (1ULL << (8 * w)) - 1;
        unsigned long long total = 0;
        for (Py_ssize_t i = 0; i < n; i++) {
            unsigned long long ln = wire::get_len(b + lens_at + i * w, w);
            if (ln != sentinel) total += ln;
        }
        if (total > (unsigned long long)(len - blob_at)) goto decline;
        PyObject* lst = PyList_New(n);
        if (!lst) goto fail;
        Py_ssize_t bp = blob_at;
        for (Py_ssize_t i = 0; i < n; i++) {
            unsigned long long ln = wire::get_len(b + lens_at + i * w, w);
            PyObject* item;
            if (ln == sentinel) {
                item = Py_None;
                Py_INCREF(item);
            } else {
                item = PyBytes_FromStringAndSize((const char*)b + bp,
                                                 (Py_ssize_t)ln);
                if (!item) {
                    Py_DECREF(lst);
                    goto fail;
                }
                bp += (Py_ssize_t)ln;
            }
            PyList_SET_ITEM(lst, i, item);
        }
        PyBuffer_Release(&view);
        return Py_BuildValue("(Nn)", lst, bp);
    }
decline:
    PyBuffer_Release(&view);
    Py_RETURN_NONE;
fail:
    PyBuffer_Release(&view);
    return nullptr;
}
