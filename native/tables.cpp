// Native staging tables for the merge hot path (C++17, no deps).
//
// The TPU merge engine's host-side cost is index resolution: key bytes ->
// row, (key,node) combo -> counter slot, (key,member) combo -> element row.
// In Python these are dict probes at ~100ns each over millions of rows; here
// they are open-addressing tables with batch entry points called once per
// column via ctypes (constdb_tpu/utils/native_tables.py).
//
//   StrTable — bytes -> dense id (insertion order).  Strings are copied into
//              an arena; id -> (offset,len) lets callers recover bytes.
//   I64Table — int64 -> int64 with tombstone deletion and batch
//              lookup/assign; used for integer combo keys.
//
// Hashing: splitmix64 finalizer for ints, FNV-1a + splitmix for strings.

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t x) {
    x += 0x9E3779B97F4A7C15ULL;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
    return x ^ (x >> 31);
}

inline uint64_t hash_bytes(const uint8_t* p, int64_t len) {
    uint64_t h = 0xCBF29CE484222325ULL;
    for (int64_t i = 0; i < len; i++) {
        h ^= p[i];
        h *= 0x100000001B3ULL;
    }
    return splitmix64(h);
}

inline size_t next_pow2(size_t n) {
    size_t p = 16;
    while (p < n) p <<= 1;
    return p;
}

}  // namespace

// ------------------------------------------------------------------ StrTable

struct StrTable {
    // slot: id+1 (0 = empty); ids index into offs/lens
    std::vector<int64_t> slots;
    std::vector<uint64_t> hashes;   // per-slot cached hash
    std::vector<uint8_t> arena;
    std::vector<int64_t> offs;      // per-id arena offset
    std::vector<int64_t> lens;      // per-id length
    size_t mask = 0;
    size_t count = 0;
    double new_ratio = 1.0;  // EMA of observed new-per-row in batches

    explicit StrTable(size_t cap_hint) {
        size_t cap = next_pow2(cap_hint * 2);
        slots.assign(cap, 0);
        hashes.assign(cap, 0);
        mask = cap - 1;
    }

    void rebuild(size_t cap) {
        std::vector<int64_t> ns(cap, 0);
        std::vector<uint64_t> nh(cap, 0);
        size_t nm = cap - 1;
        for (size_t i = 0; i < slots.size(); i++) {
            if (!slots[i]) continue;
            size_t j = hashes[i] & nm;
            while (ns[j]) j = (j + 1) & nm;
            ns[j] = slots[i];
            nh[j] = hashes[i];
        }
        slots.swap(ns);
        hashes.swap(nh);
        mask = nm;
    }

    void grow_to(size_t cap) {
        if (cap > slots.size()) rebuild(cap);
    }

    void grow() { grow_to(slots.size() * 2); }

    // presize for `extra` further inserts: one rehash up front instead of
    // several mid-batch doublings.  Gated to the bulk-ingest shape
    // (extra dominates count AND the worst case would trip growth) so a
    // duplicate-heavy re-merge or a small batch into a big healthy table
    // cannot force a rehash or permanently overallocate.
    void reserve_extra(size_t extra) {
        if (extra <= count) return;
        if ((count + extra) * 10 < slots.size() * 7) return;
        grow_to(next_pow2((count + extra) * 2));
    }

    // after a batch: a reserve sized for batch-INTERNAL duplicates that
    // never materialized leaves the table nearly empty — rehash the few
    // live entries down (ids are stable; only the slot vectors shrink;
    // the 0.2 shrink vs 0.5 post-reserve load gives hysteresis)
    void maybe_shrink() {
        size_t want = next_pow2(count * 4 + 16);
        if (slots.size() > 4096 && count * 10 < slots.size() * 2 &&
            want < slots.size())
            rebuild(want);
    }

    // shared batch protocol for BOTH binding tiers (ctypes and the
    // CPython extension): presize by the learned new-row ratio, then
    // after the loop shrink an over-eager reserve and update the EMA
    void batch_begin(size_t n) {
        reserve_extra((size_t)((double)n * new_ratio) + 16);
    }
    void batch_end(size_t n, size_t fresh) {
        maybe_shrink();
        if (n > 256) {
            double r = (double)fresh / (double)n;
            new_ratio = 0.5 * new_ratio + 0.5 * r;
            if (new_ratio < 0.02) new_ratio = 0.02;
            if (new_ratio > 1.0) new_ratio = 1.0;
        }
    }

    inline bool eq(int64_t id, const uint8_t* p, int64_t len) const {
        return lens[id] == len &&
               std::memcmp(arena.data() + offs[id], p, (size_t)len) == 0;
    }

    int64_t lookup(const uint8_t* p, int64_t len) const {
        uint64_t h = hash_bytes(p, len);
        size_t j = h & mask;
        while (slots[j]) {
            if (hashes[j] == h && eq(slots[j] - 1, p, len)) return slots[j] - 1;
            j = (j + 1) & mask;
        }
        return -1;
    }

    int64_t get_or_insert(const uint8_t* p, int64_t len) {
        uint64_t h = hash_bytes(p, len);
        size_t j = h & mask;
        while (slots[j]) {
            if (hashes[j] == h && eq(slots[j] - 1, p, len)) return slots[j] - 1;
            j = (j + 1) & mask;
        }
        int64_t id = (int64_t)count;
        offs.push_back((int64_t)arena.size());
        lens.push_back(len);
        arena.insert(arena.end(), p, p + len);
        slots[j] = id + 1;
        hashes[j] = h;
        count++;
        if (count * 10 >= slots.size() * 7) grow();
        return id;
    }
};

extern "C" {

StrTable* cst_strtab_new(int64_t cap_hint) {
    return new StrTable((size_t)(cap_hint > 0 ? cap_hint : 16));
}
void cst_strtab_free(StrTable* t) { delete t; }
int64_t cst_strtab_len(StrTable* t) { return (int64_t)t->count; }

int64_t cst_strtab_get_or_insert(StrTable* t, const uint8_t* p, int64_t len) {
    return t->get_or_insert(p, len);
}
int64_t cst_strtab_lookup(StrTable* t, const uint8_t* p, int64_t len) {
    return t->lookup(p, len);
}

// blob + offs[n+1] (offs[i]..offs[i+1] delimits item i) -> out_ids[n];
// returns how many ids are new.
int64_t cst_strtab_get_or_insert_batch(StrTable* t, const uint8_t* blob,
                                       const int64_t* offs, int64_t n,
                                       int64_t* out_ids) {
    t->batch_begin((size_t)n);
    int64_t before = (int64_t)t->count;
    for (int64_t i = 0; i < n; i++)
        out_ids[i] = t->get_or_insert(blob + offs[i], offs[i + 1] - offs[i]);
    int64_t fresh = (int64_t)t->count - before;
    t->batch_end((size_t)n, (size_t)fresh);
    return fresh;
}

void cst_strtab_lookup_batch(StrTable* t, const uint8_t* blob,
                             const int64_t* offs, int64_t n, int64_t* out) {
    for (int64_t i = 0; i < n; i++)
        out[i] = t->lookup(blob + offs[i], offs[i + 1] - offs[i]);
}

int64_t cst_strtab_bytes_len(StrTable* t, int64_t id) {
    return (id >= 0 && (size_t)id < t->count) ? t->lens[id] : -1;
}
void cst_strtab_bytes_get(StrTable* t, int64_t id, uint8_t* out) {
    if (id >= 0 && (size_t)id < t->count)
        std::memcpy(out, t->arena.data() + t->offs[id], (size_t)t->lens[id]);
}

}  // extern "C"

// ------------------------------------------------------------------ I64Table

struct I64Table {
    static constexpr int64_t kEmpty = INT64_MIN;
    static constexpr int64_t kTomb = INT64_MIN + 1;
    std::vector<int64_t> keys;
    std::vector<int64_t> vals;
    size_t mask = 0;
    size_t count = 0;   // live entries
    size_t used = 0;    // live + tombstones
    double new_ratio = 1.0;  // EMA of observed new-per-row in batches

    explicit I64Table(size_t cap_hint) {
        size_t cap = next_pow2(cap_hint * 2);
        keys.assign(cap, kEmpty);
        vals.assign(cap, 0);
        mask = cap - 1;
    }

    void rehash(size_t cap) {
        std::vector<int64_t> nk(cap, kEmpty), nv(cap, 0);
        size_t nm = cap - 1;
        for (size_t i = 0; i < keys.size(); i++) {
            if (keys[i] == kEmpty || keys[i] == kTomb) continue;
            size_t j = splitmix64((uint64_t)keys[i]) & nm;
            while (nk[j] != kEmpty) j = (j + 1) & nm;
            nk[j] = keys[i];
            nv[j] = vals[i];
        }
        keys.swap(nk);
        vals.swap(nv);
        mask = nm;
        used = count;
    }

    inline void maybe_grow() {
        if (used * 10 >= keys.size() * 7)
            rehash(count * 10 >= keys.size() * 4 ? keys.size() * 2 : keys.size());
    }

    // presize for `extra` further inserts: one up-front rehash instead of
    // several mid-batch doublings.  Same bulk-ingest gate as StrTable:
    // never triggered by small batches or duplicate-heavy re-merges.
    void reserve_extra(size_t extra) {
        if (extra <= count) return;
        if ((count + extra) * 10 < keys.size() * 7) return;
        rehash(next_pow2((count + extra) * 2));
    }

    // post-batch: undo a reserve that batch-internal duplicates left
    // nearly empty (see StrTable::maybe_shrink)
    void maybe_shrink() {
        size_t want = next_pow2(count * 4 + 16);
        if (keys.size() > 4096 && count * 10 < keys.size() * 2 &&
            want < keys.size())
            rehash(want);
    }

    // shared batch protocol for BOTH binding tiers (ctypes and the
    // CPython extension): presize by the learned new-row ratio, then
    // after the loop shrink an over-eager reserve and update the EMA
    void batch_begin(size_t n) {
        reserve_extra((size_t)((double)n * new_ratio) + 16);
    }
    void batch_end(size_t n, size_t fresh) {
        maybe_shrink();
        if (n > 256) {
            double r = (double)fresh / (double)n;
            new_ratio = 0.5 * new_ratio + 0.5 * r;
            if (new_ratio < 0.02) new_ratio = 0.02;
            if (new_ratio > 1.0) new_ratio = 1.0;
        }
    }

    int64_t get(int64_t k, int64_t dflt) const {
        size_t j = splitmix64((uint64_t)k) & mask;
        while (keys[j] != kEmpty) {
            if (keys[j] == k) return vals[j];
            j = (j + 1) & mask;
        }
        return dflt;
    }

    void put(int64_t k, int64_t v) {
        size_t j = splitmix64((uint64_t)k) & mask;
        size_t tomb = SIZE_MAX;
        while (keys[j] != kEmpty) {
            if (keys[j] == k) { vals[j] = v; return; }
            if (keys[j] == kTomb && tomb == SIZE_MAX) tomb = j;
            j = (j + 1) & mask;
        }
        if (tomb != SIZE_MAX) {
            keys[tomb] = k;
            vals[tomb] = v;
            count++;
        } else {
            keys[j] = k;
            vals[j] = v;
            count++;
            used++;
            maybe_grow();
        }
    }

    int64_t del(int64_t k, int64_t dflt) {
        size_t j = splitmix64((uint64_t)k) & mask;
        while (keys[j] != kEmpty) {
            if (keys[j] == k) {
                int64_t v = vals[j];
                keys[j] = kTomb;
                count--;
                return v;
            }
            j = (j + 1) & mask;
        }
        return dflt;
    }
};

extern "C" {

I64Table* cst_i64_new(int64_t cap_hint) {
    return new I64Table((size_t)(cap_hint > 0 ? cap_hint : 16));
}
void cst_i64_free(I64Table* t) { delete t; }
int64_t cst_i64_len(I64Table* t) { return (int64_t)t->count; }

int64_t cst_i64_get(I64Table* t, int64_t k, int64_t dflt) { return t->get(k, dflt); }
void cst_i64_put(I64Table* t, int64_t k, int64_t v) { t->put(k, v); }
int64_t cst_i64_del(I64Table* t, int64_t k, int64_t dflt) { return t->del(k, dflt); }

void cst_i64_lookup_batch(I64Table* t, const int64_t* ks, int64_t n,
                          int64_t dflt, int64_t* out) {
    for (int64_t i = 0; i < n; i++) out[i] = t->get(ks[i], dflt);
}

void cst_i64_put_batch(I64Table* t, const int64_t* ks, const int64_t* vs,
                       int64_t n) {
    t->batch_begin((size_t)n);
    size_t before = t->count;
    for (int64_t i = 0; i < n; i++) t->put(ks[i], vs[i]);
    t->batch_end((size_t)n, t->count - before);
}

// missing keys get sequential values starting at `next` (first-occurrence
// order); returns the count of newly assigned keys.
int64_t cst_i64_get_or_assign_batch(I64Table* t, const int64_t* ks, int64_t n,
                                    int64_t next, int64_t* out) {
    t->batch_begin((size_t)n);
    int64_t start = next;
    for (int64_t i = 0; i < n; i++) {
        int64_t v = t->get(ks[i], INT64_MIN);
        if (v == INT64_MIN) {
            v = next++;
            t->put(ks[i], v);
        }
        out[i] = v;
    }
    t->batch_end((size_t)n, (size_t)(next - start));
    return next - start;
}

}  // extern "C"
