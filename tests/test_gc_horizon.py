"""GC horizon retention: a long-silent peer stops pinning tombstone
collection and is forced through a full resync on return (VERDICT round-3
item 10; contrast reference replica/replica.rs:87-89, where one dead peer
pins GC forever)."""

import asyncio

from constdb_tpu.replica.manager import ReplicaManager, ReplicaMeta
from constdb_tpu.utils.hlc import now_ms


def _mgr(retention_ms=1000):
    m = ReplicaManager()
    m.gc_peer_retention_ms = retention_ms
    return m


def test_silent_peer_stops_pinning():
    mgr = _mgr(retention_ms=1000)
    fresh = mgr.add("a:1", uuid=10)
    fresh.uuid_i_acked = fresh.uuid_he_sent = 500
    fresh.last_seen_ms = now_ms()
    stale = mgr.add("b:2", uuid=10)
    stale.uuid_i_acked = stale.uuid_he_sent = 7   # would pin the horizon
    stale.last_seen_ms = now_ms() - 60_000        # silent for a minute
    assert mgr.min_uuid() == 500
    assert stale.needs_full is True
    assert fresh.needs_full is False


def test_all_peers_silent_unpins_entirely():
    mgr = _mgr(retention_ms=1000)
    stale = mgr.add("a:1", uuid=10)
    stale.uuid_i_acked = stale.uuid_he_sent = 7
    stale.last_seen_ms = now_ms() - 60_000
    assert mgr.min_uuid() is None  # collect to own clock, like no peers


def test_retention_zero_keeps_reference_behavior():
    mgr = _mgr(retention_ms=0)
    stale = mgr.add("a:1", uuid=10)
    stale.uuid_i_acked = stale.uuid_he_sent = 7
    stale.last_seen_ms = now_ms() - 60_000
    assert mgr.min_uuid() == 7  # 0 = never exclude (pin forever)


def test_fresh_meet_pins_for_one_retention_window():
    """A just-registered peer (fresh MEET, dial still in progress) pins
    for exactly one retention window: the clock starts at registration,
    so a restored-dead peer cannot pin the horizon forever."""
    mgr = _mgr(retention_ms=1000)
    m = mgr.add("a:1", uuid=10)
    m.uuid_i_acked = m.uuid_he_sent = 3
    assert m.last_seen_ms > 0          # stamped at registration
    assert mgr.min_uuid() == 3         # pins within the window
    m.last_seen_ms -= 60_000           # window long gone, still silent
    assert mgr.min_uuid() is None      # stops pinning
    assert m.needs_full is True


def test_restored_membership_gets_retention_clock():
    """Membership restored from a snapshot REPLICAS section starts its
    retention clock at restore time (runtime last_seen is not persisted)."""
    from constdb_tpu.persist.snapshot import ReplicaRecord
    mgr = _mgr(retention_ms=1000)
    mgr.merge_records([ReplicaRecord("dead:1", 9, "d", add_t=5)])
    m = mgr.get("dead:1")
    assert m is not None and m.last_seen_ms > 0


def test_delete_event_fires_and_wakes_cron_consumer():
    from constdb_tpu.resp.message import Bulk
    from constdb_tpu.server.events import EVENT_DELETED
    from constdb_tpu.server.node import Node

    async def main():
        node = Node(node_id=1)
        consumer = node.events.new_consumer(EVENT_DELETED)
        node.execute([Bulk(b"set"), Bulk(b"k"), Bulk(b"v")])
        assert await consumer.wait(timeout=0.05) is False  # no delete yet
        node.execute([Bulk(b"del"), Bulk(b"k")])
        assert await consumer.wait(timeout=1.0) is True
        consumer.close()
    asyncio.run(main())
