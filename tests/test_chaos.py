"""Chaos certification suite (constdb_tpu/chaos/).

The old randomized crash/restart loop grew into the first-class harness:
scenarios are seed + capability cell + scripted fault/op schedule, the
crash styles are the two ChaosCluster primitives (`restart_cold` boots
from a real snapshot through io.py's restore path, `restart_warm`
rebuilds the server over the surviving Node), and the invariant oracle
replaces the hand-rolled client-side expectations: convergence to the
CPU-engine reference export, continuous watermark/beacon monotonicity,
digest agreement, no-resurrection, GC drain, and loud fault accounting.

Tier-1 runs compact deterministic scenarios; the full capability matrix
and the randomized soak are slow-marked.  Every failure message carries
`[chaos seed=N cell=…]` — the replay seed IS the repro.
"""

from __future__ import annotations

import asyncio

import pytest

from constdb_tpu.chaos import (Cell, ChaosCluster, NodeSpec, Scenario,
                               certify_scenario, matrix_cells,
                               run_scenario, soak_scenario)
from constdb_tpu.chaos.cluster import Client
from constdb_tpu.resp.message import Nil


def test_certify_default_cell(tmp_path):
    """The acceptance schedule (partitions + reorder + duplication +
    mid-frame truncation + kills + cold/warm crashes + clock jitter +
    wire corruption + one mixed-version peer) on the everything-on
    cell, full oracle verified."""
    stats = run_scenario(certify_scenario(7, Cell()))
    plane = stats["plane"]
    # the schedule really injected the faults it promises (the verified
    # corrupt burst may consume a few one-shots when a carrying
    # connection dies before delivery — fate-sharing; the scenario
    # itself asserts a demotion was OBSERVED)
    assert plane.get("partitions", 0) >= 3
    assert plane.get("truncations", 0) == 1
    assert plane.get("wire_corruptions", 0) >= 1
    assert stats["reconnects"] >= 1


def test_certify_native_intake_cell(tmp_path, monkeypatch):
    """The everything-on cell with the native intake stage pinned ON
    (server/io.py + native/intake.cpp): the C scanner owns the chaos
    workload's pipelined client chunks while the full acceptance
    schedule runs, and the loud-accounting law holds through it —
    every wire corruption that reached a live parser demoted, none
    were swallowed (`1 <= repl_wire_demotions <= corruptions
    injected`, the slack being fate-shared one-shots that died with
    their connection before delivery)."""
    from constdb_tpu.utils import native_tables as NT
    ext = NT.load_ext()
    if ext is None or not hasattr(ext, "intake_scan"):
        pytest.skip("native extension with intake_scan not built")
    monkeypatch.setenv("CONSTDB_NATIVE_INTAKE", "1")
    stats = run_scenario(certify_scenario(13, Cell()))
    corruptions = stats["plane"].get("wire_corruptions", 0)
    assert corruptions >= 1
    assert 1 <= stats["wire_demotions"] <= corruptions
    # the native stage really carried traffic — clients write through
    # coalescing connections, so the gauge must have moved
    assert stats["native_intake_chunks"] > 0


def test_certify_legacy_cell(tmp_path):
    """Everything-off cell: per-frame wire, full snapshots only — the
    pure pre-capability paths under the same chaos schedule."""
    run_scenario(certify_scenario(7, Cell(wire=False, delta=False)))


def test_certify_aof_cell(tmp_path):
    """Durability cell (round 18): the full acceptance schedule PLUS
    kill9_mid_write and torn_write — cold restarts that recover from
    the node's OWN op log under fsync=always, with the oracle
    asserting every fsync-acknowledged write survived and the mesh
    re-converged byte-identically to the journal reference (the
    never-durable suffix is pruned under the emit-only-durable law)."""
    stats = run_scenario(certify_scenario(7, Cell(aof="always")))
    # the durability steps really ran: both crash styles recover from
    # the log (restart_cold takes no harness-side dump on AOF specs)
    assert stats["journal_ops"] > 0


def test_certify_checkpoint_crash_cell(tmp_path):
    """Crash-mid-checkpoint (round 20): the schedule fault-injects the
    rewrite at each commit interleaving — after the generation switch,
    after the base snapshot write, and after the meta commit with the
    old generations still on disk — then kill -9s and cold-restarts.
    Every interleaving must replay idempotently to the same bytes (the
    checkpoint-cut consistency law)."""
    stats = run_scenario(certify_scenario(7, Cell(aof="always",
                                                  ckpt=True)))
    assert stats["journal_ops"] > 0


@pytest.mark.slow  # ~5s: the 1s group-commit cadence paces every
#                    crash/restart window (the cell also runs in the
#                    ci.sh chaos smoke and the full matrix)
def test_certify_aof_everysec_cell(tmp_path):
    """The weaker everysec contract under the same schedule: durable-
    prefix recovery, zero divergence, watermarks never claim coverage
    beyond the fsync cut."""
    run_scenario(certify_scenario(11, Cell(aof="everysec")))


def test_certify_replays_from_seed(tmp_path):
    """Determinism pin: the same seed replays the same decision stream —
    identical journaled op set and identical converged state."""
    a = run_scenario(certify_scenario(21, Cell(wire=False, delta=False)))
    b = run_scenario(certify_scenario(21, Cell(wire=False, delta=False)))
    assert a["journal_ops"] == b["journal_ops"]
    assert a["canonical_keys"] == b["canonical_keys"]


def test_crash_styles_converge(tmp_path):
    """The two crash primitives back to back — cold (snapshot boot,
    in-memory watermarks/undo log lost) and warm (connections only) —
    with writes in between; the oracle still certifies."""
    steps = [
        ("ops", 40),
        ("crash", 1, "cold"),
        ("ops", 40),
        ("crash", 0, "warm"),
        ("ops", 40),
        ("crash", 2, "cold"),
        ("ops", 20),
        ("certify",),
    ]
    run_scenario(Scenario(seed=5, steps=steps))


def test_resource_cells_certify(tmp_path):
    """The resource-fault cells (chaos/resource.py): a memory-capped
    node under a firehose sheds with exact -OOM replies while
    replication intake lands and the mesh converges to the CPU
    reference; a stalled-reader client is cut at the outbuf cap without
    touching other connections; a stalled-reader peer trips the repl
    window pause and recovers through the certified resync path."""
    from constdb_tpu.chaos import run_resource_scenario

    stats = run_resource_scenario(7)
    assert stats["firehose"]["shed"] > 0
    assert stats["firehose"]["landed"] > 0
    assert stats["stalled_client"]["outbuf_disconnects"] == 1
    assert stats["stalled_peer"]["window_pauses"] >= 1
    assert stats["stalled_peer"]["resyncs"] >= 1


def test_resource_cells_replay_from_seed(tmp_path):
    """Same seed, same shed/landed split and converged key count — the
    resource schedule is deterministic like every chaos schedule."""
    from constdb_tpu.chaos import run_resource_scenario

    a = run_resource_scenario(23)
    b = run_resource_scenario(23)
    assert a["firehose"]["landed"] == b["firehose"]["landed"]
    assert a["firehose"]["canonical_keys"] == \
        b["firehose"]["canonical_keys"]


@pytest.mark.slow
def test_certify_full_matrix(tmp_path):
    """Acceptance: the scripted scenario passes the full invariant
    oracle on EVERY capability-matrix cell (wire batch on/off, delta
    sync on/off, serve shards 1/2, resident engine 0/1)."""
    for cell in matrix_cells():
        run_scenario(certify_scenario(11, cell, ops=25))


@pytest.mark.slow
def test_chaos_soak_randomized(tmp_path):
    """Randomized soak: seeded schedules over the default cell.  A
    failure prints `[chaos seed=N]`; `soak_scenario(N)` replays it."""
    for seed in (99, 1, 2):
        run_scenario(soak_scenario(seed))


@pytest.mark.parametrize("aof", [None, "always"],
                         ids=["snapshot", "aof"])
def test_cold_restart_does_not_resurrect_collected_tombstones(tmp_path,
                                                              aof):
    """Regression (round-5 chaos find): a cold-restarted node must
    resume pulling each peer from its SNAPSHOT-RECORDED watermark.
    With the watermark lost (resume 0), peers replay their whole
    repl_log ring — including ADDS whose tombstones the mesh already
    GC-collected — and the deleted member resurrects with no surviving
    delete op anywhere.

    The `aof` variant runs the SAME regression on the durable-op-log
    cold restart (no harness-side dump — recovery comes from the
    node's own log, whose WMARK records carry the watermarks under the
    persisted consistency-cut law)."""
    async def main():
        cluster = ChaosCluster(str(tmp_path), seed=1,
                               specs=[NodeSpec(aof=aof),
                                      NodeSpec(aof=aof)])
        await cluster.start()
        try:
            a, b = cluster.apps
            ca = await Client().connect(a.advertised_addr)
            cb = await Client().connect(b.advertised_addr)
            await ca.cmd("meet", b.advertised_addr)
            await cluster.converge()
            await ca.cmd("sadd", "s", "gone")
            await ca.cmd("sadd", "s", "keep")
            await cluster.converge()
            # the REMOVE originates on B — the node about to lose its
            # repl_log: after the restart no log anywhere holds the
            # delete, while A's ring still holds the add
            await cb.cmd("srem", "s", "gone")
            await cb.close()
            await cluster.converge()
            # wait until BOTH nodes physically collected the tombstone
            deadline = asyncio.get_running_loop().time() + 10.0
            while True:
                for app in cluster.apps:
                    app.node.gc()
                if all(len(app.node.ks.garbage) == 0 and
                       app.node.ks.el_row(app.node.ks.lookup(b"s"),
                                          b"gone") < 0
                       for app in cluster.apps):
                    break
                assert asyncio.get_running_loop().time() < deadline, \
                    "tombstone never collected"
                await asyncio.sleep(0.1)
            # cold-restart B; A's ring still holds the original SADD op
            assert a.node.repl_log.first_uuid <= a.node.repl_log.last_uuid
            await cluster.restart_cold(1)
            await cluster.converge(timeout=15.0)
            for app in cluster.apps:
                c = await Client().connect(app.advertised_addr)
                got = await c.cmd("smembers", "s")
                members = ({i.val for i in got.items}
                           if not isinstance(got, Nil) else set())
                assert members == {b"keep"}, (app.port, members)
                await c.close()
            await ca.close()
        finally:
            await cluster.close()
    asyncio.run(main())


def test_coverage_gates_third_party_tombstone_collection(tmp_path):
    """Regression (round-15 chaos find #1): node B must NOT collect a
    tombstone that originated on node C while node A — partitioned from
    C — has not seen the delete, even though A's acks of B's OWN stream
    are far past it.  The REPLACK cluster-coverage field (item 5) is
    what pins B's horizon; without it, a later state transfer from B to
    A adopts C's watermark over a delete A never applied and the member
    resurrects mesh-wide."""
    from constdb_tpu.chaos import FaultPlane

    async def main():
        plane = FaultPlane(3)
        cluster = ChaosCluster(str(tmp_path), seed=3,
                               specs=[NodeSpec()] * 3, plane=plane)
        await cluster.start()
        try:
            a, b, c = cluster.apps
            cl = await Client().connect(a.advertised_addr)
            await cl.cmd("meet", b.advertised_addr)
            await cl.cmd("meet", c.advertised_addr)
            await cl.close()
            await cluster.converge()
            cc = await Client().connect(c.advertised_addr)
            await cc.cmd("sadd", "s", "m")
            await cluster.converge()
            # A loses C; C removes the member — only B applies it
            plane.partition(0, 2)
            await cc.cmd("srem", "s", "m")
            await cc.close()

            def b_has_tombstone():
                ks = b.node.ks
                kid = ks.lookup(b"s")
                row = ks.el_row(kid, b"m") if kid >= 0 else -1
                return row >= 0 and \
                    int(ks.el.del_t[row]) > int(ks.el.add_t[row])

            deadline = asyncio.get_running_loop().time() + 10.0
            while not b_has_tombstone():
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.05)
            # keep B's view of A's OWN stream fresh (A writes, B acks
            # flow) so the ack-only horizon WOULD pass the delete
            ca = await Client().connect(a.advertised_addr)
            for i in range(5):
                await ca.cmd("set", "tick", f"v{i}")
                await asyncio.sleep(0.2)
                b.node.gc()
                assert b_has_tombstone(), \
                    "B collected a third-party tombstone A never saw"
            await ca.close()
            # heal: C delivers the delete to A; only then may B collect
            plane.heal()
            await cluster.converge(timeout=20.0)
            deadline = asyncio.get_running_loop().time() + 10.0
            while b_has_tombstone():
                b.node.gc()
                assert asyncio.get_running_loop().time() < deadline, \
                    "B never collected after full coverage"
                await asyncio.sleep(0.1)
        finally:
            await cluster.close()
    asyncio.run(main())


def test_backoff_delay_deterministic_and_bounded():
    """The reconnect ladder: exponential growth to the ceiling, and
    jitter that is a pure function of (node, peer, attempt) — chaos
    replays depend on it."""
    from constdb_tpu.replica.link import backoff_delay

    raw = [backoff_delay(0.2, 2.0, 5.0, 0.0, 1, "a:1", n)
           for n in range(12)]
    assert raw == sorted(raw)
    assert raw[0] == 0.2 and raw[-1] == 5.0
    jit = [backoff_delay(0.2, 2.0, 5.0, 0.2, 1, "a:1", n)
           for n in range(12)]
    assert jit == [backoff_delay(0.2, 2.0, 5.0, 0.2, 1, "a:1", n)
                   for n in range(12)]  # deterministic
    assert all(0.8 * r <= j <= 1.2 * r + 1e-9
               for r, j in zip(raw, jit))
    # distinct nodes de-synchronize against the same returned peer
    assert backoff_delay(1, 2, 60, 0.2, 1, "a:1", 3) != \
        backoff_delay(1, 2, 60, 0.2, 2, "a:1", 3)


def test_info_reports_link_state_and_reconnects(tmp_path):
    """Satellite: the previously-implicit retry cadence is observable —
    INFO carries repl_link_state + repl_reconnects, and a killed
    connection shows up in both."""
    from constdb_tpu.chaos import FaultPlane

    async def main():
        plane = FaultPlane(5)
        cluster = ChaosCluster(str(tmp_path), seed=5,
                               specs=[NodeSpec(), NodeSpec()],
                               plane=plane)
        await cluster.start()
        try:
            a, b = cluster.apps
            c = await Client().connect(a.advertised_addr)
            await c.cmd("meet", b.advertised_addr)
            await cluster.full_mesh()
            assert plane.kill_connections(0, 1) >= 1
            deadline = asyncio.get_running_loop().time() + 15.0
            while a.node.stats.repl_reconnects + \
                    b.node.stats.repl_reconnects < 1:
                assert asyncio.get_running_loop().time() < deadline, \
                    "no reconnect counted after a connection kill"
                await asyncio.sleep(0.05)
            await cluster.full_mesh(timeout=15.0)
            info = (await c.cmd("info", "replication")).val.decode()
            assert "repl_link_state" in info
            assert "state=connected" in info
            assert "reconnects=" in info
            stats = (await c.cmd("info", "stats")).val.decode()
            assert "repl_reconnects:" in stats
            await c.close()
        finally:
            await cluster.close()
    asyncio.run(main())


def test_replack_carries_cluster_coverage(tmp_path):
    """Wire pin for the coverage field: after a converged exchange both
    peers hold a non-negative coverage for each other (legacy peers
    stay at -1 and keep the ack-only horizon)."""
    async def main():
        cluster = ChaosCluster(str(tmp_path), seed=4,
                               specs=[NodeSpec(), NodeSpec()])
        await cluster.start()
        try:
            a, b = cluster.apps
            cl = await Client().connect(a.advertised_addr)
            await cl.cmd("meet", b.advertised_addr)
            await cl.cmd("set", "k", "v")
            await cl.close()
            await cluster.converge()
            deadline = asyncio.get_running_loop().time() + 10.0
            while True:
                covs = [m.coverage
                        for app in cluster.apps
                        for m in app.node.replicas.peers.values()]
                if covs and all(c >= 0 for c in covs):
                    break
                assert asyncio.get_running_loop().time() < deadline, covs
                await asyncio.sleep(0.05)
        finally:
            await cluster.close()
    asyncio.run(main())
