"""Chaos convergence: randomized concurrent writes with repeated node
crashes and restarts (cold from snapshot or warm in-memory) must still
converge to the oracle.

This extends the reference's randomized-workload strategy (reference
bin/test.rs:131-144, SURVEY.md §4) with the failure dimension §5.3 calls
for: nodes leave mid-stream, lose their process state, boot-restore from
their last snapshot, and rejoin through partial OR full resync depending
on what the survivors' repl-logs still cover.
"""

from __future__ import annotations

import asyncio
import os
import random

import pytest

from constdb_tpu.persist.snapshot import NodeMeta, dump_keyspace
from constdb_tpu.resp.message import Int
from constdb_tpu.server.io import ServerApp, start_node
from constdb_tpu.server.node import Node

from cluster_util import Client, close_cluster, converge, make_cluster, FAST


async def _restart_cold(app: ServerApp, work_dir: str) -> ServerApp:
    """Crash + cold boot: dump the node's state, close, then build a FRESH
    Node restored from the snapshot on the same port (the subprocess path
    start_node uses — io.py boot restore)."""
    old = app.node
    snap = os.path.join(work_dir, f"chaos.{old.node_id}.snapshot")
    old.ensure_flushed()
    dump_keyspace(snap, old.ks,
                  NodeMeta(node_id=old.node_id, alias=old.alias,
                           repl_last_uuid=old.repl_log.last_uuid),
                  old.replicas.records())
    port = app.port
    await app.close()
    node = Node(node_id=old.node_id, alias=old.alias)
    return await start_node(node, host="127.0.0.1", port=port,
                            work_dir=work_dir, snapshot_path=snap, **FAST)


async def _restart_warm(app: ServerApp, work_dir: str) -> ServerApp:
    """Close the server but keep the Node object (process hiccup: state
    survives, connections do not)."""
    port = app.port
    await app.close()
    app2 = ServerApp(app.node, host="127.0.0.1", port=port,
                     work_dir=work_dir, **FAST)
    await app2.start()
    return app2


@pytest.mark.parametrize("seed", [1, 2])
def test_chaos_restarts_converge(tmp_path, seed):
    async def main():
        rng = random.Random(seed)
        apps = await make_cluster(3, str(tmp_path))
        try:
            c0 = await Client().connect(apps[0].advertised_addr)
            for other in apps[1:]:
                await c0.cmd("meet", other.advertised_addr)
            await converge(apps)
            await c0.close()

            oracle_counts: dict[str, int] = {}
            oracle_sets: dict[str, set] = {}
            for round_no in range(6):
                # a burst of writes spread over whichever nodes are up
                clients = [await Client().connect(a.advertised_addr)
                           for a in apps]
                for i in range(40):
                    c = rng.choice(clients)
                    if rng.random() < 0.5:
                        k = f"cnt{rng.randrange(8)}"
                        await c.cmd("incr", k)
                        oracle_counts[k] = oracle_counts.get(k, 0) + 1
                    else:
                        k = f"set{rng.randrange(8)}"
                        m = f"m{round_no}-{i}"
                        await c.cmd("sadd", k, m)
                        oracle_sets.setdefault(k, set()).add(m)
                for c in clients:
                    await c.close()

                # crash / restart one node (skip some rounds)
                victim = rng.randrange(len(apps))
                style = rng.random()
                if style < 0.4:
                    apps[victim] = await _restart_cold(apps[victim],
                                                       str(tmp_path))
                elif style < 0.8:
                    apps[victim] = await _restart_warm(apps[victim],
                                                       str(tmp_path))
                await asyncio.sleep(0.1)

            await converge(apps, timeout=45.0)
            # converged state must equal the oracle on EVERY node
            for app in apps:
                c = await Client().connect(app.advertised_addr)
                for k, want in oracle_counts.items():
                    assert await c.cmd("get", k) == Int(want), (k, app.port)
                for k, want in oracle_sets.items():
                    got = await c.cmd("smembers", k)
                    assert {b.val.decode() for b in got.items} == want, k
                await c.close()
        finally:
            await close_cluster(apps)
    asyncio.run(main())
