"""Chaos convergence: randomized concurrent writes with repeated node
crashes and restarts (cold from snapshot or warm in-memory) must still
converge to the oracle.

This extends the reference's randomized-workload strategy (reference
bin/test.rs:131-144, SURVEY.md §4) with the failure dimension §5.3 calls
for: nodes leave mid-stream, lose their process state, boot-restore from
their last snapshot, and rejoin through partial OR full resync depending
on what the survivors' repl-logs still cover.
"""

from __future__ import annotations

import asyncio
import os
import random

import pytest

from constdb_tpu.persist.snapshot import NodeMeta, dump_keyspace
from constdb_tpu.resp.message import Arr, Int
from constdb_tpu.server.io import ServerApp, start_node
from constdb_tpu.server.node import Node

from cluster_util import Client, close_cluster, converge, make_cluster, FAST


async def _restart_cold(app: ServerApp, work_dir: str) -> ServerApp:
    """Crash + cold boot: dump the node's state, close, then build a FRESH
    Node restored from the snapshot on the same port (the subprocess path
    start_node uses — io.py boot restore)."""
    old = app.node
    snap = os.path.join(work_dir, f"chaos.{old.node_id}.snapshot")
    old.ensure_flushed()
    dump_keyspace(snap, old.ks,
                  NodeMeta(node_id=old.node_id, alias=old.alias,
                           repl_last_uuid=old.repl_log.last_uuid),
                  old.replicas.records())
    port = app.port
    await app.close()
    node = Node(node_id=old.node_id, alias=old.alias)
    return await start_node(node, host="127.0.0.1", port=port,
                            work_dir=work_dir, snapshot_path=snap, **FAST)


async def _restart_warm(app: ServerApp, work_dir: str) -> ServerApp:
    """Close the server but keep the Node object (process hiccup: state
    survives, connections do not)."""
    port = app.port
    await app.close()
    app2 = ServerApp(app.node, host="127.0.0.1", port=port,
                     work_dir=work_dir, **FAST)
    await app2.start()
    return app2


def _chaos_run(tmp_path, seed, rounds=6, ops_per_round=40,
               repl_log_cap=1_024_000, converge_timeout=45.0):
    """One randomized chaos run: bursts of mixed writes (counters, sets,
    hashes, deletes) across whichever nodes are up, with crash/restart
    between bursts (cold from snapshot or warm in-memory), then full
    convergence against a client-side oracle — the reference's randomized
    black-box strategy (bin/test.rs:131-144) plus the failure dimension.
    A small repl_log_cap forces the partial-vs-full resync decision both
    ways across the run."""
    async def main():
        rng = random.Random(seed)
        apps = await make_cluster(3, str(tmp_path),
                                  repl_log_cap=repl_log_cap)
        try:
            c0 = await Client().connect(apps[0].advertised_addr)
            for other in apps[1:]:
                await c0.cmd("meet", other.advertised_addr)
            await converge(apps)
            await c0.close()

            oracle_counts: dict[str, int] = {}
            oracle_sets: dict[str, set] = {}
            oracle_hash: dict[str, dict] = {}
            deleted: set = set()
            for round_no in range(rounds):
                # a burst of writes spread over whichever nodes are up
                clients = [await Client().connect(a.advertised_addr)
                           for a in apps]
                for i in range(ops_per_round):
                    c = rng.choice(clients)
                    die = rng.random()
                    if die < 0.4:
                        k = f"cnt{rng.randrange(8)}"
                        await c.cmd("incr", k)
                        oracle_counts[k] = oracle_counts.get(k, 0) + 1
                    elif die < 0.7:
                        k = f"set{rng.randrange(8)}"
                        m = f"m{round_no}-{i}"
                        await c.cmd("sadd", k, m)
                        oracle_sets.setdefault(k, set()).add(m)
                    elif die < 0.85:
                        k = f"h{rng.randrange(4)}"
                        f, v = f"f{rng.randrange(6)}", f"v{round_no}-{i}"
                        await c.cmd("hset", k, f, v)
                        oracle_hash.setdefault(k, {})[f] = v
                    elif die < 0.95 and oracle_sets:
                        # remove a member (tombstone traffic) — but only if
                        # it is VISIBLE on the issuing node: removing a
                        # not-yet-replicated member mints a delete uuid the
                        # node's HLC never ordered after the add, so
                        # add-wins legitimately beats it and a client-side
                        # oracle cannot model that race
                        k = rng.choice(sorted(oracle_sets))
                        if oracle_sets[k]:
                            m = rng.choice(sorted(oracle_sets[k]))
                            got = await c.cmd("smembers", k)
                            if isinstance(got, Arr) and \
                                    any(b.val.decode() == m
                                        for b in got.items):
                                await c.cmd("srem", k, m)
                                oracle_sets[k].discard(m)
                    else:
                        k = f"reg{rng.randrange(6)}"
                        await c.cmd("set", k, f"d{round_no}-{i}")
                        await c.cmd("del", k)
                        deleted.add(k)
                for c in clients:
                    await c.close()

                # crash / restart one node (skip some rounds)
                victim = rng.randrange(len(apps))
                style = rng.random()
                if style < 0.4:
                    apps[victim] = await _restart_cold(apps[victim],
                                                       str(tmp_path))
                elif style < 0.8:
                    apps[victim] = await _restart_warm(apps[victim],
                                                       str(tmp_path))
                await asyncio.sleep(0.1)

            await converge(apps, timeout=converge_timeout)
            # converged state must equal the oracle on EVERY node, and GC
            # must actually collect once the horizon passes the tombstones
            for app in apps:
                c = await Client().connect(app.advertised_addr)
                for k, want in oracle_counts.items():
                    assert await c.cmd("get", k) == Int(want), (k, app.port)
                for k, want in oracle_sets.items():
                    got = await c.cmd("smembers", k)
                    assert {b.val.decode() for b in got.items} == want, k
                for k, want in oracle_hash.items():
                    got = await c.cmd("hgetall", k)
                    pairs = {p.items[0].val.decode(): p.items[1].val.decode()
                             for p in got.items}
                    assert pairs == want, (k, app.port)
                for k in deleted:
                    from constdb_tpu.resp.message import Nil
                    assert isinstance(await c.cmd("get", k), Nil), k
                await c.close()
            # GC-drained assertion: every peer has acked the full stream at
            # convergence, so the horizon passes every tombstone — a few GC
            # cycles must empty the garbage heap (collection really ran,
            # not merely deferred — VERDICT r4 item 9)
            deadline = asyncio.get_running_loop().time() + 10.0
            while any(len(a.node.ks.garbage) for a in apps):
                for a in apps:
                    a.node.gc()
                if asyncio.get_running_loop().time() > deadline:
                    raise AssertionError(
                        "garbage heap not drained: "
                        + str([len(a.node.ks.garbage) for a in apps]))
                await asyncio.sleep(0.2)
        finally:
            await close_cluster(apps)
    asyncio.run(main())


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_chaos_restarts_converge(tmp_path, seed):
    _chaos_run(tmp_path, seed)


def test_cold_restart_does_not_resurrect_collected_tombstones(tmp_path):
    """Regression (round-5 chaos find): a cold-restarted node must resume
    pulling each peer from its SNAPSHOT-RECORDED watermark.  With the
    watermark lost (resume 0), peers replay their whole repl_log ring —
    including ADDS whose tombstones the mesh already GC-collected — and
    the deleted member resurrects with no surviving delete op anywhere.
    Requires: add on A, remove propagated + collected everywhere, THEN a
    cold restart of B followed by A's ring replay."""
    async def main():
        from constdb_tpu.resp.message import Nil

        apps = await make_cluster(2, str(tmp_path))
        try:
            a, b = apps
            ca = await Client().connect(a.advertised_addr)
            cb = await Client().connect(b.advertised_addr)
            await ca.cmd("meet", b.advertised_addr)
            await converge(apps)
            await ca.cmd("sadd", "s", "gone")
            await ca.cmd("sadd", "s", "keep")
            await converge(apps)
            # the REMOVE originates on B — the node about to lose its
            # repl_log: after the restart no log anywhere holds the delete,
            # while A's ring still holds the add
            await cb.cmd("srem", "s", "gone")
            await cb.close()
            await converge(apps)
            # wait until BOTH nodes physically collected the tombstone
            deadline = asyncio.get_running_loop().time() + 10.0
            while True:
                for app in apps:
                    app.node.gc()
                if all(len(app.node.ks.garbage) == 0 and
                       app.node.ks.el_row(app.node.ks.lookup(b"s"),
                                          b"gone") < 0 for app in apps):
                    break
                assert asyncio.get_running_loop().time() < deadline, \
                    "tombstone never collected"
                await asyncio.sleep(0.1)
            # cold-restart B; A's ring still holds the original SADD op
            assert a.node.repl_log.first_uuid <= a.node.repl_log.last_uuid
            apps[1] = await _restart_cold(apps[1], str(tmp_path))
            await converge(apps, timeout=15.0)
            for app in apps:
                c = await Client().connect(app.advertised_addr)
                got = await c.cmd("smembers", "s")
                members = ({i.val for i in got.items}
                           if not isinstance(got, Nil) else set())
                assert members == {b"keep"}, (app.port, members)
                await c.close()
            await ca.close()
        finally:
            await close_cluster(apps)
    asyncio.run(main())


@pytest.mark.skipif(not os.environ.get("CONSTDB_SLOW"),
                    reason="set CONSTDB_SLOW=1 for the chaos soak")
def test_chaos_soak(tmp_path):
    """Long randomized soak: 25 restart cycles over 5000 mixed ops, with a
    repl_log small enough that full AND partial resyncs both occur many
    times (reference bin/test.rs randomized-workload scale)."""
    _chaos_run(tmp_path, seed=99, rounds=25, ops_per_round=200,
               repl_log_cap=4_000, converge_timeout=90.0)
