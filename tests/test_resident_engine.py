"""Device-resident merge state: streaming chunk merges must equal the CPU
engine after flush, survive interleaved op-path writes, and fall back
correctly when a family takes the scatter path."""

import numpy as np
import pytest

from constdb_tpu.engine.base import ColumnarBatch, batch_from_keyspace
from constdb_tpu.engine.cpu import CpuMergeEngine
from constdb_tpu.engine.tpu import TpuMergeEngine
from constdb_tpu.persist.snapshot import batch_chunks
from constdb_tpu.resp.message import Bulk, NIL
from constdb_tpu.server.node import Node
from constdb_tpu.store.keyspace import KeySpace

from test_merge_properties import gen_store


def _cmd(node, *parts):
    return node.execute([Bulk(p if isinstance(p, bytes) else str(p).encode())
                         for p in parts])


def chunked(ks, chunk_keys=29):
    return list(batch_chunks(batch_from_keyspace(ks), chunk_keys))


@pytest.mark.parametrize("seed", range(4))
def test_streaming_chunks_match_cpu(seed):
    """Apply R replicas' snapshots chunk-by-chunk (the replica link's real
    access pattern) through a resident engine; flushed state must equal the
    CPU engine fed the same chunks."""
    srcs = [gen_store(seed=seed * 10 + i, node=i + 1) for i in range(3)]
    all_chunks = [c for src in srcs for c in chunked(src)]

    cpu_store = KeySpace()
    cpu = CpuMergeEngine()
    for c in all_chunks:
        cpu.merge(cpu_store, c)

    res_store = KeySpace()
    eng = TpuMergeEngine(resident=True)
    for c in all_chunks:
        eng.merge(res_store, c)
    assert eng.needs_flush
    eng.flush(res_store)
    assert not eng.needs_flush
    assert res_store.canonical() == cpu_store.canonical()
    # flush is idempotent and a second flush with no merges is a no-op
    eng.flush(res_store)
    assert res_store.canonical() == cpu_store.canonical()


def test_interleaved_op_writes():
    """Node-level: op-path writes between resident merges see flushed state
    and invalidate the device mirror safely."""
    src = Node(node_id=2)
    for i in range(60):
        _cmd(src, b"incr", b"c%d" % (i % 7))
        _cmd(src, b"sadd", b"s%d" % (i % 5), b"m%d" % i)
        _cmd(src, b"set", b"r%d" % (i % 3), b"v%d" % i)

    node = Node(node_id=1, engine=TpuMergeEngine(resident=True))
    chunks = chunked(src.ks, chunk_keys=7)
    half = len(chunks) // 2
    for c in chunks[:half]:
        node.merge_batch(c)
    # reads flush lazily; writes bump the keyspace version
    assert node.engine.needs_flush
    _cmd(node, b"incr", b"c0")
    assert not node.engine.needs_flush  # execute() flushed first
    _cmd(node, b"sadd", b"s0", b"extra")
    for c in chunks[half:]:
        node.merge_batch(c)
    node.ensure_flushed()

    # oracle: CPU node fed the same sequence
    ref = Node(node_id=1)
    for c in chunks[:half]:
        ref.merge_batch(c)
    _cmd(ref, b"incr", b"c0")
    _cmd(ref, b"sadd", b"s0", b"extra")
    for c in chunks[half:]:
        ref.merge_batch(c)
    # uuids minted by the two nodes differ (wall clock) — compare values
    for key in (b"c%d" % i for i in range(7)):
        assert _cmd(node, b"get", key) == _cmd(ref, b"get", key)
    got = _cmd(node, b"smembers", b"s0")
    want = _cmd(ref, b"smembers", b"s0")
    assert {m.val for m in got.items} == {m.val for m in want.items}


def test_scatter_fallback_drops_mirror():
    """A non-unique (op-stream) batch takes the scatter path; resident
    mirrors must flush+drop so host state stays authoritative."""
    src = gen_store(seed=3, node=1)
    eng = TpuMergeEngine(resident=True)
    store = KeySpace()
    for c in chunked(src):
        eng.merge(store, c)
    assert eng.needs_flush

    # craft a duplicate-slot batch (same key twice)
    b = ColumnarBatch()
    b.rows_unique_per_slot = False
    b.keys = [b"dup", b"dup"]
    b.key_enc = np.array([3, 3], dtype=np.int8)  # ENC_BYTES
    b.key_ct = np.array([5 << 22, 6 << 22], dtype=np.int64)
    b.key_mt = np.array([5 << 22, 6 << 22], dtype=np.int64)
    b.key_dt = np.zeros(2, dtype=np.int64)
    b.key_expire = np.zeros(2, dtype=np.int64)
    b.reg_val = [b"a", b"b"]
    b.reg_t = np.array([5 << 22, 6 << 22], dtype=np.int64)
    b.reg_node = np.array([1, 1], dtype=np.int64)
    eng.merge(store, b)

    cpu_store = KeySpace()
    cpu = CpuMergeEngine()
    for c in chunked(src):
        cpu.merge(cpu_store, c)
    cpu.merge(cpu_store, b)
    eng.flush(store)
    assert store.canonical() == cpu_store.canonical()
    kid = store.lookup(b"dup")
    assert store.register_get(kid) == b"b"


def test_gc_compaction_invalidates_resident_mirror():
    """gc() and element compaction reorder/shrink the element table; a
    resident engine that kept its device mirror would flush stale
    add_t/add_node/del_t over the compacted rows.  KeySpace.version must
    bump so the next merge re-uploads from the host."""
    src = Node(node_id=2)
    for i in range(40):
        _cmd(src, b"sadd", b"s%d" % (i % 4), b"m%d" % i)

    node = Node(node_id=1, engine=TpuMergeEngine(resident=True))
    ref = Node(node_id=1)  # oracle: CPU engine, same op sequence
    for c in chunked(src.ks, chunk_keys=11):
        node.merge_batch(c)
        ref.merge_batch(c)
    node.ensure_flushed()

    # tombstone half the members, collect them, and force the compaction
    # path (row REORDER) regardless of the production thresholds
    for i in range(0, 40, 2):
        _cmd(node, b"srem", b"s%d" % (i % 4), b"m%d" % i)
        _cmd(ref, b"srem", b"s%d" % (i % 4), b"m%d" % i)
    v0 = node.ks.version
    assert node.gc() > 0
    assert node.ks.version > v0
    node.ks._compact_elements()
    ref.gc()
    ref.ks._compact_elements()

    src2 = Node(node_id=3)
    for i in range(40):
        _cmd(src2, b"sadd", b"s%d" % (i % 4), b"n%d" % i)
    for c in chunked(src2.ks, chunk_keys=11):
        node.merge_batch(c)
        ref.merge_batch(c)
    node.ensure_flushed()

    for s in range(4):
        got = _cmd(node, b"smembers", b"s%d" % s)
        want = _cmd(ref, b"smembers", b"s%d" % s)
        assert {m.val for m in got.items} == {m.val for m in want.items}


def test_resident_grows_across_merges():
    """State arrays grow (neutral-filled) as later chunks add new slots."""
    eng = TpuMergeEngine(resident=True)
    store = KeySpace()
    src1 = gen_store(seed=11, node=1)
    src2 = gen_store(seed=12, node=2)
    for c in chunked(src1, chunk_keys=13):
        eng.merge(store, c)
    for c in chunked(src2, chunk_keys=13):
        eng.merge(store, c)
    eng.flush(store)

    cpu_store = KeySpace()
    cpu = CpuMergeEngine()
    for src in (src1, src2):
        cpu.merge(cpu_store, batch_from_keyspace(src))
    assert store.canonical() == cpu_store.canonical()


def test_mixed_traffic_rebuilds_stay_per_family():
    """Interleaving op-path writes with streaming chunk merges must only
    rebuild the mirrors of the planes the ops touched — a counter INCR
    between element-heavy chunks cannot re-upload the element table
    (VERDICT r3 item 6: uploads stay O(families), not O(ops))."""
    src = Node(node_id=2)
    for i in range(200):
        _cmd(src, b"sadd", b"s%d" % (i % 40), b"m%d" % i)
        _cmd(src, b"incr", b"c%d" % (i % 40))
    chunks = chunked(src.ks, 8)
    assert len(chunks) > 4

    node = Node(node_id=1, engine=TpuMergeEngine(resident=True))
    eng = node.engine
    for i, c in enumerate(chunks):
        node.merge_batch(c)
        # op write to the COUNTER plane between chunks (flush + touch)
        _cmd(node, b"incr", b"hits")
    node.ensure_flushed()

    # every INCR invalidated the counter mirror: it rebuilds once per
    # following merge round (O(writes-to-that-plane))...
    assert eng.mirror_rebuilds["cnt"] >= len(chunks) - 1, eng.mirror_rebuilds
    # ...while the element plane, which no op touched, never rebuilds
    assert eng.mirror_rebuilds["el"] == 0, eng.mirror_rebuilds
    # and the result is still exact
    ref = Node(node_id=1)
    for c in chunks:
        CpuMergeEngine().merge(ref.ks, c)
    for i in range(len(chunks)):
        _cmd(ref, b"incr", b"hits")
    # counter values differ (different uuids) — compare the element plane
    for k in (b"s%d" % i for i in range(40)):
        kid_a = node.ks.lookup(k)
        kid_b = ref.ks.lookup(k)
        a = sorted(m for m, *_ in node.ks.elem_live(kid_a))
        b = sorted(m for m, *_ in ref.ks.elem_live(kid_b))
        assert a == b


def test_lazy_expiry_survives_resident_flush():
    """A read-path lazy expiry writes the env plane (query() sets dt); the
    resident env mirror must rebuild afterwards, or its flush would write
    the older dt back and resurrect the expired key."""
    import time
    from constdb_tpu.utils.hlc import SEQ_BITS, now_ms

    src = Node(node_id=2)
    for i in range(30):
        _cmd(src, b"set", b"w%d" % i, b"v")
    chunk = batch_from_keyspace(src.ks)

    node = Node(node_id=1, engine=TpuMergeEngine(resident=True))
    _cmd(node, b"set", b"victim", b"gone-soon")
    _cmd(node, b"expireat", b"victim", b"%d" % ((now_ms() + 40) << SEQ_BITS))
    node.merge_batch(chunk)          # env mirror built (includes victim row)
    time.sleep(0.08)
    assert _cmd(node, b"get", b"victim") == NIL   # lazy expiry fires (read)
    kid = node.ks.lookup(b"victim")
    dt_expired = int(node.ks.keys.dt[kid])
    assert dt_expired > 0
    node.merge_batch(batch_from_keyspace(src.ks))  # mirror must rebuild
    node.ensure_flushed()
    # a re-read would self-heal (lazy expiry re-fires), hiding the bug —
    # the raw dt column is the truth the snapshot/replication paths see
    assert int(node.ks.keys.dt[kid]) >= dt_expired, \
        "flush reverted the expiry tombstone"
