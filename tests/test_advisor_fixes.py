"""Regression tests for the round-2 advisor findings (ADVICE.md).

Each test pins one fixed behavior: engine fallback instead of boot
failure, snapshot-dump invalidation on bulk ingest, redis LPUSH order,
the RESP fast-path bulk cap, and the structured FORGOTTEN error code.
"""

import asyncio

import numpy as np
import pytest

from constdb_tpu.errors import InvalidRequestMsg
from constdb_tpu.resp.codec import RespParser
from constdb_tpu.resp.message import Arr, Bulk, Err
from constdb_tpu.server.node import Node


def _cmd(node, *parts):
    return node.execute([Bulk(p if isinstance(p, bytes) else str(p).encode())
                         for p in parts])


# --------------------------------------------------------------- 1: engine


def test_engine_tpu_falls_back_instead_of_raising(monkeypatch, caplog):
    """engine='tpu' on a backend-less host degrades to a working engine
    with a warning — a node must boot and serve either way."""
    import constdb_tpu.conf as conf
    from constdb_tpu.utils import backend as bk

    monkeypatch.setattr(
        bk, "probe_backend",
        lambda timeout=90.0: bk.BackendProbe(False,
                                             error="simulated: no device"))
    eng = conf.build_engine("tpu")
    assert eng is not None and hasattr(eng, "merge")


# ----------------------------------------------------- 2: dump invalidation


def test_bulk_ingest_invalidates_shared_dump(tmp_path):
    """State merged OUTSIDE the repl_log (snapshot ingest) must force a
    fresh full-sync dump: the old dump + log tail would silently omit it."""
    import sys
    sys.path.insert(0, ".")
    from bench import make_workload
    from constdb_tpu.server.io import ServerApp

    async def main():
        node = Node(node_id=1)
        app = ServerApp(node, work_dir=str(tmp_path))
        _cmd(node, b"set", b"seed", b"1")
        d1 = await app.shared_dump.acquire()
        assert app.shared_dump.dumps_taken == 1
        # reuse while nothing bypassed the log
        assert (await app.shared_dump.acquire()) is d1
        # bulk ingest (not in the repl_log) must invalidate
        node.merge_batch(make_workload(50, 1, seed=3)[0])
        d2 = await app.shared_dump.acquire()
        assert app.shared_dump.dumps_taken == 2
        assert d2 is not d1
    asyncio.run(main())


# ------------------------------------------------------------ 3: lpush order


def test_lpush_multi_value_order_matches_redis():
    node = Node(node_id=1)
    _cmd(node, b"rpush", b"l", b"x")
    _cmd(node, b"lpush", b"l", b"a", b"b", b"c")
    got = _cmd(node, b"lrange", b"l", b"0", b"-1")
    assert isinstance(got, Arr)
    assert [b.val for b in got.items] == [b"c", b"b", b"a", b"x"]


# --------------------------------------------------------- 4: RESP bulk cap


def test_fast_path_rejects_oversized_bulk():
    p = RespParser()
    # flat array fast path: declared 600MB bulk must fail fast, without
    # ever buffering the body
    p.feed(b"*2\r\n$3\r\nset\r\n$629145600\r\n")
    with pytest.raises(InvalidRequestMsg):
        p.next_msg()


def test_general_path_still_rejects_oversized_bulk():
    p = RespParser()
    p.feed(b"$629145600\r\n")
    with pytest.raises(InvalidRequestMsg):
        p.next_msg()


# ------------------------------------------------------ 5: FORGOTTEN prefix


def test_forgotten_requires_structured_code(tmp_path):
    from constdb_tpu.errors import CstError
    from constdb_tpu.replica.link import ReplicaLink
    from constdb_tpu.replica.manager import ReplicaMeta
    from constdb_tpu.server.io import ServerApp

    async def main():
        node = Node(node_id=1)
        app = ServerApp(node, work_dir=str(tmp_path))
        meta = ReplicaMeta("127.0.0.1:1", add_t=1)
        link = ReplicaLink(app, meta)
        # an unrelated error that merely mentions the word must NOT suspend
        with pytest.raises(CstError):
            link._check_sync_reply(Err(b"db loading, forgotten keys pending"))
        assert meta.dial_suspended is False
        # the structured code DOES suspend
        with pytest.raises(CstError):
            link._check_sync_reply(Err(b"FORGOTTEN removed from this mesh"))
        assert meta.dial_suspended is True
    asyncio.run(main())
