"""Differential tests: the batched JAX engine must be bit-identical to the
CPU reference engine on random multi-node CRDT states (SURVEY.md §7 "Exact
tie semantics ... must be bit-identical between CPU and TPU engines or
replicas diverge").
"""

import pytest

from constdb_tpu.crdt import ENC_COUNTER, ENC_DICT, ENC_SET
from constdb_tpu.engine import CpuMergeEngine, batch_from_keyspace
from constdb_tpu.engine.tpu import TpuMergeEngine
from constdb_tpu.store import KeySpace

from test_merge_properties import gen_store


@pytest.fixture(scope="module", params=["bulk", "scatter"])
def engines(request):
    tpu = TpuMergeEngine()
    # force the chooser: both device strategies must match the CPU engine
    # (bulk needs rows-unique batches; those tests fall back to scatter)
    tpu.BULK_FRACTION = 10**18 if request.param == "bulk" else 0
    return CpuMergeEngine(), tpu


def both_sums(ks):
    return {k: ks.counter_sum(kid) for kid, k in enumerate(ks.key_bytes)
            if ks.enc_of(kid) == ENC_COUNTER}


@pytest.mark.parametrize("seed", range(10))
def test_merge_into_empty_matches_cpu(engines, seed):
    cpu, tpu = engines
    src = gen_store(seed, node=1)
    a, b = KeySpace(), KeySpace()
    s1 = cpu.merge(a, batch_from_keyspace(src))
    s2 = tpu.merge(b, batch_from_keyspace(src))
    assert a.canonical() == b.canonical()
    assert both_sums(a) == both_sums(b)
    assert (s1.keys_seen, s1.keys_created) == (s2.keys_seen, s2.keys_created)


@pytest.mark.parametrize("seed", range(10))
def test_merge_overlapping_states_matches_cpu(engines, seed):
    cpu, tpu = engines
    x = gen_store(seed, node=1)
    y = gen_store(seed + 1000, node=2)
    bx, by = batch_from_keyspace(x), batch_from_keyspace(y)

    a = KeySpace()
    cpu.merge(a, bx)
    cpu.merge(a, by)
    b = KeySpace()
    tpu.merge(b, bx)
    tpu.merge(b, by)
    assert a.canonical() == b.canonical()
    assert both_sums(a) == both_sums(b)


@pytest.mark.parametrize("seed", [0, 3, 7])
def test_three_way_and_idempotent(engines, seed):
    cpu, tpu = engines
    batches = [batch_from_keyspace(gen_store(seed + i * 77, node=i + 1)) for i in range(3)]
    a, b = KeySpace(), KeySpace()
    for bt in batches + [batches[0]]:  # re-merge first batch: idempotence
        cpu.merge(a, bt)
        tpu.merge(b, bt)
    assert a.canonical() == b.canonical()


@pytest.mark.parametrize("seed", [2, 5])
def test_gc_after_tpu_merge_matches_cpu(engines, seed):
    cpu, tpu = engines
    x = gen_store(seed, node=1)
    y = gen_store(seed + 500, node=2)
    a, b = KeySpace(), KeySpace()
    for eng, ks in ((cpu, a), (tpu, b)):
        eng.merge(ks, batch_from_keyspace(x))
        eng.merge(ks, batch_from_keyspace(y))
        ks.gc(40 << 22)  # horizon past every uuid in gen_store
    assert a.canonical() == b.canonical()
    # all dead elements must have been collected identically
    for ks in (a, b):
        for kid, key in enumerate(ks.key_bytes):
            if ks.enc_of(kid) in (ENC_SET, ENC_DICT):
                for m, at, an, dt, v in ks.elem_all(kid):
                    assert at >= dt, (key, m)


def test_type_conflict_skipped_tpu():
    tpu = TpuMergeEngine()
    a, b = KeySpace(), KeySpace()
    ka, _ = a.get_or_create(b"k", ENC_COUNTER, 5 << 22)
    a.counter_change(ka, 1, 1, 5 << 22)
    kb, _ = b.get_or_create(b"k", ENC_SET, 6 << 22)
    b.elem_add(kb, b"m", None, 6 << 22, 2)
    st = tpu.merge(a, batch_from_keyspace(b))
    assert st.type_conflicts == 1
    assert a.counter_sum(a.lookup(b"k")) == 1


def test_empty_batch():
    tpu = TpuMergeEngine()
    ks = KeySpace()
    st = tpu.merge(ks, batch_from_keyspace(KeySpace()))
    assert st.keys_seen == 0


def test_duplicate_slot_rows_in_one_batch():
    """A batch built from a raw op stream can carry several rows for the same
    (key, node) slot; the engine must LWW-reduce them, not keep the last
    placement (regression: the dense path used to silently drop all but the
    final row)."""
    import numpy as np

    from constdb_tpu.engine.base import ColumnarBatch

    b = ColumnarBatch()
    b.keys = [b"k"]
    b.key_enc = np.array([0], np.int8)  # counter
    b.key_ct = np.array([1 << 22], np.int64)
    b.key_mt = np.array([0], np.int64)
    b.key_dt = np.array([0], np.int64)
    b.key_expire = np.array([0], np.int64)
    b.reg_val = [None]
    b.reg_t = np.zeros(1, np.int64)
    b.reg_node = np.zeros(1, np.int64)
    # newer write listed FIRST: last-placement would keep the stale value
    b.cnt_ki = np.array([0, 0], np.int64)
    b.cnt_node = np.array([7, 7], np.int64)
    b.cnt_val = np.array([50, 3], np.int64)
    b.cnt_uuid = np.array([9 << 22, 2 << 22], np.int64)
    b.cnt_base = np.zeros(2, np.int64)
    b.cnt_base_t = np.full(2, KeySpace.NEUTRAL_T, np.int64)
    assert not b.rows_unique_per_slot

    for eng in (CpuMergeEngine(), TpuMergeEngine()):
        ks = KeySpace()
        eng.merge(ks, b)
        assert ks.counter_sum(ks.lookup(b"k")) == 50, eng.name


def test_duplicate_keys_in_one_batch():
    """A raw op-stream batch may list the same key twice; the engine must
    resolve both to one store row (regression: bulk-create used to make two
    rows and orphan one)."""
    import numpy as np

    from constdb_tpu.engine.base import ColumnarBatch

    b = ColumnarBatch()
    b.keys = [b"k", b"k"]
    b.key_enc = np.array([0, 0], np.int8)
    b.key_ct = np.array([1 << 22, 1 << 22], np.int64)
    b.key_mt = np.zeros(2, np.int64)
    b.key_dt = np.zeros(2, np.int64)
    b.key_expire = np.zeros(2, np.int64)
    b.reg_val = [None, None]
    b.reg_t = np.zeros(2, np.int64)
    b.reg_node = np.zeros(2, np.int64)
    b.cnt_ki = np.array([0, 1], np.int64)
    b.cnt_node = np.array([1, 2], np.int64)
    b.cnt_val = np.array([5, 10], np.int64)
    b.cnt_uuid = np.array([2 << 22, 3 << 22], np.int64)
    b.cnt_base = np.zeros(2, np.int64)
    b.cnt_base_t = np.full(2, KeySpace.NEUTRAL_T, np.int64)

    for eng in (CpuMergeEngine(), TpuMergeEngine()):
        ks = KeySpace()
        eng.merge(ks, b)
        assert ks.n_keys() == 1, eng.name
        assert ks.counter_sum(ks.lookup(b"k")) == 15, eng.name


# ------------------------------------------------- multi-device (kv mesh)

@pytest.fixture(scope="module")
def kv_mesh():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the 8-device virtual CPU platform (conftest)")
    from constdb_tpu.parallel import engine_mesh
    return engine_mesh()


@pytest.mark.parametrize("resident", [False, True])
@pytest.mark.parametrize("seed", range(4))
def test_mesh_engine_matches_cpu(kv_mesh, resident, seed):
    """The kv-sharded engine (state range-partitioned over the device
    mesh) must stay bit-identical to the CPU engine on streamed chunked
    catch-up — the production replica-link access pattern."""
    from constdb_tpu.persist.snapshot import batch_chunks

    srcs = [gen_store(seed * 10 + i, node=i + 1) for i in range(3)]
    chunks = [c for src in srcs
              for c in batch_chunks(batch_from_keyspace(src), 37)]

    a = KeySpace()
    cpu = CpuMergeEngine()
    for c in chunks:
        cpu.merge(a, c)

    b = KeySpace()
    eng = TpuMergeEngine(resident=resident, mesh=kv_mesh)
    for c in chunks:
        eng.merge(b, c)
    if eng.needs_flush:
        eng.flush(b)
    assert a.canonical() == b.canonical()
    assert both_sums(a) == both_sums(b)


def test_mesh_engine_state_is_sharded(kv_mesh):
    """The resident mirrors really are range-partitioned over "kv" (not
    silently replicated)."""
    src = gen_store(2, node=1, n_ops=400)
    b = KeySpace()
    eng = TpuMergeEngine(resident=True, mesh=kv_mesh)
    eng.merge(b, batch_from_keyspace(src))
    assert eng._res, "resident state missing"
    from jax.sharding import PartitionSpec
    for fam, res in eng._res.items():
        for name, arr in res["cols"].items():
            spec = arr.sharding.spec
            assert spec and spec[0] == "kv", \
                f"{fam}.{name} not kv-sharded: {arr.sharding}"
    eng.flush(b)


# ---------------------------------------------- aligned multi-batch fold

@pytest.fixture(scope="module")
def aligned_batches():
    import bench

    return bench.make_workload(600, 4, seed=11)


@pytest.mark.parametrize("mode", ["xla", "pallas-interpret"])
def test_aligned_fold_matches_cpu(aligned_batches, mode):
    """R aligned replica snapshots reduce on-device in one fused pass
    (Pallas on TPU / XLA dense elsewhere) then scatter once; the result
    must stay bit-identical to the CPU engine folding them one by one."""
    cpu_store = KeySpace()
    cpu = CpuMergeEngine()
    for b in aligned_batches:
        cpu.merge(cpu_store, b)

    eng = TpuMergeEngine(dense_fold=mode)
    st = KeySpace()
    eng.merge_many(st, aligned_batches)
    assert eng.folds > 0, "aligned fold did not trigger"
    assert st.canonical() == cpu_store.canonical()
    assert both_sums(st) == both_sums(cpu_store)


@pytest.mark.parametrize("mode", ["xla", "pallas-interpret"])
def test_aligned_fold_onto_existing_state(aligned_batches, mode):
    """Folding onto a non-empty store: the single scatter must still merge
    correctly against resident prior state."""
    first, rest = aligned_batches[0], aligned_batches[1:]

    cpu_store = KeySpace()
    cpu = CpuMergeEngine()
    for b in aligned_batches:
        cpu.merge(cpu_store, b)

    eng = TpuMergeEngine(resident=True, dense_fold=mode)
    st = KeySpace()
    eng.merge(st, first)
    eng.merge_many(st, rest)
    assert eng.folds > 0
    eng.flush(st)
    assert st.canonical() == cpu_store.canonical()


def test_fold_off_still_matches(aligned_batches):
    eng = TpuMergeEngine(dense_fold="off")
    st = KeySpace()
    eng.merge_many(st, aligned_batches)
    assert eng.folds == 0
    cpu_store = KeySpace()
    cpu = CpuMergeEngine()
    for b in aligned_batches:
        cpu.merge(cpu_store, b)
    assert st.canonical() == cpu_store.canonical()


@pytest.mark.parametrize("mode", ["xla", "pallas-interpret"])
def test_aligned_counter_fold_matches_cpu(mode):
    """Aligned counter rows (same (key, node) slots in every batch —
    repeated syncs from one origin) fold via the fused pair kernel."""
    import bench

    batches = bench.make_workload(400, 1, seed=3)
    # same origin twice, second sync with advanced uuids/values
    b2 = bench.make_workload(400, 1, seed=4)[0]
    b2.cnt_node = batches[0].cnt_node
    many = [batches[0], b2]

    cpu_store = KeySpace()
    cpu = CpuMergeEngine()
    for b in many:
        cpu.merge(cpu_store, b)

    eng = TpuMergeEngine(dense_fold=mode)
    st = KeySpace()
    eng.merge_many(st, many)
    assert eng.folds > 0
    assert st.canonical() == cpu_store.canonical()
    assert both_sums(st) == both_sums(cpu_store)


def _dict_none_batches():
    """Two aligned batches over one dict key: the lexicographic winner for
    member m carries value None (review regression: the winning None must
    CLEAR the stored value, exactly as the CPU engine does)."""
    import numpy as np

    def mk(add_t, val):
        b = batch_from_keyspace(KeySpace())  # empty scaffold
        b.rows_unique_per_slot = True
        b.keys = [b"d1"]
        b.key_enc = np.array([ENC_DICT], dtype=np.int8)
        b.key_ct = np.array([1 << 22], dtype=np.int64)
        b.key_mt = np.array([add_t], dtype=np.int64)
        b.key_dt = np.zeros(1, dtype=np.int64)
        b.key_expire = np.zeros(1, dtype=np.int64)
        b.reg_val = [None]
        b.reg_t = np.zeros(1, dtype=np.int64)
        b.reg_node = np.zeros(1, dtype=np.int64)
        b.el_ki = np.zeros(1, dtype=np.int64)
        b.el_member = [b"m"]
        b.el_val = [val]
        b.el_add_t = np.array([add_t], dtype=np.int64)
        b.el_add_node = np.array([1], dtype=np.int64)
        b.el_del_t = np.zeros(1, dtype=np.int64)
        return b

    lo = mk(100 << 22, b"y")
    hi = mk(200 << 22, None)   # the winner — and it carries None
    return lo, hi


@pytest.mark.parametrize("mode", ["off", "xla", "pallas-interpret"])
def test_winning_none_value_clears_dict_field(mode):
    lo, hi = _dict_none_batches()
    cpu_store = KeySpace()
    cpu = CpuMergeEngine()
    cpu.merge(cpu_store, lo)
    cpu.merge(cpu_store, hi)

    st = KeySpace()
    TpuMergeEngine(dense_fold=mode).merge_many(st, [lo, hi])
    assert st.canonical() == cpu_store.canonical()
    kid = st.lookup(b"d1")
    row = st.el_row(kid, b"m")
    assert st.el_val[row] is None


def test_non_pow2_mesh_engine():
    """State padding must round up to the kv axis size, not just pow2
    (review regression: a 6-device mesh crashed on the first merge)."""
    import jax

    if len(jax.devices()) < 6:
        pytest.skip("needs >= 6 virtual devices")
    from constdb_tpu.parallel import engine_mesh

    src = gen_store(5, node=1)
    st = KeySpace()
    eng = TpuMergeEngine(resident=True, mesh=engine_mesh(6))
    eng.merge(st, batch_from_keyspace(src))
    eng.flush(st)
    cpu_store = KeySpace()
    CpuMergeEngine().merge(cpu_store, batch_from_keyspace(src))
    assert st.canonical() == cpu_store.canonical()
