"""Property/fuzz tests for resp/codec.py: random message trees round-trip
through encode_into → parser (native and pure-Python), partial frames
never advance the cursor, malformed input raises without consuming a
clean prefix, and the drain/pushback/take_queued queue discipline holds.
"""

import random

import pytest

from constdb_tpu.errors import InvalidRequestMsg
from constdb_tpu.resp.codec import (NativeRespParser, RespParser, encode_into,
                                    encode_msg)
from constdb_tpu.resp.message import Arr, Bulk, Err, Int, NIL, Push, Simple

PARSERS = (RespParser, NativeRespParser)  # native degrades to pure w/o ext


def rand_msg(rng: random.Random, depth: int = 0):
    """A random message tree.  Simple/Err payloads exclude CR/LF (the
    encoder is not responsible for escaping line frames — no real reply
    contains them); Bulk payloads are arbitrary binary.  Push frames
    (RESP3, server/tracking.py) only ever appear top-level on a real
    wire, but the parser accepts them at any depth — fuzz both."""
    r = rng.random()
    if depth < 3 and r < 0.25:
        cls = Push if rng.random() < 0.2 else Arr
        return cls([rand_msg(rng, depth + 1)
                    for _ in range(rng.randrange(0, 6))])
    if r < 0.45:
        return Bulk(bytes(rng.randrange(256)
                          for _ in range(rng.randrange(0, 40))))
    if r < 0.65:
        return Int(rng.choice((0, 1, -1, 7, 1023, 1024, -(1 << 40),
                               (1 << 62), rng.randrange(-10**6, 10**6))))
    if r < 0.80:
        return Simple(bytes(rng.choice(b"abcXYZ 09_-") for _ in range(8)))
    if r < 0.92:
        return Err(b"ERR " + bytes(rng.choice(b"abcdef") for _ in range(6)))
    return NIL


@pytest.mark.parametrize("parser_cls", PARSERS)
def test_roundtrip_random_trees(parser_cls):
    rng = random.Random(1234)
    msgs = [rand_msg(rng) for _ in range(400)]
    wire = bytearray()
    for m in msgs:
        encode_into(wire, m)
    # feed in random-sized slices so messages straddle feed boundaries
    parser = parser_cls()
    got = []
    pos = 0
    wire = bytes(wire)
    while pos < len(wire) or len(got) < len(msgs):
        step = rng.randrange(1, 64)
        parser.feed(wire[pos:pos + step])
        pos += step
        while (m := parser.next_msg()) is not None:
            got.append(m)
    assert got == msgs


@pytest.mark.parametrize("parser_cls", PARSERS)
def test_roundtrip_drain(parser_cls):
    rng = random.Random(77)
    msgs = [rand_msg(rng) for _ in range(200)]
    parser = parser_cls()
    parser.feed(b"".join(encode_msg(m) for m in msgs))
    assert parser.drain() == msgs
    assert parser.drain() == []


@pytest.mark.parametrize("parser_cls", PARSERS)
def test_truncated_frames_never_advance_cursor(parser_cls):
    """Every proper prefix of an encoded message parses to None and
    leaves the whole prefix buffered (the cursor stays at the message
    start); feeding the remainder then yields the exact message."""
    rng = random.Random(5)
    samples = [rand_msg(rng) for _ in range(40)]
    # include the shapes with tricky internal framing explicitly
    samples += [Arr([Bulk(b"set"), Bulk(b"k"), Bulk(b"v" * 30)]),
                Arr([Int(7), Arr([Bulk(b"x"), NIL]), Simple(b"OK")]),
                Bulk(b""), Arr([])]
    for m in samples:
        wire = encode_msg(m)
        for cut in range(len(wire)):
            parser = parser_cls()
            parser.feed(wire[:cut])
            assert parser.next_msg() is None, (m, cut)
            assert parser.buffered == cut, (m, cut)
            parser.feed(wire[cut:])
            assert parser.next_msg() == m, (m, cut)
            assert parser.buffered == 0


@pytest.mark.parametrize("parser_cls", PARSERS)
@pytest.mark.parametrize("bad", (
    b"!bogus\r\n",                      # unknown type byte
    b"$-2\r\n",                         # negative non-nil bulk length
    b"*-2\r\n",                         # negative non-nil array length
    b":12x\r\n",                        # non-integer int line
    b"$x\r\n",                          # non-integer bulk length
    b"*1\r\n$3\r\nabcXY",               # bulk missing terminating CRLF
    b"$2000000000000\r\n",              # bulk too large
))
def test_malformed_raises_and_keeps_clean_prefix(parser_cls, bad):
    """Malformed input raises InvalidRequestMsg; a complete message in
    front of the bad frame is still delivered first (next_msg) or
    salvaged into the queue (drain + take_queued) — the cursor never
    skips past or consumes a clean message."""
    good = Arr([Bulk(b"set"), Bulk(b"k"), Bulk(b"v")])
    parser = parser_cls()
    parser.feed(encode_msg(good) + bad)
    assert parser.next_msg() == good
    with pytest.raises(InvalidRequestMsg):
        while parser.next_msg() is not None:
            pass
    # drain path: the clean prefix is stashed for the error path
    parser = parser_cls()
    parser.feed(encode_msg(good) + bad)
    with pytest.raises(InvalidRequestMsg):
        parser.drain()
    assert parser.take_queued() == [good]


@pytest.mark.parametrize("parser_cls", PARSERS)
def test_pushback_order(parser_cls):
    msgs = [Arr([Bulk(b"cmd%d" % i)]) for i in range(6)]
    parser = parser_cls()
    parser.feed(b"".join(encode_msg(m) for m in msgs[:4]))
    drained = parser.drain()
    assert drained == msgs[:4]
    # push the tail back, feed two more: pushed-back messages re-emerge
    # FIRST, then the buffer's
    parser.pushback(drained[2:])
    parser.feed(b"".join(encode_msg(m) for m in msgs[4:]))
    assert parser.drain() == msgs[2:]
    # pushback before a partial message in the buffer
    parser.pushback([msgs[0]])
    half = encode_msg(msgs[1])
    parser.feed(half[:5])
    assert parser.next_msg() == msgs[0]
    assert parser.next_msg() is None
    parser.feed(half[5:])
    assert parser.next_msg() == msgs[1]


@pytest.mark.parametrize("parser_cls", PARSERS)
@pytest.mark.parametrize("header", (
    b"$99999999999\r\n",                # absurd bulk: 93GB declared
    b"$536870913\r\n",                  # one past the 512MB hard ceiling
    b"*1\r\n$99999999999\r\n",          # absurd bulk inside an array
    b"*99999999\r\n",                   # absurd array header
))
def test_absurd_headers_rejected_at_parse_time(parser_cls, header):
    """Overload satellite (CONSTDB_PROTO_MAX_BULK): a malicious declared
    length is a PROTOCOL error the moment the header line parses — the
    parser must never sit buffering toward it (the pre-limit behavior
    would happily accumulate 93GB before erroring)."""
    parser = parser_cls()
    parser.feed(header)
    with pytest.raises(InvalidRequestMsg):
        parser.next_msg()


@pytest.mark.parametrize("parser_cls", PARSERS)
def test_configured_bulk_cap_enforced(parser_cls):
    """A below-default CONSTDB_PROTO_MAX_BULK is enforced at header
    parse time in BOTH parsers (the native scanner takes the cap as an
    argument and defers over-cap headers to the pure parser's raise)."""
    parser = parser_cls(max_bulk=1024)
    ok = Arr([Bulk(b"set"), Bulk(b"k"), Bulk(b"v" * 1024)])
    parser.feed(encode_msg(ok))
    assert parser.next_msg() == ok
    parser = parser_cls(max_bulk=1024)
    parser.feed(b"*3\r\n$3\r\nset\r\n$1\r\nk\r\n$1025\r\n")
    with pytest.raises(InvalidRequestMsg):
        while parser.next_msg() is None:
            pass  # pragma: no cover - raise happens on the first call
    # lone oversized header outside an array: same rejection
    parser = parser_cls(max_bulk=1024)
    parser.feed(b"$2048\r\n")
    with pytest.raises(InvalidRequestMsg):
        parser.next_msg()


@pytest.mark.parametrize("parser_cls", PARSERS)
def test_push_frames_roundtrip(parser_cls):
    """RESP3 push frames (server/tracking.py invalidation shape) round-
    trip in BOTH parsers, compare as their own type (a Push is never
    equal to the Arr with the same items), and survive every-prefix
    truncation without the cursor advancing early."""
    frames = [
        Push([Bulk(b"invalidate"), Arr([Bulk(b"k1"), Bulk(b"k2")])]),
        Push([Bulk(b"invalidate"), NIL]),
        Push([]),
        Push([Bulk(b"invalidate"),
              Arr([Bulk(bytes(range(256)))])]),  # binary key
    ]
    wire = b"".join(encode_msg(f) for f in frames)
    parser = parser_cls()
    parser.feed(wire)
    got = parser.drain()
    assert got == frames
    for g in got:
        assert type(g) is Push
    # Push != Arr with identical items, both directions
    p = Push([Bulk(b"x")])
    a = Arr([Bulk(b"x")])
    assert p != a and a != p
    assert encode_msg(p) == b">1\r\n$1\r\nx\r\n"
    assert encode_msg(a) == b"*1\r\n$1\r\nx\r\n"
    # every-prefix truncation: None + whole prefix buffered, then exact
    for f in frames:
        w = encode_msg(f)
        for cut in range(len(w)):
            parser = parser_cls()
            parser.feed(w[:cut])
            assert parser.next_msg() is None, (f, cut)
            assert parser.buffered == cut, (f, cut)
            parser.feed(w[cut:])
            assert parser.next_msg() == f, (f, cut)


@pytest.mark.parametrize("parser_cls", PARSERS)
@pytest.mark.parametrize("bad", (
    b">-2\r\n",             # negative push length
    b">99999999\r\n",       # absurd push header
    b">x\r\n",              # non-integer push length
))
def test_malformed_push_rejected(parser_cls, bad):
    parser = parser_cls()
    parser.feed(bad)
    with pytest.raises(InvalidRequestMsg):
        while parser.next_msg() is None:
            pass  # pragma: no cover - raise happens on the first call


def test_tracked_vs_untracked_lockstep_differential():
    """The serve-path differential for client tracking: one tracked
    RESP3 connection and one plain RESP2 connection send the IDENTICAL
    command stream to the same node; the tracked stream minus its push
    frames must be byte-identical to the untracked stream (tracking is
    an out-of-band overlay, never a reply rewrite) — and the RESP2
    stream must contain no push bytes at all."""
    import asyncio

    from constdb_tpu.server.io import start_node
    from constdb_tpu.server.node import Node

    rng = random.Random(31337)
    keys = [b"k%d" % i for i in range(8)]
    script: list[list[bytes]] = []
    for _ in range(120):
        k = rng.choice(keys)
        script.append(rng.choice((
            [b"set", k, b"v%d" % rng.randrange(100)],
            [b"get", k], [b"incr", b"c:" + k], [b"get", b"c:" + k],
            [b"hset", b"h:" + k, b"f", b"1"], [b"hlen", b"h:" + k],
            [b"sadd", b"s:" + k, b"m%d" % rng.randrange(4)],
            [b"scnt", b"s:" + k],
        )))

    async def main():
        node = Node(alias="difftest")
        app = await start_node(node, port=0)
        addr = app.advertised_addr

        async def stream(tracked: bool):
            host, port = addr.rsplit(":", 1)
            reader, writer = await asyncio.open_connection(host, int(port))
            parser = RespParser()

            async def roundtrip(parts):
                writer.write(encode_msg(Arr([Bulk(p) for p in parts])))
                await writer.drain()
                while True:
                    m = parser.next_msg()
                    if m is None:
                        data = await reader.read(1 << 16)
                        assert data, "server closed mid-differential"
                        parser.feed(data)
                        continue
                    if isinstance(m, Push):
                        assert tracked, "push frame on a RESP2 stream"
                        continue
                    return m

            if tracked:
                assert not isinstance(await roundtrip([b"hello", b"3"]),
                                      Err)
                assert not isinstance(
                    await roundtrip([b"client", b"tracking", b"on"]), Err)
            replies = [await roundtrip(parts) for parts in script]
            writer.close()
            return replies

        tracked = await stream(True)
        node2 = Node(alias="difftest2")
        app2 = await start_node(node2, port=0)
        addr2 = app2.advertised_addr

        async def stream2():
            host, port = addr2.rsplit(":", 1)
            reader, writer = await asyncio.open_connection(host, int(port))
            parser = RespParser()
            replies = []
            for parts in script:
                writer.write(encode_msg(Arr([Bulk(p) for p in parts])))
                await writer.drain()
                while True:
                    m = parser.next_msg()
                    if m is not None:
                        assert not isinstance(m, Push)
                        replies.append(m)
                        break
                    data = await reader.read(1 << 16)
                    assert data
                    parser.feed(data)
            writer.close()
            return replies

        untracked = await stream2()
        assert len(tracked) == len(untracked) == len(script)
        # non-push portion byte-identical: same message objects AND the
        # same re-encoded bytes
        assert tracked == untracked
        assert b"".join(map(encode_msg, tracked)) == \
            b"".join(map(encode_msg, untracked))
        assert node.stats.tracking_invalidations_sent > 0
        await app.close()
        await app2.close()

    asyncio.run(main())


def test_parsers_agree_on_random_trees():
    """The native parser (when the extension is built) and the pure
    parser produce identical message objects for identical bytes."""
    rng = random.Random(99)
    msgs = [rand_msg(rng) for _ in range(300)]
    wire = b"".join(encode_msg(m) for m in msgs)
    a, b = RespParser(), NativeRespParser()
    a.feed(wire)
    b.feed(wire)
    assert a.drain() == b.drain() == msgs


# ------------------------------------------------- native intake stage

def _intake_available() -> bool:
    p = NativeRespParser()
    p.feed(b"*2\r\n$4\r\nincr\r\n$1\r\nk\r\n")
    return p.native_drain() is not None


def rand_command(rng: random.Random) -> Arr:
    """A random client-shaped command: plannable names (good and broken
    arity), barriers, uppercase demotes, binary keys/values."""
    names = (b"set", b"incr", b"decr", b"sadd", b"srem", b"hset", b"hdel",
             b"get", b"scnt", b"sismember", b"smembers", b"hget",
             b"hgetall", b"llen", b"del", b"SET", b"INCR", b"mvget",
             b"zmystery")
    nm = rng.choice(names)
    n_args = rng.randrange(0, 5)
    items = [Bulk(nm)] + [Bulk(bytes(rng.randrange(256)
                                     for _ in range(rng.randrange(0, 12))))
                          for _ in range(n_args)]
    if rng.random() < 0.1:  # replication-shaped int item: non-flat
        items.append(Int(rng.randrange(-100, 100)))
    return Arr(items)


@pytest.mark.skipif("not _intake_available()",
                    reason="native intake stage not built")
def test_native_intake_differential_random_chunks():
    """The intake differential: for random pipelined chunks fed at
    random byte boundaries, native_drain's opcode/payload plane
    reconstructs the EXACT message sequence the pure parser yields —
    plannable runs, demote cases, and partial frames included."""
    from constdb_tpu.server.serve import _nat_msg
    rng = random.Random(2024)
    msgs = [rand_command(rng) for _ in range(500)]
    wire = b"".join(encode_msg(m) for m in msgs)
    parser = NativeRespParser()
    got = []
    pos = 0
    while pos < len(wire) or len(got) < len(msgs):
        step = rng.randrange(1, 80)
        parser.feed(wire[pos:pos + step])
        pos += step
        while (nat := parser.native_drain()) is not None:
            got.extend(_nat_msg(op, pl) for op, pl in zip(*nat))
        got.extend(parser.drain())
    assert got == msgs


@pytest.mark.skipif("not _intake_available()",
                    reason="native intake stage not built")
def test_native_intake_truncation_cursor():
    """Every-prefix truncation: the scanner's cursor only ever lands on
    message boundaries, and feeding the remainder recovers the exact
    sequence (no byte is consumed twice or skipped)."""
    from constdb_tpu.server.serve import _nat_msg
    msgs = [Arr([Bulk(b"set"), Bulk(b"k"), Bulk(b"v" * 9)]),
            Arr([Bulk(b"incr"), Bulk(b"c")]),
            Arr([Bulk(b"del"), Bulk(b"k")]),
            Arr([Bulk(b"hget"), Bulk(b"h"), Bulk(b"f")])]
    wire = b"".join(encode_msg(m) for m in msgs)
    for cut in range(len(wire) + 1):
        parser = NativeRespParser()
        parser.feed(wire[:cut])
        got = []
        while (nat := parser.native_drain()) is not None:
            got.extend(_nat_msg(op, pl) for op, pl in zip(*nat))
        got.extend(parser.drain())
        assert msgs[:len(got)] == got, cut
        parser.feed(wire[cut:])
        while (nat := parser.native_drain()) is not None:
            got.extend(_nat_msg(op, pl) for op, pl in zip(*nat))
        got.extend(parser.drain())
        assert got == msgs, cut


@pytest.mark.skipif("not _intake_available()",
                    reason="native intake stage not built")
@pytest.mark.parametrize("bad", (
    b"!bogus\r\n",
    b"$-2\r\n",
    b"*1\r\n$3\r\nabcXY",
    b"*2\r\n$4\r\nincr\r\nnope\r\n",
))
def test_native_intake_malformed_salvage(bad):
    """A malformed frame behind a clean plannable run: the scanner
    consumes (and the coalescer would execute) the clean prefix, then
    drain() raises exactly as the pure path does, with nothing left to
    salvage twice — the cursor parks at the bad frame."""
    good = [Arr([Bulk(b"set"), Bulk(b"k"), Bulk(b"v")]),
            Arr([Bulk(b"incr"), Bulk(b"c")])]
    parser = NativeRespParser()
    parser.feed(b"".join(encode_msg(m) for m in good) + bad)
    nat = parser.native_drain()
    assert nat is not None and len(nat[0]) == 2
    with pytest.raises(InvalidRequestMsg):
        parser.drain()
    assert parser.take_queued() == []
    # pure parser on the same full buffer: same clean prefix, same raise
    pure = RespParser()
    pure.feed(b"".join(encode_msg(m) for m in good) + bad)
    with pytest.raises(InvalidRequestMsg):
        pure.drain()
    assert pure.take_queued() == good
