"""Coalesced client serving (server/serve.py + server/io.py).

The load-bearing claims, each pinned here:
  * a coalescing node is byte-identical to a CONSTDB_SERVE_BATCH=1 node —
    a multi-connection pipelined workload (writes, counters, reads, DELs,
    membership ops interleaved deterministically) produces the same reply
    byte stream per connection, the same canonical keyspace export, and
    the same repl_log entry sequence;
  * reads and non-plannable commands are ordered barriers: reply order
    and read-your-writes hold inside one pipelined chunk;
  * a lone command (single-message chunk) takes the exact per-command
    path — no micro-merge, no flush, zero added latency;
  * `ReplLog.push_many` is equivalent to a push loop (entries, sizes,
    eviction, prev_uuid chain, error on non-increasing uuids);
  * a parse error mid-pipeline no longer drops the completed replies
    already encoded for earlier commands (server/io.py CstError path),
    and the error bytes are counted in net_out_bytes;
  * INFO surfaces serve_msgs_coalesced / serve_flushes / serve_barriers
    and the sampled reply-latency percentiles.
"""

import asyncio
import random

import pytest

from constdb_tpu.resp.codec import RespParser, encode_msg
from constdb_tpu.resp.message import Arr, Bulk, Err, Int, Simple
from constdb_tpu.server.io import start_node
from constdb_tpu.server.node import Node
from constdb_tpu.server.repl_log import ReplLog
from constdb_tpu.utils.hlc import SEQ_BITS

from cluster_util import FAST, Client

MS0 = 1_700_000_000_000


def u(i: int) -> int:
    return (MS0 + i) << SEQ_BITS


def stepping_clock():
    """Deterministic HLC clock: advances 1ms per call, so two nodes
    executing the same command sequence mint identical uuid streams —
    the precondition for byte-identical canonical exports."""
    ms = [MS0]

    def clock():
        ms[0] += 1
        return ms[0]
    return clock


def cmd(*parts) -> Arr:
    return Arr([p if isinstance(p, (Bulk, Int)) else
                Bulk(p if isinstance(p, bytes) else str(p).encode())
                for p in parts])


async def read_replies(client, parser_sink: bytearray, n: int) -> list:
    """Read exactly n replies; raw bytes accumulate into parser_sink."""
    out = []
    while len(out) < n:
        m = client.parser.next_msg()
        if m is not None:
            out.append(m)
            continue
        data = await asyncio.wait_for(client.reader.read(1 << 16), 10.0)
        if not data:
            raise ConnectionError("EOF")
        parser_sink += data
        client.parser.feed(data)
    return out


def mixed_workload(n_conns: int, rounds: int, seed: int = 9) -> list:
    """Per-connection chunk lists covering every plannable command plus
    every barrier class (reads, DEL, expiry, lists, admin), with some
    single-command chunks to exercise the lone-command path."""
    rng = random.Random(seed)
    work = [[] for _ in range(n_conns)]
    for _ in range(rounds):
        for ci in range(n_conns):
            chunk = []
            for _ in range(rng.choice((1, 1, 4, 8, 16, 24))):
                r = rng.random()
                k = b"k%02d" % rng.randrange(24)
                if r < 0.20:
                    chunk.append(cmd(b"set", b"r" + k, b"v%d" % rng.getrandbits(24)))
                elif r < 0.38:
                    chunk.append(cmd(b"incr", b"c" + k, rng.randrange(1, 9))
                                 if rng.random() < 0.5 else
                                 cmd(b"decr", b"c" + k))
                elif r < 0.52:
                    chunk.append(cmd(b"sadd", b"s" + k,
                                     b"m%d" % rng.randrange(8),
                                     b"m%d" % rng.randrange(8)))
                elif r < 0.60:
                    chunk.append(cmd(b"hset", b"h" + k,
                                     b"f%d" % rng.randrange(5),
                                     b"v%d" % rng.getrandbits(16)))
                elif r < 0.66:
                    chunk.append(cmd(b"srem", b"s" + k,
                                     b"m%d" % rng.randrange(8)))
                elif r < 0.70:
                    chunk.append(cmd(b"hdel", b"h" + k,
                                     b"f%d" % rng.randrange(5)))
                elif r < 0.76:
                    chunk.append(cmd(b"get", b"r" + k))
                elif r < 0.80:
                    chunk.append(cmd(b"smembers", b"s" + k))
                elif r < 0.84:
                    chunk.append(cmd(b"del", rng.choice(
                        (b"r", b"s", b"c", b"h")) + k))
                elif r < 0.88:
                    chunk.append(cmd(b"lpush", b"l" + k, b"x%d" % rng.getrandbits(16)))
                elif r < 0.90:
                    chunk.append(cmd(b"lrange", b"l" + k, 0, -1))
                elif r < 0.93:
                    # type conflict on purpose: sadd against a register
                    chunk.append(cmd(b"sadd", b"r" + k, b"m"))
                elif r < 0.96:
                    chunk.append(cmd(b"hget", b"h" + k, b"f1"))
                elif r < 0.98:
                    chunk.append(cmd(b"expireat", b"r" + k, u(1 << 20)))
                else:
                    chunk.append(cmd(b"desc", b"r" + k))
            work[ci].append(chunk)
    return work


async def drive_node(tmp_path, serve_batch, work, engine=None):
    """One node + len(work) client connections driven in deterministic
    lockstep (a conn's chunk fully replies before the next conn sends).
    `engine`: a MergeEngine INSTANCE for the node (default CPU reference;
    test_resident_steady.py passes a device-resident one and inspects
    its transfer gauges afterwards).  Returns (reply_bytes_per_conn,
    canonical, repl_entries, stats)."""
    node = Node(node_id=1, alias="n1", clock=stepping_clock(),
                **({"engine": engine} if engine is not None else {}))
    app = await start_node(node, host="127.0.0.1", port=0,
                           work_dir=str(tmp_path), serve_batch=serve_batch,
                           **FAST)
    # the cron's periodic hlc.tick fires on wall-clock timing and would
    # shift the two legs' uuid streams apart — only command execution may
    # tick in this differential
    app._cron_task.cancel()
    conns = [await Client().connect(app.advertised_addr) for _ in work]
    raw = [bytearray() for _ in work]
    try:
        for rnd in range(len(work[0])):
            for ci, c in enumerate(conns):
                chunk = work[ci][rnd]
                c.writer.write(b"".join(encode_msg(m) for m in chunk))
                await c.writer.drain()
                await read_replies(c, raw[ci], len(chunk))
        canonical = node.canonical()
        repl = [(e.uuid, e.prev_uuid, e.name, e.size,
                 tuple((type(a).__name__, a.val) for a in e.args))
                for e in node.repl_log._entries]
        return [bytes(r) for r in raw], canonical, repl, node.stats
    finally:
        for c in conns:
            await c.close()
        await app.close()


def test_multi_connection_differential(tmp_path):
    """The oracle: coalesced vs CONSTDB_SERVE_BATCH=1, same deterministic
    multi-connection workload — byte-identical reply streams, canonical
    export, and repl_log."""
    work = mixed_workload(n_conns=3, rounds=14)

    async def main():
        got = await drive_node(tmp_path / "a", 64, work)
        want = await drive_node(tmp_path / "b", 1, work)
        return got, want

    (g_raw, g_canon, g_repl, g_st), (w_raw, w_canon, w_repl, w_st) = \
        asyncio.run(main())
    for ci, (g, w) in enumerate(zip(g_raw, w_raw)):
        assert g == w, f"conn {ci} reply stream diverged"
    assert g_canon == w_canon
    assert g_repl == w_repl
    # the coalescing leg really coalesced; the pinned leg never did
    assert g_st.serve_msgs_coalesced > 0
    assert 0 < g_st.serve_flushes < g_st.serve_msgs_coalesced
    assert g_st.serve_barriers > 0
    assert w_st.serve_msgs_coalesced == 0 and w_st.serve_flushes == 0
    # same command accounting either way
    assert g_st.cmds_processed == w_st.cmds_processed


def test_reply_order_and_read_your_writes(tmp_path):
    """One pipelined chunk: replies come back strictly in request order
    and a read after a planned write observes it (the read barrier
    flushes the pending run first)."""
    async def main():
        node = Node(node_id=1)
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=str(tmp_path), serve_batch=512,
                               **FAST)
        c = await Client().connect(app.advertised_addr)
        try:
            chunk = [cmd(b"set", b"k", b"v1"), cmd(b"incr", b"n", 3),
                     cmd(b"get", b"k"), cmd(b"set", b"k", b"v2"),
                     cmd(b"get", b"k"), cmd(b"incr", b"n"),
                     cmd(b"sadd", b"s", b"a"), cmd(b"smembers", b"s"),
                     cmd(b"srem", b"s", b"a"), cmd(b"smembers", b"s")]
            c.writer.write(b"".join(encode_msg(m) for m in chunk))
            await c.writer.drain()
            r = await read_replies(c, bytearray(), len(chunk))
            assert r[0] == Simple(b"OK")
            assert r[1] == Int(3)
            assert r[2] == Bulk(b"v1")
            assert r[3] == Simple(b"OK")
            assert r[4] == Bulk(b"v2")
            assert r[5] == Int(4)
            assert r[6] == Int(1)
            assert [m.val for m in r[7].items] == [b"a"]
            assert r[8] == Int(1)
            assert r[9].items == []
            # every read was served by the planned read path (round 18:
            # reads are no longer barriers).  The first get/smembers
            # each observed a pending run and forced a read-your-writes
            # land; the second of each followed an ISOLATED write
            # (executed per-command by choice), so nothing was pending
            # and no flush was needed — still byte-exact
            assert node.stats.serve_reads_coalesced == 4
            assert node.stats.serve_read_flushes == 2
            assert node.stats.serve_barriers == 0
        finally:
            await c.close()
            await app.close()
    asyncio.run(main())


def test_lone_command_takes_per_command_path(tmp_path):
    """A single-message chunk must bypass the planner entirely: no
    flushes, no merges, no coalescing — zero added latency."""
    async def main():
        node = Node(node_id=1)
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=str(tmp_path), serve_batch=512,
                               **FAST)
        c = await Client().connect(app.advertised_addr)
        try:
            assert await c.cmd("set", "k", "v") == Simple(b"OK")
            assert await c.cmd("incr", "n") == Int(1)
            assert await c.cmd("get", "k") == Bulk(b"v")
            st = node.stats
            assert st.serve_flushes == 0
            assert st.serve_msgs_coalesced == 0
            assert st.merges == 0
            # a pipelined chunk on the same connection does coalesce
            chunk = [cmd(b"set", b"a%d" % i, b"v") for i in range(8)]
            c.writer.write(b"".join(encode_msg(m) for m in chunk))
            await c.writer.drain()
            await read_replies(c, bytearray(), len(chunk))
            assert st.serve_msgs_coalesced == 8
            assert st.serve_flushes == 1
        finally:
            await c.close()
            await app.close()
    asyncio.run(main())


def test_isolated_write_between_barriers_stays_per_command(tmp_path):
    """Inside a multi-message chunk, a plannable write with no plannable
    neighbor executes per-command — no one-row micro-merge."""
    async def main():
        node = Node(node_id=1)
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=str(tmp_path), serve_batch=512,
                               **FAST)
        c = await Client().connect(app.advertised_addr)
        try:
            chunk = [cmd(b"get", b"x"), cmd(b"set", b"k", b"v"),
                     cmd(b"get", b"k")]
            c.writer.write(b"".join(encode_msg(m) for m in chunk))
            await c.writer.drain()
            r = await read_replies(c, bytearray(), len(chunk))
            assert r[1] == Simple(b"OK") and r[2] == Bulk(b"v")
            assert node.stats.serve_flushes == 0
            assert node.stats.merges == 0
        finally:
            await c.close()
            await app.close()
    asyncio.run(main())


def test_node_id_change_mid_pipeline(tmp_path):
    """NODE ID mid-chunk rebinds the identity the counter overlays are
    tracked under — the coalescer must drop its caches (a CTRL barrier
    invalidates everything), or post-change INCRs would keep extending
    the OLD node's slot total.  Differential against SERVE_BATCH=1."""
    async def drive(serve_batch):
        node = Node(node_id=1, clock=stepping_clock())
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=str(tmp_path), serve_batch=serve_batch,
                               **FAST)
        app._cron_task.cancel()
        c = await Client().connect(app.advertised_addr)
        try:
            chunk = [cmd(b"incr", b"c"), cmd(b"incr", b"c"),
                     cmd(b"node", b"id", 7),
                     cmd(b"incr", b"c"), cmd(b"incr", b"c"),
                     cmd(b"get", b"c")]
            c.writer.write(b"".join(encode_msg(m) for m in chunk))
            await c.writer.drain()
            replies = await read_replies(c, bytearray(), len(chunk))
            canon = node.canonical()
            return replies, canon
        finally:
            await c.close()
            await app.close()

    async def main():
        return await drive(64), await drive(1)

    (g_rep, g_canon), (w_rep, w_canon) = asyncio.run(main())
    assert g_rep == w_rep
    assert g_canon == w_canon
    assert g_rep[-1] == Int(4)  # both slots visible in the sum


# --------------------------------------------------------------- repl_log


def test_push_many_equals_loop():
    def entries(log):
        return [(e.uuid, e.prev_uuid, e.name, e.size,
                 tuple(a.val for a in e.args))
                for e in log._entries]

    cmds = [(u(i), b"set" if i % 3 else b"cntset",
             [Bulk(b"k%d" % (i % 5)), Bulk(b"v" * (i % 23)) if i % 3
              else Int(i * 7)])
            for i in range(1, 120)]
    # small cap so eviction engages mid-run
    a, b = ReplLog(cap_bytes=700), ReplLog(cap_bytes=700)
    for c in cmds:
        a.push(*c)
    b.push_many(cmds)
    assert entries(a) == entries(b)
    assert a.last_uuid == b.last_uuid
    assert a.evicted_up_to == b.evicted_up_to
    assert a.total_bytes == b.total_bytes
    assert a.uuids() == b.uuids()

    # split calls chain prev_uuid across the boundary like a loop would
    c1, c2 = ReplLog(10_000), ReplLog(10_000)
    for c in cmds[:40]:
        c1.push(*c)
    c2.push_many(cmds[:17])
    c2.push_many(cmds[17:40])
    assert entries(c1) == entries(c2)

    # non-increasing uuids refuse exactly like push
    with pytest.raises(ValueError):
        b.push_many([(b.last_uuid, b"set", [Bulk(b"k")])])
    with pytest.raises(ValueError):
        ReplLog().push_many([(u(2), b"set", [Bulk(b"k")]),
                             (u(2), b"set", [Bulk(b"k")])])
    # empty run is a no-op
    before = entries(b)
    b.push_many([])
    assert entries(b) == before


# ------------------------------------------------------------ error path


@pytest.mark.parametrize("serve_batch", (512, 1))
def test_parse_error_keeps_completed_replies(tmp_path, serve_batch):
    """A malformed frame mid-pipeline: completed commands still execute
    and their replies reach the client BEFORE the protocol error, and
    the error bytes are counted in net_out_bytes."""
    async def main():
        node = Node(node_id=1)
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=str(tmp_path),
                               serve_batch=serve_batch, **FAST)
        reader, writer = await asyncio.open_connection("127.0.0.1", app.port)
        try:
            good = encode_msg(cmd(b"set", b"k", b"v")) + \
                encode_msg(cmd(b"incr", b"n"))
            writer.write(good + b"!bogus\r\n")
            await writer.drain()
            data = b""
            while True:
                chunk = await asyncio.wait_for(reader.read(1 << 16), 5.0)
                if not chunk:
                    break
                data += chunk
            parser = RespParser()
            parser.feed(data)
            replies = parser.drain()
            assert replies[0] == Simple(b"OK"), replies
            assert replies[1] == Int(1)
            assert isinstance(replies[2], Err)
            # the write really landed before the teardown
            kid = node.ks.lookup(b"k")
            assert kid >= 0 and node.ks.register_get(kid) == b"v"
            assert node.stats.net_out_bytes >= len(data)
        finally:
            writer.close()
            await app.close()
    asyncio.run(main())


@pytest.mark.parametrize("serve_batch", (512, 1))
def test_replies_flush_before_sync_upgrade(tmp_path, serve_batch):
    """Commands pipelined BEFORE a SYNC in the same chunk: their replies
    must reach the client before the handshake reply takes the stream
    over (they used to be silently dropped)."""
    async def main():
        node = Node(node_id=1)
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=str(tmp_path),
                               serve_batch=serve_batch, **FAST)
        reader, writer = await asyncio.open_connection("127.0.0.1", app.port)
        try:
            sync = Arr([Bulk(b"sync"), Int(0), Int(99), Bulk(b"nx"),
                        Bulk(b"127.9.9.9:19"), Int(0), Int(0)])
            writer.write(encode_msg(cmd(b"set", b"k", b"v")) +
                         encode_msg(cmd(b"incr", b"n")) +
                         encode_msg(sync))
            await writer.drain()
            parser = RespParser()
            got = []
            while len(got) < 3:
                data = await asyncio.wait_for(reader.read(1 << 16), 10.0)
                assert data, got
                parser.feed(data)
                got.extend(parser.drain())
            assert got[0] == Simple(b"OK")
            assert got[1] == Int(1)
            # then the handshake reply — the connection is a link now
            assert isinstance(got[2], Arr) and got[2].items[0].val == b"sync"
            # the writes really landed and were logged (a third entry is
            # the handshake's replicated MEET introduction)
            assert node.ks.lookup(b"k") >= 0
            assert [e.name for e in node.repl_log._entries][:2] == \
                [b"set", b"cntset"]
        finally:
            writer.close()
            await app.close()
    asyncio.run(main())


# ------------------------------------------------------------ bench smoke


def test_serve_bench_smoke():
    """bench.py --mode serve end-to-end on a tiny workload: JSON line
    present, oracle-verified (reply streams + export projection), and
    the coalescing leg really coalesced."""
    import json
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CONSTDB_BENCH_SERVE_OPS="1600",
               CONSTDB_BENCH_SERVE_CONNS="2",
               CONSTDB_BENCH_SERVE_PIPELINE="64",
               CONSTDB_BENCH_SERVE_KEYS="200",
               CONSTDB_BENCH_SERVE_REPS="1",
               CONSTDB_AUTO_NATIVE="0")
    r = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"), "--mode", "serve"],
        capture_output=True, text=True, timeout=300, env=env, cwd=root)
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["metric"] == "serve_requests_per_sec"
    assert out["verified"] is True
    assert out["ops"] == 1600
    assert out["value"] > 0 and out["per_command_baseline_rps"] > 0
    assert out["serve_msgs_coalesced"] > 0
    assert "reply_p99_ms" in out


# ------------------------------------------------------------------ INFO


def test_info_serve_stats(tmp_path):
    async def main():
        node = Node(node_id=1)
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=str(tmp_path), serve_batch=512,
                               **FAST)
        c = await Client().connect(app.advertised_addr)
        try:
            chunk = [cmd(b"set", b"k%d" % i, b"v") for i in range(40)]
            chunk.append(cmd(b"get", b"k0"))
            c.writer.write(b"".join(encode_msg(m) for m in chunk))
            await c.writer.drain()
            await read_replies(c, bytearray(), len(chunk))
            info = (await c.cmd("info", "stats")).val.decode()
            assert "serve_msgs_coalesced:40" in info
            assert "serve_flushes:1" in info
            assert "serve_barriers:" in info
            assert "serve_lat_p50_ms:" in info
            assert "serve_lat_p99_ms:" in info
        finally:
            await c.close()
            await app.close()
    asyncio.run(main())
