"""Element row-id stability + counter-window boundary behavior.

Row ids: the batched engine stages element ROW INDICES (possibly on a
background thread) and scatters into them at dispatch; `_compact_elements`
is the only operation allowed to re-identify rows.  The contract used to
live in a docstring — now `KeySpace.el_compact_epoch` + an engine-side
guard enforce it, and these tests pin both directions.

Counter windows: PR 1 added the dense-window → sparse-hash fallback
(`cnt_rows_lookup`/`cnt_rows_assign`) without edge-case tests; these sit
exactly on the 64k dense floor and the 1/8-occupancy threshold.
"""

import numpy as np
import pytest

import bench
from constdb_tpu.engine.base import MergeStats
from constdb_tpu.engine.cpu import CpuMergeEngine
from constdb_tpu.engine.tpu import TpuMergeEngine
from constdb_tpu.store.keyspace import KeySpace

_I64 = np.int64


# ------------------------------------------------------- row-id stability


def _store_with_elements(n_keys=300, n_rep=2, seed=17):
    ks = KeySpace()
    cpu = CpuMergeEngine()
    for b in bench.make_workload(n_keys, n_rep, seed=seed):
        cpu.merge(ks, b)
    return ks


def test_compact_bumps_epoch_and_checks_accounting():
    ks = _store_with_elements()
    assert ks.el_compact_epoch == 0
    ks._compact_elements()  # zero dead rows: a pure rebuild
    assert ks.el_compact_epoch == 1
    # corrupt the dead-row census: the stability guard must fail loudly
    ks.el.kid[0] = -1  # a row died without gc() accounting it
    with pytest.raises(RuntimeError, match="row-id stability"):
        ks._compact_elements()


def test_dispatch_rejects_stale_staged_rows():
    """A compaction between the engine's element STAGE and DISPATCH would
    alias every staged row index — the epoch guard refuses to scatter."""
    ks = _store_with_elements()
    batch = bench.make_workload(300, 1, seed=18)[0]
    eng = TpuMergeEngine(resident=False, pipeline=False)
    st = MergeStats()
    eng._unique_ok = True
    eng._n0_keys = ks.keys.n
    kid_of = eng._resolve_keys(ks, batch, st)
    plan = eng._stage_elem_rows(ks, [(batch, kid_of)], st)
    ks._compact_elements()  # the forbidden interleaving
    with pytest.raises(RuntimeError, match="row-id stability"):
        eng._dispatch_elem_rows(ks, plan, st)
    eng.close()


def test_interleaved_garbage_compaction_and_bulk_merge():
    """enqueue_garbage_bulk → gc (kills rows) → compaction → another bulk
    merge: the engine path stays canonically identical to the CPU
    reference doing the exact same sequence, and row ids stay dense."""
    seed_batches = bench.make_workload(400, 2, seed=19)
    more = bench.make_workload(400, 2, seed=20)

    def run(engine_cls):
        ks = KeySpace()
        eng = engine_cls()
        if hasattr(eng, "merge_many"):
            eng.merge_many(ks, seed_batches)
        else:
            for b in seed_batches:
                eng.merge(ks, b)
        if getattr(eng, "needs_flush", False):
            eng.flush(ks)
        # bulk tombstones + a GC sweep past every timestamp, then force a
        # compaction (the organic trigger needs >10k dead rows)
        dead_members = [ks.el_member[r] for r in range(0, ks.el.n, 3)
                        if ks.el_member[r] is not None]
        horizon = int(max(ks.el.add_t.max(), ks.el.del_t.max())) + 10
        ks.enqueue_garbage_bulk(
            [horizon] * 4,
            [ks.key_bytes[0]] * 4,
            [b"absent-%d" % i for i in range(4)])
        ks.gc(horizon)
        ks._compact_elements()
        assert (ks.el.kid[: ks.el.n] >= 0).all()  # rows are dense again
        if hasattr(eng, "merge_many"):
            eng.merge_many(ks, more)
        else:
            for b in more:
                eng.merge(ks, b)
        if getattr(eng, "needs_flush", False):
            eng.flush(ks)
        if hasattr(eng, "close"):
            eng.close()
        return ks

    got = run(lambda: TpuMergeEngine(resident=True))
    want = run(CpuMergeEngine)
    assert got.canonical() == want.canonical()
    assert got.el_compact_epoch == want.el_compact_epoch == 1


# ------------------------------------------- counter window edge behavior


def _fresh_rank(ks, kids):
    rows = ks.cnt.append_block(len(kids), kid=kids, node=7, val=0,
                               uuid=ks.NEUTRAL_T, base=0,
                               base_t=ks.NEUTRAL_T)
    rank = ks.rank_of(7)
    ks.cnt_rows_assign(rank, kids, rows)
    return rank, rows


def test_window_exactly_at_dense_floor_stays_dense():
    """A window whose cap lands EXACTLY on CNT_WINDOW_DENSE_FLOOR (64k)
    stays dense no matter how sparse — the hash fallback only engages
    PAST the floor."""
    ks = KeySpace()
    floor = KeySpace.CNT_WINDOW_DENSE_FLOOR
    kids = np.array([0, floor - 1], dtype=_I64)  # cap == floor, 2 live
    rank, rows = _fresh_rank(ks, kids)
    assert rank in ks.cnt_rank_rows and rank not in ks.cnt_rank_hash
    assert ks.cnt_rows_lookup(rank, kids).tolist() == rows.tolist()


def test_window_one_past_floor_sparse_converts():
    """One kid past the floor at minimal occupancy: the rank converts to
    hash mode instead of allocating a 128k dense window."""
    ks = KeySpace()
    floor = KeySpace.CNT_WINDOW_DENSE_FLOOR
    kids = np.array([0, floor], dtype=_I64)  # cap == 2 * floor, 2 live
    rank, rows = _fresh_rank(ks, kids)
    assert rank in ks.cnt_rank_hash and rank not in ks.cnt_rank_rows
    assert ks.cnt_rows_lookup(rank, kids).tolist() == rows.tolist()


def test_occupancy_exactly_at_threshold_stays_dense():
    """live * MIN_FILL == cap sits ON the boundary and stays dense (the
    conversion rule is strict `<`)."""
    ks = KeySpace()
    cap = 2 * KeySpace.CNT_WINDOW_DENSE_FLOOR  # 128k window
    need = cap // KeySpace.CNT_WINDOW_MIN_FILL  # 16384 live slots
    kids = np.concatenate([np.arange(need - 1, dtype=_I64),
                           np.array([cap - 1], dtype=_I64)])
    rank, rows = _fresh_rank(ks, kids)
    assert rank in ks.cnt_rank_rows and rank not in ks.cnt_rank_hash
    got = ks.cnt_rows_lookup(rank, kids)
    assert got.tolist() == rows.tolist()


def test_occupancy_one_below_threshold_converts():
    """live * MIN_FILL == cap - MIN_FILL (one slot short): converts."""
    ks = KeySpace()
    cap = 2 * KeySpace.CNT_WINDOW_DENSE_FLOOR
    need = cap // KeySpace.CNT_WINDOW_MIN_FILL - 1  # 16383 live slots
    kids = np.concatenate([np.arange(need - 1, dtype=_I64),
                           np.array([cap - 1], dtype=_I64)])
    rank, rows = _fresh_rank(ks, kids)
    assert rank in ks.cnt_rank_hash and rank not in ks.cnt_rank_rows
    got = ks.cnt_rows_lookup(rank, kids)
    assert got.tolist() == rows.tolist()
    # and the op path keeps extending the hash without re-densifying
    r_new = ks._cnt_row(cap // 2, node=7)
    assert ks.cnt_rows_lookup(rank, np.array([cap // 2]))[0] == r_new


def test_lookup_masks_outside_dense_window():
    """Pure lookups never grow the window: kids outside it come back -1,
    in-window kids resolve, and the window geometry is untouched."""
    ks = KeySpace()
    kids = np.arange(2048, 2548, dtype=_I64)
    rank, rows = _fresh_rank(ks, kids)
    base, arr = ks.cnt_rank_rows[rank]
    probe = np.array([0, 2100, 2547, 1_000_000], dtype=_I64)
    got = ks.cnt_rows_lookup(rank, probe)
    assert got[0] == -1 and got[3] == -1
    assert got[1] == rows[2100 - 2048] and got[2] == rows[-1]
    assert ks.cnt_rank_rows[rank][0] == base
    assert len(ks.cnt_rank_rows[rank][1]) == len(arr)
    # empty probe: well-defined empty result
    assert len(ks.cnt_rows_lookup(rank, np.zeros(0, dtype=_I64))) == 0
    # absent rank: all -1
    assert ks.cnt_rows_lookup(999, probe).tolist() == [-1] * 4


def test_window_boundary_merge_matches_cpu():
    """End-to-end at the boundary: a merge whose counter kids straddle
    the dense floor produces identical state through the batched engine
    and the CPU reference."""
    floor = KeySpace.CNT_WINDOW_DENSE_FLOOR
    n_keys = floor + 8  # kids run straight through the floor boundary
    b = bench.make_workload(n_keys, 1, seed=23)[0]

    ks_tpu = KeySpace()
    eng = TpuMergeEngine(resident=True)
    eng.merge_many(ks_tpu, [b])
    eng.flush(ks_tpu)
    eng.close()

    ks_cpu = KeySpace()
    CpuMergeEngine().merge(ks_cpu, b)
    # canonical comparison would walk 64k keys through Python; compare
    # the counter planes directly instead
    n = ks_cpu.cnt.n
    assert ks_tpu.cnt.n == n
    for col in ("kid", "node", "val", "uuid", "base", "base_t"):
        assert np.array_equal(ks_tpu.cnt.col(col)[:n],
                              ks_cpu.cnt.col(col)[:n]), col
    assert np.array_equal(ks_tpu.keys.cnt_sum[: ks_cpu.keys.n],
                          ks_cpu.keys.cnt_sum[: ks_cpu.keys.n])
