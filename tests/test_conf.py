"""Config loading: TOML file + CLI overrides (reference src/conf.rs)."""

from constdb_tpu.conf import Config, load_config


def test_defaults():
    cfg = load_config([])
    assert cfg.port == 9001 and cfg.ip == "127.0.0.1"
    assert cfg.repl_log_cap == 1_024_000  # reference src/server.rs:81
    assert cfg.replica_heartbeat_frequency == 4


def test_toml_and_flag_priority(tmp_path):
    toml = tmp_path / "node.toml"
    toml.write_text(
        'node_id = 7\nport = 7100\nnode_alias = "alpha"\n'
        'work_dir = "/tmp/wd"\nreplica_heartbeat_frequency = 2\n'
        'snapshot_path = "/tmp/db.snapshot"\n')
    cfg = load_config([str(toml)])
    assert cfg.node_id == 7 and cfg.port == 7100 and cfg.node_alias == "alpha"
    assert cfg.replica_heartbeat_frequency == 2
    assert cfg.snapshot_path == "/tmp/db.snapshot"
    # CLI flags override the file
    cfg = load_config([str(toml), "--port", "7200", "--alias", "beta",
                       "--engine", "cpu"])
    assert cfg.port == 7200 and cfg.node_alias == "beta"
    assert cfg.engine == "cpu"
    assert cfg.node_id == 7  # untouched by flags
