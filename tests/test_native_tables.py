"""Native staging tables vs the pure-Python fallback: behavioral equality."""

import os

import numpy as np
import pytest

from constdb_tpu.utils import native_tables as nt


def impls_str():
    yield nt._PyStrTable
    if nt.load_native():
        yield nt._NativeStrTable
    if nt.load_ext():
        yield nt._ExtStrTable


def impls_i64():
    yield nt._PyI64Dict
    if nt.load_native():
        yield nt._NativeI64Dict
    if nt.load_ext():
        yield nt._ExtI64Dict


@pytest.mark.parametrize("cls", list(impls_str()))
def test_strtab_basic(cls):
    t = cls(4)
    assert t.lookup(b"a") == -1
    assert t.get_or_insert(b"a") == 0
    assert t.get_or_insert(b"b") == 1
    assert t.get_or_insert(b"a") == 0
    assert t.lookup(b"b") == 1
    assert len(t) == 2
    assert t.bytes_of(0) == b"a"
    assert t.bytes_of(1) == b"b"


@pytest.mark.parametrize("cls", list(impls_str()))
def test_strtab_batch_and_growth(cls):
    rng = np.random.default_rng(0)
    items = [b"key:%d" % i for i in rng.integers(0, 5000, 20000)]
    t = cls(4)
    ids, n_new = t.get_or_insert_batch(items)
    assert n_new == len(set(items)) == len(t)
    # same item -> same id; ids assigned in first-occurrence order
    seen = {}
    for b, i in zip(items, ids.tolist()):
        assert seen.setdefault(b, i) == i
    assert t.lookup_batch(items).tolist() == ids.tolist()
    assert t.lookup_batch([b"nope"]).tolist() == [-1]
    # empty string is a valid key
    assert t.get_or_insert(b"") == len(seen)


@pytest.mark.parametrize("cls", list(impls_i64()))
def test_i64_basic(cls):
    t = cls(4)
    assert t.get(7) == -1
    t.put(7, 70)
    t.put(-3, 30)
    assert t.get(7) == 70
    assert t.get(-3) == 30
    assert len(t) == 2
    assert t.delete(7) == 70
    assert t.get(7) == -1
    assert len(t) == 1
    t.put(7, 71)  # reinsert over tombstone
    assert t.get(7) == 71


@pytest.mark.parametrize("cls", list(impls_i64()))
def test_i64_batch(cls):
    rng = np.random.default_rng(1)
    keys = rng.integers(-10**12, 10**12, 30000)
    t = cls(4)
    vals, n_new = t.get_or_assign_batch(keys, next_val=100)
    uniq = len(np.unique(keys))
    assert n_new == uniq == len(t)
    # stable mapping
    vals2, n_new2 = t.get_or_assign_batch(keys, next_val=100 + n_new)
    assert n_new2 == 0
    assert np.array_equal(vals, vals2)
    assert np.array_equal(t.lookup_batch(keys), vals)
    # deletes then reinserts keep other keys intact
    for k in keys[:100].tolist():
        t.delete(k)
    got = t.lookup_batch(keys[:100])
    uniq_first = set(keys[:100].tolist())
    later = keys[100:]
    still = np.isin(keys[:100], later)
    assert all((g != -1) == bool(s) for g, s in zip(got.tolist(), still))


@pytest.mark.skipif(
    not os.environ.get("CONSTDB_REQUIRE_NATIVE")
    and (nt.load_native() is None or nt.load_ext() is None),
    reason="native .so not built (run `make -C native`); the pure-Python "
           "tier is the supported fallback on a fresh checkout")
def test_native_available():
    """The built .so files should be present once `make -C native` ran.
    Set CONSTDB_REQUIRE_NATIVE=1 (CI after the build step) to make absence
    a hard failure instead of a skip."""
    assert nt.load_native() is not None
    assert nt.load_ext() is not None


def test_nonnull_mask_tiers_agree_and_are_writable():
    """nonnull_mask: native and pure tiers must return the same mask with
    the same mutability contract (the ext path once returned a read-only
    view — in-place callers would pass pure-tier tests then crash in
    production)."""
    import numpy as np

    from constdb_tpu.utils.native_tables import load_ext, nonnull_mask

    items = [None, b"", b"x", None, b"yy"] * 7 + [None]
    got = nonnull_mask(items)
    want = np.fromiter((v is not None for v in items), dtype=bool,
                       count=len(items))
    np.testing.assert_array_equal(got, want)
    assert got.flags.writeable
    got[0] = True  # must not raise on either tier
    if load_ext() is None:
        import pytest
        pytest.skip("native .so not built; pure tier verified")
