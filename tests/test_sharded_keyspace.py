"""Hash-sharded keyspace (store/sharded_keyspace.py, parallel/host_pool.py,
engine/tpu.py ShardDispatcher).

The differential contract this pins:
  * CONSTDB_SHARDS=1 IS today's single-keyspace path — byte-identical
    store state, by construction and by test;
  * N>1 produces per-shard stores byte-identical to running the same
    engine over the same hash-split sub-batches, and the UNION of the
    shards is canonically identical to the unsplit single-path merge on a
    randomized multi-family workload (counters + registers + sets with
    tombstones and key-level deletes).
"""

import numpy as np
import pytest

import bench
from constdb_tpu.engine.cpu import CpuMergeEngine
from constdb_tpu.engine.tpu import ShardDispatcher, TpuMergeEngine
from constdb_tpu.store.keyspace import KeySpace
from constdb_tpu.store.sharded_keyspace import (MAX_SHARDS, ShardedKeySpace,
                                                default_shards,
                                                extract_shard,
                                                keyspace_state_bytes,
                                                shard_ids, shard_of)

_I64 = np.int64


def _workload(n_keys=420, n_rep=3, chunk=120, seed=13):
    """Randomized multi-family chunk stream + key-level delete tombstones
    (make_workload alone never exercises del_keys)."""
    batches = bench.make_workload(n_keys, n_rep, seed=seed)
    chunks = bench.chunk_batches(batches, chunk)
    dels = [b"k%010d" % i for i in range(0, n_keys, 37)]
    c0 = chunks[0]
    c0.del_keys = dels
    c0.del_t = np.arange(1, len(dels) + 1, dtype=_I64) + (1 << 30)
    return chunks


def _split(chunks, n_shards):
    """Parent-side reference split — the same function the workers run."""
    out = [[] for _ in range(n_shards)]
    for c in chunks:
        sids = shard_ids(c.keys, n_shards)
        dsids = shard_ids(c.del_keys, n_shards) if c.del_keys else None
        for s in range(n_shards):
            sub = extract_shard(c, sids, dsids, s)
            if sub.n_rows or sub.del_keys:
                out[s].append(sub)
    return out


def _cpu_reference(chunks):
    ks = KeySpace()
    cpu = CpuMergeEngine()
    for c in chunks:
        cpu.merge(ks, c)
    return ks


# ------------------------------------------------------------------ split


def test_shard_hash_deterministic_and_bounded():
    keys = [b"k%06d" % i for i in range(500)] + [b"", b"\xff" * 40]
    sids = shard_ids(keys, 5)
    assert sids.dtype == np.uint8
    assert int(sids.max()) < 5
    for i, k in enumerate(keys):
        assert sids[i] == shard_of(k, 5)
    # every shard gets a reasonable share (crc32 spreads)
    counts = np.bincount(sids, minlength=5)
    assert (counts > 0).all()


def test_extract_shard_covers_and_remaps():
    chunks = _workload(n_keys=300, n_rep=2, chunk=300)  # one chunk/replica
    c = chunks[0]
    n = 3
    sids = shard_ids(c.keys, n)
    dsids = shard_ids(c.del_keys, n)
    subs = [extract_shard(c, sids, dsids, s) for s in range(n)]
    assert sum(s.n_keys for s in subs) == c.n_keys
    assert sum(len(s.cnt_ki) for s in subs) == len(c.cnt_ki)
    assert sum(len(s.el_ki) for s in subs) == len(c.el_ki)
    assert sum(len(s.del_keys) for s in subs) == len(c.del_keys)
    for s, sub in enumerate(subs):
        assert all(shard_of(k, n) == s for k in sub.keys)
        assert all(shard_of(k, n) == s for k in sub.del_keys)
        # counter/element rows re-point at shard-local key positions
        kid = np.asarray(sub.cnt_ki)
        assert (kid >= 0).all() and (kid < sub.n_keys).all()
        ekid = np.asarray(sub.el_ki)
        assert (ekid >= 0).all() and (ekid < sub.n_keys).all()
        # spot-check a few element rows carry the right member bytes
        for j in range(0, len(ekid), max(1, len(ekid) // 7)):
            orig = np.nonzero(sids[np.asarray(c.el_ki)] == s)[0][j]
            assert sub.el_member[j] == c.el_member[orig]
            assert sub.el_add_t[j] == c.el_add_t[orig]


def test_extract_requires_del_sids():
    chunks = _workload(n_keys=100, n_rep=1, chunk=100)
    c = chunks[0]
    with pytest.raises(ValueError, match="del_keys"):
        extract_shard(c, shard_ids(c.keys, 2), None, 0)


def test_default_shards(monkeypatch):
    monkeypatch.setenv("CONSTDB_SHARDS", "3")
    assert default_shards() == 3
    monkeypatch.setenv("CONSTDB_SHARDS", "9999")
    assert default_shards() == MAX_SHARDS
    monkeypatch.delenv("CONSTDB_SHARDS")
    monkeypatch.setattr("os.cpu_count", lambda: 2)
    assert default_shards() == 1  # <= 2 cores: today's exact path
    monkeypatch.setattr("os.cpu_count", lambda: 8)
    assert default_shards() == 8


# ----------------------------------------------- degenerate single shard


def test_shards1_byte_identical_to_plain_engine():
    """The n_shards=1 facade IS the single-keyspace path: byte-identical
    store state for the same group cadence."""
    chunks = _workload()
    group = 4
    sks = ShardedKeySpace(n_shards=1, engine_spec="tpu", group=group)
    for c in chunks:
        sks.submit(c)
    sks.flush()

    eng = TpuMergeEngine(resident=True)
    ref = KeySpace()
    for i in range(0, len(chunks), group):
        eng.merge_many(ref, chunks[i:i + group])
    eng.flush(ref)

    got = sks.state_bytes_per_shard()
    assert len(got) == 1
    assert got[0] == keyspace_state_bytes(ref)
    sks.close()
    eng.close()


# -------------------------------------------------- local (in-process) N>1


def test_sharded_local_byte_identical_and_union_matches():
    """N=3 in-process shards (ShardDispatcher, real TPU-path engines):
    every shard's store is byte-identical to the same engine run over the
    same split sub-batches, and the union equals the unsplit single-path
    merge canonically."""
    chunks = _workload()
    n, group = 3, 4
    sks = ShardedKeySpace(n_shards=n, mode="local", group=group)
    for c in chunks:
        sks.submit(c)
    sks.flush()

    # per-shard byte-level reference: same engine, same split, same cadence
    split = [[] for _ in range(n)]
    for i in range(0, len(chunks), group):
        for s, subs in enumerate(_split(chunks[i:i + group], n)):
            split[s].append(subs)
    for s in range(n):
        ref = KeySpace()
        eng = TpuMergeEngine(resident=True)
        for subs in split[s]:
            if subs:
                eng.merge_many(ref, subs)
        eng.flush(ref)
        assert keyspace_state_bytes(sks.stores[s]) == \
            keyspace_state_bytes(ref), f"shard {s} diverged"
        eng.close()

    # union vs the unsplit single path
    single = KeySpace()
    eng = TpuMergeEngine(resident=True)
    for i in range(0, len(chunks), group):
        eng.merge_many(single, chunks[i:i + group])
    eng.flush(single)
    assert sks.canonical() == single.canonical()
    eng.close()
    sks.close()


# ------------------------------------------------- process-parallel N>1


def test_sharded_process_cpu_byte_identical():
    """N=2 worker processes (shared-memory transport, CPU engines): each
    worker's store is byte-identical to the reference engine over the
    same split, and the union matches the unsplit reference."""
    chunks = _workload()
    n = 2
    sks = ShardedKeySpace(n_shards=n, mode="process", engine_spec="cpu",
                          group=4)
    for c in chunks:
        sks.submit(c)
    sks.flush()
    got = sks.state_bytes_per_shard()

    split = _split(chunks, n)
    for s in range(n):
        ref = KeySpace()
        cpu = CpuMergeEngine()
        for sub in split[s]:
            cpu.merge(ref, sub)
        assert got[s] == keyspace_state_bytes(ref), f"shard {s} diverged"

    assert sks.canonical() == _cpu_reference(chunks).canonical()
    # the facade routes key subsets by hash too
    some = [b"k%010d" % i for i in range(0, 420, 11)]
    want = {k: v for k, v in _cpu_reference(chunks).canonical().items()
            if k in set(some)}
    assert sks.canonical(keys=some) == want
    sks.close()


@pytest.mark.slow
def test_sharded_process_tpu_byte_identical():
    """The acceptance differential at full fidelity: N=2 worker processes
    each running the resident TPU-path engine — byte-identical to the
    single-shard engine over the same split, union canonically equal to
    the unsplit single path.  (slow: each worker initializes its own JAX
    runtime.)"""
    chunks = _workload()
    n, group = 2, 4
    sks = ShardedKeySpace(n_shards=n, mode="process", engine_spec="tpu",
                          group=group, env={"XLA_FLAGS": ""})
    for c in chunks:
        sks.submit(c)
    sks.flush()
    got = sks.state_bytes_per_shard()

    split = [[] for _ in range(n)]
    for i in range(0, len(chunks), group):
        for s, subs in enumerate(_split(chunks[i:i + group], n)):
            split[s].append(subs)
    for s in range(n):
        ref = KeySpace()
        eng = TpuMergeEngine(resident=True)
        for subs in split[s]:
            if subs:
                eng.merge_many(ref, subs)
        eng.flush(ref)
        assert got[s] == keyspace_state_bytes(ref), f"shard {s} diverged"
        eng.close()

    single = KeySpace()
    eng = TpuMergeEngine(resident=True)
    for i in range(0, len(chunks), group):
        eng.merge_many(single, chunks[i:i + group])
    eng.flush(single)
    assert sks.canonical() == single.canonical()
    eng.close()
    sks.close()


def test_consolidate_into_single_keyspace():
    """Shard exports merge back into one serving keyspace (the replica
    catch-up consolidation step) with nothing lost — tombstones
    included."""
    chunks = _workload()
    sks = ShardedKeySpace(n_shards=2, mode="process", engine_spec="cpu",
                          group=4)
    for c in chunks:
        sks.submit(c)
    sks.flush()
    target = KeySpace()
    sks.consolidate_into(target, CpuMergeEngine())
    ref = _cpu_reference(chunks)
    assert target.canonical() == ref.canonical()
    assert target.key_deletes == ref.key_deletes
    sks.close()


def test_load_snapshot_into_sharded_store(tmp_path):
    """load_snapshot fans raw chunk payloads into a sharded store (the
    workers decode AND hash in parallel — the submit_raw path)."""
    from constdb_tpu.persist.snapshot import NodeMeta, dump_keyspace, \
        load_snapshot
    from test_merge_properties import gen_store

    src = gen_store(seed=31, node=5)
    path = str(tmp_path / "src.snapshot")
    dump_keyspace(path, src, NodeMeta(node_id=5), chunk_keys=64)
    sks = ShardedKeySpace(n_shards=2, mode="process", engine_spec="cpu",
                          group=3)
    meta, _records = load_snapshot(path, sks)
    assert meta.node_id == 5
    assert sks.canonical() == src.canonical()
    sks.close()


def test_pool_worker_error_propagates():
    """A worker failure surfaces as a parent-side RuntimeError with the
    worker traceback, not a hang."""
    from constdb_tpu.parallel.host_pool import HostShardPool

    pool = HostShardPool(1, engine_spec="cpu")
    try:
        with pytest.raises(RuntimeError, match="shard worker 0"):
            pool.submit_group([], [(b"garbage-not-a-batch",
                                    None, None, None, -1, -1)])
            pool.barrier()
    finally:
        pool.close()
