"""Shard-per-core serving (server/serve_shards.py + parallel/serve_pool.py
+ repl_log.MergedReplLog).

The load-bearing claims, each pinned here:
  * a multi-shard node is byte-identical to the single-loop path — same
    deterministic multi-connection pipelined workload under the fixed-HLC
    hook produces the same reply byte stream per connection, the same
    canonical export, and the same repl-log entry sequence once the
    per-shard segments merge-sort by uuid;
  * shards=1 never constructs the plane: the node runs the exact PR 5
    single-loop objects (no MergedReplLog, no workers);
  * cross-shard commands are ordered barriers: they quiesce the chunk's
    outstanding routed sub-chunks first, so REPLLOG/INFO observe every
    preceding write and replies stay strictly in request order;
  * MEET/SYNC work on a sharded node in BOTH directions with an
    unmodified peer — full sync served from worker exports, steady-state
    frames routed to the owning worker, watermarks/beacons unchanged;
  * MergedReplLog's merge-sort is exact: sorted-union emission, floor
    gating (nothing at/above the smallest in-flight write uuid),
    pending_high keeping last_uuid over un-landed writes, and eviction
    horizon = max over segments.
"""

import asyncio
import random

import pytest

from constdb_tpu.resp.codec import encode_msg
from constdb_tpu.resp.message import Arr, Bulk, Int
from constdb_tpu.server.io import start_node
from constdb_tpu.server.node import Node
from constdb_tpu.server.repl_log import MergedReplLog, ReplLog

from cluster_util import FAST, Client
from test_serve_coalesce import (mixed_workload, read_replies,
                                 stepping_clock)


def cmd(*parts) -> Arr:
    return Arr([p if isinstance(p, (Bulk, Int)) else
                Bulk(p if isinstance(p, bytes) else str(p).encode())
                for p in parts])


async def canon_of(node):
    if node.serve_plane is not None:
        return await node.serve_plane.canonical()
    return node.canonical()


def log_entries(node):
    """(uuid, name, size, args) sequence — merged logs sort their
    segments by uuid (per-segment prev_uuid chains differ from the
    single log's by design, so prev is not compared)."""
    log = node.repl_log
    if isinstance(log, MergedReplLog):
        ents = sorted((e for s in log.segments for e in s._entries),
                      key=lambda e: e.uuid)
    else:
        ents = list(log._entries)
    return [(e.uuid, e.name, e.size,
             tuple((type(a).__name__, a.val) for a in e.args))
            for e in ents]


async def drive_node(tmp_path, serve_shards, work, serve_batch=64):
    """One node + len(work) client connections in deterministic
    lockstep (mirrors test_serve_coalesce.drive_node, with the shard
    plane in the loop when serve_shards > 1)."""
    node = Node(node_id=1, alias="n1", clock=stepping_clock())
    app = await start_node(node, host="127.0.0.1", port=0,
                           work_dir=str(tmp_path), serve_batch=serve_batch,
                           serve_shards=serve_shards, **FAST)
    # the cron's wall-clock hlc ticks would shift the legs' uuid streams
    app._cron_task.cancel()
    conns = [await Client().connect(app.advertised_addr) for _ in work]
    raw = [bytearray() for _ in work]
    try:
        for rnd in range(len(work[0])):
            for ci, c in enumerate(conns):
                chunk = work[ci][rnd]
                c.writer.write(b"".join(encode_msg(m) for m in chunk))
                await c.writer.drain()
                await read_replies(c, raw[ci], len(chunk))
        canonical = await canon_of(node)
        return [bytes(r) for r in raw], canonical, log_entries(node), node
    finally:
        for c in conns:
            await c.close()
        await app.close()


# ----------------------------------------------------------- differential

def test_multishards_differential(tmp_path):
    """The oracle: serve_shards=2 vs the single-loop path, same
    deterministic multi-connection workload — byte-identical reply
    streams, canonical export, and (merge-sorted) repl log."""
    # compact enough to clear the 5s marker-audit budget on the slow
    # builder box — worker spawn is most of it, and by this point in a
    # full tier-1 run the forkserver is warm from the earlier pool
    # suites; the wide slow-marked variant below is the thorough corpus
    work = mixed_workload(n_conns=2, rounds=8)

    async def main():
        g = await drive_node(tmp_path / "a", 2, work)
        w = await drive_node(tmp_path / "b", 1, work)
        return g, w

    (g_raw, g_canon, g_repl, g_node), (w_raw, w_canon, w_repl, w_node) = \
        asyncio.run(main())
    for ci, (g, w) in enumerate(zip(g_raw, w_raw)):
        assert g == w, f"conn {ci} reply stream diverged"
    assert g_canon == w_canon
    assert g_repl == w_repl
    # the sharded leg really ran through the plane
    x = g_node.stats.extra
    assert x.get("serve_shards") == 2
    assert x.get("serve_shard0_msgs", 0) + x.get("serve_shard1_msgs", 0) > 0
    assert g_node.serve_plane is not None
    assert w_node.serve_plane is None


@pytest.mark.slow
def test_multishards_differential_wide(tmp_path):
    """The bigger sweep: 3 shards, more rounds — the corpus where key
    collisions across shards and every barrier class actually occur."""
    work = mixed_workload(n_conns=4, rounds=16, seed=23)

    async def main():
        g = await drive_node(tmp_path / "a", 3, work)
        w = await drive_node(tmp_path / "b", 1, work)
        return g, w

    (g_raw, g_canon, g_repl, _), (w_raw, w_canon, w_repl, _) = \
        asyncio.run(main())
    assert g_raw == w_raw
    assert g_canon == w_canon
    assert g_repl == w_repl


def test_shards1_is_exact_single_loop_path(tmp_path):
    """serve_shards=1 (and the default) never constructs the plane: the
    node keeps the exact PR 5 objects."""
    async def main():
        node = Node(node_id=1)
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=str(tmp_path), serve_shards=1,
                               **FAST)
        try:
            assert app.serve_plane is None
            assert node.serve_plane is None
            assert type(node.repl_log) is ReplLog
        finally:
            await app.close()
    asyncio.run(main())


# ------------------------------------------------------ barrier ordering

def test_cross_shard_barrier_ordering(tmp_path):
    """One pipelined chunk spanning shards + admin barriers: replies in
    strict request order, and the barrier observes every preceding
    routed write (REPLLOG UUIDS sees all of them — quiesce-first)."""
    async def main():
        node = Node(node_id=1)
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=str(tmp_path), serve_shards=2,
                               **FAST)
        c = await Client().connect(app.advertised_addr)
        try:
            # keys spread over both shards (many distinct keys)
            writes = [cmd(b"set", b"k%02d" % i, b"v%d" % i)
                      for i in range(12)]
            chunk = writes + [cmd(b"repllog", b"uuids")] + \
                [cmd(b"get", b"k%02d" % i) for i in range(12)]
            c.writer.write(b"".join(encode_msg(m) for m in chunk))
            await c.writer.drain()
            raw = bytearray()
            replies = await read_replies(c, raw, len(chunk))
            # 12 OKs, then the uuid list covering ALL 12 writes, then
            # the 12 values in order
            assert all(r.val == b"OK" for r in replies[:12])
            assert len(replies[12].items) == 12
            for i, r in enumerate(replies[13:]):
                assert r.val == b"v%d" % i
            x = node.stats.extra
            assert x.get("serve_xshard_barriers", 0) >= 1
            # both shards actually served traffic
            assert x.get("serve_shard0_keys", 0) > 0
            assert x.get("serve_shard1_keys", 0) > 0
        finally:
            await c.close()
            await app.close()
    asyncio.run(main())


def test_node_id_barrier_reaches_workers(tmp_path):
    """NODE ID is a CTRL barrier: workers must stamp the NEW identity
    into subsequent writes (the plane resyncs ident after CTRL)."""
    async def main():
        node = Node(node_id=1)
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=str(tmp_path), serve_shards=2,
                               **FAST)
        c = await Client().connect(app.advertised_addr)
        try:
            await c.cmd(b"set", b"a", b"1")
            r = await c.cmd(b"node", b"id", b"42")
            assert r.val == b"OK"
            await c.cmd(b"set", b"b", b"2")
            canon = await canon_of(node)
            # register rows carry the writer node id
            (_enc, _ct, _mt, _dt, _exp, content) = canon[b"b"]
            assert content[2] == 42, content
        finally:
            await c.close()
            await app.close()
    asyncio.run(main())


# --------------------------------------------------- replication (2-node)

@pytest.mark.slow
def test_meet_sync_sharded_node_both_directions(tmp_path):
    """A sharded node and an UNMODIFIED single-loop peer: full sync
    served from worker exports, steady-state streams in both directions
    routed per key, watermarks advancing — the wire-compatibility
    claim."""
    async def main():
        na = Node(node_id=1, alias="a")
        nb = Node(node_id=2, alias="b")
        appa = await start_node(na, host="127.0.0.1", port=0,
                                work_dir=str(tmp_path / "a"),
                                serve_shards=2, **FAST)
        appb = await start_node(nb, host="127.0.0.1", port=0,
                                work_dir=str(tmp_path / "b"), **FAST)
        ca = await Client().connect(appa.advertised_addr)
        cb = await Client().connect(appb.advertised_addr)
        try:
            # pre-meet writes on the SHARDED node → B needs a full sync
            for i in range(30):
                await ca.cmd(b"set", b"ka%d" % i, b"va%d" % i)
                await ca.cmd(b"incr", b"cnt%d" % (i % 5), b"%d" % (i + 1))
                await ca.cmd(b"sadd", b"sa%d" % (i % 3), b"m%d" % i)
            await ca.cmd(b"meet", appb.advertised_addr)
            await wait_converged([na, nb])
            # steady-state INTO the sharded node (apply routing)
            for i in range(20):
                await cb.cmd(b"set", b"kb%d" % i, b"vb%d" % i)
                await cb.cmd(b"hset", b"hb%d" % (i % 4),
                             b"f%d" % i, b"v%d" % i)
            await cb.cmd(b"del", b"ka0")
            await wait_converged([na, nb])
            # steady-state OUT of the sharded node (merged peer stream)
            for i in range(20):
                await ca.cmd(b"sadd", b"out", b"m%d" % i)
            final = await wait_converged([na, nb])
            assert b"kb3" in final and b"out" in final
            assert b"ka0" not in final or final[b"ka0"][1] < final[b"ka0"][3]
            ma = na.replicas.get(appb.advertised_addr)
            mb = nb.replicas.get(appa.advertised_addr)
            assert ma.uuid_i_acked > 0          # B acked A's stream
            assert mb.uuid_he_sent > 0          # B's pull watermark moved
            assert nb.stats.cmds_replicated > 0
        finally:
            await ca.close()
            await cb.close()
            await appa.close()
            await appb.close()
    asyncio.run(main())


async def wait_converged(nodes, timeout=20.0):
    loop = asyncio.get_running_loop()
    t0 = loop.time()
    while True:
        cc = [await canon_of(n) for n in nodes]
        if cc[0] and all(c == cc[0] for c in cc):
            return cc[0]
        if loop.time() - t0 > timeout:
            raise AssertionError(
                "no convergence: " +
                "; ".join(str(sorted(c.keys()))[:200] for c in cc))
        await asyncio.sleep(0.1)


@pytest.mark.slow
def test_boot_snapshot_restores_into_shards(tmp_path):
    """A snapshot dumped by a plain node boots a SHARDED node: state
    fans out to the workers, watermark fences set."""
    from constdb_tpu.persist.snapshot import NodeMeta, dump_keyspace

    async def main():
        plain = Node(node_id=9, alias="p")
        for i in range(50):
            plain.execute(cmd(b"set", b"k%d" % i, b"v%d" % i))
            plain.execute(cmd(b"sadd", b"s%d" % (i % 7), b"m%d" % i))
        path = str(tmp_path / "boot.snapshot")
        dump_keyspace(path, plain.ks,
                      NodeMeta(node_id=9, alias="p", addr="",
                               repl_last_uuid=plain.repl_log.last_uuid))
        node = Node()
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=str(tmp_path), serve_shards=2,
                               snapshot_path=path, **FAST)
        try:
            assert node.node_id == 9  # identity pre-scanned from meta
            got = await canon_of(node)
            assert got == plain.canonical()
            assert node.repl_log.evicted_up_to == plain.repl_log.last_uuid
        finally:
            await app.close()
    asyncio.run(main())


# ------------------------------------------------- merged-log property

def _entry(log, uuid):
    log.push(uuid, b"set", [Bulk(b"k%d" % uuid), Bulk(b"v")])


def test_merged_repl_log_merge_sort_property():
    """Random entries scattered over segments: emission via next_after
    is exactly the sorted union, strictly increasing, and floor-gated."""
    rng = random.Random(7)
    for _trial in range(20):
        n_seg = rng.randrange(1, 5)
        merged = MergedReplLog(n_seg)
        uuids = sorted(rng.sample(range(1, 10_000), rng.randrange(0, 60)))
        owner = [rng.randrange(n_seg + 1) for _ in uuids]  # + parent seg
        for u, s in zip(uuids, owner):
            merged.segments[s].push(u, b"set", [Bulk(b"k"), Bulk(b"v")])
        # no floor: full sorted union
        got, cur = [], 0
        while (e := merged.next_after(cur)) is not None:
            got.append(e.uuid)
            cur = e.uuid
        assert got == uuids
        assert merged.last_uuid == (uuids[-1] if uuids else 0)
        assert len(merged) == len(uuids)
        # floor gate: nothing at/above the floor is emitted
        if uuids:
            floor = rng.choice(uuids)
            merged.floor = lambda f=floor: f
            got, cur = [], 0
            while (e := merged.next_after(cur)) is not None:
                got.append(e.uuid)
                cur = e.uuid
            assert got == [u for u in uuids if u < floor]
            merged.floor = lambda: None


def test_merged_repl_log_pending_high_and_eviction():
    merged = MergedReplLog(2, cap_bytes=1 << 20)
    _entry(merged.segments[0], 10)
    _entry(merged.segments[1], 20)
    assert merged.last_uuid == 20
    merged.pending_high = lambda: 50  # minted write still in flight
    assert merged.last_uuid == 50     # stream must NOT look drained
    merged.pending_high = lambda: 0
    # eviction horizon is the max across segments: a resume below ANY
    # segment's eviction point is gappy in the merged stream
    merged.segments[0].evicted_up_to = 15
    assert merged.evicted_up_to == 15
    assert not merged.can_resume_from(12)
    assert merged.can_resume_from(15)
    # fences (boot-restore / reset) fold into the maxes
    merged.evicted_up_to = 99
    merged.last_uuid = 99
    assert merged.evicted_up_to == 99 and merged.last_uuid == 99
    # at() finds entries across segments
    assert merged.at(20).uuid == 20
    assert merged.at(11) is None
    assert merged.uuids() == [10, 20]


def test_merged_repl_log_push_goes_to_local_segment():
    merged = MergedReplLog(2)
    merged.push(7, b"meet", [Bulk(b"1.2.3.4:5")])
    assert len(merged.local) == 1
    assert merged.next_after(0).uuid == 7
