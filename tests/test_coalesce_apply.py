"""Coalesced steady-state replication apply (replica/coalesce.py).

The load-bearing claims, each pinned here:
  * coalesced apply is byte-identical to the per-frame path — unit-level
    differential over every encodable command (both engines), and a live
    2-node-mesh export-compare where one subscriber coalesces and the
    other runs CONSTDB_APPLY_BATCH=1 under a mixed write/DEL/membership
    stream;
  * barrier frames flush correctly (key-scoped ones only when their key
    is pending);
  * the pull watermark / REPLACK beacon NEVER advances past an unlanded
    batch (watermark-after-land, docs/INVARIANTS.md);
  * the latency bound flushes a lone frame (and the pull loop's idle
    check lands it with zero added latency in the live mesh);
  * CONSTDB_APPLY_BATCH=1 degenerates to the exact per-frame path;
  * bench.py --mode stream smoke (CPU engine, small log).
"""

import asyncio
import os
import random

import pytest

from constdb_tpu.errors import ReplicateCommandsLost
from constdb_tpu.replica.coalesce import CoalescingApplier
from constdb_tpu.replica.manager import ReplicaMeta
from constdb_tpu.resp.message import Bulk, Int
from constdb_tpu.server.node import Node
from constdb_tpu.utils.hlc import SEQ_BITS

from cluster_util import Client, close_cluster, converge, full_mesh

MS0 = 1_700_000_000_000


def u(i: int) -> int:
    return (MS0 + i) << SEQ_BITS


def frame(prev: int, uuid: int, name: bytes, *args):
    items = [Bulk(b"replicate"), Int(7), Int(prev), Int(uuid), Bulk(name)]
    for a in args:
        items.append(Int(a) if isinstance(a, int) else Bulk(a))
    return items


def mixed_stream(n: int, seed: int = 3, keys: int = 80):
    """A deterministic mixed frame log covering every encodable command
    plus every barrier class."""
    rng = random.Random(seed)
    frames = []
    prev = 0
    for i in range(1, n + 1):
        r = rng.random()
        k = b"k%03d" % rng.randrange(keys)
        if r < 0.22:
            f = (b"set", b"r" + k, b"v%d" % i)
        elif r < 0.40:
            f = (b"cntset", b"c" + k, rng.randrange(-50, 50))
        elif r < 0.56:
            f = (b"sadd", b"s" + k, b"m%d" % rng.randrange(10),
                 b"m%d" % rng.randrange(10))
        elif r < 0.64:
            f = (b"hset", b"h" + k, b"f%d" % rng.randrange(6), b"v%d" % i)
        elif r < 0.70:
            f = (b"srem", b"s" + k, b"m%d" % rng.randrange(10))
        elif r < 0.74:
            f = (b"hdel", b"h" + k, b"f%d" % rng.randrange(6))
        elif r < 0.78:
            f = (b"lins", b"l" + k, b"p%04d" % i, b"val%d" % i)
        elif r < 0.80:
            f = (b"lremat", b"l" + k, b"p%04d" % (i - 1))
        elif r < 0.84:
            f = (b"delbytes", b"r" + k)
        elif r < 0.88:
            f = (b"delcnt", b"c" + k, 7, rng.randrange(50))
        elif r < 0.93:
            f = (b"delset", b"s" + k)
        elif r < 0.96:
            f = (b"deldict", b"h" + k)
        elif r < 0.98:
            f = (b"expireat", b"r" + k, u(i) + (1 << 45))
        else:
            f = (b"meet", b"10.9.9.%d:7%03d" % (rng.randrange(9), i % 1000))
        frames.append(frame(prev, u(i), *f))
        prev = u(i)
    return frames, prev


def drive(node, frames, max_frames=64, max_latency=999.0):
    ap = CoalescingApplier(node, ReplicaMeta("peer:1"),
                           max_frames=max_frames, max_latency=max_latency)
    for f in frames:
        ap.apply(f)
    ap.flush()
    return ap


# ---------------------------------------------------------- equivalence


def test_coalesced_equals_per_frame_cpu_engine():
    frames, last = mixed_stream(1500)
    n1, n2 = Node(node_id=1), Node(node_id=2)
    a1 = drive(n1, frames, max_frames=64)
    a2 = drive(n2, frames, max_frames=1)
    assert n1.canonical() == n2.canonical()
    assert a1.meta.uuid_he_sent == last == a2.meta.uuid_he_sent
    # batch=1 is the exact per-frame path: nothing coalesced, no merges
    assert n2.stats.repl_frames_coalesced == 0
    assert n2.stats.merges == 0
    assert n2.stats.repl_apply_barriers == len(frames)
    # the coalesced node really did batch
    assert n1.stats.repl_frames_coalesced > 0
    assert n1.stats.repl_coalesce_flushes < n1.stats.repl_frames_coalesced
    # same replicated-command accounting either way
    assert n1.stats.cmds_replicated == n2.stats.cmds_replicated


def test_coalesced_equals_per_frame_xla_engine():
    jax = pytest.importorskip("jax")  # noqa: F841
    from constdb_tpu.engine.tpu import TpuMergeEngine

    frames, last = mixed_stream(2500, seed=11)
    n1 = Node(node_id=1, engine=TpuMergeEngine(resident=True))
    n2 = Node(node_id=2)
    drive(n1, frames, max_frames=128)
    drive(n2, frames, max_frames=1)
    assert n1.canonical() == n2.canonical()
    # GC / tombstone accounting parity: the same horizon frees the same
    # entries and converges to the same state
    horizon = last + (1 << SEQ_BITS)
    assert n1.ks.gc(horizon) == n2.ks.gc(horizon)
    assert n1.canonical() == n2.canonical()


def test_key_delete_rule_across_two_links():
    """The flush-time dt rule: peer A's sadd is pending while peer B's
    delset (a barrier on ITS OWN link) lands first — the member must end
    tombstoned at the delete time, exactly like per-frame ordering."""
    for batch in (64, 1):
        node = Node(node_id=1)
        a = CoalescingApplier(node, ReplicaMeta("a:1"), max_frames=batch,
                              max_latency=999.0)
        b = CoalescingApplier(node, ReplicaMeta("b:1"), max_frames=batch,
                              max_latency=999.0)
        a.apply(frame(0, u(1), b"sadd", b"s", b"m1"))
        # B's stream: sadd (establishes the key), then delset LATER than
        # A's pending add
        b.apply(frame(0, u(2), b"sadd", b"s", b"m0"))
        b.apply(frame(u(2), u(5), b"delset", b"s"))
        a.flush()
        b.flush()
        if batch == 64:
            state = node.canonical()
        else:
            assert node.canonical() == state  # same as coalesced run
        kid = node.ks.lookup(b"s")
        elems = {m: (at, dlt) for m, at, _an, dlt, _v
                 in node.ks.elem_all(kid)}
        assert elems[b"m1"] == (u(1), u(5))  # killed by the delete
        assert elems[b"m0"] == (u(2), u(5))


# ------------------------------------------------------------- barriers


def test_barrier_flushes_pending_batch():
    node = Node(node_id=1)
    ap = CoalescingApplier(node, ReplicaMeta("p:1"), max_frames=100,
                           max_latency=999.0)
    ap.apply(frame(0, u(1), b"sadd", b"s1", b"m"))
    ap.apply(frame(u(1), u(2), b"set", b"r1", b"v"))
    assert ap.pending == 2 and node.stats.merges == 0
    # delset on a PENDING key: must flush first, then apply per-key
    ap.apply(frame(u(2), u(3), b"delset", b"s1"))
    assert ap.pending == 0
    assert node.stats.merges == 1            # the pending batch landed
    assert node.stats.repl_apply_barriers == 1
    assert ap.meta.uuid_he_sent == u(3)
    kid = node.ks.lookup(b"s1")
    assert int(node.ks.keys.dt[kid]) == u(3)


def test_scoped_barrier_skips_flush_for_untouched_key():
    node = Node(node_id=1)
    ap = CoalescingApplier(node, ReplicaMeta("p:1"), max_frames=100,
                           max_latency=999.0)
    ap.apply(frame(0, u(1), b"sadd", b"s1", b"m"))
    # delset for a key the batch does NOT touch: applies per-key in
    # place, batch stays pending, watermark stays put
    ap.apply(frame(u(1), u(2), b"delset", b"zzz"))
    assert ap.pending == 1 and node.stats.merges == 0
    assert node.stats.repl_apply_barriers == 1
    assert ap.meta.uuid_he_sent == 0
    # membership is state-free: also no flush
    ap.apply(frame(u(2), u(3), b"meet", b"10.0.0.1:7001"))
    assert ap.pending == 1 and node.stats.merges == 0
    assert node.replicas.get("10.0.0.1:7001") is not None
    ap.flush()
    assert ap.meta.uuid_he_sent == u(3)


# ---------------------------------------------- watermark / beacon gating


def test_watermark_never_advances_past_unlanded_batch():
    node = Node(node_id=1)
    meta = ReplicaMeta("p:1")
    ap = CoalescingApplier(node, meta, max_frames=100, max_latency=999.0)
    for i in range(1, 6):
        ap.apply(frame(u(i - 1) if i > 1 else 0, u(i), b"set",
                       b"k%d" % i, b"v"))
    assert ap.pending == 5
    assert meta.uuid_he_sent == 0          # nothing landed yet
    assert ap.cursor == u(5)               # but the stream cursor moved
    # a REPLACK beacon past the pending frames is STASHED, not applied
    ap.observe_beacon(u(9))
    assert meta.uuid_he_sent == 0
    ap.flush()
    assert meta.uuid_he_sent == u(9)       # batch landed -> beacon too
    assert node.ks.lookup(b"k5") >= 0
    # with nothing pending, beacons advance immediately
    ap.observe_beacon(u(12))
    assert meta.uuid_he_sent == u(12)


def test_dup_skip_and_gap_detection():
    node = Node(node_id=1)
    ap = CoalescingApplier(node, ReplicaMeta("p:1"), max_frames=100,
                           max_latency=999.0)
    f1 = frame(0, u(1), b"set", b"k", b"v1")
    ap.apply(f1)
    ap.apply(f1)  # duplicate: skipped
    assert ap.pending == 1
    with pytest.raises(ReplicateCommandsLost):
        ap.apply(frame(u(7), u(8), b"set", b"k", b"v2"))
    # the gap-free prefix landed before the teardown
    assert ap.meta.uuid_he_sent == u(1)
    assert node.ks.lookup(b"k") >= 0


def test_latency_bound_flushes_without_count_bound():
    clock = [0.0]
    node = Node(node_id=1)
    ap = CoalescingApplier(node, ReplicaMeta("p:1"), max_frames=1 << 30,
                           max_latency=0.005, now=lambda: clock[0])
    ap.apply(frame(0, u(1), b"set", b"k1", b"v"))
    assert ap.pending == 1
    clock[0] = 0.050  # well past the bound
    # the bound is sampled every 32 frames — feed one sampling window
    prev = u(1)
    for i in range(2, 40):
        ap.apply(frame(prev, u(i), b"set", b"k%d" % i, b"v"))
        prev = u(i)
    # the bound fired at the 32-frame sample point: everything up to it
    # landed (frames after it start the next window)
    assert node.stats.repl_coalesce_flushes == 1
    assert ap.meta.uuid_he_sent == u(32)
    assert ap.pending == 39 - 32


def test_malformed_frame_falls_back_and_raises_op_error():
    """An arity-broken frame in the middle of a run must not poison the
    batch: every other frame lands, and the bad one raises the exact
    op-path error at flush."""
    from constdb_tpu.errors import WrongArity

    node = Node(node_id=1)
    ap = CoalescingApplier(node, ReplicaMeta("p:1"), max_frames=100,
                           max_latency=999.0)
    ap.apply(frame(0, u(1), b"sadd", b"s1", b"m1"))
    ap.apply(frame(u(1), u(2), b"sadd", b"s2"))  # no members: WrongArity
    ap.apply(frame(u(2), u(3), b"sadd", b"s3", b"m3"))
    with pytest.raises(WrongArity):
        ap.flush()
    assert node.ks.lookup(b"s1") >= 0
    assert node.ks.lookup(b"s3") >= 0
    # the bad frame never advanced the watermark: redelivery re-raises
    assert ap.meta.uuid_he_sent == 0


# ------------------------------------------------------------ live mesh


def test_mesh_mixed_stream_export_compare(tmp_path):
    """2 subscribers of the same origin — one coalescing, one pinned to
    the exact per-frame path — under a mixed write/DEL/membership
    stream: both converge to byte-identical canonical state."""
    async def run():
        from constdb_tpu.server.io import start_node
        from cluster_util import FAST

        apps = []
        for i, batch in enumerate((None, 64, 1)):
            node = Node(node_id=i + 1, alias=f"n{i + 1}")
            apps.append(await start_node(
                node, host="127.0.0.1", port=0, work_dir=str(tmp_path),
                apply_batch=batch, apply_latency=0.02, **FAST))
        a, b, c = apps
        cli = await Client().connect(a.advertised_addr)
        await cli.cmd("meet", b.advertised_addr)
        await cli.cmd("meet", c.advertised_addr)
        await full_mesh(apps)
        rng = random.Random(5)
        for i in range(400):
            r = rng.random()
            k = "k%02d" % rng.randrange(30)
            if r < 0.25:
                await cli.cmd("set", "r" + k, "v%d" % i)
            elif r < 0.45:
                await cli.cmd("incr", "c" + k, rng.randrange(1, 9))
            elif r < 0.62:
                await cli.cmd("sadd", "s" + k, "m%d" % rng.randrange(8),
                              "m%d" % rng.randrange(8))
            elif r < 0.74:
                await cli.cmd("hset", "h" + k, "f%d" % rng.randrange(5),
                              "v%d" % i)
            elif r < 0.80:
                await cli.cmd("srem", "s" + k, "m%d" % rng.randrange(8))
            elif r < 0.86:
                await cli.cmd("lpush", "l" + k, "x%d" % i)
            elif r < 0.97:
                await cli.cmd("del", "r" + k if r < 0.90 else
                              ("s" + k if r < 0.94 else "c" + k))
            else:
                await cli.cmd("meet", "10.7.7.7:7%03d" % (i % 5))
        await converge(apps, timeout=20.0)
        # the coalescing node really coalesced; the pinned node did not
        assert b.node.stats.repl_frames_coalesced > 0
        assert c.node.stats.repl_frames_coalesced == 0
        # a lone write becomes visible without further traffic (the
        # idle-flush rule: zero added latency for a quiet stream)
        await cli.cmd("set", "lone-key", "lone-value")
        deadline = asyncio.get_running_loop().time() + 5.0
        while True:
            kid = b.node.ks.lookup(b"lone-key")
            if kid >= 0 and b.node.ks.register_get(kid) == b"lone-value":
                break
            assert asyncio.get_running_loop().time() < deadline, \
                "lone write did not land via idle flush"
            await asyncio.sleep(0.02)
        await cli.close()
        await close_cluster(apps)

    asyncio.run(run())


# ------------------------------------------------------------ bench smoke


@pytest.mark.slow
def test_stream_bench_smoke(tmp_path):
    """bench.py --mode stream end-to-end on the CPU engine with a tiny
    recorded frame log: JSON line present, oracle-verified, and the
    frame log records + replays.  Slow-marked: the wall is two python
    subprocess spawns (~2.5 s each on the burstable builder), which rode
    the 5 s tier-1 budget line — ci.sh runs its own oracle-verified
    stream smoke anyway (the resident stage)."""
    import json
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    log_path = str(tmp_path / "frames.log")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CONSTDB_BENCH_FRAMES="2000",
               CONSTDB_BENCH_STREAM_KEYS="300",
               CONSTDB_BENCH_STREAM_ENGINE="cpu",
               CONSTDB_BENCH_APPLY_BATCH="128",
               CONSTDB_AUTO_NATIVE="0")
    for expect_replay in (False, True):
        r = subprocess.run(
            [sys.executable, os.path.join(root, "bench.py"),
             "--mode", "stream", "--frame-log", log_path],
            capture_output=True, text=True, timeout=300, env=env, cwd=root)
        assert r.returncode == 0, r.stderr[-2000:]
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["metric"] == "stream_apply_frames_per_sec"
        assert out["verified"] is True
        assert out["frames"] == 2000
        assert out["value"] > 0 and out["per_frame_baseline_fps"] > 0
        assert "visibility_p99_ms" in out
        assert ("replaying recorded frame log" in r.stderr) == expect_replay
    assert os.path.exists(log_path)
