"""Device-resident steady-state merges (engine/tpu.py micro path).

The load-bearing claims of the round-12 routing inversion, each pinned:
  * op-stream micro-batches merged IN PLACE against resident device
    planes are byte-identical to the host engines — canonical export
    differentials for the coalesced replication stream and for mixed
    snapshot-ingest + stream traffic, and a fixed-HLC lockstep serving
    differential (reply streams, canonical export, repl_log) — on BOTH
    kernel backends (XLA twins and pallas-interpret);
  * flushes are PARTIAL: `flush_rows_downloaded` stays strictly below
    the whole-plane equivalent while `dev_rounds_resident` > 0;
  * consecutive coalescable stream batches merge with NO flush between
    them (env stays host-authoritative; `Node.ensure_flushed_for`
    narrows the finalize barrier);
  * the warm-streak gate routes cold planes to the host fallback and
    engages after `CONSTDB_RESIDENT_WARMUP` stable rounds;
  * `CONSTDB_RESIDENT=0` (and steady=False) pin the pre-round-12 host
    micro routing exactly;
  * `host_stale` reports exactly the families holding unflushed device
    state.
"""

import asyncio

import numpy as np
import pytest

jax = pytest.importorskip("jax")  # noqa: F841

from constdb_tpu.engine.tpu import TpuMergeEngine
from constdb_tpu.server.node import Node
from constdb_tpu.utils.hlc import SEQ_BITS

from test_coalesce_apply import drive, frame, mixed_stream, u

BACKENDS = ("auto", "pallas-interpret")


def steady_engine(fold="auto", warmup=0, **kw):
    # steady FORCED: the auto default engages only over a real
    # accelerator backend, and these differentials run on CPU builders
    kw.setdefault("steady", True)
    return TpuMergeEngine(resident=True, dense_fold=fold, warmup=warmup,
                          **kw)


def coalescable_stream(n, seed=21, keys=60):
    """Encodable-only frames (no barriers): the regime where the steady
    path should ride with zero flushes between batches."""
    import random
    rng = random.Random(seed)
    frames = []
    prev = 0
    for i in range(1, n + 1):
        r = rng.random()
        k = b"k%03d" % rng.randrange(keys)
        if r < 0.3:
            f = (b"set", b"r" + k, b"v%d" % i)
        elif r < 0.55:
            f = (b"cntset", b"c" + k, rng.randrange(-50, 50))
        elif r < 0.75:
            f = (b"sadd", b"s" + k, b"m%d" % rng.randrange(10))
        elif r < 0.9:
            f = (b"hset", b"h" + k, b"f%d" % rng.randrange(6), b"v%d" % i)
        else:
            f = (b"srem", b"s" + k, b"m%d" % rng.randrange(10))
        frames.append(frame(prev, u(i), *f))
        prev = u(i)
    return frames, prev


# ---------------------------------------------------------- differentials


def _stream_differential(fold, n_frames, keys, max_frames):
    """Coalesced replication apply on the resident micro path equals the
    per-frame CPU reference byte for byte — including tombstones,
    counter deletes, and the GC queue — with resident rounds proven and
    downloads proven partial."""
    frames, last = mixed_stream(n_frames, seed=5, keys=keys)
    eng = steady_engine(fold)
    n1 = Node(node_id=1, engine=eng)
    n2 = Node(node_id=2)
    drive(n1, frames, max_frames=max_frames)
    drive(n2, frames, max_frames=1)
    n1.ensure_flushed()
    assert n1.canonical() == n2.canonical()
    assert eng.dev_rounds_resident > 0
    # partial, not whole-plane, downloads (the acceptance criterion)
    assert 0 < eng.flush_rows_downloaded < eng.flush_rows_full_equiv
    if fold == "pallas-interpret":
        assert not eng._pallas_broken
    # GC parity under the same horizon
    horizon = last + (1 << SEQ_BITS)
    assert n1.ks.gc(horizon) == n2.ks.gc(horizon)
    assert n1.canonical() == n2.canonical()


def test_stream_differential_compact():
    """Tier-1 variant: small mixed stream, XLA backend — every barrier
    class still present, so flush-after-every-DEL interleavings stay
    covered (the wide both-backend run is the slow twin; the barrier
    flushes dominate its wall through per-shape jit traces)."""
    _stream_differential("auto", 250, 40, 64)


@pytest.mark.slow
@pytest.mark.parametrize("fold", BACKENDS)
def test_stream_differential_wide(fold):
    _stream_differential(fold, 1500, 80, 64)


@pytest.mark.parametrize(
    "fold", ("auto",
             # interpret-mode tracing rides the tier-1 budget line on the
             # burstable builder; the slow suite + the ci.sh resident
             # smoke keep the pallas-interpret leg covered
             pytest.param("pallas-interpret", marks=pytest.mark.slow)))
def test_snapshot_ingest_then_stream(fold):
    """Bulk catch-up (unique batches, whole-plane dirty) followed by
    steady-state micro rounds on the SAME engine: the dirty=None planes
    flush wholesale, later micro rounds flush their dirty rows, and the
    result equals the CPU reference — including counter sums re-derived
    through the segment-sum path under pallas-interpret."""
    from constdb_tpu.engine.base import ColumnarBatch

    n_keys = 400
    b = ColumnarBatch()
    b.keys = [b"c%05d" % i for i in range(n_keys)]
    from constdb_tpu.crdt import semantics as S
    b.key_enc = np.full(n_keys, S.ENC_COUNTER, dtype=np.int8)
    b.key_ct = np.full(n_keys, u(1), dtype=np.int64)
    b.key_mt = np.full(n_keys, u(1), dtype=np.int64)
    b.key_dt = np.zeros(n_keys, dtype=np.int64)
    b.key_expire = np.zeros(n_keys, dtype=np.int64)
    b.reg_val = [None] * n_keys
    b.reg_t = np.zeros(n_keys, dtype=np.int64)
    b.reg_node = np.zeros(n_keys, dtype=np.int64)
    b.cnt_ki = np.arange(n_keys, dtype=np.int64)
    b.cnt_node = np.full(n_keys, 9, dtype=np.int64)
    b.cnt_val = np.arange(n_keys, dtype=np.int64) - 50
    b.cnt_uuid = np.full(n_keys, u(1), dtype=np.int64)
    b.cnt_base = np.zeros(n_keys, dtype=np.int64)
    b.cnt_base_t = np.full(n_keys, S.NEUTRAL_T, dtype=np.int64)
    b.rows_unique_per_slot = True

    frames, _ = coalescable_stream(600, seed=8)
    eng = steady_engine(fold)
    n1 = Node(node_id=1, engine=eng)
    n2 = Node(node_id=2)
    for n in (n1, n2):
        n.merge_batch(b)
        drive(n, frames, max_frames=48)
        n.ensure_flushed()
    assert n1.canonical() == n2.canonical()
    assert eng.dev_rounds_resident > 0
    if fold == "pallas-interpret":
        assert not eng._pallas_broken


@pytest.mark.parametrize("fold", BACKENDS)
def test_serve_lockstep_differential(tmp_path, fold):
    """Fixed-HLC lockstep serving: a coalescing node on the resident
    micro path produces byte-identical reply streams, canonical export,
    and repl_log vs the CPU-engine coalescing node."""
    from test_serve_coalesce import drive_node, mixed_workload

    work = mixed_workload(n_conns=2, rounds=10)
    eng = steady_engine(fold)

    async def main():
        got = await drive_node(tmp_path / "dev", 64, work, engine=eng)
        want = await drive_node(tmp_path / "cpu", 64, work)
        return got, want

    (g_raw, g_canon, g_repl, g_st), (w_raw, w_canon, w_repl, w_st) = \
        asyncio.run(main())
    for ci, (g, w) in enumerate(zip(g_raw, w_raw)):
        assert g == w, f"conn {ci} reply stream diverged"
    assert g_canon == w_canon
    assert g_repl == w_repl
    assert g_st.serve_msgs_coalesced == w_st.serve_msgs_coalesced
    assert eng.dev_rounds_resident > 0
    assert eng.flush_rows_downloaded < eng.flush_rows_full_equiv
    if fold == "pallas-interpret":
        assert not eng._pallas_broken


# ------------------------------------------------------- routing behavior


def test_no_flush_between_coalescable_batches():
    """Pure-coalescable stream: batches merge in place round after round
    with exactly ONE flush at the end (the explicit ensure_flushed) —
    the narrowed finalize barrier never forces a round-trip."""
    frames, _ = coalescable_stream(800)
    eng = steady_engine()
    flushes = []
    real_flush = eng.flush

    def counting_flush(store):
        if eng.needs_flush:
            flushes.append(True)
        real_flush(store)

    eng.flush = counting_flush
    n1 = Node(node_id=1, engine=eng)
    drive(n1, frames, max_frames=64)
    assert eng.dev_rounds_resident >= 10
    assert not flushes  # nothing flushed during the whole stream
    n1.ensure_flushed()
    assert len(flushes) == 1
    n2 = Node(node_id=2)
    drive(n2, frames, max_frames=1)
    assert n1.canonical() == n2.canonical()


def test_warmup_gate_engages_after_stable_rounds():
    frames, _ = coalescable_stream(600)
    eng = steady_engine(warmup=2)
    n1 = Node(node_id=1, engine=eng)
    drive(n1, frames, max_frames=32)
    # the first `warmup` rounds route to the host fallback, the rest ride
    assert eng.host_micro_rounds == 2
    assert eng.dev_rounds_resident > 0
    n2 = Node(node_id=2)
    drive(n2, frames, max_frames=1)
    n1.ensure_flushed()
    assert n1.canonical() == n2.canonical()


def test_resident_env_pin(monkeypatch):
    """CONSTDB_RESIDENT=0 pins the exact pre-round-12 host micro routing
    (steady=False equivalently) — and `auto` resolves OFF on this
    CPU-only backend (the healthy-device clause) and ON when forced."""
    assert TpuMergeEngine(resident=True).steady is False  # auto, cpu
    monkeypatch.setenv("CONSTDB_RESIDENT", "1")
    assert TpuMergeEngine(resident=True).steady is True
    monkeypatch.setenv("CONSTDB_RESIDENT", "0")
    eng = TpuMergeEngine(resident=True)
    assert eng.steady is False
    frames, _ = coalescable_stream(300)
    n1 = Node(node_id=1, engine=eng)
    drive(n1, frames, max_frames=32)
    assert eng.dev_rounds_resident == 0
    assert eng.host_micro_rounds > 0
    assert not eng.needs_flush  # host path leaves nothing on device
    n2 = Node(node_id=2)
    drive(n2, frames, max_frames=1)
    assert n1.canonical() == n2.canonical()


def test_host_stale_reports_touched_families():
    """host_stale narrows exactly to families with unflushed device
    state; env stays host-authoritative so dt reads never flush."""
    frames, _ = coalescable_stream(200)
    eng = steady_engine()
    n1 = Node(node_id=1, engine=eng)
    drive(n1, frames, max_frames=64)
    assert eng.needs_flush
    assert not eng.host_stale(("env",))
    assert eng.host_stale(("reg", "cnt", "el"))
    n1.ensure_flushed()
    assert not eng.host_stale(("reg", "cnt", "el"))


@pytest.mark.parametrize("fold", ("xla", "pallas-interpret"))
def test_micro_delete_survives_forced_fold_bulk_round(fold):
    """Review-round regression: a micro-round element DELETE advances
    host del_t; the device mirror's del_t must advance in lockstep, or a
    later FORCED-dense_fold bulk round (whose kernels read and
    re-download del_t) merges against the stale plane and resurrects the
    deleted member at flush."""
    from constdb_tpu.engine.base import ColumnarBatch
    from constdb_tpu.crdt import semantics as S
    from constdb_tpu.engine.cpu import CpuMergeEngine

    def el_batch(member_ts, del_ts, unique):
        b = ColumnarBatch()
        b.keys = [b"s1"]
        b.key_enc = np.full(1, S.ENC_SET, dtype=np.int8)
        b.key_ct = np.array([u(1)], dtype=np.int64)
        b.key_mt = np.array([u(1)], dtype=np.int64)
        b.key_dt = np.zeros(1, dtype=np.int64)
        b.key_expire = np.zeros(1, dtype=np.int64)
        b.reg_val = [None]
        b.reg_t = np.zeros(1, dtype=np.int64)
        b.reg_node = np.zeros(1, dtype=np.int64)
        n = len(member_ts)
        b.el_ki = np.zeros(n, dtype=np.int64)
        b.el_member = [m for m, _ in member_ts]
        b.el_val = [None] * n
        b.el_add_t = np.fromiter((t for _, t in member_ts), np.int64, n)
        b.el_add_node = np.full(n, 3, dtype=np.int64)
        b.el_del_t = np.fromiter(del_ts, np.int64, n)
        b.rows_unique_per_slot = unique
        return b

    def run(engine):
        from constdb_tpu.store.keyspace import KeySpace
        ks = KeySpace()
        # micro round: add m1/m2, then a micro round observed-removes m1
        engine.merge_many(ks, [el_batch([(b"m1", u(2)), (b"m2", u(2))],
                                        [0, 0], False)])
        engine.merge_many(ks, [el_batch([(b"m1", 0)], [u(5)], False)])
        # forced-fold BULK round re-touching the same rows (unique batch)
        engine.merge_many(ks, [el_batch([(b"m1", u(3)), (b"m2", u(3))],
                                        [0, 0], True)])
        if getattr(engine, "needs_flush", False):
            engine.flush(ks)
        return ks.canonical()

    got = run(steady_engine(fold))
    want = run(CpuMergeEngine())
    assert got == want  # m1 stays dead (del u(5) > add u(3))


def test_merge_stats_carry_transfer_deltas():
    """merge_many slices per-call transfer deltas out of the cumulative
    gauges (the MergeStats surface INFO and the bench legs read)."""
    from constdb_tpu.replica.coalesce import BatchBuilder
    from constdb_tpu.resp.message import Bulk
    from constdb_tpu.server.commands import COLUMNAR_ENCODERS

    eng = steady_engine()
    n1 = Node(node_id=1, engine=eng)
    bb = BatchBuilder(n1.ks)
    recs = [(b"k%d" % i, 7, u(i + 1),
             [None] * 6 + [Bulk(b"v%d" % i)])
            for i in range(32)]
    COLUMNAR_ENCODERS[b"set"](bb, recs)
    st = eng.merge_many(n1.ks, [bb.finalize()])
    assert st.dev_rounds_resident == 1
    assert st.dev_upload_bytes > 0
    eng.flush(n1.ks)
    assert eng.flush_rows_downloaded > 0
