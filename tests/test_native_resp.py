"""Native RESP parser (native/resp.cpp) vs pure-Python parser: identical
messages on any input, any chunking.

The native parser is a drop-in fast path (resp/codec.py make_parser); the
pure parser is the semantics reference.  Differential fuzz over random
message streams with random feed boundaries is the contract.
"""

import random

import pytest

from constdb_tpu.errors import InvalidRequestMsg
from constdb_tpu.resp.codec import (NativeRespParser, RespParser, encode_msg,
                                    _ext)
from constdb_tpu.resp.message import (Arr, Bulk, Err, Int, NIL, Simple)

pytestmark = pytest.mark.skipif(_ext() is None,
                                reason="native extension not built")


def rand_msg(rng, depth=0):
    kind = rng.randrange(0, 7 if depth < 2 else 6)
    if kind == 0:
        return Simple(bytes(rng.randrange(32, 127) for _ in
                            range(rng.randrange(0, 12))))
    if kind == 1:
        return Err(b"ERR " + bytes(rng.randrange(32, 127) for _ in
                                   range(rng.randrange(0, 12))))
    if kind == 2:
        return Int(rng.randrange(-2**62, 2**62))
    if kind == 3:
        return Bulk(bytes(rng.randrange(0, 256) for _ in
                          range(rng.randrange(0, 40))))
    if kind == 4:
        return NIL
    if kind == 5:  # flat command array (the hot shape)
        return Arr([Bulk(bytes(rng.randrange(0, 256) for _ in
                               range(rng.randrange(0, 20))))
                    if rng.random() < 0.8 else Int(rng.randrange(-99, 99))
                    for _ in range(rng.randrange(1, 6))])
    return Arr([rand_msg(rng, depth + 1) for _ in range(rng.randrange(0, 4))])


@pytest.mark.parametrize("seed", range(8))
def test_differential_fuzz(seed):
    rng = random.Random(seed)
    msgs = [rand_msg(rng) for _ in range(200)]
    wire = b"".join(encode_msg(m) for m in msgs)

    native, pure = NativeRespParser(), RespParser()
    got_n, got_p = [], []
    pos = 0
    while pos < len(wire):
        step = rng.randrange(1, 64)
        chunk = wire[pos:pos + step]
        pos += step
        native.feed(chunk)
        pure.feed(chunk)
        while (m := native.next_msg()) is not None:
            got_n.append(m)
        while (m := pure.next_msg()) is not None:
            got_p.append(m)
    assert got_n == msgs
    assert got_p == msgs


def test_malformed_raises_same_error_type():
    for bad in (b"*2\r\n$3\r\nab\r\n\r\n",      # wrong bulk CRLF
                b"$99999999999999\r\n",          # huge bulk
                b"*1\r\n$-5\r\nx\r\n"):          # negative bulk in array
        native, pure = NativeRespParser(), RespParser()
        native.feed(bad)
        pure.feed(bad)
        with pytest.raises(InvalidRequestMsg):
            while native.next_msg() is not None:
                pass
        with pytest.raises(InvalidRequestMsg):
            while pure.next_msg() is not None:
                pass


def test_take_raw_interleaves_with_native_parse():
    """Snapshot download drains raw bytes from the same buffer the parser
    scans (replica/link.py)."""
    p = NativeRespParser()
    p.feed(b"*2\r\n$8\r\nfullsync\r\n:4\r\n" + b"RAWD" + b"*1\r\n$4\r\nping\r\n")
    m = p.next_msg()
    assert m == Arr([Bulk(b"fullsync"), Int(4)])
    assert p.take_raw(4) == b"RAWD"
    assert p.next_msg() == Arr([Bulk(b"ping")])


def test_pipelined_burst_order():
    p = NativeRespParser()
    burst = b"".join(b"*3\r\n$3\r\nset\r\n$2\r\nk%d\r\n$2\r\nv%d\r\n" % (i, i)
                     for i in range(10))
    p.feed(burst)
    for i in range(10):
        m = p.next_msg()
        assert m.items[1].val == b"k%d" % i
    assert p.next_msg() is None


def test_snapshot_magic_blocks_eager_parse():
    """The pull loop interleaves RESP frames with raw snapshot bytes on one
    stream; the (eager) native parser stops exactly at the raw boundary
    BECAUSE the snapshot magic's first byte is not a RESP type byte.  A
    format change that breaks this would corrupt full syncs."""
    from constdb_tpu.persist.snapshot import MAGIC
    assert MAGIC[0:1] not in b"+-:$*"


def test_overlong_integer_matches_pure_parser():
    """>64-bit integers must come back exact (the C fast path defers to
    Python's arbitrary-precision parse instead of overflowing)."""
    big = 9999999999999999999  # > 2**63
    wire = b":%d\r\n:-%d\r\n" % (big, big)
    n, p = NativeRespParser(), RespParser()
    n.feed(wire), p.feed(wire)
    assert n.next_msg() == p.next_msg() == Int(big)
    assert n.next_msg() == p.next_msg() == Int(-big)


def test_valid_messages_before_malformed_still_delivered():
    """A bad frame mid-batch must not swallow the valid messages before
    it: both parsers deliver the SET, then raise on the corrupt frame."""
    wire = b"*3\r\n$3\r\nset\r\n$1\r\nk\r\n$1\r\nv\r\n*1\r\n$3\r\nabXY\r\n"
    for parser in (NativeRespParser(), RespParser()):
        parser.feed(wire)
        first = parser.next_msg()
        assert first == Arr([Bulk(b"set"), Bulk(b"k"), Bulk(b"v")]), \
            type(parser).__name__
        with pytest.raises(InvalidRequestMsg):
            parser.next_msg()


def _random_msg(rng, depth=0):
    die = rng.random()
    if die < 0.25:
        # cover interned (0..9999), boundary, negative, and >64-bit ints
        return Int(rng.choice([0, 1, 5, 1023, 1024, 9999, 10000, -1, -7,
                               2**62, -(2**62), 2**70,
                               rng.randrange(-10**6, 10**6)]))
    if die < 0.5:
        return Bulk(bytes(rng.randrange(256)
                          for _ in range(rng.randrange(0, 40))))
    if die < 0.6:
        return Simple(b"OK%d" % rng.randrange(100))
    if die < 0.7:
        return Err(b"ERR %d" % rng.randrange(100))
    if die < 0.8:
        return NIL
    if depth >= 3:
        return Bulk(b"leaf")
    return Arr([_random_msg(rng, depth + 1)
                for _ in range(rng.randrange(0, 6))])


def test_encoder_differential_fuzz():
    """The native encoder's wire bytes must equal the pure encoder's for
    every message shape, byte for byte.  This is the direct check — the
    parser round-trip alone would self-cancel (a bad encoder feeds both
    parsers the same wrong bytes)."""
    from constdb_tpu.resp.codec import _enc, _py_encode_into

    assert _enc() is not None
    rng = random.Random(1234)
    for _ in range(20_000):
        m = _random_msg(rng)
        ref = bytearray()
        _py_encode_into(ref, m)
        got = encode_msg(m)  # native-first path
        assert got == bytes(ref), m
