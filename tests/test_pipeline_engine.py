"""Pipelined merge dispatch (engine/tpu.py stage/dispatch split).

The double-buffered pipeline overlaps host STAGING of family k+1 with
DISPATCH of family k.  Everything here pins the contract that makes the
overlap safe: byte-identical results vs the serial path, the
flush-before-touch invariant still failing loudly, and the win-pool id
ceiling flushing at a round boundary instead of raising mid-round.
"""

import numpy as np
import pytest

import bench
from constdb_tpu.engine.base import batch_from_keyspace
from constdb_tpu.engine.cpu import CpuMergeEngine
from constdb_tpu.engine.tpu import TpuMergeEngine
from constdb_tpu.store.keyspace import KeySpace


def _run_rounds(engine, chunks, group):
    """Two-plus deterministic merge_many rounds into a fresh store."""
    st = KeySpace()
    for i in range(0, len(chunks), group):
        engine.merge_many(st, chunks[i:i + group])
    if engine.needs_flush:
        engine.flush(st)
    return st


def _store_bytes(ks: KeySpace):
    """Exact store state: every numeric column byte plus the object
    planes — stricter than canonical(), which normalizes."""
    n, c, e = ks.keys.n, ks.cnt.n, ks.el.n
    return (
        {name: ks.keys.col(name)[:n].tobytes()
         for name in ("enc", "ct", "mt", "dt", "expire", "rv_t", "rv_node",
                      "cnt_sum")},
        {name: ks.cnt.col(name)[:c].tobytes()
         for name in ("kid", "node", "val", "uuid", "base", "base_t")},
        {name: ks.el.col(name)[:e].tobytes()
         for name in ("kid", "add_t", "add_node", "del_t")},
        list(ks.key_bytes), list(ks.reg_val), list(ks.el_member),
        list(ks.el_val), dict(ks.key_deletes), sorted(ks.garbage),
    )


@pytest.mark.parametrize("group", [4, 8])
def test_pipeline_matches_serial_byte_identical(group):
    """The deterministic two-round merge_many produces BYTE-identical
    store state with the pipeline on and off (the serial path stays
    selectable via the ctor knob / CONSTDB_PIPELINE)."""
    batches = bench.make_workload(600, 4, seed=11)
    chunks = bench.chunk_batches(batches, 150)  # several rounds per run
    st_pipe = _run_rounds(
        TpuMergeEngine(resident=True, pipeline=True), chunks, group)
    st_serial = _run_rounds(
        TpuMergeEngine(resident=True, pipeline=False), chunks, group)
    a, b = _store_bytes(st_pipe), _store_bytes(st_serial)
    for got, want in zip(a, b):
        assert got == want
    # and both match the CPU reference
    ref = KeySpace()
    cpu = CpuMergeEngine()
    for c in chunks:
        cpu.merge(ref, c)
    assert st_pipe.canonical() == ref.canonical()


def test_pipeline_env_knob(monkeypatch):
    monkeypatch.setenv("CONSTDB_PIPELINE", "0")
    assert TpuMergeEngine().pipeline is False
    monkeypatch.delenv("CONSTDB_PIPELINE")
    assert TpuMergeEngine().pipeline is True
    assert TpuMergeEngine(pipeline=False).pipeline is False


def test_flush_before_touch_still_raises_under_pipeline():
    """An op-path write to a plane holding unflushed merged columns must
    still fail loudly when the next (pipelined) merge finds the stale
    mirror — overlapped staging must not swallow the invariant."""
    batches = bench.make_workload(200, 2, seed=3)
    eng = TpuMergeEngine(resident=True, pipeline=True)
    st = KeySpace()
    eng.merge_many(st, batches)
    assert eng.needs_flush
    # simulate a buggy caller: host write WITHOUT Node.ensure_flushed
    st.touch("el")
    with pytest.raises(RuntimeError, match="flush-before-touch"):
        eng.merge_many(st, bench.make_workload(200, 2, seed=4))


def test_pool_ceiling_flushes_at_round_boundary():
    """A round that would cross the int32 src-plane id ceiling triggers a
    flush FIRST (the documented remedy) instead of raising mid-round."""
    batches = bench.make_workload(300, 2, seed=9)
    eng = TpuMergeEngine(resident=True, pipeline=True)
    st = KeySpace()
    eng.merge_many(st, batches)
    assert eng._pool_size > 0
    # next round's rows would cross a ceiling barely above the current
    # pool: merge_many must flush, then succeed
    eng.POOL_ID_CEILING = eng._pool_size + 1
    more = bench.make_workload(300, 2, seed=10)
    eng.merge_many(st, more)
    eng.flush(st)
    ref = KeySpace()
    cpu = CpuMergeEngine()
    for b in batches + more:
        cpu.merge(ref, b)
    assert st.canonical() == ref.canonical()


def test_pool_single_round_overflow_raises_before_mutation():
    """A single round too large for the id space raises BEFORE mutating
    pool state (the old check appended first, corrupting the pool)."""
    eng = TpuMergeEngine(resident=True)
    eng.POOL_ID_CEILING = 1  # no round fits
    with pytest.raises(RuntimeError, match="single"):
        eng._pool_add(None, col=np.arange(8, dtype=np.int64))
    assert eng._pool_size == 0 and not eng._val_pool


def test_sparse_rank_falls_back_to_hash():
    """A rank touching few kids across a wide range converts to hash mode
    instead of paying an O(kid range) dense window (round-5 advisor)."""
    ks = KeySpace()
    wide = 5_000_000
    kids = np.array([0, wide], dtype=np.int64)
    rows = ks.cnt.append_block(2, kid=kids, node=7, val=0,
                               uuid=ks.NEUTRAL_T, base=0,
                               base_t=ks.NEUTRAL_T)
    rank = ks.rank_of(7)
    ks.cnt_rows_assign(rank, kids, rows)
    assert rank in ks.cnt_rank_hash and rank not in ks.cnt_rank_rows
    got = ks.cnt_rows_lookup(rank, kids)
    assert got.tolist() == rows.tolist()
    # op path agrees and keeps extending the hash
    assert ks._cnt_row(0, node=7) == rows[0]
    assert ks._cnt_row(wide, node=7) == rows[1]
    r3 = ks._cnt_row(wide // 2, node=7)
    assert ks.cnt_rows_lookup(rank, np.array([wide // 2]))[0] == r3
    # memory: nothing dense was ever allocated for this rank
    assert ks.memory_report()["numeric_bytes"] < (1 << 22)


def test_clustered_rank_stays_dense():
    """Clustered kids keep the vectorized dense window (the fast path)."""
    ks = KeySpace()
    kids = np.arange(500, dtype=np.int64)
    rows = ks.cnt.append_block(500, kid=kids, node=3, val=0,
                               uuid=ks.NEUTRAL_T, base=0,
                               base_t=ks.NEUTRAL_T)
    rank = ks.rank_of(3)
    ks.cnt_rows_assign(rank, kids, rows)
    assert rank in ks.cnt_rank_rows and rank not in ks.cnt_rank_hash
    assert ks.cnt_rows_lookup(rank, kids).tolist() == rows.tolist()


def test_failed_probe_expires_ok_probe_sticks(monkeypatch):
    """probe_backend: failed probes get a TTL so a healed device is
    re-probed; successful probes cache for the process lifetime."""
    from constdb_tpu.utils import backend as bk

    calls = []

    def fake_fail(timeout):
        calls.append("fail")
        return bk.BackendProbe(False, error="wedged")

    def fake_ok(timeout):
        calls.append("ok")
        return bk.BackendProbe(True, platform="tpu", n_devices=1)

    monkeypatch.setattr(bk, "_PROBE_MEMO", [])
    monkeypatch.setattr(bk, "_probe_backend_uncached", fake_fail)
    assert not bk.probe_backend().ok
    # within the TTL the failure is served from cache
    assert not bk.probe_backend(fail_ttl=3600).ok
    assert calls == ["fail"]
    # past the TTL the device healed: the next call re-probes and the
    # success then sticks forever
    monkeypatch.setattr(bk, "_probe_backend_uncached", fake_ok)
    assert bk.probe_backend(fail_ttl=0.0).ok
    assert bk.probe_backend(fail_ttl=0.0).ok
    assert calls == ["fail", "ok"]


def test_bench_smoke_pipelined_end_to_end():
    """Fast tier-1 bench smoke: the pipelined engine runs the real
    chunked snapshot-merge cadence end-to-end WITH oracle verification,
    so dispatch-path regressions fail tests instead of waiting for the
    next bench round."""
    n_keys, n_rep = 50_000, 4
    batches = bench.make_workload(n_keys, n_rep, seed=7)
    chunks = bench.chunk_batches(batches, 1 << 14)
    eng = TpuMergeEngine(resident=True, dense_fold="auto", pipeline=True)
    st = KeySpace()
    group = 2 * n_rep
    for i in range(0, len(chunks), group):
        eng.merge_many(st, chunks[i:i + group])
    eng.flush(st)
    assert eng.folds > 0
    ok, n_checked, n_diff = bench.verify_store(st, batches, n_keys,
                                               target=1_500)
    assert ok, f"{n_diff} diffs on {n_checked} sampled keys"


def test_snapshot_roundtrip_through_pipeline():
    """A full keyspace dump re-merged through the pipelined engine equals
    the source (idempotent state merge)."""
    from test_merge_properties import gen_store

    src = gen_store(seed=21, node=4)
    b = batch_from_keyspace(src)
    eng = TpuMergeEngine(resident=True, pipeline=True)
    st = KeySpace()
    eng.merge_many(st, [b])
    eng.flush(st)
    assert st.canonical() == src.canonical()
