"""Coalesced read serving + versioned reply cache (round 18).

The load-bearing claims, each pinned here (docs/INVARIANTS.md "Read
coalescing laws"):
  * a coalescing node with the read planner AND reply cache active is
    byte-identical to a CONSTDB_SERVE_BATCH=1 node under a READ-HEAVY
    pipelined workload (hot keys, every read family, scnt/sismember,
    expiry-armed keys, type conflicts, DELs) — reply streams, canonical
    export, repl_log, and command accounting all match, cache on or off;
  * replication-intake invalidation: a node serving cached hot-key reads
    while its peer streams writes to the SAME keys never serves a stale
    reply — every reply matches the uncached reference byte-for-byte (a
    stale serve is a failure, not a race);
  * sharded routing: serve_shards=2 with the read planner in the workers
    stays byte-identical to the single-loop path, with per-shard read /
    cache gauges riding worker acks;
  * the cache itself: LRU byte cap, envelope-stamp verification,
    per-key invalidation, governor accounting + hard-watermark drop;
  * INFO surfaces serve_reads_coalesced / serve_read_flushes /
    read_cache_hits/misses/bytes/invalidations.
"""

import asyncio
import random

import pytest

from constdb_tpu.resp.codec import encode_msg
from constdb_tpu.resp.message import Arr, Bulk, Err, Int, Nil, Simple
from constdb_tpu.server.io import start_node
from constdb_tpu.server.node import Node
from constdb_tpu.server.read_cache import ReadReplyCache

from cluster_util import FAST, Client
from test_serve_coalesce import (cmd, read_replies, stepping_clock, u)


def read_heavy_workload(n_conns: int, rounds: int, seed: int = 31,
                        read_pct: float = 0.8) -> list:
    """Per-connection chunk lists: a hot-key read-dominated mix covering
    every planned read kind plus the demotion classes (expiry-armed
    keys, type conflicts, wrong arity) and enough writes/DELs that
    invalidation is exercised for real."""
    rng = random.Random(seed)
    work = [[] for _ in range(n_conns)]
    for rnd in range(rounds):
        for ci in range(n_conns):
            chunk = []
            for _ in range(rng.choice((1, 4, 8, 16, 24))):
                r = rng.random()
                # hot set: 6 keys absorb most reads, so the cache hits
                k = b"k%02d" % (rng.randrange(6) if rng.random() < 0.7
                                else rng.randrange(24))
                if r < read_pct:
                    q = rng.random()
                    if q < 0.30:
                        chunk.append(cmd(b"get", b"r" + k))
                    elif q < 0.45:
                        chunk.append(cmd(b"smembers", b"s" + k))
                    elif q < 0.55:
                        chunk.append(cmd(b"scnt", b"s" + k))
                    elif q < 0.65:
                        chunk.append(cmd(b"sismember", b"s" + k,
                                         b"m%d" % rng.randrange(6)))
                    elif q < 0.75:
                        chunk.append(cmd(b"hget", b"h" + k,
                                         b"f%d" % rng.randrange(4)))
                    elif q < 0.83:
                        chunk.append(cmd(b"hgetall", b"h" + k))
                    elif q < 0.89:
                        chunk.append(cmd(b"lrange", b"l" + k, 0, -1))
                    elif q < 0.91:
                        chunk.append(cmd(b"llen", b"l" + k))
                    elif q < 0.93:
                        chunk.append(cmd(b"hlen", b"h" + k))
                    elif q < 0.95:
                        chunk.append(cmd(b"get", b"c" + k))  # counter get
                    elif q < 0.97:
                        # type conflict: element read of a register
                        chunk.append(cmd(b"smembers", b"r" + k))
                    elif q < 0.99:
                        # expiry-armed key (set below): demotes
                        chunk.append(cmd(b"get", b"x" + k))
                    else:
                        # wrong arity: unplannable, exact op error
                        chunk.append(cmd(b"get"))
                else:
                    q = rng.random()
                    if q < 0.30:
                        chunk.append(cmd(b"set", b"r" + k,
                                         b"v%d" % rng.getrandbits(24)))
                    elif q < 0.50:
                        chunk.append(cmd(b"sadd", b"s" + k,
                                         b"m%d" % rng.randrange(6)))
                    elif q < 0.60:
                        chunk.append(cmd(b"srem", b"s" + k,
                                         b"m%d" % rng.randrange(6)))
                    elif q < 0.75:
                        chunk.append(cmd(b"hset", b"h" + k,
                                         b"f%d" % rng.randrange(4),
                                         b"v%d" % rng.getrandbits(16)))
                    elif q < 0.82:
                        chunk.append(cmd(b"incr", b"c" + k,
                                         rng.randrange(1, 9)))
                    elif q < 0.88:
                        chunk.append(cmd(b"lpush", b"l" + k,
                                         b"x%d" % rng.getrandbits(16)))
                    elif q < 0.93:
                        chunk.append(cmd(b"del", rng.choice(
                            (b"r", b"s", b"h", b"l")) + k))
                    elif q < 0.97:
                        chunk.append(cmd(b"set", b"x" + k, b"exp"))
                    else:
                        # arm an expiry far in the future: reads of
                        # x-keys demote forever after
                        chunk.append(cmd(b"expireat", b"x" + k,
                                         u(1 << 21)))
            work[ci].append(chunk)
    return work


async def drive_node(tmp_path, serve_batch, work, serve_shards=1):
    """Lockstep driver (the test_serve_coalesce pattern), returning the
    node for gauge inspection."""
    node = Node(node_id=1, alias="n1", clock=stepping_clock())
    app = await start_node(node, host="127.0.0.1", port=0,
                           work_dir=str(tmp_path), serve_batch=serve_batch,
                           serve_shards=serve_shards, **FAST)
    app._cron_task.cancel()
    conns = [await Client().connect(app.advertised_addr) for _ in work]
    raw = [bytearray() for _ in work]
    try:
        for rnd in range(len(work[0])):
            for ci, c in enumerate(conns):
                chunk = work[ci][rnd]
                c.writer.write(b"".join(encode_msg(m) for m in chunk))
                await c.writer.drain()
                await read_replies(c, raw[ci], len(chunk))
        if node.serve_plane is not None:
            canonical = await node.serve_plane.canonical()
            repl = None  # merged log compared via canonical + replies
        else:
            canonical = node.canonical()
            repl = [(e.uuid, e.prev_uuid, e.name, e.size,
                     tuple((type(a).__name__, a.val) for a in e.args))
                    for e in node.repl_log._entries]
        return [bytes(r) for r in raw], canonical, repl, node
    finally:
        for c in conns:
            await c.close()
        await app.close()


# ------------------------------------------------------------ differential

def test_read_heavy_differential(tmp_path):
    """The oracle: read planner + reply cache vs the exact per-command
    path — byte-identical reply streams, canonical export, repl_log,
    and command accounting under a hot-key read-heavy workload."""
    work = read_heavy_workload(n_conns=3, rounds=12)

    async def main():
        got = await drive_node(tmp_path / "a", 64, work)
        want = await drive_node(tmp_path / "b", 1, work)
        return got, want

    (g_raw, g_canon, g_repl, g_node), (w_raw, w_canon, w_repl, w_node) = \
        asyncio.run(main())
    for ci, (g, w) in enumerate(zip(g_raw, w_raw)):
        assert g == w, f"conn {ci} reply stream diverged"
    assert g_canon == w_canon
    assert g_repl == w_repl
    g_st, w_st = g_node.stats, w_node.stats
    assert g_st.cmds_processed == w_st.cmds_processed
    # the read plane engaged for real: planned reads, cache traffic,
    # read-your-writes flushes, and demotions all occurred
    assert g_st.serve_reads_coalesced > 0
    assert g_st.serve_read_flushes > 0
    rc = g_node.read_cache
    assert rc.hits > 0 and rc.misses > 0
    assert rc.invalidations > 0
    assert g_st.serve_barriers > 0  # demoted reads + DELs still barrier
    # the pinned leg never planned a read
    assert w_st.serve_reads_coalesced == 0
    assert w_node.read_cache.hits == 0


def test_read_differential_cache_off(tmp_path, monkeypatch):
    """CONSTDB_READ_CACHE_MB=0: the read planner still batches, replies
    stay byte-identical, and the cache machinery never engages."""
    monkeypatch.setenv("CONSTDB_READ_CACHE_MB", "0")
    work = read_heavy_workload(n_conns=2, rounds=8, seed=77)

    async def main():
        got = await drive_node(tmp_path / "a", 64, work)
        want = await drive_node(tmp_path / "b", 1, work)
        return got, want

    (g_raw, _gc, _gr, g_node), (w_raw, _wc, _wr, _w) = asyncio.run(main())
    for g, w in zip(g_raw, w_raw):
        assert g == w
    assert g_node.stats.serve_reads_coalesced > 0
    rc = g_node.read_cache
    assert rc.hits == 0 and rc.misses == 0 and len(rc) == 0


def test_sharded_read_differential(tmp_path):
    """serve_shards=2: reads route to the shard workers' planners and
    stay byte-identical to the single-loop path; per-shard read/cache
    gauges ride the worker acks."""
    work = read_heavy_workload(n_conns=2, rounds=8, seed=5)

    async def main():
        g = await drive_node(tmp_path / "a", 64, work, serve_shards=2)
        w = await drive_node(tmp_path / "b", 64, work, serve_shards=1)
        return g, w

    (g_raw, g_canon, _gr, g_node), (w_raw, w_canon, _wr, _w) = \
        asyncio.run(main())
    for ci, (g, w) in enumerate(zip(g_raw, w_raw)):
        assert g == w, f"conn {ci} reply stream diverged"
    assert g_canon == w_canon
    st = g_node.stats
    assert st.serve_reads_coalesced > 0
    assert g_node.read_cache.hits > 0  # folded from worker acks
    x = st.extra
    assert x.get("serve_shard0_reads", 0) + \
        x.get("serve_shard1_reads", 0) == st.serve_reads_coalesced
    assert x.get("serve_shard0_cache_bytes", 0) + \
        x.get("serve_shard1_cache_bytes", 0) > 0


# ------------------------------------- replication-intake invalidation

def test_reads_racing_replicated_writes(tmp_path):
    """The satellite differential: node A serves cached hot-key reads
    while peer B streams writes to the SAME keys.  After each round
    lands, A's (cached) replies must match the just-written values
    byte-for-byte — a stale serve is a FAILURE, not a race."""
    async def main():
        a = Node(node_id=1, alias="a")
        b = Node(node_id=2, alias="b")
        app_a = await start_node(a, host="127.0.0.1", port=0,
                                 work_dir=str(tmp_path / "a"), **FAST)
        app_b = await start_node(b, host="127.0.0.1", port=0,
                                 work_dir=str(tmp_path / "b"), **FAST)
        ca = await Client().connect(app_a.advertised_addr)
        cb = await Client().connect(app_b.advertised_addr)
        try:
            assert await ca.cmd("meet", app_b.advertised_addr) == \
                Simple(b"OK")
            stale = 0
            for rnd in range(12):
                # B writes the hot keys (replicated stream into A)
                await cb.cmd("set", "hot", "v%d" % rnd)
                await cb.cmd("sadd", "hs", "m%d" % rnd)
                await cb.cmd("incr", "hc", 3)
                # wait until A landed B's writes (watermark-backed:
                # canonical convergence on the written keys)
                for _ in range(200):
                    if (await _pipeline(ca, [cmd(b"get", b"hot"),
                                             cmd(b"get", b"hot")])
                            )[0] == Bulk(b"v%d" % rnd):
                        break
                    await asyncio.sleep(0.02)
                # pipelined read chunk on A — the planned+cached path
                r = await _pipeline(ca, [
                    cmd(b"get", b"hot"), cmd(b"scnt", b"hs"),
                    cmd(b"sismember", b"hs", b"m%d" % rnd),
                    cmd(b"get", b"hc"), cmd(b"get", b"hot")])
                want = [Bulk(b"v%d" % rnd), Int(rnd + 1), Int(1),
                        Int(3 * (rnd + 1)), Bulk(b"v%d" % rnd)]
                if r != want:
                    stale += 1
                    raise AssertionError(
                        f"stale cached reply in round {rnd}: {r} != "
                        f"{want}")
            assert stale == 0
            # the cache actually served hits across the rounds (the
            # double-read per chunk guarantees at least one per round)
            assert a.read_cache.hits > 0
            assert a.read_cache.invalidations > 0
        finally:
            await ca.close()
            await cb.close()
            await app_a.close()
            await app_b.close()
    asyncio.run(main())


async def _pipeline(client, msgs):
    client.writer.write(b"".join(encode_msg(m) for m in msgs))
    await client.writer.drain()
    return await read_replies(client, bytearray(), len(msgs))


# ---------------------------------------------------------- command twins

def test_scnt_sismember_semantics(tmp_path):
    """The new read commands: absent keys, liveness, type errors, DEL
    and add-wins behavior — per-command (lone) path."""
    async def main():
        node = Node(node_id=1)
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=str(tmp_path), **FAST)
        c = await Client().connect(app.advertised_addr)
        try:
            assert await c.cmd("scnt", "s") == Int(0)
            assert await c.cmd("sismember", "s", "a") == Int(0)
            await c.cmd("sadd", "s", "a", "b", "c")
            assert await c.cmd("scnt", "s") == Int(3)
            assert await c.cmd("sismember", "s", "a") == Int(1)
            assert await c.cmd("sismember", "s", "z") == Int(0)
            await c.cmd("srem", "s", "b")
            assert await c.cmd("scnt", "s") == Int(2)
            assert await c.cmd("sismember", "s", "b") == Int(0)
            await c.cmd("del", "s")
            assert await c.cmd("scnt", "s") == Int(0)
            assert await c.cmd("sismember", "s", "a") == Int(0)
            # add-wins: re-adding after the delete resurrects visibility
            await c.cmd("sadd", "s", "z")
            assert await c.cmd("scnt", "s") == Int(1)
            # type errors mirror smembers'
            await c.cmd("set", "r", "v")
            r = await c.cmd("scnt", "r")
            assert isinstance(r, Err)
            r = await c.cmd("sismember", "r", "a")
            assert isinstance(r, Err)
        finally:
            await c.close()
            await app.close()
    asyncio.run(main())


# ------------------------------------------------------------- cache unit

class _FakeCols:
    def __init__(self):
        import numpy as np
        self.n = 8
        self.ct = np.zeros(8, dtype="i8")
        self.mt = np.zeros(8, dtype="i8")
        self.dt = np.zeros(8, dtype="i8")
        self.expire = np.zeros(8, dtype="i8")


class _FakeIdx:
    def lookup(self, key):
        return -1

    def lookup_batch(self, keys):
        import numpy as np
        return np.full(len(keys), -1, dtype="i8")


class _FakeKs:
    def __init__(self):
        self.keys = _FakeCols()
        self.key_index = _FakeIdx()


def test_cache_lru_cap_and_stamp():
    ks = _FakeKs()
    rc = ReadReplyCache(4096)
    rc.put(b"get", b"k1", b"", 1, ks, b"x" * 100)
    rc.put(b"get", b"k2", b"", 2, ks, b"y" * 100)
    assert rc.get(b"get", b"k1", b"", ks) == b"x" * 100
    assert rc.hits == 1
    # envelope stamp mismatch drops the entry
    ks.keys.mt[2] = 5
    assert rc.get(b"get", b"k2", b"", ks) is None
    assert rc.misses == 1 and len(rc) == 1
    # expiry-armed keys are never cached
    ks.keys.expire[3] = 10
    rc.put(b"get", b"k3", b"", 3, ks, b"z")
    assert rc.get(b"get", b"k3", b"", ks) is None
    # oversized entries (over cap/8) are skipped
    rc.put(b"get", b"k4", b"", 4, ks, b"w" * 1024)
    assert len(rc) == 1
    # LRU eviction under the byte cap
    for i in range(30):
        rc.put(b"get", b"e%d" % i, b"", 5, ks, b"v" * 64)
    assert rc.bytes <= 4096
    assert rc.get(b"get", b"e0", b"", ks) is None  # evicted first
    assert rc.get(b"get", b"e29", b"", ks) is not None  # newest kept


def test_cache_invalidation_paths():
    ks = _FakeKs()
    rc = ReadReplyCache(1 << 20)
    rc.put(b"get", b"k", b"", 1, ks, b"a")
    rc.put(b"smembers", b"k", b"", 1, ks, b"b")
    rc.put(b"hget", b"k", b"f1", 1, ks, b"c")
    rc.put(b"get", b"other", b"", 2, ks, b"d")
    rc.invalidate_key(b"k")
    assert rc.invalidations == 3
    assert rc.get(b"get", b"k", b"", ks) is None
    assert rc.get(b"get", b"other", b"", ks) == b"d"
    # bulk invalidation with more keys than entries clears outright
    rc.put(b"get", b"k", b"", 1, ks, b"a")
    rc.invalidate_keys([b"a", b"b", b"c", b"k", b"other"])
    assert len(rc) == 0 and rc.bytes == 0
    # disabled cache never stores
    off = ReadReplyCache(0)
    off.put(b"get", b"k", b"", 1, ks, b"a")
    assert len(off) == 0


def test_member_scoped_invalidation():
    """Element writes drop only the touched members' sismember/hget
    entries; whole-key kinds always drop; key delete drops everything
    (the member-scoped laws in docs/INVARIANTS.md)."""
    import asyncio
    node = Node(node_id=1)
    node.execute(cmd(b"sadd", b"s", b"a", b"b", b"c"))
    node.execute(cmd(b"hset", b"h", b"f1", b"v1", b"f2", b"v2"))
    from constdb_tpu.server.serve import ServeCoalescer
    rc = node.read_cache

    def chunk(*msgs):
        out = bytearray()
        ServeCoalescer(node).run_chunk(list(msgs), out)
        return bytes(out)

    chunk(cmd(b"sismember", b"s", b"a"), cmd(b"sismember", b"s", b"b"),
          cmd(b"scnt", b"s"), cmd(b"hget", b"h", b"f1"),
          cmd(b"hget", b"h", b"f2"))
    assert len(rc) == 5
    # sadd of b: drops sismember(b) + scnt (whole-key kind); a/hget live
    node.execute(cmd(b"sadd", b"s", b"b"))
    h0 = rc.hits
    r = chunk(cmd(b"sismember", b"s", b"a"), cmd(b"sismember", b"s", b"b"),
              cmd(b"scnt", b"s"))
    assert r == b":1\r\n:1\r\n:3\r\n"
    assert rc.hits == h0 + 1  # only sismember(a) survived
    # hset of f1: hget(f2) survives, hget(f1) refreshes
    node.execute(cmd(b"hset", b"h", b"f1", b"v9"))
    h0 = rc.hits
    r = chunk(cmd(b"hget", b"h", b"f1"), cmd(b"hget", b"h", b"f2"))
    assert r == b"$2\r\nv9\r\n$2\r\nv2\r\n"
    assert rc.hits == h0 + 1
    # srem flips the surviving member's reply through invalidation
    node.execute(cmd(b"srem", b"s", b"a"))
    r = chunk(cmd(b"sismember", b"s", b"a"), cmd(b"sismember", b"s", b"b"))
    assert r == b":0\r\n:1\r\n"
    # DEL drops every entry for the key
    node.execute(cmd(b"del", b"s"))
    r = chunk(cmd(b"sismember", b"s", b"a"), cmd(b"sismember", b"s", b"b"))
    assert r == b":0\r\n:0\r\n"


def test_read_run_defers_across_disjoint_writes(tmp_path):
    """A read run stays open across interleaved writes of OTHER keys
    (replies still in exact request order, reads see their exact
    stream-position state), and closes when a write touches a run key."""
    work = [[
        # r1 read, write other key, r1 read again, write r1 -> close,
        # read r1 after the write must see it
        [cmd(b"set", b"r1", b"old"), cmd(b"set", b"r2", b"x")],
        [cmd(b"get", b"r1"), cmd(b"set", b"r2", b"y"),
         cmd(b"get", b"r1"), cmd(b"set", b"r1", b"new"),
         cmd(b"get", b"r1"), cmd(b"get", b"r2")],
    ]]

    async def main():
        got = await drive_node(tmp_path / "a", 64, work)
        want = await drive_node(tmp_path / "b", 1, work)
        return got, want

    (g_raw, g_canon, g_repl, g_node), (w_raw, w_canon, w_repl, _w) = \
        asyncio.run(main())
    assert g_raw == w_raw
    assert g_canon == w_canon
    assert g_repl == w_repl
    # the deferral engaged: reads planned despite the interleaved writes
    assert g_node.stats.serve_reads_coalesced == 4


def test_cache_governor_accounting(tmp_path):
    """Cache bytes ride used_memory; the hard-watermark reclaim drops
    the cache (it is a rebuildable warm cache)."""
    node = Node(node_id=1)
    ks = _FakeKs()
    rc = node.read_cache
    rc.configure(1 << 20)
    base = node.governor.used_memory()
    rc.put(b"get", b"k", b"", 1, ks, b"v" * 1000)
    assert node.governor.used_memory() >= base + 1000
    node.governor.configure(maxmemory=1, soft_pct=85.0)
    node.governor.tick()  # hard watermark -> reclaim
    assert len(rc) == 0 and rc.bytes == 0


def test_wipe_clears_cache():
    node = Node(node_id=1)
    node.execute(cmd(b"set", b"k", b"v"))
    # fill via a coalesced chunk (lone commands bypass the cache)
    from constdb_tpu.server.serve import ServeCoalescer
    coal = ServeCoalescer(node, max_run=64)
    out = bytearray()
    coal.run_chunk([cmd(b"get", b"k"), cmd(b"get", b"k")], out)
    assert len(node.read_cache) == 1
    node.reset_for_full_resync()
    assert len(node.read_cache) == 0


# ------------------------------------------------------------------- INFO

def test_info_read_gauges(tmp_path):
    async def main():
        node = Node(node_id=1)
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=str(tmp_path), **FAST)
        c = await Client().connect(app.advertised_addr)
        try:
            await _pipeline(c, [cmd(b"set", b"k", b"v"),
                                cmd(b"set", b"k2", b"v2")])
            await _pipeline(c, [cmd(b"get", b"k"), cmd(b"get", b"k2")])
            await _pipeline(c, [cmd(b"get", b"k"), cmd(b"get", b"k2")])
            info = (await c.cmd("info")).val.decode()
            assert "serve_reads_coalesced:4" in info
            assert "serve_read_flushes:" in info
            assert "read_cache_hits:2" in info
            assert "read_cache_misses:2" in info
            assert "read_cache_invalidations:" in info
            import re
            m = re.search(r"read_cache_bytes:(\d+)", info)
            assert m and int(m.group(1)) > 0
        finally:
            await c.close()
            await app.close()
    asyncio.run(main())
