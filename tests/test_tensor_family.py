"""Tensor-valued registers (crdt/tensor.py): strategy reductions,
commands, coalescers, resident device pools, snapshot + digest coverage.

The load-bearing pin is the canonical-order law: every strategy reduces
contributors in ascending (node, uuid) order with a FIXED sequential
operation chain, so host (numpy), XLA, and Pallas-interpret reads are
bit-identical — float non-associativity cannot diverge replicas or
engines.  Every differential below compares with array_equal /
canonical equality, never approx.
"""

import numpy as np
import pytest

from constdb_tpu.crdt import semantics as S
from constdb_tpu.crdt import tensor as T
from constdb_tpu.engine.base import ColumnarBatch, batch_from_keyspace
from constdb_tpu.engine.cpu import CpuMergeEngine
from constdb_tpu.engine.tpu import TpuMergeEngine
from constdb_tpu.replica.coalesce import CoalescingApplier
from constdb_tpu.replica.manager import ReplicaMeta
from constdb_tpu.resp.message import Arr, Bulk, Int, NoReply
from constdb_tpu.server.node import Node
from constdb_tpu.store.keyspace import KeySpace

STRATS = sorted(T.STRATEGY_IDS)


def cmd(*parts) -> Arr:
    return Arr([p if isinstance(p, (Bulk, Int))
                else Bulk(p if isinstance(p, bytes)
                          else str(p).encode()) for p in parts])


def payload(rng, elems, dtype=np.float32):
    return (rng.standard_normal(elems) * 5).astype(dtype)


def make_batch(rows, cfg, elems):
    """One op-stream micro-batch of tensor rows:
    rows = [(key_i, node, uuid, cnt, payload bytes)]."""
    b = ColumnarBatch()
    n = len(rows)
    b.keys = [b"t%04d" % r[0] for r in rows]
    b.key_enc = np.full(n, S.ENC_TENSOR, np.int8)
    uu = np.fromiter((r[2] for r in rows), dtype=np.int64, count=n)
    b.key_ct = uu.copy()
    b.key_mt = uu.copy()
    b.key_dt = np.zeros(n, np.int64)
    b.key_expire = np.zeros(n, np.int64)
    b.reg_val = [None] * n
    b.reg_t = np.zeros(n, np.int64)
    b.reg_node = np.zeros(n, np.int64)
    b.tns_ki = np.arange(n, dtype=np.int64)
    b.tns_node = np.fromiter((r[1] for r in rows), dtype=np.int64, count=n)
    b.tns_uuid = uu
    b.tns_cnt = np.fromiter((r[3] for r in rows), dtype=np.int64, count=n)
    b.tns_cfg = [cfg] * n
    b.tns_payload = [r[4] for r in rows]
    b.rows_unique_per_slot = False
    return b


def gen_rows(rng, n_rows, n_keys, n_nodes, elems, u0=1):
    rows = []
    u = u0
    for _ in range(n_rows):
        u += int(rng.integers(1, 4))
        rows.append((int(rng.integers(n_keys)),
                     int(rng.integers(1, n_nodes + 1)), u << 22,
                     int(rng.integers(1, 6)),
                     payload(rng, elems).tobytes()))
    return rows, u


# ------------------------------------------------------------- reductions


@pytest.mark.parametrize("strat", STRATS)
@pytest.mark.parametrize("n", [1, 2, 3, 8])
def test_reduce_twins_bit_identical(strat, n):
    """Host (numpy) vs XLA vs Pallas-interpret reductions: identical
    bits for every strategy and contributor count — incl. n=8 whose
    trimmed divisor (6) is the first non-pow2 (the constant-divisor
    reciprocal rewrite this pins)."""
    import jax.numpy as jnp

    from constdb_tpu.ops import dense as D
    from constdb_tpu.ops import pallas_dense as PD

    rng = np.random.default_rng(T.STRATEGY_IDS[strat] * 10 + n)
    G, K, Kp = 4, 100, 512
    sid = T.STRATEGY_IDS[strat]
    mat = (rng.standard_normal((G, n, K)) * 9).astype(np.float32)
    cnts = rng.integers(1, 9, size=(G, n)).astype(np.int64)
    uuids = rng.integers(1, 1000, size=(G, n))
    nodes = np.tile(np.arange(n), (G, 1)) + 1
    host = np.stack([T.reduce_rows(sid, mat[g], cnts[g], uuids[g],
                                   nodes[g]) for g in range(G)])
    if sid == T.STRAT_LWW:
        return  # lww picks a row — no float chain to twin
    matp = np.zeros((G, n, Kp), np.float32)
    matp[:, :, :K] = mat
    cf = cnts.astype(np.float32)
    div = np.float32(n if n <= 2 else n - 2)
    md, cd = jnp.asarray(matp), jnp.asarray(cf)
    if sid == T.STRAT_AVG:
        tots = np.empty((G, 1), np.float32)
        for g in range(G):
            t = np.float32(cf[g, 0])
            for i in range(1, n):
                t = t + np.float32(cf[g, i])
            tots[g, 0] = t
        wm = D.tensor_scale(md, cd)
        xla = np.asarray(D.tensor_div(
            D.tensor_reduce(wm, cd, div, strat=T.STRAT_SUM, n=n),
            jnp.asarray(tots)))[:, :K]
        pal = np.asarray(D.tensor_div(
            PD.tensor_reduce(wm, cd, div, strat=T.STRAT_SUM, n=n,
                             interpret=True), jnp.asarray(tots)))[:, :K]
    else:
        xla = np.asarray(D.tensor_reduce(md, cd, div, strat=sid,
                                         n=n))[:, :K]
        pal = np.asarray(PD.tensor_reduce(md, cd, div, strat=sid, n=n,
                                          interpret=True))[:, :K]
    assert np.array_equal(host, xla)
    assert np.array_equal(host, pal)


def test_take_reduce_fused_matches_two_step():
    """The fused pool-gather reductions (tensor_take_reduce /
    tensor_take_scale+tensor_sum_div) equal the two-step twins and the
    host chain bit for bit."""
    import jax.numpy as jnp

    from constdb_tpu.ops import dense as D

    rng = np.random.default_rng(3)
    g, n, Kp = 5, 8, 512
    buf = (rng.standard_normal((64, Kp)) * 7).astype(np.float32)
    idx = rng.choice(64, g * n, replace=False).astype(np.int32)
    cnts = rng.integers(1, 9, size=(g, n)).astype(np.float32)
    mat = buf[idx].reshape(g, n, Kp)
    bufd, idxd, cd = jnp.asarray(buf), jnp.asarray(idx), jnp.asarray(cnts)
    for strat in (T.STRAT_SUM, T.STRAT_MAXMAG, T.STRAT_TRIMMED):
        div = np.float32(n - 2)
        host = np.stack([T.reduce_rows(strat, mat[j], cnts[j],
                                       np.arange(n), np.arange(n))
                         for j in range(g)])
        fused = np.asarray(D.tensor_take_reduce(bufd, idxd, div,
                                                strat=strat, n=n, g=g))
        assert np.array_equal(host, fused), strat
    # avg: fused gather+scale then fused sum+div
    tots = np.empty((g, 1), np.float32)
    for j in range(g):
        t = np.float32(cnts[j, 0])
        for i in range(1, n):
            t = t + np.float32(cnts[j, i])
        tots[j, 0] = t
    host = np.stack([T.reduce_rows(T.STRAT_AVG, mat[j], cnts[j],
                                   np.arange(n), np.arange(n))
                     for j in range(g)])
    wm = D.tensor_take_scale(bufd, idxd, cd, n=n, g=g)
    fused = np.asarray(D.tensor_sum_div(wm, jnp.asarray(tots), n=n))
    assert np.array_equal(host, fused)


def test_config_pack_roundtrip_and_errors():
    meta = T.TensorMeta(T.STRAT_AVG, 1, (3, 5))
    assert T.unpack_config(T.pack_config(meta)) == meta
    with pytest.raises(T.TensorConfigError):
        T.unpack_config(b"\xff\x00\x01" + b"\x04\x00\x00\x00")
    with pytest.raises(T.TensorConfigError):
        T.parse_meta("nope", "f32", "8")
    with pytest.raises(T.TensorConfigError):
        T.parse_meta("sum", "f32", "1024", max_elems=512)
    m = T.parse_meta("-", "f64", "4x4", default_strat="maxmag")
    assert m.strat == T.STRAT_MAXMAG and m.elems == 16
    # dims must fit the wire config's u32 fields — an unbounded dim
    # would escape as OverflowError past the command error boundary
    with pytest.raises(T.TensorConfigError):
        T.parse_meta("sum", "f32", str(1 << 32), max_elems=1 << 62)
    with pytest.raises(T.TensorConfigError):  # rank > pack_config's byte
        T.parse_meta("sum", "f32", "x".join(["1"] * 300))
    with pytest.raises(T.TensorConfigError):
        T.check_count(0)


# ------------------------------------------- engine differential (micro)


@pytest.mark.parametrize("strat", STRATS)
@pytest.mark.parametrize("backend", ["xla", "pallas-interpret"])
def test_resident_micro_differential(strat, backend):
    """Resident device micro merges + device reads vs the CPU reference:
    canonical state AND per-round reads bit-identical, with the steady
    path actually engaged (the routing gauge the ci smoke also reads)."""
    rng = np.random.default_rng(11)
    elems = 96
    cfg = T.pack_config(T.TensorMeta(T.STRATEGY_IDS[strat], 0, (elems,)))
    ref = KeySpace()
    cpu = CpuMergeEngine()
    dev = KeySpace()
    eng = TpuMergeEngine(resident=True, steady=True, warmup=0,
                         dense_fold=backend)
    u = 1
    for _ in range(8):
        rows, u = gen_rows(rng, 48, 10, 4, elems, u)
        b1 = make_batch(rows, cfg, elems)
        b2 = make_batch(rows, cfg, elems)
        cpu.merge_many(ref, [b1])
        eng.merge_many(dev, [b2])
        got = eng.tensor_read_many(dev, range(dev.keys.n))
        for kid in range(ref.keys.n):
            want = ref.tensor_read(kid)
            assert np.array_equal(want, got[kid]), (strat, kid)
    assert eng.tns_dev_rows > 0 and eng.tns_host_rows == 0
    assert eng.dev_rounds_resident > 0
    eng.flush(dev)
    assert dev.canonical() == ref.canonical()
    # post-flush host reads equal the device reads that preceded them
    for kid in range(dev.keys.n):
        assert np.array_equal(dev.tensor_read(kid), got[kid])
    eng.close()


def test_resident_steady_off_routes_host():
    """CONSTDB_RESIDENT=0 semantics (steady=False): tensor rows take the
    host strategy, no pools, same results."""
    rng = np.random.default_rng(13)
    cfg = T.pack_config(T.TensorMeta(T.STRAT_SUM, 0, (32,)))
    rows, _ = gen_rows(rng, 64, 6, 3, 32)
    ref = KeySpace()
    CpuMergeEngine().merge_many(ref, [make_batch(rows, cfg, 32)])
    dev = KeySpace()
    eng = TpuMergeEngine(resident=True, steady=False)
    eng.merge_many(dev, [make_batch(rows, cfg, 32)])
    eng.flush(dev)
    assert eng.tns_dev_rows == 0 and eng.tns_host_rows == len(rows)
    assert not eng._tns_pools
    assert dev.canonical() == ref.canonical()
    eng.close()


def test_config_mismatch_and_bad_payload_skip_rows():
    """Config-mismatched and wrong-size rows drop with a log on BOTH
    engines (snapshot-merge semantics), never poisoning the batch."""
    elems = 16
    good = T.pack_config(T.TensorMeta(T.STRAT_SUM, 0, (elems,)))
    other = T.pack_config(T.TensorMeta(T.STRAT_AVG, 0, (elems,)))
    rng = np.random.default_rng(7)
    rows = [(0, 1, 10 << 22, 1, payload(rng, elems).tobytes()),
            (0, 2, 11 << 22, 1, payload(rng, elems).tobytes()),
            (1, 1, 12 << 22, 1, payload(rng, elems).tobytes())]
    stores = []
    for make in (CpuMergeEngine,
                 lambda: TpuMergeEngine(resident=True, steady=True,
                                        warmup=0)):
        b = make_batch(rows, good, elems)
        b.tns_cfg = [good, other, good]        # row 1: config mismatch
        b.tns_payload[2] = b.tns_payload[2][:-4]  # row 2: short payload
        ks = KeySpace()
        eng = make()
        eng.merge_many(ks, [b])
        if hasattr(eng, "flush"):
            eng.flush(ks)
        assert ks.tns_merges_by_strat.get("sum", 0) == 1
        stores.append(ks)
    assert stores[0].canonical() == stores[1].canonical()


def test_pool_cap_flush_and_op_write_invalidation():
    """The CONSTDB_TENSOR_POOL_MB cap flushes + drops pools mid-stream,
    and an op-path tensor write (fam_ver bump) drops clean pools —
    both keep results identical to the reference."""
    rng = np.random.default_rng(23)
    elems = 64
    cfg = T.pack_config(T.TensorMeta(T.STRAT_MAXMAG, 0, (elems,)))
    ref = KeySpace()
    cpu = CpuMergeEngine()
    dev = KeySpace()
    eng = TpuMergeEngine(resident=True, steady=True, warmup=0)
    eng.tns_pool_cap = 1 << 14  # trip the cap every couple of rounds
    u = 1
    for r in range(6):
        rows, u = gen_rows(rng, 32, 6, 3, elems, u)
        cpu.merge_many(ref, [make_batch(rows, cfg, elems)])
        eng.merge_many(dev, [make_batch(rows, cfg, elems)])
        if r == 3:
            # op-path write between rounds: flush-before-touch, then
            # the version bump must drop the (clean) pools
            eng.flush(dev)
            u += 1
            op_pay = payload(rng, elems)
            for ks in (dev, ref):
                kid = ks.tensor_get_or_create(b"t0002", cfg, u << 22)
                ks.tensor_slot_set(kid, 9, u << 22, 1, op_pay)
            dev.touch("tns")
    eng.flush(dev)
    assert dev.canonical() == ref.canonical()
    eng.close()


# ------------------------------------------------------------- commands


def mesh_pair():
    a = Node(node_id=1, engine=CpuMergeEngine())
    b = Node(node_id=2, engine=CpuMergeEngine())
    return a, b


def replay(a, b, done):
    for u in a.repl_log.uuids():
        if u in done:
            continue
        e = a.repl_log.at(u)
        b.apply_replicated(e.name, e.args, a.node_id, e.uuid)
        done.add(u)


def test_command_roundtrip_and_replication():
    rng = np.random.default_rng(31)
    a, b = mesh_pair()
    p1 = payload(rng, 8).tobytes()
    p2 = payload(rng, 8).tobytes()
    assert a.execute(cmd(b"tensor.set", b"m", b"avg", b"f32", b"8",
                         Bulk(p1), b"3")).val == b"OK"
    assert a.execute(cmd(b"tensor.merge", b"m", Bulk(p2))).val == b"OK"
    # one node = one slot: the second write LWW-replaced the first
    got = a.execute(cmd(b"tensor.get", b"m"))
    assert got.val == np.frombuffer(p2, np.float32).tobytes()
    st = a.execute(cmd(b"tensor.stat", b"m"))
    assert st.items[0].val == b"avg" and st.items[1].val == b"f32"
    assert st.items[3].val == 1  # one contributor
    done = set()
    replay(a, b, done)
    assert a.canonical() == b.canonical()
    # second writer on b flows back as a second contributor
    p3 = payload(rng, 8).tobytes()
    b.execute(cmd(b"tensor.merge", b"m", Bulk(p3), b"2"))
    for u in b.repl_log.uuids():
        e = b.repl_log.at(u)
        a.apply_replicated(e.name, e.args, b.node_id, e.uuid)
    assert a.canonical() == b.canonical()
    assert len(a.ks.tensor_contribs(a.ks.lookup(b"m"))) == 2


def test_command_errors_and_config_fixed_at_creation():
    rng = np.random.default_rng(37)
    a, _ = mesh_pair()
    p = payload(rng, 8).tobytes()
    a.execute(cmd(b"tensor.set", b"k", b"sum", b"f32", b"8", Bulk(p)))
    r = a.execute(cmd(b"tensor.set", b"k", b"avg", b"f32", b"8", Bulk(p)))
    assert b"mismatch" in r.val  # strategy is creation-fixed
    r = a.execute(cmd(b"tensor.merge", b"k", Bulk(p[:-4])))
    assert b"bytes" in r.val
    r = a.execute(cmd(b"tensor.merge", b"absent", Bulk(p)))
    assert b"no such tensor" in r.val
    r = a.execute(cmd(b"tensor.set", b"k2", b"nope", b"f32", b"8",
                      Bulk(p)))
    assert b"unknown tensor strategy" in r.val
    a.execute(cmd(b"set", b"reg", b"v"))
    r = a.execute(cmd(b"tensor.merge", b"reg", Bulk(p)))
    assert b"WRONGTYPE" in r.val
    # a config-LESS tensor key (a replicated deltensor for a never-seen
    # key materializes the tombstoned row only): TENSOR.MERGE must give
    # the clean no-such-key error, not crash on the absent meta
    a.apply_replicated(b"deltensor", [Bulk(b"ghost")], 9, 99 << 22)
    r = a.execute(cmd(b"tensor.merge", b"ghost", Bulk(p)))
    assert b"no such tensor" in r.val
    # ...and TENSOR.SET repairs it by installing the config
    r = a.execute(cmd(b"tensor.set", b"ghost", b"sum", b"f32", b"8",
                      Bulk(p)))
    assert r.val == b"OK"
    # a dim >= 2^32 errors cleanly even when the key name already
    # exists (the existing-key path lifts the elems cap but must not
    # lift the wire-format bound)
    r = a.execute(cmd(b"tensor.set", b"reg", b"sum", b"f32",
                      str(1 << 32).encode(), Bulk(p)))
    assert b"2^32" in r.val, r
    # non-positive counts would poison avg reads with 0/0 — rejected
    r = a.execute(cmd(b"tensor.merge", b"ghost", Bulk(p), b"0"))
    assert b"count" in r.val, r
    r = a.execute(cmd(b"tensor.set", b"k9", b"avg", b"f32", b"8",
                      Bulk(p), b"-2"))
    assert b"count" in r.val, r
    # a malformed replicated count skips the row on BOTH engine paths
    # (snapshot-merge semantics) instead of landing the poison
    cfg8 = T.pack_config(T.TensorMeta(T.STRAT_AVG, 0, (8,)))
    for make in (CpuMergeEngine,
                 lambda: TpuMergeEngine(resident=True, steady=True,
                                        warmup=0)):
        ks = KeySpace()
        eng = make()
        b0 = make_batch([(0, 1, 10 << 22, 0, p),      # count 0: skip
                         (0, 2, 11 << 22, 2, p)], cfg8, 8)
        b0.tns_cnt = np.array([0, 2], np.int64)
        eng.merge_many(ks, [b0])
        if hasattr(eng, "flush"):
            eng.flush(ks)
        assert len(ks.tensor_contribs(0)) == 1


def test_del_tombstones_and_add_wins_resurrect():
    rng = np.random.default_rng(41)
    a, b = mesh_pair()
    p = payload(rng, 8).tobytes()
    from constdb_tpu.resp.message import NIL
    a.execute(cmd(b"tensor.set", b"k", b"lww", b"f32", b"8", Bulk(p)))
    assert a.execute(cmd(b"del", b"k")).val == 1
    assert a.execute(cmd(b"tensor.get", b"k")) is NIL
    p2 = payload(rng, 8).tobytes()
    a.execute(cmd(b"tensor.merge", b"k", Bulk(p2)))
    assert a.execute(cmd(b"tensor.get", b"k")).val == \
        np.frombuffer(p2, np.float32).tobytes()
    done = set()
    replay(a, b, done)
    assert a.canonical() == b.canonical()


# ----------------------------------------------------------- coalescers


def test_replication_coalescer_differential():
    """tset frames through the coalescing applier (batch=N) vs the
    exact per-frame path (batch=1), on the CPU engine AND the resident
    device engine — identical canonical exports."""
    rng = np.random.default_rng(43)
    cfg = T.pack_config(T.TensorMeta(T.STRAT_TRIMMED, 0, (48,)))
    frames = []
    prev = 0
    u = 0
    for _ in range(300):
        u += int(rng.integers(1, 4))
        key = b"t%02d" % rng.integers(8)
        frames.append([Bulk(b"replicate"), Int(9), Int(prev),
                       Int(u << 22), Bulk(b"tset"), Bulk(key), Bulk(cfg),
                       Int(int(rng.integers(1, 4))),
                       Bulk(payload(rng, 48).tobytes())])
        prev = u << 22
        if rng.random() < 0.05:  # scalar tensor delete coalesces too
            u += 1
            frames.append([Bulk(b"replicate"), Int(9), Int(prev),
                           Int(u << 22), Bulk(b"deltensor"), Bulk(key)])
            prev = u << 22

    def run(make_engine, batch):
        node = Node(node_id=1, engine=make_engine())
        ap = CoalescingApplier(node, ReplicaMeta("x:0"),
                               max_frames=batch, max_latency=10)
        for f in frames:
            ap.apply(f)
        ap.flush()
        node.ensure_flushed()
        return node

    base = run(CpuMergeEngine, 1)
    assert run(CpuMergeEngine, 64).canonical() == base.canonical()
    n3 = run(lambda: TpuMergeEngine(resident=True, steady=True,
                                    warmup=0), 64)
    assert n3.canonical() == base.canonical()
    assert n3.engine.tns_dev_rows > 0
    n3.engine.close()


def test_serve_planner_differential():
    """TENSOR.SET/MERGE through the serve coalescer vs the per-command
    path under the same stepping clock: byte-identical replies, repl
    log, and canonical export; demotions (mismatch/absent/short) raise
    the exact op errors in order."""
    from constdb_tpu.resp.codec import encode_into
    from constdb_tpu.server.serve import ServeCoalescer

    def stepping_clock():
        t = [1_700_000_000_000]

        def clock():
            t[0] += 1
            return t[0]
        return clock

    def workload():
        rng = np.random.default_rng(47)
        msgs = []
        for i in range(120):
            key = b"t%02d" % rng.integers(6)
            r = rng.random()
            if r < 0.45:
                msgs.append(cmd(b"tensor.set", key, b"avg", b"f32",
                                b"16", Bulk(payload(rng, 16).tobytes()),
                                b"2"))
            elif r < 0.75:
                msgs.append(cmd(b"tensor.merge", key,
                                Bulk(payload(rng, 16).tobytes())))
            elif r < 0.82:
                msgs.append(cmd(b"tensor.get", key))       # scoped read
            elif r < 0.87:
                msgs.append(cmd(b"tensor.stat", key))
            elif r < 0.92:  # demote: wrong config for an existing key
                msgs.append(cmd(b"tensor.set", key, b"sum", b"f32",
                                b"16", Bulk(payload(rng, 16).tobytes())))
            elif r < 0.96:  # demote: short payload
                msgs.append(cmd(b"tensor.merge", key, Bulk(b"xx")))
            else:           # barrier: unrelated write
                msgs.append(cmd(b"set", b"r%d" % i, b"v"))
        return msgs

    # coalesced node
    nc = Node(node_id=1, clock=stepping_clock(), engine=CpuMergeEngine())
    coal = ServeCoalescer(nc, max_run=32)
    out_c = bytearray()
    msgs = workload()
    for lo in range(0, len(msgs), 24):  # chunked like drained pipelines
        coal.run_chunk(msgs[lo:lo + 24], out_c)
    # per-command node
    np_ = Node(node_id=1, clock=stepping_clock(),
               engine=CpuMergeEngine())
    out_p = bytearray()
    for m in workload():
        reply = np_.execute(m)
        if not isinstance(reply, NoReply):
            encode_into(out_p, reply)
    assert bytes(out_c) == bytes(out_p)
    assert nc.canonical() == np_.canonical()
    assert list(nc.repl_log.uuids()) == list(np_.repl_log.uuids())
    assert nc.stats.serve_msgs_coalesced > 0


def test_tensor_get_serves_from_device_without_flush():
    """The production read path: TENSOR.GET on a steady resident engine
    reduces from the payload pools — no flush, no dirty-row download —
    and still returns the exact host-reference bytes.  (Found by
    review: the device read originally had no production call site.)"""
    rng = np.random.default_rng(71)
    node = Node(node_id=1,
                engine=TpuMergeEngine(resident=True, steady=True,
                                      warmup=0))
    cfg = T.pack_config(T.TensorMeta(T.STRAT_AVG, 0, (32,)))
    rows, _ = gen_rows(rng, 24, 4, 3, 32)
    node.merge_batches([make_batch(rows, cfg, 32)])
    eng = node.engine
    assert eng.needs_flush and eng.tns_dev_rows == len(rows)
    got = node.execute(cmd(b"tensor.get", b"t0001"))
    # the read did NOT flush: payload truth stayed on device
    assert eng.needs_flush and eng.flush_rows_downloaded == 0
    # interleaved single-key reads each keep a cached group structure
    node.execute(cmd(b"tensor.get", b"t0002"))
    node.execute(cmd(b"tensor.get", b"t0001"))
    assert len(eng._tns_read_cache["by_kids"]) == 2
    st = node.execute(cmd(b"tensor.stat", b"t0001"))
    assert eng.flush_rows_downloaded == 0
    assert st.items[0].val == b"avg"
    # reference: an identical CPU-engine store
    ref = KeySpace()
    CpuMergeEngine().merge_many(ref, [make_batch(rows, cfg, 32)])
    want = ref.tensor_read(ref.lookup(b"t0001"))
    assert got.val == want.tobytes()
    # a family-listed scalar read flushes NARROWLY (round 18:
    # READ_FLUSH_FAMILIES) — GET observes env/reg/cnt only, so the
    # resident tensor rows stay dirty on device
    node.execute(cmd(b"get", b"t0001"))
    assert eng.needs_flush and eng.flush_rows_downloaded == 0
    # an unlisted read (desc) still takes the blanket flush barrier
    node.execute(cmd(b"desc", b"t0001"))
    assert not eng.needs_flush
    eng.close()


# ------------------------------------------------- snapshot/digest/info


def test_snapshot_roundtrip_and_chunking(tmp_path):
    from constdb_tpu.persist.snapshot import (NodeMeta, batch_chunks,
                                              dump_keyspace,
                                              load_snapshot)

    rng = np.random.default_rng(53)
    ks = KeySpace()
    cfg64 = T.pack_config(T.TensorMeta(T.STRAT_AVG, 1, (8, 8)))
    for i in range(24):
        kid = ks.tensor_get_or_create(b"t%02d" % i, cfg64, (i + 1) << 22)
        for nd in (1, 2):
            ks.tensor_slot_set(kid, nd, (i + nd + 2) << 22,
                               int(rng.integers(1, 4)),
                               payload(rng, 64, np.float64))
            ks.updated_at(kid, (i + nd + 2) << 22)
    kid, _ = ks.get_or_create(b"s", S.ENC_SET, 5 << 22)
    ks.elem_add(kid, b"m", None, 5 << 22, 1)
    path = str(tmp_path / "t.snap")
    dump_keyspace(path, ks, NodeMeta(node_id=1), chunk_keys=5)
    ks2 = KeySpace()
    load_snapshot(path, ks2, CpuMergeEngine())
    assert ks.canonical() == ks2.canonical()
    # chunked merge (tensor rows re-pointed per chunk) converges too
    ks3 = KeySpace()
    eng = CpuMergeEngine()
    for c in batch_chunks(batch_from_keyspace(ks), 7):
        eng.merge(ks3, c)
    assert ks.canonical() == ks3.canonical()
    # f64 device reads equal the host (XLA twin path)
    dev = KeySpace()
    teng = TpuMergeEngine(resident=True, steady=True, warmup=0)
    for c in batch_chunks(batch_from_keyspace(ks), 7):
        teng.merge_many(dev, [c])
    got = teng.tensor_read_many(dev, range(dev.keys.n))
    for kid2 in range(dev.keys.n):
        want = dev_want = ks2.tensor_read(ks2.lookup(
            dev.key_bytes[kid2]))
        if want is None:
            assert got[kid2] is None
        else:
            assert np.array_equal(dev_want, got[kid2])
    teng.close()


def test_digest_covers_tensor_plane():
    from constdb_tpu.store import digest as DG

    rng = np.random.default_rng(59)
    cfg = T.pack_config(T.TensorMeta(T.STRAT_SUM, 0, (16,)))

    def build():
        ks = KeySpace()
        r = np.random.default_rng(59)
        for i in range(30):
            kid = ks.tensor_get_or_create(b"t%02d" % i, cfg,
                                          (i + 1) << 22)
            ks.tensor_slot_set(kid, 1, (i + 2) << 22, 1, payload(r, 16))
            ks.updated_at(kid, (i + 2) << 22)
        return ks

    a, b = build(), build()
    m1 = DG.state_digest_matrix(a, 64, 4)
    assert np.array_equal(m1, DG.state_digest_matrix(b, 64, 4))
    # a tensor-slot divergence flags its bucket; the bucket export
    # converges the peer
    b.tensor_slot_set(b.lookup(b"t07"), 2, 999 << 22, 1,
                      payload(rng, 16))
    m2 = DG.state_digest_matrix(b, 64, 4)
    diff = (m1 != m2).reshape(-1)
    assert diff.any()
    CpuMergeEngine().merge(a, DG.export_bucket_batch(b, 64, 4, diff))
    assert np.array_equal(DG.state_digest_matrix(a, 64, 4), m2)
    # level-2 stamps see it too
    tbl = DG.KeyStampTable(b, 64, 4, diff)
    idx = DG.stamp_mismatch_indices(build(), tbl.crcs, tbl.stamps)
    assert len(idx) >= 1


def test_info_gauges_and_stats():
    rng = np.random.default_rng(61)
    node = Node(node_id=1,
                engine=TpuMergeEngine(resident=True, steady=True,
                                      warmup=0))
    p = payload(rng, 16).tobytes()
    node.execute(cmd(b"tensor.set", b"a", b"avg", b"f32", b"16",
                     Bulk(p), b"2"))
    node.execute(cmd(b"tensor.set", b"b", b"maxmag", b"f32", b"16",
                     Bulk(p)))
    # a coalesced tset lands through the engine (device routing gauge)
    cfg = T.pack_config(T.TensorMeta(T.STRAT_AVG, 0, (16,)))
    rows = [(0, 7, 10_000 << 22, 1, p)]
    node.merge_batches([make_batch(rows, cfg, 16)])
    info = node.execute(cmd(b"info")).val.decode()
    assert "tensors:3" in info
    assert "tensor_slots:" in info
    assert "tensor_merges_avg:" in info
    assert "tensor_merges_maxmag:1" in info
    assert "tns_dev_rows:" in info and "tns_pool_bytes:" in info
    got = int(info.split("tensor_payload_bytes:")[1].split("\r\n")[0])
    node.ensure_flushed()
    assert node.ks.tns_bytes == sum(
        pl.nbytes for pl in node.ks.tns_payload if pl is not None)
    assert got >= 0
    node.engine.close()


def test_extract_shard_routes_tensor_rows():
    from constdb_tpu.store.sharded_keyspace import (extract_shard,
                                                    shard_ids)

    rng = np.random.default_rng(67)
    cfg = T.pack_config(T.TensorMeta(T.STRAT_SUM, 0, (8,)))
    rows, _ = gen_rows(rng, 40, 12, 3, 8)
    b = make_batch(rows, cfg, 8)
    sids = shard_ids(b.keys, 2)
    ref = KeySpace()
    CpuMergeEngine().merge(ref, b)
    parts = [extract_shard(b, sids, None, s) for s in (0, 1)]
    assert sum(len(p.tns_ki) for p in parts) == len(b.tns_ki)
    merged = KeySpace()
    eng = CpuMergeEngine()
    for p in parts:
        eng.merge(merged, p)
    assert merged.canonical() == ref.canonical()
