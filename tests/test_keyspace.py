import pytest

from constdb_tpu.crdt import ENC_BYTES, ENC_COUNTER, ENC_DICT, ENC_SET
from constdb_tpu.errors import InvalidType
from constdb_tpu.store import KeySpace


def t(ms, seq=0):
    return (ms << 22) | seq


class TestCounter:
    def test_change_and_sum(self):
        ks = KeySpace()
        NT = KeySpace.NEUTRAL_T
        kid, _ = ks.get_or_create(b"c", ENC_COUNTER, t(1))
        assert ks.counter_change(kid, 1, 1, t(2)) == (1, 1)
        assert ks.counter_change(kid, 1, 1, t(3)) == (2, 2)
        assert ks.counter_change(kid, 2, -1, t(3)) == (1, -1)
        assert sorted(ks.counter_slots(kid)) == [
            (1, 2, t(3), 0, NT), (2, -1, t(3), 0, NT)]

    def test_stale_change_ignored(self):
        # fixed semantics: stored slot uuid advances, so an older uuid is stale
        ks = KeySpace()
        kid, _ = ks.get_or_create(b"c", ENC_COUNTER, t(1))
        ks.counter_change(kid, 1, 1, t(5))
        assert ks.counter_change(kid, 1, 100, t(4))[0] == 1  # ignored
        assert ks.counter_change(kid, 1, 1, t(6))[0] == 2

    def test_merge_slot_lww(self):
        NT = KeySpace.NEUTRAL_T
        ks = KeySpace()
        kid, _ = ks.get_or_create(b"c", ENC_COUNTER, t(1))
        ks.counter_change(kid, 1, 5, t(5))
        ks.counter_merge_slot(kid, 1, 9, t(4), 0, NT)   # older: ignored
        assert ks.counter_sum(kid) == 5
        ks.counter_merge_slot(kid, 1, 9, t(6), 0, NT)   # newer: replaces
        assert ks.counter_sum(kid) == 9
        ks.counter_merge_slot(kid, 1, 7, t(6), 0, NT)   # tie: max value
        assert ks.counter_sum(kid) == 9
        ks.counter_merge_slot(kid, 2, 3, t(2), 0, NT)   # new node
        assert ks.counter_sum(kid) == 12

    def test_delete_base_subtracts(self):
        ks = KeySpace()
        kid, _ = ks.get_or_create(b"c", ENC_COUNTER, t(1))
        ks.counter_change(kid, 1, 3, t(2))
        ks.counter_set_base(kid, 1, 3, t(5))   # delete observed total 3
        assert ks.counter_sum(kid) == 0
        ks.counter_change(kid, 1, 1, t(6))     # revive: counts from 0
        assert ks.counter_sum(kid) == 1
        ks.counter_set_base(kid, 1, 2, t(4))   # older delete: ignored
        assert ks.counter_sum(kid) == 1


class TestRegister:
    def test_lww_set(self):
        ks = KeySpace()
        kid, _ = ks.get_or_create(b"r", ENC_BYTES, t(1))
        assert ks.register_set(kid, b"a", t(2), node=1)
        assert not ks.register_set(kid, b"b", t(1), node=9)  # older loses
        assert ks.register_get(kid) == b"a"
        # equal time: larger node wins
        assert ks.register_set(kid, b"c", t(2), node=2)
        assert ks.register_get(kid) == b"c"
        assert not ks.register_set(kid, b"d", t(2), node=0)

    def test_type_conflict(self):
        ks = KeySpace()
        ks.get_or_create(b"r", ENC_BYTES, t(1))
        with pytest.raises(InvalidType):
            ks.get_or_create(b"r", ENC_COUNTER, t(2))


class TestElements:
    def test_add_wins_on_tie(self):
        ks = KeySpace()
        kid, _ = ks.get_or_create(b"s", ENC_SET, t(1))
        ks.elem_add(kid, b"m", None, t(5), node=1)
        ks.elem_rem(kid, b"m", t(5))  # same uuid: add wins
        assert [m for m, _, _ in ks.elem_live(kid)] == [b"m"]
        ks.elem_rem(kid, b"m", t(6))
        assert list(ks.elem_live(kid)) == []

    def test_stale_add_rejected_after_removal(self):
        ks = KeySpace()
        kid, _ = ks.get_or_create(b"s", ENC_SET, t(1))
        ks.elem_rem(kid, b"m", t(9))
        assert not ks.elem_add(kid, b"m", None, t(5), node=1)
        assert list(ks.elem_live(kid)) == []
        assert ks.elem_add(kid, b"m", None, t(9), node=1)  # tie: add wins
        assert [m for m, _, _ in ks.elem_live(kid)] == [b"m"]

    def test_dict_values(self):
        ks = KeySpace()
        kid, _ = ks.get_or_create(b"h", ENC_DICT, t(1))
        ks.elem_add(kid, b"f", b"v1", t(2), node=1)
        ks.elem_add(kid, b"f", b"v2", t(3), node=1)
        assert ks.elem_get(kid, b"f") == b"v2"
        ks.elem_rem(kid, b"f", t(4))
        assert ks.elem_get(kid, b"f") is None

    def test_resurrect_key(self):
        ks = KeySpace()
        kid, _ = ks.get_or_create(b"s", ENC_SET, t(1))
        ks.elem_add(kid, b"m", None, t(2), node=1)
        ks.set_delete_time(kid, t(5))
        assert not ks.alive(kid)
        ks.updated_at(kid, t(6))
        assert ks.alive(kid)  # created again at t6


class TestExpiry:
    def test_lazy_expire_on_query(self):
        ks = KeySpace()
        kid, _ = ks.get_or_create(b"k", ENC_BYTES, t(1))
        ks.register_set(kid, b"v", t(1), node=1)
        ks.expire_at(b"k", t(10))
        assert ks.query(b"k", t(5)) == kid and ks.alive(kid)
        assert ks.query(b"k", t(10)) == kid
        assert not ks.alive(kid)
        assert ks.key_deletes[b"k"] == t(10)

    def test_expire_max_merge(self):
        ks = KeySpace()
        ks.get_or_create(b"k", ENC_BYTES, t(1))
        ks.expire_at(b"k", t(10))
        ks.expire_at(b"k", t(5))
        assert int(ks.keys.expire[ks.lookup(b"k")]) == t(10)


class TestGC:
    def test_collects_acked_tombstones_only(self):
        ks = KeySpace()
        kid, _ = ks.get_or_create(b"s", ENC_SET, t(1))
        ks.elem_add(kid, b"a", None, t(2), node=1)
        ks.elem_add(kid, b"b", None, t(2), node=1)
        ks.elem_rem(kid, b"a", t(3))
        ks.elem_rem(kid, b"b", t(8))
        assert ks.gc(t(5)) == 1  # only "a" is past the horizon
        assert ks.el_row(kid, b"a") < 0
        assert ks.el_row(kid, b"b") >= 0
        assert ks.gc(t(9)) == 1
        assert ks.el_row(kid, b"b") < 0

    def test_readded_member_not_collected(self):
        ks = KeySpace()
        kid, _ = ks.get_or_create(b"s", ENC_SET, t(1))
        ks.elem_add(kid, b"m", None, t(2), node=1)
        ks.elem_rem(kid, b"m", t(3))
        ks.elem_add(kid, b"m", None, t(4), node=1)  # re-added: alive again
        ks.gc(t(10))
        assert [m for m, _, _ in ks.elem_live(kid)] == [b"m"]

    def test_dead_rows_compact(self):
        """GC marks rows dead; compaction rebuilds columns + indexes and
        keeps surviving rows addressable."""
        ks = KeySpace()
        kid, _ = ks.get_or_create(b"s", ENC_SET, t(1))
        for i in range(50):
            ks.elem_add(kid, b"m%d" % i, None, t(2), node=1)
        for i in range(40):
            ks.elem_rem(kid, b"m%d" % i, t(3))
        ks.gc(t(10))
        assert ks.el_dead == 40
        ks._compact_elements()
        assert ks.el_dead == 0 and ks.el.n == 10
        live = sorted(m for m, _, _ in ks.elem_live(kid))
        assert live == sorted(b"m%d" % i for i in range(40, 50))
        # rows remain addressable through the rebuilt index
        assert all(ks.el_row(kid, m) >= 0 for m in live)
        ks.elem_add(kid, b"x", None, t(11), node=1)
        assert [m for m, _, _ in ks.elem_live(kid)].count(b"x") == 1

    def test_key_delete_record_gc(self):
        ks = KeySpace()
        ks.get_or_create(b"k", ENC_BYTES, t(1))
        ks.record_key_delete(b"k", t(3))
        ks.gc(t(2))
        assert b"k" in ks.key_deletes
        ks.gc(t(3))
        assert b"k" not in ks.key_deletes


def test_cnt_rank_window_grows_both_directions():
    """The per-rank counter index keeps a (base, array) WINDOW over the
    kid range it has touched; extending it downward and upward must
    preserve previously assigned rows (round-5 index rework)."""
    from constdb_tpu.store.keyspace import KeySpace

    ks = KeySpace()
    # register enough keys that high kids exist
    for i in range(8):
        ks.create_key(b"k%d" % i, 5, ct=1)
    # touch a high kid first (sparse window), then a low one (grow down),
    # then the high one again (must still resolve to the same row)
    hi_row = ks._cnt_row(7, node=42)
    base1, arr1 = ks.cnt_rank_rows[ks.rank_of(42)]
    lo_row = ks._cnt_row(0, node=42)
    hi_again = ks._cnt_row(7, node=42)
    assert hi_again == hi_row and lo_row != hi_row
    # engine-path resolution agrees with the op-path rows
    import numpy as np
    from constdb_tpu.engine.cpu import CpuMergeEngine  # noqa: F401
    # window stays small for a sparse far-away rank
    base, arr = ks.cnt_rank_rows_arr(ks.rank_of(9999), 5_000_000, 5_000_001)
    assert arr.nbytes <= (1 << 13)
    assert base <= 5_000_000 < base + len(arr)
