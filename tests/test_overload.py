"""Overload governance (server/overload.py + the accounting layer).

Covers the four pillars of the overload work: (1) exact incremental
memory accounting — the `used_memory` property test across every write
family, engine path, GC/compaction shrink, and the shards=N == shards=1
summation law; (2) watermark shedding — client data writes shed with the
exact -OOM error while deletes, reads, admin, and replication intake
stay admitted, on both the per-command and coalesced serve paths; (3)
slow-client protection — a non-reading client is disconnected at
CONSTDB_CLIENT_OUTBUF_MAX without touching other connections; (4) boot
resilience + durability satellites — corrupt snapshots quarantine
through the real start_node path, and durable dumps fsync the parent
directory after the atomic rename.

The end-to-end resource-fault certification (firehose convergence,
stalled peer window pause -> eviction -> resync) lives in the chaos
harness (constdb_tpu/chaos/resource.py, run by tests/test_chaos.py and
the ci.sh overload smoke)."""

from __future__ import annotations

import asyncio
import os
import zlib

import numpy as np
import pytest

from constdb_tpu.replica.coalesce import BatchBuilder
from constdb_tpu.resp.codec import encode_msg
from constdb_tpu.resp.message import Arr, Bulk, Err, Int, NoReply
from constdb_tpu.server.commands import COLUMNAR_ENCODERS
from constdb_tpu.server.node import Node
from constdb_tpu.server.overload import OOM_ERR
from constdb_tpu.store.keyspace import KeySpace


# ------------------------------------------------------- accounting truth


def blob_truth(ks: KeySpace) -> int:
    return sum(len(x) for lst in (ks.key_bytes, ks.reg_val,
                                  ks.el_member, ks.el_val)
               for x in lst if x is not None)


def used_truth(ks: KeySpace) -> int:
    numeric = sum(t.n * sum(dt.itemsize for dt in t._spec.values())
                  for t in (ks.keys, ks.cnt, ks.el, ks.tns))
    tns = sum(p.nbytes for p in ks.tns_payload if p is not None)
    return numeric + blob_truth(ks) + tns


def check_exact(ks: KeySpace, where: str) -> None:
    assert ks.blob_bytes == blob_truth(ks), where
    assert ks.tns_bytes == sum(p.nbytes for p in ks.tns_payload
                               if p is not None), where
    assert ks.used_bytes() == used_truth(ks), where


OPS = [  # one op per write family, mixed growth shapes
    (b"set", [b"r1", b"hello"]),
    (b"set", [b"r1", b"a-longer-replacement-value"]),
    (b"incr", [b"c1", b"5"]),
    (b"decr", [b"c1", b"2"]),
    (b"sadd", [b"s1", b"m1", b"m2", b"m3"]),
    (b"srem", [b"s1", b"m2"]),
    (b"hset", [b"h1", b"f1", b"v1"]),
    (b"hset", [b"h1", b"f1", b"value-grew"]),
    (b"hdel", [b"h1", b"f1"]),
    (b"mvset", [b"mv1", b"alpha"]),
    (b"lpush", [b"l1", b"x"]),
    (b"rpush", [b"l1", b"y"]),
    (b"del", [b"r1"]),
]


def test_used_memory_tracks_every_write_family():
    """Accounting invariance: used_memory deltas match recomputed
    column/blob growth after every single op, across every family."""
    node = Node(node_id=1)
    for i, (name, args) in enumerate(OPS):
        reply = node.execute([Bulk(name)] + [Bulk(a) for a in args])
        assert not isinstance(reply, Err), (name, reply)
        check_exact(node.ks, f"op {i}: {name}")
    # tensor family: payload bytes ride tns_bytes
    arr = np.arange(64, dtype="<f4").tobytes()
    r = node.execute([Bulk(b"tensor.set"), Bulk(b"t1"), Bulk(b"sum"),
                      Bulk(b"f32"), Bulk(b"64"), Bulk(arr)])
    assert not isinstance(r, Err), r
    r = node.execute([Bulk(b"tensor.merge"), Bulk(b"t1"), Bulk(arr)])
    assert not isinstance(r, Err), r
    check_exact(node.ks, "tensor ops")


def test_used_memory_tracks_engine_merge_paths():
    """The columnar merge paths (hostbatch group encode + both engines)
    keep the gauge exact — the BlobList accounting covers the engines'
    winner-assignment loops and flush slice writes."""
    from constdb_tpu.engine.tpu import TpuMergeEngine

    for engine in (None, TpuMergeEngine(resident=True, steady=True,
                                        warmup=0)):
        node = Node(node_id=1, engine=engine)
        bb = BatchBuilder(node.ks)
        u0 = 10_000_000
        COLUMNAR_ENCODERS[b"set"](bb, [
            (b"k%d" % (j % 7), 9, u0 + j,
             [None] * 5 + [Bulk(b"k%d" % (j % 7)), Bulk(b"val%04d" % j)])
            for j in range(40)])
        COLUMNAR_ENCODERS[b"sadd"](bb, [
            (b"s%d" % (j % 3), 9, u0 + 100 + j,
             [None] * 5 + [Bulk(b"s%d" % (j % 3)), Bulk(b"mem%d" % j)])
            for j in range(30)])
        node.merge_batches([bb.finalize()])
        node.ensure_flushed()
        check_exact(node.ks, f"engine {getattr(engine, 'name', 'cpu')}")
        if engine is not None:
            engine.close()


def test_used_memory_shrinks_through_gc_and_compaction():
    node = Node(node_id=1)
    for j in range(50):
        node.execute([Bulk(b"sadd"), Bulk(b"s"), Bulk(b"m%02d" % j)])
    for j in range(50):
        node.execute([Bulk(b"srem"), Bulk(b"s"), Bulk(b"m%02d" % j)])
    before = node.ks.used_bytes()
    node.gc()  # standalone: horizon = own clock, tombstones collect
    check_exact(node.ks, "after gc")
    assert node.ks.blob_bytes < before  # member/value blobs freed
    node.ks._compact_elements()
    check_exact(node.ks, "after compaction")
    assert node.ks.el.n == 0  # every row was dead


def test_shard_sum_matches_single():
    """shards=N accounting sums to exactly the shards=1 figure: live
    numeric bytes (not pow2 capacities) + exact blob bytes partition
    with the keys.  Driven through the replication rewrites (the stream
    every shard worker applies)."""
    from constdb_tpu.store.sharded_keyspace import shard_of

    single = Node(node_id=1)
    shards = [Node(node_id=1), Node(node_id=1)]
    u = 10_000_000
    stream = []
    for j in range(60):
        stream.append((b"set", [b"r%d" % (j % 11), b"val-%04d" % j]))
        stream.append((b"cntset", [b"c%d" % (j % 5), b"%d" % j]))
        stream.append((b"sadd", [b"s%d" % (j % 3), b"m%d" % j]))
        stream.append((b"hset", [b"h%d" % (j % 4), b"f%d" % (j % 6),
                                 b"hv%d" % j]))
        if j % 7 == 0:
            stream.append((b"srem", [b"s%d" % (j % 3), b"m%d" % (j - 1)]))
            stream.append((b"delbytes", [b"r%d" % (j % 11)]))
    for name, args in stream:
        u += 7
        margs = [Bulk(a) for a in args]
        single.apply_replicated(name, margs, 9, u)
        shards[shard_of(args[0], 2)].apply_replicated(name, margs, 9, u)
    for n in (single, *shards):
        check_exact(n.ks, "shard member")
    assert sum(s.ks.used_bytes() for s in shards) == \
        single.ks.used_bytes()


# ------------------------------------------------------------ watermarks


def capped_node(cap: int = 4096, soft_pct: float = 50.0) -> Node:
    node = Node(node_id=1)
    node.governor.configure(cap, soft_pct)
    node.governor.check_every = 1
    return node


def fill(node: Node, n: int = 40, size: int = 128) -> None:
    for j in range(n):
        node.execute([Bulk(b"set"), Bulk(b"fill%d" % j),
                      Bulk(b"x" * size)])


def test_soft_watermark_sheds_exact_error():
    node = capped_node()
    fill(node)
    assert node.governor.used_memory() >= node.governor.soft_bytes
    logged = len(node.repl_log)
    keys = node.ks.n_keys()
    shed0 = node.stats.oom_shed_writes
    r = node.execute([Bulk(b"set"), Bulk(b"shed-me"), Bulk(b"v")])
    assert isinstance(r, Err) and r.val == OOM_ERR
    # never partially applied, logged, or replicated
    assert node.ks.lookup(b"shed-me") < 0
    assert len(node.repl_log) == logged and node.ks.n_keys() == keys
    assert node.stats.oom_shed_writes == shed0 + 1
    for name, args in ((b"incr", [b"c", b"1"]),
                       (b"sadd", [b"s", b"m"]),
                       (b"cntundo", [b"c"]),
                       (b"tensor.set", [b"t", b"-", b"f32", b"4",
                                        b"\0" * 16])):
        r = node.execute([Bulk(name)] + [Bulk(a) for a in args])
        assert isinstance(r, Err) and r.val == OOM_ERR, name


def test_exempt_paths_admitted_while_shedding():
    node = capped_node()
    fill(node)
    # reads, deletes, removals, expiry, admin — all admitted
    assert not isinstance(
        node.execute([Bulk(b"get"), Bulk(b"fill0")]), Err)
    assert node.execute([Bulk(b"del"), Bulk(b"fill0")]) == Int(1)
    assert not isinstance(
        node.execute([Bulk(b"expire"), Bulk(b"fill1"), Bulk(b"1000")]),
        Err)
    assert not isinstance(
        node.execute([Bulk(b"info"), Bulk(b"memory")]), Err)
    # replication intake NEVER sheds — the convergence-soundness law
    before = node.stats.oom_shed_writes
    node.apply_replicated(b"set", [Bulk(b"from-peer"), Bulk(b"x" * 512)],
                          9, 1 << 60)
    assert node.ks.lookup(b"from-peer") >= 0
    assert node.stats.oom_shed_writes == before


def test_recovery_unsheds():
    node = capped_node()
    fill(node)
    assert isinstance(
        node.execute([Bulk(b"set"), Bulk(b"nope"), Bulk(b"v")]), Err)
    node.governor.configure(1 << 30)  # operator raises the cap
    r = node.execute([Bulk(b"set"), Bulk(b"yes"), Bulk(b"v")])
    assert not isinstance(r, Err)


def test_capped_node_sheds_while_fanning_out(tmp_path):
    """Accounting completeness under the broadcast plane (round 17): a
    memory-capped node fanning out to FOUR peers — encode-once cache
    and per-link buffers included in used_memory — still sheds with the
    exact -OOM error, never crashes, and every write it DID land
    replicates to all four peers."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from cluster_util import Client, close_cluster, make_cluster

    async def main():
        apps = await make_cluster(5, str(tmp_path))
        try:
            a = apps[0]
            a.node.governor.configure(maxmemory=120_000, soft_pct=60.0)
            a.node.governor.check_every = 8
            c = await Client().connect(a.advertised_addr)
            for peer in apps[1:]:
                await c.cmd("meet", peer.advertised_addr)
            shed = landed = 0
            last_landed = None
            for i in range(600):
                r = await c.cmd("set", f"fan{i:04d}", "x" * 256)
                if isinstance(r, Err):
                    assert r.val == OOM_ERR, r.val
                    shed += 1
                else:
                    landed += 1
                    last_landed = f"fan{i:04d}".encode()
                if shed >= 5 and landed >= 20:
                    break
            assert shed >= 5, "capped fan-out node never shed"
            assert landed >= 20, "everything shed — cap far too low"
            # the cache bytes really are part of the governed total
            assert a.node.governor.used_memory() >= \
                a.node.wire_cache.used_bytes()
            # every landed write reaches all four peers (replication
            # stays admitted and the fan-out keeps flowing while the
            # node sheds client writes)
            deadline = asyncio.get_running_loop().time() + 20.0
            while True:
                ok = all(p.node.ks.lookup(last_landed) >= 0
                         for p in apps[1:])
                if ok:
                    break
                assert asyncio.get_running_loop().time() < deadline, \
                    "landed write never reached all 4 peers"
                await asyncio.sleep(0.05)
            await c.close()
        finally:
            await close_cluster(apps)
    asyncio.run(main())


def test_hard_watermark_reclaims_warm_caches():
    node = capped_node(cap=2048, soft_pct=50.0)
    # grow past the HARD watermark via replication intake — client
    # writes would shed at soft and never get there
    for j in range(10):
        node.apply_replicated(b"set", [Bulk(b"p%d" % j), Bulk(b"x" * 512)],
                              9, (1 << 60) + j)
    node.ks.key_crcs()  # warm a digest crc cache
    assert node.ks._key_crc is not None
    node.governor._last_hard = -10.0  # defeat the rate limit
    r = node.execute([Bulk(b"set"), Bulk(b"x"), Bulk(b"y")])
    assert isinstance(r, Err) and r.val == OOM_ERR
    assert node.governor.state_name == "hard"
    assert node.stats.oom_hard_reclaims >= 1
    assert node.ks._key_crc is None  # warm cache dropped


def test_serve_coalescer_sheds_planned_writes():
    """The pipelined serve path demotes data writes to the per-command
    path while shedding, so they return the exact OOM error and the run
    never plans/lands them; exempt planners (srem) keep riding."""
    from constdb_tpu.server.serve import ServeCoalescer

    node = capped_node()
    node.execute([Bulk(b"sadd"), Bulk(b"s"), Bulk(b"keep")])
    fill(node)
    coal = ServeCoalescer(node, max_run=64)
    logged = len(node.repl_log)
    shed0 = node.stats.oom_shed_writes
    out = bytearray()
    msgs = [Arr([Bulk(b"set"), Bulk(b"a%d" % j), Bulk(b"v")])
            for j in range(6)] + \
        [Arr([Bulk(b"srem"), Bulk(b"s"), Bulk(b"keep")])]
    coal.run_chunk(msgs, out)
    assert bytes(out).count(b"-" + OOM_ERR) == 6, bytes(out)[:200]
    assert b":1\r\n" in bytes(out)  # the srem flip landed
    assert node.ks.lookup(b"a0") < 0
    assert len(node.repl_log) == logged + 1  # only the srem logged
    assert node.stats.oom_shed_writes == shed0 + 6


def test_info_overload_gauges():
    node = capped_node()
    fill(node)
    node.execute([Bulk(b"set"), Bulk(b"x"), Bulk(b"y")])  # refresh state
    reply = node.execute([Bulk(b"info"), Bulk(b"memory")])
    text = bytes(reply.val)
    assert b"used_memory:" in text
    assert b"maxmemory:4096" in text
    assert b"overload_state:" in text and b"overload_state:ok" not in text
    reply = node.execute([Bulk(b"info"), Bulk(b"stats")])
    text = bytes(reply.val)
    for gauge in (b"oom_shed_writes:", b"oom_hard_reclaims:",
                  b"client_outbuf_disconnects:", b"repl_window_pauses:"):
        assert gauge in text, gauge


def test_governor_check_cadence():
    """The gate caches its verdict for check_every calls — pressure is
    observed within one window, not on every single write."""
    node = Node(node_id=1)
    node.governor.configure(4096, 50.0)  # default check_every (64)
    fill(node, n=80, size=256)
    # well past the cap: the NEXT window must shed
    shed = 0
    for j in range(130):
        r = node.execute([Bulk(b"set"), Bulk(b"w%d" % j), Bulk(b"v")])
        shed += isinstance(r, Err)
    assert shed >= 60  # at most one stale window of admits


# ------------------------------------------------------ slow-client cap


def test_outbuf_cap_disconnects_stalled_reader():
    from constdb_tpu.server.io import start_node

    async def run():
        node = Node(node_id=1)
        app = await start_node(node, host="127.0.0.1", port=0,
                               client_outbuf_max=1 << 16)
        try:
            r, w = await asyncio.open_connection("127.0.0.1", app.port)
            w.write(encode_msg(Arr([Bulk(b"set"), Bulk(b"big"),
                                    Bulk(b"x" * (64 << 10))])))
            await w.drain()
            assert (await r.read(5)) == b"+OK\r\n"
            # pipeline 1024 GETs of the 64KB value and stop reading
            w.write(b"".join(encode_msg(Arr([Bulk(b"get"), Bulk(b"big")]))
                             for _ in range(512)))
            await w.drain()
            for _ in range(500):
                if node.stats.client_outbuf_disconnects:
                    break
                await asyncio.sleep(0.02)
            assert node.stats.client_outbuf_disconnects == 1
            # a healthy connection is untouched
            r2, w2 = await asyncio.open_connection("127.0.0.1", app.port)
            w2.write(encode_msg(Arr([Bulk(b"get"), Bulk(b"big")])))
            await w2.drain()
            got = await r2.readexactly(16)
            assert got.startswith(b"$65536\r\n")
            w2.close()
            w.close()
        finally:
            await app.close()

    asyncio.run(run())


# ------------------------------------------- boot resilience + durability


def _dump_node(tmp_path, n_keys: int = 50) -> tuple[Node, str]:
    from constdb_tpu.persist.snapshot import NodeMeta, dump_keyspace

    node = Node(node_id=5, alias="orig")
    for j in range(n_keys):
        node.execute([Bulk(b"set"), Bulk(b"k%d" % j), Bulk(b"v%d" % j)])
    path = str(tmp_path / "boot.snapshot")
    dump_keyspace(path, node.ks,
                  NodeMeta(node_id=5, alias="orig",
                           repl_last_uuid=node.repl_log.last_uuid))
    return node, path


def _boot_and_expect_quarantine(path: str) -> None:
    from constdb_tpu.server.io import start_node

    async def run():
        node = Node()
        app = await start_node(node, host="127.0.0.1", port=0,
                               snapshot_path=path)
        try:
            # booted EMPTY and alive, with the evidence renamed aside
            assert node.ks.n_keys() == 0
            assert node.stats.extra["boot_snapshot_quarantined"] == \
                path + ".corrupt"
            r, w = await asyncio.open_connection("127.0.0.1", app.port)
            w.write(encode_msg(Arr([Bulk(b"set"), Bulk(b"alive"),
                                    Bulk(b"1")])))
            await w.drain()
            assert (await r.read(5)) == b"+OK\r\n"
            w.close()
        finally:
            await app.close()

    asyncio.run(run())
    assert os.path.exists(path + ".corrupt")
    assert not os.path.exists(path)


def test_boot_quarantines_truncated_snapshot(tmp_path):
    _node, path = _dump_node(tmp_path)
    data = open(path, "rb").read()
    open(path, "wb").write(data[: len(data) // 2])
    _boot_and_expect_quarantine(path)


def test_boot_quarantines_bitflipped_snapshot(tmp_path):
    _node, path = _dump_node(tmp_path)
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(path, "wb").write(bytes(data))
    _boot_and_expect_quarantine(path)


def test_clean_snapshot_still_boots(tmp_path):
    from constdb_tpu.server.io import start_node

    _node, path = _dump_node(tmp_path)

    async def run():
        node = Node()
        app = await start_node(node, host="127.0.0.1", port=0,
                               snapshot_path=path)
        try:
            assert node.ks.n_keys() == 50
            assert "boot_snapshot_quarantined" not in node.stats.extra
        finally:
            await app.close()

    asyncio.run(run())


def test_snapshot_fsync_covers_parent_dir(tmp_path, monkeypatch):
    """write_snapshot_file(fsync=True) must fsync the file AND the
    parent directory after os.replace — the rename is atomic but not
    durable until the directory entry syncs."""
    from constdb_tpu.engine.base import batch_from_keyspace
    from constdb_tpu.persist.snapshot import (NodeMeta, dump_keyspace,
                                              write_snapshot_file)

    node = Node(node_id=1)
    node.execute([Bulk(b"set"), Bulk(b"k"), Bulk(b"v")])
    synced: list = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (synced.append(
        os.path.isdir(f"/proc/self/fd/{fd}") if os.path.exists(
            f"/proc/self/fd/{fd}") else False), real_fsync(fd)))
    path = str(tmp_path / "d.snapshot")
    write_snapshot_file(path, NodeMeta(node_id=1), [],
                        [batch_from_keyspace(node.ks)], fsync=True)
    assert True in synced and False in synced, synced  # dir AND file
    synced.clear()
    dump_keyspace(str(tmp_path / "d2.snapshot"), node.ks,
                  NodeMeta(node_id=1), fsync=True)
    assert True in synced and False in synced, synced
    synced.clear()
    write_snapshot_file(str(tmp_path / "d3.snapshot"), NodeMeta(node_id=1),
                        [], [batch_from_keyspace(node.ks)], fsync=False)
    assert not synced  # fsync=False stays fsync-free


def test_snapshot_fsync_env_gate(monkeypatch):
    from constdb_tpu.bin.server import _snapshot_fsync

    monkeypatch.delenv("CONSTDB_SNAPSHOT_FSYNC", raising=False)
    assert _snapshot_fsync() is True
    monkeypatch.setenv("CONSTDB_SNAPSHOT_FSYNC", "0")
    assert _snapshot_fsync() is False
