"""Invariant lint engine (constdb_tpu/analysis): the corpus fires every
rule, the escape hatch + baseline machinery work, and the LIVE TREE is
clean against the committed baseline — the tier-1 gate that keeps the
async/stage/shard disciplines from regressing."""

import os

import pytest

from constdb_tpu import conf
from constdb_tpu.analysis import (ALL_RULES, analyze_paths,
                                  check_readme_registry,
                                  compare_to_baseline, load_baseline,
                                  run_default_analysis)
from constdb_tpu.analysis.__main__ import main as lint_main

CORPUS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "analysis_corpus")


@pytest.fixture(scope="module")
def corpus_findings():
    return analyze_paths([CORPUS], root=CORPUS)


# ------------------------------------------------------------- the corpus

def _by_rule(findings):
    out = {}
    for f in findings:
        out.setdefault(f.rule, []).append(f)
    return out


def test_every_rule_has_corpus(corpus_findings):
    """A rule without a seeded violation is a rule nobody knows works."""
    fired = {f.rule for f in corpus_findings}
    for rule in ALL_RULES:
        assert rule.name in fired, \
            f"{rule.name} has no firing snippet under tests/analysis_corpus"


def test_corpus_expectations(corpus_findings):
    by = _by_rule(corpus_findings)
    # ASYNC-BLOCK: sleep + socket + open + .result() + nested-helper open
    ab = by["ASYNC-BLOCK"]
    assert len(ab) == 5
    assert {f.token for f in ab} == \
        {"time.sleep", "socket.socket", "open", ".result()"}
    assert any("nested" in f.qualname for f in ab)
    # STAGE-PURE: 2 device touches + jax name in stages, 2 heavy calls
    # in dispatch
    sp = by["STAGE-PURE"]
    assert {f.token for f in sp} == \
        {"self._put_batch", "self._jax", "jax", "np.stack",
         "self._combine_groups"}
    # CHECK-THEN-MUTATE: raise-after-mutate + assert-after-append only
    cm = by["CHECK-THEN-MUTATE"]
    assert sorted(f.token for f in cm) == ["assert", "raise"]
    assert all("fixed" not in f.qualname for f in cm)
    # ENV-REGISTRY: direct get, subscript, unregistered helper name
    er = by["ENV-REGISTRY"]
    assert {f.token for f in er} == \
        {"CONSTDB_SECRET_KNOB", "CONSTDB_OTHER_KNOB",
         "CONSTDB_NOT_IN_REGISTRY:unregistered"}
    # SHM-LIFECYCLE: only the unguarded creation (guarded ok, ignore
    # comment honored on the transferred one)
    sh = by["SHM-LIFECYCLE"]
    assert [f.qualname.rsplit(".", 1)[-1] for f in sh] == ["leaky"]
    # BARE-EXCEPT-SWALLOW: the apply path only (narrow + __del__ exempt)
    be = by["BARE-EXCEPT-SWALLOW"]
    assert [f.qualname for f in be] == ["apply_frames"]
    # FORK-CAPTURE: lambda, closure, bound method, self.engine, engine
    fc = by["FORK-CAPTURE"]
    assert all(f.qualname.endswith("spawn_bad") for f in fc)
    assert {f.token for f in fc} == \
        {"lambda", "closure_worker", "self.run_shard", "self.engine",
         "engine"}
    # KEY-CONFINED: second-arg key + underivable key; the clean command
    # and the delegating helper stay silent
    kc = by["KEY-CONFINED"]
    assert {f.token for f in kc} == {"badswap", "nokey"}
    assert not any("good" in f.qualname for f in kc)
    # NATIVE-CONTRACT: the uncovered @serve_plan command (intake
    # direction) + every aof record-type failure mode (drift, python-
    # only type, C-only type); the covered twin (sadd) and the matching
    # REC_BATCH stay silent
    nc = by["NATIVE-CONTRACT"]
    assert {f.token for f in nc} == \
        {"zadd", "smembers:unroutable", "aof:frame:drift",
         "aof:chunk:missing-from-table", "aof:wmark:unknown-record-type"}
    assert [f.qualname for f in nc if f.token == "zadd"] == ["_plan_zadd"]
    assert [f.qualname for f in nc if f.token.endswith(":unroutable")] \
        == ["smembers_command"]
    # AWAIT-ATOMICITY: the PR 2 close-window and PR 12 quiesce-callback
    # race shapes; the post-fix re-reading forms and the pinned
    # deliberate snapshot stay silent
    aa = by["AWAIT-ATOMICITY"]
    assert {f.token for f in aa} == {"links", "pend"}
    assert {f.qualname.rsplit(".", 1)[-1] for f in aa} == \
        {"close_bad", "quiesce_bad"}
    # SLOT-EPOCH: the cached-epoch ownership flip; the re-reading and
    # pinned forms stay silent, and the general AWAIT-ATOMICITY rule
    # does not cover cluster/ (the specialization owns that dir)
    se = by["SLOT-EPOCH"]
    assert {f.token for f in se} == {"epoch"}
    assert [f.qualname for f in se] == ["flip_bad"]
    assert not any(f.path.startswith("cluster") for f in aa)
    # CUT-ORDERING: the PR 11 consistency-cut shape (export awaited
    # before the watermark capture), incl. the some-path branchy case;
    # the capture-first forms stay silent
    co = by["CUT-ORDERING"]
    assert {f.token for f in co} == {"_local_digest", "key_count"}
    assert {f.qualname.rsplit(".", 1)[-1] for f in co} == \
        {"send_delta_bad", "export_branchy_bad"}
    # LOCK-DISCIPLINE: await under a thread lock + blocking IO /
    # .result() under an asyncio lock; the snapshot-then-release and
    # run_in_executor forms stay silent
    ld = by["LOCK-DISCIPLINE"]
    assert {f.token for f in ld} == \
        {"self._crc_lock", "self._stream_lock:open",
         "self._stream_lock:.result()"}
    assert not any("fixed" in f.qualname for f in ld)


def test_findings_have_location_and_hint(corpus_findings):
    for f in corpus_findings:
        assert f.path and f.line > 0 and f.message
        assert f.hint, f"{f.rule} ships without a fix hint"
        assert f.key.startswith(f"{f.rule}:{f.path}:")
        assert f"{f.path}:{f.line}" in f.render()


def test_ignore_escape_hatch(tmp_path):
    bad = tmp_path / "parallel" / "x.py"
    bad.parent.mkdir()
    src = ("from multiprocessing import shared_memory\n"
           "def f(n):\n"
           "    a = shared_memory.SharedMemory(create=True, size=n)\n"
           "    b = shared_memory.SharedMemory(  # lint: ignore[SHM-LIFECYCLE]\n"
           "        create=True, size=n)\n"
           "    return a, b\n")
    bad.write_text(src)
    got = analyze_paths([str(tmp_path)], root=str(tmp_path))
    assert [f.token for f in got] == ["a"], got


# --------------------------------------------------------------- baseline

def test_baseline_growth_detection(corpus_findings):
    from constdb_tpu.analysis.core import baseline_payload
    base = baseline_payload(corpus_findings, notes={})
    # exact tree vs its own baseline: no growth, nothing stale
    growth, stale = compare_to_baseline(corpus_findings, base)
    assert growth == [] and stale == []
    # one more finding with a baselined key -> growth of exactly one
    extra = corpus_findings[0]
    growth, _ = compare_to_baseline(corpus_findings + [extra], base)
    assert len(growth) == 1 and growth[0].key == extra.key
    # removing a finding -> stale key reported, still no growth
    growth, stale = compare_to_baseline(corpus_findings[1:], base)
    assert growth == [] and stale == [corpus_findings[0].key]


def test_live_tree_clean_against_baseline():
    """THE gate: the package + README carry no findings beyond the
    committed baseline (constdb_tpu/analysis/baseline.json)."""
    findings = run_default_analysis() + check_readme_registry()
    growth, _stale = compare_to_baseline(findings, load_baseline())
    assert growth == [], "new lint findings:\n" + \
        "\n".join(f.render() for f in growth)


def test_baselined_keys_carry_notes():
    """Every baselined finding family has a tracking note — a baseline
    entry nobody can explain is just a muted alarm."""
    base = load_baseline()
    notes = base.get("notes", {})
    for key in base.get("findings", {}):
        assert any(key.startswith(p) for p in notes), \
            f"baselined key has no tracking note prefix: {key}"


def test_cli_baseline_mode_green(capsys):
    assert lint_main(["--baseline"]) == 0
    assert "clean" in capsys.readouterr().out


def test_cli_plain_mode_reports(capsys):
    rc = lint_main([CORPUS, "--root", CORPUS])
    out = capsys.readouterr().out
    assert rc == 1 and "finding(s)" in out


def test_cli_json_mode(capsys, corpus_findings):
    """--json: stable keys matching baseline.json, both modes."""
    import json
    rc = lint_main([CORPUS, "--root", CORPUS, "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1 and payload["version"] == 1
    assert len(payload["findings"]) == len(corpus_findings)
    # the counts map IS the baseline.json findings shape
    from constdb_tpu.analysis.core import baseline_payload
    assert payload["counts"] == \
        baseline_payload(corpus_findings, {})["findings"]
    for f in payload["findings"]:
        assert f["key"] == \
            f"{f['rule']}:{f['path']}:{f['qualname']}:{f['token']}"
    # baseline mode: growth/stale keys in the payload, clean -> rc 0
    rc = lint_main(["--baseline", "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0 and payload["baseline"]["growth"] == []


# ----------------------------------------------------------- env registry

def test_registry_documented_in_readme():
    assert check_readme_registry() == []


def test_env_helpers_and_registry_discipline(monkeypatch):
    monkeypatch.setenv("CONSTDB_POOL_FLUSH_MB", "64")
    assert conf.env_int("CONSTDB_POOL_FLUSH_MB", 1536) == 64
    monkeypatch.delenv("CONSTDB_POOL_FLUSH_MB")
    assert conf.env_int("CONSTDB_POOL_FLUSH_MB", 1536) == 1536
    monkeypatch.setenv("CONSTDB_PIPELINE", "0")
    assert conf.env_flag("CONSTDB_PIPELINE", True) is False
    monkeypatch.setenv("CONSTDB_PIPELINE", "1")
    assert conf.env_flag("CONSTDB_PIPELINE", True) is True
    with pytest.raises(KeyError):
        conf.env_str("CONSTDB_NOT_A_REAL_KNOB")
