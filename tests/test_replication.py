"""Multi-node replication: topology, sync, convergence with oracles.

Port of the reference's integration strategy (reference bin/test.rs,
SURVEY.md §4) to an in-process asyncio cluster: randomized concurrent
workloads against ≥3 live nodes with a local oracle model, convergence
asserted by polling canonical CRDT state instead of sleeping.
"""

from __future__ import annotations

import asyncio
import random

import pytest

from constdb_tpu.resp.message import Arr, Bulk, Int, Nil, Simple

from cluster_util import Client, close_cluster, converge, full_mesh, make_cluster


def run(coro):
    asyncio.run(coro)


# ---------------------------------------------------------------- topology

def test_meet_and_transitive_join(tmp_path):
    async def main():
        apps = await make_cluster(3, str(tmp_path))
        try:
            c1 = await Client().connect(apps[0].advertised_addr)
            # pre-existing data on n1 must reach late joiners via full sync
            await c1.cmd("set", "boot", "v1")
            await c1.cmd("incr", "hits")
            # n1 meets n2
            assert await c1.cmd("meet", apps[1].advertised_addr) == Simple(b"OK")
            await converge(apps[:2])
            # n3 meets n2 only — it must discover n1 transitively
            c3 = await Client().connect(apps[2].advertised_addr)
            assert await c3.cmd("meet", apps[1].advertised_addr) == Simple(b"OK")
            await full_mesh(apps)
            await converge(apps)
            assert await c3.cmd("get", "boot") == Bulk(b"v1")
            assert await c3.cmd("get", "hits") == Int(1)
            await c1.close()
            await c3.close()
        finally:
            await close_cluster(apps)
    run(main())


def test_replicas_and_forget(tmp_path):
    async def main():
        apps = await make_cluster(2, str(tmp_path))
        try:
            c1 = await Client().connect(apps[0].advertised_addr)
            await c1.cmd("meet", apps[1].advertised_addr)
            await full_mesh(apps)
            rows = await c1.cmd("replicas")
            assert isinstance(rows, Arr) and len(rows.items) == 1
            assert rows.items[0].items[3] == Bulk(b"alive")
            # forget propagates to the peer too (replicated write)
            assert await c1.cmd("forget", apps[1].advertised_addr) == Int(1)
            rows = await c1.cmd("replicas")
            assert rows.items[0].items[3] == Bulk(b"forgotten")
            await c1.close()
        finally:
            await close_cluster(apps)
    run(main())


def test_forget_sticks_and_remeet_readmits(tmp_path):
    """A forgotten live peer must STAY forgotten: its SYNC attempts are
    rejected (no auto-meet resurrection through the handshake) and it stops
    dialing; an explicit MEET re-admits it and the mesh reconverges."""
    async def main():
        apps = await make_cluster(3, str(tmp_path))
        c = [await Client().connect(a.advertised_addr) for a in apps]
        try:
            await c[0].cmd("meet", apps[1].advertised_addr)
            await c[2].cmd("meet", apps[1].advertised_addr)
            await full_mesh(apps)
            await c[0].cmd("set", "pre", "1")
            await converge(apps)

            victim = apps[2].advertised_addr
            await c[0].cmd("forget", victim)
            await converge([apps[0], apps[1]])

            # give the victim several reconnect rounds to try to come back
            await asyncio.sleep(apps[2].reconnect_delay * 4)
            for app in apps[:2]:
                m = app.node.replicas.get(victim)
                assert m is not None and not m.alive, \
                    f"{app.advertised_addr} resurrected the forgotten peer"
            # the victim learned it was expelled and stopped dialing
            assert all(m.dial_suspended or not m.alive
                       for m in apps[2].node.replicas.peers.values()
                       if m.addr != victim)

            # writes on the surviving mesh do not reach the victim
            await c[0].cmd("set", "while-out", "x")
            await converge([apps[0], apps[1]])
            await asyncio.sleep(apps[2].reconnect_delay)
            got = await c[2].cmd("get", "while-out")
            assert got == Nil()

            # explicit MEET re-admits: full mesh + convergence again
            await c[0].cmd("meet", victim)
            await full_mesh(apps)
            await converge(apps)
            assert await c[2].cmd("get", "while-out") == Bulk(b"x")
        finally:
            for cli in c:
                await cli.close()
            await close_cluster(apps)
    run(main())


def test_full_sync_dump_shared_and_reused(tmp_path):
    """Full syncs stream one shared on-disk dump: two peers syncing at
    once produce ONE dump; a later peer reuses it while the repl_log still
    covers its watermark; a peer arriving after eviction forces a fresh
    dump (reference server.rs:221-250 reuse rule, minus the fork)."""
    async def main():
        apps = await make_cluster(4, str(tmp_path), repl_log_cap=2_000)
        c = [await Client().connect(a.advertised_addr) for a in apps]
        try:
            # enough data that catch-up must go through a full snapshot
            for i in range(300):
                await c[0].cmd("set", f"k{i}", f"v{i}")
            # two peers join concurrently → one dump serves both
            await asyncio.gather(c[1].cmd("meet", apps[0].advertised_addr),
                                 c[2].cmd("meet", apps[0].advertised_addr))
            await converge(apps[:3], timeout=20.0)
            assert apps[0].shared_dump.dumps_taken == 1

            # a later joiner reuses the same dump: no writes happened, the
            # log still covers the dump watermark
            await c[3].cmd("meet", apps[0].advertised_addr)
            await converge(apps, timeout=20.0)
            assert apps[0].shared_dump.dumps_taken == 1

            # evict the log past the dump watermark → next full sync must
            # re-dump (the cached file can no longer be topped up)
            for i in range(300):
                await c[0].cmd("set", f"m{i}", f"w{i}")
            cur = next(d for d in apps[0].shared_dump._current.values()
                       if d is not None)
            assert not apps[0].node.repl_log.can_resume_from(cur.repl_last)
            fresh = (await make_cluster(1, str(tmp_path)))[0]
            try:
                cf = await Client().connect(fresh.advertised_addr)
                await cf.cmd("meet", apps[0].advertised_addr)
                await converge([apps[0], fresh], timeout=20.0)
                await cf.close()
                assert apps[0].shared_dump.dumps_taken == 2
            finally:
                await fresh.close()
        finally:
            for cli in c:
                await cli.close()
            await close_cluster(apps)
    run(main())


# -------------------------------------------------------------- convergence

async def _mesh3(tmp_path, **kw):
    apps = await make_cluster(3, str(tmp_path), **kw)
    c = [await Client().connect(a.advertised_addr) for a in apps]
    await c[0].cmd("meet", apps[1].advertised_addr)
    await c[2].cmd("meet", apps[1].advertised_addr)
    await full_mesh(apps)
    return apps, c


def test_counters_converge(tmp_path):
    """(reference bin/test.rs:123-191 test_counters)"""
    async def main():
        apps, c = await _mesh3(tmp_path)
        rng = random.Random(5)
        try:
            oracle = 0
            for _ in range(300):
                cli = c[rng.randrange(3)]
                if rng.random() < 0.5:
                    await cli.cmd("incr", "cnt")
                    oracle += 1
                else:
                    await cli.cmd("decr", "cnt")
                    oracle -= 1
            await converge(apps)
            for cli in c:
                assert await cli.cmd("get", "cnt") == Int(oracle)
            # interleave DEL: all nodes must still agree afterwards
            for i in range(60):
                cli = c[rng.randrange(3)]
                if i % 10 == 9:
                    await cli.cmd("del", "cnt")
                else:
                    await cli.cmd("incr", "cnt")
            await converge(apps)
            vals = {repr(await cli.cmd("get", "cnt")) for cli in c}
            assert len(vals) == 1
        finally:
            for cli in c:
                await cli.close()
            await close_cluster(apps)
    run(main())


def test_bytes_converge(tmp_path):
    """(reference bin/test.rs:193-220 test_bytes)"""
    async def main():
        apps, c = await _mesh3(tmp_path)
        rng = random.Random(7)
        keys = [f"b{i}" for i in range(5)]
        try:
            for _ in range(150):
                cli = c[rng.randrange(3)]
                k = rng.choice(keys)
                if rng.random() < 0.85:
                    await cli.cmd("set", k, f"v{rng.randrange(1000)}")
                else:
                    await cli.cmd("del", k)
                await asyncio.sleep(0.002)  # ensure HLC ms advances: program
                # order == uuid order, so the LWW winner is the last writer
            await converge(apps)
            for k in keys:
                vals = {repr(await cli.cmd("get", k)) for cli in c}
                assert len(vals) == 1, f"{k}: {vals}"
        finally:
            for cli in c:
                await cli.close()
            await close_cluster(apps)
    run(main())


def test_set_converge_with_oracle(tmp_path):
    """(reference bin/test.rs:222-306 test_set)"""
    async def main():
        apps, c = await _mesh3(tmp_path)
        rng = random.Random(11)
        oracle: set[bytes] = set()
        members = [b"m%d" % i for i in range(12)]
        try:
            for _ in range(200):
                cli = c[rng.randrange(3)]
                m = rng.choice(members)
                if rng.random() < 0.65:
                    await cli.cmd("sadd", b"s", m)
                    oracle.add(m)
                else:
                    await cli.cmd("srem", b"s", m)
                    oracle.discard(m)
                await asyncio.sleep(0.002)
            await converge(apps)
            for cli in c:
                got = await cli.cmd("smembers", b"s")
                assert isinstance(got, Arr)
                assert {i.val for i in got.items} == oracle
        finally:
            for cli in c:
                await cli.close()
            await close_cluster(apps)
    run(main())


def test_dict_converge_with_oracle(tmp_path):
    """(reference bin/test.rs:308-398 test_dict)"""
    async def main():
        apps, c = await _mesh3(tmp_path)
        rng = random.Random(13)
        oracle: dict[bytes, bytes] = {}
        fields = [b"f%d" % i for i in range(10)]
        try:
            for _ in range(200):
                cli = c[rng.randrange(3)]
                f = rng.choice(fields)
                if rng.random() < 0.7:
                    v = b"v%d" % rng.randrange(1000)
                    await cli.cmd("hset", b"h", f, v)
                    oracle[f] = v
                else:
                    await cli.cmd("hdel", b"h", f)
                    oracle.pop(f, None)
                await asyncio.sleep(0.002)
            await converge(apps)
            for cli in c:
                got = await cli.cmd("hgetall", b"h")
                assert isinstance(got, Arr)
                pairs = {kv.items[0].val: kv.items[1].val for kv in got.items}
                assert pairs == oracle
        finally:
            for cli in c:
                await cli.close()
            await close_cluster(apps)
    run(main())


# ------------------------------------------------------------ sync variants

def test_full_sync_large_keyspace(tmp_path):
    """A joiner pulls a multi-chunk snapshot through the MergeEngine."""
    async def main():
        apps = await make_cluster(2, str(tmp_path), snapshot_chunk_keys=128)
        try:
            n1 = apps[0].node
            c1 = await Client().connect(apps[0].advertised_addr)
            for i in range(700):
                kind = i % 3
                if kind == 0:
                    await c1.cmd("incr", f"k{i}")
                elif kind == 1:
                    await c1.cmd("set", f"k{i}", f"v{i}")
                else:
                    await c1.cmd("sadd", f"k{i}", "a", "b")
            await c1.cmd("meet", apps[1].advertised_addr)
            await converge(apps, timeout=30.0)
            assert apps[1].node.ks.n_keys() == n1.ks.n_keys()
            await c1.close()
        finally:
            await close_cluster(apps)
    run(main())


def test_partial_resync_after_restart(tmp_path):
    """A peer that goes away and returns within the repl_log window gets an
    incremental stream, not a snapshot (reference push.rs:91-111)."""
    async def main():
        apps = await make_cluster(2, str(tmp_path))
        try:
            c1 = await Client().connect(apps[0].advertised_addr)
            await c1.cmd("set", "a", "1")
            await c1.cmd("meet", apps[1].advertised_addr)
            await converge(apps)

            # take n2 offline
            await apps[1].close()
            for _ in range(20):
                await c1.cmd("incr", "cnt")

            # restart n2's server on the same port with the same state
            from constdb_tpu.server.io import ServerApp
            app2 = ServerApp(apps[1].node, host="127.0.0.1",
                             port=apps[1].port, work_dir=str(tmp_path),
                             heartbeat=0.15, reconnect_delay=0.25)
            await app2.start()
            apps[1] = app2
            full_before = apps[0].node.stats.repl_full_syncs
            await converge(apps, timeout=20.0)
            c2 = await Client().connect(app2.advertised_addr)
            assert await c2.cmd("get", "cnt") == Int(20)
            assert apps[0].node.stats.repl_full_syncs == \
                full_before, "partial resync must not dump a snapshot"
            await c1.close()
            await c2.close()
        finally:
            await close_cluster(apps)
    run(main())


def test_full_resync_after_log_eviction(tmp_path):
    """A peer that falls off the repl_log ring gets a fresh snapshot
    mid-stream (the reference leaves this TODO — pull.rs:167-172)."""
    async def main():
        apps = await make_cluster(2, str(tmp_path), repl_log_cap=2_000)
        try:
            c1 = await Client().connect(apps[0].advertised_addr)
            await c1.cmd("meet", apps[1].advertised_addr)
            await converge(apps)
            await apps[1].close()
            # push far more bytes than the ring holds
            for i in range(300):
                await c1.cmd("set", f"k{i}", "x" * 32)

            from constdb_tpu.server.io import ServerApp
            app2 = ServerApp(apps[1].node, host="127.0.0.1",
                             port=apps[1].port, work_dir=str(tmp_path),
                             heartbeat=0.15, reconnect_delay=0.25)
            await app2.start()
            apps[1] = app2
            await converge(apps, timeout=20.0)
            assert apps[0].node.stats.repl_full_syncs >= 1
            await c1.close()
        finally:
            await close_cluster(apps)
    run(main())


def test_gc_after_acks(tmp_path):
    """Tombstones are physically collected once every peer acked past them
    (reference server.rs:257-263 → db.rs:82-119)."""
    async def main():
        apps, c = await _mesh3(tmp_path)
        try:
            await c[0].cmd("sadd", "s", "a", "b", "c")
            await converge(apps)
            await c[0].cmd("srem", "s", "b")
            await converge(apps)
            # all peers ack; gc cron should eventually drop the tombstone row
            deadline = asyncio.get_running_loop().time() + 10.0
            while True:
                n1 = apps[0].node
                live = [m for m, *_ in n1.ks.elem_all(
                    n1.ks.lookup(b"s"))]
                if b"b" not in live:
                    break
                if asyncio.get_running_loop().time() > deadline:
                    raise AssertionError("tombstone never collected")
                await asyncio.sleep(0.1)
        finally:
            for cli in c:
                await cli.close()
            await close_cluster(apps)
    run(main())


def test_full_sync_grouped_merge(tmp_path):
    """Multi-chunk snapshot apply batches chunks into merge_many groups
    (the fold-capable production cadence — link.py apply_group)."""
    async def main():
        from constdb_tpu.engine.tpu import TpuMergeEngine
        apps = await make_cluster(2, str(tmp_path), snapshot_chunk_keys=64,
                                  engine=TpuMergeEngine())
        try:
            c1 = await Client().connect(apps[0].advertised_addr)
            for i in range(600):
                if i % 2:
                    await c1.cmd("incr", f"g{i}")
                else:
                    await c1.cmd("sadd", f"g{i}", "x", "y")
            # force FULL sync: pretend the history below the current uuid
            # fell off the ring (a joiner resuming at 0 must get a snapshot)
            n1 = apps[0].node
            n1.repl_log.evicted_up_to = n1.repl_log.last_uuid
            await c1.cmd("meet", apps[1].advertised_addr)
            await converge(apps, timeout=30.0)
            x = apps[1].node.stats.extra
            # the joiner applied >1 chunk per engine call at least once
            assert x.get("group_merges", 0) >= 1, x
            assert x.get("group_merge_batches", 0) > x.get("group_merges", 0), x
            await c1.close()
        finally:
            await close_cluster(apps)
    run(main())


def test_cpu_catchup_keeps_loop_live(tmp_path):
    """Client RTT on the JOINING node stays bounded while it merges a large
    full sync with the per-row CPU engine (the adaptive split in
    link.py apply_group; reference pull.rs:66,92 yields between batches)."""
    async def main():
        import numpy as np
        from bench import make_workload
        apps = await make_cluster(2, str(tmp_path),
                                  snapshot_chunk_keys=1 << 16,
                                  sync_merge_budget=0.05)
        try:
            # populate n1's keyspace in bulk (fast vectorized ingest), then
            # let n2 catch up through its (slow, per-row) CPU engine
            from constdb_tpu.engine.tpu import TpuMergeEngine
            n1 = apps[0].node
            batch = make_workload(40_000, 1, seed=11)[0]
            TpuMergeEngine().merge(n1.ks, batch)
            n1.ks.version += 1
            top = int(batch.key_mt.max())
            n1.hlc.observe(top)
            # bulk-ingested state is not in the repl_log: joiners must get
            # a snapshot, never a silently-empty PARTSYNC (io.py start_node
            # applies the same rule after a boot restore)
            n1.repl_log.last_uuid = top
            n1.repl_log.evicted_up_to = top

            c2 = await Client().connect(apps[1].advertised_addr)
            await c2.cmd("meet", apps[0].advertised_addr)
            loop = asyncio.get_running_loop()
            worst = 0.0
            deadline = loop.time() + 60.0
            while apps[1].node.ks.n_keys() < 40_000:
                t0 = loop.time()
                await c2.cmd("ping")
                worst = max(worst, loop.time() - t0)
                if loop.time() > deadline:
                    raise AssertionError("catch-up did not finish in 60s")
                await asyncio.sleep(0.01)
            assert worst < 1.0, f"loop wedged {worst:.2f}s during catch-up"
            await c2.close()
        finally:
            await close_cluster(apps)
    run(main())


# ---------------------------------------------------- full-sync compression

def test_full_sync_stream_is_compressed(tmp_path):
    """The on-wire full-sync stream IS the shared dump file, so the zlib
    column compression rides the link end-to-end (conf
    snapshot_compress_level; the reference streams raw —
    src/conn/writer.rs:92-112).  Compressed transfer must move strictly
    fewer bytes than raw for the same keyspace, and still converge."""
    async def main():
        sizes = {}
        for level in (0, 1):
            # wire_compress=False pins the PLAIN dump variant — the
            # byte stream a pre-CAP_COMPRESS peer receives, whose
            # section-level compression this test certifies (the
            # container variant is covered by tests/test_wire_compress)
            apps = await make_cluster(2, str(tmp_path),
                                      snapshot_compress_level=level,
                                      wire_compress=False)
            try:
                a, b = apps
                c = await Client().connect(a.advertised_addr)
                for i in range(400):
                    # highly compressible values — the realistic shape for
                    # telemetry/counter-style payloads
                    await c.cmd("set", f"key:{i:06d}", "v" * 128)
                    await c.cmd("sadd", f"set:{i % 20}", f"member:{i:06d}")
                # force the full-sync path: fence the log like a restored
                # node (a MEET now cannot partial-sync)
                top = a.node.repl_log.last_uuid
                a.node.repl_log.evicted_up_to = top
                await c.cmd("meet", b.advertised_addr)
                await converge(apps, timeout=20.0)
                sizes[level] = a.node.stats.extra["last_snapshot_bytes"]
                assert a.node.stats.repl_full_syncs >= 1
                got = await c.cmd("get", "key:000399")
                assert got == Bulk(b"v" * 128)
                await c.close()
            finally:
                await close_cluster(apps)
        assert sizes[1] < sizes[0], sizes
    run(main())
