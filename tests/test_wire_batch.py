"""Batch wire protocol (REPLBATCH): codec, push loop, receiver intake.

The load-bearing claims, each pinned here:
  * codec roundtrip is exact — a run group-encoded on the pusher and
    decoded on the receiver lands byte-identically to the per-frame
    path, element key-delete rule included (evaluated against the
    RECEIVING store);
  * the push loop ships runs of consecutive encodable ops as REPLBATCH
    frames, breaks runs at barriers, and degenerates to the byte-exact
    per-frame stream for legacy peers and CONSTDB_WIRE_BATCH=1;
  * every-prefix truncation and every bit flip of a payload raise
    WireFormatError — a batch decodes whole or advances nothing;
  * a malformed payload demotes that peer to per-frame delivery LOUDLY
    (counter + batch_wire_off + the capability disappears from the next
    handshake) without desyncing the stream (watermark untouched);
  * per-batch delivery bookkeeping: duplicate batches skip, gapped
    batches raise ReplicateCommandsLost, the watermark advances only
    after the covering batch lands;
  * MergedReplLog.run_after emits maximal single-segment runs that
    never violate HLC order or cross the floor, and concatenated runs
    replay to the identical per-op stream;
  * the receiver REPLACKs once per landed batch (EVENT_PULL_LANDED),
    with watermark/beacon advancement unchanged vs per-frame acks.
"""

import asyncio
import os
import random
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from test_link_pushloop import _Writer, _mk_link  # noqa: E402

from constdb_tpu.errors import CstError, ReplicateCommandsLost  # noqa: E402
from constdb_tpu.replica import wire  # noqa: E402
from constdb_tpu.replica.coalesce import CoalescingApplier  # noqa: E402
from constdb_tpu.replica.link import (CAP_BATCH_STREAM,  # noqa: E402
                                      PARTSYNC, REPLACK, REPLBATCH,
                                      REPLICATE, my_caps)
from constdb_tpu.replica.manager import ReplicaMeta  # noqa: E402
from constdb_tpu.resp.codec import encode_msg, make_parser  # noqa: E402
from constdb_tpu.resp.message import (Arr, Bulk, Int, as_bytes,  # noqa: E402
                                      as_int)
from constdb_tpu.server.node import Node  # noqa: E402
from constdb_tpu.server.repl_log import MergedReplLog  # noqa: E402
from constdb_tpu.utils.hlc import SEQ_BITS  # noqa: E402

MS0 = 1_700_000_000_000


def u(i: int) -> int:
    return (MS0 + i) << SEQ_BITS


def mixed_bodies(n: int, seed: int = 3, keys: int = 60):
    """Deterministic op bodies covering every encodable command plus
    barrier classes (the test_coalesce_apply mix, entry-shaped)."""
    rng = random.Random(seed)
    out = []
    for i in range(1, n + 1):
        r = rng.random()
        k = b"k%03d" % rng.randrange(keys)
        if r < 0.22:
            f = (b"set", b"r" + k, b"v%d" % i)
        elif r < 0.40:
            f = (b"cntset", b"c" + k, rng.randrange(-50, 50))
        elif r < 0.56:
            f = (b"sadd", b"s" + k, b"m%d" % rng.randrange(10),
                 b"m%d" % rng.randrange(10))
        elif r < 0.64:
            f = (b"hset", b"h" + k, b"f%d" % rng.randrange(6), b"v%d" % i)
        elif r < 0.70:
            f = (b"srem", b"s" + k, b"m%d" % rng.randrange(10))
        elif r < 0.74:
            f = (b"hdel", b"h" + k, b"f%d" % rng.randrange(6))
        elif r < 0.78:
            f = (b"lins", b"l" + k, b"p%04d" % i, b"val%d" % i)
        elif r < 0.80:
            f = (b"lremat", b"l" + k, b"p%04d" % (i - 1))
        elif r < 0.86:
            f = (b"delbytes", b"r" + k)
        elif r < 0.90:
            f = (b"delcnt", b"c" + k, 7, rng.randrange(50))
        elif r < 0.95:
            f = (b"delset", b"s" + k)       # barrier: breaks runs
        else:
            f = (b"meet", b"10.9.9.%d:7%03d" % (rng.randrange(9), i % 999))
        out.append(f)
    return out


def fill_log(node: Node, bodies) -> list:
    """Push op bodies into the node's repl_log; returns the entries."""
    for i, body in enumerate(bodies, 1):
        args = [Int(a) if isinstance(a, int) else Bulk(a)
                for a in body[1:]]
        node.repl_log.push(u(i), body[0], args)
    return node.repl_log.run_after(0, len(bodies) + 1)


def perframe_reference(entries, origin: int = 7) -> Node:
    """The oracle: every entry applied on the exact per-frame path."""
    node = Node(node_id=99)
    ap = CoalescingApplier(node, ReplicaMeta("oracle:1"), max_frames=1)
    prev = 0
    for e in entries:
        ap.apply([Bulk(b"replicate"), Int(origin), Int(prev), Int(e.uuid),
                  Bulk(e.name), *e.args])
        prev = e.uuid
    ap.flush()
    return node


def scan(buf: bytes):
    """Parse a written stream into (kind, items) tuples."""
    parser = make_parser()
    parser.feed(bytes(buf))
    out = []
    while (msg := parser.next_msg()) is not None:
        items = msg.items if isinstance(msg, Arr) else None
        assert items, f"unexpected frame {msg!r}"
        out.append((as_bytes(items[0]).lower(), items))
    return out


# ------------------------------------------------------------ codec unit


def test_codec_roundtrip_equals_per_frame():
    pusher = Node(node_id=7)
    bodies = [b for b in mixed_bodies(600)
              if b[0] not in (b"delset", b"meet")]  # encodable run
    entries = fill_log(pusher, bodies)
    payload = wire.build_wire_batch(entries, 7)
    assert payload is not None
    n2 = Node(node_id=2)
    wb = wire.decode_wire_batch(payload, n2.ks, 7, entries[0].prev_uuid)
    assert wb.n_frames == len(entries)
    n2.merge_stream_batch(wb, wb.n_frames)
    want = perframe_reference(entries)
    assert n2.canonical() == want.canonical()
    # the wire is the point: columnar payload well under the per-frame
    # RESP bytes for the same run
    per_frame = sum(len(encode_msg(Arr([
        Bulk(b"replicate"), Int(7), Int(e.prev_uuid), Int(e.uuid),
        Bulk(e.name), *e.args]))) for e in entries)
    assert len(payload) * 3 <= per_frame, \
        f"payload {len(payload)}B vs per-frame {per_frame}B"


def test_codec_key_delete_rule_runs_on_receiver():
    """An element add below the RECEIVER's key delete time must land
    tombstoned — the dt rule evaluates against the receiving store."""
    pusher = Node(node_id=7)
    entries = fill_log(pusher, [(b"sadd", b"s1", b"m1"),
                                (b"sadd", b"s2", b"m2")])
    payload = wire.build_wire_batch(entries, 7)

    def receiver_with_delete():
        n = Node(node_id=2)
        ap = CoalescingApplier(n, ReplicaMeta("x:1"), max_frames=1)
        # a LOCAL delete of s1 newer than both adds
        ap.apply([Bulk(b"replicate"), Int(9), Int(0), Int(u(50)),
                  Bulk(b"delset"), Bulk(b"s1")])
        return n

    n_batch = receiver_with_delete()
    wb = wire.decode_wire_batch(payload, n_batch.ks, 7,
                                entries[0].prev_uuid)
    n_batch.merge_stream_batch(wb, wb.n_frames)
    n_frame = receiver_with_delete()
    ap = CoalescingApplier(n_frame, ReplicaMeta("y:1"), max_frames=1)
    prev = 0
    for e in entries:
        ap.apply([Bulk(b"replicate"), Int(7), Int(prev), Int(e.uuid),
                  Bulk(e.name), *e.args])
        prev = e.uuid
    assert n_batch.canonical() == n_frame.canonical()
    canon = n_batch.canonical()
    # s1's add predates the local delete: the member lands tombstoned at
    # the key's delete time; s2's add (no local delete) lands live
    s1_members = canon[b"s1"][5]
    assert any(m[0] == b"m1" and m[3] == u(50) for m in s1_members), \
        s1_members
    s2_members = canon[b"s2"][5]
    assert any(m[0] == b"m2" and m[3] == 0 for m in s2_members), s2_members


def test_unencodable_run_returns_none():
    pusher = Node(node_id=7)
    entries = fill_log(pusher, [(b"set", b"k1", b"v"),
                                (b"meet", b"10.0.0.1:9")])
    assert wire.build_wire_batch(entries, 7) is None  # KeyError: meet


# ------------------------------------------------------------------ fuzz


def test_every_prefix_truncation_raises():
    pusher = Node(node_id=7)
    bodies = [b for b in mixed_bodies(40, seed=11)
              if b[0] not in (b"delset", b"meet")]
    entries = fill_log(pusher, bodies)
    payload = wire.build_wire_batch(entries, 7)
    ks = Node(node_id=2).ks
    base = entries[0].prev_uuid
    for cut in range(len(payload)):
        with pytest.raises(wire.WireFormatError):
            wire.decode_wire_batch(payload[:cut], ks, 7, base)


def test_every_bit_flip_raises():
    """crc32 integrity: ANY single-byte corruption fails the decode
    loudly (sampling every byte position, one flip each)."""
    pusher = Node(node_id=7)
    entries = fill_log(pusher, [(b"set", b"k%d" % i, b"v%d" % i)
                                for i in range(20)])
    payload = bytearray(wire.build_wire_batch(entries, 7))
    ks = Node(node_id=2).ks
    base = entries[0].prev_uuid
    for pos in range(len(payload)):
        payload[pos] ^= 0x5A
        with pytest.raises(wire.WireFormatError):
            wire.decode_wire_batch(bytes(payload), ks, 7, base)
        payload[pos] ^= 0x5A
    # the restored payload still decodes (the loop really was the flip)
    wire.decode_wire_batch(bytes(payload), ks, 7, base)


def test_trailing_garbage_raises():
    pusher = Node(node_id=7)
    entries = fill_log(pusher, [(b"set", b"k1", b"v"), (b"set", b"k2", b"w")])
    payload = wire.build_wire_batch(entries, 7)
    ks = Node(node_id=2).ks
    with pytest.raises(wire.WireFormatError):
        wire.decode_wire_batch(payload + b"x", ks, 7, entries[0].prev_uuid)


# --------------------------------------------------------- receiver intake


def batch_frame(entries, origin: int = 7):
    payload = wire.build_wire_batch(entries, origin)
    assert payload is not None
    return [Bulk(REPLBATCH), Int(origin), Int(entries[0].prev_uuid),
            Int(entries[-1].uuid), Int(len(entries)), Bulk(payload)]


def test_batch_dup_gap_and_watermark_after_land():
    pusher = Node(node_id=7)
    entries = fill_log(pusher, [(b"set", b"k%d" % i, b"v%d" % i)
                                for i in range(16)])
    a, b = entries[:8], entries[8:]
    node = Node(node_id=2)
    meta = ReplicaMeta("peer:1")
    ap = CoalescingApplier(node, meta, max_frames=64)
    ap.apply_wire_batch(batch_frame(a))
    assert meta.uuid_he_sent == a[-1].uuid  # landed => watermark covers it
    assert node.stats.repl_wire_batches_in == 1
    # duplicate redelivery: skipped whole, nothing re-merged
    flushes = node.stats.repl_coalesce_flushes
    ap.apply_wire_batch(batch_frame(a))
    assert node.stats.repl_coalesce_flushes == flushes
    assert meta.uuid_he_sent == a[-1].uuid
    # a gapped batch tears the stream down exactly like a gapped frame
    with pytest.raises(ReplicateCommandsLost):
        ap.apply_wire_batch(batch_frame(b[2:]))
    assert meta.uuid_he_sent == a[-1].uuid
    # the covering batch lands and the watermark follows
    ap.apply_wire_batch(batch_frame(b))
    assert meta.uuid_he_sent == b[-1].uuid
    assert node.canonical() == perframe_reference(entries).canonical()


def test_malformed_payload_demotes_loudly():
    pusher = Node(node_id=7)
    entries = fill_log(pusher, [(b"set", b"k%d" % i, b"v") for i in range(6)])
    frame = batch_frame(entries)
    frame[5] = Bulk(as_bytes(frame[5])[:-3] + b"zzz")  # corrupt payload
    node = Node(node_id=2)
    meta = ReplicaMeta("peer:1")
    ap = CoalescingApplier(node, meta, max_frames=64)
    with pytest.raises(CstError):
        ap.apply_wire_batch(frame)
    assert node.stats.repl_wire_demotions == 1
    assert meta.batch_wire_off is True
    assert meta.uuid_he_sent == 0, "a bad batch must not advance anything"
    # the next handshake stops inviting batches from this peer
    class _App:
        pass
    assert not (my_caps(_App(), meta) & CAP_BATCH_STREAM)
    assert my_caps(_App()) & CAP_BATCH_STREAM
    # the stream itself is not poisoned: per-frame redelivery lands
    prev = 0
    for e in entries:
        ap.apply([Bulk(b"replicate"), Int(7), Int(prev), Int(e.uuid),
                  Bulk(e.name), *e.args])
        prev = e.uuid
    ap.flush()
    assert meta.uuid_he_sent == entries[-1].uuid


def test_header_payload_frame_count_mismatch_is_malformed():
    pusher = Node(node_id=7)
    entries = fill_log(pusher, [(b"set", b"k%d" % i, b"v") for i in range(4)])
    frame = batch_frame(entries)
    frame[4] = Int(3)  # header lies about n
    node = Node(node_id=2)
    ap = CoalescingApplier(node, ReplicaMeta("peer:1"), max_frames=64)
    with pytest.raises(CstError):
        ap.apply_wire_batch(frame)
    assert node.stats.repl_wire_demotions == 1


# ------------------------------------------------------------- push loop


def drive_pushloop(tmp_path, bodies, peer_caps, app_tweaks=None,
                   rounds=400):
    """Run a real _push_loop over a filled log into a stub writer until
    the stream covers the last uuid; returns (node, writer, frames)."""
    async def main():
        node, app, link = _mk_link(tmp_path)
        for k, v in (app_tweaks or {}).items():
            setattr(app, k, v)
        last = 0
        for i, body in enumerate(bodies, 1):
            args = [Int(a) if isinstance(a, int) else Bulk(a)
                    for a in body[1:]]
            node.repl_log.push(u(i), body[0], args)
            last = u(i)
        link._peer_caps = peer_caps
        writer = _Writer()
        task = asyncio.create_task(link._push_loop(writer, peer_resume=0))
        try:
            for _ in range(rounds):
                await asyncio.sleep(0.01)
                frames = scan(writer.buf)
                covered = 0
                for kind, items in frames:
                    if kind == REPLICATE:
                        covered = as_int(items[3])
                    elif kind == REPLBATCH:
                        covered = as_int(items[3])
                if covered >= last:
                    break
        finally:
            task.cancel()
        return node, writer, scan(writer.buf)
    return asyncio.run(main())


def replay_stream_frames(frames, origin=1) -> Node:
    """Feed a scanned wire stream through a receiver applier."""
    node = Node(node_id=55)
    ap = CoalescingApplier(node, ReplicaMeta("rcv:1"), max_frames=64)
    for kind, items in frames:
        if kind == REPLICATE:
            ap.apply(items)
        elif kind == REPLBATCH:
            ap.apply_wire_batch(items)
        elif kind in (PARTSYNC, REPLACK):
            pass
        else:
            raise AssertionError(f"unexpected frame {kind!r}")
    ap.flush()
    return node


def test_pushloop_ships_runs_as_batches(tmp_path):
    bodies = mixed_bodies(500, seed=5)
    node, writer, frames = drive_pushloop(tmp_path, bodies,
                                          CAP_BATCH_STREAM)
    kinds = [k for k, _ in frames]
    assert REPLBATCH in kinds
    st = node.stats
    assert st.repl_wire_batches_out == kinds.count(REPLBATCH)
    assert st.repl_wire_batch_frames_out > kinds.count(REPLICATE)
    assert st.repl_wire_bytes_out > 0
    assert st.extra.get("repl_wire_encode_demotions", 0) == 0
    # barriers (delset/meet) broke runs and shipped per-frame (lone
    # encodable ops stranded between barriers legitimately do too)
    perframe_names = {as_bytes(items[4]).lower()
                      for k, items in frames if k == REPLICATE}
    assert {b"delset", b"meet"} <= perframe_names, perframe_names
    # the receiver lands the stream identically to the per-frame oracle
    # (origin = the pushing node's id, exactly what the wire stamps)
    got = replay_stream_frames(frames)
    entries = node.repl_log.run_after(0, len(bodies) + 1)
    want = perframe_reference(entries, origin=node.node_id)
    assert got.canonical() == want.canonical()


def test_legacy_peer_stream_is_byte_exact(tmp_path):
    """peer_caps without CAP_BATCH_STREAM: the wire opens with the exact
    pre-PR per-frame byte stream — PARTSYNC then every entry as a plain
    REPLICATE frame, byte for byte."""
    bodies = mixed_bodies(120, seed=9)
    node, writer, frames = drive_pushloop(tmp_path, bodies, peer_caps=0)
    want = bytearray(encode_msg(Arr([Bulk(PARTSYNC)])))
    for e in node.repl_log.run_after(0, len(bodies) + 1):
        want += encode_msg(Arr([
            Bulk(REPLICATE), Int(node.node_id), Int(e.prev_uuid),
            Int(e.uuid), Bulk(e.name), *e.args]))
    assert bytes(writer.buf[:len(want)]) == bytes(want)
    assert node.stats.repl_wire_batches_out == 0


def test_wire_batch_one_degenerates(tmp_path):
    """CONSTDB_WIRE_BATCH=1 (app.wire_batch=1): per-frame stream even
    for a capable peer, and the capability is not advertised."""
    bodies = mixed_bodies(80, seed=2)
    node, writer, frames = drive_pushloop(
        tmp_path, bodies, CAP_BATCH_STREAM, app_tweaks={"wire_batch": 1})
    assert all(k != REPLBATCH for k, _ in frames)
    assert node.stats.repl_wire_batches_out == 0

    class _App:
        wire_batch = 1
    assert not (my_caps(_App()) & CAP_BATCH_STREAM)


def test_apply_batch_one_withholds_the_capability():
    """CONSTDB_APPLY_BATCH=1 pins the whole replication intake to the
    per-frame apply path — inviting REPLBATCH frames would route ops
    through the columnar merge engine the pin exists to bypass."""
    class _App:
        apply_batch = 1
    assert not (my_caps(_App()) & CAP_BATCH_STREAM)

    class _Capable:
        apply_batch = 512
    assert my_caps(_Capable()) & CAP_BATCH_STREAM


def test_run_after_byte_cap():
    """A backlog of huge values must not balloon one wire frame: the
    run cuts at the byte cap (but always carries >= 1 entry)."""
    node = Node(node_id=1, repl_log_cap=1 << 30)
    big = b"x" * (1 << 16)
    for i in range(1, 33):
        node.repl_log.push(u(i), b"set", [Bulk(b"k%d" % i), Bulk(big)])
    run = node.repl_log.run_after(0, 512, 1 << 18)
    assert 1 <= len(run) <= 4  # ~64KB entries under a 256KB cap
    # a lone oversized entry still ships whole
    assert len(node.repl_log.run_after(0, 512, 16)) == 1
    # uncapped behavior is unchanged
    assert len(node.repl_log.run_after(0, 512)) == 32


# ---------------------------------------------- merged-log run extraction


def test_merged_log_run_extraction_property():
    """MergedReplLog.run_after: runs are single-segment, never out of
    HLC order, never cross the floor, and concatenated runs replay to
    the identical per-op stream (satellite: run-extraction property)."""
    rng = random.Random(17)
    for trial in range(20):
        n_shards = rng.randrange(1, 5)
        merged = MergedReplLog(n_shards, cap_bytes=1 << 24)
        uuids = []
        for i in range(1, rng.randrange(50, 300)):
            seg = rng.randrange(n_shards + 1)
            merged.segments[seg].push(u(i), b"set",
                                      [Bulk(b"k%d" % i), Bulk(b"v")])
            uuids.append(u(i))
        floor_val = [None]
        merged.floor = lambda: floor_val[0]
        if rng.random() < 0.5:
            floor_val[0] = uuids[rng.randrange(len(uuids))]
        # oracle: the per-op merged stream under the same floor
        expected = []
        cursor = 0
        while (e := merged.next_after(cursor)) is not None:
            expected.append(e.uuid)
            cursor = e.uuid
        # extraction: concatenated runs with random caps
        got = []
        cursor = 0
        while True:
            run = merged.run_after(cursor, rng.randrange(1, 40))
            if not run:
                # a run bounded to zero length by another segment's next
                # entry still has a nonempty per-op stream — but only
                # the FLOOR can bound the FIRST entry away
                assert merged.next_after(cursor) is None
                break
            segs = {id(s) for s in merged.segments
                    if any(s.at(e.uuid) is e for e in run)}
            assert len(segs) == 1, "run spans segments"
            for e in run:
                assert e.uuid > cursor, "run out of HLC order"
                if floor_val[0] is not None:
                    assert e.uuid < floor_val[0], "run crossed the floor"
                cursor = e.uuid
            got.extend(e.uuid for e in run)
        assert got == expected, f"trial {trial}: replay diverged"


def test_merged_log_runs_interleave_in_hlc_order():
    """Two segments with interleaved uuids: no run may contain an entry
    newer than another segment's pending one."""
    merged = MergedReplLog(1, cap_bytes=1 << 24)
    s0, s1 = merged.segments[0], merged.segments[1]
    s0.push(u(1), b"set", [Bulk(b"a"), Bulk(b"v")])
    s0.push(u(2), b"set", [Bulk(b"b"), Bulk(b"v")])
    s1.push(u(3), b"set", [Bulk(b"c"), Bulk(b"v")])
    s0.push(u(4), b"set", [Bulk(b"d"), Bulk(b"v")])
    run = merged.run_after(0, 100)
    assert [e.uuid for e in run] == [u(1), u(2)]  # bounded by s1's u(3)
    run = merged.run_after(u(2), 100)
    assert [e.uuid for e in run] == [u(3)]
    run = merged.run_after(u(3), 100)
    assert [e.uuid for e in run] == [u(4)]


# ------------------------------------------------------- REPLACK batching


def test_replack_once_per_landed_batch(tmp_path):
    """The receiver acks once per covering land (EVENT_PULL_LANDED
    wake), not per frame and not a heartbeat later — and the watermark
    it acks matches the per-frame applier's advancement exactly."""
    async def main():
        node, app, link = _mk_link(tmp_path)
        app.heartbeat = 30.0  # isolate event-driven acks from heartbeats
        meta = link.meta
        writer = _Writer()
        task = asyncio.create_task(link._push_loop(writer, peer_resume=0))

        async def acks_at_least(n: int) -> int:
            for _ in range(400):
                got = sum(1 for k, _ in scan(writer.buf) if k == REPLACK)
                if got >= n:
                    return got
                await asyncio.sleep(0.01)
            raise AssertionError(f"never saw {n} REPLACKs")

        base_acks = await acks_at_least(1)  # initial ack (last_ack=0)

        pusher = Node(node_id=7)
        entries = fill_log(pusher, [(b"set", b"k%d" % i, b"v%d" % i)
                                    for i in range(64)])
        ap = CoalescingApplier(node, meta, max_frames=512,
                               max_latency=999.0)
        # per-frame twin for the watermark-equivalence pin
        twin_node = Node(node_id=8)
        twin_meta = ReplicaMeta("twin:1")
        twin = CoalescingApplier(twin_node, twin_meta, max_frames=1)
        acks_seen = []
        for lo, hi in ((0, 32), (32, 64)):
            prev = entries[lo].prev_uuid
            for e in entries[lo:hi]:
                f = [Bulk(b"replicate"), Int(7), Int(prev), Int(e.uuid),
                     Bulk(e.name), *e.args]
                ap.apply(f)
                twin.apply(f)
                prev = e.uuid
            ap.flush()  # ONE land covering the 32-frame window
            assert meta.uuid_he_sent == twin_meta.uuid_he_sent == \
                entries[hi - 1].uuid
            n_acks = await acks_at_least(len(acks_seen) + base_acks + 1)
            acks = [items for k, items in scan(writer.buf)
                    if k == REPLACK]
            acks_seen.append(len(acks))
            assert as_int(acks[-1][1]) == entries[hi - 1].uuid
            assert n_acks >= len(acks_seen) + base_acks
        task.cancel()
        # one ack per landed batch (not per frame): exactly two more
        # than the baseline after two lands (the 30s heartbeat cannot
        # have contributed)
        assert acks_seen[-1] - base_acks == 2, \
            f"expected 2 batch acks, saw {acks_seen[-1] - base_acks}"
    asyncio.run(main())


def test_beacon_handling_unchanged_with_wire_batches():
    """A drained-stream beacon stashed during a wire batch applies
    after the covering land, exactly like the per-frame path."""
    pusher = Node(node_id=7)
    entries = fill_log(pusher, [(b"set", b"k%d" % i, b"v") for i in range(8)])
    beacon = entries[-1].uuid + (10 << SEQ_BITS)
    node = Node(node_id=2)
    meta = ReplicaMeta("peer:1")
    ap = CoalescingApplier(node, meta, max_frames=512, max_latency=999.0)
    # frames pending -> beacon must stash, not advance
    prev = 0
    for e in entries[:4]:
        ap.apply([Bulk(b"replicate"), Int(7), Int(prev), Int(e.uuid),
                  Bulk(e.name), *e.args])
        prev = e.uuid
    ap.observe_beacon(beacon)
    assert meta.uuid_he_sent == 0
    # the wire batch flushes the pending window first, lands, and the
    # stashed beacon advances with it
    ap.apply_wire_batch(batch_frame(entries[4:]))
    assert meta.uuid_he_sent == beacon
    assert ap.cursor == beacon
