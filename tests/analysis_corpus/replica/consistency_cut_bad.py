"""CUT-ORDERING corpus: the PR 11 consistency-cut bug, minimized.

The shipped bug (replica/link.py _send_delta): the digest was awaited
BEFORE the replication watermark was captured.  Writes landing during
the await advanced the watermark past the digested state — the
(watermark, digest) pair described a cut no replica could ever converge
to.  The fix captures watermarks + records FIRST, then awaits every
derived export.
"""


class _Link:
    def __init__(self, node, app):
        self.node = node
        self.app = app

    async def send_delta_bad(self, writer):
        """Pre-fix shape: export awaited before the capture."""
        digest = await self._local_digest(self.node)   # CUT-ORDERING fires
        repl_last = self.node.repl_log.last_uuid       # capture, too late
        records = self.node.replicas.records()
        return digest, repl_last, records

    async def send_delta_fixed(self, writer):
        """Post-fix shape: watermarks first, digest after."""
        repl_last = self.node.repl_log.last_uuid       # capture FIRST
        records = self.node.replicas.records()
        digest = await self._local_digest(self.node)   # stays clean
        return digest, repl_last, records

    async def export_branchy_bad(self):
        """Capture on ONE path only: the some-path semantics — the
        else-free branch reaches the export uncaptured."""
        repl_last = 0
        if self.app.fast_path:
            repl_last = self.node.repl_log.landed_last_uuid
        counts = await self.node.serve_plane.key_count()  # fires
        return repl_last, counts

    async def export_branchy_fixed(self):
        """Capture dominates the export: every path is covered."""
        repl_last = self.node.repl_log.landed_last_uuid
        if not self.app.fast_path:
            return repl_last, None
        counts = await self.node.serve_plane.key_count()  # stays clean
        return repl_last, counts

    async def _local_digest(self, node):
        return node
