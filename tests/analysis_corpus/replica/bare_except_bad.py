"""Seeded BARE-EXCEPT-SWALLOW violations (never imported)."""


def apply_frames(frames, node):
    for f in frames:
        try:
            node.apply(f)
        except Exception:          # BARE-EXCEPT-SWALLOW: hides apply
            pass                   # failures in a replication path


def cleanup(path):
    import os
    try:
        os.unlink(path)
    except OSError:                # clean: narrowed to fs errors
        pass


class Thing:
    def __del__(self):
        try:
            self.close()
        except Exception:          # clean: __del__ is exempt
            pass
