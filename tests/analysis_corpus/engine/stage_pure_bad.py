"""Seeded STAGE-PURE violations (never imported)."""
import numpy as np


class FakeEngine:
    def _stage_widgets(self, store, resolved, st):
        dev = self._put_batch(np.zeros(4))       # STAGE-PURE: device call
        self._jax.block_until_ready(dev)         # STAGE-PURE: self._jax
        return {"staged": dev}

    def _stage_gadgets(self, store, resolved, st):
        import jax
        return jax.numpy.zeros(4)                # STAGE-PURE: jax in stage

    def _dispatch_widgets(self, store, plan, st):
        stack = np.stack([plan["staged"]])       # STAGE-PURE: heavy staging
        combined = self._combine_groups(         # STAGE-PURE: stage helper
            [stack], None, None)
        return combined

    def _dispatch_clean(self, store, plan, st):
        return self._put_batch(plan["staged"])   # clean: device work is
        #                                          dispatch's job
