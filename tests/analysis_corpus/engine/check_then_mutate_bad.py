"""Seeded CHECK-THEN-MUTATE violations (never imported) — the
_pool_add bug class: mutate pool/table state, THEN notice the problem."""

CEILING = 1 << 31


class FakePool:
    def __init__(self):
        self._pool_size = 0
        self._val_pool = []
        self._win_count = 0

    def pool_add_bug(self, vals):
        base = self._pool_size
        self._val_pool.append((base, vals))      # mutation first...
        self._pool_size = base + len(vals)
        if self._pool_size >= CEILING:           # ...check after
            raise RuntimeError("pool overflow")  # CHECK-THEN-MUTATE
        return base

    def window_assert_bug(self, store, n_new, rows):
        got = store.keys.append_block(n_new)     # mutation...
        assert got[0] == rows[0]                 # CHECK-THEN-MUTATE:
        return got                               # assert after (and -O
        #                                          strips it)

    def pool_add_fixed(self, vals):
        base = self._pool_size
        if base + len(vals) >= CEILING:          # clean: check BEFORE
            raise RuntimeError("pool overflow")
        self._val_pool.append((base, vals))
        self._pool_size = base + len(vals)
        return base
