"""Seeded KEY-CONFINED violations: coalesced commands that are not
first-key-confined.  `badswap` resolves a key taken as its SECOND
argument (the shard router would execute it in the wrong worker);
`nokey` never binds a first-argument key at all; `goodcmd` is the clean
shape (first next_bytes is the key, only that name is resolved) and a
delegating `goodstep` mirrors the incr/_counter_step hop — neither may
fire."""


def register(name, flags=0, families=()):
    def deco(fn):
        return fn
    return deco


def serve_plan(name):
    def deco(fn):
        return fn
    return deco


def columnar(name):
    def deco(fn):
        return fn
    return deco


@register("badswap")
def badswap_command(node, ctx, args):
    field = args.next_bytes()
    key = args.next_bytes()  # the key is the SECOND argument
    kid, _created = node.ks.get_or_create(key, 1, ctx.uuid)
    return kid, field


@serve_plan("badswap")
def _plan_badswap(coal, items):
    return None


@register("nokey")
def nokey_command(node, ctx, args):
    idx = args.next_int()
    return node.ks.lookup(b"static-key"), idx


@columnar("nokey")
def _enc_nokey(bb, recs):
    return None


@register("goodcmd")
def goodcmd_command(node, ctx, args):
    key = args.next_bytes()
    member = args.next_bytes()
    kid, _created = node.ks.get_or_create(key, 2, ctx.uuid)
    node.ks.elem_add(kid, member, None, ctx.uuid, ctx.nodeid)
    return kid


@serve_plan("goodcmd")
def _plan_goodcmd(coal, items):
    return None


def _step_helper(node, ctx, args, delta):
    key = args.next_bytes()
    kid, _created = node.ks.get_or_create(key, 3, ctx.uuid)
    return kid + delta


@register("goodstep")
def goodstep_command(node, ctx, args):
    return _step_helper(node, ctx, args, 1)


@columnar("goodstep")
def _enc_goodstep(bb, recs):
    return None
