"""AWAIT-ATOMICITY corpus: the PR 2 close-window race, minimized.

The shipped bug (server/io.py close()): the shutdown path snapshotted
the live link set, then awaited the listener teardown — during which a
connection accepted just before the listener closed could still reach
_upgrade_to_replica and register a FRESH link.  The sweep then walked
the stale snapshot, missing the newcomer: a zombie stream pumping
replication frames into a dead node.  The fix re-reads the link set
after the await (a second sweep).
"""


class _App:
    def __init__(self, listener):
        self._links = set()
        self._listener = listener

    async def close_bad(self):
        """Pre-fix shape: snapshot, await, sweep the snapshot."""
        links = list(self._links)          # cached shared read
        self._listener.close()
        await self._listener.wait_closed()  # upgrades can register here
        for lk in links:                    # AWAIT-ATOMICITY fires: stale
            lk.stop()
            self._links.discard(lk)

    async def close_fixed(self):
        """Post-fix shape: re-read after every await (second sweep)."""
        for lk in list(self._links):
            lk.stop()
        self._listener.close()
        await self._listener.wait_closed()
        for lk in list(self._links):        # fresh read — stays clean
            lk.stop()
            self._links.discard(lk)

    async def sweep_pinned(self):
        """A DELIBERATE pre-await snapshot, declared as such."""
        # lint: pin[doomed] — links registered after the cutoff belong
        # to the next epoch and are swept by the next cycle
        doomed = list(self._links)
        await self._listener.wait_closed()
        for lk in doomed:                   # pinned — stays clean
            lk.stop()
            self._links.discard(lk)
