"""AWAIT-ATOMICITY corpus: the PR 12 quiesce done-callback race.

The shipped bug (server/serve_shards.py quiesce()): awaiting a resolved
future returns BEFORE its queued done-callbacks run, so the quiesce
path's snapshot of the pending-ack list, taken before the awaits, no
longer described reality when it was used to decide the final drain —
acks enqueued by the still-queued callbacks were dropped.  The fix
drains inline after each await and re-reads the pending state.
"""


class _Plane:
    def __init__(self):
        self._inflight = []
        self._ack_pend = []

    async def quiesce_bad(self):
        """Pre-fix shape: pending snapshot taken before the awaits."""
        pend = list(self._ack_pend)        # cached shared read
        for fut in list(self._inflight):
            await fut                       # done-callbacks still queued
        if pend:                            # AWAIT-ATOMICITY fires: stale
            self._ack_pend = []
            self._drain(pend)

    async def quiesce_fixed(self):
        """Post-fix shape: drain inline, re-read after the awaits."""
        for fut in list(self._inflight):
            await fut
            self._on_serve_ack(fut)         # run what the callback would
        pend = list(self._ack_pend)         # fresh read — stays clean
        if pend:
            self._ack_pend = []
            self._drain(pend)

    def _on_serve_ack(self, fut):
        self._ack_pend.append(fut)

    def _drain(self, pend):
        return len(pend)
