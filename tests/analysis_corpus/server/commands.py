"""Seeded NATIVE-CONTRACT violation: a command registered for
coalescing that the native/intake.cpp table does not cover.  `zadd` is
decorated @serve_plan but appears in none of the marker table's rows
(native / native-reads / python-only), so the C scanner would demote it
to OTHER silently — exactly one finding, on the decorator.  The handler
itself is first-key-confined, so KEY-CONFINED stays quiet; `sadd`
mirrors a real covered command and may not fire anything.

Also seeds the cluster routability direction: `smembers` IS in the
intake table's native-reads row, but registering it CMD_CTRL makes the
slot router skip it while the C scanner still fast-paths it — exactly
one `smembers:unroutable` finding on the decorator."""

CMD_READONLY = 1
CMD_CTRL = 4


def register(name, flags=0, families=()):
    def deco(fn):
        return fn
    return deco


def serve_plan(name):
    def deco(fn):
        return fn
    return deco


def serve_read(name, kind, enc=None, arity=2):
    def deco(fn):
        return fn
    return deco


@register("zadd")
def zadd_command(node, ctx, args):
    key = args.next_bytes()
    score = args.next_int()
    member = args.next_bytes()
    kid, _created = node.ks.get_or_create(key, 2, ctx.uuid)
    node.ks.elem_add(kid, member, score, ctx.uuid, ctx.nodeid)
    return kid


@serve_plan("zadd")
def _plan_zadd(coal, items):
    return None


@register("sadd")
def sadd_command(node, ctx, args):
    key = args.next_bytes()
    member = args.next_bytes()
    kid, _created = node.ks.get_or_create(key, 2, ctx.uuid)
    node.ks.elem_add(kid, member, None, ctx.uuid, ctx.nodeid)
    return kid


@serve_plan("sadd")
def _plan_sadd(coal, items):
    return None


@register("smembers", CMD_READONLY | CMD_CTRL)
def smembers_command(node, ctx, args):
    key = args.next_bytes()
    return node.ks.members(key)
