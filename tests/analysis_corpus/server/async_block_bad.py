"""Seeded ASYNC-BLOCK violations (never imported)."""
import socket
import time


async def handler(path, fut):
    time.sleep(1)                       # ASYNC-BLOCK: time.sleep
    s = socket.socket()                 # ASYNC-BLOCK: sync socket
    with open(path) as f:               # ASYNC-BLOCK: sync file IO
        data = f.read()
    got = fut.result()                  # ASYNC-BLOCK: blocking future wait
    return s, data, got


async def outer(path):
    def nested_helper():                # runs on the loop when called
        return open(path).read()        # ASYNC-BLOCK: nested sync helper
    return nested_helper()


async def fine(reader):
    return await reader.read(1024)      # clean: async IO
