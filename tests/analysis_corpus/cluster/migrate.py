"""Seeded SLOT-EPOCH violation: slot-table state cached across an
await and then used to guard an ownership mutation.

`flip_bad` snapshots the epoch from ``node.cluster`` before awaiting;
a FINALIZE or CLUSTERTAB adoption interleaving at that await bumps the
live epoch, so the stale comparison lets an outdated table through —
exactly one finding, token ``epoch``.  `flip_fixed` re-reads
``cl.epoch`` at the guard (attribute deref reads fresh state), and
`flip_pinned` declares the snapshot deliberate — both stay silent.
The file lives under ``cluster/`` so only the specialized rule (not
the general AWAIT-ATOMICITY) covers it.
"""


async def flip_bad(node, slot, table):
    cl = node.cluster
    epoch = cl.epoch
    await node.events.wait()
    if epoch == table.epoch:
        cl.table = table
        cl.migrating.pop(slot, None)


async def flip_fixed(node, slot, table):
    cl = node.cluster
    await node.events.wait()
    if cl.epoch < table.epoch:
        cl.table = table
        cl.migrating.pop(slot, None)


async def flip_pinned(node, slot, table):
    cl = node.cluster
    epoch = cl.epoch  # lint: pin[epoch]
    await node.events.wait()
    if epoch == table.epoch:
        cl.table = table
        cl.migrating.pop(slot, None)
