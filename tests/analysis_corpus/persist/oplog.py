"""NATIVE-CONTRACT corpus (aof direction): a record-type table drifted
from native/aof.cpp's NATIVE-AOF-TABLE marker block.

The real persist/oplog.py REC_* constants must match the C scanner's
record types exactly — a value drift means each side classifies the
other's records as corruption (the crc gate rejects unknown rtypes).
This mirror seeds every failure mode: a drifted value, a Python-only
record type, and a C-side type with no Python twin.
"""

REC_BATCH = 1   # matches the native table — stays clean
REC_FRAME = 9   # drift: native/aof.cpp declares frame=2
REC_CHUNK = 7   # missing-from-table: the C scanner rejects it
# REC_WMARK deliberately absent -> unknown-record-type (the C scanner
# emits wmark=3 records the Python decoder cannot replay)
