"""Seeded FORK-CAPTURE violations (never imported)."""
import multiprocessing as mp


def _worker(conn, shard, n_shards):
    conn.send(("ok", shard, n_shards))


class FakePool:
    def spawn_bad(self, ctx, conn, engine):
        p1 = ctx.Process(target=lambda: engine.flush())   # FORK-CAPTURE:
        #                                                   lambda capture

        def closure_worker():
            return engine
        p2 = ctx.Process(target=closure_worker)           # FORK-CAPTURE:
        #                                                   closure
        p3 = ctx.Process(target=self.run_shard)           # FORK-CAPTURE:
        #                                                   bound method
        p4 = mp.Process(target=_worker,
                        args=(conn, self.engine, engine))  # FORK-CAPTURE:
        #                                   instance state + live engine
        return p1, p2, p3, p4

    def spawn_ok(self, ctx, conn, shard):
        return ctx.Process(target=_worker,                # clean: module-
                           args=(conn, shard, 2))         # level fn + data

    def run_shard(self):
        return None
