"""Seeded SHM-LIFECYCLE violations (never imported)."""
from multiprocessing import shared_memory


def leaky(payload: bytes) -> str:
    shm = shared_memory.SharedMemory(create=True,    # SHM-LIFECYCLE:
                                     size=len(payload))  # no guard
    shm.buf[: len(payload)] = payload
    return shm.name


def guarded(payload: bytes) -> str:
    shm = shared_memory.SharedMemory(create=True, size=len(payload))
    try:                                             # clean: handler
        shm.buf[: len(payload)] = payload            # closes + unlinks
        return shm.name
    except BaseException:
        shm.close()
        shm.unlink()
        raise


def transferred(payload: bytes):
    # documented ownership hand-off  # lint: ignore[SHM-LIFECYCLE]
    shm = shared_memory.SharedMemory(create=True, size=len(payload))
    return shm
