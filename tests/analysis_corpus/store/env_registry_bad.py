"""Seeded ENV-REGISTRY violations (never imported)."""
import os

from constdb_tpu.conf import env_int


def direct_read():
    return os.environ.get("CONSTDB_SECRET_KNOB", "1")   # ENV-REGISTRY


def subscript_read():
    return os.environ["CONSTDB_OTHER_KNOB"]             # ENV-REGISTRY


def unregistered_helper_read():
    return env_int("CONSTDB_NOT_IN_REGISTRY", 3)        # ENV-REGISTRY


def fine():
    return env_int("CONSTDB_POOL_FLUSH_MB", 1536)       # clean: registered
