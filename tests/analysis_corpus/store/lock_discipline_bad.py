"""LOCK-DISCIPLINE corpus: both lock flavors crossed with the wrong
execution world.

* A thread lock (`_crc_lock`-style) held across an `await` parks the
  lock for as many scheduler turns as the loop pleases — merge workers
  contending on it stall, and re-entry through the same coroutine path
  self-deadlocks.
* An asyncio lock (`_stream_lock`-style) held across blocking sync
  calls wedges the loop AND every waiter queued on the lock; spill IO
  belongs in run_in_executor (replica/link.py _stream_file is the
  reference shape).
"""

import asyncio
import threading


class _WarmCache:
    def __init__(self):
        self._crc_lock = threading.Lock()
        self._stream_lock = asyncio.Lock()
        self._warm = {}

    async def crc_window_bad(self):
        with self._crc_lock:
            crcs = dict(self._warm)
            await self._publish(crcs)   # LOCK-DISCIPLINE fires: await
        return crcs                     # under a thread lock

    async def crc_window_fixed(self):
        with self._crc_lock:            # sync body: snapshot + release
            crcs = dict(self._warm)
        await self._publish(crcs)       # stays clean
        return crcs

    async def stream_window_bad(self, path):
        async with self._stream_lock:
            f = open(path, "rb")        # LOCK-DISCIPLINE fires: blocking
            data = f.read()             # IO while holding the loop lock
            fut = self._spill(data)
            return fut.result()         # LOCK-DISCIPLINE fires: .result()

    async def stream_window_fixed(self, path):
        loop = asyncio.get_running_loop()
        async with self._stream_lock:   # awaits under an asyncio lock
            data = await loop.run_in_executor(None, self._read, path)
        return data                     # are the sanctioned shape

    async def _publish(self, crcs):
        return crcs

    def _spill(self, data):
        return data

    def _read(self, path):
        return path
