"""Counter UNDO (`CNTUNDO key [uuid]`) — the one sound CRDT undo.

Grounded in "The Only Undoable CRDTs are Counters" (PAPERS.md, arXiv
2006.10494): a PN-counter step's inverse is just the negated delta, and
since slots are single-writer LWW registers the ORIGIN can apply it as
a fresh write that commutes with everything concurrent.  The inverse
replicates as an ordinary absolute-total `cntset`, so it rides every
fast path like any increment; no other family is undoable (an element
re-add is a NEW add, not an un-remove) and the command says so.
"""

from __future__ import annotations

import asyncio

from constdb_tpu.resp.message import Bulk, Err, Int
from constdb_tpu.server.node import CounterUndoLog, Node


def ex(node, *parts):
    return node.execute([Bulk(p if isinstance(p, bytes) else
                              str(p).encode()) for p in parts])


def test_undo_is_exact_inverse():
    node = Node(node_id=1)
    assert ex(node, "incr", "k") == Int(1)
    assert ex(node, "incr", "k", 5) == Int(6)
    # stack undo walks USER ops newest-first (never the inverses)
    assert ex(node, "cntundo", "k") == Int(1)
    u_inverse = node.hlc.current  # the undo op's own uuid
    assert ex(node, "cntundo", "k") == Int(0)
    r = ex(node, "cntundo", "k")
    assert isinstance(r, Err)  # no user op left
    # undo of an undo — REDO — takes the inverse op's explicit uuid
    assert ex(node, "cntundo", "k", u_inverse) == Int(5)


def test_undo_by_explicit_uuid_and_errors():
    node = Node(node_id=1)
    ex(node, "incr", "k")
    u1 = node.hlc.current
    ex(node, "incr", "k", 10)
    # undo the FIRST op by uuid, not the newest
    assert ex(node, "cntundo", "k", u1) == Int(10)
    # double-undo of the same op is rejected cleanly
    r = ex(node, "cntundo", "k", u1)
    assert isinstance(r, Err) and b"already undone" in r.val
    # unknown uuid
    r = ex(node, "cntundo", "k", 12345)
    assert isinstance(r, Err) and b"unknown, remote, or evicted" in r.val
    # key mismatch: a real op uuid against the wrong key
    ex(node, "incr", "other")
    u3 = node.hlc.current
    r = ex(node, "cntundo", "k", u3)
    assert isinstance(r, Err)


def test_undo_rejected_on_non_counter_families():
    node = Node(node_id=1)
    ex(node, "set", "reg", "v")
    r = ex(node, "cntundo", "reg")
    assert isinstance(r, Err) and b"only sound for counters" in r.val
    ex(node, "sadd", "s", "m")
    r = ex(node, "cntundo", "s", 1)
    assert isinstance(r, Err) and b"only sound for counters" in r.val


def test_undo_window_evicts_fifo():
    log = CounterUndoLog(cap=2)
    log.record(1, b"k", 1)
    log.record(2, b"k", 2)
    log.record(3, b"q", 3)  # evicts uuid 1
    assert log.resolve(b"k", 1) is None
    assert log.resolve(b"k") == (2, 2)
    assert log.resolve(b"q") == (3, 3)
    log.mark_undone(2)
    assert log.resolve(b"k") is None


def test_undo_replicates_and_is_remote_rejected(tmp_path):
    """The inverse converges mesh-wide like any write, and a REPLICA of
    the op cannot undo it (single-writer slots: not its to invert)."""
    from constdb_tpu.chaos import ChaosCluster, NodeSpec
    from constdb_tpu.chaos.cluster import Client

    async def main():
        cluster = ChaosCluster(str(tmp_path), seed=2,
                               specs=[NodeSpec(), NodeSpec()])
        await cluster.start()
        try:
            a, b = cluster.apps
            ca = await Client().connect(a.advertised_addr)
            await ca.cmd("meet", b.advertised_addr)
            assert await ca.cmd("incr", "k", 7) == Int(7)
            await cluster.converge()
            # B holds the replicated total but NOT the op: remote undo
            # is cleanly rejected
            cb = await Client().connect(b.advertised_addr)
            r = await cb.cmd("cntundo", "k")
            assert isinstance(r, Err)
            # the origin undoes; the inverse replicates as cntset
            assert await ca.cmd("cntundo", "k") == Int(0)
            await cluster.converge()
            assert await cb.cmd("get", "k") == Int(0)
            await ca.close()
            await cb.close()
        finally:
            await cluster.close()
    asyncio.run(main())


def test_undo_plans_through_serve_coalescer(tmp_path):
    """A pipelined chunk mixing INCR and CNTUNDO rides the serve
    planner (no barrier demotion for the valid case), with replies
    byte-identical to the per-command path's values."""
    from constdb_tpu.chaos import ChaosCluster, NodeSpec
    from constdb_tpu.chaos.cluster import Client
    from constdb_tpu.resp.codec import encode_msg
    from constdb_tpu.resp.message import Arr

    async def main():
        cluster = ChaosCluster(str(tmp_path), seed=3, specs=[NodeSpec()])
        await cluster.start()
        try:
            app = cluster.apps[0]
            c = await Client().connect(app.advertised_addr)
            buf = bytearray()
            for parts in ((b"incr", b"k", b"3"), (b"incr", b"k", b"4"),
                          (b"cntundo", b"k"), (b"incr", b"k", b"10")):
                buf += encode_msg(Arr([Bulk(p) for p in parts]))
            c.writer.write(bytes(buf))
            await c.writer.drain()
            replies = []
            while len(replies) < 4:
                msg = c.parser.next_msg()
                if msg is not None:
                    replies.append(msg)
                    continue
                data = await asyncio.wait_for(c.reader.read(1 << 16), 10.0)
                c.parser.feed(data)
            # 3, 7, undo(-4) -> 3, +10 -> 13
            assert replies == [Int(3), Int(7), Int(3), Int(13)], replies
            assert await c.cmd("get", "k") == Int(13)
            # the whole chunk coalesced: one flush, no barriers for the
            # plannable run (serve_barriers counts only real demotions)
            assert app.node.stats.serve_msgs_coalesced >= 4
            await c.close()
        finally:
            await cluster.close()
    asyncio.run(main())


def test_undo_survives_warm_restart_not_cold(tmp_path):
    """The undo log is process state: a warm restart keeps it, a cold
    restart loses it and the op reports 'evicted' — never a wrong
    inverse."""
    from constdb_tpu.chaos import ChaosCluster, NodeSpec
    from constdb_tpu.chaos.cluster import Client

    async def main():
        cluster = ChaosCluster(str(tmp_path), seed=4, specs=[NodeSpec()])
        await cluster.start()
        try:
            c = await Client().connect(cluster.apps[0].advertised_addr)
            assert await c.cmd("incr", "k", 5) == Int(5)
            await c.close()
            await cluster.restart_warm(0)
            c = await Client().connect(cluster.apps[0].advertised_addr)
            assert await c.cmd("cntundo", "k") == Int(0)
            assert await c.cmd("incr", "k", 9) == Int(9)
            await c.close()
            await cluster.restart_cold(0)
            c = await Client().connect(cluster.apps[0].advertised_addr)
            assert await c.cmd("get", "k") == Int(9)
            r = await c.cmd("cntundo", "k")
            assert isinstance(r, Err)
            await c.close()
        finally:
            await cluster.close()
    asyncio.run(main())
