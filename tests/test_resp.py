import random

import pytest

from constdb_tpu.errors import InvalidRequestMsg
from constdb_tpu.resp import (
    NIL, NO_REPLY, OK, Arr, Bulk, Err, Int, RespParser, Simple,
    as_bytes, as_int, as_uint, encode_msg, mkcmd, msg_size,
)


GOLDEN = [
    (Simple(b"OK"), b"+OK\r\n"),
    (Err(b"boom"), b"-boom\r\n"),
    (Int(42), b":42\r\n"),
    (Int(-7), b":-7\r\n"),
    (Bulk(b""), b"$0\r\n\r\n"),
    (Bulk(b"hello"), b"$5\r\nhello\r\n"),
    (Bulk(b"with\r\nnewline"), b"$13\r\nwith\r\nnewline\r\n"),
    (NIL, b"$-1\r\n"),
    (Arr([]), b"*0\r\n"),
    (Arr([Bulk(b"GET"), Bulk(b"k")]), b"*2\r\n$3\r\nGET\r\n$1\r\nk\r\n"),
    (Arr([Int(1), Arr([Simple(b"a")]), NIL]), b"*3\r\n:1\r\n*1\r\n+a\r\n$-1\r\n"),
]


class TestEncode:
    @pytest.mark.parametrize("msg,wire", GOLDEN)
    def test_golden(self, msg, wire):
        assert encode_msg(msg) == wire

    def test_no_reply_encodes_nothing(self):
        assert encode_msg(NO_REPLY) == b""

    def test_mkcmd(self):
        assert mkcmd("SYNC", 0, b"n1", 17) == Arr(
            [Bulk(b"SYNC"), Bulk(b"0"), Bulk(b"n1"), Bulk(b"17")]
        )


class TestParse:
    @pytest.mark.parametrize("msg,wire", GOLDEN)
    def test_golden_roundtrip(self, msg, wire):
        p = RespParser()
        p.feed(wire)
        assert p.next_msg() == msg
        assert p.next_msg() is None

    def test_pipelined(self):
        p = RespParser()
        p.feed(b"+a\r\n:1\r\n$1\r\nx\r\n")
        assert p.next_msg() == Simple(b"a")
        assert p.next_msg() == Int(1)
        assert p.next_msg() == Bulk(b"x")
        assert p.next_msg() is None

    def test_byte_at_a_time(self):
        # parity: reference conn.rs:136-202 round-trips random messages
        wire = b"".join(w for _, w in GOLDEN)
        msgs = [m for m, _ in GOLDEN]
        p = RespParser()
        got = []
        for i in range(len(wire)):
            p.feed(wire[i:i + 1])
            while (m := p.next_msg()) is not None:
                got.append(m)
        assert got == msgs

    def test_random_split_points(self):
        rng = random.Random(11)
        msgs = []
        for _ in range(100):
            r = rng.random()
            if r < 0.3:
                msgs.append(Bulk(rng.randbytes(rng.randrange(0, 40))))
            elif r < 0.5:
                msgs.append(Int(rng.randrange(-(1 << 40), 1 << 40)))
            elif r < 0.6:
                msgs.append(NIL)
            elif r < 0.7:
                msgs.append(Simple(bytes(rng.choices(range(33, 127), k=5))))
            else:
                msgs.append(Arr([Bulk(rng.randbytes(3)), Int(rng.randrange(100))]))
        wire = b"".join(encode_msg(m) for m in msgs)
        p = RespParser()
        got = []
        pos = 0
        while pos < len(wire):
            step = rng.randrange(1, 30)
            p.feed(wire[pos:pos + step])
            pos += step
            while (m := p.next_msg()) is not None:
                got.append(m)
        assert got == msgs

    def test_malformed_type_byte(self):
        p = RespParser()
        p.feed(b"!bad\r\n")
        with pytest.raises(InvalidRequestMsg):
            p.next_msg()

    def test_bulk_missing_crlf(self):
        p = RespParser()
        p.feed(b"$3\r\nabcXX")
        with pytest.raises(InvalidRequestMsg):
            p.next_msg()

    def test_bad_integer(self):
        p = RespParser()
        p.feed(b":notanint\r\n")
        with pytest.raises(InvalidRequestMsg):
            p.next_msg()

    def test_nested_array_partial(self):
        wire = encode_msg(Arr([Arr([Bulk(b"deep")]), Int(2)]))
        p = RespParser()
        p.feed(wire[:8])
        assert p.next_msg() is None
        p.feed(wire[8:])
        assert p.next_msg() == Arr([Arr([Bulk(b"deep")]), Int(2)])

    def test_depth_limit(self):
        p = RespParser(max_depth=4)
        p.feed(b"*1\r\n" * 10 + b":1\r\n")
        with pytest.raises(InvalidRequestMsg):
            p.next_msg()

    def test_compaction_keeps_parsing(self):
        p = RespParser()
        big = encode_msg(Bulk(b"z" * 70000))
        p.feed(big)
        p.feed(b":5\r\n")
        assert p.next_msg() == Bulk(b"z" * 70000)
        assert p.next_msg() == Int(5)


class TestCoercion:
    def test_as_bytes(self):
        assert as_bytes(Bulk(b"x")) == b"x"
        assert as_bytes(Int(12)) == b"12"
        with pytest.raises(InvalidRequestMsg):
            as_bytes(Arr([]))

    def test_as_int(self):
        assert as_int(Int(-3)) == -3
        assert as_int(Bulk(b"44")) == 44
        with pytest.raises(InvalidRequestMsg):
            as_int(Bulk(b"x"))

    def test_as_uint(self):
        assert as_uint(Bulk(b"7")) == 7
        with pytest.raises(InvalidRequestMsg):
            as_uint(Int(-1))

    def test_msg_size(self):
        assert msg_size(Arr([Bulk(b"abc"), Int(1)])) == 11
        assert msg_size(NIL) == 0
