"""Regression tests for the round-4 advisor findings (ADVICE.md, fixed in
round 5).

Each test pins one fixed behavior: GC peer retention defaults OFF and,
when enabled, a returning excluded peer gets a STATE-CLEARING full resync
(no mesh-wide resurrection); the native RESP batch scan stops at a
FULLSYNC frame; the flush-before-touch invariant raises (not assert);
engine='tpu!' fails fast and the 'tpu' fallback is visible in INFO; a
negative per-slot bytes-column length is rejected at the section.
"""

import asyncio

import numpy as np
import pytest

from constdb_tpu.conf import Config
from constdb_tpu.replica.manager import ReplicaManager
from constdb_tpu.resp.message import Arr, Bulk, Int
from constdb_tpu.server.node import Node

from cluster_util import Client, close_cluster, converge, make_cluster, FAST


def _cmd(node, *parts):
    return node.execute([Bulk(p if isinstance(p, bytes) else str(p).encode())
                         for p in parts])


# ------------------------------------------------- 1: gc_peer_retention


def test_retention_defaults_off_everywhere():
    """Default = reference behavior (a dead peer pins GC forever); the
    lossy exclusion rule is opt-in (advisor round-4 medium)."""
    from constdb_tpu.server.io import ServerApp

    assert Config().gc_peer_retention == 0
    assert ReplicaManager().gc_peer_retention_ms == 0
    node = Node(node_id=1)
    ServerApp(node, work_dir="/tmp")
    assert node.replicas.gc_peer_retention_ms == 0


class _StubLink:
    def __init__(self):
        self.kicked = 0

    def kick(self):
        self.kicked += 1


def test_reset_for_full_resync_wipes_state():
    node = Node(node_id=1)
    _cmd(node, b"set", b"k", b"v")
    _cmd(node, b"sadd", b"s", b"m")
    node.replicas.add("peer:1", uuid=5)
    node.replicas.get("peer:1").uuid_he_sent = 99
    node.replicas.add("peer:2", uuid=5)
    keep = _StubLink()
    other = _StubLink()
    node.replicas.get("peer:1").link = keep
    node.replicas.get("peer:2").link = other
    old_last = node.repl_log.last_uuid
    assert old_last > 0
    epoch0 = node.reset_epoch
    node.reset_for_full_resync(keep_link=keep)
    assert node.ks.keys.n == 0
    # the fresh log is FENCED at the pre-wipe watermark: peers resuming
    # below it must get a full snapshot, never a PARTSYNC of nothing
    assert len(node.repl_log) == 0
    assert node.repl_log.evicted_up_to >= old_last
    assert not node.repl_log.can_resume_from(old_last - 1)
    # membership survives, pull watermarks do not
    m = node.replicas.get("peer:1")
    assert m is not None and m.alive and m.uuid_he_sent == 0
    # other streams are kicked into a fresh handshake; the delivering
    # stream (keep_link) survives; stale-stream beacons are fenced off
    assert other.kicked == 1 and keep.kicked == 0
    assert node.reset_epoch == epoch0 + 1
    # the node still serves writes afterwards
    _cmd(node, b"set", b"k2", b"v2")
    assert _cmd(node, b"get", b"k2") == Bulk(b"v2")


def test_excluded_peer_gets_state_clearing_resync(tmp_path):
    """The full scenario from the advisor finding: node B goes silent past
    the retention window, A collects B's unseen tombstones AND B's resume
    point falls off A's repl_log.  On return, B must be wiped + resynced —
    the deleted key must NOT resurrect mesh-wide."""
    async def main():
        from constdb_tpu.server.io import ServerApp

        apps = await make_cluster(2, str(tmp_path), repl_log_cap=600,
                                  gc_peer_retention=3600.0)
        try:
            a, b = apps
            c = await Client().connect(a.advertised_addr)
            await c.cmd("meet", b.advertised_addr)
            await converge(apps)
            await c.cmd("sadd", "s", "stale")
            await c.cmd("set", "doomed", "v")
            await converge(apps)

            # B goes dark (warm: keeps its Node state, loses connections)
            b_port = b.port
            await b.close()
            await asyncio.sleep(0.1)

            # A deletes while B is away, then the silence exceeds the window
            await c.cmd("srem", "s", "stale")
            await c.cmd("del", "doomed")
            meta_b = a.node.replicas.get(b.advertised_addr)
            meta_b.last_seen_ms -= 10_000_000  # silent "forever"
            # horizon unpins, tombstones collect, needs_full latches
            a.node.gc()
            assert meta_b.needs_full is True
            assert len(a.node.ks.garbage) == 0  # tombstones physically gone
            # enough traffic to evict B's resume point off the tiny ring
            for i in range(60):
                await c.cmd("set", f"fill{i}", "x" * 32)
            assert not a.node.repl_log.can_resume_from(meta_b.uuid_i_sent)

            # B returns with the stale member/key still live locally
            assert b"stale" in {m for m, _, _ in
                                b.node.ks.elem_live(b.node.ks.lookup(b"s"))}
            b2 = ServerApp(b.node, host="127.0.0.1", port=b_port,
                           work_dir=str(tmp_path), **FAST)
            await b2.start()
            apps[1] = b2
            await converge(apps, timeout=20.0)
            # no resurrection anywhere: the delete sticks on BOTH nodes
            for app in apps:
                cx = await Client().connect(app.advertised_addr)
                from constdb_tpu.resp.message import Nil
                assert isinstance(await cx.cmd("get", "doomed"), Nil)
                got = await cx.cmd("smembers", "s")
                assert b"stale" not in {i.val for i in got.items}
                assert await cx.cmd("get", "fill59") == Bulk(b"x" * 32)
                await cx.close()
            await c.close()
        finally:
            await close_cluster(apps)
    asyncio.run(main())


def test_reset_resync_rekicks_surviving_streams(tmp_path):
    """Ops applied just before a wipe must be RE-delivered by the peers
    that originated them: after B wipes, C's surviving idle stream resends
    nothing, and C's REPLACK beacon would quietly re-advance B's zeroed
    pull watermark past C's ops — losing them forever.  The wipe must kick
    C's connection (fresh handshake at resume 0) and fence stale-stream
    beacons behind the reset epoch (code-review round-5 finding).

    Deterministic shape: only C holds its origin ops when B wipes (there
    is no third node whose snapshot could smuggle them back), and C is
    idle afterwards, so ONLY a kicked re-handshake can restore them."""
    async def main():
        apps = await make_cluster(2, str(tmp_path))
        try:
            b, c = apps
            cc = await Client().connect(c.advertised_addr)
            await cc.cmd("meet", b.advertised_addr)
            await converge(apps)
            await cc.cmd("set", "late", "from-c")
            await converge(apps)
            assert b.node.ks.lookup(b"late") >= 0

            # B is wiped (the receive side of a reset-fullsync from some
            # excluding peer; keep_link=None — the exciser is gone)
            b.node.reset_for_full_resync()
            assert b.node.ks.lookup(b"late") < 0
            # C is idle: no new ops will ever arrive.  Only the kick-forced
            # re-handshake (resume 0 → C replays its log from the start)
            # can re-deliver "late"; without it, C's idle beacon advances
            # B's zeroed watermark and convergence never happens.
            await converge(apps, timeout=15.0)
            assert b.node.ks.lookup(b"late") >= 0
            got = await cc.cmd("get", "late")
            assert got == Bulk(b"from-c")
            await cc.close()
        finally:
            await close_cluster(apps)
    asyncio.run(main())


# ------------------------------------- 2: native scan stops at FULLSYNC


def test_native_scan_stops_at_fullsync_frame():
    from constdb_tpu.resp.codec import NativeRespParser, _ext, encode_msg

    if _ext() is None:
        pytest.skip("native extension not built")
    p = NativeRespParser()
    frame = encode_msg(Arr([Bulk(b"fullsync"), Int(10), Int(7)]))
    # raw snapshot bytes that LOOK like RESP (':' int frames) — the exact
    # corruption the advisor demonstrated
    raw = b":123\r\n:456\r\nXY"
    p.feed(encode_msg(Arr([Bulk(b"partsync")])) + frame + raw)
    assert p.next_msg().items[0].val == b"partsync"
    msg = p.next_msg()
    assert msg.items[0].val == b"fullsync"
    # the scan must NOT have consumed the raw run as frames
    assert p.take_raw(10) == raw[:10]
    assert p.take_raw(4) == raw[10:]


# ------------------------------------ 3: invariant raises, not asserts


def test_mirror_invariant_raises_runtime_error():
    jax = pytest.importorskip("jax")  # noqa: F841
    from constdb_tpu.engine.tpu import TpuMergeEngine
    from constdb_tpu.store.keyspace import KeySpace

    eng = TpuMergeEngine(resident=True)
    store = KeySpace()
    eng._res["el"] = {"cols": {}, "n": 0, "cap": 0, "ver": -12345,
                      "src": None, "written": {"add_t"}}
    with pytest.raises(RuntimeError, match="flush-before-touch"):
        eng._resident_state(store, "el", 0)


# ------------------------------------------- 4: strict engine variant


def test_engine_strict_variant_fails_fast(monkeypatch):
    import constdb_tpu.conf as conf
    from constdb_tpu.utils import backend as bk

    monkeypatch.setattr(
        bk, "probe_backend",
        lambda timeout=90.0: bk.BackendProbe(False,
                                             error="simulated: no device"))
    with pytest.raises(RuntimeError, match="tpu!"):
        conf.build_engine("tpu!")
    # the soft variant still boots, but visibly degraded
    eng = conf.build_engine("tpu")
    assert eng is not None and hasattr(eng, "merge")
    assert "simulated: no device" in getattr(eng, "degraded", "") or \
        getattr(eng, "degraded", "")


def test_degraded_engine_surfaces_in_info():
    node = Node(node_id=1)
    node.engine.degraded = "tpu requested, running XLA-on-CPU: test"
    out = _cmd(node, b"info", b"stats").val.decode()
    assert "engine_degraded:tpu requested" in out


def test_info_memory_rss_current_and_peak():
    node = Node(node_id=1)
    out = _cmd(node, b"info", b"memory").val.decode()
    fields = dict(line.split(":", 1) for line in out.splitlines()
                  if ":" in line)
    rss = int(fields["used_memory_rss"])
    peak = int(fields["used_memory_peak"])
    assert 0 < rss <= peak


# ------------------------------------- 5: negative bytes-column length


def test_snapshot_rejects_negative_slot_length():
    from constdb_tpu.persist.snapshot import _read_bytes_list
    from constdb_tpu.utils.varint import VarintReader

    # mixed corruption whose TOTAL is still positive: [-5, +9] → total 2
    # with one slot walking pos backwards — must fail at the section
    lens = np.array([-5, 9], dtype="<i4").tobytes()
    r = VarintReader(lens + b"payloadbytes")
    with pytest.raises(ValueError, match="negative"):
        _read_bytes_list(r, 2)
