"""MultiValue register + List: the two CRDTs the reference advertises but
never wires (reference README.md:10, src/crdt/vclock.rs, src/crdt/list.rs).
Full-surface tests: commands over TCP, concurrent-sibling semantics,
replication convergence, snapshot round-trip, and DEL."""

import asyncio

import pytest

from constdb_tpu.resp.message import Arr, Bulk, Err, Int, Nil
from constdb_tpu.server.node import Node

from cluster_util import Client, close_cluster, converge, full_mesh, make_cluster


def run(coro):
    asyncio.run(coro)


def _cmd(node, *parts):
    return node.execute([Bulk(p if isinstance(p, bytes) else str(p).encode())
                         for p in parts])


# ------------------------------------------------------------- multi-value

def test_mv_single_node_roundtrip():
    n = Node(node_id=1)
    tok = _cmd(n, b"mvset", b"k", b"v1")
    assert isinstance(tok, Bulk)
    got = _cmd(n, b"mvget", b"k")
    vals, token = got.items
    assert [b.val for b in vals.items] == [b"v1"]
    # a write WITH the read context supersedes (one sibling remains)
    _cmd(n, b"mvset", b"k", b"v2", token.val)
    got = _cmd(n, b"mvget", b"k")
    assert [b.val for b in got.items[0].items] == [b"v2"]


def test_mv_concurrent_writes_surface_as_siblings():
    """Writes that did not see each other (stale/absent context) both
    survive; a context-carrying write supersedes exactly what was read."""
    n = Node(node_id=1)
    _cmd(n, b"mvset", b"k", b"a")
    got = _cmd(n, b"mvget", b"k")
    stale_token = got.items[1].val
    _cmd(n, b"mvset", b"k", b"b", stale_token)  # supersedes a
    # node 2's concurrent write (empty context — it saw nothing)
    n2 = Node(node_id=2)
    _cmd(n2, b"mvset", b"x", b"ignore")  # advance clock a bit
    # simulate n2's concurrent write arriving by replicated mvwrite
    from constdb_tpu.crdt.multivalue import VClock, clock_to_bytes
    wc = VClock().bump(2)
    n.apply_replicated(b"mvwrite",
                       [Bulk(b"k"), Bulk(clock_to_bytes(wc)), Bulk(b"c")],
                       2, 1000 << 22)
    got = _cmd(n, b"mvget", b"k")
    assert sorted(b.val for b in got.items[0].items) == [b"b", b"c"]
    # resolving write with the merged context collapses both
    _cmd(n, b"mvset", b"k", b"final", got.items[1].val)
    got = _cmd(n, b"mvget", b"k")
    assert [b.val for b in got.items[0].items] == [b"final"]


def test_mv_wrongtype_and_del():
    n = Node(node_id=1)
    _cmd(n, b"mvset", b"k", b"v")
    bad = _cmd(n, b"sadd", b"k", b"m")
    assert isinstance(bad, Err)
    assert _cmd(n, b"del", b"k") == Int(1)
    assert _cmd(n, b"mvget", b"k") == Nil()
    # write-after-delete resurrects (add-wins)
    _cmd(n, b"mvset", b"k", b"back")
    got = _cmd(n, b"mvget", b"k")
    assert [b.val for b in got.items[0].items] == [b"back"]


# ------------------------------------------------------------------- lists

def test_list_single_node_ops():
    n = Node(node_id=1)
    assert _cmd(n, b"rpush", b"l", b"a", b"b", b"c") == Int(3)
    assert _cmd(n, b"lpush", b"l", b"z") == Int(4)
    got = _cmd(n, b"lrange", b"l", 0, -1)
    assert [b.val for b in got.items] == [b"z", b"a", b"b", b"c"]
    assert _cmd(n, b"linsert", b"l", 2, b"mid") == Int(5)
    got = _cmd(n, b"lrange", b"l", 0, -1)
    assert [b.val for b in got.items] == [b"z", b"a", b"mid", b"b", b"c"]
    assert _cmd(n, b"llen", b"l") == Int(5)
    assert _cmd(n, b"lrem", b"l", 0) == Int(1)
    got = _cmd(n, b"lrange", b"l", 1, 2)
    assert [b.val for b in got.items] == [b"mid", b"b"]
    assert _cmd(n, b"del", b"l") == Int(1)
    assert _cmd(n, b"llen", b"l") == Int(0)


def test_list_range_edges():
    n = Node(node_id=1)
    _cmd(n, b"rpush", b"l", b"0", b"1", b"2", b"3")
    assert [b.val for b in _cmd(n, b"lrange", b"l", -2, -1).items] == [b"2", b"3"]
    assert _cmd(n, b"lrange", b"l", 3, 1) == Arr([])
    assert _cmd(n, b"lrange", b"missing", 0, -1) == Arr([])


# ------------------------------------------------------------ replication

def test_mv_and_list_converge_over_mesh(tmp_path):
    async def main():
        apps = await make_cluster(3, str(tmp_path))
        c = [await Client().connect(a.advertised_addr) for a in apps]
        try:
            # TRULY concurrent MV writes: both happen before the nodes ever
            # meet, so neither write could have seen the other
            await c[0].cmd("mvset", "mk", "from-n1")
            await c[2].cmd("mvset", "mk", "from-n3")
            await c[0].cmd("meet", apps[1].advertised_addr)
            await c[2].cmd("meet", apps[1].advertised_addr)
            await full_mesh(apps)
            await converge(apps)
            got = await c[1].cmd("mvget", "mk")
            sibs = sorted(b.val for b in got.items[0].items)
            assert sibs == [b"from-n1", b"from-n3"]
            # resolve on node 2 with its merged context; all converge to one
            await c[1].cmd("mvset", "mk", "resolved", got.items[1].val)
            await converge(apps)
            for cli in c:
                got = await cli.cmd("mvget", "mk")
                assert [b.val for b in got.items[0].items] == [b"resolved"]

            # list ops from different nodes
            await c[0].cmd("rpush", "ll", "a", "b")
            await converge(apps)
            await c[2].cmd("rpush", "ll", "c")
            await c[1].cmd("lpush", "ll", "front")
            await converge(apps)
            views = []
            for cli in c:
                got = await cli.cmd("lrange", "ll", 0, -1)
                views.append([b.val for b in got.items])
            assert views[0] == views[1] == views[2]
            assert set(views[0]) == {b"front", b"a", b"b", b"c"}
            assert views[0][0] == b"front" and views[0].index(b"a") < views[0].index(b"b")

            # delete + convergence
            await c[1].cmd("del", "ll")
            await converge(apps)
            for cli in c:
                assert await cli.cmd("llen", "ll") == Int(0)
        finally:
            for cli in c:
                await cli.close()
            await close_cluster(apps)
    run(main())


# ---------------------------------------------------------------- snapshot

def test_mv_list_snapshot_roundtrip(tmp_path):
    from constdb_tpu.engine.base import batch_from_keyspace
    from constdb_tpu.persist.snapshot import (NodeMeta, dump_keyspace,
                                              load_snapshot)
    from constdb_tpu.store.keyspace import KeySpace

    from constdb_tpu.crdt.multivalue import VClock, clock_to_bytes

    n = Node(node_id=1)
    _cmd(n, b"mvset", b"mk", b"v1")
    # a concurrent sibling arriving from node 2's replication stream
    n.apply_replicated(
        b"mvwrite",
        [Bulk(b"mk"), Bulk(clock_to_bytes(VClock().bump(2))), Bulk(b"v2")],
        2, 2_000_000 << 22)
    _cmd(n, b"rpush", b"ll", b"a", b"b", b"c")
    _cmd(n, b"lrem", b"ll", 1)

    path = str(tmp_path / "s.snap")
    dump_keyspace(path, n.ks, NodeMeta(node_id=1, repl_last_uuid=7))
    ks2 = KeySpace()
    load_snapshot(path, ks2)
    assert ks2.canonical() == n.ks.canonical()

    # and through a second node's command surface
    n2 = Node(node_id=2)
    n2.ks = ks2
    got = _cmd(n2, b"lrange", b"ll", 0, -1)
    assert [b.val for b in got.items] == [b"a", b"c"]
    got = _cmd(n2, b"mvget", b"mk")
    assert sorted(b.val for b in got.items[0].items) == [b"v1", b"v2"]
