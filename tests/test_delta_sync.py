"""Digest-driven delta anti-entropy (replica/link.py + store/digest.py).

The protocol under test: a pusher whose peer's resume point fell off the
repl_log ring exchanges a two-level state digest over the crc32 shard
partition — per-shard rollups, then per-key-range leaf digests for the
shards that mismatch — and streams ONLY the divergent buckets as a
snapshot-format delta, instead of re-shipping the whole keyspace.
Soundness rests on the digest being a pure function of logical CRDT
state (store/digest.py module header): any two stores holding the same
state produce the same matrix, whatever engine merged it, however its
shards are laid out, in whatever order the ops arrived.  The
determinism suite pins that; the e2e suites pin the wire protocol, the
O(divergence) transfer, the threshold demotion, and the mid-stream
ring-falloff recovery riding the same negotiation.
"""

import asyncio
import io
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from test_link_pushloop import _Writer, _mk_link  # noqa: E402

from constdb_tpu.crdt import semantics as S  # noqa: E402
from constdb_tpu.engine.base import batch_from_keyspace  # noqa: E402
from constdb_tpu.engine.cpu import CpuMergeEngine  # noqa: E402
from constdb_tpu.persist.snapshot import SectionDemux  # noqa: E402
from constdb_tpu.replica.link import (CAP_DELTA_SYNC,  # noqa: E402
                                      CAP_FULLSYNC_RESET, DELTASYNC, DIGEST,
                                      DIGESTACK, FULLSYNC, REPLICATE)
from constdb_tpu.resp.codec import make_parser  # noqa: E402
from constdb_tpu.resp.message import Arr, Bulk, Int, as_bytes, as_int  # noqa: E402
from constdb_tpu.server.node import Node  # noqa: E402
from constdb_tpu.store import digest as D  # noqa: E402
from constdb_tpu.store.keyspace import KeySpace  # noqa: E402

MS0 = 1_600_000_000_000 << 22  # uuid base well below any live HLC tick


# --------------------------------------------------------------------------
# state builders


def _mixed_ops(n_keys: int = 160, seed: int = 3):
    """A deterministic mixed op list [(kind, key, member/val, uuid)]
    covering registers, counters, and sets — applied through whichever
    path a test exercises."""
    import random
    rng = random.Random(seed)
    ops = []
    t = 0
    for i in range(n_keys):
        t += 1 + rng.randrange(3)
        r = i % 10
        key = b"k%04d" % i
        if r < 4:
            ops.append(("set", key, b"v%06d" % rng.randrange(10_000),
                        MS0 + (t << 10)))
        elif r < 7:
            ops.append(("cnt", key, rng.randrange(-50, 50),
                        MS0 + (t << 10)))
        else:
            for m in range(3):
                t += 1
                ops.append(("sadd", key, b"m%02d" % rng.randrange(8),
                           MS0 + (t << 10)))
            if rng.random() < 0.5:
                t += 1
                ops.append(("srem", key, b"m%02d" % rng.randrange(8),
                           MS0 + (t << 10)))
    return ops


def _apply_ops(ks: KeySpace, ops, node: int = 7) -> None:
    for kind, key, x, uuid in ops:
        if kind == "set":
            kid, _ = ks.get_or_create(key, S.ENC_BYTES, uuid)
            ks.register_set(kid, x, uuid, node)
        elif kind == "cnt":
            kid, _ = ks.get_or_create(key, S.ENC_COUNTER, uuid)
            ks.counter_change(kid, node, x, uuid)
        elif kind == "sadd":
            kid, _ = ks.get_or_create(key, S.ENC_SET, uuid)
            ks.elem_add(kid, x, None, uuid, node)
            ks.updated_at(kid, uuid)
        elif kind == "srem":
            kid, _ = ks.get_or_create(key, S.ENC_SET, uuid)
            ks.elem_rem(kid, x, uuid)


def _digest_of(ks: KeySpace, fanout: int = 16, leaves: int = 8):
    return D.state_digest_matrix(ks, fanout, leaves)


def test_full_state_digest_is_geometry_independent():
    """The scalar fold (the chaos oracle's digest-agreement law and the
    resync bench's cross-check) is the mod-2^64 sum of the matrix, so
    every (fanout, leaves) layout of one state agrees — and two states
    that differ by one write do not."""
    ks = KeySpace()
    _apply_ops(ks, _mixed_ops())
    want = D.full_state_digest(ks)
    for fanout, leaves in ((1, 1), (4, 2), (16, 8), (64, 1)):
        assert D.full_state_digest(ks, fanout, leaves) == want
    other = KeySpace()
    _apply_ops(other, _mixed_ops())
    assert D.full_state_digest(other) == want  # same ops, same state
    kid, _ = other.get_or_create(b"extra", S.ENC_COUNTER, 77 << 22)
    other.counter_change(kid, 9, 1, 77 << 22)
    assert D.full_state_digest(other) != want


# --------------------------------------------------------------------------
# digest determinism: one logical state, many construction routes


def test_digest_engine_and_shard_determinism():
    """CPU engine merge, TPU (XLA) engine merge, and the hash-sharded
    plane at 1/2/3 shards all produce the SAME per-shard digest matrix
    for the same logical state — the invariant the whole anti-entropy
    protocol rests on (a sharded-serving node SUMS its workers'
    matrices, so plane-wide must equal single-store)."""
    ops = _mixed_ops()
    ref = KeySpace()
    _apply_ops(ref, ops)
    want = _digest_of(ref)
    dump = batch_from_keyspace(ref)

    # CPU engine replay of the state dump
    ks_cpu = KeySpace()
    CpuMergeEngine().merge(ks_cpu, dump)
    assert (_digest_of(ks_cpu) == want).all()

    # XLA engine replay (the batched device path)
    from constdb_tpu.engine.tpu import TpuMergeEngine
    eng = TpuMergeEngine()
    ks_tpu = KeySpace()
    eng.merge(ks_tpu, batch_from_keyspace(ref))
    if getattr(eng, "needs_flush", False):
        eng.flush(ks_tpu)
    assert (_digest_of(ks_tpu) == want).all()

    # sharded plane, 1/2/3 shards: per-shard stores digest their
    # disjoint keys; the plane matrix is the SUM (store/digest.py)
    from constdb_tpu.store.sharded_keyspace import ShardedKeySpace
    for n in (1, 2, 3):
        sks = ShardedKeySpace(n_shards=n, mode="local",
                              engine_factory=CpuMergeEngine)
        sks.submit(batch_from_keyspace(ref))
        sks.flush()
        mats = [D.state_digest_matrix(s, 16, 8) for s in sks.stores]
        got = D.sum_matrices(mats, 16, 8)
        assert (got == want).all(), f"shards={n} digest diverged"
        sks.close()


def test_digest_order_independence_and_locality():
    """Row order and merge order are invisible — one store built by a
    single whole-state merge, another by permuted partial merges (with
    an idempotent re-merge on top), digest identically; and a single
    divergent write flags exactly its own bucket."""
    ops = _mixed_ops()
    ref = KeySpace()
    _apply_ops(ref, ops)
    a, b = KeySpace(), KeySpace()
    CpuMergeEngine().merge(a, batch_from_keyspace(ref))
    n = ref.keys.n
    perm = np.random.RandomState(7).permutation(n)
    eng = CpuMergeEngine()
    # halves land in swapped order, rows permuted, then the whole state
    # re-merges on top: state merges are idempotent + commutative, and
    # the digest sees only the landed result
    eng.merge(b, batch_from_keyspace(ref, key_sel=perm[n // 2:]))
    eng.merge(b, batch_from_keyspace(ref, key_sel=perm[:n // 2]))
    eng.merge(b, batch_from_keyspace(ref, key_sel=perm))
    assert a.canonical() == b.canonical()
    assert (_digest_of(a) == _digest_of(b)).all()

    kid = a.lookup(b"k0000")
    a.register_set(kid, b"DIVERGED", MS0 + (1 << 30), 9)
    da, db = _digest_of(a), _digest_of(b)
    assert int((da != db).sum()) == 1
    # and the divergent bucket's export re-converges the digests
    mask = (da != db).reshape(-1)
    CpuMergeEngine().merge(b, D.export_bucket_batch(a, 16, 8, mask))
    assert (_digest_of(b) == da).all()


def test_digest_inert_tombstone_and_gc_invariance():
    """The two GC-related normalizations: an element del_t at or below
    its add_t is inert and digests as 0 (GC-timing skew must not flag
    spurious divergence), and same-horizon GC on two replicas leaves
    their digests equal (collected rows drop out of the fold on both)."""
    a, b = KeySpace(), KeySpace()
    for ks in (a, b):
        kid, _ = ks.get_or_create(b"s1", S.ENC_SET, MS0 + 100)
        ks.elem_add(kid, b"m1", None, MS0 + 100, 7)
        ks.updated_at(kid, MS0 + 100)
    # an older remove lands on `a` only: semantically inert (the add
    # wins), and the digest must agree it is invisible
    a.elem_merge(a.lookup(b"s1"), b"m1", MS0 + 100, 7, MS0 + 50, None)
    b.elem_merge(b.lookup(b"s1"), b"m1", MS0 + 100, 7, 0, None)
    assert a.canonical() == b.canonical()
    assert (_digest_of(a) == _digest_of(b)).all()

    # dead tombstones + key deletes, collected at the SAME horizon
    ops = _mixed_ops(80, seed=11)
    for ks in (a, b):
        _apply_ops(ks, ops)
        kid = ks.lookup(b"k0004")
        ks.set_delete_time(kid, MS0 + (2 << 30))
        ks.record_key_delete(b"k0004", MS0 + (2 << 30))
        kid = ks.lookup(b"k0007")
        ks.elem_rem(kid, b"m01", MS0 + (2 << 30))
    assert (_digest_of(a) == _digest_of(b)).all()
    horizon = MS0 + (3 << 30)
    assert a.gc(horizon) == b.gc(horizon)
    assert not a.key_deletes and b.lookup(b"k0004") >= 0
    assert (_digest_of(a) == _digest_of(b)).all()


def test_digest_matches_after_coalesced_stream_apply():
    """A node fed by the COALESCED replication applier digests
    identically to one fed the exact per-frame path — the digest is
    computed over landed state, so the micro-batch route is invisible."""
    from constdb_tpu.replica.coalesce import CoalescingApplier
    from constdb_tpu.replica.manager import ReplicaMeta

    frames = []
    prev = 0
    for i, (kind, key, x, uuid) in enumerate(_mixed_ops(120, seed=5)):
        if kind == "set":
            body = [Bulk(b"set"), Bulk(key), Bulk(x)]
        elif kind == "cnt":
            body = [Bulk(b"cntset"), Bulk(key), Int(x)]
        elif kind == "sadd":
            body = [Bulk(b"sadd"), Bulk(key), Bulk(x)]
        else:
            body = [Bulk(b"srem"), Bulk(key), Bulk(x)]
        frames.append([Bulk(b"replicate"), Int(99), Int(prev),
                       Int(MS0 + ((i + 1) << 12)), *body])
        prev = MS0 + ((i + 1) << 12)

    nodes = []
    for batch in (256, 1):  # coalesced vs exact per-frame
        node = Node(node_id=1, engine=CpuMergeEngine())
        applier = CoalescingApplier(node, ReplicaMeta("p:0"),
                                    max_frames=batch, max_latency=10.0)
        for items in frames:
            applier.apply(items)
        applier.flush()
        node.ensure_flushed()
        nodes.append(node)
    d0, d1 = (_digest_of(n.ks) for n in nodes)
    assert (d0 == d1).all()


# --------------------------------------------------------------------------
# e2e: partitioned pair resyncs by delta, not by snapshot


async def _sever(apps) -> None:
    for app in apps:
        for m in list(app.node.replicas.peers.values()):
            m.dial_suspended = True
            if m.link is not None:
                await m.link.stop()
    await asyncio.sleep(0.1)


def _rejoin(apps) -> None:
    for app in apps:
        for m in app.node.replicas.peers.values():
            m.dial_suspended = False
            app.ensure_link(m)


def test_delta_resync_e2e(tmp_path):
    """Partition a converged pair, diverge a small key set past the
    repl_log ring, reconnect: the resync must go DELTA (not snapshot),
    ship less than the full dump would, and land byte-identical
    canonical state; the stream then keeps replicating normally."""
    from cluster_util import Client, close_cluster, converge, make_cluster

    async def main():
        # wire_compress=False pins the pre-compression byte accounting
        # this test is ABOUT (delta bytes vs the full dump it replaced);
        # at this toy scale a compressed full dump is ~2KB and the
        # digest negotiation's frames alone would drown the comparison.
        # Compressed delta/fullsync transfers ride tests/
        # test_wire_compress.py and the chaos compression cells.
        apps = await make_cluster(2, str(tmp_path), repl_log_cap=3000,
                                  wire_compress=False)
        a, b = apps
        try:
            c = await Client().connect(a.advertised_addr)
            for i in range(1000):
                await c.cmd("set", f"k{i:04d}", "v" * 24)
            await c.cmd("meet", b.advertised_addr)
            await converge(apps, timeout=30)
            # the JOIN sync (empty peer = total divergence) must have
            # demoted to a full snapshot, loudly
            assert a.node.stats.repl_full_syncs >= 1
            assert a.node.stats.extra.get("repl_delta_demotions", 0) >= 1
            full_bytes = a.node.stats.extra["last_snapshot_bytes"]
            full0 = a.node.stats.repl_full_syncs

            await _sever(apps)
            # overwrite 20 distinct keys, enough times to evict the ring
            for r in range(12):
                for i in range(20):
                    await c.cmd("set", f"k{i:04d}",
                                f"D{r}-{i}" + "x" * 16)
            resume = b.node.replicas.get(a.advertised_addr).uuid_he_sent
            assert not a.node.repl_log.can_resume_from(resume), \
                "divergence did not evict the ring; test is vacuous"
            b_in0 = b.node.stats.repl_in_bytes
            _rejoin(apps)
            await converge(apps, timeout=30)

            st = a.node.stats
            assert st.repl_delta_syncs >= 1, "resync did not go delta"
            assert st.repl_full_syncs == full0, \
                "delta resync fell back to a snapshot"
            assert st.repl_digest_rounds >= 2
            assert 0 < st.repl_delta_bytes < full_bytes
            resync_in = b.node.stats.repl_in_bytes - b_in0
            assert resync_in < full_bytes, \
                f"resync moved {resync_in}B >= full dump {full_bytes}B"
            assert a.node.canonical() == b.node.canonical()

            # the same connection keeps streaming after the delta
            deltas = st.repl_delta_syncs
            for i in range(30):
                await c.cmd("set", f"post{i}", "z")
            await converge(apps, timeout=15)
            assert st.repl_delta_syncs == deltas  # no re-negotiation
            await c.close()
        finally:
            await close_cluster(apps)
    asyncio.run(main())


def test_delta_disabled_pins_full_sync(tmp_path):
    """CONSTDB_DELTA_SYNC=0 (ServerApp delta_sync=False): the identical
    scenario ships a full snapshot — the delta path is opt-out-able."""
    from cluster_util import Client, close_cluster, converge, make_cluster

    async def main():
        apps = await make_cluster(2, str(tmp_path), repl_log_cap=2000,
                                  delta_sync=False)
        a, b = apps
        try:
            c = await Client().connect(a.advertised_addr)
            for i in range(300):
                await c.cmd("set", f"k{i:04d}", "v" * 24)
            await c.cmd("meet", b.advertised_addr)
            await converge(apps, timeout=30)
            full0 = a.node.stats.repl_full_syncs
            assert full0 >= 1
            await _sever(apps)
            for r in range(12):
                for i in range(10):
                    await c.cmd("set", f"k{i:04d}",
                                f"D{r}-{i}" + "x" * 16)
            _rejoin(apps)
            await converge(apps, timeout=30)
            st = a.node.stats
            assert st.repl_delta_syncs == 0
            assert st.repl_digest_rounds == 0
            assert st.repl_full_syncs > full0
            assert a.node.canonical() == b.node.canonical()
            await c.close()
        finally:
            await close_cluster(apps)
    asyncio.run(main())


# --------------------------------------------------------------------------
# mid-stream ring falloff recovers via digest negotiation (satellite:
# the PR-2 in-place fallback no longer costs a full snapshot)


def _log_write(node: Node, i: int) -> None:
    """One logged `set` mirroring the REAL op exactly (get_or_create
    with ENC_BYTES + register_set + repl_log append) — unlike the
    pushloop suite's enc-agnostic stub, because the loopback sim below
    applies the replicated frames through apply_replicated for real and
    the converged canonical states must match."""
    uuid = node.hlc.tick(True)
    key = b"k%d" % i
    kid, _ = node.ks.get_or_create(key, S.ENC_BYTES, uuid)
    node.ks.register_set(kid, b"x" * 40, uuid, node.node_id)
    node.replicate_cmd(uuid, b"set", [Bulk(key), Bulk(b"x" * 40)])


class _PullerSim:
    """Simulated CAP_DELTA_SYNC puller for a unit-harness pusher: holds
    a real Node, parses every frame the pusher writes, answers digest
    questions through the link's ack queue, applies delta payloads and
    replicate frames — a loopback replica without sockets."""

    def __init__(self, link, writer, node: Node):
        self.link = link
        self.writer = writer
        self.node = node
        self.parser = make_parser()
        self.fed = 0
        self.kinds: list = []
        self._matrix = {}
        self._want_raw = 0
        self._raw = bytearray()

    def _feed(self) -> None:
        buf = self.writer.buf
        if len(buf) > self.fed:
            self.parser.feed(bytes(buf[self.fed:]))
            self.fed = len(buf)

    async def run(self) -> None:
        while True:
            await asyncio.sleep(0.005)
            self._feed()
            while True:
                if self._want_raw:
                    raw = self.parser.take_raw(self._want_raw)
                    if not raw:
                        break
                    self._raw += raw
                    self._want_raw -= len(raw)
                    if self._want_raw:
                        break
                    self._apply_delta(bytes(self._raw))
                    self._raw.clear()
                msg = self.parser.next_msg()
                if msg is None:
                    break
                items = msg.items if isinstance(msg, Arr) else None
                assert items, f"bad frame {msg!r}"
                kind = as_bytes(items[0]).lower()
                self.kinds.append(kind)
                if kind == DIGEST:
                    self._answer(items)
                elif kind == DELTASYNC:
                    self._want_raw = as_int(items[1])
                    self.node.hlc.observe(as_int(items[2]))
                elif kind == FULLSYNC:
                    self._want_raw = as_int(items[1])
                elif kind == REPLICATE:
                    self.node.apply_replicated(
                        as_bytes(items[4]), items[5:], as_int(items[1]),
                        as_int(items[3]))

    def _answer(self, items) -> None:
        token, level = as_int(items[1]), as_int(items[2])
        fanout, leaves = as_int(items[3]), as_int(items[4])
        if level == 0:
            mat = D.state_digest_matrix(self.node.ks, fanout, leaves)
            self._matrix[token] = mat
            theirs = np.frombuffer(as_bytes(items[5]), dtype="<u8")
            mine = mat.sum(axis=1, dtype=np.uint64)
            reply = np.nonzero(mine != theirs)[0].astype("<i8").tobytes()
        else:
            shards = np.frombuffer(as_bytes(items[5]),
                                   dtype="<i8").astype(np.int64)
            sub = np.frombuffer(as_bytes(items[6]), dtype="<u8") \
                .reshape(len(shards), leaves)
            mine = self._matrix[token][shards]
            srow, leaf = np.nonzero(mine != sub)
            reply = (shards[srow] * leaves + leaf).astype("<i8").tobytes()
        self.link._digest_acks.put_nowait(
            [Bulk(DIGESTACK), Int(token), Int(level), Bulk(reply)])

    def _apply_delta(self, payload: bytes) -> None:
        demux = SectionDemux(io.BytesIO(payload))
        eng = CpuMergeEngine()
        for b in demux.batches():
            eng.merge(self.node.ks, b)


def test_midstream_falloff_resyncs_by_delta(tmp_path):
    """Evict the ring past the send cursor mid-stream against a
    CAP_DELTA_SYNC peer: the in-place recovery must run the digest
    negotiation and stream a DELTA — never a full snapshot, never a
    gapped frame — and the loopback puller must converge."""
    async def main():
        node, app, link = _mk_link(tmp_path, cap=100_000)
        # flush+drain per 64-frame run (the pre-wire-buffer cadence):
        # this test's eviction is rigged to fire at drain #1, which must
        # land MID-backlog for the horizon to pass the send cursor
        app.wire_latency = 0.0
        for i in range(100):
            _log_write(node, i)
        link._peer_caps = CAP_FULLSYNC_RESET | CAP_DELTA_SYNC
        link._digest_acks = asyncio.Queue()

        puller = Node(node_id=2)
        CpuMergeEngine().merge(puller.ks, batch_from_keyspace(node.ks))

        def evict(drain_no):
            if drain_no == 1:
                # a burst of 8 large writes on a shrunken ring: eviction
                # races the in-flight stream, divergence stays small
                # enough that the digest path must NOT demote
                node.repl_log.cap = 400
                for i in range(8):
                    _log_write(node, 1000 + i)

        writer = _Writer(on_drain=evict)
        sim = _PullerSim(link, writer, puller)
        sim_task = asyncio.create_task(sim.run())
        push = asyncio.create_task(link._push_loop(writer, peer_resume=0))
        try:
            for _ in range(600):  # phase 1: delta negotiated + applied
                await asyncio.sleep(0.01)
                if node.stats.repl_delta_syncs and not sim._want_raw \
                        and DELTASYNC in sim.kinds:
                    break
            for i in range(2):  # the stream continues after the delta
                _log_write(node, 5000 + i)
            for _ in range(600):  # phase 2: post-delta frames land
                await asyncio.sleep(0.01)
                if puller.ks.lookup(b"k5001") >= 0:
                    break
        finally:
            push.cancel()
            sim_task.cancel()
        assert FULLSYNC not in sim.kinds, \
            "mid-stream falloff still paid a full snapshot"
        assert sim.kinds.count(DIGEST) == 2
        assert DELTASYNC in sim.kinds
        assert node.stats.repl_delta_syncs == 1
        assert node.stats.repl_full_syncs == 0
        assert app.shared_dump.dumps == 0
        # replay is complete: every frame the sim applied + the delta
        # re-based it onto the pusher's state
        assert puller.canonical() == node.canonical()
        assert not writer.closed
    asyncio.run(main())


# --------------------------------------------------------------------------
# serve-plane pusher: digests sum over workers, buckets export encoded


@pytest.mark.slow
def test_delta_resync_from_sharded_pusher(tmp_path, monkeypatch):
    """A shard-per-core node (CONSTDB_SERVE_SHARDS=2) answers the same
    protocol: worker digests sum into the plane matrix, divergent
    buckets export worker-encoded, and the plain peer converges by
    delta."""
    from cluster_util import Client, close_cluster, converge, make_cluster
    monkeypatch.setenv("CONSTDB_SHARD_ENGINE", "cpu")

    async def main():
        apps = await make_cluster(2, str(tmp_path), repl_log_cap=3000,
                                  serve_shards=2)
        a, b = apps  # a is sharded; b (also sharded) pulls by delta too
        try:
            c = await Client().connect(a.advertised_addr)
            for i in range(600):
                await c.cmd("set", f"k{i:04d}", "v" * 24)
            await c.cmd("meet", b.advertised_addr)
            await converge_plane(apps)
            await _sever(apps)
            # every shard SEGMENT carries the full byte cap, so eviction
            # needs ~n_shards times the single-ring divergence volume
            for r in range(30):
                for i in range(15):
                    await c.cmd("set", f"k{i:04d}",
                                f"D{r}-{i}" + "x" * 16)
            resume = b.node.replicas.get(a.advertised_addr).uuid_he_sent
            assert not a.node.repl_log.can_resume_from(resume), \
                "divergence did not evict the ring; test is vacuous"
            _rejoin(apps)
            await converge_plane(apps)
            st = a.node.stats
            assert st.repl_delta_syncs >= 1, "plane pusher never went delta"
            await c.close()
        finally:
            await close_cluster(apps)

    async def converge_plane(apps, timeout=30.0):
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while True:
            canons = []
            for app in apps:
                if app.node.serve_plane is not None:
                    canons.append(await app.node.serve_plane.canonical())
                else:
                    canons.append(app.node.canonical())
            if all(c == canons[0] for c in canons[1:]):
                return
            assert loop.time() < deadline, "no convergence"
            await asyncio.sleep(0.1)

    asyncio.run(main())
