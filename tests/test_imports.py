"""Tripwire: every module in the package must import, and no tracked-dir
source file may be gitignored.

Round 3 lost constdb_tpu/persist/snapshot.py to a `.gitignore` pattern that
silently excluded it from every commit; the dangling import then broke the
persist/replica/server layers two rounds later.  These tests make that
class of loss fail the suite at the first commit instead.
"""

import importlib
import pkgutil
import subprocess
from pathlib import Path

import constdb_tpu

REPO_ROOT = Path(__file__).resolve().parents[1]


def test_every_module_imports():
    failures = []
    for info in pkgutil.walk_packages(constdb_tpu.__path__,
                                      prefix="constdb_tpu."):
        try:
            importlib.import_module(info.name)
        except Exception as e:  # noqa: BLE001 — report them all at once
            failures.append(f"{info.name}: {type(e).__name__}: {e}")
    assert not failures, "unimportable modules:\n" + "\n".join(failures)


def test_no_gitignored_source_files():
    """`git status --ignored` over the package must show no .py/.cpp files
    (a gitignored source file silently vanishes from every commit)."""
    if not (REPO_ROOT / ".git").exists():
        return  # not a git checkout (sdist install) — nothing to check
    try:
        # --ignored=matching lists individual files even when a whole
        # directory is ignored (the default mode collapses to "dir/")
        proc = subprocess.run(
            ["git", "status", "--ignored=matching", "--porcelain",
             "--", "constdb_tpu/", "tests/", "native/"],
            capture_output=True, text=True, timeout=30, cwd=REPO_ROOT)
    except (OSError, subprocess.TimeoutExpired):
        return
    assert proc.returncode == 0, f"git status failed: {proc.stderr}"
    bad = [line for line in proc.stdout.splitlines()
           if line.startswith("!!") and line.endswith((".py", ".cpp", ".h"))
           and "__pycache__" not in line and "/_native/" not in line]
    assert not bad, "gitignored source files (would be lost on reset):\n" \
        + "\n".join(bad)
