"""Handshake capability negotiation (replica/link.py CAP_*) and explicit
watermark adoption (replica/manager.py merge_records).

ADVICE.md round 5: the FULLSYNC `reset` (state-wipe) flag silently
downgraded on mixed-version meshes — a pre-flag peer merged the snapshot
WITHOUT wiping, recreating exactly the resurrection scenario the flag
prevents, with no error on either side.  The handshake now advertises a
capability bitmask (items[6] of both SYNC frames) and the pusher
log-and-REFUSES the state-clearing resync when the peer lacks it."""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from test_link_pushloop import _Writer, _log_write, _mk_link  # noqa: E402

from constdb_tpu.persist.snapshot import ReplicaRecord  # noqa: E402
from constdb_tpu.replica.link import (CAP_DELTA_SYNC,  # noqa: E402
                                      CAP_FULLSYNC_RESET, FULLSYNC, MY_CAPS)
from constdb_tpu.replica.manager import ReplicaManager  # noqa: E402
from constdb_tpu.resp.codec import make_parser  # noqa: E402
from constdb_tpu.resp.message import Arr, Bulk, Int, as_bytes, as_int  # noqa: E402


def _fullsync_reset_flags(buf: bytes):
    """Every FULLSYNC frame's 4th (reset) field in the written stream."""
    parser = make_parser()
    parser.feed(bytes(buf))
    out = []
    while (msg := parser.next_msg()) is not None:
        items = msg.items if isinstance(msg, Arr) else None
        if not items or as_bytes(items[0]).lower() != FULLSYNC:
            continue
        out.append(as_int(items[3]) if len(items) > 3 else None)
        size = as_int(items[1])
        raw = parser.take_raw(size)
        while raw is not None and len(raw) < size:
            more = parser.take_raw(size - len(raw))
            if not more:
                break
            raw += more
    return out


def _off_ring_link(tmp_path, needs_full: bool, peer_caps: int):
    """A link whose peer resume point (0) fell off the repl_log ring, so
    the first push round must decide full-vs-refuse."""
    node, app, link = _mk_link(tmp_path, cap=500)
    for i in range(120):
        _log_write(node, i)
    assert not node.repl_log.can_resume_from(0)
    link.meta.needs_full = needs_full
    link._peer_caps = peer_caps
    return node, app, link


def test_pusher_refuses_reset_without_capability(tmp_path, caplog):
    """needs_full peer + caps=0 (pre-capability build): NO snapshot is
    streamed, the connection drops, the refusal is logged + counted, and
    needs_full stays latched for the retry."""
    async def main():
        node, app, link = _off_ring_link(tmp_path, needs_full=True,
                                         peer_caps=0)
        writer = _Writer()
        await asyncio.wait_for(link._push_loop(writer, peer_resume=0),
                               timeout=5.0)
        assert writer.closed, "refusal must drop the connection"
        assert _fullsync_reset_flags(writer.buf) == []
        assert app.shared_dump.dumps == 0, "no snapshot for a refused sync"
        assert node.stats.extra.get("fullsync_reset_refused") == 1
        assert link.meta.needs_full is True, "refusal must not consume " \
            "the needs_full latch"
        assert any("fullsync-reset capability" in r.message
                   for r in caplog.records)
    asyncio.run(main())


def test_pusher_sends_wiping_resync_with_capability(tmp_path):
    """Same situation, peer advertises CAP_FULLSYNC_RESET: FULLSYNC with
    reset=1 streams and the needs_full latch clears."""
    async def main():
        node, app, link = _off_ring_link(
            tmp_path, needs_full=True, peer_caps=CAP_FULLSYNC_RESET)
        writer = _Writer()
        task = asyncio.create_task(link._push_loop(writer, peer_resume=0))
        try:
            for _ in range(400):
                await asyncio.sleep(0.01)
                if _fullsync_reset_flags(writer.buf):
                    break
        finally:
            task.cancel()
        assert _fullsync_reset_flags(writer.buf) == [1]
        assert app.shared_dump.dumps == 1
        assert link.meta.needs_full is False
        assert not writer.closed
    asyncio.run(main())


def test_plain_fullsync_keeps_reset_zero(tmp_path):
    """An ordinary off-ring catch-up (needs_full=False) never wipes —
    whatever the peer's capabilities."""
    async def main():
        node, app, link = _off_ring_link(tmp_path, needs_full=False,
                                         peer_caps=0)
        writer = _Writer()
        task = asyncio.create_task(link._push_loop(writer, peer_resume=0))
        try:
            for _ in range(400):
                await asyncio.sleep(0.01)
                if _fullsync_reset_flags(writer.buf):
                    break
        finally:
            task.cancel()
        assert _fullsync_reset_flags(writer.buf) == [0]
    asyncio.run(main())


def test_legacy_peer_gets_exact_prepr_fullsync_stream(tmp_path):
    """Mixed-version pin for CAP_DELTA_SYNC: an off-ring catch-up against
    a peer WITHOUT the bit writes not one digest frame — the wire stream
    is the exact pre-delta byte layout (FULLSYNC header + the snapshot
    dump's bytes, reset=0), so a legacy peer never sees a frame kind it
    cannot parse."""
    async def main():
        node, app, link = _off_ring_link(tmp_path, needs_full=False,
                                         peer_caps=CAP_FULLSYNC_RESET)
        assert not (link._peer_caps & CAP_DELTA_SYNC)
        writer = _Writer()
        task = asyncio.create_task(link._push_loop(writer, peer_resume=0))
        try:
            for _ in range(400):
                await asyncio.sleep(0.01)
                if _fullsync_reset_flags(writer.buf):
                    break
        finally:
            task.cancel()
        st = node.stats
        assert st.repl_digest_rounds == 0
        assert st.repl_delta_syncs == 0
        assert st.repl_full_syncs == 1
        assert _fullsync_reset_flags(writer.buf) == [0]
        # byte-exact: the stream opens with the FULLSYNC header followed
        # by the dump file's bytes, nothing negotiated in between
        with open(os.path.join(str(tmp_path), "dump1.snapshot"),
                  "rb") as f:
            dump = f.read()
        from constdb_tpu.resp.codec import encode_msg
        header = encode_msg(Arr([Bulk(FULLSYNC), Int(len(dump)),
                                 Int(node.repl_log.last_uuid), Int(0)]))
        want = header + dump
        assert bytes(writer.buf[:len(want)]) == want
    asyncio.run(main())


def test_check_sync_reply_parses_caps(tmp_path):
    node, app, link = _mk_link(tmp_path)
    reply = Arr([Bulk(b"sync"), Int(1), Int(7), Bulk(b"peer"),
                 Bulk(b"127.0.0.1:2"), Int(42), Int(MY_CAPS)])
    assert link._check_sync_reply(reply) == 42
    assert link._peer_caps == MY_CAPS
    legacy = Arr([Bulk(b"sync"), Int(1), Int(7), Bulk(b"peer"),
                  Bulk(b"127.0.0.1:2"), Int(42)])  # 6-item pre-cap frame
    assert link._check_sync_reply(legacy) == 42
    assert link._peer_caps == 0


def test_caps_exchanged_end_to_end(tmp_path):
    """Real two-node handshake: both sides land on MY_CAPS."""
    from cluster_util import Client, close_cluster, make_cluster

    async def main():
        apps = await make_cluster(2, str(tmp_path))
        try:
            c = await Client().connect(apps[0].advertised_addr)
            await c.cmd("meet", apps[1].advertised_addr)
            for _ in range(200):
                await asyncio.sleep(0.05)
                links = [m.link for a in apps
                         for m in a.node.replicas.live_peers()
                         if m.link is not None and m.link.connected]
                if len(links) >= 2:
                    break
            assert len(links) >= 2
            assert all(lk._peer_caps == MY_CAPS for lk in links)
            # the delta-sync bit is part of the exchanged mask on both
            # sides — the partial-resync path is negotiable mesh-wide
            assert all(lk._peer_caps & CAP_DELTA_SYNC for lk in links)
            await c.close()
        finally:
            await close_cluster(apps)
    asyncio.run(main())


# ------------------------------------------- batch wire (CAP_BATCH_STREAM)


def test_mixed_version_peer_gets_per_frame_stream_both_directions(tmp_path):
    """Mixed-version pin for CAP_BATCH_STREAM: one node pinned to the
    per-frame wire (wire_batch=1 — the pre-PR build's behavior, and the
    CONSTDB_WIRE_BATCH=1 degenerate) meshes with a capable node.  The
    capable node must never send a REPLBATCH frame (the peer did not
    advertise the bit) and the pinned node never does either (kill
    switch disables both legs) — the stream is per-frame in BOTH
    directions, and the mesh still converges.  The byte-exactness of
    that per-frame stream is pinned at the unit level in
    tests/test_wire_batch.py (test_legacy_peer_stream_is_byte_exact)."""
    from cluster_util import Client, close_cluster, converge, make_cluster
    from constdb_tpu.replica.link import CAP_BATCH_STREAM

    async def main():
        apps = await make_cluster(2, str(tmp_path))
        apps[1].wire_batch = 1  # pre-handshake: the bit is never offered
        try:
            c0 = await Client().connect(apps[0].advertised_addr)
            c1 = await Client().connect(apps[1].advertised_addr)
            await c0.cmd("meet", apps[1].advertised_addr)
            for i in range(120):
                await c0.cmd("set", f"a{i}", "x" * 24)
                await c1.cmd("sadd", f"s{i % 7}", f"m{i}")
            await converge(apps, timeout=30.0)
            for app in apps:
                st = app.node.stats
                assert st.repl_wire_batches_out == 0, \
                    "a REPLBATCH frame reached a per-frame stream"
                assert st.repl_wire_batches_in == 0
                assert st.repl_wire_demotions == 0
            # the capable node really did see the bit withheld
            links = [m.link for m in apps[0].node.replicas.live_peers()
                     if m.link is not None and m.link.connected]
            assert links and all(
                not (lk._peer_caps & CAP_BATCH_STREAM) for lk in links)
            await c0.close()
            await c1.close()
        finally:
            await close_cluster(apps)
    asyncio.run(main())


def test_capable_mesh_actually_ships_batches(tmp_path):
    """Control for the mixed-version pin: two capable nodes DO ride the
    batch wire under a pipelined write burst, and converge."""
    from cluster_util import Client, close_cluster, converge, make_cluster
    from constdb_tpu.resp.codec import encode_msg

    async def read_replies(c: "Client", n: int) -> None:
        got = 0
        while got < n:
            if c.parser.next_msg() is not None:
                got += 1
                continue
            data = await asyncio.wait_for(c.reader.read(1 << 16), 10.0)
            assert data, "EOF mid-pipeline"
            c.parser.feed(data)

    async def main():
        apps = await make_cluster(2, str(tmp_path))
        try:
            c0 = await Client().connect(apps[0].advertised_addr)
            await c0.cmd("meet", apps[1].advertised_addr)
            # pipelined burst: the repl_log backlog forms runs
            for chunk in range(6):
                for i in range(50):
                    c0.writer.write(encode_msg(Arr([
                        Bulk(b"set"), Bulk(b"k%d-%d" % (chunk, i)),
                        Bulk(b"v" * 16)])))
                await c0.writer.drain()
                await read_replies(c0, 50)
            await converge(apps, timeout=30.0)
            assert apps[0].node.stats.repl_wire_batches_out > 0, \
                "no REPLBATCH frames on a capable mesh under load"
            assert apps[1].node.stats.repl_wire_batch_frames_in > 0
            assert apps[1].node.stats.repl_wire_demotions == 0
            await c0.close()
        finally:
            await close_cluster(apps)
    asyncio.run(main())


def test_mesh_differential_batch_vs_perframe_node(tmp_path):
    """3-node mesh differential: two batch-wire nodes + one per-frame
    node under mixed write/DEL/membership traffic converge to the
    byte-identical canonical export (the BENCH_r14 acceptance's mesh
    leg, deterministic form)."""
    import random
    from cluster_util import Client, close_cluster, converge, \
        full_mesh, make_cluster

    async def main():
        apps = await make_cluster(3, str(tmp_path))
        apps[2].wire_batch = 1  # the per-frame node
        try:
            clients = [await Client().connect(a.advertised_addr)
                       for a in apps]
            await clients[0].cmd("meet", apps[1].advertised_addr)
            await clients[0].cmd("meet", apps[2].advertised_addr)
            await full_mesh(apps, timeout=30.0)
            rng = random.Random(23)
            for i in range(240):
                c = clients[i % 3]
                r = rng.random()
                k = f"k{rng.randrange(40)}"
                if r < 0.35:
                    await c.cmd("set", "r" + k, f"v{i}")
                elif r < 0.55:
                    await c.cmd("incrby", "c" + k, rng.randrange(1, 9))
                elif r < 0.75:
                    await c.cmd("sadd", "s" + k, f"m{rng.randrange(12)}")
                elif r < 0.85:
                    await c.cmd("hset", "h" + k, "f1", f"v{i}")
                elif r < 0.95:
                    await c.cmd("del", "r" + k)
                else:
                    # membership chatter exercises the barrier plane
                    await c.cmd("replicas")
            # a pipelined burst backs the repl_log up so runs actually
            # form (awaited round-trips drain the log one op at a time)
            from constdb_tpu.resp.codec import encode_msg
            c0 = clients[0]
            for i in range(200):
                c0.writer.write(encode_msg(Arr([
                    Bulk(b"set"), Bulk(b"burst%d" % i), Bulk(b"v" * 12)])))
            await c0.writer.drain()
            got = 0
            while got < 200:
                if c0.parser.next_msg() is not None:
                    got += 1
                    continue
                data = await asyncio.wait_for(c0.reader.read(1 << 16), 10.0)
                assert data, "EOF mid-burst"
                c0.parser.feed(data)
            await converge(apps, timeout=45.0)
            # the batch wire actually carried the capable pairs' stream
            assert sum(a.node.stats.repl_wire_batches_out
                       for a in apps[:2]) > 0
            assert apps[2].node.stats.repl_wire_batches_out == 0
            assert apps[2].node.stats.repl_wire_batches_in == 0
            for a in apps:
                assert a.node.stats.repl_wire_demotions == 0
            for c in clients:
                await c.close()
        finally:
            await close_cluster(apps)
    asyncio.run(main())


# --------------------------------------------------- watermark adoption

def test_merge_records_watermarks_opt_in():
    """A bare membership merge must NOT adopt pull watermarks (it has no
    keyspace state behind them); the snapshot-backed call sites opt in
    explicitly (ADVICE.md round 5)."""
    rows = [ReplicaRecord("10.0.0.9:1", 9, "p", add_t=5,
                          uuid_he_sent=1_000)]
    mgr = ReplicaManager()
    got = mgr.merge_records(rows)  # bare membership merge
    assert got and got[0].addr == "10.0.0.9:1"
    assert mgr.get("10.0.0.9:1").uuid_he_sent == 0

    mgr2 = ReplicaManager()
    mgr2.merge_records(rows, adopt_watermarks=True)  # snapshot-backed
    assert mgr2.get("10.0.0.9:1").uuid_he_sent == 1_000
    # LWW max-merge: an older record never regresses the watermark
    mgr2.merge_records([ReplicaRecord("10.0.0.9:1", 9, "p", add_t=5,
                                      uuid_he_sent=500)],
                       adopt_watermarks=True)
    assert mgr2.get("10.0.0.9:1").uuid_he_sent == 1_000
