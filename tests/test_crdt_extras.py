"""Multi-value register + sequence CRDTs: convergence property tests.

These complete the reference's vestigial scaffolds (src/crdt/vclock.rs,
src/crdt/list.rs) — merge must be commutative, associative, idempotent.
"""

import random

import pytest

from constdb_tpu.crdt.multivalue import MultiValue, VClock
from constdb_tpu.crdt.sequence import Sequence


# ---------------------------------------------------------------- multivalue

def test_vclock_partial_order():
    a = VClock({1: 2, 2: 1})
    b = VClock({1: 1, 2: 1})
    c = VClock({1: 1, 2: 2})
    assert a.dominates(b) and not b.dominates(a)
    assert a.concurrent(c)
    assert a.merge(c).c == {1: 2, 2: 2}


def test_concurrent_writes_become_siblings():
    r1, r2 = MultiValue(), MultiValue()
    r1.write(b"x", node=1)
    r2.write(b"y", node=2)  # concurrent: neither saw the other
    r1.merge(r2)
    assert sorted(r1.read()) == [b"x", b"y"]
    # a reader resolves by writing with the read context
    r1.write(b"z", node=1, context=r1.context())
    assert r1.read() == [b"z"]


def test_causal_write_supersedes():
    r1, r2 = MultiValue(), MultiValue()
    r1.write(b"x", node=1)
    r2.merge(r1)
    r2.write(b"y", node=2)  # saw x
    r1.merge(r2)
    assert r1.read() == [b"y"]


def _random_mv_ops(seed: int, n_nodes: int = 3, n_ops: int = 40):
    rng = random.Random(seed)
    regs = [MultiValue() for _ in range(n_nodes)]
    for i in range(n_ops):
        n = rng.randrange(n_nodes)
        if rng.random() < 0.6:
            regs[n].write(b"v%d" % i, node=n + 1)
        else:
            regs[n].merge(regs[rng.randrange(n_nodes)])
    return regs


@pytest.mark.parametrize("seed", range(8))
def test_mv_merge_properties(seed):
    regs = _random_mv_ops(seed)

    # commutative + convergent: full pairwise mixing in any order agrees
    import copy
    order1 = copy.deepcopy(regs)
    order2 = copy.deepcopy(regs)
    for i in range(len(order1)):
        for j in range(len(order1)):
            order1[i].merge(order1[j])
    for i in reversed(range(len(order2))):
        for j in reversed(range(len(order2))):
            order2[i].merge(order2[j])
    states1 = {r.state() for r in order1}
    states2 = {r.state() for r in order2}
    assert len(states1) == 1 and states1 == states2

    # idempotent
    before = order1[0].state()
    order1[0].merge(order1[0])
    assert order1[0].state() == before


# ------------------------------------------------------------------ sequence

def test_sequence_basic_order():
    s = Sequence()
    s.insert(0, b"b", node=1, uuid=2)
    s.insert(0, b"a", node=1, uuid=3)
    s.insert(2, b"c", node=1, uuid=4)
    assert s.read() == [b"a", b"b", b"c"]
    s.delete(1, uuid=5)
    assert s.read() == [b"a", b"c"]


def test_sequence_concurrent_inserts_converge():
    base = Sequence()
    base.insert(0, b"x", node=1, uuid=1)
    import copy
    s1, s2 = copy.deepcopy(base), copy.deepcopy(base)
    s1.insert(1, b"from1", node=1, uuid=10)
    s2.insert(1, b"from2", node=2, uuid=11)
    s1.merge(s2)
    s2.merge(s1)
    assert s1.read() == s2.read()
    assert set(s1.read()) == {b"x", b"from1", b"from2"}


@pytest.mark.parametrize("seed", range(8))
def test_sequence_merge_properties(seed):
    rng = random.Random(seed)
    import copy
    nodes = [Sequence() for _ in range(3)]
    uuid = 1
    for _ in range(50):
        n = rng.randrange(3)
        s = nodes[n]
        uuid += 1
        live = len(s.read())
        if rng.random() < 0.6 or live == 0:
            s.insert(rng.randrange(live + 1), b"v%d" % uuid, node=n + 1,
                     uuid=uuid)
        elif rng.random() < 0.5:
            s.delete(rng.randrange(live), uuid=uuid)
        else:
            s.merge(nodes[rng.randrange(3)])
    merged = copy.deepcopy(nodes)
    for i in range(3):
        for j in range(3):
            merged[i].merge(merged[j])
    reads = {tuple(m.read()) for m in merged}
    states = {m.state() for m in merged}
    assert len(reads) == 1 and len(states) == 1
    # idempotent
    before = merged[0].state()
    merged[0].merge(merged[0])
    assert merged[0].state() == before
