"""Shared-memory segment lifecycle (parallel/host_pool.py).

A leaked /dev/shm segment survives the creating process on Linux; at
snapshot-merge scale (one segment per job group) leaks fill the tmpfs
and take the box down.  These tests pin the SHM-LIFECYCLE invariant the
lint rule checks statically, at runtime: no segment outlives the pool
after (a) normal completion, (b) a worker crash mid-job, and (c) pool
shutdown with jobs still in flight."""

import os
import signal

import numpy as np
import pytest

import bench
from constdb_tpu.parallel.host_pool import HostShardPool
from constdb_tpu.persist.snapshot import _encode_batch
from constdb_tpu.store.sharded_keyspace import ShardedKeySpace

_I64 = np.int64


def _shm_names() -> set:
    try:
        return {n for n in os.listdir("/dev/shm") if n.startswith("psm_")}
    except FileNotFoundError:  # pragma: no cover - non-Linux
        pytest.skip("/dev/shm not available on this platform")


def _chunks(n_keys=240, n_rep=2, chunk=80):
    return bench.chunk_batches(bench.make_workload(n_keys, n_rep, seed=7),
                               chunk)


def _raw_entries(chunks):
    """Encoded batch sections in the submit_group wire shape (the
    submit_raw path: workers decode + hash themselves)."""
    return [(bytes(_encode_batch(c)), None, None, None, -1, -1)
            for c in chunks]


def test_no_leak_after_normal_completion():
    before = _shm_names()
    sks = ShardedKeySpace(n_shards=2, mode="process", engine_spec="cpu",
                          group=3)
    for c in _chunks():
        sks.submit(c)
    sks.flush()
    assert sks.n_keys() > 0  # the merge actually happened
    sks.close()
    assert _shm_names() - before == set(), "leaked /dev/shm segments"


def test_no_leak_after_worker_crash_mid_job():
    """SIGKILL a worker while groups are in flight: the parent's reap
    surfaces the dead pipe as an error and close() still unlinks every
    job segment."""
    before = _shm_names()
    pool = HostShardPool(2, engine_spec="cpu", max_inflight=2)
    try:
        entries = _raw_entries(_chunks())
        pool.submit_group([], entries[:2])
        os.kill(pool._procs[1].pid, signal.SIGKILL)
        with pytest.raises((EOFError, OSError, RuntimeError)):
            # keep feeding until the dead pipe surfaces (the first
            # submit may have fully completed before the kill landed)
            for _ in range(20):
                pool.submit_group([], entries[2:4])
                pool.barrier()
    finally:
        pool.close()
    assert _shm_names() - before == set(), "leaked /dev/shm segments"


def test_no_leak_on_shutdown_with_jobs_in_flight():
    before = _shm_names()
    sks = ShardedKeySpace(n_shards=2, mode="process", engine_spec="cpu",
                          group=1)  # group=1: every submit ships a segment
    for c in _chunks():
        sks.submit(c)
    sks.close()  # no barrier, no flush: jobs still in flight
    assert _shm_names() - before == set(), "leaked /dev/shm segments"


def test_submit_group_guard_frees_segment_on_failure(monkeypatch):
    """The new creation guard: a failure while POPULATING the segment
    (before registration hands ownership to reap/close) must close +
    unlink it instead of leaking until process exit."""
    before = _shm_names()
    pool = HostShardPool(1, engine_spec="cpu")
    try:
        # entry shaped to blow up inside the population loop: a str has
        # a len() (so sizing + creation succeed) but is not a buffer, so
        # the segment write raises after the segment exists
        with pytest.raises(TypeError):
            pool.submit_group([], [("x" * 64, None, None, None, -1, -1)])
    finally:
        pool.close()
    assert _shm_names() - before == set(), "leaked /dev/shm segments"
