"""Native intake plane (native/intake.cpp + server/serve.py
run_native_chunk + server/io.py): the C scanner's opcode table, the
serve-level byte-identity oracle against the pure planner path, chunk
split invariance, the SYNC/ upgrade stop, the ABI build stamp gate, the
REPLBATCH blob-column fast path, and the end-to-end INFO gauges."""

import asyncio
import random

import pytest

from constdb_tpu.resp.codec import encode_msg, make_parser
from constdb_tpu.resp.message import Arr, Bulk, Err, Int, NIL, Simple
from constdb_tpu.server import serve as SV
from constdb_tpu.server.node import Node
from constdb_tpu.server.serve import ServeCoalescer
from constdb_tpu.utils import native_tables as NT
from constdb_tpu.utils.hlc import SEQ_BITS

ext = NT.load_ext()
pytestmark = pytest.mark.skipif(
    ext is None or not hasattr(ext, "intake_scan"),
    reason="native extension with intake_scan not built")

MS0 = 1_700_000_000_000


def stepping_clock():
    ms = [MS0]

    def clock():
        ms[0] += 1
        return ms[0]
    return clock


def cmd(*parts) -> Arr:
    return Arr([p if isinstance(p, (Bulk, Int)) else
                Bulk(p if isinstance(p, bytes) else str(p).encode())
                for p in parts])


def scan(raw: bytes, pos: int = 0):
    return ext.intake_scan(raw, pos, Arr, Bulk, Int, Simple, Err, NIL)


def mixed_chunks(seed: int, rounds: int = 30):
    """Random pipelined chunks covering every native opcode, OTHER
    demotes (uppercase, barriers, arity errors), and planner demotes
    (non-int counter args)."""
    rng = random.Random(seed)
    keys = [b"k%d" % i for i in range(8)]
    chunks = []
    for _ in range(rounds):
        msgs = []
        for _ in range(rng.randint(1, 12)):
            k = rng.choice(keys)
            c = rng.randint(0, 17)
            if c == 0:
                msgs.append(cmd(b"set", k, b"v%d" % rng.randint(0, 99)))
            elif c == 1:
                msgs.append(cmd(b"incr", k))
            elif c == 2:
                msgs.append(cmd(b"incr", k, rng.randint(-5, 50)))
            elif c == 3:
                msgs.append(cmd(b"decr", k))
            elif c == 4:
                msgs.append(cmd(b"decr", k, rng.randint(0, 9)))
            elif c == 5:
                msgs.append(cmd(b"sadd", k, b"a", b"b%d" % rng.randint(0, 3)))
            elif c == 6:
                msgs.append(cmd(b"srem", k, b"a"))
            elif c == 7:
                msgs.append(cmd(b"hset", k, b"f1", b"x", b"f2",
                                b"y%d" % rng.randint(0, 3)))
            elif c == 8:
                msgs.append(cmd(b"hdel", k, b"f1"))
            elif c == 9:
                msgs.append(cmd(b"get", k))
            elif c == 10:
                msgs.append(cmd(b"scnt", k))
            elif c == 11:
                msgs.append(cmd(b"sismember", k, b"a"))
            elif c == 12:
                msgs.append(cmd(b"smembers", k))
            elif c == 13:
                msgs.append(cmd(b"hget", k, b"f1"))
            elif c == 14:
                msgs.append(cmd(b"hgetall", k))
            elif c == 15:
                msgs.append(cmd(b"llen", k))
            elif c == 16:
                msgs.append(cmd(b"del", k))          # barrier -> OTHER
            else:
                msgs.append(cmd(b"SET", k, b"up"))   # uppercase -> OTHER
        if rng.random() < 0.3:  # planner demote: non-int counter arg
            msgs.append(cmd(b"incr", rng.choice(keys), b"notanint"))
        if rng.random() < 0.2:  # classify demote: set arity
            msgs.append(cmd(b"set", rng.choice(keys), b"v", b"extra"))
        chunks.append(msgs)
    return chunks


def logview(node):
    return [(e.uuid, e.prev_uuid, e.name, e.size,
             tuple((type(a).__name__, a.val) for a in e.args))
            for e in node.repl_log._entries]


# ------------------------------------------------------------ the scanner

def test_opcode_table_and_payload_shapes():
    """The frozen opcode ABI: exact lowercase names + arity gates; write
    payloads carry (bulks, raws) views over the SAME bytes objects;
    anything else is OTHER with a fully-parsed Msg."""
    pipeline = [
        (cmd(b"set", b"k", b"v"), 1),
        (cmd(b"incr", b"k"), 2),
        (cmd(b"incr", b"k", b"5"), 3),
        (cmd(b"decr", b"k"), 4),
        (cmd(b"decr", b"k", b"2"), 5),
        (cmd(b"sadd", b"s", b"a"), 6),
        (cmd(b"srem", b"s", b"a"), 7),
        (cmd(b"hset", b"h", b"f", b"v"), 8),
        (cmd(b"hdel", b"h", b"f"), 9),
        (cmd(b"get", b"k"), 10),
        (cmd(b"scnt", b"s"), 11),
        (cmd(b"sismember", b"s", b"a"), 12),
        (cmd(b"smembers", b"s"), 13),
        (cmd(b"hget", b"h", b"f"), 14),
        (cmd(b"hgetall", b"h"), 15),
        (cmd(b"llen", b"l"), 16),
        (cmd(b"SET", b"k", b"v"), 0),          # uppercase: exact-name only
        (cmd(b"set", b"k", b"v", b"x"), 0),    # arity demote
        (cmd(b"hset", b"h", b"f"), 0),         # hset needs pairs
        (cmd(b"del", b"k"), 0),                # barrier
    ]
    raw = b"".join(encode_msg(m) for m, _ in pipeline)
    ops, payloads, pos = scan(raw)
    assert pos == len(raw)
    assert list(ops) == [op for _, op in pipeline]
    for (msg, op), pl in zip(pipeline, payloads):
        if op == 0:
            assert pl == msg                       # full parsed Msg
        elif op < SV._FIRST_READ_OP:
            bulks, raws = pl
            assert [b.val for b in bulks] == list(raws)
            assert all(b.val is r for b, r in zip(bulks, raws))
            assert Arr([SV._OP_HEAD[op]] + bulks) == msg
        else:
            assert Arr([SV._OP_HEAD[op]] + [Bulk(x) for x in pl]) == msg


def test_scan_stops_at_upgrade_and_partials():
    """The scanner never consumes a SYNC/FULLSYNC frame or a partial
    frame — those bytes stay for the pure parser (server/io.py owns the
    upgrade hand-off)."""
    head = encode_msg(cmd(b"set", b"k", b"v"))
    sync = encode_msg(cmd(b"sync", b"0"))
    tail = encode_msg(cmd(b"incr", b"k"))
    raw = head + sync + tail
    ops, _payloads, pos = scan(raw)
    assert list(ops) == [1] and pos == len(head)
    for cut in range(len(raw)):        # every-prefix truncation
        ops, _p, pos = scan(raw[:cut])
        assert pos <= cut
        boundaries = (0, len(head), len(head) + len(sync))
        assert pos in boundaries       # never lands mid-frame


def test_native_drain_vs_pure_parser_split():
    """Parser-level differential: native_drain's (ops, payloads) recover
    the exact message sequence the pure parser sees, across random feed
    boundaries."""
    rng = random.Random(31)
    msgs = [m for ch in mixed_chunks(31, rounds=10) for m in ch]
    wire = b"".join(encode_msg(m) for m in msgs)
    parser = make_parser()
    got = []
    pos = 0
    while pos < len(wire) or len(got) < len(msgs):
        step = rng.randrange(1, 48)
        parser.feed(wire[pos:pos + step])
        pos += step
        while (nat := parser.native_drain()) is not None:
            for op, pl in zip(nat[0], nat[1]):
                got.append(SV._nat_msg(op, pl))
        got.extend(parser.drain())
    assert got == msgs


# ------------------------------------------------------- the serve oracle

def run_pure(chunks, setup=None):
    node = Node(node_id=1, alias="n1", clock=stepping_clock())
    if setup is not None:
        setup(node)
    coal = ServeCoalescer(node, max_run=64)
    out = bytearray()
    for msgs in chunks:
        coal.run_chunk(list(msgs), out)
    return node, bytes(out)


def run_native(chunks, setup=None):
    node = Node(node_id=1, alias="n1", clock=stepping_clock())
    if setup is not None:
        setup(node)
    coal = ServeCoalescer(node, max_run=64)
    out = bytearray()
    for msgs in chunks:
        raw = b"".join(encode_msg(m) for m in msgs)
        ops, payloads, pos = scan(raw)
        assert pos == len(raw)
        coal.run_native_chunk(ops, payloads, out)
    return node, bytes(out)


@pytest.mark.parametrize("seed", range(8))
def test_native_plan_byte_identity(seed):
    """THE oracle: the native-opcode plan path and the pure planner path
    produce byte-identical reply streams, canonical exports, and
    repl_log entry sequences for the same pipelined workload."""
    chunks = mixed_chunks(seed)
    na, ra = run_pure(chunks)
    nb, rb = run_native(chunks)
    assert ra == rb
    assert na.canonical() == nb.canonical()
    assert logview(na) == logview(nb)


def test_chunk_split_invariance():
    """Splitting the same byte stream at arbitrary boundaries into many
    native chunks (partial frames resuming across feeds) changes nothing:
    same replies, same state as the one-chunk pure run."""
    chunks = mixed_chunks(404, rounds=12)
    msgs = [m for ch in chunks for m in ch]
    na, ra = run_pure([msgs])

    rng = random.Random(7)
    node = Node(node_id=1, alias="n1", clock=stepping_clock())
    coal = ServeCoalescer(node, max_run=64)
    parser = make_parser()
    out = bytearray()
    wire = b"".join(encode_msg(m) for m in msgs)
    pos = 0
    while pos < len(wire):
        step = rng.randrange(1, 96)
        parser.feed(wire[pos:pos + step])
        pos += step
        while (nat := parser.native_drain()) is not None:
            coal.run_native_chunk(nat[0], nat[1], out)
        rest = parser.drain()
        if rest:
            coal.run_chunk(rest, out)
    assert bytes(out) == ra
    assert node.canonical() == na.canonical()
    assert logview(node) == logview(na)


def test_oom_shed_parity():
    """The OOM write-shed decision covers native opcodes exactly like
    pure planner entries (CMD_DENYOOM parity via _OOM_OPS)."""
    chunks = [[cmd(b"set", b"k%d" % i, b"v" * 64) for i in range(8)] +
              [cmd(b"incr", b"c"), cmd(b"srem", b"s", b"a"),
               cmd(b"get", b"k0")]]

    def shed_everything(node):  # tiny cap: every data write sheds
        node.governor.configure(maxmemory=1, soft_pct=0.0)

    na, ra = run_pure(chunks, setup=shed_everything)
    nb, rb = run_native(chunks, setup=shed_everything)
    assert ra == rb
    assert b"OOM" in ra or na.stats.oom_shed_writes > 0
    assert na.stats.oom_shed_writes == nb.stats.oom_shed_writes
    assert na.canonical() == nb.canonical()


# ----------------------------------------------- cluster redirect parity

def _install_cluster(node):
    """Group 0 of a 2-group even split, with the first owned workload
    key's slot mid-handoff — so the differential stream carries local
    serves, MOVED redirects, and ASK redirects at once."""
    from constdb_tpu.cluster import ClusterState, even_split, slot_of
    cl = ClusterState(0, even_split(
        2, addrs=["127.0.0.1:7100", "127.0.0.1:7101"]))
    for i in range(8):
        s = slot_of(b"k%d" % i)
        if cl.owns(s):
            cl.migrating[s] = "127.0.0.1:7101"
            break
    node.cluster = cl


@pytest.mark.parametrize("seed", range(8))
def test_native_redirect_byte_parity(seed):
    """Cluster routing differential: with half the keyspace foreign and
    one owned slot in its ASK window, the native-opcode path and the
    pure planner path emit byte-identical MOVED/ASK redirect streams,
    identical surviving state, and the identical redirects_sent count
    (the serve-plan demotion probe is counter-free; only execute()
    counts)."""
    chunks = mixed_chunks(seed)
    na, ra = run_pure(chunks, setup=_install_cluster)
    nb, rb = run_native(chunks, setup=_install_cluster)
    assert b"MOVED " in ra and b"ASK " in ra
    assert ra == rb
    assert na.canonical() == nb.canonical()
    assert logview(na) == logview(nb)
    assert na.cluster.redirects_sent == nb.cluster.redirects_sent > 0


# ------------------------------------------------------------- abi stamp

def test_abi_stamp_matches_sources():
    assert ext.abi_stamp() == NT.expected_abi_stamp()


def test_stale_extension_refused(monkeypatch):
    """A .so whose compiled-in stamp disagrees with the sources on disk
    must not load (frozen-row-layout law, docs/INVARIANTS.md)."""
    monkeypatch.setattr(NT, "expected_abi_stamp", lambda: "0" * 64)
    monkeypatch.setattr(NT, "_ext", None)
    assert NT.load_ext() is None
    monkeypatch.undo()
    assert NT.reload_tiers()


# ------------------------------------------------------- wire blob columns

def test_wire_blob_pack_unpack_differential():
    """native/wire.cpp vs the pure packers: byte-identical columns and
    round-trips across random shapes, including None sentinels and the
    width-4 boundary."""
    from constdb_tpu.replica import wire as W
    rng = random.Random(11)
    trials = []
    for _ in range(200):
        n = rng.randrange(0, 24)
        trials.append([None if rng.random() < 0.2 else
                       bytes(rng.randrange(256)
                             for _ in range(rng.choice((0, 1, 7, 300))))
                       for _ in range(n)])
    trials.append([b"x" * 0x10000, None, b""])  # forces width 4
    for items in trials:
        nat, pure = bytearray(), bytearray()
        W._pack_blobs(nat, items)               # native engaged
        try:
            W._WIRE_NATIVE_CACHE[:] = [None]    # pin pure
            W._pack_blobs(pure, items)
            assert bytes(nat) == bytes(pure)
            rd = W._Reader(memoryview(bytes(pure)))
            got_pure = rd.blobs(len(items))
            pure_pos = rd.pos
        finally:
            W._WIRE_NATIVE_CACHE.clear()
        rd = W._Reader(memoryview(bytes(nat)))
        got_nat = rd.blobs(len(items))
        assert got_nat == got_pure == items
        assert rd.pos == pure_pos


def test_wire_blob_malformed_errors_unchanged():
    """C decline paths fall through to the pure reader's reference
    errors: bad width byte and truncated payloads raise
    WireFormatError either way."""
    from constdb_tpu.replica import wire as W
    rd = W._Reader(memoryview(b"\x03\x01"))
    with pytest.raises(W.WireFormatError):
        rd.blobs(1)
    good = bytearray()
    W._pack_blobs(good, [b"abcdef"])
    rd = W._Reader(memoryview(bytes(good[:-2])))
    with pytest.raises(W.WireFormatError):
        rd.blobs(1)


# ------------------------------------------------------------- end to end

def test_e2e_gauges_and_pinned_leg(tmp_path, monkeypatch):
    """Over a real socket: the native leg counts native_intake_chunks /
    native_intake_msgs; CONSTDB_NATIVE_INTAKE=0 pins them to zero; both
    legs reply byte-identically."""
    import sys
    sys.path.insert(0, str(tmp_path))  # no-op, keeps flake quiet
    from cluster_util import FAST, Client
    from constdb_tpu.server.io import start_node

    chunk = [cmd(b"set", b"k", b"v"), cmd(b"incr", b"c"),
             cmd(b"sadd", b"s", b"a", b"b"), cmd(b"get", b"k"),
             cmd(b"scnt", b"s"), cmd(b"del", b"k"), cmd(b"get", b"k")]

    async def leg(work_dir, native):
        monkeypatch.setenv("CONSTDB_NATIVE_INTAKE", "1" if native else "0")
        node = Node(node_id=1, alias="n1")
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=str(work_dir), **FAST)
        c = await Client().connect(app.advertised_addr)
        c.writer.write(b"".join(encode_msg(m) for m in chunk))
        await c.writer.drain()
        replies = []
        while len(replies) < len(chunk):
            m = c.parser.next_msg()
            if m is not None:
                replies.append(m)
                continue
            c.parser.feed(await asyncio.wait_for(
                c.reader.read(1 << 16), 5.0))
        await c.close()
        gauges = (node.stats.native_intake_chunks,
                  node.stats.native_intake_msgs)
        await app.close()
        return replies, gauges

    async def main():
        d1 = tmp_path / "on"
        d2 = tmp_path / "off"
        d1.mkdir()
        d2.mkdir()
        r_on, g_on = await leg(d1, True)
        r_off, g_off = await leg(d2, False)
        assert g_on[0] > 0 and g_on[1] >= len(chunk)
        assert g_off == (0, 0)
        assert [encode_msg(m) for m in r_on] == \
            [encode_msg(m) for m in r_off]

    asyncio.run(main())
