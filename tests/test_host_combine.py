"""Host group pre-combine + deferred win resolution (engine/tpu.py).

The transfer-bound paths: aligned groups fold on host before upload,
disjoint groups concatenate to one transfer, and resident mode resolves
win VALUES from the device src plane once at flush instead of downloading
win flags per call.  All must stay bit-identical to the CPU engine.
"""

import numpy as np
import pytest

from constdb_tpu.engine.base import batch_from_keyspace
from constdb_tpu.engine.cpu import CpuMergeEngine
from constdb_tpu.engine.tpu import TpuMergeEngine
from constdb_tpu.persist.snapshot import batch_chunks
from constdb_tpu.resp.message import Bulk
from constdb_tpu.server.node import Node
from constdb_tpu.store.keyspace import KeySpace

from test_merge_properties import gen_store


def _cmd(node, *parts):
    return node.execute([Bulk(p if isinstance(p, bytes) else str(p).encode())
                         for p in parts])


def _cpu_ref(batches):
    st = KeySpace()
    cpu = CpuMergeEngine()
    for b in batches:
        cpu.merge(st, b)
    return st


@pytest.mark.parametrize("resident", [False, True])
def test_aligned_group_host_folds(resident):
    """R replica dumps over one key list fold on host: folds > 0, exact."""
    import bench
    batches = bench.make_workload(400, 4, seed=5)
    eng = TpuMergeEngine(resident=resident)
    st = KeySpace()
    eng.merge_many(st, batches)
    if eng.needs_flush:
        eng.flush(st)
    assert eng.folds > 0
    assert st.canonical() == _cpu_ref(batches).canonical()


@pytest.mark.parametrize("resident", [False, True])
def test_disjoint_group_combines(resident):
    """Consecutive chunks of ONE snapshot (disjoint key ranges) merge as a
    single combined call — the link's grouped cadence."""
    src = gen_store(seed=77, node=3)
    chunks = list(batch_chunks(batch_from_keyspace(src), 5))
    assert len(chunks) > 2
    eng = TpuMergeEngine(resident=resident)
    st = KeySpace()
    eng.merge_many(st, chunks)
    if eng.needs_flush:
        eng.flush(st)
    assert st.canonical() == _cpu_ref(chunks).canonical()


def test_deferred_dict_values_resolve_at_flush():
    """Dict VALUES win through the src plane and appear only after flush."""
    a, b = Node(node_id=1), Node(node_id=2)
    for i in range(40):
        _cmd(a, b"hset", b"h%d" % (i % 5), b"f%d" % i, b"va%d" % i)
    for i in range(40):
        _cmd(b, b"hset", b"h%d" % (i % 5), b"f%d" % i, b"vb%d" % i)
    batches = [batch_from_keyspace(a.ks), batch_from_keyspace(b.ks)]
    eng = TpuMergeEngine(resident=True)
    st = KeySpace()
    eng.merge_many(st, batches)
    assert eng.needs_flush
    eng.flush(st)
    assert st.canonical() == _cpu_ref(batches).canonical()
    # a second flush with no merges must not re-resolve a cleared pool
    eng.flush(st)
    assert st.canonical() == _cpu_ref(batches).canonical()


def test_valueless_add_clears_stale_dict_value():
    """A winning None-valued element (set-style row on a dict key) must
    CLEAR a stored value through the deferred path, exactly like the CPU
    engine's local-loses rule."""
    a = Node(node_id=1)
    _cmd(a, b"hset", b"h", b"f", b"old")
    base = batch_from_keyspace(a.ks)

    newer = Node(node_id=2)
    _cmd(newer, b"hset", b"h", b"f", b"mid")
    nb = batch_from_keyspace(newer.ks)
    # strip the value but keep a LATER add (valueless winning add)
    nb.el_val = [None] * len(nb.el_val)
    nb.el_add_t = base.el_add_t + (1 << 30)

    eng = TpuMergeEngine(resident=True)
    st = KeySpace()
    eng.merge(st, base)
    eng.merge(st, nb)
    eng.flush(st)
    assert st.canonical() == _cpu_ref([base, nb]).canonical()


def test_src_plane_is_int32_and_replaces_column_downloads():
    """The el src plane is always tracked (round-5 transfer diet): one
    int32 download at flush replaces the add_t + add_node int64 downloads
    — strictly cheaper even for pure set traffic (4 bytes/slot vs 16)."""
    import numpy as np

    batches = []
    for r in range(3):
        n = Node(node_id=r + 1)
        for i in range(50):
            _cmd(n, b"sadd", b"s%d" % (i % 9), b"m%d-%d" % (r, i))
        batches.append(batch_from_keyspace(n.ks))
    eng = TpuMergeEngine(resident=True)
    st = KeySpace()
    eng.merge_many(st, batches)
    res = eng._res.get("el")
    assert res is not None and res.get("src") is not None
    assert np.asarray(res["src"]).dtype == np.int32
    assert res.get("recon") == {"add_t": "add_t", "add_node": "add_node"}
    eng.flush(st)
    assert st.canonical() == _cpu_ref(batches).canonical()


def test_reconstructed_columns_bit_identical_to_downloads():
    """Round-5 transfer diet: flush reconstructs el add_t/add_node, reg
    rv_t/rv_node and cnt val/uuid from the host win pool via the src
    plane.  Control = the same merged device state with reconstruction
    disabled (recon cleared → every written column downloads).  The two
    keyspaces must match column-for-column, bit for bit."""
    import bench
    chunks = []
    for b in bench.make_workload(3000, 4, seed=11):
        chunks.extend(batch_chunks(b, 700))

    def run(strip_recon: bool) -> KeySpace:
        eng = TpuMergeEngine(resident=True)
        st = KeySpace()
        for i in range(0, len(chunks), 4):
            eng.merge_many(st, chunks[i:i + 4])
        assert any(r.get("src") is not None for r in eng._res.values())
        if strip_recon:
            for r in eng._res.values():
                r["recon"] = None  # force the full-download flush path
        eng.flush(st)
        return st

    recon, ctrl = run(False), run(True)
    for name in ("ct", "mt", "dt", "expire", "rv_t", "rv_node"):
        np.testing.assert_array_equal(recon.keys.col(name)[:recon.keys.n],
                                      ctrl.keys.col(name)[:ctrl.keys.n],
                                      err_msg=f"keys.{name}")
    for name in ("val", "uuid", "base", "base_t"):
        np.testing.assert_array_equal(recon.cnt.col(name)[:recon.cnt.n],
                                      ctrl.cnt.col(name)[:ctrl.cnt.n],
                                      err_msg=f"cnt.{name}")
    for name in ("add_t", "add_node", "del_t"):
        np.testing.assert_array_equal(recon.el.col(name)[:recon.el.n],
                                      ctrl.el.col(name)[:ctrl.el.n],
                                      err_msg=f"el.{name}")
    assert recon.reg_val == ctrl.reg_val
    assert recon.el_val == ctrl.el_val
    assert recon.canonical() == ctrl.canonical() == \
        _cpu_ref(chunks).canonical()


def test_device_iota_idx_matches_uploaded_idx():
    """Contiguous-row batches derive their scatter index on device from
    three scalars (engine IDX_IOTA_MIN); the merged store must be
    bit-identical to the uploaded-index path AND to the CPU engine —
    including non-contiguous batches that must keep uploading."""
    import bench
    chunks = []
    for b in bench.make_workload(2500, 4, seed=31):
        chunks.extend(batch_chunks(b, 600))

    def run(iota_min: int) -> KeySpace:
        eng = TpuMergeEngine(resident=True)
        eng.IDX_IOTA_MIN = iota_min
        st = KeySpace()
        for i in range(0, len(chunks), 4):
            eng.merge_many(st, chunks[i:i + 4])
        eng.flush(st)
        return st

    a, b = run(1), run(1 << 60)
    assert a.canonical() == b.canonical() == _cpu_ref(chunks).canonical()


def test_mixed_streaming_groups_match_cpu():
    """Streaming grouped catch-up from several replicas (the bench shape,
    interleaved chunk arrival) stays exact across group boundaries."""
    srcs = [gen_store(seed=50 + i, node=i + 1) for i in range(3)]
    per = [list(batch_chunks(batch_from_keyspace(s), 17)) for s in srcs]
    interleaved = [p[i] for i in range(max(map(len, per)))
                   for p in per if i < len(p)]
    eng = TpuMergeEngine(resident=True)
    st = KeySpace()
    for i in range(0, len(interleaved), 3):
        eng.merge_many(st, interleaved[i:i + 3])
    eng.flush(st)
    assert st.canonical() == _cpu_ref(interleaved).canonical()


def test_hierarchical_mixed_group_combines():
    """A group spanning several key RANGES from several REPLICAS (the
    large-group catch-up shape) folds per aligned cluster, then the folds
    concatenate — one engine call for the whole group, still exact."""
    import bench
    batches = bench.make_workload(300, 4, seed=9)
    per = [list(batch_chunks(b, 100)) for b in batches]      # 3 ranges x 4
    mixed = [p[i] for i in range(3) for p in per]            # interleaved
    assert len(mixed) == 12
    eng = TpuMergeEngine(resident=True)
    st = KeySpace()
    eng.merge_many(st, mixed)     # ONE call with all 12 chunks
    eng.flush(st)
    assert eng.folds >= 3         # one fold per aligned range cluster
    assert st.canonical() == _cpu_ref(mixed).canonical()


def test_del_plane_never_crosses_the_link():
    """The element DEL side is host-maintained in the src path (round-5
    diet): the add kernels never read del_t for wins and del-merge is a
    plain max, so zero del bytes cross the link in either direction —
    for all-add traffic AND for tombstone-heavy batches.  Newly-dead rows
    still reach the GC queue (via the flush-time _el_del_touched sweep)."""
    adds = []
    for r in range(3):
        n = Node(node_id=r + 1)
        for i in range(60):
            _cmd(n, b"sadd", b"k%d" % (i % 12), b"m%d-%d" % (r, i))
        adds.append(batch_from_keyspace(n.ks))
    eng = TpuMergeEngine(resident=True)
    st = KeySpace()
    eng.merge_many(st, adds)
    assert "del_t" not in eng._res["el"]["written"]  # nothing shipped
    eng.flush(st)
    assert st.canonical() == _cpu_ref(adds).canonical()

    # deletion-heavy batch: still no device del plane, still exact, and
    # the tombstones are queued for GC exactly like the CPU engine's
    heavy = Node(node_id=9)
    for i in range(40):
        _cmd(heavy, b"sadd", b"d%d" % (i % 8), b"x%d" % i)
    for i in range(40):
        _cmd(heavy, b"srem", b"d%d" % (i % 8), b"x%d" % i)
    hb = batch_from_keyspace(heavy.ks)
    eng2 = TpuMergeEngine(resident=True)
    st2 = KeySpace()
    eng2.merge_many(st2, [hb, adds[0]])
    assert "del_t" not in eng2._res["el"]["written"]
    eng2.flush(st2)
    ref = _cpu_ref([hb, adds[0]])
    assert st2.canonical() == ref.canonical()
    assert sorted(st2.garbage) == sorted(ref.garbage)


def test_auto_flush_mid_stream_stays_exact():
    """The win-pool byte bound (engine pool_flush_bytes) forces flushes
    MID catch-up; interleaving device merges with reconstruction flushes
    must stay bit-identical to the CPU engine (the bench and replica link
    normally flush once at the end, so this path needs its own pin)."""
    import bench
    chunks = []
    for b in bench.make_workload(4000, 4, seed=55):
        chunks.extend(batch_chunks(b, 900))
    eng = TpuMergeEngine(resident=True)
    eng.pool_flush_bytes = 1 << 12  # 4KB: every group trips the bound
    st = KeySpace()
    staged = 0
    for i in range(0, len(chunks), 4):
        eng.merge_many(st, chunks[i:i + 4])
        if not eng.needs_flush:
            staged += 1
    # anti-vacuity: real flush WORK happened mid-stream (several source
    # downloads), not merely "nothing was ever staged"
    assert eng.family_secs["flush"] > 0
    assert eng.bytes_d2h > 0
    assert staged >= 3, "bound never tripped — test is vacuous"
    eng.flush(st)
    assert st.canonical() == _cpu_ref(chunks).canonical()
