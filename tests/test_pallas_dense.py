"""Pallas fused dense merge kernels vs the XLA reference (ops/dense.py).

Runs through the Pallas interpreter on the CPU platform (same kernel code
path as TPU, minus the Mosaic compile), over adversarial int64 data:
NEUTRAL_T sentinels, negative values, 63-bit uuids, exact ties.
"""

import numpy as np
import pytest

import jax

from constdb_tpu.crdt.semantics import NEUTRAL_T
from constdb_tpu.ops import dense as D
from constdb_tpu.ops import pallas_dense as PD

INTERPRET = jax.default_backend() != "tpu"


def _cols(rng, R, S, ties=True):
    t = rng.integers(0, 1 << 62, (R, S)).astype(np.int64)
    t[rng.random((R, S)) < 0.25] = NEUTRAL_T
    if ties:
        # force exact ties between rows on a third of the slots
        cols = rng.random(S) < 0.33
        t[:, cols] = t[0, cols]
    return t


@pytest.mark.parametrize("seed,R,S", [(0, 2, 64), (1, 8, 512),
                                      (2, 9, 1000), (3, 16, 4096)])
def test_merge_elems_matches_xla(seed, R, S):
    rng = np.random.default_rng(seed)
    at = _cols(rng, R, S)
    an = rng.integers(0, 1 << 31, (R, S)).astype(np.int64)
    an[rng.random((R, S)) < 0.2] = NEUTRAL_T
    dt = np.where(rng.random((R, S)) < 0.5,
                  rng.integers(0, 1 << 62, (R, S)), 0).astype(np.int64)

    a1, n1, d1, w1 = (np.asarray(x) for x in D.dense_merge_elems(at, an, dt))
    a2, n2, d2, w2 = (np.asarray(x) for x in
                      PD.merge_elems(at, an, dt, interpret=INTERPRET))
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(n1, n2)
    np.testing.assert_array_equal(d1, d2)
    np.testing.assert_array_equal(w1, w2)


@pytest.mark.parametrize("seed,R,S", [(0, 2, 64), (1, 8, 512), (2, 16, 3000)])
def test_merge_counters_matches_xla(seed, R, S):
    rng = np.random.default_rng(seed)
    ts = _cols(rng, R, S)
    vals = rng.integers(-(1 << 40), 1 << 40, (R, S)).astype(np.int64)
    # exact-uuid ties must resolve by max value on both paths
    v1, t1 = (np.asarray(x) for x in D.dense_merge_counters(vals, ts))
    v2, t2 = (np.asarray(x) for x in
              PD.merge_counters(vals, ts, interpret=INTERPRET))
    np.testing.assert_array_equal(t1, t2)
    np.testing.assert_array_equal(v1, v2)


def test_negative_and_extreme_values():
    """Full-range int64 round-trips through the hi/lo split correctly."""
    at = np.array([[NEUTRAL_T, -1, (1 << 62) - 1, 0],
                   [0, -2, (1 << 62) - 2, NEUTRAL_T]], dtype=np.int64)
    an = np.array([[1, 5, 2, NEUTRAL_T],
                   [2, 4, 3, NEUTRAL_T]], dtype=np.int64)
    dt = np.array([[0, 3, 0, 0], [5, 0, 0, 0]], dtype=np.int64)
    a1, n1, d1, w1 = (np.asarray(x) for x in D.dense_merge_elems(at, an, dt))
    a2, n2, d2, w2 = (np.asarray(x) for x in
                      PD.merge_elems(at, an, dt, interpret=INTERPRET))
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(n1, n2)
    np.testing.assert_array_equal(d1, d2)
    np.testing.assert_array_equal(w1, w2)
