"""Pallas fused dense merge kernels vs the XLA reference (ops/dense.py).

Runs through the Pallas interpreter on the CPU platform (same kernel code
path as TPU, minus the Mosaic compile), over adversarial int64 data:
NEUTRAL_T sentinels, negative values, 63-bit uuids, exact ties.
"""

import numpy as np
import pytest

import jax

from constdb_tpu.crdt.semantics import NEUTRAL_T
from constdb_tpu.ops import dense as D
from constdb_tpu.ops import pallas_dense as PD

INTERPRET = jax.default_backend() != "tpu"


def _cols(rng, R, S, ties=True):
    t = rng.integers(0, 1 << 62, (R, S)).astype(np.int64)
    t[rng.random((R, S)) < 0.25] = NEUTRAL_T
    if ties:
        # force exact ties between rows on a third of the slots
        cols = rng.random(S) < 0.33
        t[:, cols] = t[0, cols]
    return t


@pytest.mark.parametrize("seed,R,S", [(0, 2, 64), (1, 8, 512),
                                      (2, 9, 1000), (3, 16, 4096)])
def test_merge_elems_matches_xla(seed, R, S):
    rng = np.random.default_rng(seed)
    at = _cols(rng, R, S)
    an = rng.integers(0, 1 << 31, (R, S)).astype(np.int64)
    an[rng.random((R, S)) < 0.2] = NEUTRAL_T
    dt = np.where(rng.random((R, S)) < 0.5,
                  rng.integers(0, 1 << 62, (R, S)), 0).astype(np.int64)

    a1, n1, d1, w1 = (np.asarray(x) for x in D.dense_merge_elems(at, an, dt))
    a2, n2, d2, w2 = (np.asarray(x) for x in
                      PD.merge_elems(at, an, dt, interpret=INTERPRET))
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(n1, n2)
    np.testing.assert_array_equal(d1, d2)
    np.testing.assert_array_equal(w1, w2)


@pytest.mark.parametrize("seed,R,S", [(0, 2, 64), (1, 8, 512), (2, 16, 3000)])
def test_merge_counters_matches_xla(seed, R, S):
    rng = np.random.default_rng(seed)
    ts = _cols(rng, R, S)
    vals = rng.integers(-(1 << 40), 1 << 40, (R, S)).astype(np.int64)
    # exact-uuid ties must resolve by max value on both paths
    v1, t1 = (np.asarray(x) for x in D.dense_merge_counters(vals, ts))
    v2, t2 = (np.asarray(x) for x in
              PD.merge_counters(vals, ts, interpret=INTERPRET))
    np.testing.assert_array_equal(t1, t2)
    np.testing.assert_array_equal(v1, v2)


def test_negative_and_extreme_values():
    """Full-range int64 round-trips through the hi/lo split correctly."""
    at = np.array([[NEUTRAL_T, -1, (1 << 62) - 1, 0],
                   [0, -2, (1 << 62) - 2, NEUTRAL_T]], dtype=np.int64)
    an = np.array([[1, 5, 2, NEUTRAL_T],
                   [2, 4, 3, NEUTRAL_T]], dtype=np.int64)
    dt = np.array([[0, 3, 0, 0], [5, 0, 0, 0]], dtype=np.int64)
    a1, n1, d1, w1 = (np.asarray(x) for x in D.dense_merge_elems(at, an, dt))
    a2, n2, d2, w2 = (np.asarray(x) for x in
                      PD.merge_elems(at, an, dt, interpret=INTERPRET))
    np.testing.assert_array_equal(a1, a2)
    np.testing.assert_array_equal(n1, n2)
    np.testing.assert_array_equal(d1, d2)
    np.testing.assert_array_equal(w1, w2)


# -------------------------------------------------- resident scatter kernels
# The steady-state micro-path kernels (gather-compare-scatter over one LWW
# pair + the segment-sum counter re-derivation) vs their XLA twins
# (ops/bulk.py bulk_lww_src / ops/dense.py segment_sum) and the host
# reference, over the engine's exact padding protocol.

import jax.numpy as jnp

from constdb_tpu.engine.tpu import TpuMergeEngine
from constdb_tpu.ops import bulk as B


def _pad1(arr, n, fill):
    out = np.full(n, fill, dtype=arr.dtype)
    out[:len(arr)] = arr
    return out


def _scatter_both(p, s, src, idx, bp, bs, base):
    """Run the Pallas scatter (engine padding protocol: pads target a
    free row with NEUTRAL values) and the XLA twin (pads out of range)
    on copies; -> ((p, s, src) pallas, (p, s, src) xla)."""
    sp, n = len(p), len(idx)
    np2 = PD._pow2(max(n, 1))
    pad_row = TpuMergeEngine._scatter_pad_row(idx.astype(np.int64), n, sp) \
        if np2 > n else 0
    pl_out = PD.scatter_pair_src(
        jnp.array(p), jnp.array(s), jnp.array(src),
        jnp.array(_pad1(idx, np2, pad_row)),
        jnp.array(_pad1(bp, np2, NEUTRAL_T)),
        jnp.array(_pad1(bs, np2, NEUTRAL_T)),
        np.int32(base), interpret=INTERPRET)
    idx_x = np.concatenate([idx, (sp + np.arange(np2 - n)).astype(np.int32)])
    xla_out = B.bulk_lww_src(
        jnp.array(p), jnp.array(s), jnp.array(src), jnp.array(idx_x),
        jnp.array(_pad1(bp, np2, NEUTRAL_T)),
        jnp.array(_pad1(bs, np2, NEUTRAL_T)), base)
    return tuple(np.asarray(x) for x in pl_out), \
        tuple(np.asarray(x) for x in xla_out)


def _host_scatter_ref(p, s, src, idx, bp, bs, base):
    """Per-row host reference: lexicographic (primary, secondary) win —
    exactly crdt/semantics.py lww_wins / hostbatch's fold rule."""
    p, s, src = p.copy(), s.copy(), src.copy()
    for j, r in enumerate(idx.tolist()):
        win = (bp[j] > p[r]) or (bp[j] == p[r] and bs[j] > s[r])
        if win:
            p[r], s[r], src[r] = bp[j], bs[j], base + j
    return p, s, src


def _scatter_case(rng, sp):
    n = int(rng.integers(1, sp + 1))
    idx = np.sort(rng.choice(sp, n, replace=False)).astype(np.int32)
    p = rng.integers(-9, 9, sp).astype(np.int64)
    s = rng.integers(-9, 9, sp).astype(np.int64)
    p[rng.random(sp) < 0.2] = NEUTRAL_T
    src = np.where(rng.random(sp) < 0.5, -1,
                   rng.integers(0, 50, sp)).astype(np.int32)
    bp = rng.integers(-9, 9, n).astype(np.int64)
    bs = rng.integers(-9, 9, n).astype(np.int64)
    # equal-stamp ties (local must keep) and full-pair ties
    for j in range(n):
        if rng.random() < 0.3:
            bp[j] = p[idx[j]]
        if rng.random() < 0.3:
            bs[j] = s[idx[j]]
    return p, s, src, idx, bp, bs, int(rng.integers(0, 1000))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_scatter_pair_xla_twin_matches_host(seed):
    """The XLA resident-scatter twin (ops/bulk.py bulk_lww_src) vs the
    per-row host reference, randomized — cheap enough for tier-1 at full
    shape coverage (XLA traces are ~ms; the Pallas interpreter pays ~1s
    PER SHAPE to trace, so its randomized twin runs in the slow suite
    and tier-1 keeps the small fixed-shape Pallas cases below)."""
    from constdb_tpu.ops import bulk as B
    rng = np.random.default_rng(seed)
    for _ in range(25):
        sp = int(2 ** rng.integers(0, 7))
        p, s, src, idx, bp, bs, base = _scatter_case(rng, sp)
        n = len(idx)
        np2 = PD._pow2(n)
        idx_x = np.concatenate([idx,
                                (sp + np.arange(np2 - n)).astype(np.int32)])
        got = tuple(np.asarray(x) for x in B.bulk_lww_src(
            jnp.array(p), jnp.array(s), jnp.array(src), jnp.array(idx_x),
            jnp.array(_pad1(bp, np2, NEUTRAL_T)),
            jnp.array(_pad1(bs, np2, NEUTRAL_T)), base))
        want = _host_scatter_ref(p, s, src, idx, bp, bs, base)
        for g, w, name in zip(got, want, ("primary", "secondary", "src")):
            np.testing.assert_array_equal(g, w, err_msg=name)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_scatter_pair_src_matches_xla_and_host(seed):
    rng = np.random.default_rng(seed)
    for _ in range(16):
        sp = int(2 ** rng.integers(0, 7))
        p, s, src, idx, bp, bs, base = _scatter_case(rng, sp)
        got_pl, got_xla = _scatter_both(p, s, src, idx, bp, bs, base)
        want = _host_scatter_ref(p, s, src, idx, bp, bs, base)
        for g, x, w, name in zip(got_pl, got_xla, want,
                                 ("primary", "secondary", "src")):
            np.testing.assert_array_equal(x, w, err_msg=f"xla {name}")
            np.testing.assert_array_equal(g, w, err_msg=f"pallas {name}")


def test_scatter_pad_collision_would_revert():
    """The pad-targeting contract (ops/pallas_dense.py): a pad aliased
    onto a REAL row's target reads pre-merge state and reverts the
    merge.  _scatter_pad_row must therefore pick a row outside the
    batch — pinned both ways."""
    sp = 8
    p = np.zeros(sp, dtype=np.int64)
    s = np.zeros(sp, dtype=np.int64)
    src = np.full(sp, -1, np.int32)
    idx = np.array([0], dtype=np.int32)       # one real row, wins slot 0
    bp = np.array([5], dtype=np.int64)
    bs = np.array([1], dtype=np.int64)
    # engine helper picks a free row — result must match the reference
    assert TpuMergeEngine._scatter_pad_row(idx.astype(np.int64), 1, sp) == 1
    got_pl, got_xla = _scatter_both(p, s, src, idx, bp, bs, 7)
    want = _host_scatter_ref(p, s, src, idx, bp, bs, 7)
    for g, x, w in zip(got_pl, got_xla, want):
        np.testing.assert_array_equal(g, w)
        np.testing.assert_array_equal(x, w)


def test_scatter_pad_row_finds_interior_gap():
    rows = np.array([0, 1, 3, 4, 6, 7], dtype=np.int64)  # 2 and 5 absent
    assert TpuMergeEngine._scatter_pad_row(rows, len(rows), 8) == 2
    rows = np.array([1, 2, 3], dtype=np.int64)
    assert TpuMergeEngine._scatter_pad_row(rows, len(rows), 4) == 0
    rows = np.array([0, 1, 2], dtype=np.int64)
    assert TpuMergeEngine._scatter_pad_row(rows, len(rows), 8) == 3


@pytest.mark.parametrize("seed,n,n_seg", [(0, 1, 1), (1, 33, 7),
                                          (2, 257, 64), (3, 1000, 100)])
def test_segment_sum_matches_xla_and_host(seed, n, n_seg):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, n_seg, n).astype(np.int32)
    # full-range magnitudes force the unsigned lo-word carry chains
    vals = rng.integers(-(1 << 61), 1 << 61, n).astype(np.int64)
    got = np.asarray(PD.segment_sum(jnp.array(ids), jnp.array(vals),
                                    n_seg=n_seg, interpret=INTERPRET))
    xla = np.asarray(D.segment_sum(jnp.array(ids), jnp.array(vals),
                                   n_seg=n_seg))
    want = np.zeros(n_seg, dtype=np.int64)
    np.add.at(want, ids, vals)
    np.testing.assert_array_equal(xla, want)
    np.testing.assert_array_equal(got, want)


def test_segment_sum_carry_boundary():
    """Sums crossing the uint32 boundary exercise the explicit carry."""
    ids = np.zeros(8, dtype=np.int32)
    vals = np.full(8, (1 << 32) - 1, dtype=np.int64)
    got = np.asarray(PD.segment_sum(jnp.array(ids), jnp.array(vals),
                                    n_seg=3, interpret=INTERPRET))
    assert got.tolist() == [8 * ((1 << 32) - 1), 0, 0]
    # negative totals round-trip the split sign correctly
    vals = np.array([-(1 << 40), 1, -(1 << 33), 5], dtype=np.int64)
    ids = np.array([0, 1, 0, 1], dtype=np.int32)
    got = np.asarray(PD.segment_sum(jnp.array(ids), jnp.array(vals),
                                    n_seg=2, interpret=INTERPRET))
    assert got.tolist() == [-(1 << 40) - (1 << 33), 6]


def test_segment_sum_scratch_cap():
    with pytest.raises(ValueError):
        PD.segment_sum(jnp.zeros(4, jnp.int32), jnp.zeros(4, jnp.int64),
                       n_seg=PD.SEGMENT_SUM_MAX_SEG + 1, interpret=INTERPRET)

# ---------------------------------------------------- pre-split planes
# The retired PR 8 follow-up: LWW pair planes live PRE-SPLIT as hi/lo
# 32-bit pairs between micro rounds (scatter_pair_src_split), so the
# steady path pays no O(plane) int64<->hi/lo pass per call.  The int64
# wrapper (scatter_pair_src) — which every test above still drives —
# splits/joins around the SAME kernel, so the pad-collision and
# randomized differentials pin the split kernel too; the cases below
# additionally pin the CHAINED form (state stays split across rounds)
# and the engine's split-cache lifecycle.


@pytest.mark.parametrize("seed", [0, 1])
def test_scatter_split_chained_rounds(seed):
    """Several rounds over the SAME planes with the state kept in split
    form throughout (joined only at the end) — bit-identical to the
    per-round host reference and to the int64 XLA twin chain."""
    rng = np.random.default_rng(seed)
    sp = 32
    p = rng.integers(-(1 << 60), 1 << 60, sp).astype(np.int64)
    s = rng.integers(-(1 << 40), 1 << 40, sp).astype(np.int64)
    src = np.full(sp, -1, np.int32)
    p_hi, p_lo = PD.split_plane(jnp.array(p))
    s_hi, s_lo = PD.split_plane(jnp.array(s))
    src_d = jnp.array(src)
    want_p, want_s, want_src = p.copy(), s.copy(), src.copy()
    base = 0
    for _ in range(5):
        n = int(rng.integers(1, sp))
        idx = np.sort(rng.choice(sp, n, replace=False)).astype(np.int32)
        bp = rng.integers(-(1 << 60), 1 << 60, n).astype(np.int64)
        bs = rng.integers(-(1 << 40), 1 << 40, n).astype(np.int64)
        np2 = PD._pow2(n)
        pad = TpuMergeEngine._scatter_pad_row(idx.astype(np.int64), n, sp) \
            if np2 > n else 0
        p_hi, p_lo, s_hi, s_lo, src_d = PD.scatter_pair_src_split(
            p_hi, p_lo, s_hi, s_lo, src_d,
            jnp.array(_pad1(idx, np2, pad)),
            jnp.array(_pad1(bp, np2, NEUTRAL_T)),
            jnp.array(_pad1(bs, np2, NEUTRAL_T)),
            np.int32(base), interpret=True)
        want_p, want_s, want_src = _host_scatter_ref(
            want_p, want_s, want_src, idx, bp, bs, base)
        base += np2
    np.testing.assert_array_equal(
        np.asarray(PD.join_plane(p_hi, p_lo)), want_p)
    np.testing.assert_array_equal(
        np.asarray(PD.join_plane(s_hi, s_lo)), want_s)
    np.testing.assert_array_equal(np.asarray(src_d), want_src)


def test_engine_split_cache_steady_state():
    """The engine keeps pair planes split BETWEEN micro rounds under a
    Pallas backend (res['split'] populated, int64 cols stale-by-design)
    and still flushes/reads exactly the host-engine results."""
    from constdb_tpu.engine.base import ColumnarBatch
    from constdb_tpu.engine.cpu import CpuMergeEngine
    from constdb_tpu.store import KeySpace

    rng = np.random.default_rng(5)

    def batch(u0):
        b = ColumnarBatch()
        n = 12
        b.keys = [b"r%02d" % rng.integers(6) for _ in range(n)]
        uu = (np.arange(n, dtype=np.int64) + u0) << 22
        b.key_enc = np.full(n, 3, np.int8)  # ENC_BYTES
        b.key_ct = uu.copy()
        b.key_mt = uu.copy()
        b.key_dt = np.zeros(n, np.int64)
        b.key_expire = np.zeros(n, np.int64)
        b.reg_val = [b"v%d" % (u0 + i) for i in range(n)]
        b.reg_t = uu
        b.reg_node = np.full(n, 1, np.int64)
        b.rows_unique_per_slot = False
        return b

    ref = KeySpace()
    cpu = CpuMergeEngine()
    dev = KeySpace()
    eng = TpuMergeEngine(resident=True, steady=True, warmup=0,
                         dense_fold="pallas-interpret")
    for r in range(4):
        b1, b2 = batch(100 + 20 * r), batch(100 + 20 * r)
        b2.keys = list(b1.keys)
        b2.reg_val = list(b1.reg_val)
        cpu.merge_many(ref, [b1])
        eng.merge_many(dev, [b2])
        if r:
            res = eng._res.get("reg")
            assert res is not None and res.get("split"), \
                "pair planes not kept split between micro rounds"
    eng.flush(dev)
    assert dev.canonical() == ref.canonical()
    eng.close()


def test_recompute_sums_joins_split_cache():
    """A bulk counter catch-up (whole-plane cnt mirror, dirty=None)
    followed by steady micro rounds leaves the val/uuid truth in the
    split cache; the flush-time device segment-sum must JOIN it before
    re-deriving cnt_sum, or counters serve pre-merge totals (found by
    review: canonical() matched while cnt_sum was stale)."""
    from constdb_tpu.engine.base import ColumnarBatch
    from constdb_tpu.engine.cpu import CpuMergeEngine
    from constdb_tpu.store import KeySpace

    def cnt_batch(totals, u0, unique):
        b = ColumnarBatch()
        n = len(totals)
        b.keys = [b"c%02d" % i for i in range(n)]
        uu = (np.arange(n, dtype=np.int64) + u0) << 22
        b.key_enc = np.zeros(n, np.int8)  # ENC_COUNTER
        b.key_ct = uu.copy()
        b.key_mt = uu.copy()
        b.key_dt = np.zeros(n, np.int64)
        b.key_expire = np.zeros(n, np.int64)
        b.reg_val = [None] * n
        b.reg_t = np.zeros(n, np.int64)
        b.reg_node = np.zeros(n, np.int64)
        b.cnt_ki = np.arange(n, dtype=np.int64)
        b.cnt_node = np.full(n, 7, np.int64)
        b.cnt_val = np.asarray(totals, dtype=np.int64)
        b.cnt_uuid = uu
        b.cnt_base = np.zeros(n, np.int64)
        b.cnt_base_t = np.full(n, NEUTRAL_T, np.int64)
        b.rows_unique_per_slot = unique
        return b

    ref = KeySpace()
    cpu = CpuMergeEngine()
    dev = KeySpace()
    # the production shape is dense_fold="auto" RESOLVING to pallas (a
    # real TPU backend): host-combine staging stays on (env rides host
    # mode — no env mirror, so nothing flushes between the bulk round
    # and the micro rounds) while the scatter kernels run Pallas.  On
    # this CPU box auto resolves to xla, so pin the resolution.
    eng = TpuMergeEngine(resident=True, steady=True, warmup=0,
                         dense_fold="auto")
    eng._fold_backend = lambda: "pallas-interpret"
    # bulk catch-up: whole-plane cnt mirror (dirty=None)
    b1, b2 = (cnt_batch([100, 101, 102, 103], 10, True) for _ in range(2))
    cpu.merge_many(ref, [b1])
    eng.merge_many(dev, [b2])
    # steady micro rounds: winners land in the split pair cache
    for r in range(3):
        t = [200 + 10 * r + i for i in range(4)]
        m1, m2 = (cnt_batch(t, 50 + 10 * r, False) for _ in range(2))
        cpu.merge_many(ref, [m1])
        eng.merge_many(dev, [m2])
    eng.flush(dev)
    np.testing.assert_array_equal(dev.keys.cnt_sum[:4], ref.keys.cnt_sum[:4])
    assert dev.canonical() == ref.canonical()
    eng.close()
