"""Test harness configuration.

Tests prefer the virtual 8-device CPU platform so multi-chip sharding
(parallel/) is exercised without TPU hardware.  If the axon TPU plugin was
already bound by sitecustomize (it loads before any conftest), these env
vars cannot take effect in-process — tests then run on the TPU, and the
sharded-mesh suite re-launches itself in a subprocess with a clean
environment (see tests/test_sharded_merge.py).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "true")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


CPU_MESH_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "JAX_ENABLE_X64": "true",
    "CONSTDB_MESH_RERUN": "1",  # recursion guard for the subprocess re-run
}


def cpu_mesh_subprocess_env() -> dict:
    """Environment for re-running a test module on the virtual CPU mesh."""
    env = dict(os.environ)
    env.update(CPU_MESH_ENV)
    # unset (not empty-string) so sitecustomize skips the TPU plugin
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env
