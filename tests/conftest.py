"""Test harness configuration.

Tests run on CPU with a virtual 8-device platform so multi-chip sharding
(parallel/) is exercised without TPU hardware; these env vars must be set
before jax is imported anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "true")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
