"""Test harness configuration.

Tests run on the virtual 8-device CPU platform by default, so CRDT
semantics, the merge engines, and the multi-chip sharding (parallel/) are
exercised fast and without TPU hardware — and without depending on the
health of a tunnel-attached device (a wedged device would hang the whole
suite at backend init).  Set CONSTDB_TEST_TPU=1 to run against the real
chip instead.

Forcing CPU needs care here: the environment's sitecustomize registers the
axon TPU plugin and sets `jax_platforms="axon,cpu"` through jax.config,
which OVERRIDES the JAX_PLATFORMS env var — so this conftest overrides it
back at the config level before any backend initializes.
"""

import os
import sys

if not os.environ.get("CONSTDB_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "true")
if os.environ.get("CONSTDB_TEST_TPU"):
    # real-chip runs pay ~20-40s per kernel compile through the tunnel;
    # the persistent cache makes suite reruns tractable (same knob
    # bench.py sets)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/constdb_jax_cache")
    os.environ.setdefault(
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ------------------------------------------------------------ marker audit
# Tier-1 filters `-m 'not slow'`, so a long test that FORGOT the marker
# silently bloats the tier-1 wall until the timeout bites.  scripts/
# audit_markers.sh runs the suite with CONSTDB_MARKER_AUDIT=<report path>:
# every test whose call phase exceeds CONSTDB_MARKER_AUDIT_BUDGET seconds
# (default 5) WITHOUT a `slow` marker lands in the report file, and the
# script fails when it is non-empty.  Inert unless the env var is set.
_AUDIT_PATH = os.environ.get("CONSTDB_MARKER_AUDIT")
if _AUDIT_PATH:
    _AUDIT_BUDGET = float(os.environ.get("CONSTDB_MARKER_AUDIT_BUDGET", "5"))
    _audit_offenders = []

    def pytest_runtest_logreport(report):
        if report.when == "call" and report.duration > _AUDIT_BUDGET \
                and "slow" not in report.keywords:
            _audit_offenders.append(
                f"{report.nodeid} {report.duration:.1f}s")

    def pytest_sessionfinish(session, exitstatus):
        with open(_AUDIT_PATH, "w") as f:
            for line in _audit_offenders:
                f.write(line + "\n")


CPU_MESH_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "JAX_ENABLE_X64": "true",
    "CONSTDB_MESH_RERUN": "1",  # recursion guard for subprocess re-runs
}


def cpu_mesh_subprocess_env() -> dict:
    """Environment for re-running a test module on the virtual CPU mesh."""
    env = dict(os.environ)
    env.update(CPU_MESH_ENV)
    # unset (not empty-string) so sitecustomize skips the TPU plugin
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env
