"""Test harness configuration.

Tests run on the virtual 8-device CPU platform by default, so CRDT
semantics, the merge engines, and the multi-chip sharding (parallel/) are
exercised fast and without TPU hardware — and without depending on the
health of a tunnel-attached device (a wedged device would hang the whole
suite at backend init).  Set CONSTDB_TEST_TPU=1 to run against the real
chip instead.

Forcing CPU needs care here: the environment's sitecustomize registers the
axon TPU plugin and sets `jax_platforms="axon,cpu"` through jax.config,
which OVERRIDES the JAX_PLATFORMS env var — so this conftest overrides it
back at the config level before any backend initializes.
"""

import os
import sys

if not os.environ.get("CONSTDB_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "true")
if os.environ.get("CONSTDB_TEST_TPU"):
    # real-chip runs pay ~20-40s per kernel compile through the tunnel;
    # the persistent cache makes suite reruns tractable (same knob
    # bench.py sets)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          "/tmp/constdb_jax_cache")
    os.environ.setdefault(
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


CPU_MESH_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "JAX_ENABLE_X64": "true",
    "CONSTDB_MESH_RERUN": "1",  # recursion guard for subprocess re-runs
}


def cpu_mesh_subprocess_env() -> dict:
    """Environment for re-running a test module on the virtual CPU mesh."""
    env = dict(os.environ)
    env.update(CPU_MESH_ENV)
    # unset (not empty-string) so sitecustomize skips the TPU plugin
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env
