"""Encode-once run cache (replica/encode_cache.py + the push loop).

The load-bearing claims, each pinned here:
  * cache mechanics: ref-counted entries drop when the last expected
    reader consumes them, the byte-capped LRU evicts oldest-first, ring
    eviction sweeps dead cursor ranges, and cap 0 disables everything;
  * fan-out reuse: two push loops draining the same log publish/reuse
    ONE encoding per run and both receivers land the per-frame oracle's
    exact state — for the batch class AND the per-frame ("f") class two
    legacy peers share (the satellite fix: one legacy peer must not
    reintroduce per-peer re-encody for its whole cursor range);
  * caps-class keying: peers in different classes never share bytes
    (a batch peer's stream is not served from a frame peer's entry);
  * governor accounting: published bytes count into used_memory and the
    hard-watermark reclaim drops them.
"""

import asyncio
import os
import sys
import types

import pytest  # noqa: F401

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from test_link_pushloop import _SharedDumpStub, _Writer  # noqa: E402
from test_wire_batch import (mixed_bodies, perframe_reference,  # noqa: E402
                             replay_stream_frames, scan, u)

from constdb_tpu.replica.encode_cache import RunEncodeCache  # noqa: E402
from constdb_tpu.replica.link import (CAP_BATCH_STREAM,  # noqa: E402
                                      REPLBATCH, REPLICATE, ReplicaLink)
from constdb_tpu.replica.manager import ReplicaMeta  # noqa: E402
from constdb_tpu.resp.message import Bulk, Int  # noqa: E402
from constdb_tpu.server.node import Node  # noqa: E402


# ------------------------------------------------------------- unit level


def test_refcount_and_lru_bound():
    c = RunEncodeCache(cap_bytes=100)
    c.put("b", 0, 10, b"x" * 40, readers=2)
    assert c.bytes == 40
    e = c.get("b", 0)
    assert e is not None and e.end == 10 and e.refs == 1
    assert c.get("b", 0) is not None  # second (last) expected reader
    assert c.get("b", 0) is None      # consumed: entry dropped
    assert c.bytes == 0

    # LRU byte bound: oldest entries leave first
    c.put("b", 0, 1, b"a" * 60, readers=9)
    c.put("b", 1, 2, b"b" * 60, readers=9)  # 120 > 100: first evicted
    assert c.get("b", 0) is None
    assert c.get("b", 1) is not None
    # zero readers / zero cap publish nothing
    c.put("b", 5, 6, b"c" * 10, readers=0)
    assert c.get("b", 5) is None
    off = RunEncodeCache(cap_bytes=0)
    off.put("b", 0, 1, b"zz", readers=5)
    assert not off.enabled and off.get("b", 0) is None


def test_ring_eviction_sweep_and_class_isolation():
    c = RunEncodeCache(cap_bytes=1 << 20)
    c.put("b", 100, 200, b"x" * 8, readers=3)
    c.put("f", 100, 200, b"y" * 8, readers=3)
    c.put("b", 300, 400, b"z" * 8, readers=3)
    # classes are isolated: a frame peer never reads the batch bytes
    assert c.get("f", 100).payload == b"y" * 8
    assert c.get("b", 100).payload == b"x" * 8
    # ring evicted past 250: the 100-cursor entries are unreachable
    c.evict_below(250)
    assert c.get("b", 100) is None and c.get("f", 100) is None
    assert c.get("b", 300) is not None


def test_governor_counts_cache_bytes():
    node = Node(node_id=1)
    node.governor.configure(maxmemory=1 << 30)
    base = node.governor.used_memory()
    node.wire_cache.put("b", 0, 10, b"p" * 5000, readers=4)
    assert node.governor.used_memory() == base + 5000
    # the hard-watermark reclaim treats it as a droppable warm cache
    node.governor._on_hard()
    assert node.wire_cache.used_bytes() == 0
    assert node.governor.used_memory() == base


# ------------------------------------------------------------ push fan-out


def drive_fanout(tmp_path, bodies, caps_list, rounds=400,
                 cache_mb=None):
    """Drive one push loop PER entry of caps_list over the same filled
    log (real ReplicaLink metas registered in the manager, so the
    expected-reader count is live).  Returns (node, writers)."""
    async def main():
        node = Node(node_id=1, repl_log_cap=100_000)
        if cache_mb is not None:
            node.wire_cache.configure(cache_mb << 20)
        app = types.SimpleNamespace(node=node, heartbeat=0.05,
                                    reconnect_delay=0.05,
                                    handshake_timeout=1.0,
                                    work_dir=str(tmp_path))
        app.shared_dump = _SharedDumpStub(node, str(tmp_path))
        last = 0
        for i, body in enumerate(bodies, 1):
            args = [Int(a) if isinstance(a, int) else Bulk(a)
                    for a in body[1:]]
            node.repl_log.push(u(i), body[0], args)
            last = u(i)
        links, writers = [], []
        for i, caps in enumerate(caps_list):
            meta = ReplicaMeta(addr=f"fan:{i}")
            node.replicas.peers[meta.addr] = meta
            link = ReplicaLink(app, meta)
            link._peer_caps = caps
            links.append(link)
            writers.append(_Writer())
        tasks = [asyncio.create_task(lk._push_loop(w, peer_resume=0))
                 for lk, w in zip(links, writers)]
        try:
            for _ in range(rounds):
                await asyncio.sleep(0.01)
                done = 0
                for w in writers:
                    covered = 0
                    for kind, items in scan(w.buf):
                        if kind in (REPLICATE, REPLBATCH):
                            covered = int(items[3].val)
                    done += covered >= last
                if done == len(writers):
                    break
        finally:
            for t in tasks:
                t.cancel()
        return node, writers
    return asyncio.run(main())


def test_batch_fanout_encodes_once(tmp_path):
    bodies = mixed_bodies(400, seed=5)
    node, writers = drive_fanout(tmp_path, bodies,
                                 [CAP_BATCH_STREAM, CAP_BATCH_STREAM,
                                  CAP_BATCH_STREAM])
    st = node.stats
    assert st.repl_encode_cache_hits > 0, "fan-out never reused"
    assert st.repl_encode_cache_misses > 0
    # every peer landed the per-frame oracle's exact state
    entries = node.repl_log.run_after(0, len(bodies) + 1)
    want = perframe_reference(entries, origin=node.node_id).canonical()
    for w in writers:
        got = replay_stream_frames(scan(w.buf))
        assert got.canonical() == want


def test_frame_class_fanout_shares_legacy_rendering(tmp_path):
    """The satellite fix: TWO legacy peers at the same cursor share one
    per-frame rendering — and it stays byte-exact."""
    bodies = mixed_bodies(200, seed=9)
    node, writers = drive_fanout(tmp_path, bodies, [0, 0])
    assert node.stats.repl_encode_cache_hits > 0, \
        "legacy fan-out never reused the per-frame rendering"
    entries = node.repl_log.run_after(0, len(bodies) + 1)
    want = perframe_reference(entries, origin=node.node_id).canonical()
    for w in writers:
        frames = scan(w.buf)
        assert all(k != REPLBATCH for k, _ in frames)
        got = replay_stream_frames(frames)
        assert got.canonical() == want


def test_mixed_classes_never_share(tmp_path):
    """One batch peer + one legacy peer: each gets its own class's
    bytes (the legacy stream holds no REPLBATCH, the batch stream
    does), and both land identical state."""
    bodies = mixed_bodies(200, seed=3)
    node, writers = drive_fanout(tmp_path, bodies, [CAP_BATCH_STREAM, 0])
    batch_frames = scan(writers[0].buf)
    legacy_frames = scan(writers[1].buf)
    assert any(k == REPLBATCH for k, _ in batch_frames)
    assert all(k != REPLBATCH for k, _ in legacy_frames)
    entries = node.repl_log.run_after(0, len(bodies) + 1)
    want = perframe_reference(entries, origin=node.node_id).canonical()
    assert replay_stream_frames(batch_frames).canonical() == want
    assert replay_stream_frames(legacy_frames).canonical() == want


def test_cache_disabled_still_exact(tmp_path):
    """CONSTDB_ENCODE_CACHE_MB=0 (cap 0): every loop re-encodes — the
    pre-broadcast path — and streams stay exact."""
    bodies = mixed_bodies(150, seed=7)
    node, writers = drive_fanout(tmp_path, bodies,
                                 [CAP_BATCH_STREAM, CAP_BATCH_STREAM],
                                 cache_mb=0)
    assert not node.wire_cache.enabled
    assert node.stats.repl_encode_cache_hits == 0
    entries = node.repl_log.run_after(0, len(bodies) + 1)
    want = perframe_reference(entries, origin=node.node_id).canonical()
    for w in writers:
        assert replay_stream_frames(scan(w.buf)).canonical() == want
