"""Negotiated replication compression (CAP_COMPRESS) + the container.

The load-bearing claims, each pinned here:
  * the chunked framing (utils/compressio.py) roundtrips exactly under
    every alg/filter combination, and EVERY structural defect —
    truncation, bit flips across the whole container, trailing garbage
    — raises CompressFormatError (a consumer never acts on bytes it
    could not fully validate);
  * the push loop compresses REPLBATCH payloads only over the floor and
    only for peers that advertised CAP_COMPRESS — a batch-only peer's
    payloads are the byte-exact plain encoding;
  * the receiver lands a compressed stream identically to the per-frame
    oracle, and a malformed compressed payload demotes that peer LOUDLY
    (repl_wire_demotions + compress_wire_off + the capability disappears
    from the next handshake) with the watermark untouched;
  * the compressed snapshot container roundtrips through dump/load,
    pre-PR plain files stay loadable, and a corrupt container is
    quarantined as InvalidSnapshot;
  * the shared full-sync dump produces at most one file per variant,
    and the compressed variant really is the container.
"""

import asyncio
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from test_link_pushloop import _mk_link  # noqa: E402
from test_wire_batch import (drive_pushloop, mixed_bodies,  # noqa: E402
                             perframe_reference, replay_stream_frames, u)

from constdb_tpu.errors import CstError, InvalidSnapshot  # noqa: E402
from constdb_tpu.persist.snapshot import (NodeMeta,  # noqa: E402
                                          dump_keyspace, load_snapshot)
from constdb_tpu.replica.coalesce import CoalescingApplier  # noqa: E402
from constdb_tpu.replica.link import (CAP_BATCH_STREAM,  # noqa: E402
                                      CAP_COMPRESS, REPLBATCH, REPLICATE,
                                      my_caps)
from constdb_tpu.replica.manager import ReplicaMeta  # noqa: E402
from constdb_tpu.resp.message import (Arr, Bulk, Int,  # noqa: E402
                                      as_bytes)
from constdb_tpu.server.node import Node  # noqa: E402
from constdb_tpu.store.keyspace import KeySpace  # noqa: E402
from constdb_tpu.utils import compressio as zio  # noqa: E402

CAPS_Z = CAP_BATCH_STREAM | CAP_COMPRESS


# --------------------------------------------------------------- framing


@pytest.mark.parametrize("alg", ["zlib", "lzma"])
@pytest.mark.parametrize("filt", ["none", "transpose", "auto"])
def test_framing_roundtrip(alg, filt):
    data = bytes(range(256)) * 3000 + b"odd-tail"
    c = zio.compress_bytes(data, level=6, filt=filt, alg=alg)
    assert zio.decompress_bytes(c) == data
    assert zio.is_compressed(c)
    # empty payload roundtrips too (zero chunks)
    assert zio.decompress_bytes(
        zio.compress_bytes(b"", alg=alg)) == b""


def test_framing_rejects_every_defect():
    data = os.urandom(512) + bytes(5000)
    c = zio.compress_bytes(data, level=1, filt="auto", alg="lzma")
    # every byte position flipped must be caught (magic, alg, chunk
    # headers, payload, end marker)
    for pos in range(len(c)):
        bad = bytearray(c)
        bad[pos] ^= 0xFF
        with pytest.raises(zio.CompressFormatError):
            zio.decompress_bytes(bytes(bad))
    # every truncation point
    for cut in range(len(c)):
        with pytest.raises(zio.CompressFormatError):
            zio.decompress_bytes(c[:cut])
    with pytest.raises(zio.CompressFormatError):
        zio.decompress_bytes(c + b"x")
    with pytest.raises(zio.CompressFormatError):
        zio.decompress_bytes(c, max_raw=len(data) - 1)


# ------------------------------------------------------------- push side


def test_pushloop_compresses_over_the_floor(tmp_path):
    bodies = [(b"set", b"r%03d" % (i % 40), b"v" * 64)
              for i in range(400)]
    node, writer, frames = drive_pushloop(
        tmp_path, bodies, CAPS_Z, app_tweaks={"wire_compress_min": 64})
    payloads = [as_bytes(items[5]) for k, items in frames
                if k == REPLBATCH]
    assert payloads, "no batches shipped"
    assert any(zio.is_compressed(p) for p in payloads), \
        "no payload compressed over the floor"
    st = node.stats
    assert st.repl_comp_raw_bytes > st.repl_comp_wire_bytes > 0
    # the receiver lands the compressed stream identically to the
    # per-frame oracle
    got = replay_stream_frames(frames)
    entries = node.repl_log.run_after(0, len(bodies) + 1)
    want = perframe_reference(entries, origin=node.node_id)
    assert got.canonical() == want.canonical()


def test_floor_and_capability_gate_compression(tmp_path):
    bodies = [(b"set", b"r%03d" % (i % 40), b"v" * 64)
              for i in range(200)]
    # huge floor: nothing compresses even for a capable peer
    node, _, frames = drive_pushloop(
        tmp_path, bodies, CAPS_Z,
        app_tweaks={"wire_compress_min": 1 << 30})
    assert all(not zio.is_compressed(as_bytes(items[5]))
               for k, items in frames if k == REPLBATCH)
    assert node.stats.repl_comp_wire_bytes == 0
    # batch-only peer: plain payloads regardless of the floor
    node2, _, frames2 = drive_pushloop(
        tmp_path, bodies, CAP_BATCH_STREAM,
        app_tweaks={"wire_compress_min": 1})
    assert all(not zio.is_compressed(as_bytes(items[5]))
               for k, items in frames2 if k == REPLBATCH)


def test_kill_switch_withholds_capability():
    class _On:
        pass

    class _Off:
        wire_compress = False
    assert my_caps(_On()) & CAP_COMPRESS
    assert not (my_caps(_Off()) & CAP_COMPRESS)
    # a peer that shipped a malformed compressed frame is pinned plain
    meta = ReplicaMeta("p:1")
    meta.compress_wire_off = True
    assert not (my_caps(_On(), meta) & CAP_COMPRESS)


# ---------------------------------------------------------- receive side


def _compressed_batch_frame(node):
    """A valid REPLBATCH frame whose payload is compressed."""
    from constdb_tpu.replica import wire
    entries = []

    class _E:
        __slots__ = ("uuid", "prev_uuid", "name", "args")

    prev = 0
    for i in range(1, 9):
        e = _E()
        e.uuid, e.prev_uuid = u(i), prev
        e.name = b"set"
        e.args = [Bulk(b"k%d" % i), Bulk(b"v" * 64)]
        prev = e.uuid
        entries.append(e)
    payload = wire.build_wire_batch(entries, 7)
    assert payload is not None
    z = zio.compress_bytes(payload, level=1)
    return [Bulk(b"replbatch"), Int(7), Int(0), Int(entries[-1].uuid),
            Int(len(entries)), Bulk(z)], entries


def test_compressed_batch_applies_and_corrupt_demotes_loudly():
    frame, entries = _compressed_batch_frame(None)
    node = Node(node_id=2)
    meta = ReplicaMeta("peer:1")
    ap = CoalescingApplier(node, meta, max_frames=64)
    ap.apply_wire_batch(frame)
    assert meta.uuid_he_sent == entries[-1].uuid
    assert node.stats.extra.get("repl_comp_batches_in") == 1
    want = perframe_reference(entries, origin=7)
    assert node.canonical() == want.canonical()

    # corrupt INSIDE the compressed payload: loud demotion, watermark
    # untouched, capability withdrawn from the next handshake
    frame2, entries2 = _compressed_batch_frame(None)
    z = bytearray(as_bytes(frame2[5]))
    z[len(z) // 2] ^= 0xFF
    frame2[5] = Bulk(bytes(z))
    node2 = Node(node_id=3)
    meta2 = ReplicaMeta("peer:2")
    ap2 = CoalescingApplier(node2, meta2, max_frames=64)
    with pytest.raises(CstError):
        ap2.apply_wire_batch(frame2)
    st = node2.stats
    assert st.repl_wire_demotions == 1
    assert st.extra.get("repl_compress_demotions") == 1
    assert meta2.compress_wire_off
    assert not meta2.batch_wire_off  # the BATCH layer stays negotiated
    assert meta2.uuid_he_sent == 0   # watermark untouched
    assert node2.ks.n_keys() == 0    # nothing partially applied

    class _App:
        pass
    assert not (my_caps(_App(), meta2) & CAP_COMPRESS)
    assert my_caps(_App(), meta2) & CAP_BATCH_STREAM


# ----------------------------------------------------- snapshot container


def _filled_node(n=300):
    node = Node(node_id=1)
    for i in range(n):
        uu = node.hlc.tick(True)
        kid, _ = node.ks.get_or_create(b"key%06d" % i, 1, uu)
        node.ks.register_set(kid, b"val%06d" % i, uu, 1)
    return node


def test_container_dump_roundtrip_and_quarantine(tmp_path):
    node = _filled_node()
    plain = os.path.join(str(tmp_path), "plain.snapshot")
    comp = os.path.join(str(tmp_path), "z.snapshot")
    s_plain = dump_keyspace(plain, node.ks, NodeMeta(node_id=1))
    s_comp = dump_keyspace(comp, node.ks, NodeMeta(node_id=1),
                           container_level=6)
    with open(comp, "rb") as f:
        assert zio.is_compressed(f.read(8))
    with open(plain, "rb") as f:
        assert not zio.is_compressed(f.read(8))
    canons = []
    for p in (plain, comp):
        ks = KeySpace()
        load_snapshot(p, ks)  # loader sniffs the magic — both formats
        canons.append(ks.canonical())
    assert canons[0] == canons[1] == node.ks.canonical()
    assert s_comp < s_plain  # the container actually pays

    # a flipped byte inside the container quarantines as InvalidSnapshot
    data = bytearray(open(comp, "rb").read())
    data[len(data) // 2] ^= 0xFF
    bad = os.path.join(str(tmp_path), "bad.snapshot")
    open(bad, "wb").write(bytes(data))
    with pytest.raises(InvalidSnapshot):
        load_snapshot(bad, KeySpace())


def test_shared_dump_variants(tmp_path):
    """One dump per VARIANT: a mixed-capability mesh costs at most two
    files, and each is reused while the log covers its watermark."""
    import types

    from constdb_tpu.persist.share import SharedDump

    node = _filled_node(100)
    app = types.SimpleNamespace(node=node, work_dir=str(tmp_path),
                                advertised_addr="t:1",
                                snapshot_chunk_keys=1 << 16,
                                snapshot_compress_level=1)

    async def main():
        sd = SharedDump(app)
        d_plain = await sd.acquire(compressed=False)
        d_comp = await sd.acquire(compressed=True)
        assert sd.dumps_taken == 2
        # reuse: same variant, no new dump
        assert (await sd.acquire(compressed=False)).path == d_plain.path
        assert (await sd.acquire(compressed=True)).path == d_comp.path
        assert sd.dumps_taken == 2
        with open(d_comp.path, "rb") as f:
            assert zio.is_compressed(f.read(8))
        with open(d_plain.path, "rb") as f:
            assert not zio.is_compressed(f.read(8))
        assert d_comp.size < d_plain.size
    asyncio.run(main())


# ------------------------------------------------------------ e2e fullsync


def test_compressed_fullsync_on_the_wire(tmp_path):
    """A fenced pusher full-syncs a CAP_COMPRESS peer: the streamed
    window IS the compressed container, and the peer converges."""
    from cluster_util import Client, close_cluster, converge, make_cluster

    async def main():
        apps = await make_cluster(2, str(tmp_path))
        try:
            a, b = apps
            c = await Client().connect(a.advertised_addr)
            for i in range(300):
                await c.cmd("set", f"key:{i:06d}", "v" * 64)
            top = a.node.repl_log.last_uuid
            a.node.repl_log.evicted_up_to = top  # force FULLSYNC
            await c.cmd("meet", b.advertised_addr)
            await converge(apps, timeout=20.0)
            assert a.node.stats.repl_full_syncs >= 1
            assert "last_snapshot_z_bytes" in a.node.stats.extra
            got = await c.cmd("get", "key:000299")
            assert got == Bulk(b"v" * 64)
            await c.close()
        finally:
            await close_cluster(apps)
    asyncio.run(main())


def test_info_broadcast_gauges(tmp_path):
    """Satellite: per-peer wire observability — replica<i> rows carry
    bytes_out / compressed_ratio / cache counts, and the node-level
    encode-cache + compression gauges ride the stats section."""
    from cluster_util import Client, close_cluster, converge, make_cluster
    from constdb_tpu.resp.codec import encode_msg

    async def main():
        apps = await make_cluster(3, str(tmp_path),
                                  wire_compress_min=64)
        try:
            c = await Client().connect(apps[0].advertised_addr)
            await c.cmd("meet", apps[1].advertised_addr)
            await c.cmd("meet", apps[2].advertised_addr)
            # a pipelined chunk logs one consecutive run, so BOTH push
            # loops drain the same cursor range (encode-once food)
            buf = bytearray()
            for i in range(300):
                buf += encode_msg(Arr([Bulk(b"set"),
                                       Bulk(b"k%d" % (i % 16)),
                                       Bulk(b"v" * 48)]))
            c.writer.write(bytes(buf))
            await c.writer.drain()
            got = 0
            while got < 300:
                if c.parser.next_msg() is not None:
                    got += 1
                    continue
                data = await asyncio.wait_for(c.reader.read(1 << 16), 10)
                if not data:
                    raise ConnectionError("EOF")
                c.parser.feed(data)
            await converge(apps, timeout=20.0)
            st = apps[0].node.stats
            assert st.repl_comp_wire_bytes > 0, "stream never compressed"
            assert st.repl_encode_cache_hits > 0, \
                "fan-out never reused an encoding"
            info = (await c.cmd("info", "stats")).val
            for gauge in (b"repl_encode_cache_hits",
                          b"repl_encode_cache_misses",
                          b"repl_encode_cache_bytes",
                          b"repl_compress_ratio"):
                assert gauge in info, gauge
            info = (await c.cmd("info", "replication")).val
            for field in (b"bytes_out=", b"compressed_ratio=",
                          b"cache_hits=", b"cache_misses="):
                assert field in info, field
            await c.close()
        finally:
            await close_cluster(apps)
    asyncio.run(main())
