"""Property tests for the MergeEngine: commutativity, associativity,
idempotence over randomized multi-node CRDT states.

This is the test the reference lacks — its merge defects (Dict::merge panic,
Counter stale-uuid, order-dependent register ties; SURVEY.md §"Known
reference defects") are exactly what these properties catch.
"""

import os
import random

import numpy as np
import pytest

from constdb_tpu.crdt import (ENC_BYTES, ENC_COUNTER, ENC_DICT, ENC_LIST,
                              ENC_MV, ENC_SET, ENC_TENSOR)
from constdb_tpu.crdt import tensor as TS
from constdb_tpu.engine import CpuMergeEngine, batch_from_keyspace
from constdb_tpu.engine.tpu import TpuMergeEngine
from constdb_tpu.store import KeySpace

KEYS = [b"cnt:%d" % i for i in range(4)] + [b"reg:%d" % i for i in range(4)] + \
       [b"set:%d" % i for i in range(3)] + [b"dic:%d" % i for i in range(3)] + \
       [b"mvr:%d" % i for i in range(2)] + [b"lst:%d" % i for i in range(2)] + \
       [b"tns:%d" % i for i in range(len(TS.STRATEGY_IDS))]
MEMBERS = [b"m%d" % i for i in range(6)]
# one tensor key per registered strategy, so EVERY strategy's
# delivered-set semantics replay through the property suite below
TNS_CFGS = {
    b"tns:%d" % i: TS.pack_config(TS.TensorMeta(sid, 0, (6,)))
    for i, sid in enumerate(sorted(TS.STRATEGY_IDS.values()))
}
# MV siblings / list entries are element rows keyed by opaque bytes (clock
# serializations / LSEQ positions); merge-wise any byte-string member works
MV_CLOCKS = [b"1:%d" % i for i in range(1, 4)] + [b"2:%d" % i for i in range(1, 4)]
LIST_POS = [bytes([0, s, 0, 0, 0, 0, 0, 0, 0, n]) for s in (10, 20, 30)
            for n in (1, 2)]


def enc_for(key: bytes) -> int:
    return {b"c": ENC_COUNTER, b"r": ENC_BYTES, b"s": ENC_SET, b"d": ENC_DICT,
            b"m": ENC_MV, b"l": ENC_LIST, b"t": ENC_TENSOR}[key[:1]]


def gen_store(seed: int, node: int, n_ops: int = 120) -> KeySpace:
    """A random but op-rule-respecting state for one node.  uuids are drawn
    from a small range so cross-store ties actually happen."""
    rng = random.Random(seed)
    ks = KeySpace()
    for _ in range(n_ops):
        key = rng.choice(KEYS)
        enc = enc_for(key)
        uuid = (rng.randrange(1, 40) << 22) | rng.randrange(0, 3)
        if enc == ENC_TENSOR:
            # contributor-slot write (op rule: LWW per (key, node)); the
            # payload derives from (node, uuid) so any two replicas that
            # deliver the same write hold the same bytes
            kid = ks.tensor_get_or_create(key, TNS_CFGS[key], uuid)
            pay = np.arange(6, dtype=np.float32) * node + np.float32(
                uuid % 97)
            ks.tensor_slot_set(kid, node, uuid,
                               1 + uuid % 5, pay)
            ks.updated_at(kid, uuid)
            continue
        kid, _created = ks.get_or_create(key, enc, uuid)
        op = rng.random()
        if enc == ENC_COUNTER:
            ks.counter_change(kid, node, rng.choice([1, -1]), uuid)
            ks.updated_at(kid, uuid)
        elif enc == ENC_BYTES:
            if ks.register_set(kid, b"v%d:%d" % (node, rng.randrange(100)), uuid, node):
                pass
        elif op < 0.55:
            if enc == ENC_MV:
                member = rng.choice(MV_CLOCKS)
            elif enc == ENC_LIST:
                member = rng.choice(LIST_POS)
            else:
                member = rng.choice(MEMBERS)
            val = None if enc == ENC_SET else b"x%d" % rng.randrange(50)
            ks.elem_add(kid, member, val, uuid, node)
            ks.updated_at(kid, uuid)
        elif op < 0.85:
            pool = (MV_CLOCKS if enc == ENC_MV
                    else LIST_POS if enc == ENC_LIST else MEMBERS)
            ks.elem_rem(kid, rng.choice(pool), uuid)
            ks.updated_at(kid, uuid)
        else:  # key-level delete: tombstone all members + envelope
            for m, *_ in list(ks.elem_all(kid)):
                ks.elem_rem(kid, m, uuid)
            ks.set_delete_time(kid, uuid)
            ks.record_key_delete(key, uuid)
        if rng.random() < 0.1:
            ks.expire_at(key, (rng.randrange(30, 60) << 22))
    return ks


def merged(engine, *stores) -> dict:
    acc = KeySpace()
    for s in stores:
        engine.merge(acc, batch_from_keyspace(s))
    return acc.canonical()


@pytest.fixture(scope="module")
def engine():
    return CpuMergeEngine()


@pytest.mark.parametrize("seed", range(8))
def test_merge_into_empty_is_identity(engine, seed):
    a = gen_store(seed, node=1)
    assert merged(engine, a) == a.canonical()


@pytest.mark.parametrize("seed", range(8))
def test_commutative(engine, seed):
    a, b = gen_store(seed, node=1), gen_store(seed + 100, node=2)
    assert merged(engine, a, b) == merged(engine, b, a)


@pytest.mark.parametrize("seed", range(8))
def test_associative(engine, seed):
    a = gen_store(seed, node=1)
    b = gen_store(seed + 100, node=2)
    c = gen_store(seed + 200, node=3)
    ab = KeySpace()
    engine.merge(ab, batch_from_keyspace(a))
    engine.merge(ab, batch_from_keyspace(b))
    bc = KeySpace()
    engine.merge(bc, batch_from_keyspace(b))
    engine.merge(bc, batch_from_keyspace(c))
    left = KeySpace()
    engine.merge(left, batch_from_keyspace(ab))
    engine.merge(left, batch_from_keyspace(c))
    right = KeySpace()
    engine.merge(right, batch_from_keyspace(a))
    engine.merge(right, batch_from_keyspace(bc))
    assert left.canonical() == right.canonical()


@pytest.mark.parametrize("seed", range(8))
def test_idempotent(engine, seed):
    a = gen_store(seed, node=1)
    assert merged(engine, a, a) == a.canonical()
    b = gen_store(seed + 100, node=2)
    assert merged(engine, a, b, b) == merged(engine, a, b)


@pytest.mark.parametrize("seed", range(4))
def test_convergence_all_orders(engine, seed):
    stores = [gen_store(seed + i * 50, node=i + 1) for i in range(3)]
    import itertools

    results = {tuple(sorted(merged(engine, *perm).items()))
               for perm in itertools.permutations(stores)}
    assert len(results) == 1


@pytest.mark.parametrize("seed", range(4))
def test_tensor_reads_deterministic_across_orders_and_engines(engine, seed):
    """Canonical-order determinism pin: the visible tensor VALUE (the
    strategy reduction — float math included) is a pure function of the
    delivered contribution set.  Any delivery order, any engine (CPU
    reference, resident XLA, resident Pallas-interpret, device reads)
    produces bit-identical reads for every registered strategy."""
    import itertools

    stores = [gen_store(seed + i * 50, node=i + 1, n_ops=60)
              for i in range(3)]
    reads = set()
    for perm in itertools.permutations(stores):
        acc = KeySpace()
        for s in perm:
            engine.merge(acc, batch_from_keyspace(s))
        got = tuple(
            (key, None if (r := acc.tensor_read(acc.lookup(key))) is None
             else r.tobytes())
            for key in sorted(TNS_CFGS))
        reads.add(got)
    assert len(reads) == 1
    want = reads.pop()
    for backend in ("xla", "pallas-interpret"):
        eng = TpuMergeEngine(resident=True, steady=True, warmup=0,
                             dense_fold=backend)
        acc = KeySpace()
        for s in stores:
            eng.merge_many(acc, [batch_from_keyspace(s)])
        kids = {key: acc.lookup(key) for key in sorted(TNS_CFGS)}
        dev = eng.tensor_read_many(acc, [k for k in kids.values()
                                         if k >= 0])
        got = tuple(
            (key, None if kids[key] < 0 or dev[kids[key]] is None
             else dev[kids[key]].tobytes())
            for key in sorted(TNS_CFGS))
        assert got == want, backend
        eng.flush(acc)
        host = tuple(
            (key, None if kids[key] < 0 or
             (r := acc.tensor_read(kids[key])) is None else r.tobytes())
            for key in sorted(TNS_CFGS))
        assert host == want, backend
        eng.close()


def test_type_conflict_skipped(engine):
    a, b = KeySpace(), KeySpace()
    ka, _ = a.get_or_create(b"k", ENC_COUNTER, 5 << 22)
    a.counter_change(ka, 1, 1, 5 << 22)
    kb, _ = b.get_or_create(b"k", ENC_SET, 6 << 22)
    b.elem_add(kb, b"m", None, 6 << 22, 2)
    st = engine.merge(a, batch_from_keyspace(b))
    assert st.type_conflicts == 1
    assert a.counter_sum(a.lookup(b"k")) == 1  # local survives


# --------------------------------------------------------------------
# Faulted delivery orders through the REAL apply path (round 15): the
# merge laws above hold for state merges; these replay them through the
# CoalescingApplier — the machinery a chaotic mesh actually drives —
# under every delivery shape the transport contract admits: arbitrary
# cross-origin interleavings (each origin's stream in order), arbitrary
# coalescing batch sizes, and whole-stream REDELIVERY (the reconnect
# window, delivered twice through a fresh applier).  One fixpoint.
# --------------------------------------------------------------------

def _origin_streams(seed: int, n_origins: int = 3, n_ops: int = 80):
    """Per-origin replication-rewrite streams (gap-free, increasing
    uuids per origin; uuid ranges overlap ACROSS origins so LWW ties
    and interleaved wins actually happen).  Only commuting rewrites —
    the delivered-set semantics the chaos oracle's reference relies on."""
    from constdb_tpu.resp.message import Bulk as B, Int as I

    rng = random.Random(seed)
    streams = []
    for o in range(1, n_origins + 1):
        ticks = sorted(rng.sample(range(1, n_ops * 8), n_ops))
        prev = 0
        ops = []
        totals: dict[bytes, int] = {}
        for t in ticks:
            uuid = (t << 22) | o  # distinct across origins, sorted within
            k = rng.random()
            if k < 0.3:
                key = b"cnt:%d" % rng.randrange(4)
                totals[key] = totals.get(key, 0) + rng.choice([1, -1, 3])
                frame = (b"cntset", [B(key), I(totals[key])])
            elif k < 0.5:
                frame = (b"set", [B(b"reg:%d" % rng.randrange(4)),
                                  B(b"v%d:%d" % (o, t))])
            elif k < 0.65:
                frame = (b"sadd", [B(b"set:%d" % rng.randrange(3)),
                                   B(b"m%d" % rng.randrange(8))])
            elif k < 0.75:
                frame = (b"srem", [B(b"set:%d" % rng.randrange(3)),
                                   B(b"m%d" % rng.randrange(8))])
            elif k < 0.9:
                frame = (b"hset", [B(b"h:%d" % rng.randrange(3)),
                                   B(b"f%d" % rng.randrange(4)),
                                   B(b"w%d:%d" % (o, t))])
            else:
                frame = (b"delbytes", [B(b"reg:%d" % rng.randrange(4))])
            ops.append((uuid, prev, frame[0], frame[1]))
            prev = uuid
        streams.append((o, ops))
    return streams


def _deliver(streams, interleave_rng, batch: int,
             redeliver: bool = False):
    """One delivery run: a fresh node pulls every origin stream through
    its own CoalescingApplier in a seeded cross-origin interleaving."""
    from constdb_tpu.replica.coalesce import CoalescingApplier
    from constdb_tpu.replica.manager import ReplicaMeta
    from constdb_tpu.resp.message import Bulk as B, Int as I
    from constdb_tpu.server.node import Node

    node = Node(node_id=99)

    def run_once():
        appliers = {}
        for o, _ops in streams:
            meta = ReplicaMeta(addr=f"origin-{o}")
            appliers[o] = CoalescingApplier(node, meta, max_frames=batch)
        cursors = {o: 0 for o, _ in streams}
        by_origin = dict(streams)
        while True:
            live = [o for o in cursors if cursors[o] < len(by_origin[o])]
            if not live:
                break
            o = live[interleave_rng.randrange(len(live))]
            uuid, prev, name, args = by_origin[o][cursors[o]]
            cursors[o] += 1
            appliers[o].apply([B(b"replicate"), I(o), I(prev), I(uuid),
                               B(name), *args])
        for a in appliers.values():
            a.flush()

    run_once()
    if redeliver:
        # the reconnect window, at its widest: the WHOLE of every
        # stream re-delivered through fresh appliers (fresh metas =
        # watermark 0); every re-apply must be an idempotent merge
        run_once()
    return node.canonical()


@pytest.mark.parametrize("seed", range(4))
def test_faulted_delivery_orders_converge(seed):
    """Any interleaving x any coalescing granularity x full redelivery
    = one canonical state, equal to the per-frame reference."""
    streams = _origin_streams(seed)
    want = _deliver(streams, random.Random(0), batch=1)
    got = set()
    for d_seed in range(3):
        for batch in (1, 7, 512):
            got.add(tuple(sorted(
                _deliver(streams, random.Random(d_seed), batch).items())))
    got.add(tuple(sorted(
        _deliver(streams, random.Random(9), 64, redeliver=True).items())))
    assert got == {tuple(sorted(want.items()))}


@pytest.mark.parametrize("seed", range(2))
def test_faulted_delivery_matches_state_merge(seed):
    """The op-path fixpoint IS the state-merge fixpoint: delivering the
    streams through the coalescer equals applying each origin's ops to
    its own store and state-merging the stores (the certified-MRDT
    correspondence the chaos oracle's reference replay rests on)."""
    from constdb_tpu.server.node import Node

    streams = _origin_streams(seed)
    via_ops = _deliver(streams, random.Random(3), batch=16)
    per_origin = []
    for o, ops in streams:
        n = Node(node_id=o)
        for uuid, _prev, name, args in ops:
            n.apply_replicated(name, args, o, uuid)
        per_origin.append(n.ks)
    engine = CpuMergeEngine()
    acc = KeySpace()
    for s in per_origin:
        engine.merge(acc, batch_from_keyspace(s))
    assert via_ops == acc.canonical()


@pytest.mark.skipif(not os.environ.get("CONSTDB_SLOW"),
                    reason="set CONSTDB_SLOW=1 for the extended fuzz")
def test_extended_differential_fuzz():
    """Extended randomized differential soak (CONSTDB_SLOW): many seeds x
    randomized chunking x randomized group sizes through the RESIDENT
    engine, each run canonical()-checked against the CPU engine.  The
    narrow suites pin specific paths; this sweeps their combinations."""
    import bench
    from constdb_tpu.persist.snapshot import batch_chunks

    for seed in range(40):
        rng = random.Random(seed)
        n_keys = rng.choice([67, 257, 1024, 3001])
        n_rep = rng.choice([2, 3, 5, 8])
        chunk = rng.choice([0, 61, 129, 500])
        group = rng.choice([1, 3, n_rep, 4 * n_rep])
        batches = bench.make_workload(n_keys, n_rep, seed=seed + 1)
        if chunk:
            chunks = []
            for b in batches:
                chunks.extend(batch_chunks(b, chunk))
        else:
            chunks = batches
        eng = TpuMergeEngine(resident=True)
        if rng.random() < 0.3:
            eng.IDX_IOTA_MIN = 1
        if rng.random() < 0.3:
            eng.pool_flush_bytes = 1 << 14
        st = KeySpace()
        for i in range(0, len(chunks), group):
            eng.merge_many(st, chunks[i:i + group])
        eng.flush(st)
        ref = KeySpace()
        cpu = CpuMergeEngine()
        for b in batches:
            cpu.merge(ref, b)
        assert st.canonical() == ref.canonical(), \
            (seed, n_keys, n_rep, chunk, group)
