"""Push-loop ring-falloff fallback (replica/link.py `_push_loop`).

The module header documents: a pusher that falls off its own repl_log ring
mid-stream re-sends a full snapshot ON THE SAME CONNECTION (the reference
leaves the case as a TODO — pull.rs:167-172).  Before this PR the push loop
would stream the next surviving entry with a gapped prev_uuid, the peer
would raise ReplicateCommandsLost, and recovery rode a teardown + redial.
These tests drive the eviction mid-drain and assert the in-place fallback:
no gapped frame is ever written, and a FULLSYNC follows on the same writer.
"""

import asyncio
import os
import types

from constdb_tpu.persist.snapshot import NodeMeta, dump_keyspace
from constdb_tpu.replica.link import FULLSYNC, PARTSYNC, REPLACK, \
    REPLICATE, ReplicaLink
from constdb_tpu.replica.manager import ReplicaMeta
from constdb_tpu.resp.codec import make_parser
from constdb_tpu.resp.message import Arr, Bulk, as_bytes, as_int
from constdb_tpu.server.node import Node


class _Writer:
    """Stub StreamWriter collecting every frame; `on_drain` fires on each
    drain so the test can evict the ring exactly at a yield point."""

    def __init__(self, on_drain=None):
        self.buf = bytearray()
        self.on_drain = on_drain
        self.drains = 0
        self.closed = False

    def write(self, data: bytes) -> None:
        self.buf += data

    async def drain(self) -> None:
        self.drains += 1
        if self.on_drain is not None:
            self.on_drain(self.drains)
        await asyncio.sleep(0)

    def close(self) -> None:
        self.closed = True


class _SharedDumpStub:
    def __init__(self, node, work_dir):
        self.node = node
        self.work_dir = work_dir
        self.dumps = 0

    async def acquire(self, compressed=False):
        from constdb_tpu.persist.share import Dump
        self.dumps += 1
        path = os.path.join(self.work_dir, f"dump{self.dumps}.snapshot")
        size = dump_keyspace(path, self.node.ks,
                             NodeMeta(node_id=self.node.node_id))
        return Dump(path=path, repl_last=self.node.repl_log.last_uuid,
                    size=size)


def _mk_link(tmp_path, cap=100_000):
    node = Node(node_id=1, repl_log_cap=cap)
    app = types.SimpleNamespace(node=node, heartbeat=0.05,
                                reconnect_delay=0.05,
                                handshake_timeout=1.0, work_dir=str(tmp_path))
    app.shared_dump = _SharedDumpStub(node, str(tmp_path))
    meta = ReplicaMeta(addr="127.0.0.1:1")
    return node, app, ReplicaLink(app, meta)


def _log_write(node, i):
    """One logged write (k{i}) through the node's keyspace + repl_log."""
    uuid = node.hlc.tick(True)
    key = b"k%d" % i
    kid, _ = node.ks.get_or_create(key, 1, uuid)
    node.ks.register_set(kid, b"x" * 40, uuid, node.node_id)
    node.replicate_cmd(uuid, b"set", [Bulk(key), Bulk(b"x" * 40)])


def _scan_frames(buf: bytes):
    """Parse the written stream; returns (kinds, gap_frames) where
    gap_frames collects REPLICATE frames whose prev_uuid skipped past the
    last streamed uuid (the bug this PR removes)."""
    parser = make_parser()
    parser.feed(bytes(buf))
    kinds = []
    gaps = []
    cursor = 0
    while True:
        msg = parser.next_msg()
        if msg is None:
            break
        items = msg.items if isinstance(msg, Arr) else None
        assert items, f"unexpected frame {msg!r}"
        kind = as_bytes(items[0]).lower()
        kinds.append(kind)
        if kind == FULLSYNC:
            size = as_int(items[1])
            cursor = as_int(items[2])  # dump watermark = new resume point
            raw = parser.take_raw(size)
            while len(raw) < size:  # skip the snapshot bytes
                more = parser.take_raw(size - len(raw))
                assert more, "snapshot bytes truncated in stream"
                raw += more
        elif kind == REPLICATE:
            prev, uuid = as_int(items[2]), as_int(items[3])
            if prev > cursor:
                gaps.append((cursor, prev, uuid))
            cursor = uuid
        elif kind in (PARTSYNC, REPLACK):
            pass
        else:  # pragma: no cover - future frame kinds
            raise AssertionError(f"unknown frame {kind!r}")
    return kinds, gaps


def test_midstream_eviction_resyncs_in_place(tmp_path):
    """Evict the ring past the send cursor at a mid-stream drain: the
    pusher must stop, send a FULLSYNC on the SAME writer, and continue
    gap-free — never writing a gapped REPLICATE frame."""
    async def main():
        node, app, link = _mk_link(tmp_path, cap=100_000)
        for i in range(100):
            _log_write(node, i)

        def evict(drain_no):
            if drain_no == 1:
                # shrink the ring so eviction races the in-flight stream
                # exactly the way a burst of writes would
                node.repl_log.cap = 500
                for i in range(100, 160):
                    _log_write(node, 1000 + i)

        writer = _Writer(on_drain=evict)
        task = asyncio.create_task(link._push_loop(writer, peer_resume=0))
        try:
            for _ in range(400):  # phase 1: in-place snapshot sent
                await asyncio.sleep(0.01)
                kinds, _ = _scan_frames(writer.buf)
                if FULLSYNC in kinds:
                    break
            for i in range(2):  # the log moves on after the snapshot...
                _log_write(node, 5000 + i)
            for _ in range(400):  # ...phase 2: the SAME stream resumes
                await asyncio.sleep(0.01)
                kinds, _ = _scan_frames(writer.buf)
                if REPLICATE in kinds[kinds.index(FULLSYNC):]:
                    break
        finally:
            task.cancel()
        kinds, gaps = _scan_frames(writer.buf)
        assert not gaps, f"gapped REPLICATE frames written: {gaps}"
        assert FULLSYNC in kinds, "no in-place full resync on the stream"
        assert kinds[0] == PARTSYNC  # fresh log: first round is partial
        # the snapshot was produced once, for this same connection
        assert app.shared_dump.dumps == 1
        assert not writer.closed  # recovery never tore the stream down
    asyncio.run(main())


def test_no_eviction_stays_partial(tmp_path):
    """Control: with the ring intact the loop streams gap-free and never
    dumps a snapshot."""
    async def main():
        node, app, link = _mk_link(tmp_path)
        for i in range(80):
            _log_write(node, i)
        writer = _Writer()
        task = asyncio.create_task(link._push_loop(writer, peer_resume=0))
        for _ in range(100):
            await asyncio.sleep(0.01)
            kinds, _ = _scan_frames(writer.buf)
            if kinds.count(REPLICATE) >= 80:
                break
        task.cancel()
        kinds, gaps = _scan_frames(writer.buf)
        assert not gaps
        assert FULLSYNC not in kinds
        assert app.shared_dump.dumps == 0
    asyncio.run(main())


def test_closed_app_does_not_keep_applying(tmp_path):
    """Regression for the close-window zombie: a connection upgraded to a
    replica link while ServerApp.close() is sweeping must not keep the
    "closed" node applying its peer's stream (this silently kept a downed
    peer caught up, masking the full-resync path mesh-wide)."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from cluster_util import Client, close_cluster, make_cluster

    async def main():
        apps = await make_cluster(2, str(tmp_path), repl_log_cap=2_000)
        try:
            c1 = await Client().connect(apps[0].advertised_addr)
            await c1.cmd("meet", apps[1].advertised_addr)
            # close n2 immediately — racing the first SYNC handshake
            await apps[1].close()
            for i in range(200):
                await c1.cmd("set", f"k{i}", "x" * 32)
            await asyncio.sleep(0.6)
            assert apps[1].node.ks.n_keys() == 0, \
                "a zombie link kept the closed node applying"
            await c1.close()
        finally:
            await close_cluster(apps)
    asyncio.run(main())


def test_sharded_snapshot_ingest_e2e(tmp_path, monkeypatch):
    """Full-sync catch-up through the process-parallel sharded ingest
    (ServerApp ingest_shards > 1): a joiner whose resume point is off the
    pusher's ring downloads a snapshot, fans it out to shard workers, and
    consolidates into its serving keyspace — converging to the same state
    the plain path produces."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from cluster_util import Client, close_cluster, converge, make_cluster

    monkeypatch.setenv("CONSTDB_SHARD_ENGINE", "cpu")  # jax-free workers

    async def main():
        apps = await make_cluster(2, str(tmp_path), repl_log_cap=2_000,
                                  ingest_shards=2, ingest_shard_min_bytes=0)
        try:
            c1 = await Client().connect(apps[0].advertised_addr)
            # enough bytes that the joiner's resume=0 falls off the ring
            # (cap 2000 holds ~50 of these entries): the sync decision
            # then must ship a snapshot
            for i in range(160):
                await c1.cmd("set", f"k{i}", "v" * 32)
            await c1.cmd("sadd", "members", "a", "b", "c")
            await c1.cmd("incr", "hits")
            await c1.cmd("meet", apps[1].advertised_addr)
            await converge(apps, timeout=30.0)
            n2 = apps[1].node
            assert n2.ks.n_keys() >= 162
            assert n2.stats.extra.get("sharded_ingests", 0) >= 1, \
                "snapshot did not take the sharded ingest path"
            assert n2.stats.extra.get("sharded_ingest_workers") == 2
            await c1.close()
        finally:
            await close_cluster(apps)
    asyncio.run(main())
