"""In-process cluster harness for replication tests.

The reference tests multi-node behavior black-box against live processes
driven by a client with a local oracle (reference bin/test.rs, SURVEY.md §4).
This harness keeps the black-box client-over-TCP shape but runs every node
in ONE asyncio loop and replaces convergence *sleeps* with convergence
*polling* on canonical state — deterministic and fast.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from constdb_tpu.resp.codec import RespParser, encode_msg
from constdb_tpu.resp.message import Arr, Bulk, Msg
from constdb_tpu.server.io import ServerApp, start_node
from constdb_tpu.server.node import Node

FAST = dict(heartbeat=0.15, reconnect_delay=0.25, gc_interval=0.2)


async def make_cluster(n: int, work_dir: str, engine=None,
                       repl_log_cap: int = 1_024_000, **kw) -> list[ServerApp]:
    apps = []
    for i in range(n):
        node = Node(node_id=i + 1, alias=f"n{i + 1}", engine=engine,
                    repl_log_cap=repl_log_cap)
        opts = {**FAST, **kw}
        apps.append(await start_node(node, host="127.0.0.1", port=0,
                                     work_dir=work_dir, **opts))
    return apps


async def close_cluster(apps) -> None:
    for app in apps:
        await app.close()


class Client:
    """Minimal RESP client (the reference's constdb-cli/test transport)."""

    def __init__(self) -> None:
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.parser = RespParser()

    async def connect(self, addr: str) -> "Client":
        host, port = addr.rsplit(":", 1)
        self.reader, self.writer = await asyncio.open_connection(host, int(port))
        return self

    async def cmd(self, *parts) -> Msg:
        items = [Bulk(p if isinstance(p, bytes) else str(p).encode())
                 for p in parts]
        self.writer.write(encode_msg(Arr(items)))
        await self.writer.drain()
        while True:
            msg = self.parser.next_msg()
            if msg is not None:
                return msg
            data = await asyncio.wait_for(self.reader.read(1 << 16), 10.0)
            if not data:
                raise ConnectionError("EOF")
            self.parser.feed(data)

    async def close(self) -> None:
        if self.writer is not None:
            self.writer.close()
            try:
                await self.writer.wait_closed()
            except (ConnectionError, OSError):
                pass


async def converge(apps, timeout: float = 15.0, poll: float = 0.05) -> None:
    """Poll until every node's canonical CRDT state is identical."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while True:
        canons = [app.node.canonical() for app in apps]
        if all(c == canons[0] for c in canons[1:]):
            return
        if loop.time() > deadline:
            diff_keys = set()
            for c in canons[1:]:
                for k in set(c) | set(canons[0]):
                    if c.get(k) != canons[0].get(k):
                        diff_keys.add(k)
            raise AssertionError(
                f"no convergence after {timeout}s; {len(diff_keys)} keys "
                f"differ, e.g. {sorted(diff_keys)[:5]}")
        await asyncio.sleep(poll)


async def full_mesh(apps, timeout: float = 15.0) -> None:
    """Wait until every node has a connected link to every other."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    want = {app.advertised_addr for app in apps}
    while True:
        ok = True
        for app in apps:
            peers = {m.addr for m in app.node.replicas.live_peers()
                     if m.link is not None and m.link.connected}
            if want - {app.advertised_addr} - peers:
                ok = False
                break
        if ok:
            return
        if loop.time() > deadline:
            raise AssertionError("mesh did not fully connect")
        await asyncio.sleep(0.05)
