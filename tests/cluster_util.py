"""In-process cluster helpers for replication tests.

The reference tests multi-node behavior black-box against live processes
driven by a client with a local oracle (reference bin/test.rs, SURVEY.md
§4).  These helpers keep the black-box client-over-TCP shape but run
every node in ONE asyncio loop and replace convergence *sleeps* with
convergence *polling* on canonical state — deterministic and fast.

Since round 15 the heavy machinery lives in `constdb_tpu/chaos/` (the
fault-injecting certification harness): the RESP `Client` and the FAST
cadence knobs are re-exported from `chaos.cluster`, and crash/restart
are ChaosCluster scenario primitives (`restart_cold`/`restart_warm`)
instead of per-test helpers.  This module keeps the thin plain-apps
surface the replication suites drive (`make_cluster` over a list of
ServerApps + converge/full_mesh polling).
"""

from __future__ import annotations

import asyncio

from constdb_tpu.chaos.cluster import FAST, Client  # noqa: F401 (re-export)
from constdb_tpu.server.io import ServerApp, start_node
from constdb_tpu.server.node import Node


async def make_cluster(n: int, work_dir: str, engine=None,
                       repl_log_cap: int = 1_024_000, **kw) -> list[ServerApp]:
    apps = []
    for i in range(n):
        node = Node(node_id=i + 1, alias=f"n{i + 1}", engine=engine,
                    repl_log_cap=repl_log_cap)
        opts = {**FAST, **kw}
        apps.append(await start_node(node, host="127.0.0.1", port=0,
                                     work_dir=work_dir, **opts))
    return apps


async def close_cluster(apps) -> None:
    for app in apps:
        await app.close()


async def converge(apps, timeout: float = 15.0, poll: float = 0.05) -> None:
    """Poll until every node's canonical CRDT state is identical."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while True:
        canons = [app.node.canonical() for app in apps]
        if all(c == canons[0] for c in canons[1:]):
            return
        if loop.time() > deadline:
            diff_keys = set()
            for c in canons[1:]:
                for k in set(c) | set(canons[0]):
                    if c.get(k) != canons[0].get(k):
                        diff_keys.add(k)
            raise AssertionError(
                f"no convergence after {timeout}s; {len(diff_keys)} keys "
                f"differ, e.g. {sorted(diff_keys)[:5]}")
        await asyncio.sleep(poll)


async def full_mesh(apps, timeout: float = 15.0) -> None:
    """Wait until every node has a connected link to every other."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    want = {app.advertised_addr for app in apps}
    while True:
        ok = True
        for app in apps:
            peers = {m.addr for m in app.node.replicas.live_peers()
                     if m.link is not None and m.link.connected}
            if want - {app.advertised_addr} - peers:
                ok = False
                break
        if ok:
            return
        if loop.time() > deadline:
            raise AssertionError("mesh did not fully connect")
        await asyncio.sleep(0.05)
