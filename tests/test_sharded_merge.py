"""Sharded SPMD merge over a virtual 8-device mesh must equal the
single-device dense kernels bit-for-bit."""

import subprocess
import sys

import jax
import numpy as np
import pytest

from constdb_tpu.ops import dense as D
from constdb_tpu.ops.segment import NEUTRAL_T
from constdb_tpu.parallel import make_mesh, shard_batch_arrays, sharded_merge_step

_HAVE_MESH = len(jax.devices()) >= 8

needs_mesh = pytest.mark.skipif(
    not _HAVE_MESH, reason="needs 8 devices (re-run via subprocess below)")


def test_reruns_on_virtual_cpu_mesh_if_needed():
    """When the TPU plugin owns this interpreter (1 device), the mesh tests
    above are skipped — re-run this module in a subprocess on the virtual
    8-device CPU platform so they always execute somewhere."""
    if _HAVE_MESH:
        return  # ran inline
    import os

    if os.environ.get("CONSTDB_MESH_RERUN"):
        pytest.fail("virtual CPU mesh unavailable even in the clean-env "
                    "subprocess — not recursing further")
    from conftest import cpu_mesh_subprocess_env

    r = subprocess.run(
        [sys.executable, "-m", "pytest", __file__, "-q", "--no-header"],
        env=cpu_mesh_subprocess_env(), capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert " passed" in r.stdout and "failed" not in r.stdout, r.stdout


def _random_inputs(rng, R, S):
    # single source of truth for this input shape lives in __graft_entry__
    from __graft_entry__ import _example_arrays
    return _example_arrays(R, S, seed=int(rng.integers(0, 1 << 31)))


@needs_mesh
@pytest.mark.parametrize("rep,seed", [(1, 0), (2, 1), (4, 2), (8, 3)])
def test_matches_single_device(rep, seed):
    R, S = 8, 256
    rng = np.random.default_rng(seed)
    vals, ts, at, an, dt, env = _random_inputs(rng, R, S)

    mesh = make_mesh(8, rep=rep)
    step = sharded_merge_step(mesh)
    d_in = shard_batch_arrays(mesh, vals, ts, at, an, dt, env)
    V, T, AT, AN, DT, WIN, ENV, touched = jax.device_get(step(*d_in))

    v1, t1 = jax.device_get(D.dense_merge_counters(vals, ts))
    a1, n1, d1, w1 = jax.device_get(D.dense_merge_elems(at, an, dt))
    e1 = jax.device_get(D.dense_max(env))

    np.testing.assert_array_equal(V, v1)
    np.testing.assert_array_equal(T, t1)
    np.testing.assert_array_equal(AT, a1)
    np.testing.assert_array_equal(AN, n1)
    np.testing.assert_array_equal(DT, d1)
    np.testing.assert_array_equal(ENV, e1)
    # winner indices must agree wherever a real winner exists
    np.testing.assert_array_equal(WIN, w1)
    assert touched == np.sum(t1 > NEUTRAL_T)


slow = pytest.mark.skipif(
    not __import__("os").environ.get("CONSTDB_SLOW"),
    reason="set CONSTDB_SLOW=1 for the 100k-key mesh soak")


@needs_mesh
@slow
def test_kv_sharded_engine_at_scale():
    """The PRODUCTION kv-sharded merge path (TpuMergeEngine(mesh=...)) at
    real scale: ≥100k keys streamed as non-pow2 chunks, so per-shard state
    spans many tiles, the pow2+multiple-of-kv padding rule exercises both
    branches, and chunk boundaries straddle range-partition edges.  Must
    stay canonical()-identical to the CPU engine (VERDICT r4 item 6 —
    shard-boundary bugs hide at toy sizes where every slot fits one tile).
    """
    import bench
    from constdb_tpu.engine.cpu import CpuMergeEngine
    from constdb_tpu.engine.tpu import TpuMergeEngine
    from constdb_tpu.parallel import engine_mesh
    from constdb_tpu.persist.snapshot import batch_chunks
    from constdb_tpu.store.keyspace import KeySpace

    n_keys, n_rep = 120_000, 4
    batches = bench.make_workload(n_keys, n_rep, seed=23)
    # 13_331 is deliberately non-pow2 and coprime with 8: every chunk ends
    # inside a shard's slot range, never on a partition edge
    chunks = bench.chunk_batches(batches, 13_331)

    eng = TpuMergeEngine(resident=True, mesh=engine_mesh(8))
    st = KeySpace()
    group = 2 * n_rep
    for i in range(0, len(chunks), group):
        eng.merge_many(st, chunks[i:i + group])
    eng.flush(st)

    oracle = KeySpace()
    cpu = CpuMergeEngine()
    for b in batches:
        cpu.merge(oracle, b)
    got, want = st.canonical(), oracle.canonical()
    assert len(got) == n_keys
    diff = [k for k in want if got.get(k) != want[k]]
    assert not diff, f"{len(diff)} keys diverge, e.g. {diff[:3]}"
    assert got == want


@needs_mesh
def test_kv_sharded_engine_device_iota_idx():
    """The device-derived (iota) idx must carry the replicated mesh
    sharding — mixing a default-device idx with kv-sharded state would
    crash or silently degrade the bulk kernels.  Forced on (threshold 1)
    at small scale, canonical()-checked against the CPU engine."""
    import bench
    from constdb_tpu.engine.cpu import CpuMergeEngine
    from constdb_tpu.engine.tpu import TpuMergeEngine
    from constdb_tpu.parallel import engine_mesh
    from constdb_tpu.store.keyspace import KeySpace

    batches = bench.make_workload(3000, 4, seed=41)
    eng = TpuMergeEngine(resident=True, mesh=engine_mesh(8))
    eng.IDX_IOTA_MIN = 1
    st = KeySpace()
    eng.merge_many(st, batches)
    eng.flush(st)
    oracle = KeySpace()
    cpu = CpuMergeEngine()
    for b in batches:
        cpu.merge(oracle, b)
    assert st.canonical() == oracle.canonical()


@needs_mesh
def test_row0_wins_ties_across_rep_shards():
    """The local-state row (global row 0) must win exact (t, node) ties even
    when the tying replica row lives on another rep shard."""
    R, S = 8, 128
    at = np.full((R, S), NEUTRAL_T, np.int64)
    an = np.zeros((R, S), np.int64)
    dt = np.zeros((R, S), np.int64)
    at[0], an[0] = 5 << 22, 3   # local state
    at[7], an[7] = 5 << 22, 3   # identical write from a replica on shard 3
    vals = np.zeros((R, S), np.int64)
    ts = np.full((R, S), NEUTRAL_T, np.int64)
    env = np.zeros((R, S, 4), np.int64)

    mesh = make_mesh(8, rep=4)
    step = sharded_merge_step(mesh)
    out = jax.device_get(step(*shard_batch_arrays(mesh, vals, ts, at, an, dt, env)))
    WIN = out[5]
    assert (WIN == 0).all()
