import random

import pytest

from constdb_tpu.utils.bytesutil import bytes2i64, bytes2u64, i64_to_bytes
from constdb_tpu.utils.checksum import StreamChecksum, _crc64_py, crc64
from constdb_tpu.utils.hlc import HLC, SEQ_MASK, uuid_ms, uuid_seq
from constdb_tpu.utils.varint import (
    VarintReader,
    read_uvarint,
    read_varint,
    write_uvarint,
    write_varint,
)


class TestHLC:
    def test_write_uuids_strictly_monotonic(self):
        # parity: reference src/server.rs:433-443 test_uuid
        h = HLC()
        prev = 0
        for _ in range(10_000):
            u = h.tick(True)
            assert u > prev
            prev = u

    def test_reads_do_not_consume_sequence(self):
        t = [100]
        h = HLC(clock=lambda: t[0])
        w = h.tick(True)
        r1 = h.tick(False)
        r2 = h.tick(False)
        assert r1 == w and r2 == w

    def test_monotonic_under_clock_regression(self):
        t = [1000]
        h = HLC(clock=lambda: t[0])
        u1 = h.tick(True)
        t[0] = 500  # clock steps back
        u2 = h.tick(True)
        assert u2 > u1
        t[0] = 2000
        u3 = h.tick(True)
        assert u3 > u2 and uuid_ms(u3) == 2000

    def test_seq_overflow_rolls_into_ms(self):
        t = [7]
        h = HLC(clock=lambda: t[0])
        h._uuid = (7 << 22) | SEQ_MASK
        u = h.tick(True)
        assert uuid_ms(u) == 8 and uuid_seq(u) == 0

    def test_observe_remote(self):
        t = [100]
        h = HLC(clock=lambda: t[0])
        h.tick(True)
        remote = (10_000 << 22) | 5
        h.observe(remote)
        assert h.tick(True) > remote


class TestVarint:
    @pytest.mark.parametrize(
        "v",
        [0, 1, 63, 64, 100, (1 << 14) - 1, 1 << 14, (1 << 30) - 1, 1 << 30, (1 << 41), (1 << 64) - 1],
    )
    def test_uvarint_roundtrip(self, v):
        out = bytearray()
        write_uvarint(out, v)
        got, pos = read_uvarint(out, 0)
        assert got == v and pos == len(out)

    def test_uvarint_sizes(self):
        for v, n in [(0, 1), (63, 1), (64, 2), ((1 << 14) - 1, 2), (1 << 14, 4), ((1 << 30) - 1, 4), (1 << 30, 9)]:
            out = bytearray()
            write_uvarint(out, v)
            assert len(out) == n, v

    @pytest.mark.parametrize("v", [0, -1, 1, -64, 63, -(1 << 62), (1 << 62), -(1 << 63), (1 << 63) - 1])
    def test_varint_signed_roundtrip(self, v):
        out = bytearray()
        write_varint(out, v)
        got, pos = read_varint(out, 0)
        assert got == v and pos == len(out)

    def test_random_streams(self):
        rng = random.Random(7)
        vals = [rng.getrandbits(rng.randrange(1, 64)) - (1 << 62) for _ in range(500)]
        out = bytearray()
        for v in vals:
            write_varint(out, v)
        r = VarintReader(out)
        assert [r.varint() for _ in vals] == vals
        assert r.remaining == 0

    def test_truncated_raises(self):
        out = bytearray()
        write_uvarint(out, 1 << 40)
        with pytest.raises(IndexError):
            read_uvarint(out[:4], 0)


class TestChecksum:
    def test_crc64_xz_known_vector(self):
        # CRC-64/XZ check value for "123456789"
        assert _crc64_py(b"123456789") == 0x995DC9BBDF1939FA

    def test_crc64_incremental_matches_oneshot(self):
        data = bytes(range(256)) * 11
        one = crc64(data)
        inc = 0
        for i in range(0, len(data), 97):
            inc = crc64(data[i:i + 97], inc)
        assert inc == one

    def test_native_matches_python_if_built(self):
        from constdb_tpu.utils import checksum

        if not checksum._load_native():
            pytest.skip("native library not built")
        data = random.Random(3).randbytes(10_000)
        assert checksum.crc64(data) == checksum._crc64_py(data)

    @pytest.mark.parametrize("alg", [StreamChecksum.ALG_CRC64, StreamChecksum.ALG_BLAKE2B64])
    def test_stream_checksum(self, alg):
        a = StreamChecksum(alg)
        b = StreamChecksum(alg)
        a.update(b"hello ")
        a.update(b"world")
        b.update(b"hello world")
        assert a.digest() == b.digest()
        c = StreamChecksum(alg)
        c.update(b"hello worlx")
        assert c.digest() != a.digest()


class TestBytesUtil:
    def test_bytes2i64(self):
        assert bytes2i64(b"123") == 123
        assert bytes2i64(b"-9") == -9
        assert bytes2i64(b"0") == 0
        for bad in (b"", b"+1", b" 1", b"01", b"1.5", b"abc", b"1a", str(1 << 63).encode()):
            assert bytes2i64(bad) is None, bad

    def test_bytes2u64(self):
        assert bytes2u64(b"5") == 5
        assert bytes2u64(b"-5") is None

    def test_i64_to_bytes_interned(self):
        assert i64_to_bytes(-1) == b"-1"
        assert i64_to_bytes(0) == b"0"
        assert i64_to_bytes(9999) == b"9999"
        assert i64_to_bytes(123456789) == b"123456789"
        assert i64_to_bytes(5) is i64_to_bytes(5)
