"""Client-assisted caching (round 20): RESP3 push tracking + near-cache.

The load-bearing claims, each pinned here (docs/INVARIANTS.md
"Tracking laws"):
  * registry bookkeeping — default mode is one-shot per (conn, key);
    the per-connection tracked set is capped (flush-all past the cap,
    never silently stale); unsubscribe drops every trace;
  * coalescing — invalidations flush under a dual batch/latency bound:
    one push frame carries the whole pending batch;
  * BCAST — prefix filtering is exact, and a flush encodes ONCE per
    prefix class regardless of subscriber count (the PR 13 encode-once
    cache shares the bytes);
  * backpressure — a tracked connection over the PR 12 outbuf cap is
    demoted LOUDLY (counter + abort), never silently stale;
  * slot migration — keys hashing into a lost slot are invalidated the
    moment ownership flips (cluster/slots.py adopt hook);
  * end-to-end over real sockets — HELLO 3 negotiation, CLIENT
    TRACKING/ID/LIST, push delivery on peer writes, INFO gauges, and
    the client near-cache's reconnect-flush + own-write laws.
"""

import asyncio
import types

from constdb_tpu.client import NearCacheClient
from constdb_tpu.resp.codec import RespParser
from constdb_tpu.resp.message import Arr, Bulk, Err, Int, Nil, Push
from constdb_tpu.server import tracking as tracking_mod
from constdb_tpu.server.node import Node
from constdb_tpu.server.tracking import (TRACK_DEFAULT, TRACK_OFF,
                                         TrackingRegistry, ClientConn)

from cluster_util import Client, close_cluster, make_cluster


def run(coro):
    asyncio.run(coro)


# ====================================================================
# registry unit tests (fake transports, no sockets)
# ====================================================================

class FakeTransport:
    def __init__(self):
        self.buf_size = 0
        self.closed = False
        self.aborted = False

    def is_closing(self):
        return self.closed

    def get_write_buffer_size(self):
        return self.buf_size

    def abort(self):
        self.aborted = True
        self.closed = True


class FakeWriter:
    def __init__(self):
        self.transport = FakeTransport()
        self.frames: list[bytes] = []

    def write(self, data):
        self.frames.append(bytes(data))


def parse_pushes(frames: list[bytes]) -> list:
    """Decode a writer's frames; every one must be a RESP3 push."""
    parser = RespParser()
    for f in frames:
        parser.feed(f)
    out = []
    while (m := parser.next_msg()) is not None:
        assert isinstance(m, Push), m
        assert m.items[0] == Bulk(b"invalidate")
        out.append(m.items[1])
    return out


def push_keys(payload) -> set:
    assert isinstance(payload, Arr), payload
    return {i.val for i in payload.items}


def make_registry(batch: int = 1) -> tuple[Node, TrackingRegistry]:
    node = Node(node_id=77)
    reg = node.tracking
    reg.batch = batch          # deterministic: flush on the batch bound
    return node, reg


def tracked_conn(reg, cid=1, bcast=False, prefixes=()):
    c = ClientConn(cid, f"t:{cid}", FakeWriter())
    c.resp3 = True
    reg.subscribe(c, bcast=bcast, prefixes=prefixes)
    return c


def test_registry_default_mode_one_shot():
    node, reg = make_registry()
    c = tracked_conn(reg)
    assert reg.active and c.tracking == TRACK_DEFAULT
    reg.note_read(c, b"k1")
    reg.note_read(c, b"k1")           # idempotent
    assert reg.key_map == {b"k1": {c}}
    # a mutation of an untracked key sends nothing
    reg.invalidate_key(b"other")
    assert not c.writer.frames
    # first mutation of the tracked key pushes; the promise is spent
    reg.invalidate_key(b"k1")
    (payload,) = parse_pushes(c.writer.frames)
    assert push_keys(payload) == {b"k1"}
    assert b"k1" not in reg.key_map and b"k1" not in c.tracked
    c.writer.frames.clear()
    reg.invalidate_key(b"k1")         # one-shot: no second push
    assert not c.writer.frames
    assert node.stats.tracking_invalidations_sent == 1
    assert node.stats.tracking_pushes == 1
    # unsubscribe drops every trace and deactivates the registry
    reg.note_read(c, b"k2")
    reg.unsubscribe(c)
    assert c.tracking == TRACK_OFF and not c.tracked and not c.pend
    assert not reg.key_map and not reg.active


def test_registry_batch_coalescing():
    node, reg = make_registry(batch=3)
    c = tracked_conn(reg)
    for k in (b"a", b"b", b"c"):
        reg.note_read(c, k)
    reg.invalidate_key(b"a")
    reg.invalidate_key(b"b")
    assert not c.writer.frames            # below the batch bound, no loop
    reg.invalidate_key(b"c")              # bound reached: one frame, 3 keys
    (payload,) = parse_pushes(c.writer.frames)
    assert push_keys(payload) == {b"a", b"b", b"c"}
    assert node.stats.tracking_pushes == 1
    assert node.stats.tracking_invalidations_sent == 3


def test_registry_max_keys_flush_all():
    node, reg = make_registry()
    reg.max_keys = 3
    c = tracked_conn(reg)
    for i in range(3):
        reg.note_read(c, b"k%d" % i)
    assert len(c.tracked) == 3 and not c.writer.frames
    reg.note_read(c, b"k3")               # over the cap: flush-all, reset
    (payload,) = parse_pushes(c.writer.frames)
    assert isinstance(payload, Nil)       # nil payload = flush everything
    assert not c.tracked and not reg.key_map
    assert node.stats.tracking_invalidations_sent == 1


def test_registry_bcast_prefix_filter_and_encode_once():
    node, reg = make_registry(batch=4)
    u1 = tracked_conn(reg, 1, bcast=True, prefixes=(b"user:",))
    u2 = tracked_conn(reg, 2, bcast=True, prefixes=(b"user:",))
    every = tracked_conn(reg, 3, bcast=True)
    encodes = {"n": 0}
    real = tracking_mod._encode_keys_frame

    def counting(keys):
        encodes["n"] += 1
        return real(keys)

    tracking_mod._encode_keys_frame = counting
    try:
        for k in (b"user:a", b"user:b", b"item:c", b"item:d"):
            reg.invalidate_key(k)
    finally:
        tracking_mod._encode_keys_frame = real
    # one encode per prefix class — NOT per subscriber (u2 spliced u1's
    # published bytes through node.wire_cache)
    assert encodes["n"] == 2
    (p1,) = parse_pushes(u1.writer.frames)
    (p2,) = parse_pushes(u2.writer.frames)
    assert push_keys(p1) == {b"user:a", b"user:b"}   # prefix-filtered
    assert u1.writer.frames == u2.writer.frames      # byte-identical
    (pe,) = parse_pushes(every.writer.frames)
    assert push_keys(pe) == {b"user:a", b"user:b", b"item:c", b"item:d"}
    assert node.stats.tracking_pushes == 3
    # no per-read bookkeeping in BCAST mode
    assert not reg.key_map and not u1.tracked


def test_registry_outbuf_demotion_is_loud():
    node, reg = make_registry()
    node.app = types.SimpleNamespace(client_outbuf_max=100)
    c = tracked_conn(reg)
    reg.note_read(c, b"k")
    c.writer.transport.buf_size = 1000    # over the cap when the push fires
    reg.invalidate_key(b"k")
    assert not c.writer.frames            # frame dropped, not buffered
    assert c.writer.transport.aborted     # client observes a disconnect
    assert c.tracking == TRACK_OFF and not reg.active
    assert node.stats.tracking_demotions == 1


def test_registry_flush_all_and_slots_lost():
    from constdb_tpu.cluster.slots import slot_of
    node, reg = make_registry()
    c = tracked_conn(reg, 1)
    b = tracked_conn(reg, 2, bcast=True)
    reg.note_read(c, b"moved")
    reg.note_read(c, b"stays")
    # pick a slot set containing only "moved"
    reg.slots_lost({slot_of(b"moved")} - {slot_of(b"stays")})
    (payload,) = parse_pushes(c.writer.frames)
    assert push_keys(payload) == {b"moved"}
    assert b"stays" in c.tracked          # unmoved key still tracked
    # BCAST subscription is prefix-, not slot-scoped: flush-all
    (pb,) = parse_pushes(b.writer.frames)
    assert isinstance(pb, Nil)
    c.writer.frames.clear()
    b.writer.frames.clear()
    # state-wipe events flush every tracked client wholesale
    reg.flush_all()
    (pc,) = parse_pushes(c.writer.frames)
    (pb,) = parse_pushes(b.writer.frames)
    assert isinstance(pc, Nil) and isinstance(pb, Nil)
    assert not reg.key_map and not c.tracked


# ====================================================================
# end-to-end over real sockets
# ====================================================================

async def wait_for(pred, timeout=5.0, what="condition"):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while not pred():
        assert loop.time() < deadline, f"timed out waiting for {what}"
        await asyncio.sleep(0.01)


def test_tracking_e2e_push_info_and_client_list(tmp_path):
    async def main():
        apps = await make_cluster(1, str(tmp_path))
        node = apps[0].node
        nc = await NearCacheClient(apps[0].advertised_addr).connect()
        w = await Client().connect(apps[0].advertised_addr)
        try:
            assert nc.client_id > 0
            await w.cmd("set", "k", "v1")
            assert await nc.get(b"k") == Bulk(b"v1")
            assert await nc.get(b"k") == Bulk(b"v1")   # near-cache hit
            assert nc.hits == 1 and nc.misses == 1
            # a peer write pushes an invalidation; the near-cache drops
            # the key without this client issuing any command
            await w.cmd("set", "k", "v2")
            await wait_for(lambda: b"k" not in nc.cache,
                           what="invalidation push")
            assert nc.invalidations == 1
            assert await nc.get(b"k") == Bulk(b"v2")   # fresh re-read
            assert node.stats.tracking_invalidations_sent >= 1
            assert node.stats.tracking_pushes >= 1
            # CLIENT ID / LIST + INFO gauges
            assert isinstance(await w.cmd("client", "id"), Int)
            listing = (await w.cmd("client", "list")).val.decode()
            assert "resp=3 tracking=on" in listing
            assert "resp=2 tracking=off" in listing
            info = (await w.cmd("info", "clients")).val.decode()
            assert "tracking_clients:1" in info
            assert "connected_clients:2" in info
            stats = (await w.cmd("info", "stats")).val.decode()
            assert "tracking_invalidations_sent:" in stats
            assert "tracking_pushes:" in stats
            assert "tracking_demotions:0" in stats
        finally:
            await nc.close()
            await w.close()
            await close_cluster(apps)
    run(main())


def test_near_cache_reconnect_flushes(tmp_path):
    """Reconnect-flush law, client half: ANY disconnect makes every
    cached entry untrustworthy (the server's one-shot promise died with
    the connection), so the first read after reconnect goes to the
    server."""
    async def main():
        apps = await make_cluster(1, str(tmp_path))
        nc = await NearCacheClient(apps[0].advertised_addr).connect()
        w = await Client().connect(apps[0].advertised_addr)
        try:
            await w.cmd("set", "k", "old")
            assert await nc.get(b"k") == Bulk(b"old")
            assert b"k" in nc.cache
            # sever the tracked connection (socket-level, no goodbye)
            nc.writer.transport.abort()
            await wait_for(lambda: not nc._connected,
                           what="disconnect detection")
            assert not nc.cache and nc.flushes >= 1
            # the write happens while no tracking subscription exists —
            # no push will ever describe it
            await w.cmd("set", "k", "new")
            await nc.connect()
            assert await nc.get(b"k") == Bulk(b"new")  # NOT the stale "old"
        finally:
            await nc.close()
            await w.close()
            await close_cluster(apps)
    run(main())


def test_near_cache_own_writes_drop_locally(tmp_path):
    async def main():
        apps = await make_cluster(1, str(tmp_path))
        nc = await NearCacheClient(apps[0].advertised_addr).connect()
        try:
            await nc.set(b"k", b"v1")
            assert await nc.get(b"k") == Bulk(b"v1")
            await nc.set(b"k", b"v2")          # drops b"k" at send time
            assert b"k" not in nc.cache
            assert await nc.get(b"k") == Bulk(b"v2")
        finally:
            await nc.close()
            await close_cluster(apps)
    run(main())


def test_near_cache_bcast_prefixes(tmp_path):
    async def main():
        apps = await make_cluster(1, str(tmp_path))
        nc = await NearCacheClient(apps[0].advertised_addr, bcast=True,
                                   prefixes=(b"hot:",)).connect()
        w = await Client().connect(apps[0].advertised_addr)
        try:
            await w.cmd("set", "hot:k", "a")
            await w.cmd("set", "cold:k", "a")
            assert await nc.get(b"hot:k") == Bulk(b"a")
            assert await nc.get(b"cold:k") == Bulk(b"a")
            await w.cmd("set", "hot:k", "b")
            await wait_for(lambda: b"hot:k" not in nc.cache,
                           what="bcast invalidation")
            # outside the prefix: no push, entry stays (by design —
            # the subscription scopes trust to the prefix list)
            await asyncio.sleep(0.05)
            assert b"cold:k" in nc.cache
            assert await nc.get(b"hot:k") == Bulk(b"b")
        finally:
            await nc.close()
            await w.close()
            await close_cluster(apps)
    run(main())


def test_slots_lost_pushes_over_the_wire(tmp_path):
    """Cluster mode: adopting a slot table that moves a tracked key's
    slot away fires the adopt-time hook (io.py wires
    cluster.on_slots_lost to the registry) and the invalidation
    reaches the tracked client as a real push frame — no CTRL command
    involved, the pure gossip-adoption path."""
    async def main():
        from constdb_tpu.cluster.slots import slot_of

        apps = await make_cluster(1, str(tmp_path), cluster=True,
                                  slot_groups=2, cluster_group=0)
        node = apps[0].node
        nc = await NearCacheClient(apps[0].advertised_addr).connect()
        w = await Client().connect(apps[0].advertised_addr)
        try:
            # two group-0-owned keys in distinct slots
            keys, j = [], 0
            while len(keys) < 2:
                k = b"adopt%d" % j
                if slot_of(k) < 8192 and (not keys or
                                          slot_of(k) != slot_of(keys[0])):
                    keys.append(k)
                j += 1
            moving, staying = keys
            for k in keys:
                await w.cmd(b"set", k, b"v")
                assert await nc.get(k) == Bulk(b"v")
            # adopt a table minting the moved slot to the other group
            table = node.cluster.table.copy()
            s = slot_of(moving)
            table.epoch = node.cluster.epoch + 1
            table.assign(s, s + 1, 1, epoch=table.epoch)
            node.cluster.adopt(table)
            await wait_for(lambda: moving not in nc.cache,
                           what="slots_lost push")
            assert nc.invalidations == 1 and nc.flushes == 0
            assert staying in nc.cache     # per-slot, not flush-all
        finally:
            await nc.close()
            await w.close()
            await close_cluster(apps)
    run(main())


# ====================================================================
# HLEN: the hash twin of SCNT/LLEN on the read planner
# ====================================================================

def test_hlen_command_surface(tmp_path):
    async def main():
        apps = await make_cluster(1, str(tmp_path))
        c = await Client().connect(apps[0].advertised_addr)
        try:
            assert await c.cmd("hlen", "h") == Int(0)      # missing key
            await c.cmd("hset", "h", "f1", "v1")
            await c.cmd("hset", "h", "f2", "v2")
            assert await c.cmd("hlen", "h") == Int(2)
            await c.cmd("hdel", "h", "f1")
            assert await c.cmd("hlen", "h") == Int(1)
            await c.cmd("set", "s", "v")
            bad = await c.cmd("hlen", "s")                 # type conflict
            assert isinstance(bad, Err) and b"WRONGTYPE" in bad.val
            bad = await c.cmd("hlen")                      # wrong arity
            assert isinstance(bad, Err)
            await c.cmd("del", "h")
            assert await c.cmd("hlen", "h") == Int(0)
        finally:
            await c.close()
            await close_cluster(apps)
    run(main())


def test_hlen_rides_read_planner_and_cache(tmp_path):
    """Pipelined HLEN goes through the coalesced read planner (a cache
    entry forms) and repeat rounds hit the reply cache; a member write
    stamps the entry dead."""
    async def main():
        from constdb_tpu.resp.codec import encode_msg

        apps = await make_cluster(1, str(tmp_path))
        node = apps[0].node
        host, port = apps[0].advertised_addr.rsplit(":", 1)
        reader, writer = await asyncio.open_connection(host, int(port))
        parser = RespParser()

        async def chunk(cmds):
            writer.write(b"".join(
                encode_msg(Arr([Bulk(p) for p in parts])) for parts in cmds))
            await writer.drain()
            out = []
            while len(out) < len(cmds):
                data = await reader.read(1 << 16)
                assert data
                parser.feed(data)
                while (m := parser.next_msg()) is not None:
                    out.append(m)
            return out

        await chunk([[b"hset", b"h", b"f%d" % i, b"v"] for i in range(3)])
        r1 = await chunk([[b"hlen", b"h"]] * 4)
        assert all(m == Int(3) for m in r1)
        hits0 = node.read_cache.hits
        r2 = await chunk([[b"hlen", b"h"]] * 4)
        assert all(m == Int(3) for m in r2)
        assert node.read_cache.hits > hits0, "hlen not cache-served"
        # member write invalidates the whole-key card entry
        await chunk([[b"hdel", b"h", b"f0"]])
        (r3,) = await chunk([[b"hlen", b"h"]])
        assert r3 == Int(3 - 1)
        writer.close()
        await close_cluster(apps)
    run(main())
