"""The flow-sensitive half of the lint engine (analysis/cfg.py +
analysis/flow.py): CFG shape, staleness dataflow, pin/value-usage
semantics, and the cut-ordering must-analysis — unit-level, so rule
regressions point at the engine layer, not just a corpus diff."""

import ast
import textwrap

from constdb_tpu.analysis import flow
from constdb_tpu.analysis.cfg import awaits_in, build_cfg


def _fn(src: str) -> ast.AST:
    tree = ast.parse(textwrap.dedent(src))
    for node in ast.walk(tree):
        if isinstance(node, (ast.AsyncFunctionDef, ast.FunctionDef)):
            return node
    raise AssertionError("no function in snippet")


def _flow(src: str, pins=None) -> flow.FunctionFlow:
    return flow.FunctionFlow(_fn(src), pins)


# ------------------------------------------------------------------ cfg

def test_cfg_straight_line_and_branches():
    fn = _fn("""
    async def f(self, x):
        a = 1
        if x:
            b = 2
        else:
            b = 3
        while b:
            b -= 1
        return b
    """)
    cfg = build_cfg(fn)
    order = cfg.rpo()
    assert order[0] is cfg.entry
    # every non-exit block reaches the exit
    reach = {cfg.exit.bid}
    for blk in reversed(order):
        if any(s in reach for s in blk.succs):
            reach.add(blk.bid)
    assert cfg.entry.bid in reach


def test_cfg_await_points_found():
    fn = _fn("""
    async def f(self):
        await self.a()
        async with self.lk:
            pass
        async for x in self.it:
            await self.b(x)
    """)
    assert len(awaits_in(fn)) == 2  # explicit awaits; async-with/for
    #                                 are handled as header effects


def test_cfg_nested_defs_opaque():
    fn = _fn("""
    async def f(self):
        def g():
            return self._links
        await self.h(g)
    """)
    assert len(awaits_in(fn)) == 1


# ----------------------------------------------------------- staleness

def test_snapshot_goes_stale_across_await():
    fa = _flow("""
    async def f(self):
        links = list(self._links)
        await self.close()
        if links:
            self._links.clear()
    """)
    test_envs = [env for env in fa.env_at.values() if "links" in env]
    assert test_envs, "snapshot local never tracked"
    final = max(test_envs, key=lambda e: e["links"].stale)
    st = final["links"]
    assert st.sources == frozenset({"self._links"})
    assert st.stale and st.stale_line > st.line


def test_rebind_after_await_clears_staleness():
    fa = _flow("""
    async def f(self):
        links = list(self._links)
        await self.close()
        links = list(self._links)
        if links:
            self._links.clear()
    """)
    fn = fa.fn
    guard = [n for n in ast.walk(fn) if isinstance(n, ast.If)][0]
    st = fa.env_at[id(guard.test)]["links"]
    assert not st.stale


def test_pin_is_function_scoped():
    src = """
    async def f(self):
        doomed = list(self._links)  # lint: pin[doomed]
        await self.close()
        doomed = list(self._links)
        if doomed:
            self._links.clear()
    """
    pins = flow.pins_by_line(textwrap.dedent(src))
    fa = _flow(src, pins)
    assert all("doomed" not in env or not env["doomed"].sources
               for env in fa.env_at.values())


def test_loop_back_edge_joins_staleness():
    fa = _flow("""
    async def f(self):
        snap = dict(self._warm)
        while True:
            if snap:
                self._warm.clear()
            await self.tick()
    """)
    fn = fa.fn
    guard = [n for n in ast.walk(fn) if isinstance(n, ast.If)][0]
    st = fa.env_at[id(guard.test)]["snap"]
    # first iteration: fresh; via the back edge: stale — the join must
    # keep the MAY-stale fact
    assert st.stale


def test_value_used_names_exemptions():
    names = flow.value_used_names(ast.parse(
        "meta.needs_full or coal is None or cursor > 0",
        mode="eval").body)
    assert names == {"cursor"}  # deref base + is-None test are exempt


# -------------------------------------------------------- cut ordering

def test_cut_violation_and_fix():
    bad = _fn("""
    async def f(self):
        d = await self._local_digest(self.node)
        last = self.node.repl_log.last_uuid
        return d, last
    """)
    got = flow.cut_violations(bad)
    assert [term for _aw, term in got] == ["_local_digest"]

    fixed = _fn("""
    async def f(self):
        last = self.node.repl_log.last_uuid
        d = await self._local_digest(self.node)
        return d, last
    """)
    assert flow.cut_violations(fixed) == []


def test_cut_requires_both_halves():
    no_capture = _fn("""
    async def f(self):
        return await self.node.serve_plane.key_count()
    """)
    assert flow.cut_violations(no_capture) == []
    no_export = _fn("""
    async def f(self):
        last = self.node.repl_log.last_uuid
        await self.flush()
        return last
    """)
    assert flow.cut_violations(no_export) == []


def test_cut_some_path_semantics():
    branchy = _fn("""
    async def f(self):
        if self.app.fast:
            last = self.node.repl_log.last_uuid
        return await self.node.serve_plane.key_count()
    """)
    assert [t for _a, t in flow.cut_violations(branchy)] == ["key_count"]
    dominated = _fn("""
    async def f(self):
        last = self.node.repl_log.last_uuid
        if self.app.fast:
            return await self.node.serve_plane.key_count()
        return last
    """)
    assert flow.cut_violations(dominated) == []
