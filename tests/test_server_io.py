"""Server-level black-box tests over real TCP: command surface, INFO,
expiry, boot-time snapshot restore, and fault injection on the sync path."""

import asyncio
import os

import pytest

from constdb_tpu.errors import ConnBroken
from constdb_tpu.resp.message import Arr, Bulk, Err, Int, Nil, Simple

from cluster_util import (FAST, Client, close_cluster, converge, full_mesh,
                          make_cluster)


def run(coro):
    asyncio.run(coro)


def test_command_surface(tmp_path):
    async def main():
        apps = await make_cluster(1, str(tmp_path))
        c = await Client().connect(apps[0].advertised_addr)
        try:
            # errors
            bad = await c.cmd("nope")
            assert isinstance(bad, Err) and b"unknown command" in bad.val
            bad = await c.cmd("get")
            assert isinstance(bad, Err)
            await c.cmd("set", "s", "v")
            bad = await c.cmd("sadd", "s", "m")
            assert isinstance(bad, Err) and b"WRONGTYPE" in bad.val
            # node / client / desc / repllog
            assert await c.cmd("node", "id") == Int(apps[0].node.node_id)
            await c.cmd("node", "alias", "prima")
            assert await c.cmd("node", "alias") == Bulk(b"prima")
            tid = await c.cmd("client", "threadid")
            assert isinstance(tid, Bulk)
            d = await c.cmd("desc", "s")
            assert isinstance(d, Arr) and any(b"Bytes" in i.val for i in d.items)
            uuids = await c.cmd("repllog", "uuids")
            assert isinstance(uuids, Arr) and len(uuids.items) >= 1
            entry = await c.cmd("repllog", "at", uuids.items[0].val)
            assert isinstance(entry, Arr)
            # spop
            await c.cmd("sadd", "pop", "only")
            assert await c.cmd("spop", "pop") == Bulk(b"only")
            assert await c.cmd("spop", "pop") == Nil()
        finally:
            await c.close()
            await close_cluster(apps)
    run(main())


def test_info_sections(tmp_path):
    async def main():
        apps = await make_cluster(2, str(tmp_path))
        c = await Client().connect(apps[0].advertised_addr)
        try:
            await c.cmd("meet", apps[1].advertised_addr)
            await full_mesh(apps)
            await c.cmd("incr", "k")
            info = (await c.cmd("info")).val.decode()
            for section in ("# Server", "# Clients", "# Memory", "# Stats",
                            "# Replication", "# Keyspace"):
                assert section in info, info
            assert "connected_replicas:1" in info
            assert "counters:1" in info
            # store-exact memory accounting (L0 gauge)
            assert "store_numeric_bytes:" in info
            assert "store_keys:1" in info
            only = (await c.cmd("info", "keyspace")).val.decode()
            assert "# Keyspace" in only and "# Server" not in only
        finally:
            await c.close()
            await close_cluster(apps)
    run(main())


def test_expire_replicates(tmp_path):
    async def main():
        apps = await make_cluster(2, str(tmp_path))
        c1 = await Client().connect(apps[0].advertised_addr)
        c2 = await Client().connect(apps[1].advertised_addr)
        try:
            await c1.cmd("meet", apps[1].advertised_addr)
            await full_mesh(apps)
            await c1.cmd("set", "tmp", "v")
            assert await c1.cmd("expire", "tmp", "1") == Int(1)
            await converge(apps)
            ttl = await c2.cmd("ttl", "tmp")
            assert isinstance(ttl, Int) and 0 <= ttl.val <= 1
            assert await c2.cmd("get", "tmp") == Bulk(b"v")
            await asyncio.sleep(1.2)
            assert await c1.cmd("get", "tmp") == Nil()
            assert await c2.cmd("get", "tmp") == Nil()
            assert await c2.cmd("ttl", "tmp") == Int(-2)
        finally:
            await c1.close()
            await c2.close()
            await close_cluster(apps)
    run(main())


def test_snapshot_boot_restore(tmp_path):
    async def main():
        from constdb_tpu.persist.snapshot import NodeMeta, dump_keyspace
        from constdb_tpu.server.io import start_node
        from constdb_tpu.server.node import Node

        snap = str(tmp_path / "boot.snapshot")
        apps = await make_cluster(1, str(tmp_path))
        c = await Client().connect(apps[0].advertised_addr)
        node_id = apps[0].node.node_id
        await c.cmd("incr", "persisted")
        await c.cmd("sadd", "tags", "a", "b")
        dump_keyspace(snap, apps[0].node.ks,
                      NodeMeta(node_id=node_id,
                               repl_last_uuid=apps[0].node.repl_log.last_uuid))
        await c.close()
        await close_cluster(apps)

        # a fresh process restores from the snapshot (the reference restarts
        # empty — SURVEY.md §5.4)
        node2 = Node()
        app2 = await start_node(node2, host="127.0.0.1", port=0,
                                work_dir=str(tmp_path), snapshot_path=snap)
        try:
            c2 = await Client().connect(app2.advertised_addr)
            assert node2.node_id == node_id
            assert await c2.cmd("get", "persisted") == Int(1)
            got = await c2.cmd("smembers", "tags")
            assert {i.val for i in got.items} == {b"a", b"b"}
            await c2.close()
        finally:
            await app2.close()
    run(main())


def test_restored_node_full_syncs_fresh_peer(tmp_path):
    """A node restored from a boot snapshot must serve a FULL sync to any
    peer resuming below the restored watermark: its fresh repl_log holds
    none of the restored history, so a partial stream would silently omit
    every restored key (permanent divergence).  Same rule as the reference
    when the resume point falls outside the ring (push.rs:95-110)."""
    async def main():
        from constdb_tpu.persist.snapshot import NodeMeta, dump_keyspace
        from constdb_tpu.server.io import start_node
        from constdb_tpu.server.node import Node

        snap = str(tmp_path / "boot.snapshot")
        apps = await make_cluster(1, str(tmp_path))
        c = await Client().connect(apps[0].advertised_addr)
        for i in range(50):
            await c.cmd("set", f"old{i}", f"v{i}")
        dump_keyspace(snap, apps[0].node.ks,
                      NodeMeta(node_id=apps[0].node.node_id,
                               repl_last_uuid=apps[0].node.repl_log.last_uuid))
        await c.close()
        await close_cluster(apps)

        node2 = Node()
        app2 = await start_node(node2, host="127.0.0.1", port=0,
                                work_dir=str(tmp_path), snapshot_path=snap,
                                **FAST)
        # the restored log must not claim to cover pre-restore history
        assert not node2.repl_log.can_resume_from(0)
        fresh = (await make_cluster(1, str(tmp_path)))[0]
        try:
            c2 = await Client().connect(app2.advertised_addr)
            await c2.cmd("meet", fresh.advertised_addr)
            await converge([app2, fresh], timeout=15.0)
            await c2.close()
            assert fresh.node.ks.n_keys() == node2.ks.n_keys()
        finally:
            await app2.close()
            await fresh.close()
    run(main())


def test_sync_survives_injected_snapshot_failure(tmp_path):
    """Fault injection at the sync seam: the first snapshot download dies
    mid-transfer; the link must reconnect and fully converge (reference
    behavior: reconnect-forever, replica/replica.rs:254-271)."""
    async def main():
        from constdb_tpu.replica.link import ReplicaLink

        # tiny repl_log: catch-up MUST go through a full snapshot
        apps = await make_cluster(2, str(tmp_path), repl_log_cap=2_000)
        c1 = await Client().connect(apps[0].advertised_addr)
        try:
            for i in range(300):
                await c1.cmd("set", f"k{i}", f"v{i}")

            original = ReplicaLink._receive_snapshot
            failures = {"n": 0}

            async def flaky(self, reader, parser, size, repl_last, **kw):
                if failures["n"] == 0:
                    failures["n"] += 1
                    # consume nothing: simulate the peer dying mid-transfer
                    raise ConnectionError("injected snapshot failure")
                return await original(self, reader, parser, size, repl_last,
                                      **kw)

            ReplicaLink._receive_snapshot = flaky
            try:
                await c1.cmd("meet", apps[1].advertised_addr)
                await converge(apps, timeout=20.0)
            finally:
                ReplicaLink._receive_snapshot = original
            assert failures["n"] == 1
            assert apps[1].node.ks.n_keys() == apps[0].node.ks.n_keys()
        finally:
            await c1.close()
            await close_cluster(apps)
    run(main())


def test_repl_bytes_and_cpu_section(tmp_path):
    """Replication traffic must be visible in INFO: repl_* gauges count
    link bytes into the net totals (round-1 blind spot), and the CPU
    section exists (reference stats.rs)."""
    async def main():
        # wire_compress=False: this test pins RAW byte accounting (the
        # ~5KB of replicated values must show up on the gauges); the
        # compressed stream's accounting rides tests/test_wire_compress
        apps = await make_cluster(2, str(tmp_path), wire_compress=False)
        c = await Client().connect(apps[0].advertised_addr)
        try:
            for i in range(100):
                await c.cmd("set", f"k{i}", "x" * 50)
            await c.cmd("meet", apps[1].advertised_addr)
            await converge(apps)
            for app in apps:
                st = app.node.stats
                assert st.repl_out_bytes > 0, "push traffic uncounted"
                assert st.repl_in_bytes > 0, "pull traffic uncounted"
                assert st.net_out_bytes >= st.repl_out_bytes
                assert st.net_in_bytes >= st.repl_in_bytes
            # the receiver pulled at least the ~5KB of replicated values
            assert apps[1].node.stats.repl_in_bytes > 4000
            info = await c.cmd("info", "cpu")
            assert b"used_cpu_user" in info.val and b"used_cpu_sys" in info.val
            info = await c.cmd("info", "stats")
            assert b"repl_net_input_bytes" in info.val
        finally:
            await c.close()
            await close_cluster(apps)
    run(main())
