"""Cluster mode (constdb_tpu/cluster): slot math, routing, migration,
and the off-means-off wire pins.

The load-bearing identities under test (docs/INVARIANTS.md "Slot
ownership laws"):

  * slot == digest bucket under the canonical 64x256 geometry, so the
    digest plane's per-bucket exports/digests ARE the per-slot ones;
  * the four-way routing contract (None | MOVED | ASK | import-serve),
    with the redirect minting no uuid and replicating nothing;
  * a live migration flips ownership only behind the digest fixpoint,
    releases its GC pin, and leaves both groups on the same epoch;
  * CONSTDB_CLUSTER=0 (the default) and legacy peers see byte-exact
    pre-cluster replication streams — zero CLUSTERTAB frames, no
    CAP_CLUSTER bit (replica/link.py points here for that pin).
"""

import asyncio
import os
import sys
import types
import zlib

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from test_link_pushloop import (_log_write, _SharedDumpStub,  # noqa: E402
                                _Writer)

from constdb_tpu.cluster import (NSLOTS, SLOT_FANOUT,  # noqa: E402
                                 SLOT_LEAVES, ClusterState, SlotTable,
                                 bucket_of_slot, even_split, slot_of)
from constdb_tpu.replica.link import (CAP_CLUSTER,  # noqa: E402
                                      CAP_FULLSYNC_RESET, MY_CAPS,
                                      ReplicaLink, my_caps)
from constdb_tpu.replica.manager import ReplicaMeta  # noqa: E402
from constdb_tpu.resp.codec import make_parser  # noqa: E402
from constdb_tpu.resp.message import (Arr, Bulk, Err, Int,  # noqa: E402
                                      as_bytes, as_int)
from constdb_tpu.server.commands import execute  # noqa: E402
from constdb_tpu.server.node import Node  # noqa: E402

ADDRS = ["127.0.0.1:7100", "127.0.0.1:7101"]


def _key_for_group(gid: int, prefix: bytes = b"k") -> bytes:
    """A key the even 2-group split assigns to `gid`."""
    j = 0
    while True:
        k = prefix + b"%d" % j
        if (slot_of(k) < NSLOTS // 2) == (gid == 0):
            return k
        j += 1


def _two_group_state(my_gid: int = 0) -> ClusterState:
    return ClusterState(my_gid, even_split(2, addrs=ADDRS))


# --------------------------------------------------------------- slot math


def test_slot_of_is_the_digest_crc():
    for k in (b"a", b"foo", b"k%d" % 12345, b"\x00\xff" * 9):
        assert slot_of(k) == zlib.crc32(k) % NSLOTS


def test_bucket_of_slot_is_a_bijection():
    assert sorted(bucket_of_slot(s) for s in range(NSLOTS)) == \
        list(range(NSLOTS))
    assert SLOT_FANOUT * SLOT_LEAVES == NSLOTS


def test_slot_is_one_digest_cell():
    """A single write perturbs exactly its slot's cell of the 64x256
    digest matrix — the identity the migration fixpoint stands on."""
    from constdb_tpu.store.digest import state_digest_matrix
    node = Node(node_id=1)
    key = b"cellkey7"
    execute(node, Arr([Bulk(b"set"), Bulk(key), Bulk(b"v")]))
    node.ensure_flushed()
    mat = state_digest_matrix(node.ks, SLOT_FANOUT, SLOT_LEAVES).reshape(-1)
    hot = [i for i in range(NSLOTS) if int(mat[i]) != 0]
    assert hot == [bucket_of_slot(slot_of(key))]


def test_slot_export_carries_exactly_the_slot():
    """export_slot_batch ships the slot's keys (and nothing else) and
    merges into a fresh node — the migration payload path."""
    from constdb_tpu.cluster.migrate import export_slot_batch
    node = Node(node_id=1)
    key, other = b"exp0", None
    for j in range(1, 200):
        other = b"exp%d" % j
        if slot_of(other) != slot_of(key):
            break
    execute(node, Arr([Bulk(b"set"), Bulk(key), Bulk(b"inslot")]))
    execute(node, Arr([Bulk(b"set"), Bulk(other), Bulk(b"outside")]))
    sink = Node(node_id=2)
    sink.merge_batches([export_slot_batch(node, slot_of(key))])
    canon = sink.canonical()
    assert key in canon and other not in canon


def test_slot_table_codec_roundtrip():
    t = even_split(3, addrs=ADDRS + ["127.0.0.1:7102"])
    t.assign(100, 200, 2)
    t.epoch = 9
    back = SlotTable.deserialize(t.serialize())
    assert back.epoch == 9
    assert list(back.owner) == list(t.owner)
    assert back.groups == t.groups
    assert back.ranges() == t.ranges()


def test_even_split_covers_everything():
    for n in (1, 2, 3, 5):
        t = even_split(n)
        assert sorted({g for _, _, g in t.ranges()}) == list(range(n))
        assert sum(b - a + 1 for a, b, _ in t.ranges()) == NSLOTS


# ----------------------------------------------------------------- routing


def test_route_four_way_contract():
    cl = _two_group_state(0)
    mine, theirs = _key_for_group(0), _key_for_group(1)
    # owned, not migrating: serve locally
    assert cl.route(mine) is None
    # not owned: MOVED with the owner's address — reads and writes alike
    r = cl.route(theirs)
    assert isinstance(r, Err)
    assert r.val == b"MOVED %d %s" % (slot_of(theirs), ADDRS[1].encode())
    assert isinstance(cl.route(theirs, False), Err)
    # owned but mid-handoff: WRITES get ASK at the migration target,
    # reads keep serving from the still-complete source copy (a read
    # redirected before the final delta lands could miss a write the
    # source already committed)
    cl.migrating[slot_of(mine)] = "127.0.0.1:9999"
    r = cl.route(mine)
    assert r.val == b"ASK %d 127.0.0.1:9999" % slot_of(mine)
    assert cl.route(mine, False) is None
    assert cl.redirects_sent == 3
    # the target side serves a slot it is importing, table or no table
    imp = ClusterState(1, even_split(2, addrs=ADDRS))
    assert isinstance(imp.route(mine), Err)
    imp.importing[slot_of(mine)] = ADDRS[0]
    assert imp.route(mine) is None


def test_needs_redirect_is_counter_free():
    cl = _two_group_state(0)
    theirs = _key_for_group(1)
    assert cl.needs_redirect(theirs) and not cl.needs_redirect(
        _key_for_group(0))
    # probe matches route() on the read/write split too
    mine = _key_for_group(0)
    cl.migrating[slot_of(mine)] = "127.0.0.1:9999"
    assert cl.needs_redirect(mine, True)
    assert not cl.needs_redirect(mine, False)
    assert cl.redirects_sent == 0


def test_ask_window_serves_reads_locally_through_execute():
    """A committed write must stay readable on the source during its
    slot's ASK window: reads serve locally, writes redirect."""
    node = Node(node_id=1)
    node.cluster = _two_group_state(0)
    mine = _key_for_group(0)
    execute(node, Arr([Bulk(b"set"), Bulk(mine), Bulk(b"committed")]))
    node.cluster.migrating[slot_of(mine)] = "127.0.0.1:9999"
    r = execute(node, Arr([Bulk(b"get"), Bulk(mine)]))
    assert as_bytes(r) == b"committed"
    r = execute(node, Arr([Bulk(b"set"), Bulk(mine), Bulk(b"x")]))
    assert isinstance(r, Err) and r.val.startswith(b"ASK ")
    assert node.cluster.redirects_sent == 1


def test_adopt_joins_and_merges_addrs():
    cl = _two_group_state(0)
    same = even_split(2)
    assert not cl.adopt(same)  # no news: refused (and rev untouched)
    assert cl.rev == 0
    newer = even_split(2)
    newer.epoch = 5
    newer.groups = {1: "127.0.0.1:9001"}  # no address for group 0
    assert cl.adopt(newer)
    assert cl.epoch == 5 and cl.rev == 1
    # locally-known address survives the adoption
    assert cl.table.groups[0] == ADDRS[0]
    assert cl.table.groups[1] == "127.0.0.1:9001"


def _finalize_like(base, slot: int, gid: int):
    """A table a concurrent FINALIZE on `gid` would mint from `base`."""
    t = base.copy()
    t.assign(slot, slot + 1, gid, epoch=base.epoch + 1)
    t.epoch = base.epoch + 1
    return t


def test_adopt_merges_concurrent_equal_epoch_mints():
    """The REVIEW.md collision: two migrations to DIFFERENT groups
    finalize concurrently and both mint epoch N+1.  The per-slot
    (epoch, gid) join merges the tables — both flips survive, any
    exchange order converges byte-identically — where whole-table
    strictly-newer adoption would drop one and silently revert its
    flip."""
    base = even_split(3, addrs=ADDRS + ["127.0.0.1:7102"])
    s_a = 0      # owned by gid 0, flips to gid 1
    s_b = 16000  # owned by gid 2, flips to gid 1... use distinct gids
    t_a = _finalize_like(base, s_a, 1)
    t_b = _finalize_like(base, s_b, 0)
    assert t_a.epoch == t_b.epoch == base.epoch + 1
    one = ClusterState(0, base.copy())
    two = ClusterState(1, base.copy())
    assert one.adopt(t_a) and one.adopt(t_b)
    assert two.adopt(t_b) and two.adopt(t_a)
    for cl in (one, two):
        assert cl.table.owner[s_a] == 1
        assert cl.table.owner[s_b] == 0
        assert cl.epoch == base.epoch + 1
    assert one.table.serialize() == two.table.serialize()
    # idempotent: re-adopting either input changes nothing
    assert not one.adopt(t_a) and not one.adopt(t_b)


def test_adopt_same_slot_same_epoch_ties_break_on_gid():
    """A same-slot same-epoch conflict (can only arise from a split
    lineage) resolves deterministically — higher gid — in every
    exchange order, so the mesh converges instead of ping-ponging."""
    base = even_split(3, addrs=ADDRS + ["127.0.0.1:7102"])
    slot = 0
    t_lo = _finalize_like(base, slot, 1)
    t_hi = _finalize_like(base, slot, 2)
    one = ClusterState(0, base.copy())
    two = ClusterState(0, base.copy())
    one.adopt(t_lo)
    one.adopt(t_hi)
    two.adopt(t_hi)
    assert not two.adopt(t_lo)  # lower gid at the same epoch: no news
    assert one.table.owner[slot] == two.table.owner[slot] == 2
    assert one.table.serialize() == two.table.serialize()


def test_codec_roundtrips_slot_epochs():
    base = even_split(2, addrs=ADDRS)
    t = _finalize_like(base, 7, 1)
    back = SlotTable.deserialize(t.serialize())
    assert list(back.slot_epoch) == list(t.slot_epoch)
    assert back.slot_epoch[7] == 2 and back.slot_epoch[8] == 1
    # legacy 3-element runs (pre-slot-epoch payload) stamp the table
    # epoch — the strongest claim the old format could make
    import json as _json
    doc = _json.loads(t.serialize().decode())
    doc["runs"] = [[a, b, g] for a, b, g, _e in doc["runs"]]
    legacy = SlotTable.deserialize(_json.dumps(doc).encode())
    assert set(legacy.slot_epoch) == {t.epoch}


def test_execute_redirects_before_any_state():
    node = Node(node_id=1)
    node.cluster = _two_group_state(0)
    theirs, mine = _key_for_group(1), _key_for_group(0)
    hlc0 = node.hlc.current
    log0 = node.repl_log.last_uuid
    r = execute(node, Arr([Bulk(b"set"), Bulk(theirs), Bulk(b"v")]))
    assert isinstance(r, Err) and r.val.startswith(b"MOVED ")
    # reads route identically
    r = execute(node, Arr([Bulk(b"get"), Bulk(theirs)]))
    assert isinstance(r, Err) and r.val.startswith(b"MOVED ")
    # a redirect mints no uuid, applies nothing, replicates nothing
    assert node.hlc.current == hlc0
    assert node.repl_log.last_uuid == log0
    assert theirs not in node.canonical()
    assert node.cluster.redirects_sent == 2
    # owned keys execute normally
    execute(node, Arr([Bulk(b"set"), Bulk(mine), Bulk(b"v")]))
    assert mine in node.canonical()
    # control-plane commands never route (shard_routable gate)
    r = execute(node, Arr([Bulk(b"cluster"), Bulk(b"info")]))
    assert b"cluster_enabled:1" in as_bytes(r)


def test_replication_path_never_routes():
    """Replicated ops are group-scoped by construction (the writer
    routed); apply_replicated must land them even for foreign slots."""
    node = Node(node_id=1)
    node.cluster = _two_group_state(0)
    theirs = _key_for_group(1)
    node.apply_replicated(b"set", [Bulk(theirs), Bulk(b"v")], 2,
                          node.hlc.tick(True))
    assert theirs in node.canonical()


def test_cluster_off_serves_every_slot():
    node = Node(node_id=1)
    assert node.cluster is None
    for gid in (0, 1):
        k = _key_for_group(gid)
        execute(node, Arr([Bulk(b"set"), Bulk(k), Bulk(b"v")]))
        assert k in node.canonical()


# ------------------------------------------------------------------ GC pin


def test_gc_horizon_clamped_by_migration_pin():
    node = Node(node_id=1)
    cl = _two_group_state(0)
    node.cluster = cl
    execute(node, Arr([Bulk(b"set"), Bulk(b"gk"), Bulk(b"v")]))
    free = node.gc_horizon()
    assert free == node.hlc.current  # standalone: own clock
    a = cl.pin_gc(7)
    b = cl.pin_gc(12)  # lowest pin wins while both are held
    assert node.gc_horizon() == 7
    # pins are per holder (a MULTISET): releasing one migration's pin
    # never releases a concurrent one's — the REVIEW.md resurrection
    # shape was exactly a second migration's unpin wiping the first's
    # pin during its bulk/catch-up phase
    cl.unpin_gc(b)
    assert node.gc_horizon() == 7
    cl.unpin_gc(b)  # double-release: no-op, the other pin survives
    assert node.gc_horizon() == 7
    cl.unpin_gc(a)
    assert cl.gc_pin() is None
    assert node.gc_horizon() == node.hlc.current


def test_gc_pins_survive_concurrent_release_order():
    """Equal-valued pins from two overlapping migrations are distinct
    holders: one release drops exactly one instance."""
    cl = _two_group_state(0)
    cl.pin_gc(5)
    cl.pin_gc(5)
    cl.unpin_gc(5)
    assert cl.gc_pin() == 5
    cl.unpin_gc(5)
    assert cl.gc_pin() is None


def test_import_window_lifecycle_pins_and_expiry():
    """open_import pins once (a retry re-marks without stacking),
    drop_import releases exactly the window's pin, and a silent source
    trips the staleness sweep — the target never pins GC forever."""
    cl = _two_group_state(1)
    cl.open_import(3, ADDRS[0], 40, now=100.0)
    assert cl.gc_pin() == 40 and 3 in cl.importing
    # a RETRIED migration re-marks the slot: buffer resets, pin does
    # not stack (and keeps the ORIGINAL, lower, clamp)
    cl._import_buf[3] = bytearray(b"partial")
    cl.open_import(3, ADDRS[0], 55, now=101.0)
    assert cl.gc_pin() == 40
    assert 3 not in cl._import_buf
    # fresh stamps survive the sweep; silence past the stall drops the
    # window, the buffer, and the pin
    cl.expire_stale_imports(now=101.0 + cl.import_stall_s)
    assert 3 in cl.importing
    cl.touch_import(3, 200.0)
    cl.expire_stale_imports(now=200.0 + cl.import_stall_s + 1)
    assert 3 not in cl.importing
    assert cl.gc_pin() is None
    # drop_import is idempotent
    assert not cl.drop_import(3)


def test_setslot_stable_closes_the_window():
    """The source's abort verb: SETSLOT STABLE drops the importing
    mark, the partial chunk buffer, and the GC pin — and is idempotent
    (the staleness sweep can race it)."""
    node = Node(node_id=1)
    node.cluster = _two_group_state(1)
    slot = slot_of(_key_for_group(0))
    r = execute(node, Arr([Bulk(b"cluster"), Bulk(b"setslot"),
                           Bulk(b"%d" % slot), Bulk(b"importing"),
                           Bulk(b"1"), Bulk(ADDRS[0].encode())]))
    assert as_bytes(r) == b"OK"
    assert slot in node.cluster.importing
    assert node.cluster.gc_pin() is not None
    node.cluster._import_buf[slot] = bytearray(b"partial")
    for _ in range(2):  # idempotent
        r = execute(node, Arr([Bulk(b"cluster"), Bulk(b"setslot"),
                               Bulk(b"%d" % slot), Bulk(b"stable")]))
        assert as_bytes(r) == b"OK"
        assert slot not in node.cluster.importing
        assert node.cluster.gc_pin() is None
        assert slot not in node.cluster._import_buf


# ----------------------------------------------------- observability arms


def test_cluster_slots_and_info_sections():
    node = Node(node_id=1)
    node.cluster = _two_group_state(0)
    r = execute(node, Arr([Bulk(b"cluster"), Bulk(b"slots")]))
    rows = [(as_int(row.items[0]), as_int(row.items[1]),
             as_int(row.items[2]), as_bytes(row.items[3]))
            for row in r.items]
    assert rows == [(0, NSLOTS // 2 - 1, 0, ADDRS[0].encode()),
                    (NSLOTS // 2, NSLOTS - 1, 1, ADDRS[1].encode())]
    info = as_bytes(execute(node, Arr([Bulk(b"info"), Bulk(b"cluster")])))
    for want in (b"cluster_enabled:1", b"cluster_group:0",
                 b"cluster_epoch:1", b"slots_owned:%d" % (NSLOTS // 2),
                 b"migrations_out:0", b"redirects_sent:"):
        assert want in info, want
    off = Node(node_id=2)
    assert b"cluster_enabled:0" in as_bytes(
        execute(off, Arr([Bulk(b"info"), Bulk(b"cluster")])))
    assert b"cluster_enabled:0" in as_bytes(
        execute(off, Arr([Bulk(b"cluster"), Bulk(b"info")])))


# ------------------------------------------- off-means-off wire pins


def _fixed_clock():
    t = [1_700_000_000_000]

    def clock() -> int:
        t[0] += 1
        return t[0]
    return clock


def _stream_link(tmp_path, cluster: bool):
    """A push-loop link over a deterministic node: fixed HLC clock +
    identical writes, so two nodes differing ONLY in cluster mode must
    produce byte-identical streams to a legacy peer."""
    node = Node(node_id=1, repl_log_cap=100_000, clock=_fixed_clock())
    if cluster:
        node.cluster = _two_group_state(0)
    for i in range(25):
        _log_write(node, i)
    app = types.SimpleNamespace(node=node, heartbeat=0.05,
                                reconnect_delay=0.05,
                                handshake_timeout=1.0,
                                work_dir=str(tmp_path))
    app.shared_dump = _SharedDumpStub(node, str(tmp_path))
    return node, ReplicaLink(app, ReplicaMeta(addr="127.0.0.1:1"))


async def _pump(link, caps: int) -> bytes:
    writer = _Writer()
    link._peer_caps = caps
    task = asyncio.create_task(link._push_loop(writer, peer_resume=0))
    try:
        for _ in range(400):
            await asyncio.sleep(0.01)
            if b"k24" in writer.buf:  # the last logged write streamed
                break
    finally:
        task.cancel()
        try:
            await task
        except (asyncio.CancelledError, Exception):
            pass
    assert b"k24" in writer.buf
    return bytes(writer.buf)


def _frame_kinds(buf: bytes) -> list[bytes]:
    parser = make_parser()
    parser.feed(buf)
    kinds = []
    while (msg := parser.next_msg()) is not None:
        kinds.append(as_bytes(msg.items[0]).lower())
    return kinds


def test_cap_cluster_outside_my_caps():
    assert not (MY_CAPS & CAP_CLUSTER)
    on, off = Node(node_id=1), Node(node_id=2)
    on.cluster = _two_group_state(0)
    assert my_caps(types.SimpleNamespace(node=on)) & CAP_CLUSTER
    assert not my_caps(types.SimpleNamespace(node=off)) & CAP_CLUSTER


def test_legacy_peer_stream_is_byte_exact(tmp_path):
    """The pin replica/link.py names: a cluster-ON node pushing to a
    peer WITHOUT CAP_CLUSTER writes the byte-identical stream a
    CONSTDB_CLUSTER=0 node would — zero CLUSTERTAB frames, nothing
    reordered or resized around them.  REPLACK heartbeats carry real
    wall time (link.py now_ms()) and are filtered before the compare —
    every other frame must match byte-for-byte."""
    from constdb_tpu.resp.codec import encode_msg

    def data_frames(buf: bytes) -> list[bytes]:
        parser = make_parser()
        parser.feed(buf)
        out = []
        while (msg := parser.next_msg()) is not None:
            if as_bytes(msg.items[0]).lower() != b"replack":
                out.append(encode_msg(msg))
        return out

    async def main():
        _, link_on = _stream_link(tmp_path, cluster=True)
        _, link_off = _stream_link(tmp_path, cluster=False)
        buf_on = await _pump(link_on, CAP_FULLSYNC_RESET)
        buf_off = await _pump(link_off, CAP_FULLSYNC_RESET)
        assert b"clustertab" not in buf_on
        frames_on, frames_off = data_frames(buf_on), data_frames(buf_off)
        n = min(len(frames_on), len(frames_off))
        assert n >= 26  # partsync + the 25 replicate frames
        assert frames_on[:n] == frames_off[:n]
    asyncio.run(main())


def test_cluster_peer_gets_one_clustertab_per_epoch(tmp_path):
    async def main():
        node, link = _stream_link(tmp_path, cluster=True)
        buf = await _pump(link, CAP_FULLSYNC_RESET | CAP_CLUSTER)
        kinds = _frame_kinds(buf)
        assert kinds.count(b"clustertab") == 1
        parser = make_parser()
        parser.feed(buf)
        while (msg := parser.next_msg()) is not None:
            if as_bytes(msg.items[0]).lower() == b"clustertab":
                assert as_int(msg.items[1]) == node.cluster.epoch
                table = SlotTable.deserialize(as_bytes(msg.items[2]))
                assert table.serialize() == node.cluster.table.serialize()
    asyncio.run(main())


class _EOFReader:
    async def read(self, n: int) -> bytes:
        return b""


def _feed_clustertab(table: SlotTable):
    from constdb_tpu.resp.codec import encode_msg
    parser = make_parser()
    parser.feed(encode_msg(Arr([Bulk(b"clustertab"), Int(table.epoch),
                                Bulk(table.serialize())])))
    return parser


def test_clustertab_on_disabled_node_is_a_protocol_error(tmp_path):
    """A CONSTDB_CLUSTER=0 node never advertised CAP_CLUSTER; a
    CLUSTERTAB frame arriving anyway is a capability mismatch and must
    be rejected loudly, not half-adopted."""
    from constdb_tpu.errors import CstError
    _, link = _stream_link(tmp_path, cluster=False)
    parser = _feed_clustertab(even_split(2, addrs=ADDRS))

    async def main():
        with pytest.raises(CstError, match="non-cluster"):
            await link._pull_frames(
                _EOFReader(), None, parser,
                types.SimpleNamespace(pending=False))
    asyncio.run(main())


def test_clustertab_pull_adopts_strictly_newer(tmp_path):
    node, link = _stream_link(tmp_path, cluster=True)
    newer = even_split(2, addrs=ADDRS)
    newer.epoch = 5
    stale = even_split(2, addrs=ADDRS)  # epoch 1 == current: refused

    async def main():
        for table, want_epoch in ((newer, 5), (stale, 5)):
            with pytest.raises(ConnectionError):
                await link._pull_frames(
                    _EOFReader(), None, _feed_clustertab(table),
                    types.SimpleNamespace(pending=False))
            assert node.cluster.epoch == want_epoch
    asyncio.run(main())


# --------------------------------------------------------- migration e2e


def test_slot_migration_end_to_end(tmp_path):
    """Two served single-node groups, a live migration of one slot:
    ownership flips behind the digest fixpoint, both groups land on the
    bumped epoch, the data serves from the new owner, the old owner
    redirects, counters tick, and the GC pins release."""
    from constdb_tpu.chaos.cluster import Client
    from constdb_tpu.chaos.cluster_cells import (RedirectClient,
                                                 _migrate, _seed_addrs,
                                                 _specs)
    from constdb_tpu.chaos.cluster import ChaosCluster

    async def main():
        cluster = ChaosCluster(str(tmp_path), 11, _specs())
        await cluster.start()
        rc = RedirectClient()
        try:
            await _seed_addrs(cluster)
            addr0 = cluster.apps[0].advertised_addr
            addr1 = cluster.apps[1].advertised_addr
            node0, node1 = cluster.apps[0].node, cluster.apps[1].node
            key = _key_for_group(0, b"mig")
            slot = slot_of(key)
            await rc.cmd(addr0, b"set", key, b"payload")
            await rc.cmd(addr0, b"sadd", key + b":s", b"a", b"b")
            assert await _migrate(cluster, 0, slot, addr1), \
                "migration never flipped ownership"
            assert not node0.cluster.owns(slot)
            assert node1.cluster.owns(slot)
            # both sides on the same bumped epoch (finalize reply
            # adoption — no repl link exists between the groups)
            assert node0.cluster.epoch == node1.cluster.epoch == 2
            # the data answers at the new owner; the old owner redirects
            c1 = await Client().connect(addr1)
            try:
                assert as_bytes(await c1.cmd(b"get", key)) == b"payload"
            finally:
                await c1.close()
            c0 = await Client().connect(addr0)
            try:
                r = await c0.cmd(b"get", key)
                assert isinstance(r, Err)
                assert r.val == b"MOVED %d %s" % (slot, addr1.encode())
            finally:
                await c0.close()
            # the redirect-following client still reads through node 0
            assert as_bytes(await rc.cmd(addr0, b"get", key)) == b"payload"
            # counters + pins
            assert node0.cluster.migrations_out == 1
            assert node1.cluster.migrations_in == 1
            assert node0.cluster.gc_pin() is None
            assert node1.cluster.gc_pin() is None
            assert not node0.cluster.migrating
            assert not node1.cluster.importing
            info = as_bytes(await rc.cmd(addr0, b"info", b"cluster"))
            assert b"migrations_out:1" in info
        finally:
            await rc.close()
            await cluster.close()
    asyncio.run(main())


def test_abort_after_ask_window_reclaims_target_writes(tmp_path, monkeypatch):
    """REVIEW.md abort law, end to end: a migration that dies AFTER its
    ASK window opened must pull the window's target-acknowledged writes
    back to the source (SETSLOT STABLE + SLOTEXPORT) before the source
    resumes serving the slot — there is deliberately no inter-group
    repl stream to carry them later."""
    from constdb_tpu.chaos.cluster import ChaosCluster, Client
    from constdb_tpu.chaos.cluster_cells import (RedirectClient,
                                                 _seed_addrs, _specs)
    from constdb_tpu.cluster import migrate
    from constdb_tpu.errors import CstError

    reached = asyncio.Event()
    proceed = asyncio.Event()
    probes = [0]

    class _StuckChan(migrate._Chan):
        """Real wire for everything except SLOTDIGEST, which (a) parks
        the first probe so the test can inject window writes and (b)
        never repeats a value, so the fixpoint can never certify and
        the migration aborts with its window open."""

        async def call(self, *parts):
            if len(parts) > 1 and parts[1] == b"slotdigest":
                if not reached.is_set():
                    reached.set()
                    await proceed.wait()
                probes[0] += 1
                return Bulk(b"%d" % probes[0])
            return await super().call(*parts)

    async def main():
        monkeypatch.setattr(migrate, "_Chan", _StuckChan)
        cluster = ChaosCluster(str(tmp_path), 23, _specs())
        await cluster.start()
        rc = RedirectClient()
        try:
            await _seed_addrs(cluster)
            addr0 = cluster.apps[0].advertised_addr
            addr1 = cluster.apps[1].advertised_addr
            node0, node1 = cluster.apps[0].node, cluster.apps[1].node
            key = _key_for_group(0, b"mig")
            slot = slot_of(key)
            # a second key in the SAME slot, born during the window
            j, fresh = 0, None
            while fresh is None:
                k = b"w%d" % j
                if slot_of(k) == slot:
                    fresh = k
                j += 1
            await rc.cmd(addr0, b"set", key, b"payload")
            task = asyncio.create_task(migrate.migrate_slot(
                node0, cluster.apps[0], slot, addr1, timeout=5.0))
            await asyncio.wait_for(reached.wait(), 5.0)
            # the ASK window is open: these writes redirect to the
            # target and are acknowledged ONLY there
            assert slot in node0.cluster.migrating
            await rc.cmd(addr0, b"set", key, b"window-write")
            await rc.cmd(addr0, b"set", fresh, b"window-born")
            assert rc.redirects >= 2
            proceed.set()
            with pytest.raises(CstError, match="fixpoint"):
                await task
            # ownership never flipped and every window artifact is gone
            assert node0.cluster.owns(slot)
            assert not node1.cluster.owns(slot)
            assert not node0.cluster.migrating
            assert not node1.cluster.importing
            assert node0.cluster.gc_pin() is None
            assert node1.cluster.gc_pin() is None
            assert not node1.cluster._export_buf
            # the reclaimed writes answer DIRECTLY on the source
            c0 = await Client().connect(addr0)
            try:
                assert as_bytes(await c0.cmd(b"get", key)) \
                    == b"window-write"
                assert as_bytes(await c0.cmd(b"get", fresh)) \
                    == b"window-born"
            finally:
                await c0.close()
            # the target, its window closed by STABLE, bounces the slot
            # back at the settled owner
            c1 = await Client().connect(addr1)
            try:
                r = await c1.cmd(b"get", key)
                assert isinstance(r, Err)
                assert r.val.startswith(b"MOVED %d " % slot)
            finally:
                await c1.close()
        finally:
            await rc.close()
            await cluster.close()
    asyncio.run(main())
