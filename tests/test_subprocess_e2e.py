"""Black-box cluster smoke: REAL server subprocesses booted from TOML
configs, driven by the shipped test binary over TCP, then a restart that
must warm-boot from the final snapshot.

This is the reference's integration strategy run end-to-end against our
actual binaries (reference bin/test.rs:95-116 spawns servers the same
way), guarding the whole boot → serve → replicate → dump → restore loop.
"""

import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # sitecustomize: skip TPU plugin
    return env


def _resp(port, *parts, retries=60):
    for _ in range(retries):
        try:
            s = socket.create_connection(("127.0.0.1", port), timeout=2)
            break
        except OSError:
            time.sleep(0.25)
    else:
        raise RuntimeError(f"cannot connect :{port}")
    req = b"*%d\r\n" % len(parts) + b"".join(
        b"$%d\r\n%s\r\n" % (len(p), p) for p in
        (x if isinstance(x, bytes) else str(x).encode() for x in parts))
    s.sendall(req)
    time.sleep(0.15)
    out = s.recv(1 << 16)
    s.close()
    return out


@pytest.mark.slow  # ~6s of real process spawns: over the tier-1 per-test
# budget (scripts/audit_markers.sh); still runs in unfiltered invocations
def test_three_node_cluster_from_toml(tmp_path):
    ports = [_free_port() for _ in range(3)]
    procs = []
    try:
        for i, port in enumerate(ports):
            wd = tmp_path / f"n{i + 1}"
            wd.mkdir()
            cfgp = tmp_path / f"n{i + 1}.toml"
            cfgp.write_text(
                f'node_id = {i + 1}\n'
                f'node_alias = "n{i + 1}"\n'
                f'ip = "127.0.0.1"\n'
                f'port = {port}\n'
                f'work_dir = "{wd}"\n'
                f'engine = "cpu"\n'
                f'snapshot_path = "{wd}/boot.snapshot"\n'
                f'replica_heartbeat_frequency = 1\n'
                f'replica_gossip_frequency = 2\n'
                f'log_level = "info"\n')
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "constdb_tpu.bin.server", str(cfgp)],
                cwd=REPO, env=_env(),
                stdout=open(tmp_path / f"n{i + 1}.log", "ab"),
                stderr=subprocess.STDOUT))
        for port in ports:
            assert b"PONG" in _resp(port, b"ping") or True  # wait until up

        # the shipped black-box harness forms the mesh and asserts
        # convergence with its oracle model
        run = subprocess.run(
            [sys.executable, "-m", "constdb_tpu.bin.test", "--replicas",
             *[f"127.0.0.1:{p}" for p in ports], "--ops", "120"],
            cwd=REPO, env=_env(), capture_output=True, text=True,
            timeout=120)
        assert run.returncode == 0, run.stdout + run.stderr

        # a marker write, then restart node 3: SIGTERM dumps, boot restores
        assert b"OK" in _resp(ports[0], b"set", b"marker", b"v1")
        deadline = time.time() + 20
        while b"v1" not in _resp(ports[2], b"get", b"marker"):
            assert time.time() < deadline, "marker did not replicate"
            time.sleep(0.3)
        procs[2].send_signal(signal.SIGTERM)
        procs[2].wait(timeout=20)
        assert os.path.exists(tmp_path / "n3" / "boot.snapshot")
        procs[2] = subprocess.Popen(
            [sys.executable, "-m", "constdb_tpu.bin.server",
             str(tmp_path / "n3.toml")],
            cwd=REPO, env=_env(),
            stdout=open(tmp_path / "n3.log", "ab"),
            stderr=subprocess.STDOUT)
        assert b"v1" in _resp(ports[2], b"get", b"marker"), \
            "warm boot lost the marker"

        # the mesh reconverges: a write on n1 reaches the restarted n3
        assert b"OK" in _resp(ports[0], b"set", b"post", b"v2")
        deadline = time.time() + 30
        while b"v2" not in _resp(ports[2], b"get", b"post"):
            assert time.time() < deadline, "restarted node never reconverged"
            time.sleep(0.4)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
