"""Command-layer tests: dispatch, per-type semantics, del rewrites, and
two-node convergence through the replication stream (the op-path analogue of
the reference's bin/test.rs oracle harness)."""

import pytest

from constdb_tpu.resp.message import Arr, Bulk, Err, Int, Msg, NIL, Nil, NoReply, Simple, mkcmd
from constdb_tpu.server.node import Node
from constdb_tpu.server.repl_log import ReplLog


class FakeClock:
    def __init__(self, start=1000):
        self.ms = start

    def __call__(self):
        return self.ms

    def advance(self, d=1):
        self.ms += d


def mknode(node_id=1, start_ms=1000):
    clk = FakeClock(start_ms)
    n = Node(node_id=node_id, alias=f"n{node_id}", clock=clk)
    n.clock = clk
    return n


def run(n, *parts):
    reply = n.execute(mkcmd(*parts))
    n.clock.advance()
    return reply


def replay(src: Node, dst: Node):
    """Feed src's repl_log to dst the way a Puller would."""
    for e in list(src.repl_log._entries):
        dst.apply_replicated(e.name, e.args, src.node_id, e.uuid)


def converged(a: Node, b: Node) -> bool:
    return a.ks.canonical() == b.ks.canonical()


# ---------------------------------------------------------------- basics

def test_set_get_roundtrip():
    n = mknode()
    assert run(n, "set", "k", "v") == Simple(b"OK")
    assert run(n, "get", "k") == Bulk(b"v")
    assert run(n, "get", "missing") == NIL


def test_incr_decr_and_get():
    n = mknode()
    assert run(n, "incr", "c") == Int(1)
    assert run(n, "incr", "c") == Int(2)
    assert run(n, "decr", "c") == Int(1)
    assert run(n, "get", "c") == Int(1)


def test_wrongtype_errors():
    n = mknode()
    run(n, "incr", "c")
    r = run(n, "set", "c", "x")
    assert isinstance(r, Err) and b"WRONGTYPE" in r.val
    r = run(n, "sadd", "c", "m")
    assert isinstance(r, Err)


def test_unknown_and_arity():
    n = mknode()
    assert isinstance(run(n, "nope"), Err)
    assert isinstance(run(n, "get"), Err)


def test_set_ops():
    n = mknode()
    assert run(n, "sadd", "s", "a", "b") == Int(2)
    assert run(n, "sadd", "s", "b") == Int(0)
    r = run(n, "smembers", "s")
    assert sorted(m.val for m in r.items) == [b"a", b"b"]
    assert run(n, "srem", "s", "a") == Int(1)
    assert run(n, "srem", "s", "zz") == Int(0)
    r = run(n, "smembers", "s")
    assert [m.val for m in r.items] == [b"b"]


def test_hash_ops():
    n = mknode()
    assert run(n, "hset", "h", "f1", "v1", "f2", "v2") == Int(2)
    assert run(n, "hget", "h", "f1") == Bulk(b"v1")
    assert run(n, "hget", "h", "zz") == NIL
    r = run(n, "hgetall", "h")
    got = sorted((p.items[0].val, p.items[1].val) for p in r.items)
    assert got == [(b"f1", b"v1"), (b"f2", b"v2")]
    assert run(n, "hdel", "h", "f1") == Int(1)
    assert run(n, "hget", "h", "f1") == NIL


def test_hset_overwrites_value():
    n = mknode()
    run(n, "hset", "h", "f", "v1")
    assert run(n, "hset", "h", "f", "v2") == Int(0)  # not newly-visible
    assert run(n, "hget", "h", "f") == Bulk(b"v2")


# ---------------------------------------------------------------- del

def test_del_bytes_tombstones_and_rewrites():
    n = mknode()
    run(n, "set", "k", "v")
    assert run(n, "del", "k") == Int(1)
    assert run(n, "get", "k") == NIL
    names = [e.name for e in n.repl_log._entries]
    assert names == [b"set", b"delbytes"]


def test_del_counter_tombstones_and_rewrites():
    n = mknode()
    run(n, "incr", "c")
    run(n, "incr", "c")
    assert run(n, "del", "c") == Int(1)
    assert run(n, "get", "c") == NIL
    e = [e for e in n.repl_log._entries if e.name == b"delcnt"]
    assert len(e) == 1 and e[0].args[0].val == b"c"
    # resurrect: a later incr counts from 0 (dt gated out the old slots)
    assert run(n, "incr", "c") == Int(1)
    assert run(n, "get", "c") == Int(1)


def test_counter_delete_converges_despite_interleaving():
    """The divergence that killed the reference's delta-based delcnt: a
    deleting node and a lagging node apply {incr, del} in different orders."""
    a, b, c = mknode(1, 1000), mknode(2, 2000), mknode(3, 3000)
    run(a, "incr", "c")
    replay(a, b)                       # b saw a's incr, c did NOT yet
    run(b, "del", "c")                 # b deletes knowing only a's 1 incr
    run(c, "incr", "c")                # c's own concurrent incr (t < b's del)
    # now everything reaches everyone, in different orders
    replay(b, c); replay(a, c)
    replay(c, a); replay(b, a)
    replay(c, b)
    assert converged(a, b) and converged(b, c)
    # c's incr is NEWER than b's delete, so it revives the counter from zero
    assert run(a, "get", "c") == run(b, "get", "c") == run(c, "get", "c") == Int(1)


def test_del_set_and_resurrect():
    n = mknode()
    run(n, "sadd", "s", "a", "b")
    assert run(n, "del", "s") == Int(1)
    assert run(n, "smembers", "s") == Arr([])
    run(n, "sadd", "s", "c")
    r = run(n, "smembers", "s")
    assert [m.val for m in r.items] == [b"c"]


def test_del_missing_key():
    n = mknode()
    assert run(n, "del", "zz") == Int(0)


def test_repl_only_rejected_from_client():
    n = mknode()
    r = run(n, "delset", "s")
    assert isinstance(r, Err) and b"replicas" in r.val


def test_client_only_rejected_from_repl():
    n = mknode()
    from constdb_tpu.errors import InvalidRequestMsg
    with pytest.raises(InvalidRequestMsg):
        n.apply_replicated(b"del", [Bulk(b"k")], 9, 1 << 30)


# ------------------------------------------------------------ replication

def test_two_node_convergence_basic():
    a, b = mknode(1, 1000), mknode(2, 2000)
    run(a, "set", "k", "va")
    run(a, "incr", "c")
    run(a, "sadd", "s", "x", "y")
    run(a, "hset", "h", "f", "v")
    replay(a, b)
    assert run(b, "get", "k") == Bulk(b"va")
    assert run(b, "get", "c") == Int(1)
    assert converged(a, b)


def test_concurrent_set_lww_converges():
    # b's clock is ahead, so b's write wins on both nodes
    a, b = mknode(1, 1000), mknode(2, 50_000)
    run(a, "set", "k", "va")
    run(b, "set", "k", "vb")
    replay(a, b)
    replay(b, a)
    assert run(a, "get", "k") == Bulk(b"vb")
    assert run(b, "get", "k") == Bulk(b"vb")
    assert converged(a, b)


def test_concurrent_counter_adds_sum():
    a, b = mknode(1, 1000), mknode(2, 2000)
    run(a, "incr", "c")
    run(a, "incr", "c")
    run(b, "decr", "c")
    replay(a, b)
    replay(b, a)
    assert run(a, "get", "c") == Int(1)
    assert run(b, "get", "c") == Int(1)
    assert converged(a, b)


def test_sadd_vs_remote_key_delete():
    # a deletes the whole set at a LATER time than b's concurrent sadd:
    # the delete wins for b's members once streams cross
    a, b = mknode(1, 10_000), mknode(2, 1000)
    run(a, "sadd", "s", "m1")
    replay(a, b)
    run(b, "sadd", "s", "m2")       # t ~ 1001 < a's del time
    run(a, "del", "s")              # t ~ 10001
    replay(a, b)                     # b sees delset AFTER its own sadd
    replay(b, a)                     # a sees b's sadd AFTER its delset
    assert run(a, "smembers", "s") == Arr([])
    assert run(b, "smembers", "s") == Arr([])
    assert converged(a, b)


def test_hset_vs_remote_key_delete():
    a, b = mknode(1, 10_000), mknode(2, 1000)
    run(a, "hset", "h", "f1", "v1")
    replay(a, b)
    run(b, "hset", "h", "f2", "v2")
    run(a, "del", "h")
    replay(a, b)
    replay(b, a)
    assert run(a, "hgetall", "h") == Arr([])
    assert run(b, "hgetall", "h") == Arr([])
    assert converged(a, b)


def test_spop_replicates_deterministic_srem():
    a, b = mknode(1, 1000), mknode(2, 2000)
    run(a, "sadd", "s", "a", "b", "c")
    popped = run(a, "spop", "s")
    assert isinstance(popped, Bulk)
    replay(a, b)
    ra = sorted(m.val for m in run(a, "smembers", "s").items)
    rb = sorted(m.val for m in run(b, "smembers", "s").items)
    assert ra == rb and len(ra) == 2 and popped.val not in ra
    names = [e.name for e in a.repl_log._entries]
    assert b"spop" not in names and names.count(b"srem") == 1


def test_replicated_uuid_advances_local_clock():
    a, b = mknode(1, 50_000), mknode(2, 1000)
    run(a, "set", "k", "va")
    replay(a, b)
    run(b, "set", "k", "vb")  # must win: b's HLC observed a's larger uuid
    replay(b, a)
    assert run(a, "get", "k") == Bulk(b"vb")
    assert run(b, "get", "k") == Bulk(b"vb")


# ------------------------------------------------------------------ expiry

def test_expire_ttl_and_lazy_delete():
    n = mknode()
    run(n, "set", "k", "v")
    assert run(n, "expire", "k", 10) == Int(1)
    ttl = run(n, "ttl", "k")
    assert isinstance(ttl, Int) and 0 <= ttl.val <= 10
    assert run(n, "ttl", "missing") == Int(-2)
    run(n, "set", "k2", "v")
    assert run(n, "ttl", "k2") == Int(-1)


def test_expire_fires_via_hlc():
    clk = FakeClock(1000)
    import constdb_tpu.server.commands as C
    n = Node(node_id=1, clock=clk)
    n.clock = clk
    run(n, "set", "k", "v")
    # bypass wall clock: expire at an absolute uuid just past now
    kid = n.ks.lookup(b"k")
    exp_uuid = (clk.ms + 5) << 22
    n.ks.expire_at(b"k", exp_uuid)
    assert run(n, "get", "k") == Bulk(b"v")
    clk.advance(100)
    assert run(n, "get", "k") == NIL  # lazily tombstoned
    assert not n.ks.alive(kid)


def test_expiry_replicates_absolute_deadline():
    n = mknode()
    run(n, "set", "k", "v")
    run(n, "expire", "k", 10)
    names = [e.name for e in n.repl_log._entries]
    assert names == [b"set", b"expireat"]


# ------------------------------------------------------------------ misc

def test_node_command():
    n = mknode(7)
    assert run(n, "node", "id") == Int(7)
    assert run(n, "node", "id", "9") == Simple(b"OK")
    assert n.node_id == 9
    assert run(n, "node", "alias") == Bulk(b"n7")
    assert run(n, "node", "alias", "bob") == Simple(b"OK")
    assert run(n, "node", "alias") == Bulk(b"bob")


def test_desc_and_repllog():
    n = mknode()
    run(n, "set", "k", "v")
    d = run(n, "desc", "k")
    assert isinstance(d, Arr)
    uuids = run(n, "repllog", "uuids")
    assert len(uuids.items) == 1
    at = run(n, "repllog", "at", uuids.items[0].val)
    assert isinstance(at, Arr) and at.items[0].val == b"set"
    assert run(n, "repllog", "at", 42) == NIL


def test_readonly_commands_do_not_replicate():
    n = mknode()
    run(n, "get", "k")
    run(n, "smembers", "s")
    assert len(n.repl_log) == 0


# ---------------------------------------------------------------- repl_log

def test_repl_log_ring_eviction_and_resume():
    rl = ReplLog(cap_bytes=100)
    for i in range(1, 50):
        rl.push(i, b"set", [Bulk(b"k" * 10), Bulk(b"v" * 10)])
    assert rl.total_bytes <= 100 + 23
    assert rl.evicted_up_to > 0
    assert not rl.can_resume_from(0)
    assert rl.can_resume_from(rl.evicted_up_to)
    assert rl.first_uuid == rl.evicted_up_to + 1
    e = rl.next_after(rl.evicted_up_to)
    assert e is not None and e.uuid == rl.first_uuid
    assert rl.next_after(49) is None
    assert rl.at(49).uuid == 49


def test_repl_log_rejects_regressing_uuid():
    rl = ReplLog()
    rl.push(10, b"set", [])
    with pytest.raises(ValueError):
        rl.push(10, b"set", [])


def test_gc_frees_acked_tombstones():
    n = mknode()
    run(n, "sadd", "s", "a", "b")
    run(n, "srem", "s", "a")
    kid = n.ks.lookup(b"s")
    assert len(list(n.ks.elem_all(kid))) == 2
    freed = n.gc()  # standalone: horizon = own clock
    assert freed >= 1
    assert len(list(n.ks.elem_all(kid))) == 1


def test_incr_decr_optional_amount():
    """INCR/DECR take an optional amount (Redis INCRBY/DECRBY folded in;
    the reference steps by exactly 1 — type_counter.rs:169-189).  The
    wire stays the absolute cntset total either way, so replaying the
    log on a peer converges."""
    node = mknode()
    assert run(node, "incr", "c") == Int(1)
    assert run(node, "incr", "c", "41") == Int(42)
    assert run(node, "decr", "c", "40") == Int(2)
    assert run(node, "decr", "c") == Int(1)
    peer = mknode(node_id=9, start_ms=5000)
    replay(node, peer)
    assert run(peer, "get", "c") == Int(1)
