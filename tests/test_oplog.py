"""Durable op log (persist/oplog.py): framing fuzz, replay
differentials, watermark consistency cuts, rewrite compaction, the
boot-quarantine fallback, and the INFO Durability section.

The load-bearing suites:

  * the torn-tail fuzz sweep — truncate the log at EVERY byte offset
    and flip EVERY bit across record boundaries; recovery must always
    land on a valid record prefix, never crash-loop, never replay a
    corrupt record (the compressio every-bit-flip discipline, applied
    to the AOF framing);
  * the replay differential — a recovered node's canonical export AND
    full-state digest equal a never-crashed reference node fed the
    same stream (boot replay routes through the real merge path);
  * the persisted consistency-cut regression — recovered watermarks
    never claim pull coverage beyond the fsync cut (no adopt-then-skip
    resurrection).
"""

from __future__ import annotations

import asyncio
import os

import numpy as np
import pytest

from constdb_tpu.chaos.cluster import FAST, Client
from constdb_tpu.persist import oplog as OL
from constdb_tpu.persist.oplog import (MAGIC, OpLog, RecoveryInfo,
                                       scan_segment)
from constdb_tpu.resp.codec import encode_msg
from constdb_tpu.resp.message import Arr, Bulk
from constdb_tpu.server.io import start_node
from constdb_tpu.server.node import Node
from constdb_tpu.store.digest import full_state_digest


# ---------------------------------------------------------------- helpers


async def _pipelined(addr: str, cmds: list) -> list:
    """One pipelined chunk; returns the replies in order."""
    c = await Client().connect(addr)
    try:
        buf = bytearray()
        for parts in cmds:
            buf += encode_msg(Arr([Bulk(p) for p in parts]))
        c.writer.write(bytes(buf))
        await c.writer.drain()
        out = []
        while len(out) < len(cmds):
            msg = c.parser.next_msg()
            if msg is not None:
                out.append(msg)
                continue
            data = await asyncio.wait_for(c.reader.read(1 << 16), 10.0)
            assert data, "EOF mid-pipeline"
            c.parser.feed(data)
        return out
    finally:
        await c.close()


def _workload_cmds(n: int = 120) -> list:
    cmds = []
    for i in range(n):
        k = i % 7
        if k < 3:
            cmds.append([b"set", b"reg%d" % (i % 9), b"v%d" % i])
        elif k < 5:
            cmds.append([b"incr", b"cnt%d" % (i % 4), b"%d" % (1 + i % 3)])
        elif k == 5:
            cmds.append([b"sadd", b"s%d" % (i % 3), b"m%d" % (i % 11)])
        else:
            cmds.append([b"hset", b"h%d" % (i % 2), b"f%d" % (i % 5),
                         b"w%d" % i])
    # a few deletes and removes so tombstones replay too
    cmds += [[b"del", b"reg0"], [b"srem", b"s0", b"m0"],
             [b"set", b"reg0", b"back"]]
    return cmds


async def _start(tmp, name, policy="always", **kw):
    node = Node(node_id=kw.pop("node_id", 1), alias=name,
                repl_log_cap=kw.pop("repl_log_cap", 1_024_000))
    return await start_node(node, host="127.0.0.1", port=0,
                            work_dir=os.path.join(str(tmp), name),
                            aof=True, aof_fsync=policy,
                            aof_dir=os.path.join(str(tmp), name, "aof"),
                            **FAST, **kw)


async def _drain_gc(app) -> None:
    """Collect every pending tombstone so canonical exports compare
    GC-invariantly (replicas legally collect at different times — the
    same fixpoint rule certify_state applies)."""
    node = app.node
    for _ in range(64):
        if node.serve_plane is not None:
            await node.serve_plane.gc(node.gc_horizon())
            await asyncio.sleep(0)
        else:
            node.gc()
            if not node.ks.garbage:
                return
            await asyncio.sleep(0)


async def _canon(app):
    await _drain_gc(app)
    if app.node.serve_plane is not None:
        return await app.serve_plane.canonical()
    return app.node.canonical()


# ------------------------------------------------------- replay differential


def test_replay_differential_and_digest(tmp_path):
    """A recovered node == a never-crashed reference fed the same
    stream: canonical export AND full-state digest, byte-identical.
    Also pins the recovery gauges and the Durability INFO section."""
    async def main():
        app = await _start(tmp_path, "a")
        cmds = _workload_cmds()
        await _pipelined(app.advertised_addr, cmds)
        canon = await _canon(app)
        dig = full_state_digest(app.node.ks)
        await app.close()

        # reference node: same stream, never crashed
        ref = Node(node_id=1, alias="ref")
        rapp = await start_node(ref, host="127.0.0.1", port=0,
                                work_dir=str(tmp_path / "ref"), **FAST)
        await _pipelined(rapp.advertised_addr, cmds)

        app2 = await _start(tmp_path, "a")
        try:
            assert app2.node.stats.extra["aof_recovery_source"] == \
                "log-only"
            assert app2.node.stats.extra["aof_recovered_ops"] == len(cmds)
            assert (await _canon(app2)) == canon
            assert full_state_digest(app2.node.ks) == dig
            # LWW winners equal the reference's (timestamps differ per
            # node run, so compare VALUES, not stamps)
            rcanon = ref.canonical()
            assert set(rcanon) == set(canon)
            # INFO section present and sane
            c = await Client().connect(app2.advertised_addr)
            info = (await c.cmd("info", "durability")).val.decode()
            await c.close()
            assert "aof_enabled:1" in info
            assert "aof_recovery_source:log-only" in info
            assert "aof_tail_truncated:0" in info
        finally:
            await app2.close()
            await rapp.close()
    asyncio.run(main())


def test_replayed_node_reconverges_with_reference_peer(tmp_path):
    """End-to-end: crash + AOF recovery, then the recovered node joins
    a never-crashed peer and both land on the same canonical."""
    async def main():
        a = await _start(tmp_path, "a", node_id=1)
        b = await _start(tmp_path, "b", node_id=2, policy="everysec")
        c = await Client().connect(a.advertised_addr)
        await c.cmd("meet", b.advertised_addr)
        await c.close()
        await _pipelined(a.advertised_addr, _workload_cmds(80))
        await _pipelined(b.advertised_addr, _workload_cmds(40))
        deadline = asyncio.get_running_loop().time() + 20
        while a.node.canonical() != b.node.canonical():
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.1)
        canon = a.node.canonical()
        await a.close()
        a2 = await _start(tmp_path, "a", node_id=1)
        try:
            deadline = asyncio.get_running_loop().time() + 20
            while a2.node.canonical() != canon or \
                    b.node.canonical() != canon:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.1)
            # the recovered node's log replayed BOTH its own serve runs
            # (batch records) and b's spliced intake
            assert a2.node.stats.extra["aof_recovered_ops"] > 0
        finally:
            await a2.close()
            await b.close()
    asyncio.run(main())


# ------------------------------------------------------------ torn-tail fuzz


def _build_small_log(tmp_path) -> tuple:
    """A small single-segment log with mixed record types; returns
    (segment path, records, canonical, digest) of a full replay."""
    async def main():
        app = await _start(tmp_path, "fz")
        await _pipelined(app.advertised_addr, _workload_cmds(24))
        lg = app.node.oplog
        path = lg.seg_path(lg.dir, lg.generation, 0)
        canon = app.node.canonical()
        await app.close()
        records, valid, total = scan_segment(path)
        assert valid == total
        return path, records, canon
    return asyncio.run(main())


def _recover_fresh(aof_dir: str):
    node = Node(node_id=1, alias="fz")
    info = OL.recover(node, aof_dir)
    return node, info


def test_torn_tail_fuzz_every_offset(tmp_path):
    """Truncate the log at EVERY byte offset: recovery always lands on
    a valid record prefix (never crashes, never replays a corrupt
    record), and the prefix grows monotonically with the offset."""
    path, records, _canon = _build_small_log(tmp_path)
    data = open(path, "rb").read()
    aof_dir = os.path.dirname(path)
    prev_ops = 0
    last_full = -1
    for cut in range(len(MAGIC), len(data) + 1):
        open(path, "wb").write(data[:cut])
        node, info = _recover_fresh(aof_dir)
        got = info.frames + info.batch_frames
        if cut == len(data):
            assert info.tail_truncated == 0 and got >= prev_ops
        else:
            assert info.tail_truncated in (0, 1)
        assert got >= last_full  # prefixes only ever grow
        last_full = max(last_full, got)
        prev_ops = got
    # restore the intact file for the bit-flip sweep
    open(path, "wb").write(data)


def test_torn_tail_truncation_boundaries(tmp_path):
    """The tier-1 compact twin of the full sweep: every truncation
    offset across the LAST THREE record boundaries, plus the header
    edge cases."""
    path, records, canon = _build_small_log(tmp_path)
    data = open(path, "rb").read()
    aof_dir = os.path.dirname(path)
    # find the byte offsets of the last three record starts
    starts = []
    pos = len(MAGIC)
    while pos + 8 <= len(data):
        ln = int.from_bytes(data[pos:pos + 4], "little")
        starts.append(pos)
        pos += 8 + ln
    assert pos == len(data)
    full_ops = None
    boundaries = set(starts) | {len(data)}
    for cut in range(starts[-3], len(data) + 1):
        open(path, "wb").write(data[:cut])
        node, info = _recover_fresh(aof_dir)
        got = info.frames + info.batch_frames
        if cut == len(data):
            assert info.tail_truncated == 0
            full_ops = got
        elif cut in boundaries:
            # an exact record boundary is a VALID prefix — nothing torn
            assert info.tail_truncated == 0
        else:
            # a partial tail truncates loudly and the file shrinks to
            # the valid prefix ON DISK (the next boot is clean)
            assert info.tail_truncated == 1
            assert os.path.getsize(path) <= cut
            node2, info2 = _recover_fresh(aof_dir)
            assert info2.tail_truncated == 0
            assert info2.frames + info2.batch_frames == got
    assert full_ops is not None
    # a clipped HEADER is unreadable (not torn): quarantined, loudly
    open(path, "wb").write(data[:4])
    node, info = _recover_fresh(aof_dir)
    assert info.quarantined == 1
    assert not os.path.exists(path)
    assert os.path.exists(path + ".corrupt")


@pytest.mark.slow  # ~6s: every (offset, bit) pair spins a recovery;
#                    the boundary-targeted compact twin stays tier-1
def test_bit_flip_sweep_never_replays_garbage(tmp_path):
    """Flip every bit of the log body: recovery must stop at (or
    before) the flipped record — never crash, never apply a record
    whose bytes changed."""
    path, records, _canon = _build_small_log(tmp_path)
    data = bytearray(open(path, "rb").read())
    aof_dir = os.path.dirname(path)
    intact = bytes(data)
    for off in range(len(MAGIC), len(data)):
        for bit in range(8):
            data[off] ^= 1 << bit
            open(path, "wb").write(data)
            node, info = _recover_fresh(aof_dir)
            assert info.frames + info.batch_frames <= len(records) * 600
            data[off] ^= 1 << bit
    open(path, "wb").write(intact)


def test_bit_flip_boundaries_compact(tmp_path):
    """Tier-1 twin: flip one bit in each region of the LAST record
    (length field, crc field, type byte, payload) — recovery lands on
    the prefix BEFORE it each time, and the flipped record's ops are
    never applied."""
    path, records, canon = _build_small_log(tmp_path)
    data = bytearray(open(path, "rb").read())
    aof_dir = os.path.dirname(path)
    from constdb_tpu.persist.oplog import REC_WMARK
    starts = []
    pos = len(MAGIC)
    while pos + 8 <= len(data):
        starts.append((pos, data[pos + 8]))
        pos += 8 + int.from_bytes(data[pos:pos + 4], "little")
    # the last OP-carrying record (a trailing WMARK flip changes no
    # replayed-op count; its own decode-failure path is separate)
    last, end = None, len(data)
    for p0, rtype in reversed(starts):
        if rtype != REC_WMARK:
            last = p0
            break
        end = p0
    assert last is not None
    node_full, info_full = _recover_fresh(aof_dir)
    full_ops = info_full.frames + info_full.batch_frames
    for off in (last, last + 4, last + 8, last + 9,
                (last + 8 + end) // 2, end - 1):
        data[off] ^= 0x10
        open(path, "wb").write(data)
        node, info = _recover_fresh(aof_dir)
        got = info.frames + info.batch_frames
        assert got < full_ops, f"flipped record replayed (off {off})"
        data[off] ^= 0x10
    open(path, "wb").write(data)


# ----------------------------------------------------- watermark cut law


def test_recovered_watermarks_never_claim_beyond_cut(tmp_path):
    """The adopt-then-skip regression pin: watermark records appended
    to the log are durable-capped AND positioned after the frames they
    cover, so however the tail tears, the recovered uuid_he_sent never
    exceeds the newest intake frame of that origin actually replayed.
    (A higher claim would make the peer skip redelivery of frames the
    recovered state lacks — silent divergence forever.)"""
    async def main():
        a = await _start(tmp_path, "a", node_id=1)
        b = await _start(tmp_path, "b", node_id=2)
        ca = await Client().connect(a.advertised_addr)
        await ca.cmd("meet", b.advertised_addr)
        await ca.close()
        # writes on b stream into a; wait until a landed them
        await _pipelined(b.advertised_addr, _workload_cmds(60))
        deadline = asyncio.get_running_loop().time() + 20
        while a.node.canonical() != b.node.canonical():
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.1)
        # force a WMARK record + group commit, then MORE intake that
        # stays unsynced in a's log
        await a.node.oplog.cron(a)
        lg = a.node.oplog
        path = lg.seg_path(lg.dir, lg.generation, 0)
        synced = lg.synced_sizes[0]
        await _pipelined(b.advertised_addr,
                         [[b"set", b"late%d" % i, b"x"]
                          for i in range(40)])
        deadline = asyncio.get_running_loop().time() + 20
        while a.node.canonical() != b.node.canonical():
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.1)
        live_wm = a.node.replicas.get(b.advertised_addr).uuid_he_sent
        # kill -9 with a torn tail: clip a's log inside the unsynced
        # suffix (never below the last group commit)
        lg._drain_all()
        size = os.path.getsize(path)
        lg._closed = True
        await a.close()
        if size > synced:
            with open(path, "r+b") as f:
                f.truncate(synced + (size - synced) // 2)
        a2 = await _start(tmp_path, "a", node_id=1)
        try:
            m = a2.node.replicas.get(b.advertised_addr)
            assert m is not None
            # the recovered claim never exceeds what the log replayed
            # of b's stream — and never exceeds the live pre-crash one
            assert m.uuid_he_sent <= live_wm
            assert m.uuid_he_sent <= a2.node.hlc.current
            # and b redelivers the clipped window: both converge again
            deadline = asyncio.get_running_loop().time() + 20
            while a2.node.canonical() != b.node.canonical():
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.1)
        finally:
            await a2.close()
            await b.close()
    asyncio.run(main())


# ----------------------------------------------------------- fsync gating


def test_always_policy_gates_emission_and_acks(tmp_path):
    """Emit-only-durable: with appends pending (no fsync yet), the
    repl log's floor hides them from run_after and cap_ack withholds
    the intake watermark; a group commit releases both."""
    async def main():
        app = await _start(tmp_path, "a")
        node = app.node
        lg = node.oplog
        # append a local op WITHOUT the ack barrier (replicate_cmd path)
        uuid = node.hlc.tick(True)
        node.ks.touch()
        node.replicate_cmd(uuid, b"set", [Bulk(b"k"), Bulk(b"v")])
        assert lg.durable_floor() == uuid
        assert node.repl_log.run_after(0, 16) == []
        assert node.repl_log.next_after(0) is None
        # intake cap: a pending intake record withholds the ack
        lg.append_frame(99, uuid + 5, b"set", [Bulk(b"x"), Bulk(b"y")])
        assert lg.cap_ack(99, uuid + 10) == uuid + 4
        assert lg.cap_coverage(uuid + 10) == uuid + 4
        await lg.ack_barrier()
        assert lg.durable_floor() is None
        assert len(node.repl_log.run_after(0, 16)) == 1
        assert lg.cap_ack(99, uuid + 10) == uuid + 10
        await app.close()
    asyncio.run(main())


# ------------------------------------------------------------- compaction


def test_cap_ack_cached_min_tracks_deque(tmp_path):
    """cap_ack/cap_coverage are O(1) per ack-loop wake via a cached
    per-origin minimum; the cache must agree with a full deque scan
    through out-of-order appends (reconnect redeliveries append BELOW
    the current min) and partial settles."""
    lg = OpLog(str(tmp_path / "aof"), fsync_policy="always")

    def scan_min(origin):
        d = lg._intake_pend.get(origin)
        return min(u for _s, u in d) if d else None

    lg._track_intake(7, 100)
    lg._track_intake(7, 104)
    lg._track_intake(7, 96)   # the redelivery-below-min shape
    lg._track_intake(9, 50)
    assert lg.cap_ack(7, 1000) == scan_min(7) - 1 == 95
    assert lg.cap_ack(9, 1000) == 49
    assert lg.cap_ack(5, 1000) == 1000          # no pending intake
    assert lg.cap_coverage(1000) == 49
    # settle the first two of origin 7 and all of origin 9: the cached
    # min must be REcomputed (96 released-order-wise sits behind 104)
    marks, _files, oldest = lg._capture()
    upto_partial = lg._intake_pend[7][1][0]     # seq of uuid 104
    lg._settle((upto_partial, marks[1], marks[2]), oldest)
    assert lg.cap_ack(7, 1000) == scan_min(7) - 1 == 95
    assert lg.cap_ack(9, 1000) == 49            # seq after the cut: kept
    # full settle clears both dicts in lockstep
    marks, _files, oldest = lg._capture()
    lg._settle(marks, oldest)
    assert not lg._intake_pend and not lg._intake_min
    assert lg.cap_ack(7, 1000) == 1000
    assert lg.cap_coverage(1000) == 1000
    lg.close()


def test_rewrite_compaction_roundtrip(tmp_path):
    """The rewrite cuts a base snapshot + fresh generation atomically;
    recovery from base+tail is byte-identical, old generations are
    gone, and the INFO gauge counts it."""
    async def main():
        app = await _start(tmp_path, "a")
        await _pipelined(app.advertised_addr, _workload_cmds(100))
        lg = app.node.oplog
        size_before = lg.size_bytes()
        assert size_before > 100
        lg.rewrite_min_bytes = 1
        lg.base_size = 1
        assert lg.rewrite_due()
        gen0 = lg.generation
        await lg.rewrite(app)
        assert lg.rewrites == 1
        assert lg.generation == gen0 + 1
        # regression: the rewrite must NOT double-register the buffer
        # gauge with the governor (arm()'s permanent source already
        # includes the rewrite working set) — a second equal entry
        # double-counted every oplog byte in used_memory during
        # compaction and could spuriously shed near maxmemory_soft
        assert app.node.governor.sources.count(lg.used_buffer_bytes) == 1
        assert lg.size_bytes() < size_before
        assert os.path.exists(
            lg.base_snapshot_path(lg.dir, lg.generation))
        assert not os.path.exists(lg.seg_path(lg.dir, gen0, 0))
        # post-rewrite writes land in the new generation and replay
        await _pipelined(app.advertised_addr,
                         [[b"set", b"post", b"rewrite"]])
        canon = app.node.canonical()
        await app.close()
        app2 = await _start(tmp_path, "a")
        try:
            assert app2.node.stats.extra["aof_recovery_source"] == \
                "aof-base-snapshot+log"
            assert app2.node.canonical() == canon
        finally:
            await app2.close()
    asyncio.run(main())


def test_bulk_sync_schedules_rewrite(tmp_path):
    """Out-of-log state (a received full sync) suppresses watermark
    records and re-bases the log via an immediate rewrite, after which
    a crash recovers the bulk-delivered state from the new base."""
    async def main():
        a = await _start(tmp_path, "a", node_id=1)
        # b holds pre-existing state a must receive OUT of the stream:
        # the tiny ring cap evicts b's ops, so a's resume-from-0 takes
        # the full/delta sync path (the out-of-log delivery class)
        b = await _start(tmp_path, "b", node_id=2, repl_log_cap=512)
        await _pipelined(b.advertised_addr, _workload_cmds(60))
        ca = await Client().connect(a.advertised_addr)
        await ca.cmd("meet", b.advertised_addr)
        await ca.close()
        deadline = asyncio.get_running_loop().time() + 20
        while a.node.canonical() != b.node.canonical():
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.1)
        lg = a.node.oplog
        # the full sync marked the log dirty; drive the cron rewrite
        deadline = asyncio.get_running_loop().time() + 20
        while lg.rewrites == 0:
            assert asyncio.get_running_loop().time() < deadline, \
                "bulk sync never triggered the re-basing rewrite"
            await asyncio.sleep(0.1)
        canon = a.node.canonical()
        await a.close()
        await b.close()
        a2 = await _start(tmp_path, "a", node_id=1)
        try:
            assert a2.node.canonical() == canon
        finally:
            await a2.close()
    asyncio.run(main())


# ------------------------------------------------- quarantine fallback


def test_corrupt_boot_snapshot_falls_back_to_aof(tmp_path):
    """The boot-quarantine satellite: a corrupt snapshot quarantines
    and recovery falls back to AOF-only replay (pre-AOF behavior was
    booting EMPTY); the oplog itself is quarantined only when its
    header is unreadable."""
    async def main():
        # build a node with BOTH a boot snapshot and an oplog
        work = str(tmp_path / "a")
        snap = os.path.join(work, "boot.snapshot")
        node = Node(node_id=1, alias="a")
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=work, snapshot_path=snap,
                               aof=True, aof_fsync="always",
                               aof_dir=os.path.join(work, "aof"), **FAST)
        await _pipelined(app.advertised_addr, _workload_cmds(50))
        canon = await _canon(app)
        from constdb_tpu.persist.snapshot import NodeMeta, dump_keyspace
        node.ensure_flushed()
        dump_keyspace(snap, node.ks,
                      NodeMeta(node_id=1, repl_last_uuid=0))
        await app.close()
        # corrupt the snapshot: flip a byte mid-file
        data = bytearray(open(snap, "rb").read())
        data[len(data) // 2] ^= 0xFF
        open(snap, "wb").write(data)
        node2 = Node(node_id=1, alias="a")
        app2 = await start_node(node2, host="127.0.0.1", port=0,
                                work_dir=work, snapshot_path=snap,
                                aof=True, aof_fsync="always",
                                aof_dir=os.path.join(work, "aof"),
                                **FAST)
        try:
            x = node2.stats.extra
            assert "boot_snapshot_quarantined" in x
            assert x["aof_recovery_source"] == "log-only"
            assert (await _canon(app2)) == canon
            assert os.path.exists(snap + ".corrupt")
        finally:
            await app2.close()
    asyncio.run(main())


# ----------------------------------------------------------- sharded node


def test_sharded_aof_roundtrip(tmp_path):
    """Per-shard segment files, merged by HLC order at replay: a
    2-shard node's recovery equals its pre-crash canonical."""
    async def main():
        node = Node(node_id=1, alias="sh")
        work = str(tmp_path / "sh")
        app = await start_node(node, host="127.0.0.1", port=0,
                               work_dir=work, serve_shards=2,
                               aof=True, aof_fsync="always",
                               aof_dir=os.path.join(work, "aof"), **FAST)
        await _pipelined(app.advertised_addr, _workload_cmds(80))
        canon = await _canon(app)
        lg = node.oplog
        assert lg.n_segments == 3  # 2 shards + the parent local segment
        seg_sizes = [os.path.getsize(lg.seg_path(lg.dir, lg.generation, s))
                     for s in range(2)]
        assert all(sz > len(MAGIC) for sz in seg_sizes), \
            "both shard segments must carry mirrored entries"
        await app.close()
        node2 = Node(node_id=1, alias="sh")
        app2 = await start_node(node2, host="127.0.0.1", port=0,
                                work_dir=work, serve_shards=2,
                                aof=True, aof_fsync="always",
                                aof_dir=os.path.join(work, "aof"),
                                **FAST)
        try:
            assert (await _canon(app2)) == canon
            assert node2.stats.extra["aof_recovery_source"] == "log-only"
        finally:
            await app2.close()
    asyncio.run(main())


# ------------------------------------------------------------ wipe fences


def test_wipe_truncates_log_and_fences_recovery(tmp_path):
    """A state wipe discards every record; a crash before the
    post-resync rewrite boots (near) empty with a fence instead of
    resurrecting pre-wipe state."""
    async def main():
        app = await _start(tmp_path, "a")
        node = app.node
        await _pipelined(app.advertised_addr, _workload_cmds(40))
        assert node.oplog.size_bytes() > len(MAGIC)
        fence_before = node.repl_log.last_uuid
        node.reset_for_full_resync()
        lg = node.oplog
        assert lg.size_bytes() <= len(MAGIC) + 64
        await app.close()
        app2 = await _start(tmp_path, "a")
        try:
            n2 = app2.node
            assert n2.ks.n_keys() == 0, "pre-wipe state resurrected"
            assert n2.repl_log.evicted_up_to >= fence_before
        finally:
            await app2.close()
    asyncio.run(main())


# ------------------------------------------------ builder-view equivalence


def test_serve_builder_wire_view_equals_from_scratch_encode(tmp_path):
    """The fast path (serializing the serve flush's builder through the
    chk-fixing _WireView) must be BYTE-identical to the from-scratch
    build_wire_batch over the run's repl-log entries — the pin that
    lets append_local_run skip the re-encode without the log's payload
    ever drifting from the wire protocol."""
    from constdb_tpu.replica.coalesce import BatchBuilder
    from constdb_tpu.server.commands import SERVE_ENCODERS

    async def main():
        app = await _start(tmp_path, "a")
        node = app.node
        captured = []
        orig = OL.OpLog.append_local_run

        def spy(self, entries, prev_uuid, seg=None, publish=True,
                builder=None):
            if builder is not None and len(entries) >= 2:
                fast = OL._encode_serve_builder(builder, prev_uuid,
                                                node.node_id)
                slow = OL._encode_run(entries, prev_uuid, node.node_id)
                captured.append((fast, slow))
            return orig(self, entries, prev_uuid, seg=seg,
                        publish=publish, builder=builder)

        OL.OpLog.append_local_run = spy
        try:
            await _pipelined(app.advertised_addr, _workload_cmds(120))
        finally:
            OL.OpLog.append_local_run = orig
            await app.close()
        assert captured, "no coalesced runs reached the op log"
        for fast, slow in captured:
            assert fast is not None and fast == slow
    asyncio.run(main())


# ------------------------------------------------------- fast restart (r20)


def test_bulk_replay_matches_serial_reference(tmp_path):
    """The bulk merge-round landing strategy (CONSTDB_RECOVER_BULK, the
    default) is byte-identical to the per-record serial reference:
    canonical export AND full-state digest, over a workload mixing
    scalar sets/dels, counter steps, and element adds/removes — the
    key-delete-rule hazard the round's flush discipline exists for."""
    async def main():
        app = await _start(tmp_path, "a")
        await _pipelined(app.advertised_addr, _workload_cmds(300))
        await app.close()
        aof_dir = app.aof_dir
        serial = Node(node_id=1, alias="s")
        si = OL.recover(serial, aof_dir, bulk=False)
        bulk = Node(node_id=1, alias="b")
        bi = OL.recover(bulk, aof_dir, bulk=True)
        assert si.mode == "serial" and bi.mode == "bulk"
        assert bi.merge_rounds >= 1 and si.merge_rounds == 0
        assert si.frames + si.batch_frames == bi.frames + bi.batch_frames
        assert serial.canonical() == bulk.canonical()
        assert full_state_digest(serial.ks) == full_state_digest(bulk.ks)
    asyncio.run(main())


def test_native_scan_shapes_and_raw_replay(tmp_path):
    """The native AOF scanner (cst_ext.aof_scan) is shape- and
    content-equivalent to the pure-Python scan + decode across its
    three modes: plain (2-tuples), fused (frame 5-tuples with RESP
    message args), and raw (frame 5-tuples with plain-bytes args, flat
    all-bulk commands only).  Raw-mode bulk recovery — which feeds the
    columnar encoders unwrapped bytes and re-wraps for barrier applies
    — must stay byte-identical to the serial reference."""
    from constdb_tpu.persist.oplog import (REC_FRAME, _decode_frame,
                                           _frame_ctx)
    from constdb_tpu.resp.message import Int

    aof_dir = os.path.join(str(tmp_path), "aof")
    node = Node(node_id=1, alias="w")
    lg = OpLog(aof_dir, fsync_policy="no", node=node)
    node.oplog = lg
    cmds = _workload_cmds(90)
    # barrier op (expireat is non-encodable) + an integer-typed arg
    # frame, which raw mode must hand to the object decoder instead
    cmds.insert(40, [b"expireat", b"reg1", b"99999999999"])
    for parts in cmds:
        node.execute(Arr([Bulk(p) for p in parts]))
    node.execute(Arr([Bulk(b"set"), Bulk(b"intarg"), Int(7)]))
    lg.close()
    node.oplog = None

    path = OpLog.seg_path(aof_dir, 0, 0)
    classes = _frame_ctx()[1:]
    plain, valid, total = scan_segment(path)
    assert valid == total
    assert all(len(r) == 2 for r in plain)
    fused, fvalid, _ = scan_segment(path, classes)
    raw, rvalid, _ = scan_segment(path, classes, raw=True)
    assert fvalid == rvalid == valid
    assert len(plain) == len(fused) == len(raw)
    saw_bytes = saw_obj_fallback = 0
    for p, f, r in zip(plain, fused, raw):
        assert p[0] == f[0] == r[0]
        if p[0] != REC_FRAME:
            assert p == f == r
            continue
        origin, uuid, name, args = _decode_frame(p[1])
        for rec in (f, r):
            if len(rec) == 2:   # scanner degraded: python decode agrees
                rec = (REC_FRAME, *_decode_frame(rec[1]))
            assert rec[1] == origin and rec[2] == uuid
            assert rec[3] == name
            vals = [a if type(a) is bytes else a.val for a in rec[4]]
            assert vals == [getattr(a, "val", a) for a in args]
        if len(r) == 5 and r[4] and type(r[4][0]) is bytes:
            saw_bytes += 1
        elif len(r) == 5:
            saw_obj_fallback += 1
    assert saw_bytes > 50          # raw fast path took the flat frames
    assert saw_obj_fallback >= 1   # the Int-arg frame fell back cleanly

    serial = Node(node_id=1, alias="s")
    OL.recover(serial, aof_dir, bulk=False)
    bulk = Node(node_id=1, alias="b")
    bi = OL.recover(bulk, aof_dir, bulk=True)
    assert bi.merge_rounds >= 1
    assert serial.canonical() == bulk.canonical()
    assert full_state_digest(serial.ks) == full_state_digest(bulk.ks)


def test_checkpoint_cuts_restart_tail(tmp_path):
    """CONSTDB_CHECKPOINT_SECS: the time-triggered cut re-bases the log
    behind a consistent snapshot, so the next restart replays only the
    post-checkpoint tail — asserted via the recovery gauges and the
    INFO Recovery section."""
    async def main():
        app = await _start(tmp_path, "a", checkpoint_secs=0.05,
                           checkpoint_min_mb=0)
        cmds = _workload_cmds(200)
        await _pipelined(app.advertised_addr, cmds)
        lg = app.node.oplog
        deadline = asyncio.get_running_loop().time() + 10
        while not lg.rewrites:
            assert asyncio.get_running_loop().time() < deadline, \
                "checkpoint cron never cut"
            await asyncio.sleep(0.05)
        lg.checkpoint_secs = 0.0  # freeze further cuts for determinism
        assert lg.checkpoint_uuid > 0
        await _pipelined(app.advertised_addr, [[b"set", b"tail", b"1"]])
        canon = await _canon(app)
        await app.close()
        app2 = await _start(tmp_path, "a")
        try:
            x = app2.node.stats.extra
            assert x["aof_recovery_source"] == "aof-base-snapshot+log"
            # tail-only replay: the pre-checkpoint workload came from
            # the snapshot, not the log
            assert 0 < x["aof_recovered_ops"] < len(cmds)
            assert (await _canon(app2)) == canon
            assert x["recovery_wall_s"] >= 0
            c = await Client().connect(app2.advertised_addr)
            info = (await c.cmd("info", "recovery")).val.decode()
            await c.close()
            assert "recovery_mode:bulk" in info
            assert "recovery_wall_s:" in info
            lines = dict(ln.split(":", 1) for ln in info.splitlines()
                         if ":" in ln)
            assert int(lines["checkpoint_last_uuid"]) > 0
            assert float(lines["checkpoint_age_s"]) >= 0
        finally:
            await app2.close()
    asyncio.run(main())


def test_restore_to_point_in_time(tmp_path):
    """--restore-to <uuid>: replay stops at the target, later acked
    writes are gone, and the log re-bases immediately so the dropped
    tail can never resurrect on a later restart."""
    from constdb_tpu.resp.message import Nil

    async def main():
        app = await _start(tmp_path, "a")
        await _pipelined(app.advertised_addr, [[b"set", b"early", b"1"]])
        cut = app.node.repl_log.last_uuid
        await _pipelined(app.advertised_addr, [[b"set", b"late", b"1"]])
        await app.close()
        app2 = await _start(tmp_path, "a", restore_to=cut)
        try:
            x = app2.node.stats.extra
            assert x["recovery_restore_to"] == cut
            assert x["recovery_restore_skipped"] >= 1
            # the immediate re-base cut a fresh generation
            assert app2.node.oplog.rewrites == 1
            assert not app2.node.oplog._rewrite_asap
            c = await Client().connect(app2.advertised_addr)
            assert (await c.cmd("get", "early")).val == b"1"
            assert (await c.cmd("get", "late")) == Nil()
            await c.close()
        finally:
            await app2.close()
        # a PLAIN restart after the restore must not resurrect the tail
        app3 = await _start(tmp_path, "a")
        try:
            c = await Client().connect(app3.advertised_addr)
            assert (await c.cmd("get", "early")).val == b"1"
            assert (await c.cmd("get", "late")) == Nil()
            await c.close()
        finally:
            await app3.close()
    asyncio.run(main())


def test_sharded_parallel_recovery_gauges(tmp_path):
    """A 2-shard node's segments replay through concurrent per-segment
    tasks (CONSTDB_RECOVER_SHARDS=0 auto): the gauges record the
    concurrency and the recovered state still equals the pre-crash
    canonical."""
    async def main():
        node = Node(node_id=1, alias="sh")
        work = str(tmp_path / "sh")
        kw = dict(work_dir=work, serve_shards=2, aof=True,
                  aof_fsync="always",
                  aof_dir=os.path.join(work, "aof"), **FAST)
        app = await start_node(node, host="127.0.0.1", port=0, **kw)
        await _pipelined(app.advertised_addr, _workload_cmds(120))
        canon = await _canon(app)
        await app.close()
        node2 = Node(node_id=1, alias="sh")
        app2 = await start_node(node2, host="127.0.0.1", port=0, **kw)
        try:
            x = node2.stats.extra
            assert x["recovery_shards"] >= 2
            assert x["recovery_mode"].startswith("bulk+shards")
            assert x["recovery_wall_s"] >= 0
            assert (await _canon(app2)) == canon
        finally:
            await app2.close()
    asyncio.run(main())
