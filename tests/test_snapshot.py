"""Snapshot format: round-trip, chunking, checksum, boot-time restore.

Mirrors the reference's snapshot unit test intent (reference
src/snapshot.rs:335-392 round-trips entries through a temp file and asserts
the checksum) at the whole-file level, plus the boot-restore capability the
reference lacks.
"""

import io
import os

import numpy as np
import pytest

from constdb_tpu.engine.base import batch_from_keyspace
from constdb_tpu.errors import InvalidSnapshot, InvalidSnapshotChecksum
from constdb_tpu.persist.snapshot import (NodeMeta, ReplicaRecord,
                                          SnapshotLoader, SnapshotWriter,
                                          dump_keyspace, iter_keyspace_chunks,
                                          load_snapshot)
from constdb_tpu.server.node import Node
from constdb_tpu.resp.message import Bulk


def _cmd(node, *parts):
    return node.execute([Bulk(p if isinstance(p, bytes) else str(p).encode())
                         for p in parts])


def populated_node(n_keys: int = 200, seed: int = 3) -> Node:
    rng = np.random.default_rng(seed)
    node = Node(node_id=1)
    for i in range(n_keys):
        kind = i % 4
        key = b"key:%d" % i
        if kind == 0:
            for _ in range(int(rng.integers(1, 4))):
                _cmd(node, b"incr", key)
        elif kind == 1:
            _cmd(node, b"set", key, b"v%d" % int(rng.integers(0, 1000)))
        elif kind == 2:
            _cmd(node, b"sadd", key, b"a", b"b", b"m%d" % int(rng.integers(0, 10)))
            if rng.random() < 0.3:
                _cmd(node, b"srem", key, b"a")
        else:
            _cmd(node, b"hset", key, b"f1", b"x", b"f2", b"y%d" % i)
            if rng.random() < 0.3:
                _cmd(node, b"hdel", key, b"f1")
        if rng.random() < 0.1:
            _cmd(node, b"del", key)
    return node


def test_roundtrip_file(tmp_path):
    node = populated_node()
    meta = NodeMeta(node_id=1, alias="n1", addr="127.0.0.1:7001",
                    repl_last_uuid=node.hlc.current)
    reps = [ReplicaRecord("127.0.0.1:7002", 2, "n2", add_t=5, uuid_he_sent=17)]
    path = str(tmp_path / "db.snapshot")
    dump_keyspace(path, node.ks, meta, reps)

    ks2 = Node(node_id=1).ks
    meta2, reps2 = load_snapshot(path, ks2)
    assert meta2.node_id == meta.node_id
    assert meta2.alias == "n1"
    assert meta2.repl_last_uuid == meta.repl_last_uuid
    assert reps2 == reps
    assert ks2.canonical() == node.ks.canonical()
    assert ks2.key_deletes == node.ks.key_deletes


def test_chunked_equals_whole(tmp_path):
    node = populated_node(300)
    chunks = list(iter_keyspace_chunks(node.ks, chunk_keys=37))
    assert len(chunks) > 1
    assert sum(c.n_keys for c in chunks) == node.ks.n_keys()

    path = str(tmp_path / "db.snapshot")
    dump_keyspace(path, node.ks, NodeMeta(node_id=1), chunk_keys=37)
    ks2 = Node(node_id=1).ks
    load_snapshot(path, ks2)
    assert ks2.canonical() == node.ks.canonical()


def test_tpu_engine_load(tmp_path):
    node = populated_node(150)
    path = str(tmp_path / "db.snapshot")
    dump_keyspace(path, node.ks, NodeMeta(node_id=1), chunk_keys=64)
    from constdb_tpu.engine.tpu import TpuMergeEngine
    ks2 = Node(node_id=1).ks
    load_snapshot(path, ks2, engine=TpuMergeEngine())
    assert ks2.canonical() == node.ks.canonical()


def test_checksum_detects_corruption(tmp_path):
    node = populated_node(50)
    path = str(tmp_path / "db.snapshot")
    dump_keyspace(path, node.ks, NodeMeta(node_id=1))
    raw = bytearray(open(path, "rb").read())
    # flip one bit inside the body (past the header, before the digest)
    raw[len(raw) // 2] ^= 0x40
    with open(path, "wb") as f:
        f.write(raw)
    with pytest.raises((InvalidSnapshotChecksum, InvalidSnapshot, Exception)):
        load_snapshot(path, Node(node_id=1).ks)


def test_truncated_file_rejected(tmp_path):
    node = populated_node(50)
    path = str(tmp_path / "db.snapshot")
    dump_keyspace(path, node.ks, NodeMeta(node_id=1))
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[: len(raw) - 5])
    with pytest.raises(InvalidSnapshot):
        load_snapshot(path, Node(node_id=1).ks)


def test_bad_magic():
    with pytest.raises(InvalidSnapshot):
        SnapshotLoader(io.BytesIO(b"NOTASNAPSHOT"))


def test_none_values_roundtrip(tmp_path):
    """None el_val (set members) and None reg_val survive the bytes-column
    encoding; empty bytes stay distinct from None."""
    node = Node(node_id=1)
    _cmd(node, b"sadd", b"s", b"")          # empty member
    _cmd(node, b"hset", b"h", b"f", b"")    # empty value
    _cmd(node, b"set", b"r", b"")           # empty register
    path = "/tmp/none_rt.snapshot"
    dump_keyspace(path, node.ks, NodeMeta(node_id=1))
    ks2 = Node(node_id=1).ks
    load_snapshot(path, ks2)
    assert ks2.canonical() == node.ks.canonical()
    os.unlink(path)


def test_uncompressed_mode(tmp_path):
    node = populated_node(40)
    path = str(tmp_path / "db.snapshot")
    dump_keyspace(path, node.ks, NodeMeta(node_id=1), compress_level=0)
    ks2 = Node(node_id=1).ks
    load_snapshot(path, ks2)
    assert ks2.canonical() == node.ks.canonical()


def test_writer_to_stream():
    """The writer targets any binary file object (socket send path)."""
    node = populated_node(30)
    buf = io.BytesIO()
    w = SnapshotWriter(buf)
    w.write_node(NodeMeta(node_id=9))
    for c in iter_keyspace_chunks(node.ks, chunk_keys=8):
        w.write_chunk(c)
    w.finish()
    buf.seek(0)
    kinds = [k for k, _ in SnapshotLoader(buf)]
    assert kinds[0] == "node"
    assert all(k == "batch" for k in kinds[1:])
