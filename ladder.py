#!/usr/bin/env python
"""Config-ladder benchmark: BASELINE.json configs 1-4, one family per rung.

Each rung isolates one CRDT family's merge path, so a regression in one
family cannot hide inside the mixed 10M aggregate (bench.py):

  1. pncounter — 100k INCR PNCounter keys, 2 replicas (cnt val/uuid path)
  2. lwwreg    — 1M LWWRegister keys, 4 replicas, conflicting timestamps
                 (reg rv_t/rv_node + win-value path)
  3. orset     — 1M ORSet keys x 4 members, 8 replicas, add-win union +
                 ~10% tombstones (el sparse-del path)
  4. lwwhash   — 500k LWW-Hash keys x 32 fields, 8 replicas (el
                 value-heavy src path)

For each rung: CPU-engine rate (capped key count — the per-row engine is
scale-flat, bench.py README note), device-engine rate at FULL size, and
the same subsample oracle verification as bench.py (verified flag).

Writes LADDER_r05.json style output:
    python ladder.py [--out LADDER.json] [--cpu-keys 100000]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench import _uuids, chunk_batches, time_engine, verify_store  # noqa: E402
from constdb_tpu.crdt import semantics as S  # noqa: E402
from constdb_tpu.engine.base import ColumnarBatch  # noqa: E402
from constdb_tpu.engine.cpu import CpuMergeEngine  # noqa: E402

_I64 = np.int64


def _key_plane(b: ColumnarBatch, keys, enc, rng):
    """`keys`/`enc` are SHARED across the replica batches — snapshots of
    one keyspace really do carry identical key planes, and sharing the
    objects lets the engine's shape memo resolve them once."""
    n = len(keys)
    b.rows_unique_per_slot = True
    b.keys = keys
    b.key_enc = enc
    b.key_ct = _uuids(rng, n)
    b.key_mt = b.key_ct.copy()
    b.key_dt = np.zeros(n, dtype=_I64)
    b.key_expire = np.zeros(n, dtype=_I64)
    b.reg_val = [None] * n
    b.reg_t = np.zeros(n, dtype=_I64)
    b.reg_node = np.zeros(n, dtype=_I64)
    return n


def gen_pncounter(n_keys, n_rep, seed=11):
    """Config 1: every replica carries its own (key, node) counter slot —
    the post-INCR snapshot state of a 100k-key PN-counter keyspace."""
    rng = np.random.default_rng(seed)
    keys = [b"cnt%08d" % i for i in range(n_keys)]
    enc = np.full(n_keys, S.ENC_COUNTER, dtype=np.int8)
    out = []
    for r in range(n_rep):
        b = ColumnarBatch()
        _key_plane(b, keys, enc, rng)
        b.cnt_ki = np.arange(n_keys, dtype=_I64)
        b.cnt_node = np.full(n_keys, r + 1, dtype=_I64)
        b.cnt_val = rng.integers(-10_000, 10_000, n_keys).astype(_I64)
        b.cnt_uuid = _uuids(rng, n_keys)
        b.cnt_base = np.zeros(n_keys, dtype=_I64)
        b.cnt_base_t = np.full(n_keys, S.NEUTRAL_T, dtype=_I64)
        out.append(b)
    return out


def gen_lwwreg(n_keys, n_rep, seed=12):
    """Config 2: same keys on every replica with CONFLICTING timestamps —
    every slot resolves through the lexicographic (t, node) LWW."""
    rng = np.random.default_rng(seed)
    keys = [b"reg%08d" % i for i in range(n_keys)]
    enc = np.full(n_keys, S.ENC_BYTES, dtype=np.int8)
    pool = [b"val-%05d" % i for i in range(2048)]
    out = []
    for r in range(n_rep):
        b = ColumnarBatch()
        _key_plane(b, keys, enc, rng)
        idx = rng.integers(0, len(pool), n_keys)
        b.reg_val = [pool[i] for i in idx]
        b.reg_t = _uuids(rng, n_keys)
        b.reg_node = np.full(n_keys, r + 1, dtype=_I64)
        out.append(b)
    return out


def gen_orset(n_keys, n_rep, seed=13, members_per_set=4):
    """Config 3: add-win union with ~10% tombstones (sparse del side)."""
    rng = np.random.default_rng(seed)
    keys = [b"set%08d" % i for i in range(n_keys)]
    member_pool = [b"m%04d" % i for i in range(4096)]
    ki = np.repeat(np.arange(n_keys, dtype=_I64), members_per_set)
    midx = rng.integers(0, len(member_pool), len(ki))
    combo = (ki << 32) | midx
    _, first = np.unique(combo, return_index=True)
    first.sort()
    ki, midx = ki[first], midx[first]
    members = [member_pool[i] for i in midx]
    vals = [None] * len(ki)
    enc = np.full(n_keys, S.ENC_SET, dtype=np.int8)
    out = []
    for r in range(n_rep):
        b = ColumnarBatch()
        _key_plane(b, keys, enc, rng)
        b.el_ki = ki
        b.el_member = members
        b.el_val = vals
        b.el_add_t = _uuids(rng, len(ki))
        b.el_add_node = np.full(len(ki), r + 1, dtype=_I64)
        b.el_del_t = np.where(rng.random(len(ki)) < 0.1,
                              _uuids(rng, len(ki)), 0).astype(_I64)
        out.append(b)
    return out


def gen_lwwhash(n_keys, n_rep, seed=14, fields=32):
    """Config 4: per-field LWW with VALUES — the el src/win-value path at
    32 fields per key."""
    rng = np.random.default_rng(seed)
    keys = [b"h%08d" % i for i in range(n_keys)]
    field_names = [b"f%02d" % i for i in range(fields)]
    val_pool = [b"hv-%05d" % i for i in range(4096)]
    ki = np.repeat(np.arange(n_keys, dtype=_I64), fields)
    members = field_names * n_keys
    enc = np.full(n_keys, S.ENC_DICT, dtype=np.int8)
    out = []
    for r in range(n_rep):
        b = ColumnarBatch()
        _key_plane(b, keys, enc, rng)
        b.el_ki = ki
        b.el_member = members
        vidx = rng.integers(0, len(val_pool), len(ki))
        b.el_val = [val_pool[i] for i in vidx]
        b.el_add_t = _uuids(rng, len(ki))
        b.el_add_node = np.full(len(ki), r + 1, dtype=_I64)
        b.el_del_t = np.zeros(len(ki), dtype=_I64)
        out.append(b)
    return out


CONFIGS = [
    ("pncounter", gen_pncounter, 100_000, 2),
    ("lwwreg", gen_lwwreg, 1_000_000, 4),
    ("orset", gen_orset, 1_000_000, 8),
    ("lwwhash", gen_lwwhash, 500_000, 8),
]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="")
    ap.add_argument("--cpu-keys", type=int, default=100_000,
                    help="key cap for the pure-Python baseline run")
    ap.add_argument("--chunk", type=int, default=1 << 17)
    ns = ap.parse_args()

    from constdb_tpu.utils.backend import force_cpu_platform, probe_backend
    probe = probe_backend()
    if not probe.ok:
        print(f"[ladder] WARNING: no device backend ({probe.error}); "
              "XLA-on-CPU", file=sys.stderr)
        force_cpu_platform()
    from constdb_tpu.engine.tpu import TpuMergeEngine
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("CONSTDB_JAX_CACHE",
                                         "/tmp/constdb_jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception:
        pass
    backend = jax.default_backend()
    print(f"[ladder] backend: {backend} devices={jax.devices()}",
          file=sys.stderr)

    results = []
    for name, gen, n_keys, n_rep in CONFIGS:
        t0 = time.perf_counter()
        n_cpu = min(n_keys, ns.cpu_keys)
        cpu_chunks = chunk_batches(gen(n_cpu, n_rep), ns.chunk)
        cpu_t, _ = time_engine(CpuMergeEngine, cpu_chunks, repeats=1)
        cpu_rate = n_cpu / cpu_t

        batches = gen(n_keys, n_rep)
        chunks = chunk_batches(batches, ns.chunk)
        group = 4 * n_rep
        dev_t, store = time_engine(
            lambda: TpuMergeEngine(resident=True), chunks,
            repeats=1 if n_keys >= 500_000 else 2, group=group)
        dev_rate = n_keys / dev_t
        ok, n_checked, n_diff = verify_store(store, batches, n_keys,
                                             target=50_000)
        row = {"config": name, "keys": n_keys, "replicas": n_rep,
               "cpu_keys": n_cpu, "cpu_keys_per_sec": round(cpu_rate, 1),
               "device_keys_per_sec": round(dev_rate, 1),
               "device_wall_s": round(dev_t, 2),
               "speedup": round(dev_rate / cpu_rate, 2),
               "verified": ok, "verify_keys": n_checked,
               "backend": backend}
        results.append(row)
        print(f"[ladder] {name}: cpu {cpu_rate:,.0f} k/s (at {n_cpu}), "
              f"device {dev_rate:,.0f} k/s ({dev_t:.2f}s), "
              f"verify={'OK' if ok else f'{n_diff} DIFFS'} "
              f"(total {time.perf_counter() - t0:.1f}s)", file=sys.stderr)
        if not ok:
            print(json.dumps({"error": f"{name} verification failed",
                              "results": results}))
            sys.exit(1)

    out = {"metric": "family_ladder_keys_per_sec", "backend": backend,
           "results": results}
    print(json.dumps(out))
    if ns.out:
        with open(ns.out, "w") as f:
            json.dump(out, f, indent=1)
        print(f"[ladder] wrote {ns.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
