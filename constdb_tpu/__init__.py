"""constdb-tpu: a TPU-native, Redis-protocol, master-master replicated CRDT store.

A brand-new framework with the capabilities of fxsjy/ConstDB (reference:
/root/reference — Rust/tokio).  Not a port: the bulk CRDT merge path
(snapshot ingest, replica catch-up) is a batched JAX/Pallas engine that treats
replica reconciliation as parallel max/union/sum reductions over columnar
(key_id, node_id, uuid, value) tensors, sharded over a `jax.sharding.Mesh`.
The serving plane is a columnar keyspace (numpy struct-of-arrays mirrored to
device) rather than per-key heap objects.

Layer map (mirrors SURVEY.md §1):
  utils/      core types: HLC uuids, varint, checksum, byte helpers  (L1)
  resp/       RESP wire protocol: incremental parser + encoder       (L2)
  crdt/       CRDT conflict-resolution semantics (pure, shared)      (L7)
  store/      columnar keyspace: counters/elements/registers, GC     (L7)
  engine/     MergeEngine boundary: CPU reference + batched JAX      (L7/TPU)
  ops/        JAX segment/scatter kernels, Pallas hot loops          (TPU)
  parallel/   mesh + shard_map sharded merge                         (TPU)
  snapshot/   columnar snapshot format, streaming writer/loader      (L8)
  server/     asyncio server core, command dispatch, repl log        (L3-L6)
  replica/    MEET/SYNC, puller/pusher state machines                (L9)
  stats/      metrics + INFO                                         (L10)
"""

__version__ = "0.1.0"
