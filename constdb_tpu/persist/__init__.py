"""Persistence: columnar snapshot format + background dump orchestration."""

from .snapshot import (NodeMeta, ReplicaRecord, SnapshotLoader, SnapshotWriter,
                       dump_keyspace, load_snapshot)

__all__ = ["NodeMeta", "ReplicaRecord", "SnapshotLoader", "SnapshotWriter",
           "dump_keyspace", "load_snapshot"]
