"""Chunked columnar snapshot format: writer, loader, dump/restore.

Capability parity with the reference's snapshot layer (reference
src/snapshot.rs:9-69 `SnapshotWriter` with its running checksum,
src/snapshot.rs:100-301 incremental validated loading, src/server.rs:183-250
dump orchestration), redesigned for the columnar keyspace: instead of one
varint record per key (the reference walks `DB::iter` one Object at a
time), the body is a sequence of CHUNK sections, each holding a
`ColumnarBatch` slice of the keyspace — numeric planes as raw
little-endian i64 columns (zlib-compressed), bytes planes as length-column
+ blob.  A loaded chunk goes straight into `MergeEngine.merge` without any
per-row Python work, which is what lets snapshot ingest ride the batched
TPU merge path (engine/tpu.py) instead of a 10M-iteration loop.

File layout (all multi-byte scalars big-endian varints per utils/varint.py,
bulk columns little-endian raw):

    magic   b"CSTPU1\\n\\x00" (8 bytes)
    alg     1 byte — checksum algorithm tag (utils/checksum.StreamChecksum)
    section*:
        kind    1 byte  (1=NODE, 2=REPLICAS, 3=BATCH)
        flag    1 byte  (0=raw payload, 1=zlib payload)
        length  uvarint (stored payload bytes)
        payload
    end     1 byte 0xFF
    digest  8 bytes big-endian — checksum of every byte above (magic
            through the end marker)

The checksum covers the whole stream, so a loader that streams chunks into
an engine learns of corruption only at the end marker — callers that merge
into a live store must treat `InvalidSnapshotChecksum` as "discard the
store" (load_snapshot targets fresh keyspaces: boot restore and full-sync
download both do).  Truncation anywhere raises `InvalidSnapshot`
immediately, exactly like the reference's short-read handling
(src/snapshot.rs:207-214).

Varint scalars use the zigzag encoding from utils/varint.py (well-defined
for negatives — the reference's encoder corrupts them, SURVEY.md §2.6).

Compressed container (round 17): a snapshot stream may be wrapped whole
in the chunked compression framing from utils/compressio.py
(`container_level` on the writer entry points).  The container is
magic-tagged (b"CSTPUZ1\\n" vs the plain b"CSTPU1\\n\\x00"), so
`SnapshotLoader` sniffs the first bytes and reads either transparently —
pre-PR plain files stay loadable, and every consumer (boot restore,
FULLSYNC/DELTASYNC spill apply, sharded ingest) inherits the support
for free.  Whole-stream compression beats the per-section zlib because
it folds CROSS-section redundancy (the columnar key/uuid planes repeat
heavily across chunks); container dumps therefore write their inner
sections raw (compress_level=0) rather than compressing twice.
"""

from __future__ import annotations

import os
import zlib
from dataclasses import dataclass
from typing import IO, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from ..engine.base import (ColumnarBatch, batch_from_keyspace,
                           has_values)
from ..errors import InvalidSnapshot, InvalidSnapshotChecksum
from ..utils.checksum import StreamChecksum
from ..utils.compressio import (CompressFormatError, DecompressReader,
                                is_compressed)
from ..utils.varint import VarintReader, write_uvarint

_I64 = np.int64

MAGIC = b"CSTPU1\n\x00"
SEC_NODE = 1
SEC_REPLICAS = 2
SEC_BATCH = 3
SEC_END = 0xFF

# a stored section larger than this is corruption, not data (guards the
# loader against allocating on a bit-flipped length field)
_MAX_SECTION = 1 << 31

_KIND_NAMES = {SEC_NODE: "node", SEC_REPLICAS: "replicas", SEC_BATCH: "batch"}


@dataclass
class NodeMeta:
    """NODE section: the dumping node's identity + replication watermark
    (reference src/snapshot.rs:45-49 writes uuid/addr ahead of the body)."""

    node_id: int = 0
    alias: str = ""
    addr: str = ""
    repl_last_uuid: int = 0


@dataclass
class ReplicaRecord:
    """One row of the REPLICAS section: membership LWW state + the pull
    watermarks a restored node resumes from (reference
    src/replica/replica.rs:131-147 ReplicaMeta, persisted subset)."""

    addr: str
    node_id: int = 0
    alias: str = ""
    add_t: int = 0
    del_t: int = 0
    uuid_he_sent: int = 0
    uuid_he_acked: int = 0


# --------------------------------------------------------------------------
# payload primitives


def _write_str(out: bytearray, s: str) -> None:
    b = s.encode("utf-8")
    write_uvarint(out, len(b))
    out += b


def _read_str(r: VarintReader) -> str:
    return r.take(r.uvarint()).decode("utf-8", "replace")


def _write_i64_col(out: bytearray, arr: np.ndarray) -> None:
    out += np.ascontiguousarray(arr, dtype="<i8").tobytes()


def _read_i64_col(r: VarintReader, n: int) -> np.ndarray:
    return np.frombuffer(r.take(8 * n), dtype="<i8")


def _write_bytes_list(out: bytearray, items: list) -> None:
    """None-able bytes column: i32 length-plus-one per slot (0 encodes
    None, so empty bytes stay distinct — tests/test_snapshot.py
    test_none_values_roundtrip), then the concatenated blob.

    Vectorized: the original per-item numpy scalar-assignment loop cost
    ~1µs/slot, which put snapshot ENCODING on the critical path of the
    sharded merge fan-out (the parent encodes every chunk for the shard
    workers) — ~0.5s per 131k-key chunk, slower than the merge itself.
    The common all-None / no-None columns now skip per-item Python
    entirely (list.count and map(len) run at C speed)."""
    n = len(items)
    n_none = items.count(None)
    if n_none == n:
        out += b"\x00" * (4 * n)
        return
    if n_none == 0:
        lens = np.fromiter(map(len, items), dtype="<i4", count=n)
        lens += 1
        out += lens.tobytes()
        out += b"".join(items)
        return
    lens = np.fromiter((0 if b is None else len(b) + 1 for b in items),
                       dtype="<i4", count=n)
    out += lens.tobytes()
    out += b"".join(b for b in items if b is not None)


def _read_bytes_list(r: VarintReader, n: int) -> list:
    lens = np.frombuffer(r.take(4 * n), dtype="<i4")
    # reject corruption at the section: one negative slot length would walk
    # `pos` backwards below, silently mis-slicing every later value (only
    # caught — maybe — by the end-of-stream checksum); the aggregate total
    # check alone misses mixed positive/negative corruption
    if n and bool((lens < 0).any()):
        raise ValueError("negative bytes-column slot length")
    if n and not lens.any():
        return [None] * n  # all-None column: no blob, no per-item loop
    total = int(lens.sum()) - int(np.count_nonzero(lens)) if n else 0
    if total < 0:
        raise ValueError("negative bytes-column length")
    blob = r.take(total)
    out: list = []
    pos = 0
    for ln in lens.tolist():
        if ln == 0:
            out.append(None)
        else:
            end = pos + ln - 1
            out.append(blob[pos:end])
            pos = end
    return out


def _encode_node(meta: NodeMeta) -> bytearray:
    out = bytearray()
    write_uvarint(out, meta.node_id)
    _write_str(out, meta.alias)
    _write_str(out, meta.addr)
    write_uvarint(out, meta.repl_last_uuid)
    return out


def _decode_node(payload: bytes) -> NodeMeta:
    r = VarintReader(payload)
    return NodeMeta(node_id=r.uvarint(), alias=_read_str(r),
                    addr=_read_str(r), repl_last_uuid=r.uvarint())


def _encode_replicas(records: Iterable[ReplicaRecord]) -> bytearray:
    records = list(records)
    out = bytearray()
    write_uvarint(out, len(records))
    for rec in records:
        _write_str(out, rec.addr)
        write_uvarint(out, rec.node_id)
        _write_str(out, rec.alias)
        write_uvarint(out, rec.add_t)
        write_uvarint(out, rec.del_t)
        write_uvarint(out, rec.uuid_he_sent)
        write_uvarint(out, rec.uuid_he_acked)
    return out


def _decode_replicas(payload: bytes) -> List[ReplicaRecord]:
    r = VarintReader(payload)
    return [ReplicaRecord(addr=_read_str(r), node_id=r.uvarint(),
                          alias=_read_str(r), add_t=r.uvarint(),
                          del_t=r.uvarint(), uuid_he_sent=r.uvarint(),
                          uuid_he_acked=r.uvarint())
            for _ in range(r.uvarint())]


def _encode_batch(b: ColumnarBatch, skip_keys: bool = False,
                  skip_members: bool = False) -> bytearray:
    """`skip_keys` / `skip_members`: omit the key / member bytes planes
    entirely (not even length columns).  Snapshot FILES never skip — the
    on-disk format is unchanged; the sharded-merge transport
    (parallel/host_pool.py) skips planes that replica chunks share and
    ships each exactly once per job, with the decoder receiving them via
    the matching `_decode_batch` kwargs."""
    out = bytearray()
    n = b.n_keys
    write_uvarint(out, n)
    if not skip_keys:
        _write_bytes_list(out, b.keys)
    out += np.ascontiguousarray(b.key_enc, dtype=np.int8).tobytes()
    for col in (b.key_ct, b.key_mt, b.key_dt, b.key_expire, b.reg_t,
                b.reg_node):
        _write_i64_col(out, col)
    _write_bytes_list(out, b.reg_val)

    write_uvarint(out, len(b.cnt_ki))
    for col in (b.cnt_ki, b.cnt_node, b.cnt_val, b.cnt_uuid, b.cnt_base,
                b.cnt_base_t):
        _write_i64_col(out, col)

    write_uvarint(out, len(b.el_ki))
    for col in (b.el_ki, b.el_add_t, b.el_add_node, b.el_del_t):
        _write_i64_col(out, col)
    if not skip_members:
        _write_bytes_list(out, b.el_member)
    _write_bytes_list(out, b.el_val)

    write_uvarint(out, len(b.del_keys))
    _write_bytes_list(out, b.del_keys)
    _write_i64_col(out, b.del_t)
    out.append(1 if b.rows_unique_per_slot else 0)

    # tensor planes (always written — one varint when empty; decoders
    # treat an exhausted payload as zero rows, so pre-tensor snapshot
    # FILES stay loadable)
    nt = len(b.tns_ki)
    write_uvarint(out, nt)
    if nt:
        for col in (b.tns_ki, b.tns_node, b.tns_uuid, b.tns_cnt):
            _write_i64_col(out, col)
        _write_bytes_list(out, list(b.tns_cfg))
        _write_bytes_list(out, [p.tobytes() if isinstance(p, np.ndarray)
                                else p for p in b.tns_payload])
    return out


def _decode_batch(payload: bytes, keys: Optional[list] = None,
                  el_member: Optional[list] = None) -> ColumnarBatch:
    """`keys` / `el_member`: externally-supplied bytes planes for a
    payload encoded with the matching skip flag (shared planes decoded
    once per job by the shard workers).  The returned batch references
    the supplied lists directly — callers must treat them read-only."""
    r = VarintReader(payload)
    b = ColumnarBatch()
    n = r.uvarint()
    if keys is None:
        b.keys = _read_bytes_list(r, n)
    else:
        if len(keys) != n:
            raise ValueError("supplied keys plane length mismatch")
        b.keys = keys
    b.key_enc = np.frombuffer(r.take(n), dtype=np.int8)
    b.key_ct = _read_i64_col(r, n)
    b.key_mt = _read_i64_col(r, n)
    b.key_dt = _read_i64_col(r, n)
    b.key_expire = _read_i64_col(r, n)
    b.reg_t = _read_i64_col(r, n)
    b.reg_node = _read_i64_col(r, n)
    b.reg_val = _read_bytes_list(r, n)

    nc = r.uvarint()
    b.cnt_ki = _read_i64_col(r, nc)
    b.cnt_node = _read_i64_col(r, nc)
    b.cnt_val = _read_i64_col(r, nc)
    b.cnt_uuid = _read_i64_col(r, nc)
    b.cnt_base = _read_i64_col(r, nc)
    b.cnt_base_t = _read_i64_col(r, nc)

    ne = r.uvarint()
    b.el_ki = _read_i64_col(r, ne)
    b.el_add_t = _read_i64_col(r, ne)
    b.el_add_node = _read_i64_col(r, ne)
    b.el_del_t = _read_i64_col(r, ne)
    if el_member is None:
        b.el_member = _read_bytes_list(r, ne)
    else:
        if len(el_member) != ne:
            raise ValueError("supplied member plane length mismatch")
        b.el_member = el_member
    b.el_val = _read_bytes_list(r, ne)

    nd = r.uvarint()
    b.del_keys = _read_bytes_list(r, nd)
    b.del_t = _read_i64_col(r, nd)
    b.rows_unique_per_slot = bool(r.byte())
    if r.pos < len(r.buf):  # tensor planes (absent in pre-tensor files)
        nt = r.uvarint()
        if nt:
            b.tns_ki = _read_i64_col(r, nt)
            b.tns_node = _read_i64_col(r, nt)
            b.tns_uuid = _read_i64_col(r, nt)
            b.tns_cnt = _read_i64_col(r, nt)
            b.tns_cfg = _read_bytes_list(r, nt)
            b.tns_payload = _read_bytes_list(r, nt)
    return b


# --------------------------------------------------------------------------
# chunking


def batch_chunks(batch: ColumnarBatch,
                 chunk_keys: int) -> Iterator[ColumnarBatch]:
    """Split a batch into key-range chunks of at most `chunk_keys` keys.

    Chunk boundaries are positional, so chunks of same-shape batches from
    different replicas stay slot-ALIGNED (the engine's fused dense-fold
    path relies on this — engine/tpu.py merge_many).  Counter/element rows
    are routed to the chunk owning their key and re-indexed chunk-locally;
    key-level delete tombstones ride the first chunk (merge order is
    immaterial: every component merge is commutative).
    """
    n = batch.n_keys
    if chunk_keys <= 0:
        chunk_keys = max(n, 1)

    if n == 0:
        if batch.del_keys:
            c = ColumnarBatch()
            c.rows_unique_per_slot = batch.rows_unique_per_slot
            c.del_keys = list(batch.del_keys)
            c.del_t = np.asarray(batch.del_t, dtype=_I64)
            yield c
        return

    # each chunk is a searchsorted slice.  When a plane's key ids are
    # already non-decreasing (true for keyspace dumps built in kid order,
    # and the common case generally) the slice is CONTIGUOUS: columns
    # become zero-copy views and the bytes lists plain list slices —
    # otherwise one stable sort per plane fixes the order first.
    cnt_arr = np.asarray(batch.cnt_ki)
    cnt_presorted = bool(len(cnt_arr) == 0 or (np.diff(cnt_arr) >= 0).all())
    cnt_order = None if cnt_presorted else np.argsort(cnt_arr, kind="stable")
    cnt_sorted = cnt_arr if cnt_presorted else cnt_arr[cnt_order]
    el_arr = np.asarray(batch.el_ki)
    el_presorted = bool(len(el_arr) == 0 or (np.diff(el_arr) >= 0).all())
    el_order = None if el_presorted else np.argsort(el_arr, kind="stable")
    el_sorted = el_arr if el_presorted else el_arr[el_order]
    tns_arr = np.asarray(batch.tns_ki)
    tns_presorted = bool(len(tns_arr) == 0
                         or (np.diff(tns_arr) >= 0).all())
    tns_order = None if tns_presorted \
        else np.argsort(tns_arr, kind="stable")
    tns_sorted = tns_arr if tns_presorted else tns_arr[tns_order]
    # one values scan for the whole batch; chunks inherit the hint (the
    # engine otherwise rescans per chunk per replica)
    el_hv = batch.el_has_vals
    if el_hv is None:
        el_hv = has_values(batch.el_val)

    for lo in range(0, n, chunk_keys):
        hi = min(n, lo + chunk_keys)
        c = ColumnarBatch()
        c.rows_unique_per_slot = batch.rows_unique_per_slot
        # identity tokens: replica chunks sliced from SHARED plane objects
        # compare equal, so the engine resolves each shape once (the
        # parent objects stay alive through the chunk's plane views)
        c.key_shape = (id(batch.keys), id(batch.key_enc), lo, hi)
        c.el_shape = (id(batch.el_ki), id(batch.el_member), lo, hi)
        c.shape_refs = (batch.keys, batch.key_enc, batch.el_ki,
                        batch.el_member)
        c.el_has_vals = el_hv
        c.keys = batch.keys[lo:hi]
        c.key_enc = batch.key_enc[lo:hi]
        c.key_ct = batch.key_ct[lo:hi]
        c.key_mt = batch.key_mt[lo:hi]
        c.key_dt = batch.key_dt[lo:hi]
        c.key_expire = batch.key_expire[lo:hi]
        c.reg_val = batch.reg_val[lo:hi]
        c.reg_t = batch.reg_t[lo:hi]
        c.reg_node = batch.reg_node[lo:hi]

        a, z = (int(x) for x in np.searchsorted(cnt_sorted, (lo, hi)))
        rows = slice(a, z) if cnt_presorted else cnt_order[a:z]
        c.cnt_ki = cnt_arr[rows] - lo
        c.cnt_node = np.asarray(batch.cnt_node)[rows]
        c.cnt_val = np.asarray(batch.cnt_val)[rows]
        c.cnt_uuid = np.asarray(batch.cnt_uuid)[rows]
        c.cnt_base = np.asarray(batch.cnt_base)[rows]
        c.cnt_base_t = np.asarray(batch.cnt_base_t)[rows]

        a, z = (int(x) for x in np.searchsorted(el_sorted, (lo, hi)))
        if el_presorted:
            rows = slice(a, z)
            c.el_member = batch.el_member[a:z]
            c.el_val = batch.el_val[a:z]
        else:
            rows = el_order[a:z]
            idx = rows.tolist()
            c.el_member = [batch.el_member[i] for i in idx]
            c.el_val = [batch.el_val[i] for i in idx]
        c.el_ki = el_arr[rows] - lo
        c.el_add_t = np.asarray(batch.el_add_t)[rows]
        c.el_add_node = np.asarray(batch.el_add_node)[rows]
        c.el_del_t = np.asarray(batch.el_del_t)[rows]

        if len(tns_arr):
            a, z = (int(x) for x in np.searchsorted(tns_sorted, (lo, hi)))
            if tns_presorted:
                rows = slice(a, z)
                c.tns_cfg = batch.tns_cfg[a:z]
                c.tns_payload = batch.tns_payload[a:z]
            else:
                rows = tns_order[a:z]
                idx = rows.tolist()
                c.tns_cfg = [batch.tns_cfg[i] for i in idx]
                c.tns_payload = [batch.tns_payload[i] for i in idx]
            c.tns_ki = tns_arr[rows] - lo
            c.tns_node = np.asarray(batch.tns_node)[rows]
            c.tns_uuid = np.asarray(batch.tns_uuid)[rows]
            c.tns_cnt = np.asarray(batch.tns_cnt)[rows]

        if lo == 0 and batch.del_keys:
            c.del_keys = list(batch.del_keys)
            c.del_t = np.asarray(batch.del_t, dtype=_I64)
        yield c


def iter_keyspace_chunks(ks, chunk_keys: int = 1 << 16,
                         include_deletes: bool = True) -> Iterator[ColumnarBatch]:
    """Chunked columnar dump of a keyspace (the snapshot body producer —
    reference src/server.rs:183-220 walks the DB per key instead)."""
    yield from batch_chunks(batch_from_keyspace(ks, include_deletes),
                            chunk_keys)


# --------------------------------------------------------------------------
# writer


class SnapshotWriter:
    """Streams sections to any binary file object with a running checksum
    (reference src/snapshot.rs:9-69 `checksum_writter`; ours tags the
    algorithm in the header so native CRC64 and the hashlib fallback
    interoperate)."""

    def __init__(self, f: IO[bytes], compress_level: int = 1,
                 alg: Optional[int] = None, container_level: int = 0):
        self._zw = None
        if container_level > 0:
            # compressed container: the WHOLE inner stream (magic
            # through digest) rides the chunked framing; callers
            # normally pair this with compress_level=0 so sections are
            # not compressed twice (module docstring)
            from ..utils.compressio import CompressWriter
            self._zw = CompressWriter(f, level=container_level,
                                      chunk=1 << 20)
            f = self._zw
        self._f = f
        self._level = compress_level
        self._sum = StreamChecksum(alg)
        self._finished = False
        header = MAGIC + bytes([self._sum.alg])
        self._emit(header)

    def _emit(self, data: bytes) -> None:
        self._sum.update(data)
        self._f.write(data)

    def _section(self, kind: int, payload: bytearray) -> None:
        assert not self._finished, "writer already finished"
        flag = 0
        body = bytes(payload)
        if self._level > 0:
            packed = zlib.compress(body, self._level)
            if len(packed) < len(body):
                flag, body = 1, packed
        head = bytearray([kind, flag])
        write_uvarint(head, len(body))
        self._emit(bytes(head))
        self._emit(body)

    def write_node(self, meta: NodeMeta) -> None:
        self._section(SEC_NODE, _encode_node(meta))

    def write_replicas(self, records: Iterable[ReplicaRecord]) -> None:
        self._section(SEC_REPLICAS, _encode_replicas(records))

    def write_chunk(self, batch: ColumnarBatch) -> None:
        self._section(SEC_BATCH, _encode_batch(batch))

    def write_chunk_raw(self, payload: bytes) -> None:
        """A BATCH section from an already-encoded (uncompressed) batch
        payload — the delta-sync path writes shard workers' bucket
        exports without a decode/re-encode round trip
        (server/serve_shards.py export_bucket_payloads)."""
        self._section(SEC_BATCH, bytearray(payload))

    def finish(self) -> None:
        """End marker + digest.  The digest covers the marker, so dropping
        trailing sections can't go unnoticed.  A container writer is
        finished AFTER the digest — the whole inner stream, digest
        included, rides the validated chunk framing."""
        self._emit(bytes([SEC_END]))
        self._f.write(self._sum.digest().to_bytes(8, "big"))
        if self._zw is not None:
            self._zw.finish()
        self._finished = True


# --------------------------------------------------------------------------
# loader


class SnapshotLoader:
    """Incremental section iterator over a binary file object.

    Yields `(kind, payload)` with kind in {"node", "replicas", "batch"} and
    payload NodeMeta / list[ReplicaRecord] / ColumnarBatch.  Magic is
    validated at construction; every malformed or truncated byte raises
    `InvalidSnapshot(offset)`; the end-marker digest raises
    `InvalidSnapshotChecksum` on mismatch (reference
    src/snapshot.rs:100-301).  Batch numeric columns are zero-copy
    read-only views over the section payload — engines only read them.
    """

    def __init__(self, f: IO[bytes], raw_batches: bool = False):
        """`raw_batches`: yield BATCH sections as ("batch_raw", payload
        bytes) without decoding — the sharded ingest path ships the
        payload to worker processes, which decode in parallel (the parent
        then pays only the read + decompress)."""
        self._off = 0
        self._done = False
        self._raw = raw_batches
        # container sniff: a compressed container wraps a whole plain
        # snapshot stream — read THROUGH the validating inflater, so
        # every consumer (boot restore, sync spill apply, sharded
        # ingest) handles both formats without knowing which it got
        first = f.read(len(MAGIC))
        if len(first) == len(MAGIC) and is_compressed(first):
            try:
                self._f = DecompressReader(f, head=first)
            except CompressFormatError:
                raise InvalidSnapshot(0) from None
            first = b""
        else:
            self._f = f
        self._off = len(first)
        head = first + self._read(len(MAGIC) + 1 - len(first),
                                  checked=False)
        if head[: len(MAGIC)] != MAGIC:
            raise InvalidSnapshot(0)
        try:
            self._sum = StreamChecksum(head[len(MAGIC)])
        except ValueError:
            raise InvalidSnapshot(len(MAGIC)) from None
        self._sum.update(head)

    def _read(self, n: int, checked: bool = True) -> bytes:
        try:
            data = self._f.read(n)
        except CompressFormatError:
            # a corrupt container chunk is snapshot corruption: surface
            # it through the loader's normal quarantine class
            raise InvalidSnapshot(self._off) from None
        if len(data) != n:
            raise InvalidSnapshot(self._off + len(data))
        self._off += n
        if checked:
            self._sum.update(data)
        return data

    def _read_uvarint(self) -> int:
        first = self._read(1)
        tag = first[0] >> 6
        extra = (0, 1, 3, 8)[tag]
        buf = first + (self._read(extra) if extra else b"")
        try:
            return VarintReader(buf).uvarint()
        except (ValueError, IndexError):
            raise InvalidSnapshot(self._off) from None

    def __iter__(self) -> Iterator[Tuple[str, object]]:
        return self

    def __next__(self) -> Tuple[str, object]:
        if self._done:
            raise StopIteration
        kind = self._read(1)[0]
        if kind == SEC_END:
            try:
                digest = self._f.read(8)
            except CompressFormatError:
                raise InvalidSnapshot(self._off) from None
            if len(digest) != 8:
                raise InvalidSnapshot(self._off + len(digest))
            self._off += 8
            if int.from_bytes(digest, "big") != self._sum.digest():
                raise InvalidSnapshotChecksum()
            self._done = True
            raise StopIteration
        name = _KIND_NAMES.get(kind)
        if name is None:
            raise InvalidSnapshot(self._off - 1)
        flag = self._read(1)[0]
        length = self._read_uvarint()
        if flag not in (0, 1) or length > _MAX_SECTION:
            raise InvalidSnapshot(self._off)
        payload = self._read(length)
        try:
            if flag == 1:
                # bound the inflated size too: this format arrives over the
                # network during full sync, and zlib expands up to ~1032x —
                # a corrupt length must not OOM the node before the
                # end-of-stream digest can reject the file
                d = zlib.decompressobj()
                payload = d.decompress(payload, _MAX_SECTION)
                if d.unconsumed_tail:
                    raise ValueError("decompressed section exceeds size cap")
            if kind == SEC_NODE:
                return name, _decode_node(payload)
            if kind == SEC_REPLICAS:
                return name, _decode_replicas(payload)
            if self._raw:
                return "batch_raw", payload
            return name, _decode_batch(payload)
        except (zlib.error, ValueError, IndexError) as e:
            raise InvalidSnapshot(self._off) from e


# --------------------------------------------------------------------------
# high-level dump / restore


def _fsync_parent_dir(path: str) -> None:
    """fsync the directory holding `path`: os.replace makes the rename
    ATOMIC but not DURABLE — until the directory entry itself is synced,
    a crash can roll the rename back and the just-written snapshot is
    gone (its tmp name was already unlinked).  POSIX requires an fsync
    on the directory fd to pin the entry."""
    d = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:  # pragma: no cover - exotic fs without dir-open
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def dump_keyspace(path: str, ks, meta: NodeMeta,
                  replicas: Iterable[ReplicaRecord] = (),
                  chunk_keys: int = 1 << 16,
                  compress_level: int = 1,
                  fsync: bool = False,
                  container_level: int = 0) -> int:
    """Atomic whole-keyspace dump (reference src/server.rs:183-220, minus
    the fork: the columnar capture is the consistent cut).  Returns the
    file size.  `fsync`: durable like write_snapshot_file — file data
    before the rename, parent directory entry after it.
    `container_level` > 0 writes the compressed container (inner
    sections then ship raw — module docstring)."""
    if container_level > 0:
        compress_level = 0
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            w = SnapshotWriter(f, compress_level=compress_level,
                               container_level=container_level)
            w.write_node(meta)
            records = list(replicas)
            if records:
                w.write_replicas(records)
            for chunk in iter_keyspace_chunks(ks, chunk_keys):
                w.write_chunk(chunk)
            w.finish()
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if fsync:
            _fsync_parent_dir(path)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return os.path.getsize(path)


def write_snapshot_file(path: str, meta: NodeMeta,
                        records: Iterable[ReplicaRecord],
                        captures: Iterable[ColumnarBatch],
                        chunk_keys: int = 1 << 16,
                        compress_level: int = 1,
                        fsync: bool = False,
                        container_level: int = 0) -> int:
    """Atomic snapshot dump of pre-captured columnar state: the ONE
    tmp-file + SnapshotWriter + replace recipe every dump site shares
    (persist/share.py full-sync dumps, bin/server.py background and
    shutdown dumps — including the sharded-node variants, whose
    `captures` are the per-shard worker exports — and the delta-sync
    bucket exports, replica/link.py _send_delta).  A capture may be a
    ColumnarBatch (chunked + encoded here) or pre-encoded section bytes
    (written as-is — shard workers encode their own bucket exports).
    Blocking file IO: call from a worker thread when on the event loop.
    Returns the file size.  `container_level` > 0 writes the compressed
    container (inner sections then ship raw — module docstring; raw
    captures keep whatever encoding their producer chose)."""
    if container_level > 0:
        compress_level = 0
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            w = SnapshotWriter(f, compress_level=compress_level,
                               container_level=container_level)
            w.write_node(meta)
            w.write_replicas(records)
            for part in captures:
                if isinstance(part, (bytes, bytearray)):
                    w.write_chunk_raw(part)
                    continue
                for chunk in batch_chunks(part, chunk_keys):
                    w.write_chunk(chunk)
            w.finish()
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if fsync:
            # the rename is atomic but not durable until the DIRECTORY
            # entry syncs — a crash right after os.replace could roll
            # it back, losing the dump whose bytes were just fsynced
            _fsync_parent_dir(path)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return os.path.getsize(path)


class SectionDemux:
    """Split a snapshot stream into its three section kinds: `batches()`
    yields the data sections in file order while the node meta and
    replica records accumulate on the instance — the demux every
    snapshot CONSUMER shares (plain/sharded/plane full-sync applies in
    replica/link.py, the sharded boot restore in server/io.py).  Meta
    and replica rows are only safely readable after the generator is
    exhausted; deferring their adoption until then is load-bearing for
    the apply sites (recorded pull watermarks are only backed by state
    once every chunk has merged)."""

    __slots__ = ("_f", "_raw", "meta", "replica_rows")

    def __init__(self, f: IO[bytes], raw_batches: bool = False):
        self._f = f
        self._raw = raw_batches
        self.meta: Optional[NodeMeta] = None
        self.replica_rows: List[ReplicaRecord] = []

    def batches(self) -> Iterator:
        for kind, payload in SnapshotLoader(self._f,
                                            raw_batches=self._raw):
            if kind == "node":
                self.meta = payload
            elif kind == "replicas":
                self.replica_rows.extend(payload)
            else:
                yield payload


def load_snapshot(path: str, ks, engine=None
                  ) -> Tuple[NodeMeta, List[ReplicaRecord]]:
    """Stream a snapshot file into a keyspace through a MergeEngine
    (boot-time restore — server/io.py start_node; the reference restarts
    empty, SURVEY.md §5.4).  Targets a FRESH keyspace: if the trailing
    checksum fails, partial merges have already been applied and the
    keyspace must be discarded.  Returns (NodeMeta, replica records).

    `ks` may also be a hash-sharded store (store/sharded_keyspace.py
    ShardedKeySpace, duck-typed on `submit`/`flush`): chunks then fan out
    by key hash as they decode, the shard workers merge them in parallel,
    and per-shard completions are consumed as they land — `engine` is
    ignored (each shard owns its own)."""
    sharded = hasattr(ks, "submit") and hasattr(ks, "n_shards")
    if engine is None and not sharded:
        from ..engine.cpu import CpuMergeEngine
        engine = CpuMergeEngine()
    meta = NodeMeta()
    records: List[ReplicaRecord] = []
    with open(path, "rb") as f:
        for kind, payload in SnapshotLoader(f, raw_batches=sharded):
            if kind == "node":
                meta = payload
            elif kind == "replicas":
                records = payload
            elif kind == "batch_raw":
                ks.submit_raw(payload)
            else:
                engine.merge(ks, payload)
    if sharded:
        ks.flush()
    elif getattr(engine, "needs_flush", False):
        engine.flush(ks)
    return meta, records
