"""Durable op log (AOF): group-commit append-only segments with
certified crash recovery.

Every repl-log append — client writes and replicated intake alike — is
mirrored here as a crc-framed record, so a `kill -9` between snapshot
dumps no longer loses acknowledged writes.  The design leans on three
things the codebase already certifies:

  * **payloads ARE the columnar wire encoding** (replica/wire.py): a
    serve-coalescer run is group-encoded ONCE into the exact REPLBATCH
    payload the push loops would build (and the finished encoding is
    published into the encode-once cache, replica/encode_cache.py, so
    the fan-out splices it instead of re-encoding); a received REPLBATCH
    payload is spliced into the log verbatim (it was just crc-validated
    by the decoder).  Everything else — barriers, lone writes, demoted
    runs — mirrors as per-frame RESP records.
  * **boot replay routes through the real apply path**: batch records
    decode with `decode_wire_batch` and land via
    `Node.merge_stream_batch`; frame records group-encode through the
    same `COLUMNAR_ENCODERS`/`BatchBuilder` machinery the live
    replication coalescer uses, with non-encodable frames applying as
    `apply_replicated` barriers.  There is no second apply
    implementation to drift.
  * **watermark/state consistency cuts** (docs/INVARIANTS.md): replica
    watermark records (WMARK) are appended AFTER the frames they cover
    — `uuid_he_sent` only advances at land, and frames mirror at land —
    so any valid log PREFIX (which is all torn-tail repair can leave)
    contains every frame its surviving watermark records claim.  A
    recovered node can never claim pull coverage of frames its log
    never held.

Record framing (little-endian):

    segment header   b"CSTAOF1\\n"
    record*          u32 len | u32 crc32(body) | body
    body             u8 type + payload

    BATCH payload    uvarint origin, base, last, n  + wire payload
    FRAME payload    uvarint origin, uuid           + RESP Arr(name,*args)
    WMARK payload    uvarint own_landed_uuid        + REPLICAS section

Torn-tail repair: recovery scans to the last valid record boundary and
truncates the torn suffix LOUDLY (`aof_tail_truncated` gauge + log
line).  A record either validates whole (length bound + crc + known
type) or ends the valid prefix — a bit-flipped or half-written record
is never replayed (tests/test_oplog.py sweeps every offset).

Group-commit fsync (`CONSTDB_AOF_FSYNC`):

    always    a serve chunk is acknowledged only after its covering
              fsync lands — server/io.py awaits `ack_barrier()` before
              flushing replies, riding the serve coalescer's existing
              end-of-chunk flush barrier, so one fsync covers the whole
              pipelined chunk (group commit).
    everysec  a background fsync every second (the cron tick drives
              it); a power loss can cost up to the last second.
    no        the OS decides (records are still written through).

**Emit-only-durable law**: the push stream never advertises an op the
log has not yet made durable — `durable_floor()` plugs into the repl
log's floor discipline (the same gating MergedReplLog uses for
minted-but-unlanded writes), so a peer can never hold an op this node
could still lose to a torn tail.  Crash recovery therefore loses, at
most, ops that (a) were never fsync-acknowledged and (b) no peer ever
saw — exactly the set the chaos oracle prunes from its journal
obligation (chaos/oracle.py `prune_origin`).

Log-rewrite compaction (`CONSTDB_AOF_REWRITE_PCT`): when the log grows
past the configured fraction over its post-rewrite base size, the node
captures a consistent state cut on the loop, switches appends to a
fresh segment GENERATION, writes the cut as a durable base snapshot
(the same tmp + rename + parent-fsync recipe every dump site uses —
persist/snapshot.py), commits the new generation in the meta file, and
deletes the old generation.  A crash at any point replays base + every
surviving generation in order — idempotent CRDT merges make the overlap
harmless.

Out-of-log state (full/delta sync, bulk ingest) cannot be replayed from
the log; `note_bulk_sync()` suppresses watermark records (a WMARK
claiming bulk-delivered coverage would skip redelivery of state the log
never held) and schedules an immediate rewrite to re-base the log on a
snapshot that covers it.  A state WIPE (`on_wipe`) discards every
record and fences recovery so peers full-sync a crashed-mid-resync node
instead of resurrecting pre-wipe state.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
import zlib
from typing import Optional

from ..errors import CstError
from ..utils.varint import VarintReader, read_uvarint, write_uvarint

log = logging.getLogger(__name__)

MAGIC = b"CSTAOF1\n"
REC_BATCH = 1
REC_FRAME = 2
REC_WMARK = 3

_REC_TYPES = frozenset((REC_BATCH, REC_FRAME, REC_WMARK))
# a stored record larger than this is corruption, not data
_MAX_RECORD = 1 << 30
# drain the per-segment append buffer to the OS past this many bytes
_BUF_FLUSH = 1 << 16
# min consecutive encodable serve-run ops before the run mirrors as ONE
# columnar batch record (mirrors replica/link.py _MIN_WIRE_RUN)
_MIN_BATCH_RUN = 2

FSYNC_POLICIES = ("always", "everysec", "no")
_EVERYSEC = 1.0
# force a WMARK record (and with it a fresh durable HLC mark) once the
# clock has advanced this far since the last one, even with no
# watermark movement — HLC uuids carry wall-ms in their high bits, so
# this is ~0.5s of clock travel
_WMARK_HLC_STRIDE = 500 << 22
# boot-replay bulk-merge rounds (CONSTDB_RECOVER_BULK): decoded records
# accumulate until this many columnar rows, then land through ONE
# engine merge_many call.  The budget is pinned AT the host
# micro-strategy ceiling (engine/hostbatch.py HOST_MICRO_MAX): one row
# past it and the CPU engine routes the round onto its per-row
# reference loop — the very path bulk replay exists to avoid — so
# rounds close BEFORE a push would cross it, never after
_REPLAY_ROUND_ROWS = 1 << 15
# op-stream frames buffered per columnar encode in bulk replay: larger
# than the live coalescer's 512 because boot replay has no latency
# bound — fewer, wider group-encode runs (serial replay buffers
# nothing: one apply per record, the reference path)
_REPLAY_BULK_FRAMES = 1 << 13
# progress log cadence during a long replay (ops between lines), so a
# multi-minute restart is observable instead of silent
_REPLAY_PROGRESS_EVERY = 200_000


class OpLogError(CstError):
    """Unreadable op log (bad header/meta) — quarantine class."""


def _pack_record(rtype: int, payload: bytes) -> bytes:
    body = bytes([rtype]) + payload
    return (len(body).to_bytes(4, "little")
            + zlib.crc32(body).to_bytes(4, "little") + body)


def scan_segment(path: str, classes: tuple = (), raw: bool = False):
    """-> (records, valid_bytes, total_bytes).  `records` is the maximal
    valid prefix as (rtype, payload bytes); `valid_bytes` is the offset
    of the first invalid byte (== total when the file is whole).  A
    missing/short/wrong magic header raises OpLogError — that file is
    UNREADABLE, not torn (the boot-quarantine satellite distinguishes
    the two).

    The per-record walk (framing + crc + rtype gate) runs in the native
    extension when built — one C call per segment instead of ~9us of
    interpreter dispatch per record.  `classes`: the six RESP message
    classes (`_frame_ctx()[1:]`); when given AND the native scanner is
    available, REC_FRAME records whose payload decodes cleanly come
    back pre-decoded as `(REC_FRAME, origin, uuid, name, args)`
    5-tuples (no payload bytes object, no second parse pass) — any
    anomaly degrades that record to the raw `(rtype, payload)` shape
    so the Python reference decode accepts-or-skips it unchanged.
    `raw` (bulk replay only): flat all-bulk command frames decode to
    PLAIN BYTES args instead of Bulk objects — the columnar encoders
    unwrap every argument anyway, so the wrappers are pure overhead
    there; the arg coercions (resp/message.py as_bytes/as_int/as_uint)
    pass bytes through, and _ReplayApplier re-wraps before any
    reference apply."""
    with open(path, "rb") as f:
        data = f.read()
    n = len(data)
    if n < len(MAGIC) or data[:len(MAGIC)] != MAGIC:
        raise OpLogError(f"bad oplog segment header: {path}")
    from ..resp.codec import _ext
    ext = _ext()
    if ext is not None and hasattr(ext, "aof_scan"):
        flags = (1,) if (raw and classes) else ()
        records, pos = ext.aof_scan(data, len(MAGIC), _MAX_RECORD,
                                    *classes, *flags)
        return records, pos, n
    records = []
    pos = len(MAGIC)
    while pos + 8 <= n:
        ln = int.from_bytes(data[pos:pos + 4], "little")
        if ln < 1 or ln > _MAX_RECORD or pos + 8 + ln > n:
            break
        crc = int.from_bytes(data[pos + 4:pos + 8], "little")
        body = data[pos + 8:pos + 8 + ln]
        if zlib.crc32(body) != crc or body[0] not in _REC_TYPES:
            break
        records.append((body[0], body[1:]))
        pos += 8 + ln
    return records, pos, n


# ------------------------------------------------------------------ meta

def _write_meta(path: str, fields: dict) -> None:
    """Atomic + durable tiny key=value meta file (the rename recipe
    every dump site shares — persist/snapshot.py)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        for k, v in fields.items():
            f.write(f"{k}={v}\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    from .snapshot import _fsync_parent_dir
    _fsync_parent_dir(path)


def _read_meta(path: str) -> dict:
    out: dict = {}
    try:
        with open(path) as f:
            for line in f:
                k, sep, v = line.strip().partition("=")
                if sep:
                    out[k] = v
    except OSError:
        pass
    return out


class RecoveryInfo:
    """What boot replay found and did (INFO Durability mirrors this)."""

    __slots__ = ("source", "frames", "batches", "batch_frames", "wmarks",
                 "skipped", "tail_truncated", "truncated_bytes",
                 "quarantined", "wmark_unsafe", "local_max",
                 "replayed_max", "fence", "hlc_mark", "mode", "shards",
                 "merge_rounds", "restore_to", "restore_skipped")

    def __init__(self) -> None:
        self.source = "empty"
        self.frames = 0
        self.batches = 0
        self.batch_frames = 0
        self.wmarks = 0
        self.skipped = 0           # corrupt/erroring ops never replayed
        self.tail_truncated = 0    # segments whose tail was torn
        self.truncated_bytes = 0
        self.quarantined = 0       # unreadable segments renamed aside
        # True when adopting the log's watermark records would be
        # UNSOUND: a quarantined base snapshot / segment may have held
        # frames a surviving WMARK claims, so recovery keeps watermarks
        # at zero and lets the peers resync us instead of skipping
        # redelivery (the consistency-cut law, inverted)
        self.wmark_unsafe = False
        self.local_max = 0         # newest LOCAL-origin uuid replayed
        self.replayed_max = 0      # newest uuid of ANY origin replayed
        self.fence = 0
        # newest durable HLC mark (WMARK records): the highest beacon
        # any peer can have seen — recovery re-observes it so post-
        # crash mints can never dip below a pre-crash beacon promise
        self.hlc_mark = 0
        # how the replay ran (INFO Recovery gauges): "serial" is the
        # per-record reference path, "bulk" the merge-round path,
        # "bulk+shards<N>" the concurrent per-segment plane replay
        self.mode = "serial"
        self.shards = 1            # replay concurrency actually used
        self.merge_rounds = 0      # bulk merge_many rounds landed
        self.restore_to = 0        # point-in-time target uuid (0 = full)
        self.restore_skipped = 0   # ops past the target, not replayed


class OpLog:
    """One node's durable op log (module docstring).  All append entry
    points run on the event loop (the single writer); only fsync leaves
    it (asyncio.to_thread), against raw unbuffered file objects."""

    def __init__(self, aof_dir: str, n_segments: int = 1,
                 fsync_policy: str = "everysec",
                 rewrite_pct: int = 100,
                 rewrite_min_bytes: int = 16 << 20,
                 checkpoint_secs: float = 0.0,
                 checkpoint_min_bytes: int = 1 << 20,
                 node=None) -> None:
        if fsync_policy not in FSYNC_POLICIES:
            raise ValueError(f"CONSTDB_AOF_FSYNC must be one of "
                             f"{FSYNC_POLICIES}, not {fsync_policy!r}")
        self.dir = aof_dir
        self.n_segments = max(1, n_segments)
        self.policy = fsync_policy
        self.rewrite_pct = max(0, rewrite_pct)
        self.rewrite_min_bytes = max(1 << 20, rewrite_min_bytes)
        # incremental checkpoints (CONSTDB_CHECKPOINT_SECS): the rewrite
        # machinery time-triggered — a periodic consistent base snapshot
        # + generation cut so a restart replays only the tail.  0 = off
        # (growth-triggered rewrites still run).  min_bytes keeps an
        # idle node from churning snapshots on a clock cadence alone.
        self.checkpoint_secs = max(0.0, checkpoint_secs)
        self.checkpoint_min_bytes = max(0, checkpoint_min_bytes)
        self.node = node
        os.makedirs(aof_dir, exist_ok=True)
        meta = _read_meta(self.meta_path(aof_dir))
        self.generation = int(meta.get("gen", 0) or 0)
        self._files: list = []
        self._bufs: list[bytearray] = []
        self.sizes: list[int] = []
        self._open_generation(self.generation, resume=True)
        # size of the log right after the last rewrite (the growth base)
        self.base_size = int(meta.get("base_size", 0) or 0) \
            or self.size_bytes()
        # durability tracking: pending local ops not yet covered by the
        # policy's durability point (fsync, or plain write under "no").
        # FIFO in append order; the floor is the min pending uuid.
        from collections import deque
        # pending entries carry a monotone sequence stamp so a settle
        # releases exactly the entries its capture covered — concurrent
        # commits (an ack barrier in flight while a rewrite/shutdown
        # sync runs) must never release entries appended after their
        # own capture
        self._seq = 0
        self._pend: deque = deque()          # (seq, local uuid)
        self._pend_min: Optional[int] = None
        # replicated-intake records not yet durable, per origin: the
        # REPLACK/coverage cap (cap_ack/cap_coverage) — a pull
        # watermark may only be ADVERTISED once the frames it covers
        # are in the log's durable prefix, or a torn tail could clip
        # frames a peer already believes we hold (and its GC would
        # collect tombstones we then never see again)
        self._intake_pend: dict[int, deque] = {}
        # cached min uuid per origin's pending deque: cap_ack runs on
        # EVERY ack-loop wake (per delivered batch under firehose), so
        # the cap must be O(1) — maintained at append, recomputed only
        # when a settle releases entries (reconnect redeliveries can
        # append BELOW the current min, so the deque is not monotonic
        # and d[0] alone is not the answer)
        self._intake_min: dict[int, int] = {}
        # durable HLC mark: the newest hlc value stored in a WMARK
        # record a completed group commit covers.  Outgoing REPLACK
        # beacons are CAPPED at it (replica/link.py): a beacon is the
        # promise "every uuid I will ever mint from now on exceeds B" —
        # a crash that rewinds the clock below an uncapped beacon makes
        # peers dup-skip the re-minted window forever (found by the
        # chaos everysec cell: a torn-crashed node re-minted uuids
        # below its own pre-crash beacon after a peer's clock jump had
        # pulled its HLC far ahead of its durable state).  Recovery
        # re-observes the mark, so every beacon any peer ever saw is
        # below every post-crash mint.
        self.beacon_cap = 0
        self._wmark_pend: deque = deque()   # (seq, hlc mark)
        self._last_wmark_hlc = 0
        self.synced_sizes: list[int] = list(self.sizes)
        self._dirty = False            # bytes written since last fsync
        self._oldest_dirty_ts = 0.0
        self._last_sync = time.monotonic()
        self.last_fsync_lag_ms = 0.0
        self.fsyncs = 0
        self.rewrites = 0
        self.tail_truncated = 0
        self.appended_ops = 0
        self.spliced_batches = 0       # intake payloads mirrored verbatim
        self.encoded_batches = 0       # serve runs group-encoded here
        self._wmark_ok = meta.get("wmark_ok", "1") != "0"
        self._last_wmark_sig = None
        self._rewrite_asap = meta.get("dirty", "0") == "1"
        # last checkpoint/rewrite cut, persisted in the meta so the
        # INFO gauges (checkpoint_last_uuid / checkpoint_age_s) and
        # --restore-to survive a restart
        try:
            self.checkpoint_uuid = int(meta.get("ckpt_uuid", 0) or 0)
            self.checkpoint_ts = float(meta.get("ckpt_ts", 0) or 0.0)
        except ValueError:
            self.checkpoint_uuid, self.checkpoint_ts = 0, 0.0
        # cadence is measured on the monotonic clock from boot (a node
        # restored from an old checkpoint must not cut immediately just
        # because the persisted wall ts is stale)
        self._last_ckpt_mono = time.monotonic()
        # chaos fault injection: name of the rewrite stage to crash at
        # ("switch" | "snapshot" | "meta"); "" = no fault.  The chaos
        # crash-mid-checkpoint cell sets it, drives one rewrite, then
        # kill -9s the node — certifying every on-disk interleaving of
        # the generation switch / meta commit / old-gen delete replays
        # idempotently.
        self._ckpt_fault = ""
        self._rewriting = False
        self._rewrite_buf_bytes = 0
        self._sync_lock = asyncio.Lock() if _has_loop() else None
        self._closed = False

    # ------------------------------------------------------------ paths

    @staticmethod
    def meta_path(aof_dir: str) -> str:
        return os.path.join(aof_dir, "aof.meta")

    @staticmethod
    def seg_path(aof_dir: str, gen: int, seg: int) -> str:
        return os.path.join(aof_dir, f"aof.g{gen}.s{seg}.log")

    @staticmethod
    def base_snapshot_path(aof_dir: str, gen: int) -> str:
        return os.path.join(aof_dir, f"aof.g{gen}.base.snapshot")

    @classmethod
    def list_generations(cls, aof_dir: str) -> list[int]:
        gens = set()
        try:
            names = os.listdir(aof_dir)
        except OSError:
            return []
        for name in names:
            if name.startswith("aof.g") and name.endswith(".log"):
                try:
                    gens.add(int(name[5:].split(".", 1)[0]))
                except ValueError:
                    pass
        return sorted(gens)

    def size_bytes(self) -> int:
        return sum(self.sizes) + sum(map(len, self._bufs))

    def used_buffer_bytes(self) -> int:
        """Governed memory (server/overload.py source): un-drained
        append buffers plus the rewrite capture's working estimate."""
        return sum(map(len, self._bufs)) + self._rewrite_buf_bytes

    # ----------------------------------------------------------- opening

    def _open_generation(self, gen: int, resume: bool = False) -> None:
        self._files = []
        self._bufs = []
        self.sizes = []
        for s in range(self.n_segments):
            path = self.seg_path(self.dir, gen, s)
            fresh = not (resume and os.path.exists(path))
            f = open(path, "ab", buffering=0)
            if fresh and f.tell() == 0:
                f.write(MAGIC)
            self._files.append(f)
            self._bufs.append(bytearray())
            self.sizes.append(f.tell())
        self.generation = gen
        self.synced_sizes = list(self.sizes)

    # ------------------------------------------------------ append surface

    def _append(self, seg: int, rec: bytes) -> None:
        buf = self._bufs[seg]
        buf += rec
        if not self._dirty:
            self._dirty = True
            self._oldest_dirty_ts = time.monotonic()
        if len(buf) >= _BUF_FLUSH:
            self._drain(seg)

    def _drain(self, seg: int) -> None:
        buf = self._bufs[seg]
        if buf:
            self._files[seg].write(buf)
            self.sizes[seg] += len(buf)
            self._bufs[seg] = bytearray()

    def _drain_all(self) -> None:
        for s in range(self.n_segments):
            self._drain(s)

    def _track_local(self, uuid: int) -> None:
        self._seq += 1
        self._pend.append((self._seq, uuid))
        if self._pend_min is None or uuid < self._pend_min:
            self._pend_min = uuid

    def _track_intake(self, origin: int, uuid: int) -> None:
        from collections import deque
        d = self._intake_pend.get(origin)
        if d is None:
            d = self._intake_pend[origin] = deque()
        self._seq += 1
        d.append((self._seq, uuid))
        m = self._intake_min.get(origin)
        if m is None or uuid < m:
            self._intake_min[origin] = uuid

    def cap_ack(self, origin: int, ack: int) -> int:
        """The REPLACK watermark this node may ADVERTISE for `origin`'s
        stream: never past its first undurable intake record — a peer
        told we hold a frame must stay told the truth through any torn
        tail (the persisted-coverage half of emit-only-durable).
        O(1): this runs on every ack-loop wake (replica/link.py)."""
        m = self._intake_min.get(origin)
        if m is None:
            return ack
        return min(ack, m - 1)

    def cap_coverage(self, coverage: int) -> int:
        """Same rule for the CLUSTER COVERAGE claim (REPLACK item 5):
        third-party tombstone GC gates on it, so it may only name the
        durable prefix."""
        for m in self._intake_min.values():
            coverage = min(coverage, m - 1)
        return coverage

    def append_local(self, uuid: int, name: bytes, args: list,
                     seg: Optional[int] = None) -> None:
        """One locally-executed write, mirrored at repl-log push time
        (Node.replicate_cmd / the sharded ack mirror)."""
        if self._closed:
            return
        self._append(self._local_seg if seg is None else seg,
                     _pack_record(REC_FRAME, self._frame_payload(
                         self.node.node_id, uuid, name, args)))
        self._track_local(uuid)
        self.appended_ops += 1

    @property
    def _local_seg(self) -> int:
        """Single-loop nodes log everything in segment 0; a sharded
        node's parent-loop (barrier-plane) writes take the LAST segment
        — the same index MergedReplLog gives its `local` segment."""
        return self.n_segments - 1

    def append_local_run(self, entries: list, prev_uuid: int,
                         seg: Optional[int] = None,
                         publish: bool = True, builder=None) -> None:
        """A serve-coalescer run of `(uuid, name, args)` just pushed via
        ReplLog.push_many.  Group-encoded ONCE into the exact columnar
        wire payload the push loops would build (replica/wire.py); the
        finished REPLBATCH frame is PUBLISHED into the encode-once cache
        so the peer fan-out splices these very bytes instead of
        re-encoding (caps-class "b").  Runs the codec rejects mirror as
        per-frame records — the same demotion the wire path applies.

        `builder`: the serve flush's ALREADY-FILLED BatchBuilder — its
        rows are the wire rows modulo the element-add dt-check flag
        (fresh client uuids make the rule provably inert locally, but a
        RECEIVER must still evaluate it), so serializing it through a
        chk-fixing view skips the whole re-encode
        (tests/test_oplog.py pins byte-equality with the from-scratch
        encoding)."""
        if self._closed or not entries:
            return
        node = self.node
        payload = None
        if len(entries) >= _MIN_BATCH_RUN:
            if builder is not None:
                payload = _encode_serve_builder(builder, prev_uuid,
                                                node.node_id)
            if payload is None:
                payload = _encode_run(entries, prev_uuid, node.node_id)
        s = self._local_seg if seg is None else seg
        if payload is None:
            for uuid, name, args in entries:
                self._append(s, _pack_record(REC_FRAME, self._frame_payload(
                    node.node_id, uuid, name, args)))
                self._track_local(uuid)
            self.appended_ops += len(entries)
            return
        last = entries[-1][0]
        out = bytearray()
        write_uvarint(out, node.node_id)
        write_uvarint(out, prev_uuid)
        write_uvarint(out, last)
        write_uvarint(out, len(entries))
        out += payload
        self._append(s, _pack_record(REC_BATCH, bytes(out)))
        # ONE pending marker per run: the floor is the min unsynced
        # local uuid, and a capture releases whole runs — per-entry
        # markers would only burn hot-path time for the same floor
        self._track_local(entries[0][0])
        self.appended_ops += len(entries)
        self.encoded_batches += 1
        if publish:
            self._publish_run(prev_uuid, last, len(entries), payload)

    def _publish_run(self, prev: int, last: int, n: int,
                     payload: bytes) -> None:
        """Hand the finished encoding to the broadcast plane: the push
        loops' caps-class entries at this exact cursor are the full
        REPLBATCH wire frames wrapping this payload — byte-identical to
        what replica/link.py _encode_wire_run would build for the same
        run (build_wire_batch is a pure function of the run, and the
        compressed variant mirrors its keep-only-if-smaller rule) — so
        the fan-out splices the log's encoding instead of re-doing it."""
        node = self.node
        cache = getattr(node, "wire_cache", None)
        if cache is None or not cache.enabled:
            return
        app = node.app
        if node.replicas is None or app is None:
            return
        from ..replica.link import (CAP_BATCH_STREAM, CAP_COMPRESS,
                                    REPLBATCH, wire_compress_min,
                                    wire_compress_of)
        compress_on = wire_compress_of(app)
        readers = {"b": 0, "bz": 0}
        for m in node.replicas.live_peers():
            link = m.link
            if link is None or not getattr(link, "connected", False):
                continue
            caps = getattr(link, "_peer_caps", 0)
            if not caps & CAP_BATCH_STREAM or m.batch_wire_off:
                continue
            if compress_on and caps & CAP_COMPRESS \
                    and not m.compress_wire_off:
                readers["bz"] += 1
            else:
                readers["b"] += 1
        if not (readers["b"] or readers["bz"]):
            return
        from ..resp.codec import encode_into
        from ..resp.message import Arr, Bulk, Int

        def frame_for(body: bytes) -> bytes:
            out = bytearray()
            encode_into(out, Arr([
                Bulk(REPLBATCH), Int(node.node_id), Int(prev), Int(last),
                Int(n), Bulk(body)]))
            return bytes(out)

        if readers["b"]:
            cache.put("b", prev, last, frame_for(payload), batches=1,
                      batch_frames=n, readers=readers["b"])
        if readers["bz"]:
            comp_raw = comp_wire = 0
            body = payload
            comp_min = wire_compress_min(app)
            if len(payload) >= comp_min:
                from ..utils.compressio import compress_bytes
                z = compress_bytes(payload, level=1)
                if len(z) < len(payload):
                    comp_raw, comp_wire = len(payload), len(z)
                    body = z
            cache.put("bz", prev, last, frame_for(body), batches=1,
                      batch_frames=n, comp_raw=comp_raw,
                      comp_wire=comp_wire, readers=readers["bz"])

    def append_frame(self, origin: int, uuid: int, name: bytes,
                     args: list, seg: int = 0) -> None:
        """One replicated-intake frame (the coalescing applier's buffer
        and barriers; a sharded node's ShardApplier routes by shard)."""
        if self._closed:
            return
        self._append(seg, _pack_record(
            REC_FRAME, self._frame_payload(origin, uuid, name, args)))
        self._track_intake(origin, uuid)
        self.appended_ops += 1

    def append_batch(self, origin: int, base: int, last: int, n: int,
                     payload: bytes, seg: int = 0) -> None:
        """One received REPLBATCH payload, spliced verbatim — it IS the
        columnar wire encoding and was just crc-validated by the
        decoder (replica/coalesce.py apply_wire_batch)."""
        if self._closed:
            return
        out = bytearray()
        write_uvarint(out, origin)
        write_uvarint(out, base)
        write_uvarint(out, last)
        write_uvarint(out, n)
        out += payload
        self._append(seg, _pack_record(REC_BATCH, bytes(out)))
        # the whole run (base, last] is undurable until the next commit:
        # the pending marker is its first covered uuid
        self._track_intake(origin, base + 1)
        self.appended_ops += n
        self.spliced_batches += 1

    @staticmethod
    def _frame_payload(origin: int, uuid: int, name: bytes,
                       args: list) -> bytes:
        from ..resp.codec import encode_into
        from ..resp.message import Arr, Bulk
        out = bytearray()
        write_uvarint(out, origin)
        write_uvarint(out, uuid)
        encode_into(out, Arr([Bulk(name), *args]))
        return bytes(out)

    def maybe_wmark(self) -> None:
        """Append a replica watermark/coverage record when the
        watermarks moved.  Captured on the loop BEFORE the next fsync
        cut, and suppressed while out-of-log bulk state is pending a
        rewrite (a WMARK claiming bulk-delivered coverage would skip
        redelivery of state the log never held — module docstring)."""
        if self._closed or not self._wmark_ok or self.node is None:
            return
        node = self.node
        if node.replicas is None:
            return
        records = node.replicas.records()
        for r in records:
            # durable cap: a WMARK lives in the LOCAL segment while the
            # frames it covers may live in another — file order alone
            # cannot make that cut consistent across segments, so the
            # persisted watermark names only fsync-covered frames
            r.uuid_he_sent = self.cap_ack(r.node_id, r.uuid_he_sent)
        landed = getattr(node.repl_log, "landed_last_uuid",
                         node.repl_log.last_uuid)
        if self._pend_min is not None:
            # the own-stream claim gets the same durable cap: on a
            # sharded node the covered local entries live in OTHER
            # segments, so file order alone cannot protect the cut
            landed = min(landed, self._pend_min - 1)
        hlc_now = node.hlc.current
        sig = (landed, tuple((r.addr, r.node_id, r.add_t, r.del_t,
                              r.uuid_he_sent, r.uuid_he_acked)
                             for r in records))
        # a WMARK also refreshes the durable HLC mark (the beacon cap —
        # see beacon_cap), so one is forced when the clock moved
        # meaningfully even if no watermark changed: an idle-but-alive
        # node must keep its beacon promise renewable
        if sig == self._last_wmark_sig and \
                hlc_now - self._last_wmark_hlc < _WMARK_HLC_STRIDE:
            return
        self._last_wmark_sig = sig
        self._last_wmark_hlc = hlc_now
        from .snapshot import _encode_replicas
        out = bytearray()
        write_uvarint(out, landed)
        write_uvarint(out, hlc_now)
        out += _encode_replicas(records)
        self._append(self._local_seg, _pack_record(REC_WMARK, bytes(out)))
        self._seq += 1
        self._wmark_pend.append((self._seq, hlc_now))

    # ---------------------------------------------------------- durability

    def durable_floor(self) -> Optional[int]:
        """The repl-log emission floor (MergedReplLog floor semantics:
        entries with uuid >= floor are invisible to the push stream):
        the smallest LOCAL uuid not yet covered by this policy's
        durability point.  None = everything durable, no gate."""
        return self._pend_min

    def install_floor(self) -> None:
        """Compose the durability floor into the node's repl log —
        called at arm time and re-called whenever the log object is
        replaced (state wipe, plane reset)."""
        rl = self.node.repl_log
        prev = getattr(rl, "floor", None)
        mine = self.durable_floor
        if prev is None:
            rl.floor = mine
        else:
            def combined(_prev=prev, _mine=mine):
                a, b = _prev(), _mine()
                if a is None:
                    return b
                if b is None:
                    return a
                return min(a, b)
            rl.floor = combined

    def _capture(self):
        """Pre-fsync cut, on the loop: drain buffers so every pending
        record is OS-visible, then remember how many pending entries
        (local and per-origin intake) the fsync will cover."""
        self._drain_all()
        self._dirty = False
        oldest = self._oldest_dirty_ts
        marks = (self._seq, list(self.sizes), self.generation)
        return marks, list(self._files), oldest

    def _settle(self, marks, oldest: float,
                fsynced: bool = True) -> None:
        """Post-fsync bookkeeping, on the loop: exactly the pending
        entries the capture covered (seq stamp at or below it) are
        durable now — release them from the floor/ack caps and wake the
        push loops past them.  Seq-bounded release is what makes
        concurrent commits safe: a settle never releases an entry
        appended after its own capture, and an entry already released
        by an overlapping commit is simply gone."""
        upto, sizes, gen = marks
        released = 0
        pend = self._pend
        while pend and pend[0][0] <= upto:
            pend.popleft()
            released += 1
        self._pend_min = min(u for _s, u in pend) if pend else None
        for origin in list(self._intake_pend):
            d = self._intake_pend[origin]
            dropped = False
            while d and d[0][0] <= upto:
                d.popleft()
                released += 1
                dropped = True
            if not d:
                del self._intake_pend[origin]
                del self._intake_min[origin]
            elif dropped:
                # one scan per settle, not per ack wake (cap_ack)
                self._intake_min[origin] = min(u for _s, u in d)
        wp = self._wmark_pend
        while wp and wp[0][0] <= upto:
            self.beacon_cap = max(self.beacon_cap, wp.popleft()[1])
        if gen == self.generation and len(sizes) == len(self.synced_sizes):
            self.synced_sizes = [max(a, b) for a, b in
                                 zip(self.synced_sizes, sizes)]
        now = time.monotonic()
        self._last_sync = now
        if fsynced:
            if oldest:
                self.last_fsync_lag_ms = round((now - oldest) * 1000.0, 3)
            self.fsyncs += 1
        node = self.node
        if node is not None and released:
            from ..server.events import EVENT_REPLICATED
            node.events.trigger(EVENT_REPLICATED)

    def _pending(self) -> bool:
        return bool(self._pend) or bool(self._intake_pend)

    def sync_now(self) -> None:
        """Blocking group commit (shutdown, tests, the wipe path)."""
        marks, files, oldest = self._capture()
        for f in files:
            try:
                os.fsync(f.fileno())
            except (OSError, ValueError):  # pragma: no cover
                pass  # closed under us — see _fsync_all
        self._settle(marks, oldest)

    async def _sync_async(self) -> None:
        if self._sync_lock is None:
            self._sync_lock = asyncio.Lock()
        async with self._sync_lock:
            if not self._dirty and not self._pending():
                return
            marks, files, oldest = self._capture()

            def _fsync_all():
                for f in files:
                    try:
                        os.fsync(f.fileno())
                    except (OSError, ValueError):
                        # rewrite/on_wipe/close swapped the generation
                        # and closed this file mid-commit (fileno() on
                        # a closed file is ValueError).  Settling is
                        # still sound: every closer either fsynced the
                        # captured bytes first (rewrite, close — their
                        # sync_now covers this capture's drain) or
                        # discarded the log wholesale (on_wipe), so
                        # nothing this capture covered can be torn away
                        pass

            await asyncio.to_thread(_fsync_all)
            self._settle(marks, oldest)

    @property
    def ack_barrier_needed(self) -> bool:
        """Does the next reply flush have to wait on a group commit?
        Only under `always` — and only when something is pending."""
        return self.policy == "always" and not self._closed and \
            (self._dirty or self._pending())

    async def ack_barrier(self) -> None:
        """The `always` ack gate (server/io.py): replies for a chunk
        reach the socket only after the fsync covering the chunk's
        appends lands — one fsync per pipelined chunk, group commit."""
        await self._sync_async()

    async def cron(self, app) -> None:
        """Driven from the server cron tick: everysec group commits,
        watermark records, policy=no write-through, rewrite checks."""
        if self._closed:
            return
        self.maybe_wmark()
        if self.policy == "no":
            # durability point == the OS write: drain and release
            # (no fsync — that is the policy's contract)
            marks, _files, oldest = self._capture()
            self._settle(marks, oldest, fsynced=False)
        elif self._dirty or self._pending():
            if self.policy == "everysec":
                if time.monotonic() - self._last_sync >= _EVERYSEC:
                    await self._sync_async()
            elif self.policy == "always":
                # idle-node belt and braces: an append whose connection
                # died before the ack barrier must not sit unsynced
                # forever (the barrier is the normal path)
                if time.monotonic() - self._last_sync >= _EVERYSEC:
                    await self._sync_async()
        if self._rewrite_asap or self.rewrite_due() or \
                self.checkpoint_due():
            await self.rewrite(app)

    # ---------------------------------------------------- out-of-log state

    def note_bulk_sync(self) -> None:
        """Out-of-log state landed (full/delta sync, bulk ingest): the
        log alone can no longer reproduce this node.  Watermark records
        are suppressed until a rewrite re-bases the log on a snapshot
        covering the bulk state (module docstring); the next cron tick
        runs that rewrite."""
        if self._closed:
            return
        self._wmark_ok = False
        self._rewrite_asap = True
        try:
            _write_meta(self.meta_path(self.dir), self._meta_fields())
        except OSError:  # pragma: no cover - fs-dependent
            pass

    def on_wipe(self, fence: int) -> None:
        """State wipe (reset_for_full_resync): every logged record
        describes discarded state — replaying any of it would resurrect
        keys whose tombstones are gone mesh-wide.  Discard the log,
        fence recovery at the pre-wipe watermark (peers full-sync a
        node that crashes before the post-wipe rewrite lands), and
        reinstall the floor on the freshly-swapped repl log."""
        if self._closed:
            return
        gen = self.generation + 1
        for f in self._files:
            try:
                f.close()
            except OSError:
                pass
        self._bufs = [bytearray() for _ in range(self.n_segments)]
        self._pend.clear()
        self._pend_min = None
        self._intake_pend.clear()
        self._intake_min.clear()
        self._wmark_pend.clear()
        self._dirty = False
        self._wmark_ok = False
        self._rewrite_asap = True
        self._last_wmark_sig = None
        self._open_generation(gen)
        # _meta_fields, not a raw dict: the persisted node_id must
        # survive the wipe, or a crash before the re-basing rewrite
        # boots with prescan_node_id()==0 (snapshot="" and
        # boot_snap_ok=0 rule out both snapshot fallbacks) and sharded
        # workers would stamp origin 0 into new writes
        _write_meta(self.meta_path(self.dir), self._meta_fields(
            gen=gen, base_size=0, snapshot="", boot_snap_ok=0,
            fence=fence))
        self._gc_generations(keep_from=gen)
        self.base_size = self.size_bytes()
        self.install_floor()

    def _meta_fields(self, **over) -> dict:
        fields = dict(gen=self.generation, base_size=self.base_size,
                      snapshot=os.path.basename(self._base_snapshot())
                      if self._base_snapshot() else "",
                      boot_snap_ok=1,
                      fence=0,
                      node_id=getattr(self.node, "node_id", 0) or 0,
                      wmark_ok=int(self._wmark_ok),
                      dirty=int(self._rewrite_asap),
                      ckpt_uuid=self.checkpoint_uuid,
                      ckpt_ts=f"{self.checkpoint_ts:.3f}")
        fields.update(over)
        return fields

    def _base_snapshot(self) -> str:
        path = self.base_snapshot_path(self.dir, self.generation)
        return path if os.path.exists(path) else ""

    def _gc_generations(self, keep_from: int) -> None:
        for g in self.list_generations(self.dir):
            if g >= keep_from:
                continue
            for s in range(64 + 2):
                p = self.seg_path(self.dir, g, s)
                if os.path.exists(p):
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
            p = self.base_snapshot_path(self.dir, g)
            if os.path.exists(p):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    # -------------------------------------------------------------- rewrite

    def rewrite_due(self) -> bool:
        if not self.rewrite_pct or self._rewriting or self._closed:
            return False
        size = self.size_bytes()
        if size < self.rewrite_min_bytes:
            return False
        return size > self.base_size * (1.0 + self.rewrite_pct / 100.0)

    def checkpoint_due(self) -> bool:
        """Time-triggered incremental checkpoint (CONSTDB_CHECKPOINT_*):
        due once the cadence elapsed AND the post-checkpoint tail has
        grown past the floor — the rewrite IS the checkpoint (consistent
        snapshot + generation cut), so a restart replays only the
        tail."""
        if not self.checkpoint_secs or self._rewriting or self._closed:
            return False
        if time.monotonic() - self._last_ckpt_mono < self.checkpoint_secs:
            return False
        return self.size_bytes() - self.base_size >= \
            self.checkpoint_min_bytes

    def _fault(self, stage: str) -> None:
        """Chaos fault point inside rewrite() (see _ckpt_fault)."""
        if self._ckpt_fault == stage:
            self._ckpt_fault = ""
            raise RuntimeError(f"injected checkpoint fault: {stage}")

    async def rewrite(self, app) -> None:
        """Compact snapshot + tail atomically (module docstring): cut on
        the loop, switch generations so new appends survive, write the
        base snapshot durably off-loop, commit the meta, drop the old
        generation."""
        if self._rewriting or self._closed:
            return
        self._rewriting = True
        node = self.node
        try:
            from ..engine.base import batch_from_keyspace
            from .snapshot import NodeMeta, write_snapshot_file
            plane = node.serve_plane
            # the rewrite working set rides the PERMANENT governor
            # source arm() installed — used_buffer_bytes includes
            # _rewrite_buf_bytes, so registering it again here would
            # double-count every oplog byte for the rewrite's duration
            self._rewrite_buf_bytes = 1 << 20
            gen = self.generation + 1
            # switch BEFORE the capture — the load-bearing order: every
            # op that lands from here on appends to the NEW generation
            # and survives the old one's deletion whether or not the
            # capture caught its effect.  A sharded capture AWAITS the
            # worker exports, and ops landing during those awaits used
            # to append to the OLD generation while missing the base —
            # the rewrite then deleted their only durable record
            # (acked, fsynced, emitted — found by the sharded chaos
            # cell as mesh-vs-reference divergence).
            self.sync_now()
            for f in self._files:
                try:
                    f.close()
                except OSError:
                    pass
            self._open_generation(gen)
            self._fault("switch")
            if plane is not None:
                repl_last = node.repl_log.landed_last_uuid
                records = node.replicas.records()
                captures = await plane.export_batches()
            else:
                node.ensure_flushed()
                repl_last = getattr(node.repl_log, "landed_last_uuid",
                                    node.repl_log.last_uuid)
                records = node.replicas.records()
                captures = [batch_from_keyspace(node.ks)]
            meta = NodeMeta(node_id=node.node_id, alias=node.alias,
                            addr=getattr(app, "advertised_addr", ""),
                            repl_last_uuid=repl_last)
            snap = self.base_snapshot_path(self.dir, gen)
            await asyncio.to_thread(
                write_snapshot_file, snap, meta, records, captures,
                chunk_keys=getattr(app, "snapshot_chunk_keys", 1 << 16),
                fsync=True)
            self._fault("snapshot")
            self._wmark_ok = True
            self._rewrite_asap = False
            self._last_wmark_sig = None
            self.base_size = self.size_bytes()
            # the cut this base represents — a restart from it replays
            # only records past repl_last (the checkpoint gauges)
            self.checkpoint_uuid = repl_last
            self.checkpoint_ts = time.time()
            self._last_ckpt_mono = time.monotonic()
            _write_meta(self.meta_path(self.dir), self._meta_fields(
                gen=gen, base_size=self.base_size,
                snapshot=os.path.basename(snap)))
            self._fault("meta")
            self._gc_generations(keep_from=gen)
            self.rewrites += 1
            log.info("aof rewrite #%d: base %s at uuid %d, log reset "
                     "(gen %d)", self.rewrites, snap, repl_last, gen)
        except (OSError, RuntimeError) as e:
            log.error("aof rewrite failed (will retry): %s", e)
            self._rewrite_asap = True
        finally:
            self._rewrite_buf_bytes = 0
            self._rewriting = False

    def close(self) -> None:
        if self._closed:
            return
        if self.policy != "no":
            self.sync_now()
        else:
            self._drain_all()
        self._closed = True
        for f in self._files:
            try:
                f.close()
            except OSError:
                pass


def _has_loop() -> bool:
    try:
        asyncio.get_running_loop()
        return True
    except RuntimeError:
        return False


# ----------------------------------------------------------------- encode

def _encode_run(entries: list, prev_uuid: int, node_id: int
                ) -> Optional[bytes]:
    """Group-encode a serve run of `(uuid, name, args)` into one
    columnar wire payload via the REAL wire codec (replica/wire.py
    build_wire_batch over stub repl-log entries).  None = the codec
    demoted the run (per-frame records instead)."""
    from ..replica.wire import build_wire_batch
    from ..server.repl_log import ReplEntry
    stubs = []
    prev = prev_uuid
    for uuid, name, args in entries:
        stubs.append(ReplEntry(uuid, prev, name, args, 0))
        prev = uuid
    return build_wire_batch(stubs, node_id)


class _WireView:
    """A serve BatchBuilder seen through wire-pattern glasses: element
    ADD rows get their dt-check mark set (the serve encoders leave it
    False — locally provably inert, but the wire format must tell the
    receiver to evaluate the rule).  Everything else is the same rows
    by reference."""

    __slots__ = ("keys", "enc", "ct", "mt", "dt", "reg_runs",
                 "cnt_rows", "el_rows", "tns_rows")

    def __init__(self, bb) -> None:
        self.keys = bb.keys
        self.enc = bb.enc
        self.ct = bb.ct
        self.mt = bb.mt
        self.dt = bb.dt
        self.reg_runs = bb.reg_runs
        self.cnt_rows = bb.cnt_rows
        self.el_rows = [
            (ki, m, v, at, an, dlt, at != 0)
            for ki, m, v, at, an, dlt, _chk in bb.el_rows]
        self.tns_rows = bb.tns_rows


def _encode_serve_builder(bb, prev_uuid: int, node_id: int
                          ) -> Optional[bytes]:
    """Serialize the serve flush's filled builder straight into the
    wire payload (skipping the from-scratch re-encode); None = a row
    fell outside the wire patterns — the caller falls back."""
    from ..replica import wire
    try:
        return wire._encode_builder(_WireView(bb), node_id, prev_uuid)
    except (wire._PatternError, *wire._ENC_ERRORS):
        return None


# ---------------------------------------------------------------- recovery

class _ReplayApplier:
    """Boot-replay twin of the live coalescing applier: frame records
    buffer per command and group-encode through the SAME
    COLUMNAR_ENCODERS/BatchBuilder machinery; non-encodable frames apply
    as apply_replicated barriers.  Erroring ops are logged and SKIPPED
    (recovery must never crash-loop on one bad op), counted into
    RecoveryInfo.

    Two landing strategies (CONSTDB_RECOVER_BULK):

      * serial (`bulk=False`): every record applies individually —
        frames through `Node.apply_replicated`, REPLBATCH records
        through one `Node.merge_stream_batch` call each.  This is the
        per-record reference path the bench oracle compares against;
        no buffering, no coalescing, strict log order.
      * bulk (`bulk=True`, the default): finalized batches accumulate
        into MERGE ROUNDS of ~_REPLAY_ROUND_ROWS columnar rows and land
        through one `Node.merge_batches` call per round (the engine's
        merge_many group path — the same WIDE strategy snapshot ingest
        rides).  CRDT merges commute, so batch order within a round is
        free; the ONE order-sensitive step is `finalize()`'s
        element-plane key-delete rule, which reads LIVE key dt columns
        — so a flush carrying checked element rows forces the pending
        round to land first iff the round holds a dt RAISE for one of
        the flush's OWN keys (`_round_dt_keys`; disjoint key sets
        commute).  Non-encodable barriers land buffer + round before
        applying — except KEY_SCOPED barriers whose key has no
        pending rows, which commute with everything pending and apply
        in place (the live coalescer's exact scoping discipline).
    """

    def __init__(self, node, info: RecoveryInfo,
                 bulk: bool = False) -> None:
        # frame() runs once per REC_FRAME record: bind its lookup
        # tables here instead of importing them per record
        from ..resp.message import Bulk, as_bytes
        from ..server.commands import (COLUMNAR_ENCODERS,
                                       KEY_SCOPED_BARRIERS,
                                       STATE_FREE_BARRIERS)
        self._as_bytes = as_bytes
        self._bulk_cls = Bulk
        self._encoders = COLUMNAR_ENCODERS
        self._key_scoped = KEY_SCOPED_BARRIERS
        self._state_free = STATE_FREE_BARRIERS
        self.node = node
        self.info = info
        self.bulk = bulk
        self._buf: dict[bytes, list] = {}
        self._frames = 0
        self._rows_bound = 0    # upper bound on the buffer's batch rows
        self._round: list = []      # finalized batches pending one merge
        self._round_rows = 0
        self._round_dt_keys: set = set()  # keys the round raises dts of
        self._pending_keys: set = set()   # keys with buffered/round rows
        self._next_progress = _REPLAY_PROGRESS_EVERY

    def frame(self, origin: int, uuid: int, name: bytes,
              args: list) -> None:
        info = self.info
        if self.bulk and name in self._encoders and len(args) >= 1:
            key = args[0]
            if type(key) is not bytes:   # raw-scanned args skip this
                try:
                    key = self._as_bytes(key)
                except CstError:
                    info.skipped += 1
                    return
            recs = self._buf.setdefault(name, [])
            recs.append((key, origin, uuid,
                         (None, None, None, None, None, *args)))
            self._pending_keys.add(key)
            self._frames += 1
            # args over-counts rows (values/pairs ride along), so this
            # keeps the flushed batch under the round budget — an
            # over-budget batch would fall off the engines' vectorized
            # micro path (see _REPLAY_ROUND_ROWS)
            self._rows_bound += len(args)
            if self._frames >= _REPLAY_BULK_FRAMES or \
                    self._rows_bound >= _REPLAY_ROUND_ROWS:
                self.flush()
        else:
            if self.bulk and name not in self._state_free:
                # a KEY_SCOPED barrier reads/sweeps exactly its own
                # key: with no pending rows for it, it commutes with
                # buffer and round and applies in place (the live
                # coalescer's scoping — replica/coalesce.py barrier())
                scoped = name in self._key_scoped and len(args) >= 1
                if scoped:
                    try:
                        scoped = self._as_bytes(args[0]) \
                            not in self._pending_keys
                    except CstError:
                        scoped = False
                if not scoped:
                    # any other state-reading barrier must see every
                    # prior record landed: drain buffer AND round
                    if self._frames:
                        self.flush()
                    self._merge_round()
            self._apply_one(origin, uuid, name, args)
        self._observe(origin, uuid)

    def batch(self, origin: int, base: int, last: int, n: int,
              payload: bytes) -> None:
        from ..replica import wire
        self.flush()
        node = self.node
        try:
            wb = wire.decode_wire_batch(payload, node.ks, origin, base)
            if wb.n_frames != n:
                raise wire.WireFormatError("frame count mismatch")
        except wire.WireFormatError as e:
            # a crc-valid record with an undecodable payload: skip it
            # loudly, never replay garbage
            log.error("aof replay: undecodable batch record (%s); "
                      "skipping %d ops", e, n)
            self.info.skipped += n
            return
        if not self.bulk:
            node.merge_stream_batch(wb, n)
        else:
            # finalize()'s key-delete rule reads LIVE dt columns: land
            # the pending round first iff it raises a dt of one of
            # THIS batch's keys (disjoint key sets commute)
            if self._round_dt_keys and not \
                    self._round_dt_keys.isdisjoint(wb.batch.keys):
                self._merge_round()
            node.ensure_flushed_for(("env",))
            self._push_round(wb.finalize())
        self.info.batches += 1
        self.info.batch_frames += n
        self._observe(origin, last)

    def _apply_one(self, origin: int, uuid: int, name: bytes,
                   args: list) -> None:
        if args and type(args[0]) is bytes:
            # raw-scanned frame (scan_segment raw mode: every arg is
            # plain bytes, all-or-nothing): the reference apply path
            # takes RESP messages, so re-wrap — barriers and other
            # non-encodable frames only, the columnar encoders take
            # the bytes as-is
            bulk = self._bulk_cls
            args = [bulk(a) for a in args]
        try:
            self.node.apply_replicated(name, args, origin, uuid)
            self.info.frames += 1
        except CstError as e:
            log.warning("aof replay: op %d (%s) failed (%s); skipped",
                        uuid, name, e)
            self.info.skipped += 1

    def _observe(self, origin: int, uuid: int) -> None:
        info = self.info
        if uuid > info.replayed_max:
            info.replayed_max = uuid
        if origin == self.node.node_id and uuid > info.local_max:
            info.local_max = uuid
        self.node.hlc.observe(uuid)
        done = info.frames + info.batch_frames
        if done >= self._next_progress:
            self._next_progress += _REPLAY_PROGRESS_EVERY
            log.info("aof replay progress: %d ops replayed "
                     "(%d skipped, %d merge rounds)", done,
                     info.skipped, info.merge_rounds)

    # ------------------------------------------------- bulk merge rounds

    def _push_round(self, b) -> None:
        # close the round BEFORE it would cross the row budget: the
        # budget equals the engines' host micro-strategy ceiling, and
        # an over-budget round falls off the vectorized path
        if self._round and \
                self._round_rows + b.n_rows > _REPLAY_ROUND_ROWS:
            self._merge_round()
        self._round.append(b)
        self._round_rows += b.n_rows
        self._pending_keys.update(b.keys)
        if len(b.del_keys):
            self._round_dt_keys.update(b.del_keys)
            self._pending_keys.update(b.del_keys)
        if b.key_dt.any():
            self._round_dt_keys.update(
                k for k, dt in zip(b.keys, b.key_dt.tolist()) if dt)

    def _merge_round(self) -> None:
        rnd, self._round = self._round, []
        self._round_rows = 0
        self._round_dt_keys.clear()
        # the frame buffer is always empty here (every caller flushes
        # first), so pendency collapses with the round
        self._pending_keys.clear()
        if not rnd:
            return
        # land the round as ONE wide batch: concatenating first means
        # one key resolution + one vectorized pass per plane for the
        # whole round, where per-batch merges would pay the numpy
        # fixed costs once per few-hundred-row record
        from ..engine.base import concat_batches
        self.node.merge_batches([concat_batches(rnd)])
        self.info.merge_rounds += 1

    def drain(self) -> None:
        """End-of-stream drain: frame buffer, then the pending round."""
        self.flush()
        self._merge_round()

    def flush(self) -> None:
        from ..replica.coalesce import BatchBuilder
        from ..server.commands import COLUMNAR_ENCODERS, NotColumnar
        buf, self._buf = self._buf, {}
        frames, self._frames = self._frames, 0
        self._rows_bound = 0
        if not frames:
            return
        node = self.node
        bb = BatchBuilder(node.ks)
        enc_errors = (NotColumnar, CstError, IndexError, TypeError,
                      ValueError, KeyError)
        failures: list = []
        for name, recs in buf.items():
            enc = COLUMNAR_ENCODERS[name]
            try:
                enc(bb, recs)
            except enc_errors:
                for r in recs:
                    try:
                        enc(bb, [r])
                    except enc_errors:
                        failures.append((name, r))
        if not self.bulk:
            node.merge_stream_batch(bb, frames - len(failures))
        else:
            # same dt-rule discipline as batch(): checked element rows
            # may not finalize over a pending dt raise of their OWN
            # key — disjoint key sets commute and keep the round open
            rdk = self._round_dt_keys
            if rdk and any(r[0] in rdk
                           for recs in buf.values() for r in recs):
                self._merge_round()
            node.ensure_flushed_for(("env",))
            self._push_round(bb.finalize())
            if failures:
                # the per-op fallbacks below read live state
                self._merge_round()
        self.info.frames += frames - len(failures)
        if failures:
            failures.sort(key=lambda f: f[1][2])
            for name, r in failures:
                self._apply_one(r[1], r[2], name, list(r[3][5:]))


def _frame_ctx():
    """Per-stream decode context: the native parser entry plus the RESP
    message classes, resolved ONCE instead of per record — replay
    decodes millions of frame records and the per-record import
    machinery + `_ext()` lookups were a measurable slice of the scan."""
    from ..resp import codec as C
    from ..resp.message import NIL, Arr, Bulk, Err, Int, Simple
    return C._ext(), Arr, Bulk, Int, Simple, Err, NIL


def _decode_frame(payload: bytes, parser=None, ctx=None):
    """Decode one REC_FRAME payload: varint header + exactly one RESP
    array.  The hot path hands the array straight to the native C
    parser (one call per record, no parser object, no buffer copy) —
    replay decodes millions of frame records and the per-record python
    around RespParser was a top scan cost.  `parser`: a reusable
    pure-python fallback parser for builds without the extension
    (_decode_stream rebuilds it after any failure, so a malformed
    record can never desync the stream that follows it).  `ctx`: a
    `_frame_ctx()` tuple shared across a stream's records."""
    if ctx is None:
        ctx = _frame_ctx()
    ext, Arr, Bulk, Int, Simple, Err, NIL = ctx
    origin, pos = read_uvarint(payload, 0)
    uuid, pos = read_uvarint(payload, pos)
    if ext is not None:
        try:
            msgs, new_pos, fallback = ext.resp_parse(
                payload, pos, Arr, Bulk, Int, Simple, Err, NIL, 2,
                512 << 20)
        except TypeError:   # prebuilt ext predating the max_bulk param
            msgs, new_pos, fallback = ext.resp_parse(
                payload, pos, Arr, Bulk, Int, Simple, Err, NIL)
        if len(msgs) != 1 or new_pos != len(payload) or fallback:
            raise ValueError("malformed frame record")
        msg = msgs[0]
    else:
        if parser is None:
            from ..resp import codec as C
            parser = C.RespParser()
        parser.feed(payload[pos:])
        msg = parser.next_msg()
        # a frame record holds exactly ONE message: anything left
        # queued or buffered would desync every later frame fed to
        # this parser (state peek, not a second parse call)
        if parser._qpos < len(parser._q) or \
                parser._pos < len(parser._buf):
            raise ValueError("trailing bytes in frame record")
    if not isinstance(msg, Arr) or not msg.items or \
            not isinstance(msg.items[0], Bulk):
        raise ValueError("malformed frame record")
    return origin, uuid, msg.items[0].val, msg.items[1:]


def _decode_batch_head(payload: bytes):
    r = VarintReader(payload)
    return r.uvarint(), r.uvarint(), r.uvarint(), r.uvarint(), \
        payload[r.pos:]


def _decode_wmark(payload: bytes):
    from .snapshot import _decode_replicas
    r = VarintReader(payload)
    landed = r.uvarint()
    hlc_mark = r.uvarint()
    return landed, hlc_mark, _decode_replicas(payload[r.pos:])


def scan_generation(aof_dir: str, gen: int, info: RecoveryInfo,
                    classes: tuple = (), raw: bool = False) -> list:
    """All segment record streams of one generation, with torn tails
    repaired (truncated on disk, LOUDLY).  Returns a list of per-segment
    record lists in segment order.  `classes` (see scan_segment): lets
    the native scanner pre-decode REC_FRAME records at scan time."""
    streams = []
    s = 0
    while True:
        path = OpLog.seg_path(aof_dir, gen, s)
        if not os.path.exists(path):
            break
        try:
            records, valid, total = scan_segment(path, classes, raw)
        except OpLogError as e:
            # unreadable (bad header — not a torn tail): quarantine the
            # SEGMENT, keep recovering from the others, and void the
            # log's watermark records (they may claim frames this
            # segment held)
            qpath = path + ".corrupt"
            try:
                os.replace(path, qpath)
            except OSError:  # pragma: no cover - fs-dependent
                qpath = path
            log.error("aof segment %s is unreadable (%s); quarantined "
                      "to %s", path, e, qpath)
            info.quarantined += 1
            info.wmark_unsafe = True
            streams.append([])
            s += 1
            continue
        if valid < total:
            info.tail_truncated += 1
            info.truncated_bytes += total - valid
            log.error(
                "aof segment %s has a torn tail: truncating %d bytes "
                "after the last valid record boundary (offset %d)",
                path, total - valid, valid)
            with open(path, "r+b") as f:
                f.truncate(valid)
        streams.append(records)
        s += 1
    return streams


def _decode_stream(recs: list) -> list:
    """Decode one segment's raw records into `(sortkey, rtype, data)`
    items — sortkey is the max uuid seen so far in file order, the
    k-way merge key.  Records the native scanner already pre-decoded
    (REC_FRAME 5-tuples, see scan_segment) pass straight through;
    crc-valid but undecodable records are skipped, loudly."""
    from ..resp.codec import make_parser
    seq = []
    last = 0
    parser = make_parser()
    ctx = _frame_ctx()
    for item in recs:
        rtype = item[0]
        try:
            if rtype == REC_FRAME:
                if len(item) == 5:   # pre-decoded at scan time
                    _, origin, uuid, name, args = item
                else:
                    origin, uuid, name, args = _decode_frame(
                        item[1], parser, ctx)
                last = max(last, uuid)
                seq.append((last, rtype, (origin, uuid, name, args)))
            elif rtype == REC_BATCH:
                origin, base, lastu, n, body = \
                    _decode_batch_head(item[1])
                last = max(last, base + 1)
                seq.append((last, rtype, (origin, base, lastu, n,
                                          body)))
                last = max(last, lastu)
            else:
                seq.append((last, rtype, item[1]))
        except (ValueError, IndexError, OverflowError, CstError):
            log.error("aof replay: undecodable record skipped")
            parser = make_parser()   # a bad frame may leave stale bytes
    return seq


def _merge_decoded(decoded: list):
    """K-way merge of decoded per-segment streams by uuid, preserving
    FILE order within a segment (barrier frames read live state, so a
    segment's arrival order is its execution order; cross-segment
    records touch disjoint key shards and commute — the parallel
    replay path leans on exactly this).  WMARK records sort with the
    record before them."""
    live = [d for d in decoded if d]
    if len(live) == 1:
        # single populated segment (every unsharded log): file order IS
        # the merge order, skip the per-record k-way scan
        yield from live[0]
        return
    idx = [0] * len(decoded)
    while True:
        best = -1
        best_key = None
        for i, seq in enumerate(decoded):
            if idx[i] < len(seq):
                key = seq[idx[i]][0]
                if best < 0 or key < best_key:
                    best, best_key = i, key
        if best < 0:
            return
        yield decoded[best][idx[best]]
        idx[best] += 1


def _iter_single_stream(recs: list):
    """(rtype, data) items of ONE populated segment, decoded lazily in
    file order — the sortkey bookkeeping `_decode_stream` does for the
    k-way merge is pure overhead when there is nothing to merge with,
    and every unsharded log is this case."""
    from ..resp.codec import make_parser
    parser = make_parser()
    ctx = _frame_ctx()
    for item in recs:
        rtype = item[0]
        try:
            if rtype == REC_FRAME:
                if len(item) == 5:   # pre-decoded at scan time
                    yield rtype, item[1:]
                else:
                    yield rtype, _decode_frame(item[1], parser, ctx)
            elif rtype == REC_BATCH:
                yield rtype, _decode_batch_head(item[1])
            else:
                yield rtype, item[1]
        except (ValueError, IndexError, OverflowError, CstError):
            log.error("aof replay: undecodable record skipped")
            parser = make_parser()   # a bad frame may leave stale bytes


def _merge_streams(streams: list):
    """Decode + k-way merge (see _decode_stream / _merge_decoded);
    yields (rtype, data) pairs."""
    live = [r for r in streams if r]
    if len(live) == 1:
        yield from _iter_single_stream(live[0])
        return
    for item in _merge_decoded([_decode_stream(r) for r in streams]):
        yield item[1:]


def arm(app, info: RecoveryInfo, n_segments: int = 1) -> OpLog:
    """Post-recovery arming (server/io.py start_node): open the live
    OpLog (resuming the current generation's segments), install the
    emission floor, register the buffer bytes with the overload
    governor, fence the repl log at the recovered watermark, and
    surface the recovery gauges in INFO."""
    node = app.node
    lg = OpLog(app.aof_dir, n_segments=n_segments,
               fsync_policy=app.aof_fsync,
               rewrite_pct=app.aof_rewrite_pct,
               rewrite_min_bytes=app.aof_rewrite_min_mb << 20,
               checkpoint_secs=getattr(app, "checkpoint_secs", 0.0),
               checkpoint_min_bytes=int(
                   getattr(app, "checkpoint_min_mb", 1)) << 20,
               node=node)
    lg.tail_truncated = info.tail_truncated
    node.oplog = lg
    lg.install_floor()
    node.governor.register_source(lg.used_buffer_bytes)
    if info.restore_to:
        # point-in-time restore dropped acked records above the target:
        # surviving watermarks over-claim, and the tail still holds the
        # dropped records — void the wmark law for this generation and
        # force an immediate rewrite to cut a fresh base
        lg._wmark_ok = False
        lg._rewrite_asap = True
        try:
            _write_meta(lg.meta_path(lg.dir), lg._meta_fields())
        except OSError:  # pragma: no cover - fs-dependent
            pass
    if node.node_id:
        # persist the identity so a future recovery can distinguish
        # local-origin records even when no snapshot survives
        try:
            _write_meta(lg.meta_path(lg.dir),
                        lg._meta_fields(node_id=node.node_id))
        except OSError:  # pragma: no cover - fs-dependent
            pass
    if info.fence:
        rl = node.repl_log
        rl.last_uuid = max(rl.last_uuid, info.fence)
        rl.evicted_up_to = max(rl.evicted_up_to, info.fence)
        node.hlc.observe(info.fence)
    if info.hlc_mark:
        # the beacon promise survives the crash: every beacon a peer
        # ever saw was capped at a durable HLC mark <= this, so
        # observing it keeps every post-crash mint above them
        node.hlc.observe(info.hlc_mark)
        lg.beacon_cap = info.hlc_mark
    x = node.stats.extra
    x["aof_recovery_source"] = info.source
    x["aof_tail_truncated"] = info.tail_truncated
    x["aof_recovered_ops"] = info.frames + info.batch_frames
    x["aof_recovered_local_max"] = info.local_max
    # every surviving op of THIS node's origin is at or below this —
    # the chaos oracle prunes its journal obligation above it
    x["aof_recovered_fence"] = info.fence
    x["recovery_mode"] = info.mode
    x["recovery_shards"] = info.shards
    x["recovery_merge_rounds"] = info.merge_rounds
    if info.restore_to:
        x["recovery_restore_to"] = info.restore_to
        x["recovery_restore_skipped"] = info.restore_skipped
    if info.quarantined:
        x["aof_segments_quarantined"] = info.quarantined
    if info.skipped:
        x["aof_replay_skipped"] = info.skipped
    if info.frames or info.batches or info.tail_truncated:
        log.info(
            "aof recovery (%s): %d frame ops + %d batch ops replayed, "
            "%d skipped, %d torn tail(s) truncated (%d bytes), fence "
            "%d", info.source, info.frames, info.batch_frames,
            info.skipped, info.tail_truncated, info.truncated_bytes,
            info.fence)
    return lg


def rearm(app, n_segments: int = 1) -> OpLog:
    """Re-open a node's op log WITHOUT replay — for a server rebuild
    over a surviving Node (the chaos harness's warm restart): the state
    lost nothing, the previous close() group-committed the log, so the
    fresh OpLog just resumes appending to the current generation."""
    node = app.node
    old = node.oplog
    if old is not None:
        node.governor.unregister_source(old.used_buffer_bytes)
        old.close()
    lg = OpLog(app.aof_dir, n_segments=n_segments,
               fsync_policy=app.aof_fsync,
               rewrite_pct=app.aof_rewrite_pct,
               rewrite_min_bytes=app.aof_rewrite_min_mb << 20,
               checkpoint_secs=getattr(app, "checkpoint_secs", 0.0),
               checkpoint_min_bytes=int(
                   getattr(app, "checkpoint_min_mb", 1)) << 20,
               node=node)
    node.oplog = lg
    lg.install_floor()
    node.governor.register_source(lg.used_buffer_bytes)
    return lg


async def recover_into_plane(app, restore_to: int = 0) -> RecoveryInfo:
    """Sharded-node boot recovery: the serve workers ARE the store, so
    the chosen snapshot fans out through plane.ingest_batches and log
    frames route to the worker owning their key (the exact per-key
    apply path ShardApplier uses).  Runs as start()'s boot-restore hook
    — plane up, listener not yet accepting.

    Fast-restart structure: segment scan + decode runs in a worker
    thread OVERLAPPED with the snapshot section ingest (the apply side
    waits for the ingest — a failed ingest resets the workers, so
    nothing may land before the snapshot settles).  Per-segment streams
    then replay CONCURRENTLY (CONSTDB_RECOVER_SHARDS; 0 = one task per
    segment, 1 = the serial merged-stream reference) — legal because
    segment-crossing records touch disjoint key shards and CRDT merges
    commute; each task keeps its own buffers so within-segment order is
    preserved end to end, and barriers fall back to the merged serial
    path (a generation containing any is replayed serially)."""
    node = app.node
    plane = node.serve_plane
    info = RecoveryInfo()
    info.restore_to = restore_to
    aof_dir = app.aof_dir
    meta = _read_meta(OpLog.meta_path(aof_dir))
    start_gen = int(meta.get("gen", 0) or 0)
    info.fence = int(meta.get("fence", 0) or 0)
    boot_ok = meta.get("boot_snap_ok", "1") != "0"
    gens = [g for g in OpLog.list_generations(aof_dir) if g >= start_gen]

    from ..conf import env_flag, env_int
    bulk = env_flag("CONSTDB_RECOVER_BULK", True)
    shards_knob = env_int("CONSTDB_RECOVER_SHARDS", 0)

    from ..server.io import _SNAPSHOT_LOAD_ERRORS, _quarantine_snapshot
    from .snapshot import SectionDemux
    loop = asyncio.get_running_loop()

    # -- overlap: scan + torn-tail repair + decode in a worker thread
    # while the snapshot sections stream into the shard workers below
    def _scan_all():
        classes = _frame_ctx()[1:]
        return {g: [_decode_stream(r)
                    for r in scan_generation(aof_dir, g, info, classes)]
                for g in gens}

    scan_fut = loop.run_in_executor(None, _scan_all)

    snap_name = meta.get("snapshot", "")
    base = os.path.join(aof_dir, snap_name) if snap_name else ""
    snap_meta = None
    records = []
    base_failed = False
    for candidate, label in ((base, "aof-base"),
                             (app.snapshot_path if boot_ok else "",
                              "boot")):
        if not candidate or not os.path.exists(candidate) or base_failed:
            continue
        f = await loop.run_in_executor(None, open, candidate, "rb")
        demux = SectionDemux(f)
        try:
            await plane.ingest_batches(demux.batches())
        except _SNAPSHOT_LOAD_ERRORS as e:
            await plane.pool.call_all("reset")
            _quarantine_snapshot(node, candidate, e)
            if candidate == base:
                base_failed = True
                info.wmark_unsafe = True
            continue
        finally:
            f.close()
        snap_meta = demux.meta
        records = demux.replica_rows
        info.source = f"{label}-snapshot"
        break

    decoded = await scan_fut
    if restore_to and snap_meta is not None and \
            snap_meta.repl_last_uuid > restore_to:
        raise OpLogError(
            f"--restore-to {restore_to} predates the recovered snapshot "
            f"cut (uuid {snap_meta.repl_last_uuid}); restore from a "
            "copy of an older checkpoint")

    # -- log replay: frames route to the worker owning their shard (the
    # worker-side per-key apply path); unroutable frames apply on the
    # parent exactly as ShardApplier.aapply does.  BATCH records only
    # appear when a node previously ran unsharded on the same log —
    # decode and aggregate into merge rounds fanned out like snapshot
    # chunks (bulk) or ingest one at a time (serial reference).
    from ..replica import wire
    from ..resp.codec import encode_into
    from ..resp.message import Arr, Bulk, Int, as_bytes
    from ..server.commands import COMMANDS, shard_routable
    from ..store.sharded_keyspace import shard_of
    n_shards = plane.n_shards
    wmarks: list = []
    prog = [_REPLAY_PROGRESS_EVERY]

    class _SegReplay:
        """One record stream's router: per-key frames buffer toward the
        owning worker, barriers drain and apply on the parent, batch
        records aggregate into merge rounds.  One instance per
        concurrent segment task — buffers and futures are private, so
        within-segment order survives the concurrency."""

        def __init__(self):
            self.bufs = [bytearray() for _ in range(n_shards)]
            self.counts = [0] * n_shards
            self.pending = 0
            self.round: list = []
            self.round_rows = 0

        async def flush_routed(self):
            if not self.pending:
                return
            futs = []
            for s in range(n_shards):
                if self.counts[s]:
                    futs.append((s, plane.pool.submit(
                        s, ("apply", bytes(self.bufs[s]),
                            self.counts[s]))))
                    self.bufs[s] = bytearray()
                    self.counts[s] = 0
            self.pending = 0
            for s, fut in futs:
                entries, _deleted, _stats = await fut
                if entries:
                    plane.merged.segments[s].push_many(entries)

        async def flush_round(self):
            rnd, self.round = self.round, []
            self.round_rows = 0
            if rnd:
                # one wide batch per round: ingest_batches splits,
                # encodes and submits per batch, so concatenating
                # first pays those once per round (engine/base.py)
                from ..engine.base import concat_batches
                await plane.ingest_batches([concat_batches(rnd)])
                info.merge_rounds += 1

        def _observe(self, origin, uuid):
            info.replayed_max = max(info.replayed_max, uuid)
            if origin == node.node_id:
                info.local_max = max(info.local_max, uuid)
            node.hlc.observe(uuid)
            done = info.frames + info.batch_frames
            if done >= prog[0]:
                prog[0] += _REPLAY_PROGRESS_EVERY
                log.info("aof replay progress: %d ops replayed "
                         "(%d skipped, %d merge rounds)", done,
                         info.skipped, info.merge_rounds)

        async def run(self, items):
            for item in items:
                rtype = item[1]
                if rtype == REC_FRAME:
                    origin, uuid, name, args = item[2]
                    if restore_to and uuid > restore_to:
                        info.restore_skipped += 1
                        continue
                    cmd = COMMANDS.get(name) or \
                        COMMANDS.get(name.lower())
                    routable = cmd is not None and \
                        shard_routable(cmd) and len(args) >= 1
                    key = None
                    if routable:
                        try:
                            key = as_bytes(args[0])
                        except CstError:
                            key = None
                    if key is not None:
                        # a pending batch round must land before any
                        # later frame touches its keys in a worker
                        if self.round:
                            await self.flush_round()
                        s = shard_of(key, n_shards)
                        encode_into(self.bufs[s], Arr([
                            Bulk(b"replicate"), Int(origin), Int(0),
                            Int(uuid), Bulk(name), *args]))
                        self.counts[s] += 1
                        self.pending += 1
                        info.frames += 1
                        if self.pending >= 512:
                            await self.flush_routed()
                    else:
                        await self.flush_round()
                        await self.flush_routed()
                        try:
                            node.apply_replicated(name, args, origin,
                                                  uuid)
                            info.frames += 1
                        except CstError as e:
                            log.warning("aof replay: op %d (%s) failed "
                                        "(%s); skipped", uuid, name, e)
                            info.skipped += 1
                    self._observe(origin, uuid)
                elif rtype == REC_BATCH:
                    origin, bbase, lastu, n, body = item[2]
                    if restore_to and lastu > restore_to:
                        info.restore_skipped += n
                        continue
                    await self.flush_routed()
                    try:
                        wb = wire.decode_wire_batch(body, node.ks,
                                                    origin, bbase)
                        if wb.n_frames != n:
                            raise wire.WireFormatError(
                                "frame count mismatch")
                    except wire.WireFormatError as e:
                        log.error("aof replay: undecodable batch "
                                  "record (%s); skipping %d ops", e, n)
                        info.skipped += n
                        continue
                    if bulk:
                        b = wb.finalize()
                        # close before crossing the row budget (the
                        # host micro-strategy ceiling — see
                        # _REPLAY_ROUND_ROWS)
                        if self.round and self.round_rows + b.n_rows \
                                > _REPLAY_ROUND_ROWS:
                            await self.flush_round()
                        self.round.append(b)
                        self.round_rows += b.n_rows
                    else:
                        await plane.ingest_batches([wb.finalize()])
                    info.batches += 1
                    info.batch_frames += n
                    self._observe(origin, lastu)
                else:
                    try:
                        w = _decode_wmark(item[2])
                        info.wmarks += 1
                        info.hlc_mark = max(info.hlc_mark, w[1])
                        if not restore_to or w[0] <= restore_to:
                            wmarks.append(w)
                    except (ValueError, IndexError, OverflowError):
                        log.error("aof replay: undecodable wmark "
                                  "skipped")
            await self.flush_round()
            await self.flush_routed()

    for gen in gens:
        streams = decoded.get(gen, [])
        nonempty = [s for s in streams if s]
        parallel = shards_knob != 1 and len(nonempty) > 1 and not any(
            r[1] == REC_BATCH for s in nonempty for r in s)
        if parallel:
            conc = len(nonempty) if shards_knob <= 0 \
                else min(shards_knob, len(nonempty))
            info.shards = max(info.shards, conc)
            sem = asyncio.Semaphore(conc)

            async def _one(items):
                async with sem:
                    await _SegReplay().run(items)

            await asyncio.gather(*[_one(s) for s in nonempty])
        else:
            await _SegReplay().run(_merge_decoded(streams))

    info.mode = ("bulk" if bulk else "serial") + (
        f"+shards{info.shards}" if info.shards > 1 else "")
    if info.frames or info.batches:
        info.source = (info.source + "+log") if snap_meta is not None \
            else "log-only"
    elif snap_meta is None:
        info.source = "empty"

    if snap_meta is not None:
        node.hlc.observe(snap_meta.repl_last_uuid)
        info.fence = max(info.fence, snap_meta.repl_last_uuid)
    adopt = list(records)
    # the newest surviving WMARK wins: landed coverage is non-decreasing
    # in file order, and all WMARKs live in one segment's stream
    wmark = None
    for w in wmarks:
        if wmark is None or w[0] >= wmark[0]:
            wmark = w
    if wmark is not None and not info.wmark_unsafe:
        landed, _hlc, wrecords = wmark
        info.fence = max(info.fence, landed)
        adopt.extend(wrecords)
    if adopt:
        node.replicas.merge_records(adopt, my_addr=app.advertised_addr,
                                    adopt_watermarks=not info.wmark_unsafe)
    info.fence = max(info.fence, info.local_max, info.replayed_max
                     if info.wmark_unsafe else 0)
    arm(app, info, n_segments=n_shards + 1)
    return info


def prescan_node_id(aof_dir: str, boot_snapshot: str = "") -> int:
    """The node identity a recovery would restore, WITHOUT replaying
    anything — the sharded boot path needs it before the workers spawn
    (they stamp it into writes)."""
    meta = _read_meta(OpLog.meta_path(aof_dir))
    nid = int(meta.get("node_id", 0) or 0)
    if nid:
        return nid
    from .snapshot import SnapshotLoader
    snap_name = meta.get("snapshot", "")
    boot_ok = meta.get("boot_snap_ok", "1") != "0"
    for candidate in (os.path.join(aof_dir, snap_name) if snap_name
                      else "", boot_snapshot if boot_ok else ""):
        if not candidate or not os.path.exists(candidate):
            continue
        try:
            with open(candidate, "rb") as f:
                for kind, payload in SnapshotLoader(f):
                    if kind == "node":
                        if payload.node_id:
                            return payload.node_id
                        break
        except Exception:  # noqa: BLE001 - recovery quarantines later
            continue
    return 0


def recover(node, aof_dir: str, boot_snapshot: str = "",
            engine=None, bulk=None, restore_to: int = 0) -> RecoveryInfo:
    """Single-keyspace boot recovery: base/boot snapshot + oplog tail,
    replayed through the real merge path (module docstring).  The
    caller (server/io.py start_node) sets the repl-log fences and INFO
    gauges from the returned RecoveryInfo.  Blocking; runs before the
    listener opens.

    `bulk` selects the merge-round landing strategy (None reads
    CONSTDB_RECOVER_BULK; see _ReplayApplier).  `restore_to` caps the
    replay at a point-in-time uuid: records above it are skipped (batch
    records at record granularity — a batch whose last uuid exceeds the
    target is dropped whole), watermarks above it are not adopted, and
    the caller must re-base the log afterwards (arm() flags the log
    dirty so the next rewrite cuts a fresh generation)."""
    from ..conf import env_flag
    if bulk is None:
        bulk = env_flag("CONSTDB_RECOVER_BULK", True)
    info = RecoveryInfo()
    info.mode = "bulk" if bulk else "serial"
    info.restore_to = restore_to
    meta = _read_meta(OpLog.meta_path(aof_dir))
    start_gen = int(meta.get("gen", 0) or 0)
    info.fence = int(meta.get("fence", 0) or 0)
    boot_ok = meta.get("boot_snap_ok", "1") != "0"
    gens = [g for g in OpLog.list_generations(aof_dir) if g >= start_gen]

    # -- snapshot source: the AOF base (log-consistent cut) when one
    # exists, the boot snapshot otherwise (its state covers its
    # watermarks — a consistent cut too; replaying the whole log over
    # it is idempotent re-merge).  A wipe fence forbids the boot
    # snapshot (it holds pre-wipe state).
    snap_name = meta.get("snapshot", "")
    base = os.path.join(aof_dir, snap_name) if snap_name else ""
    snap_meta = None
    records = []
    from ..server.io import _SNAPSHOT_LOAD_ERRORS, _quarantine_snapshot
    from .snapshot import load_snapshot
    base_failed = False
    for candidate, label in ((base, "aof-base"),
                             (boot_snapshot if boot_ok else "", "boot")):
        if not candidate or not os.path.exists(candidate) or base_failed:
            continue
        try:
            snap_meta, records = load_snapshot(candidate, node.ks,
                                               engine=engine or node.engine)
            info.source = f"{label}-snapshot"
            break
        except _SNAPSHOT_LOAD_ERRORS as e:
            if hasattr(node.engine, "discard_resident"):
                node.engine.discard_resident()
            node.ks = node._make_keyspace()
            _quarantine_snapshot(node, candidate, e)
            if candidate == base:
                # the base covered every pre-rewrite frame the log's
                # WMARKs may claim; with it gone, adopting them (or the
                # OLDER boot snapshot) would skip redelivery of ops the
                # recovered state lacks — replay ops only, keep
                # watermarks at zero, and let the peers resync us
                base_failed = True
                info.wmark_unsafe = True

    if restore_to and snap_meta is not None and \
            snap_meta.repl_last_uuid > restore_to:
        raise OpLogError(
            f"--restore-to {restore_to} predates the recovered snapshot "
            f"cut (uuid {snap_meta.repl_last_uuid}); restore from a "
            "copy of an older checkpoint")

    # -- log replay through the real apply path
    applier = _ReplayApplier(node, info, bulk=bulk)
    wmark = None
    classes = _frame_ctx()[1:]
    for gen in gens:
        for item in _merge_streams(
                scan_generation(aof_dir, gen, info, classes, raw=bulk)):
            rtype = item[0]
            if rtype == REC_FRAME:
                if restore_to and item[1][1] > restore_to:
                    info.restore_skipped += 1
                    continue
                applier.frame(*item[1])
            elif rtype == REC_BATCH:
                if restore_to and item[1][2] > restore_to:
                    info.restore_skipped += item[1][3]
                    continue
                applier.batch(*item[1])
            else:
                try:
                    w = _decode_wmark(item[1])
                    info.wmarks += 1
                    info.hlc_mark = max(info.hlc_mark, w[1])
                    if not restore_to or w[0] <= restore_to:
                        wmark = w
                except (ValueError, IndexError, OverflowError):
                    log.error("aof replay: undecodable wmark skipped")
        # generation boundary = a rewrite cut: land everything before
        # the next generation's records (they may read barrier state)
        applier.drain()
    applier.drain()
    if info.frames or info.batches:
        info.source = (info.source + "+log") if snap_meta is not None \
            else "log-only"
    elif snap_meta is None:
        info.source = "empty"

    # -- watermarks: snapshot records first (state-backed), then the
    # newest surviving WMARK (log-cut-backed: every frame it claims is
    # in the valid prefix BEFORE it — the consistency-cut law)
    if snap_meta is not None:
        if snap_meta.node_id and not node.node_id:
            node.node_id = snap_meta.node_id
        node.hlc.observe(snap_meta.repl_last_uuid)
        info.fence = max(info.fence, snap_meta.repl_last_uuid)
    adopt = list(records)
    if wmark is not None and not info.wmark_unsafe:
        landed, _hlc, wrecords = wmark
        info.fence = max(info.fence, landed)
        adopt.extend(wrecords)
    if adopt and node.replicas is not None:
        # membership always merges (the mesh must re-form around us);
        # pull watermarks adopt only when the backing state survived
        # whole — adopt_watermarks=False keeps them at zero and the
        # peers resync us instead (merge_records' own coupling law)
        node.replicas.merge_records(adopt, my_addr=node.addr or "",
                                    adopt_watermarks=not info.wmark_unsafe)
    info.fence = max(info.fence, info.local_max, info.replayed_max
                     if info.wmark_unsafe else 0)
    return info
