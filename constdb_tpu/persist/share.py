"""Shared full-sync dumps: one on-disk snapshot serves every syncing peer.

Capability parity with the reference's background-dump orchestration
(reference src/server.rs:221-250): it fork-COW-dumps ONCE, reuses a recent
snapshot for subsequent peers (reuse check at server.rs:225-227), and
streams the resulting FILE to each socket (push.rs:34-71 +
conn/writer.rs:92-112 send_file) — full-sync memory is O(io-buffer), not
O(keyspace).

The TPU build reaches the same properties fork-free:
  * consistency — the columnar capture happens on the event loop (the
    single writer), so it is a consistent cut by construction;
  * one dump, many peers — concurrent full syncs await the same in-flight
    dump task; later syncs REUSE the file while the repl_log still covers
    its watermark (`can_resume_from`), exactly the reference's freshness
    rule expressed over our exact eviction bound;
  * bounded memory — SnapshotWriter streams chunk sections straight to the
    file on a worker thread, and the pusher streams the file to the socket
    in fixed-size pieces.  No whole-keyspace blob is ever materialized
    per peer (the round-1 implementation did exactly that).
"""

from __future__ import annotations

import asyncio
import logging
import os
from dataclasses import dataclass
from typing import Optional, TYPE_CHECKING

from ..engine.base import batch_from_keyspace
from .snapshot import NodeMeta, write_snapshot_file

if TYPE_CHECKING:
    from ..server.io import ServerApp

log = logging.getLogger(__name__)


@dataclass
class Dump:
    path: str
    repl_last: int
    size: int


class SharedDump:
    """Produces and caches the node's current full-sync snapshot file.

    Two VARIANTS of the same state cut may coexist (round 17): the
    plain snapshot stream every pre-compression peer must receive
    byte-exactly, and the compressed container (utils/compressio.py)
    streamed to CAP_COMPRESS peers.  Each variant is produced once and
    reused by every concurrently or subsequently syncing peer of its
    class — a mixed-capability mesh costs at most two dumps, never one
    per peer."""

    def __init__(self, app: "ServerApp"):
        self.app = app
        self._current: dict[bool, Optional[Dump]] = {False: None,
                                                     True: None}
        self._inflight: dict[bool, Optional[asyncio.Task]] = {False: None,
                                                              True: None}
        self.dumps_taken = 0   # observability + tests

    async def acquire(self, compressed: bool = False) -> Dump:
        """The freshest usable dump of the requested variant, producing
        one if needed.  Concurrent callers share a single in-flight dump
        per variant."""
        node = self.app.node
        cur = self._current[compressed]
        if cur is not None and node.repl_log.can_resume_from(cur.repl_last) \
                and os.path.exists(cur.path):
            return cur
        inflight = self._inflight[compressed]
        if inflight is None or inflight.done():
            inflight = self._inflight[compressed] = \
                asyncio.create_task(self._dump(compressed))
        # shield: one awaiter being cancelled must not kill the dump the
        # other peers are waiting on
        return await asyncio.shield(inflight)

    async def _dump(self, compressed: bool = False) -> Dump:
        app, node = self.app, self.app.node
        plane = node.serve_plane
        if plane is not None:
            # shard-per-core node: the workers hold the state.  The
            # LANDED watermark (fences included — after a reset the
            # segments are empty but the fence is the resume floor) AND
            # the replica records are captured BEFORE the exports; ops
            # landing during the export are then in the state but above
            # every recorded watermark, so the peer re-applies them over
            # state that already includes them (idempotent merges, the
            # redelivery class replica/coalesce.py documents).  The
            # REVERSE order is a real loss: a pull watermark recorded
            # AFTER the export claims coverage of frames the exported
            # state never saw, and a receiver adopting it skips their
            # redelivery forever (found by the chaos harness in the
            # cold-restart dump, which had the same shape).
            repl_last = node.repl_log.landed_last_uuid
            records = node.replicas.records()
            captures = await plane.export_batches()
        else:
            node.ensure_flushed()  # device-resident merge state → host
            captures = [batch_from_keyspace(node.ks)]  # on the loop
            repl_last = node.repl_log.last_uuid
            records = node.replicas.records()
        if node.oplog is not None and node.oplog.policy != "no":
            # emit-only-durable (persist/oplog.py): the dump streams
            # state effects of every op in the capture — group-commit
            # AFTER the capture, so everything it contains is durable
            # before a peer can hold it.  Capture-THEN-commit is the
            # load-bearing order: a commit taken first covers only its
            # own capture instant, and ops landing DURING its fsync
            # would be in the state cut but not in the durable prefix —
            # exactly the emitted-but-torn-away divergence the chaos
            # everysec cell caught.  The yield first: on a SHARDED node
            # the worker exports can resolve before earlier serve acks'
            # done-callbacks ran (the quiesce race serve_shards.py
            # documents), so ops already IN the captures may not have
            # mirrored into the op log yet — one loop turn runs those
            # queued callbacks, and the commit's capture then covers
            # them.
            await asyncio.sleep(0)
            await node.oplog.ack_barrier()
        meta = NodeMeta(node_id=node.node_id, alias=node.alias,
                        addr=app.advertised_addr, repl_last_uuid=repl_last)
        suffix = ".z" if compressed else ""
        path = os.path.join(app.work_dir,
                            f"fullsync.{node.node_id}.snapshot{suffix}")
        # the full-sync stream sends this very file, so the compression
        # rides the wire end-to-end: the plain variant carries the
        # per-section zlib (conf snapshot_compress_level — the exact
        # pre-compression stream), the compressed variant the whole-
        # stream container (contrast reference src/conn/writer.rs:92-112,
        # which streams raw)
        # the container writer's working buffer is bounded by its chunk
        # size; register that bound as a used_memory source for the
        # dump's duration (the governor's accounting-completeness law —
        # server/overload.py)
        gov = node.governor
        src = (lambda: 1 << 20) if compressed else None
        if src is not None:
            gov.register_source(src)
        try:
            size = await asyncio.to_thread(
                write_snapshot_file, path, meta, records, captures,
                chunk_keys=app.snapshot_chunk_keys,
                compress_level=getattr(app, "snapshot_compress_level", 1),
                container_level=getattr(app, "bulk_compress_level", 6)
                if compressed else 0)
        finally:
            if src is not None:
                gov.unregister_source(src)
        self.dumps_taken += 1
        dump = Dump(path, repl_last, size)
        self._current[compressed] = dump
        key = "last_snapshot_z_bytes" if compressed \
            else "last_snapshot_bytes"
        node.stats.extra[key] = size
        log.info("full-sync dump #%d%s: %d bytes at uuid %d",
                 self.dumps_taken, " (compressed)" if compressed else "",
                 size, repl_last)
        return dump

    def invalidate(self) -> None:
        self._current = {False: None, True: None}
