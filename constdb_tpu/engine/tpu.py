"""Batched JAX MergeEngine: the TPU path for bulk CRDT merges.

Two device strategies, picked per CRDT family by batch density:

  * dense (the fast path, ops/dense.py): the host pad-aligns every batch's
    rows into the store's dense row space — [R+1, S] tensors with the local
    state as row 0 — and the device reduces over the R axis elementwise.
    No scatter (XLA TPU scatter serializes colliding updates), one transfer
    each way.  Chosen when the batches cover a meaningful fraction of the
    store (snapshot ingest, replica catch-up).
  * scatter (ops/segment.py): touched-slot gather + scatter-max kernels.
    Chosen for sparse merges (steady-state replication trickle).

Host staging is bulk/vectorized (list-comp index probes, block appends,
`dict.update`); the only remaining per-row Python is new element-row index
insertion (native staging library replaces it later).

Must be semantically bit-identical to engine/cpu.py — differential-tested in
tests/test_engine_equivalence.py.
"""

from __future__ import annotations

import logging

import numpy as np

from ..crdt import semantics as S
from ..ops import dense as D
from ..ops import segment as K
from ..store.keyspace import KeySpace
from .base import ColumnarBatch, MergeStats

log = logging.getLogger(__name__)

_I64 = np.int64
_RANK_BITS = KeySpace.NODE_RANK_BITS


def _pad(arr: np.ndarray, size: int, fill) -> np.ndarray:
    if len(arr) == size:
        return np.asarray(arr)
    out = np.full(size, fill, dtype=np.asarray(arr).dtype)
    out[: len(arr)] = arr
    return out


class TpuMergeEngine:
    name = "tpu"
    # dense when staged rows cover >= 1/DENSE_FRACTION of the slot space
    DENSE_FRACTION = 8
    MEM_LIMIT = 6 << 30  # bytes of [R, S] staging we allow on device

    def __init__(self) -> None:
        import jax  # ensure a backend exists before we advertise ourselves

        self._jax = jax
        self._devices = jax.devices()

    # ------------------------------------------------------------------ API

    def merge(self, store: KeySpace, batch: ColumnarBatch) -> MergeStats:
        return self.merge_many(store, [batch])

    def merge_many(self, store: KeySpace, batches: list[ColumnarBatch]) -> MergeStats:
        """Fold any number of columnar batches into the store.  Reductions
        are associative + commutative, so all batches merge in one device
        pass per CRDT family."""
        st = MergeStats()
        # the dense path places each batch row once per slot, which is only
        # a merge if slots are unique within every batch
        self._dense_ok = all(b.rows_unique_per_slot for b in batches)
        resolved = [(b, self._resolve_keys(store, b, st)) for b in batches]
        self._merge_envelopes(store, resolved)
        self._merge_registers(store, resolved)
        self._merge_counter_rows(store, resolved, st)
        self._merge_elem_rows(store, resolved, st)
        for b, _ in resolved:
            for i, key in enumerate(b.del_keys):
                store.record_key_delete(key, int(b.del_t[i]))
        # slot merges bypass the incremental sum cache — re-derive it in one
        # vectorized pass (envelope-only merges cannot change counter sums)
        if any(len(b.cnt_ki) for b, _ in resolved):
            store.recompute_counter_sums()
        return st

    # ------------------------------------------------------- key resolution

    def _resolve_keys(self, store: KeySpace, batch: ColumnarBatch,
                      st: MergeStats) -> np.ndarray:
        """batch key position -> local kid (-1 on type conflict); bulk-creates
        missing keys with the batch envelope (max-merge later is identity)."""
        n = batch.n_keys
        st.keys_seen += n
        if n == 0:
            return np.zeros(0, dtype=_I64)
        index = store.index
        kid_of = np.fromiter((index.get(k, -1) for k in batch.keys),
                             dtype=_I64, count=n)
        missing = np.nonzero(kid_of < 0)[0]
        if len(missing):
            # a raw op-stream batch may repeat a key: create each unique key
            # once and point every occurrence at the same row
            by_key: dict = {}
            for i in missing.tolist():
                by_key.setdefault(batch.keys[i], []).append(i)
            first = np.fromiter((poss[0] for poss in by_key.values()),
                                dtype=_I64, count=len(by_key))
            rows = store.keys.append_block(
                len(first),
                enc=batch.key_enc[first], ct=batch.key_ct[first], mt=0,
                dt=batch.key_dt[first], expire=0, rv_t=0, rv_node=0, cnt_sum=0)
            store.key_bytes.extend(by_key.keys())
            store.reg_val.extend([None] * len(first))
            index.update(zip(by_key.keys(), rows.tolist()))
            for poss, row in zip(by_key.values(), rows.tolist()):
                kid_of[poss] = row
            st.keys_created += len(first)

        # conflict check over ALL positions: duplicate occurrences of a key
        # created above must also match the enc the first occurrence chose
        bad = np.nonzero(store.keys.enc[kid_of] != batch.key_enc)[0]
        if len(bad):
            for i in bad:
                log.error("type conflict merging key %r: local=%s incoming=%s",
                          batch.keys[i], int(store.keys.enc[kid_of[i]]),
                          int(batch.key_enc[i]))
            st.type_conflicts += len(bad)
            kid_of[bad] = -1
        return kid_of

    # ------------------------------------------------- dense/scatter chooser

    def _use_dense(self, total_rows: int, n_slots: int, n_batches: int,
                   n_cols: int) -> bool:
        if not getattr(self, "_dense_ok", False):
            return False
        if total_rows * self.DENSE_FRACTION < n_slots:
            return False
        # _dense_stack pads both axes to powers of two — budget the real size
        mem = K.next_pow2(n_batches + 1) * K.next_pow2(max(n_slots, 1)) * 8 * n_cols
        return mem <= self.MEM_LIMIT

    @staticmethod
    def _dense_stack(cur: np.ndarray, staged: list[tuple[np.ndarray, np.ndarray]],
                     neutral, s_pad: int) -> np.ndarray:
        """[Rp, Sp] tensor: row 0 = current column, one row per batch with
        its values placed at its positions, neutral elsewhere."""
        r_pad = K.next_pow2(len(staged) + 1)
        out = np.full((r_pad, s_pad), neutral, dtype=_I64)
        out[0, : len(cur)] = cur
        for r, (pos, col) in enumerate(staged):
            out[r + 1, pos] = col
        return out

    # ------------------------------------------------------------ envelopes

    def _merge_envelopes(self, store: KeySpace, resolved) -> None:
        staged = []  # (pos, [ct, mt, dt, exp])
        for b, kid_of in resolved:
            valid = np.nonzero(kid_of >= 0)[0]
            if len(valid):
                staged.append((kid_of[valid],
                               [b.key_ct[valid], b.key_mt[valid],
                                b.key_dt[valid], b.key_expire[valid]]))
        if not staged:
            return
        total = sum(len(p) for p, _ in staged)
        S_ = store.keys.n
        if self._use_dense(total, S_, len(staged), 4):
            s_pad = K.next_pow2(S_)
            cols = np.stack([
                self._dense_stack(cur, [(p, c[i]) for p, c in staged],
                                  K.NEUTRAL_T, s_pad)
                for i, cur in enumerate((store.keys.ct, store.keys.mt,
                                         store.keys.dt, store.keys.expire))
            ], axis=-1)  # [Rp, Sp, 4]
            out = np.asarray(self._jax.device_get(D.dense_max(cols)))
            store.keys.ct[:] = out[:S_, 0]
            store.keys.mt[:] = out[:S_, 1]
            store.keys.dt[:] = out[:S_, 2]
            store.keys.expire[:] = out[:S_, 3]
            return
        # scatter path over touched slots
        kv = np.concatenate([p for p, _ in staged])
        trows, slot_idx = np.unique(kv, return_inverse=True)
        n_slots = K.next_pow2(len(trows) + 1)
        n_rows = K.next_pow2(len(kv))
        out = K.scatter_max4(
            _pad(slot_idx.astype(_I64), n_rows, n_slots - 1),
            _pad(np.concatenate([c[0] for _, c in staged]), n_rows, K.NEUTRAL_T),
            _pad(np.concatenate([c[1] for _, c in staged]), n_rows, K.NEUTRAL_T),
            _pad(np.concatenate([c[2] for _, c in staged]), n_rows, K.NEUTRAL_T),
            _pad(np.concatenate([c[3] for _, c in staged]), n_rows, K.NEUTRAL_T),
            _pad(store.keys.ct[trows], n_slots, 0),
            _pad(store.keys.mt[trows], n_slots, 0),
            _pad(store.keys.dt[trows], n_slots, 0),
            _pad(store.keys.expire[trows], n_slots, 0),
            n_slots)
        ct, mt, dt, exp = (a[: len(trows)] for a in self._jax.device_get(out))
        store.keys.ct[trows] = ct
        store.keys.mt[trows] = mt
        store.keys.dt[trows] = dt
        store.keys.expire[trows] = exp

    # ------------------------------------------------------------ registers

    def _merge_registers(self, store: KeySpace, resolved) -> None:
        staged = []  # (pos=kids, t, node, vals)
        for b, kid_of in resolved:
            if not b.n_keys:
                continue
            has = np.fromiter((v is not None for v in b.reg_val),
                              dtype=bool, count=b.n_keys)
            idx = np.nonzero((kid_of >= 0) & (b.key_enc == S.ENC_BYTES) & has)[0]
            if len(idx):
                staged.append((kid_of[idx], b.reg_t[idx], b.reg_node[idx],
                               [b.reg_val[i] for i in idx]))
        if not staged:
            return
        S_ = store.keys.n
        total = sum(len(p) for p, *_ in staged)
        if self._use_dense(total, S_, len(staged), 2):
            s_pad = K.next_pow2(S_)
            t = self._dense_stack(store.keys.rv_t,
                                  [(p, t) for p, t, _, _ in staged],
                                  K.NEUTRAL_T, s_pad)
            n = self._dense_stack(store.keys.rv_node,
                                  [(p, nn) for p, _, nn, _ in staged],
                                  K.NEUTRAL_T, s_pad)
            t_m, n_m, win = (np.asarray(a) for a in
                             self._jax.device_get(D.dense_merge_lww(t, n)))
            store.keys.rv_t[:] = t_m[:S_]
            store.keys.rv_node[:] = n_m[:S_]
            reg_val = store.reg_val
            for r, (pos, _, _, vals) in enumerate(staged):
                slots_w = np.nonzero(win[:S_] == r + 1)[0]
                if not len(slots_w):
                    continue
                inv = np.full(S_, -1, dtype=_I64)
                inv[pos] = np.arange(len(pos), dtype=_I64)
                for s_ in slots_w:
                    reg_val[int(s_)] = vals[int(inv[s_])]
            return
        # scatter path: registers are LWW slots — reuse the element add-side
        # kernel with a zero del side
        kids = np.concatenate([p for p, *_ in staged])
        vals: list = []
        for _, _, _, v in staged:
            vals.extend(v)
        trows, slot_idx = np.unique(kids, return_inverse=True)
        n_slots = K.next_pow2(len(trows) + 1)
        n_rows = K.next_pow2(len(kids))
        out = K.merge_elems(
            _pad(slot_idx.astype(_I64), n_rows, n_slots - 1),
            _pad(np.concatenate([t for _, t, _, _ in staged]), n_rows, K.NEUTRAL_T),
            _pad(np.concatenate([n for _, _, n, _ in staged]), n_rows, K.NEUTRAL_T),
            np.zeros(n_rows, dtype=_I64),
            _pad(store.keys.rv_t[trows], n_slots, 0),
            _pad(store.keys.rv_node[trows], n_slots, 0),
            np.zeros(n_slots, dtype=_I64),
            n_slots)
        t, node, _dt, win_row = (a[: len(trows)] for a in self._jax.device_get(out))
        store.keys.rv_t[trows] = t
        store.keys.rv_node[trows] = node
        reg_val = store.reg_val
        for di in np.nonzero(win_row >= 0)[0]:
            reg_val[int(trows[di])] = vals[int(win_row[di])]

    # ------------------------------------------------------------- counters

    def _merge_counter_rows(self, store: KeySpace, resolved,
                            st: MergeStats) -> None:
        staged = []  # (rows, total, uuid, base, base_t)
        for b, kid_of in resolved:
            if not len(b.cnt_ki):
                continue
            kid_arr = kid_of[b.cnt_ki]
            keep = np.nonzero(kid_arr >= 0)[0]
            if not len(keep):
                continue
            st.counter_rows += len(keep)
            # vectorized combo keys: node ids -> dense ranks via the (tiny)
            # per-batch unique node set, then (kid << RANK_BITS) | rank
            uniq_nodes, inv = np.unique(b.cnt_node[keep], return_inverse=True)
            ranks = np.fromiter((store.rank_of(int(x)) for x in uniq_nodes),
                                dtype=_I64, count=len(uniq_nodes))
            combos = (kid_arr[keep] << _RANK_BITS) | ranks[inv]
            rows = self._resolve_cnt_rows(store, combos)
            staged.append((rows, b.cnt_val[keep], b.cnt_uuid[keep],
                           b.cnt_base[keep], b.cnt_base_t[keep]))
        if not staged:
            return
        S_ = store.cnt.n
        total = sum(len(r) for r, *_ in staged)

        # both slot pairs — (total @ uuid) and (base @ base_t) — are plain
        # per-slot LWW-with-max-tie merges; run the same kernel twice
        if self._use_dense(total, S_, len(staged), 4):
            s_pad = K.next_pow2(S_)
            for vcol, tcol, vi, ti in (("val", "uuid", 1, 2),
                                       ("base", "base_t", 3, 4)):
                vals = self._dense_stack(store.cnt.col(vcol),
                                         [(s[0], s[vi]) for s in staged], 0, s_pad)
                ts = self._dense_stack(store.cnt.col(tcol),
                                       [(s[0], s[ti]) for s in staged],
                                       K.NEUTRAL_T, s_pad)
                new_val, new_t = (np.asarray(a)[:S_] for a in
                                  self._jax.device_get(D.dense_merge_counters(vals, ts)))
                store.cnt.col(vcol)[:] = new_val
                store.cnt.col(tcol)[:] = new_t
            return  # sums re-derived in one pass by merge_many

        all_rows = np.concatenate([s[0] for s in staged])
        trows, slot_idx = np.unique(all_rows, return_inverse=True)
        n_slots = K.next_pow2(len(trows) + 1)
        n_rows = K.next_pow2(len(all_rows))
        slot_ids = _pad(slot_idx.astype(_I64), n_rows, n_slots - 1)
        for vcol, tcol, vi, ti in (("val", "uuid", 1, 2),
                                   ("base", "base_t", 3, 4)):
            out = K.merge_counters(
                slot_ids,
                _pad(np.concatenate([s[vi] for s in staged]), n_rows, 0),
                _pad(np.concatenate([s[ti] for s in staged]), n_rows, K.NEUTRAL_T),
                _pad(store.cnt.col(vcol)[trows], n_slots, 0),
                _pad(store.cnt.col(tcol)[trows], n_slots, K.NEUTRAL_T),
                n_slots)
            new_val, new_t = (a[: len(trows)] for a in self._jax.device_get(out))
            store.cnt.col(vcol)[trows] = new_val
            store.cnt.col(tcol)[trows] = new_t
        # sums re-derived in one pass by merge_many

    def _resolve_cnt_rows(self, store: KeySpace, combos: np.ndarray) -> np.ndarray:
        """(kid, node) combo keys -> store cnt rows, bulk-creating missing
        slots as neutral (val=0, t=NEUTRAL_T)."""
        cnt_index = store.cnt_index
        rows = np.fromiter((cnt_index.get(c, -1) for c in combos.tolist()),
                           dtype=_I64, count=len(combos))
        miss = np.nonzero(rows < 0)[0]
        if len(miss):
            miss_combos, minv = np.unique(combos[miss], return_inverse=True)
            nodes = np.asarray(store.node_ids, dtype=_I64)[
                miss_combos & ((1 << _RANK_BITS) - 1)]
            new_rows = store.cnt.append_block(
                len(miss_combos), kid=miss_combos >> _RANK_BITS,
                node=nodes, val=0, uuid=K.NEUTRAL_T, base=0, base_t=K.NEUTRAL_T)
            cnt_index.update(zip(miss_combos.tolist(), new_rows.tolist()))
            by_kid = store.cnt_rows_by_kid
            for combo, row in zip((miss_combos >> _RANK_BITS).tolist(),
                                  new_rows.tolist()):
                by_kid.setdefault(combo, []).append(row)
            rows[miss] = new_rows[minv]
        return rows

    # ------------------------------------------------------------- elements

    def _merge_elem_rows(self, store: KeySpace, resolved,
                         st: MergeStats) -> None:
        staged = []  # (rows, at, an, dt, vals, has_vals)
        elems = store.elems
        for b, kid_of in resolved:
            if not len(b.el_ki):
                continue
            kid_arr = kid_of[b.el_ki]
            keep = np.nonzero(kid_arr >= 0)[0]
            if not len(keep):
                continue
            st.elem_rows += len(keep)
            rows = np.empty(len(keep), dtype=_I64)
            members = b.el_member
            for j, r in enumerate(keep):
                kid = int(kid_arr[r])
                member = members[r]
                ems = elems.setdefault(kid, {})
                row = ems.get(member, -1)
                if row < 0:
                    row = store._el_new_row(kid, member, None, 0, 0)
                    ems[member] = row
                rows[j] = row
            vals = [b.el_val[r] for r in keep]
            staged.append((rows, b.el_add_t[keep], b.el_add_node[keep],
                           b.el_del_t[keep], vals,
                           any(v is not None for v in vals)))
        if not staged:
            return
        S_ = store.el.n
        total = sum(len(r) for r, *_ in staged)
        old_dt = store.el.del_t.copy()

        if self._use_dense(total, S_, len(staged), 3):
            s_pad = K.next_pow2(S_)
            at = self._dense_stack(store.el.add_t, [(r, a) for r, a, *_ in staged],
                                   K.NEUTRAL_T, s_pad)
            an = self._dense_stack(store.el.add_node,
                                   [(r, x) for r, _, x, *_ in staged],
                                   K.NEUTRAL_T, s_pad)
            dt = self._dense_stack(store.el.del_t,
                                   [(r, d) for r, _, _, d, *_ in staged], 0, s_pad)
            m_at, m_an, m_dt, win = (np.asarray(a)[:S_] for a in
                                     self._jax.device_get(D.dense_merge_elems(at, an, dt)))
            store.el.add_t[:] = m_at
            store.el.add_node[:] = m_an
            store.el.del_t[:] = m_dt
            el_val = store.el_val
            for r, (pos, _, _, _, vals, has_vals) in enumerate(staged):
                slots_w = np.nonzero(win == r + 1)[0]
                if not len(slots_w) or not has_vals:
                    continue
                inv = np.full(S_, -1, dtype=_I64)
                inv[pos] = np.arange(len(pos), dtype=_I64)
                for s_ in slots_w:
                    el_val[int(s_)] = vals[int(inv[s_])]
            self._enqueue_elem_garbage(store, np.arange(S_), m_at, m_dt, old_dt)
            return

        all_rows = np.concatenate([r for r, *_ in staged])
        vals_flat: list = []
        for _, _, _, _, v, _ in staged:
            vals_flat.extend(v)
        trows, slot_idx = np.unique(all_rows, return_inverse=True)
        cur_dt = old_dt[trows]
        n_slots = K.next_pow2(len(trows) + 1)
        n_rows = K.next_pow2(len(all_rows))
        out = K.merge_elems(
            _pad(slot_idx.astype(_I64), n_rows, n_slots - 1),
            _pad(np.concatenate([a for _, a, *_ in staged]), n_rows, K.NEUTRAL_T),
            _pad(np.concatenate([x for _, _, x, *_ in staged]), n_rows, K.NEUTRAL_T),
            _pad(np.concatenate([d for _, _, _, d, _, _ in staged]), n_rows, 0),
            _pad(store.el.add_t[trows], n_slots, 0),
            _pad(store.el.add_node[trows], n_slots, 0),
            _pad(cur_dt, n_slots, 0),
            n_slots)
        kk = len(trows)
        m_at, m_an, m_dt, win_row = (a[:kk] for a in self._jax.device_get(out))
        store.el.add_t[trows] = m_at
        store.el.add_node[trows] = m_an
        store.el.del_t[trows] = m_dt
        el_val = store.el_val
        for di in np.nonzero(win_row >= 0)[0]:
            el_val[int(trows[di])] = vals_flat[int(win_row[di])]
        self._enqueue_elem_garbage(store, trows, m_at, m_dt, cur_dt)

    @staticmethod
    def _enqueue_elem_garbage(store: KeySpace, rows, at, dt, old_dt) -> None:
        """Queue tombstones whose del_t advanced (dead rows need GC once the
        cluster horizon passes)."""
        newly = np.nonzero((at < dt) & (dt > old_dt))[0]
        el_kid = store.el.kid
        el_member = store.el_member
        key_bytes = store.key_bytes
        for di in newly:
            row = int(rows[di])
            store._enqueue_garbage(int(dt[di]), key_bytes[int(el_kid[row])],
                                   el_member[row])
